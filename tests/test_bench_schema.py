"""Bench-artifact schema gate: every checked-in SERVE_BENCH_*.json /
BENCH_*.json must validate, so cross-round comparisons can trust the
field names and types. Also pins the checker's own failure modes —
a validator that passes everything is worse than none."""
import json
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
CHECKER = REPO / "tools" / "check_bench_schema.py"

sys.path.insert(0, str(REPO / "tools"))
import check_bench_schema as cbs  # noqa: E402


def test_checked_in_artifacts_validate():
    """The real gate: the repo's own artifacts, via the CLI."""
    proc = subprocess.run(
        [sys.executable, str(CHECKER)], cwd=str(REPO),
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "all valid" in proc.stdout


def _problems_for(name, obj, tmp_path):
    p = tmp_path / name
    p.write_text(json.dumps(obj))
    problems = []
    cbs.check_file(str(p), problems)
    return problems


def test_rejects_missing_metric_field(tmp_path):
    good = {"throughput_tok_s": 1.0, "p50_ms": 2.0, "p99_ms": 3.0,
            "ttft_ms": 4.0, "stream_tok_s": 5.0}
    assert _problems_for("SERVE_BENCH_x.json", good, tmp_path) == []
    bad = dict(good)
    del bad["ttft_ms"]
    probs = _problems_for("SERVE_BENCH_x.json", bad, tmp_path)
    assert probs and "ttft_ms" in probs[0]


def test_rejects_string_typed_number(tmp_path):
    bad = {"throughput_tok_s": "1260.4", "p50_ms": 2.0, "p99_ms": 3.0,
           "ttft_ms": 4.0, "stream_tok_s": 5.0}
    probs = _problems_for("SERVE_BENCH_x.json", bad, tmp_path)
    assert any("throughput_tok_s" in p for p in probs)


def test_ab_requires_both_sections_and_ratio(tmp_path):
    res = {"throughput_tok_s": 1.0, "p50_ms": 2.0, "p99_ms": 3.0,
           "ttft_ms": 4.0, "stream_tok_s": 5.0}
    ok = {"engine_continuous_batching": res,
          "legacy_decode_to_completion": res,
          "throughput_ratio": 1.5}
    assert _problems_for("SERVE_BENCH_ab.json", ok, tmp_path) == []
    no_ratio = {k: v for k, v in ok.items()
                if not k.endswith("_ratio")}
    assert _problems_for("SERVE_BENCH_ab.json", no_ratio, tmp_path)
    no_leg = {"engine_continuous_batching": res,
              "throughput_ratio": 1.5}
    assert _problems_for("SERVE_BENCH_ab.json", no_leg, tmp_path)


_PC = {"hit_tokens": 608, "miss_tokens": 352, "hit_rate": 0.63,
       "evictions": 0, "cached_pages": 44}


def test_prefix_cache_block_validated_when_present(tmp_path):
    res = {"throughput_tok_s": 1.0, "p50_ms": 2.0, "p99_ms": 3.0,
           "ttft_ms": 4.0, "stream_tok_s": 5.0}
    ok = dict(res, prefix_cache=dict(_PC))
    assert _problems_for("SERVE_BENCH_x.json", ok, tmp_path) == []
    for field in _PC:
        bad = dict(res, prefix_cache={k: v for k, v in _PC.items()
                                      if k != field})
        probs = _problems_for("SERVE_BENCH_x.json", bad, tmp_path)
        assert any(field in p for p in probs), field
    typed = dict(res, prefix_cache=dict(_PC, hit_rate="0.63"))
    assert _problems_for("SERVE_BENCH_x.json", typed, tmp_path)
    not_obj = dict(res, prefix_cache=[1, 2])
    assert _problems_for("SERVE_BENCH_x.json", not_obj, tmp_path)


def test_prefix_cache_ab_requires_stats_and_ratio(tmp_path):
    res = {"throughput_tok_s": 1.0, "p50_ms": 2.0, "p99_ms": 3.0,
           "ttft_ms": 4.0, "stream_tok_s": 5.0}
    eng = dict(res, prefix_cache=dict(_PC))
    ok = {"engine_continuous_batching": eng,
          "legacy_decode_to_completion": dict(res),
          "engine_prefix_cache_off": dict(res),
          "throughput_ratio": 1.5, "prefix_ttft_ratio": 0.75}
    assert _problems_for("SERVE_BENCH_ab.json", ok, tmp_path) == []
    # cache-off section present but engine carries no cache stats
    no_stats = dict(ok, engine_continuous_batching=dict(res))
    probs = _problems_for("SERVE_BENCH_ab.json", no_stats, tmp_path)
    assert any("no prefix_cache stats" in p for p in probs)
    # missing the dedicated ratio
    no_ratio = {k: v for k, v in ok.items()
                if k != "prefix_ttft_ratio"}
    probs = _problems_for("SERVE_BENCH_ab.json", no_ratio, tmp_path)
    assert any("prefix_ttft_ratio" in p for p in probs)
    # the off section is itself a full serve result
    bad_off = dict(ok, engine_prefix_cache_off={"ttft_ms": 1.0})
    probs = _problems_for("SERVE_BENCH_ab.json", bad_off, tmp_path)
    assert any("engine_prefix_cache_off" in p for p in probs)


_SP = {"proposed_tokens": 120, "accepted_tokens": 90,
       "rejected_tokens": 30, "accept_rate": 0.75,
       "tokens_per_dispatch": 1.8}


def test_spec_block_validated_when_present(tmp_path):
    res = {"throughput_tok_s": 1.0, "p50_ms": 2.0, "p99_ms": 3.0,
           "ttft_ms": 4.0, "stream_tok_s": 5.0}
    ok = dict(res, spec=dict(_SP))
    assert _problems_for("SERVE_BENCH_x.json", ok, tmp_path) == []
    for field in _SP:
        bad = dict(res, spec={k: v for k, v in _SP.items()
                              if k != field})
        probs = _problems_for("SERVE_BENCH_x.json", bad, tmp_path)
        assert any(field in p for p in probs), field
    typed = dict(res, spec=dict(_SP, accept_rate="0.75"))
    assert _problems_for("SERVE_BENCH_x.json", typed, tmp_path)
    not_obj = dict(res, spec=[1, 2])
    assert _problems_for("SERVE_BENCH_x.json", not_obj, tmp_path)


def test_spec_ab_requires_stats_and_ratio(tmp_path):
    res = {"throughput_tok_s": 1.0, "p50_ms": 2.0, "p99_ms": 3.0,
           "ttft_ms": 4.0, "stream_tok_s": 5.0}
    eng = dict(res, spec=dict(_SP))
    ok = {"engine_continuous_batching": eng,
          "legacy_decode_to_completion": dict(res),
          "engine_spec_off": dict(res),
          "throughput_ratio": 1.5, "spec_throughput_ratio": 1.3}
    assert _problems_for("SERVE_BENCH_ab.json", ok, tmp_path) == []
    # spec-off section present but engine carries no spec stats
    no_stats = dict(ok, engine_continuous_batching=dict(res))
    probs = _problems_for("SERVE_BENCH_ab.json", no_stats, tmp_path)
    assert any("no spec stats" in p for p in probs)
    # missing the dedicated ratio
    no_ratio = {k: v for k, v in ok.items()
                if k != "spec_throughput_ratio"}
    probs = _problems_for("SERVE_BENCH_ab.json", no_ratio, tmp_path)
    assert any("spec_throughput_ratio" in p for p in probs)
    # the off section is itself a full serve result
    bad_off = dict(ok, engine_spec_off={"ttft_ms": 1.0})
    probs = _problems_for("SERVE_BENCH_ab.json", bad_off, tmp_path)
    assert any("engine_spec_off" in p for p in probs)


_LC = {"max_queued": 2, "max_retries": 2, "retry_backoff_s": 0.02,
       "shed": 18, "cancelled": 4, "deadline_exceeded": 4,
       "contained_faults": 0, "retries": 0, "retry_exhausted": 0,
       "fault_failed": 0}


def test_lifecycle_block_validated_when_present(tmp_path):
    res = {"throughput_tok_s": 1.0, "p50_ms": 2.0, "p99_ms": 3.0,
           "ttft_ms": 4.0, "stream_tok_s": 5.0}
    ok = dict(res, lifecycle=dict(_LC))
    assert _problems_for("SERVE_BENCH_x.json", ok, tmp_path) == []
    # unbounded admission reports max_queued: null — still valid
    unbounded = dict(res, lifecycle=dict(_LC, max_queued=None))
    assert _problems_for("SERVE_BENCH_x.json", unbounded,
                         tmp_path) == []
    for field in ("max_queued", "max_retries", "retry_backoff_s",
                  "shed", "cancelled", "deadline_exceeded"):
        bad = dict(res, lifecycle={k: v for k, v in _LC.items()
                                   if k != field})
        probs = _problems_for("SERVE_BENCH_x.json", bad, tmp_path)
        assert any(field in p for p in probs), field
    typed = dict(res, lifecycle=dict(_LC, shed="18"))
    assert _problems_for("SERVE_BENCH_x.json", typed, tmp_path)
    not_obj = dict(res, lifecycle=[1, 2])
    assert _problems_for("SERVE_BENCH_x.json", not_obj, tmp_path)


def _lifecycle_smoke():
    return {
        "unsaturated": {"p50_ms": 50.0, "p99_ms": 80.0,
                        "requests": 16, "client_threads": 4},
        "overloaded": {"attempts": 64, "admitted": 30, "shed": 18,
                       "other_errors": 0, "admitted_p50_ms": 52.0,
                       "admitted_p99_ms": 90.0, "shed_p50_ms": 2.0,
                       "client_threads": 16},
        "admitted_p50_ratio": 1.04,
        "lifecycle": dict(_LC),
        "git_sha": "abc1234",
    }


def test_lifecycle_smoke_artifact_validates(tmp_path):
    ok = _lifecycle_smoke()
    assert _problems_for("SERVE_BENCH_lifecycle_cpu_smoke.json", ok,
                         tmp_path) == []


def test_lifecycle_smoke_requires_measured_shedding(tmp_path):
    # shed == 0 on either side means the overload burst never
    # overloaded: a broken run, not evidence of bounded admission
    no_client_shed = _lifecycle_smoke()
    no_client_shed["overloaded"]["shed"] = 0
    probs = _problems_for("SERVE_BENCH_lifecycle_cpu_smoke.json",
                          no_client_shed, tmp_path)
    assert any("shed nothing" in p for p in probs)
    no_engine_shed = _lifecycle_smoke()
    no_engine_shed["lifecycle"]["shed"] = 0
    probs = _problems_for("SERVE_BENCH_lifecycle_cpu_smoke.json",
                          no_engine_shed, tmp_path)
    assert any("shed counter is 0" in p for p in probs)


def test_lifecycle_smoke_requires_sections_and_bounded_queue(tmp_path):
    for missing in ("unsaturated", "overloaded", "lifecycle",
                    "admitted_p50_ratio"):
        bad = {k: v for k, v in _lifecycle_smoke().items()
               if k != missing}
        probs = _problems_for("SERVE_BENCH_lifecycle_cpu_smoke.json",
                              bad, tmp_path)
        assert probs, missing
    # a lifecycle smoke against an UNBOUNDED queue proves nothing
    unbounded = _lifecycle_smoke()
    unbounded["lifecycle"]["max_queued"] = None
    probs = _problems_for("SERVE_BENCH_lifecycle_cpu_smoke.json",
                          unbounded, tmp_path)
    assert any("max_queued" in p for p in probs)
    # overloaded section missing its admitted p50
    no_p50 = _lifecycle_smoke()
    del no_p50["overloaded"]["admitted_p50_ms"]
    probs = _problems_for("SERVE_BENCH_lifecycle_cpu_smoke.json",
                          no_p50, tmp_path)
    assert any("admitted_p50_ms" in p for p in probs)


def test_git_sha_must_be_string_when_present(tmp_path):
    res = {"throughput_tok_s": 1.0, "p50_ms": 2.0, "p99_ms": 3.0,
           "ttft_ms": 4.0, "stream_tok_s": 5.0}
    ok = dict(res, git_sha="abc1234")
    assert _problems_for("SERVE_BENCH_x.json", ok, tmp_path) == []
    bad = dict(res, git_sha=1234)
    probs = _problems_for("SERVE_BENCH_x.json", bad, tmp_path)
    assert any("git_sha" in p for p in probs)


def test_bench_wrapper_and_flat_metric(tmp_path):
    wrapper = {"n": 3, "cmd": "python bench.py", "rc": 0,
               "tail": "...", "parsed": {"metric": "m", "value": 1.0}}
    assert _problems_for("BENCH_x.json", wrapper, tmp_path) == []
    # rc == 0 with no parsed payload is a broken round
    broken = dict(wrapper, parsed=None)
    assert _problems_for("BENCH_x.json", broken, tmp_path)
    flat = {"metric": "m", "value": 2.5, "unit": "tok/s"}
    assert _problems_for("BENCH_SELF_x.json", flat, tmp_path) == []
    assert _problems_for("BENCH_SELF_x.json",
                         {"metric": "m"}, tmp_path)


def test_unreadable_json_is_a_problem(tmp_path):
    p = tmp_path / "BENCH_bad.json"
    p.write_text("{not json")
    problems = []
    cbs.check_file(str(p), problems)
    assert problems and "unreadable" in problems[0]

_POOL = {"routed": 64, "affinity_hits": 50, "affinity_hit_rate": 0.78,
         "spill_rate": 0.05, "n_replicas": 2,
         "replicas": [{"idx": 0, "state": "healthy", "deaths": 0,
                       "generation": 0},
                      {"idx": 1, "state": "healthy", "deaths": 0,
                       "generation": 0}]}
_KILL = {"requests": 8, "completed": 6, "failed_typed": 2,
         "resubmitted": 5, "replica_deaths": 1,
         "token_identical": True, "lost": 0}


def _pool_ab():
    res = {"throughput_tok_s": 1.0, "p50_ms": 2.0, "p99_ms": 3.0,
           "ttft_ms": 4.0, "stream_tok_s": 5.0}
    return {"engine_pool": dict(res, pool=json.loads(
                json.dumps(_POOL))),
            "engine_single": dict(res),
            "replicas": 2, "pool_throughput_ratio": 1.6,
            "affinity_hit_rate": 0.78, "spill_rate": 0.05,
            "replica_kill": dict(_KILL), "git_sha": "abc1234"}


def test_pool_ab_artifact_validates(tmp_path):
    assert _problems_for("SERVE_BENCH_pool_cpu_smoke.json",
                         _pool_ab(), tmp_path) == []


def test_pool_ab_requires_sections_ratios_and_stats(tmp_path):
    for missing in ("engine_single", "pool_throughput_ratio",
                    "affinity_hit_rate", "spill_rate",
                    "replica_kill"):
        bad = {k: v for k, v in _pool_ab().items() if k != missing}
        probs = _problems_for("SERVE_BENCH_pool_cpu_smoke.json",
                              bad, tmp_path)
        assert any(missing in p for p in probs), missing
    # the pool section must carry its routing-stats block
    no_stats = _pool_ab()
    del no_stats["engine_pool"]["pool"]
    probs = _problems_for("SERVE_BENCH_pool_cpu_smoke.json",
                          no_stats, tmp_path)
    assert any("no pool routing-stats" in p for p in probs)
    # ... with a non-empty replicas list
    no_reps = _pool_ab()
    no_reps["engine_pool"]["pool"]["replicas"] = []
    probs = _problems_for("SERVE_BENCH_pool_cpu_smoke.json",
                          no_reps, tmp_path)
    assert any("non-empty list" in p for p in probs)
    # a one-replica "pool A/B" is not an A/B
    one = dict(_pool_ab(), replicas=1)
    probs = _problems_for("SERVE_BENCH_pool_cpu_smoke.json",
                          one, tmp_path)
    assert any("int >= 2" in p for p in probs)


_ARM = {"requests": 400, "completed": 396, "shed": 4, "errors": 0,
        "shed_events": 9, "retry_after_violations": 0,
        "slo_attainment": 0.95, "chip_seconds": 40.0,
        "ttft_p50_ms": 120.0, "ttft_p95_ms": 600.0}


def _autoscale():
    auto = dict(_ARM, replica_timeline=[[0.0, 1], [3.2, 2], [4.1, 3],
                                        [18.5, 2], [21.0, 1]],
                replicas_min_seen=1, replicas_max_seen=3,
                scale_ups=2, scale_downs=2, holds=80, denied=0)
    return {"trace": "bursty", "seed": 0, "replicas_min": 1,
            "replicas_max": 4,
            "slo": {"ttft_ms": 1000.0, "attainment_floor": 0.9},
            "autoscale": auto,
            "static_max": dict(_ARM, chip_seconds=84.0),
            "chip_seconds_ratio": 0.48, "ttft_p50_ratio": 1.1,
            "git_sha": "abc1234"}


def test_autoscale_artifact_validates(tmp_path):
    assert _problems_for("SERVE_BENCH_autoscale_cpu_smoke.json",
                         _autoscale(), tmp_path) == []


def test_autoscale_requires_sections_and_fields(tmp_path):
    for missing in ("trace", "seed", "slo", "replicas_min",
                    "replicas_max", "chip_seconds_ratio"):
        bad = {k: v for k, v in _autoscale().items() if k != missing}
        probs = _problems_for("SERVE_BENCH_autoscale_cpu_smoke.json",
                              bad, tmp_path)
        assert any(missing in p for p in probs), missing
    for field in ("requests", "slo_attainment", "chip_seconds",
                  "retry_after_violations", "ttft_p50_ms"):
        bad = _autoscale()
        del bad["autoscale"][field]
        probs = _problems_for("SERVE_BENCH_autoscale_cpu_smoke.json",
                              bad, tmp_path)
        assert any(field in p for p in probs), field
        bad = _autoscale()
        del bad["static_max"][field]
        probs = _problems_for("SERVE_BENCH_autoscale_cpu_smoke.json",
                              bad, tmp_path)
        assert any(field in p for p in probs), field


def test_autoscale_refuses_attainment_below_recorded_floor(tmp_path):
    # the floor the run RECORDED is the contract: an artifact whose
    # autoscale arm missed its own floor documents an SLO breach
    bad = _autoscale()
    bad["autoscale"]["slo_attainment"] = 0.8
    probs = _problems_for("SERVE_BENCH_autoscale_cpu_smoke.json",
                          bad, tmp_path)
    assert any("below the run's own recorded floor" in p
               for p in probs)
    # the static arm is a BASELINE, not a contract: it may miss
    ok = _autoscale()
    ok["static_max"]["slo_attainment"] = 0.5
    assert _problems_for("SERVE_BENCH_autoscale_cpu_smoke.json",
                         ok, tmp_path) == []


def test_autoscale_refuses_retry_after_violations(tmp_path):
    bad = _autoscale()
    bad["autoscale"]["retry_after_violations"] = 2
    probs = _problems_for("SERVE_BENCH_autoscale_cpu_smoke.json",
                          bad, tmp_path)
    assert any("Retry-After violation" in p for p in probs)


def test_autoscale_requires_scaling_timeline(tmp_path):
    missing = _autoscale()
    del missing["autoscale"]["replica_timeline"]
    probs = _problems_for("SERVE_BENCH_autoscale_cpu_smoke.json",
                          missing, tmp_path)
    assert any("replica_timeline" in p for p in probs)
    empty = _autoscale()
    empty["autoscale"]["replica_timeline"] = []
    probs = _problems_for("SERVE_BENCH_autoscale_cpu_smoke.json",
                          empty, tmp_path)
    assert any("non-empty" in p for p in probs)
    # a flat timeline means the pool never scaled: the artifact
    # proves nothing about autoscaling
    flat = _autoscale()
    flat["autoscale"]["replica_timeline"] = [[0.0, 2], [20.0, 2]]
    probs = _problems_for("SERVE_BENCH_autoscale_cpu_smoke.json",
                          flat, tmp_path)
    assert any("flat" in p for p in probs)


def test_autoscale_refuses_chip_seconds_ratio_ge_one(tmp_path):
    bad = _autoscale()
    bad["chip_seconds_ratio"] = 1.0
    probs = _problems_for("SERVE_BENCH_autoscale_cpu_smoke.json",
                          bad, tmp_path)
    assert any("chip_seconds_ratio" in p for p in probs)


def test_pool_ab_kill_run_must_lose_nothing(tmp_path):
    lossy = _pool_ab()
    lossy["replica_kill"]["lost"] = 1
    probs = _problems_for("SERVE_BENCH_pool_cpu_smoke.json",
                          lossy, tmp_path)
    assert any("failover must lose none" in p for p in probs)
    mangled = _pool_ab()
    mangled["replica_kill"]["token_identical"] = False
    probs = _problems_for("SERVE_BENCH_pool_cpu_smoke.json",
                          mangled, tmp_path)
    assert any("not token-identical" in p for p in probs)
    # a kill run that killed nothing proves nothing
    no_kill = _pool_ab()
    no_kill["replica_kill"]["replica_deaths"] = 0
    probs = _problems_for("SERVE_BENCH_pool_cpu_smoke.json",
                          no_kill, tmp_path)
    assert any("killed no replica" in p for p in probs)


# ---------------------------------------------------------------------------
# tp A/B family (serve_bench.py --tp-ab artifacts)
# ---------------------------------------------------------------------------


_TP_ARM = {"throughput_tok_s": 35.0, "per_token_ms": 28.5,
           "requests": 8, "gen_tokens": 16, "devices": 1,
           "wall_s": 3.6, "compile_s": 9.1}


def _tp_ab():
    return {"tp_ab": {"tp1": dict(_TP_ARM),
                      "tpn": dict(_TP_ARM, devices=4,
                                  per_token_ms=40.0),
                      "parity": {"token_identical": True,
                                 "checked": 8},
                      "per_token_ratio": 1.4,
                      "throughput_ratio": 0.71},
            "mesh": {"tp": 4, "replicas": 1},
            "model": "llama-tiny", "git_sha": "abc1234"}


def test_tp_ab_artifact_validates(tmp_path):
    assert _problems_for("SERVE_BENCH_tp_ab_cpu_smoke.json",
                         _tp_ab(), tmp_path) == []


def test_tp_ab_refuses_missing_or_malformed_mesh(tmp_path):
    # a tensor-parallel artifact without its mesh stamp proves nothing
    no_mesh = {k: v for k, v in _tp_ab().items() if k != "mesh"}
    probs = _problems_for("SERVE_BENCH_tp_ab_cpu_smoke.json",
                          no_mesh, tmp_path)
    assert any("mesh stamp" in p for p in probs)
    one_chip = _tp_ab()
    one_chip["mesh"]["tp"] = 1
    probs = _problems_for("SERVE_BENCH_tp_ab_cpu_smoke.json",
                          one_chip, tmp_path)
    assert any("tp must be >= 2" in p for p in probs)
    typed = _tp_ab()
    typed["mesh"]["tp"] = "4"
    probs = _problems_for("SERVE_BENCH_tp_ab_cpu_smoke.json",
                          typed, tmp_path)
    assert any("mesh" in p and "tp" in p for p in probs)


def test_tp_ab_refuses_non_parity(tmp_path):
    # token-identical greedy output across widths IS the contract
    diverged = _tp_ab()
    diverged["tp_ab"]["parity"]["token_identical"] = False
    probs = _problems_for("SERVE_BENCH_tp_ab_cpu_smoke.json",
                          diverged, tmp_path)
    assert any("not token-identical" in p for p in probs)
    empty = _tp_ab()
    empty["tp_ab"]["parity"]["checked"] = 0
    probs = _problems_for("SERVE_BENCH_tp_ab_cpu_smoke.json",
                          empty, tmp_path)
    assert any("checked nothing" in p for p in probs)
    no_parity = _tp_ab()
    del no_parity["tp_ab"]["parity"]
    probs = _problems_for("SERVE_BENCH_tp_ab_cpu_smoke.json",
                          no_parity, tmp_path)
    assert any("parity block" in p for p in probs)


def test_tp_ab_requires_arms_and_ratio(tmp_path):
    no_arm = _tp_ab()
    del no_arm["tp_ab"]["tpn"]
    probs = _problems_for("SERVE_BENCH_tp_ab_cpu_smoke.json",
                          no_arm, tmp_path)
    assert any("tpn" in p for p in probs)
    no_field = _tp_ab()
    del no_field["tp_ab"]["tp1"]["per_token_ms"]
    probs = _problems_for("SERVE_BENCH_tp_ab_cpu_smoke.json",
                          no_field, tmp_path)
    assert any("per_token_ms" in p for p in probs)
    no_ratio = _tp_ab()
    del no_ratio["tp_ab"]["per_token_ratio"]
    probs = _problems_for("SERVE_BENCH_tp_ab_cpu_smoke.json",
                          no_ratio, tmp_path)
    assert any("per_token_ratio" in p for p in probs)


# ------------------------------------------------ overlap A/B family


def _overlap_arm(frac, ttft):
    return {"throughput_tok_s": 7000.0, "wall_s": 0.04,
            "requests": 6, "gen_tokens": 48, "rounds": 10,
            "host_gap_s": 0.001, "round_wall_s": 0.038,
            "host_gap_fraction": frac, "ttft_p50_s": ttft}


def _overlap_ab():
    return {"overlap_ab": {"lockstep": _overlap_arm(0.03, 0.022),
                           "overlapped": _overlap_arm(0.011, 0.020),
                           "parity": {"token_identical": True,
                                      "checked": 6},
                           "host_gap_fraction_ratio": 0.37,
                           "ttft_p50_ratio": 0.91},
            "mesh": {"tp": 1, "replicas": 1}, "seed": 0,
            "model": "llama-tiny", "git_sha": "abc1234"}


def test_overlap_ab_artifact_validates(tmp_path):
    assert _problems_for("SERVE_BENCH_overlap_ab_cpu_smoke.json",
                         _overlap_ab(), tmp_path) == []


def test_overlap_ab_refuses_missing_stamp(tmp_path):
    no_mesh = {k: v for k, v in _overlap_ab().items() if k != "mesh"}
    probs = _problems_for("SERVE_BENCH_overlap_ab_cpu_smoke.json",
                          no_mesh, tmp_path)
    assert any("mesh stamp" in p for p in probs)
    no_seed = {k: v for k, v in _overlap_ab().items() if k != "seed"}
    probs = _problems_for("SERVE_BENCH_overlap_ab_cpu_smoke.json",
                          no_seed, tmp_path)
    assert any("seed" in p for p in probs)


def test_overlap_ab_refuses_non_parity(tmp_path):
    # an overlapped loop that changes greedy tokens is broken,
    # whatever its pipeline efficiency
    diverged = _overlap_ab()
    diverged["overlap_ab"]["parity"]["token_identical"] = False
    probs = _problems_for("SERVE_BENCH_overlap_ab_cpu_smoke.json",
                          diverged, tmp_path)
    assert any("not token-identical" in p for p in probs)
    empty = _overlap_ab()
    empty["overlap_ab"]["parity"]["checked"] = 0
    probs = _problems_for("SERVE_BENCH_overlap_ab_cpu_smoke.json",
                          empty, tmp_path)
    assert any("checked nothing" in p for p in probs)
    no_parity = _overlap_ab()
    del no_parity["overlap_ab"]["parity"]
    probs = _problems_for("SERVE_BENCH_overlap_ab_cpu_smoke.json",
                          no_parity, tmp_path)
    assert any("parity" in p for p in probs)


def test_overlap_ab_refuses_non_improving_host_gap(tmp_path):
    # equal fractions: NOT strictly lower -> refused
    flat = _overlap_ab()
    flat["overlap_ab"]["overlapped"]["host_gap_fraction"] = 0.03
    probs = _problems_for("SERVE_BENCH_overlap_ab_cpu_smoke.json",
                          flat, tmp_path)
    assert any("not strictly below" in p for p in probs)
    worse = _overlap_ab()
    worse["overlap_ab"]["overlapped"]["host_gap_fraction"] = 0.05
    probs = _problems_for("SERVE_BENCH_overlap_ab_cpu_smoke.json",
                          worse, tmp_path)
    assert any("not strictly below" in p for p in probs)


def test_overlap_ab_requires_arms_and_ratio(tmp_path):
    no_arm = _overlap_ab()
    del no_arm["overlap_ab"]["overlapped"]
    probs = _problems_for("SERVE_BENCH_overlap_ab_cpu_smoke.json",
                          no_arm, tmp_path)
    assert any("overlapped" in p for p in probs)
    no_field = _overlap_ab()
    del no_field["overlap_ab"]["lockstep"]["host_gap_fraction"]
    probs = _problems_for("SERVE_BENCH_overlap_ab_cpu_smoke.json",
                          no_field, tmp_path)
    assert any("host_gap_fraction" in p for p in probs)
    no_ratio = _overlap_ab()
    del no_ratio["overlap_ab"]["host_gap_fraction_ratio"]
    probs = _problems_for("SERVE_BENCH_overlap_ab_cpu_smoke.json",
                          no_ratio, tmp_path)
    assert any("host_gap_fraction_ratio" in p for p in probs)


def test_mesh_stamp_validated_when_present_elsewhere(tmp_path):
    # pre-stamp artifacts (no mesh) keep passing; a malformed stamp
    # never does
    res = {"throughput_tok_s": 1.0, "p50_ms": 2.0, "p99_ms": 3.0,
           "ttft_ms": 4.0, "stream_tok_s": 5.0}
    ok = dict(res, mesh={"tp": 1, "replicas": 2})
    assert _problems_for("SERVE_BENCH_x.json", ok, tmp_path) == []
    typed = dict(res, mesh={"tp": "1", "replicas": 2})
    assert _problems_for("SERVE_BENCH_x.json", typed, tmp_path)
    zero = dict(res, mesh={"tp": 1, "replicas": 0})
    assert _problems_for("SERVE_BENCH_x.json", zero, tmp_path)


# ---------------------------------------------------------------------------
# TRAIN_CHAOS family (tools/chaos_train.py artifacts)
# ---------------------------------------------------------------------------


def _chaos_ok():
    return {
        "seed": 45, "steps_total": 120, "checkpoint_interval": 6,
        "workers": 2, "restarts": 5, "preemptions": 1, "resizes": 1,
        "duplicate_steps": 0, "missing_steps": 0, "max_lost_steps": 6,
        "loss_max_abs_err": 0.0, "final_step": 119, "wall_s": 4.9,
        "injected": {"kill": 1, "hang": 1, "preempt": 1,
                     "torn_ckpt": 1},
        "schedule": [{"kind": "kill", "at_step": 15, "rank": 0,
                      "fired": True}],
        "elastic": {"min_world": 1, "max_world": 2},
        "git_sha": "abc1234",
    }


def test_train_chaos_valid_artifact_passes(tmp_path):
    assert _problems_for("TRAIN_CHAOS_x.json", _chaos_ok(),
                         tmp_path) == []


def test_train_chaos_rejects_zero_injected_faults(tmp_path):
    bad = _chaos_ok()
    bad["injected"] = {k: 0 for k in bad["injected"]}
    probs = _problems_for("TRAIN_CHAOS_x.json", bad, tmp_path)
    assert any("zero faults" in p for p in probs)


def test_train_chaos_rejects_duplicate_and_missing_steps(tmp_path):
    dup = dict(_chaos_ok(), duplicate_steps=3)
    probs = _problems_for("TRAIN_CHAOS_x.json", dup, tmp_path)
    assert any("duplicate" in p for p in probs)
    miss = dict(_chaos_ok(), missing_steps=2)
    probs = _problems_for("TRAIN_CHAOS_x.json", miss, tmp_path)
    assert any("missing" in p for p in probs)


def test_train_chaos_rejects_lost_progress_beyond_interval(tmp_path):
    bad = dict(_chaos_ok(), max_lost_steps=7)
    probs = _problems_for("TRAIN_CHAOS_x.json", bad, tmp_path)
    assert any("checkpoint interval" in p for p in probs)
    # Exactly one interval is the contract boundary: allowed.
    edge = dict(_chaos_ok(), max_lost_steps=6)
    assert _problems_for("TRAIN_CHAOS_x.json", edge, tmp_path) == []


def test_train_chaos_rejects_missing_seed(tmp_path):
    bad = _chaos_ok()
    del bad["seed"]
    probs = _problems_for("TRAIN_CHAOS_x.json", bad, tmp_path)
    assert any("seed" in p for p in probs)


def test_train_chaos_rejects_loss_divergence(tmp_path):
    bad = dict(_chaos_ok(), loss_max_abs_err=0.25)
    probs = _problems_for("TRAIN_CHAOS_x.json", bad, tmp_path)
    assert any("diverged" in p for p in probs)


def test_train_chaos_requires_elastic_block(tmp_path):
    bad = _chaos_ok()
    del bad["elastic"]
    probs = _problems_for("TRAIN_CHAOS_x.json", bad, tmp_path)
    assert any("elastic" in p for p in probs)
    bad = dict(_chaos_ok(), elastic={"min_world": 1})
    assert _problems_for("TRAIN_CHAOS_x.json", bad, tmp_path)


# ---------------------------------------------------------------------------
# SERVE_CHAOS family (tools/chaos_serve.py artifacts)
# ---------------------------------------------------------------------------


def _serve_chaos_ok():
    return {
        "seed": 47,
        "mesh": {"tp": 1, "replicas": 3},
        "knobs": {"duration_s": 3.0, "stall_deadline_s": 1.0},
        "schedule": [{"kind": "hang", "at_s": 0.9, "fired": True,
                      "target_idx": 2}],
        "injected": {"kill": 1, "hang": 1, "slow": 1, "readback": 1,
                     "stockout": 1, "kill_during_drain": 1},
        "requests": {"admitted": 360, "completed": 356,
                     "failed_typed": 3, "failed_injected": 1,
                     "lost": 0, "mismatched": 0, "shed": 220},
        "attainment": 0.9889, "attainment_floor": 0.5,
        "wedge": {"detected": True, "detect_stall_age_s": 1.06,
                  "within_deadline": True},
        "watchdog": {"ticks": 96, "suspected": 1, "recovered": 0,
                     "wedged": 1},
        "quiesced": True, "wall_s": 6.6, "git_sha": "abc1234",
    }


def test_serve_chaos_valid_artifact_passes(tmp_path):
    assert _problems_for("SERVE_CHAOS_x.json", _serve_chaos_ok(),
                         tmp_path) == []


def test_serve_chaos_rejects_lost_requests(tmp_path):
    bad = _serve_chaos_ok()
    bad["requests"]["lost"] = 1
    probs = _problems_for("SERVE_CHAOS_x.json", bad, tmp_path)
    assert any("LOST" in p for p in probs)


def test_serve_chaos_rejects_mismatched_completions(tmp_path):
    bad = _serve_chaos_ok()
    bad["requests"]["mismatched"] = 2
    probs = _problems_for("SERVE_CHAOS_x.json", bad, tmp_path)
    assert any("not token-identical" in p for p in probs)


def test_serve_chaos_rejects_undetected_or_late_wedge(tmp_path):
    undetected = _serve_chaos_ok()
    undetected["wedge"]["detected"] = False
    probs = _problems_for("SERVE_CHAOS_x.json", undetected, tmp_path)
    assert any("undetected" in p for p in probs)
    late = _serve_chaos_ok()
    late["wedge"]["within_deadline"] = False
    probs = _problems_for("SERVE_CHAOS_x.json", late, tmp_path)
    assert any("past the stall deadline" in p for p in probs)
    gone = _serve_chaos_ok()
    del gone["wedge"]
    probs = _problems_for("SERVE_CHAOS_x.json", gone, tmp_path)
    assert any("wedge" in p for p in probs)


def test_serve_chaos_rejects_faultless_campaign(tmp_path):
    # a campaign that never fired its headline faults proves nothing
    for kind in ("kill", "hang", "stockout"):
        bad = _serve_chaos_ok()
        bad["injected"][kind] = 0
        probs = _problems_for("SERVE_CHAOS_x.json", bad, tmp_path)
        assert any(f"never fired a {kind!r}" in p
                   for p in probs), kind
    # a slow-step that never fired is only a lost false-positive
    # control, not a refusal
    ok = _serve_chaos_ok()
    ok["injected"]["slow"] = 0
    assert _problems_for("SERVE_CHAOS_x.json", ok, tmp_path) == []


def test_serve_chaos_rejects_attainment_below_recorded_floor(tmp_path):
    bad = _serve_chaos_ok()
    bad["attainment"] = 0.4
    probs = _problems_for("SERVE_CHAOS_x.json", bad, tmp_path)
    assert any("below the run's own recorded floor" in p
               for p in probs)


def test_serve_chaos_rejects_missing_seed_or_mesh(tmp_path):
    no_seed = _serve_chaos_ok()
    del no_seed["seed"]
    probs = _problems_for("SERVE_CHAOS_x.json", no_seed, tmp_path)
    assert any("seed" in p for p in probs)
    no_mesh = _serve_chaos_ok()
    del no_mesh["mesh"]
    probs = _problems_for("SERVE_CHAOS_x.json", no_mesh, tmp_path)
    assert any("mesh stamp" in p for p in probs)


def test_serve_chaos_rejects_unquiesced_or_idle_pool(tmp_path):
    leaky = _serve_chaos_ok()
    leaky["quiesced"] = False
    probs = _problems_for("SERVE_CHAOS_x.json", leaky, tmp_path)
    assert any("quiesce" in p for p in probs)
    idle = _serve_chaos_ok()
    idle["requests"]["admitted"] = 0
    probs = _problems_for("SERVE_CHAOS_x.json", idle, tmp_path)
    assert any("zero requests" in p for p in probs)


def test_serve_chaos_flight_recorder_validated_if_present(tmp_path):
    # campaigns predating the recorder carry no block and still pass
    assert _problems_for("SERVE_CHAOS_x.json", _serve_chaos_ok(),
                         tmp_path) == []
    ok = _serve_chaos_ok()
    ok["flight_recorder"] = {"dir": "/tmp/f", "bundles": 3,
                             "reasons": ["engine-fail-all", "wedged-r1"],
                             "kill_explained": True,
                             "hang_explained": True}
    assert _problems_for("SERVE_CHAOS_x.json", ok, tmp_path) == []
    empty = _serve_chaos_ok()
    empty["flight_recorder"] = {"bundles": 0, "kill_explained": True,
                                "hang_explained": True}
    probs = _problems_for("SERVE_CHAOS_x.json", empty, tmp_path)
    assert any("no flight bundles" in p for p in probs)
    for key, what in (("kill_explained", "kill"),
                      ("hang_explained", "hang")):
        bad = _serve_chaos_ok()
        bad["flight_recorder"] = {"bundles": 2, "kill_explained": True,
                                  "hang_explained": True}
        bad["flight_recorder"][key] = False
        probs = _problems_for("SERVE_CHAOS_x.json", bad, tmp_path)
        assert any(f"no bundle explains the injected {what}" in p
                   for p in probs), key


def _migration_drill():
    # the kv_migration fault-drill block as tools/chaos_serve.py
    # _run_migration_phases emits it
    return {
        "donor_kill_mid_pull": {
            "prefix_pages": 12, "aborts": 1, "fallbacks": 1,
            "completed_token_identical": True,
            "busy_outcome": "completed",
            "sacrifice_outcome": "completed"},
        "peer_resume": {
            "migrated_pages": 12, "pull_fallbacks": 0,
            "resume_token_identical": True,
            "peer_prefix_hit_tokens_delta": 96,
            "busy_outcome": "completed"},
        "requests": {"admitted": 8, "lost": 0, "mismatched": 0},
        "flight": {"donor_kill_explained": True,
                   "peer_resume_explained": True, "kill_bundles": 3},
        "quiesced": True,
    }


def test_serve_chaos_kv_migration_validated_if_present(tmp_path):
    # campaigns predating the migration drill carry no block and pass
    assert _problems_for("SERVE_CHAOS_x.json", _serve_chaos_ok(),
                         tmp_path) == []
    ok = _serve_chaos_ok()
    ok["kv_migration"] = _migration_drill()
    assert _problems_for("SERVE_CHAOS_x.json", ok, tmp_path) == []
    not_obj = _serve_chaos_ok()
    not_obj["kv_migration"] = 7
    probs = _problems_for("SERVE_CHAOS_x.json", not_obj, tmp_path)
    assert any("must be an object" in p for p in probs)
    for phase in ("donor_kill_mid_pull", "peer_resume", "flight"):
        bad = _serve_chaos_ok()
        bad["kv_migration"] = _migration_drill()
        del bad["kv_migration"][phase]
        probs = _problems_for("SERVE_CHAOS_x.json", bad, tmp_path)
        assert any(f"'{phase}'" in p for p in probs), phase


def test_serve_chaos_kv_migration_rejects_unexercised_abort(tmp_path):
    # a donor kill that produced no plain-prefill fallback never
    # exercised the abort path the drill exists to prove
    bad = _serve_chaos_ok()
    bad["kv_migration"] = _migration_drill()
    bad["kv_migration"]["donor_kill_mid_pull"]["fallbacks"] = 0
    probs = _problems_for("SERVE_CHAOS_x.json", bad, tmp_path)
    assert any("no plain-prefill fallback" in p for p in probs)
    bad = _serve_chaos_ok()
    bad["kv_migration"] = _migration_drill()
    bad["kv_migration"]["donor_kill_mid_pull"][
        "completed_token_identical"] = False
    probs = _problems_for("SERVE_CHAOS_x.json", bad, tmp_path)
    assert any("did not complete token-identically" in p
               for p in probs)


def test_serve_chaos_kv_migration_rejects_recomputed_resume(tmp_path):
    bad = _serve_chaos_ok()
    bad["kv_migration"] = _migration_drill()
    bad["kv_migration"]["peer_resume"]["migrated_pages"] = 0
    probs = _problems_for("SERVE_CHAOS_x.json", bad, tmp_path)
    assert any("nothing migrated" in p for p in probs)
    bad = _serve_chaos_ok()
    bad["kv_migration"] = _migration_drill()
    bad["kv_migration"]["peer_resume"]["resume_token_identical"] = False
    probs = _problems_for("SERVE_CHAOS_x.json", bad, tmp_path)
    assert any("did not resume token-identically" in p for p in probs)
    # zero prefix hit-tokens on the peer means the session was
    # silently recomputed — the pages moved for nothing
    bad = _serve_chaos_ok()
    bad["kv_migration"] = _migration_drill()
    bad["kv_migration"]["peer_resume"][
        "peer_prefix_hit_tokens_delta"] = 0
    probs = _problems_for("SERVE_CHAOS_x.json", bad, tmp_path)
    assert any("recomputed, not resumed" in p for p in probs)


def test_serve_chaos_kv_migration_rejects_losses_and_leaks(tmp_path):
    for key in ("lost", "mismatched"):
        bad = _serve_chaos_ok()
        bad["kv_migration"] = _migration_drill()
        bad["kv_migration"]["requests"][key] = 1
        probs = _problems_for("SERVE_CHAOS_x.json", bad, tmp_path)
        assert any(key in p and "migration drill" in p
                   for p in probs), key
    for key, what in (("donor_kill_explained", "donor kill"),
                      ("peer_resume_explained", "peer resume")):
        bad = _serve_chaos_ok()
        bad["kv_migration"] = _migration_drill()
        bad["kv_migration"]["flight"][key] = False
        probs = _problems_for("SERVE_CHAOS_x.json", bad, tmp_path)
        assert any(f"no flight bundle explains the {what}" in p
                   for p in probs), key
    bad = _serve_chaos_ok()
    bad["kv_migration"] = _migration_drill()
    bad["kv_migration"]["quiesced"] = False
    probs = _problems_for("SERVE_CHAOS_x.json", bad, tmp_path)
    assert any("did not quiesce leak-free" in p for p in probs)


def _rollout_drill():
    # the weight_rollout fault-drill block as tools/chaos_serve.py
    # _run_rollout_phases emits it
    return {
        "kill_mid_swap": {
            "completed": True, "converged": True,
            "swap_attempts": 2, "weights_id": "13f3a0203ac3"},
        "torn_checkpoint": {
            "refused_typed": True, "fleet_untouched": True,
            "flipped_file": "arrays/x", "reason": "hash mismatch"},
        "controller_resume": {
            "completed": True, "converged": True,
            "resumed_replicas": 1, "weights_id": "e7b2d4403dc6"},
        "requests": {"admitted": 27, "completed": 27,
                     "failed_typed": 0, "lost": 0, "mismatched": 0},
        "flight": {"kill_mid_swap_explained": True,
                   "rollout_done_explained": True},
        "quiesced": True,
    }


def test_serve_chaos_weight_rollout_validated_if_present(tmp_path):
    # campaigns predating the rollout drill carry no block and pass
    ok = _serve_chaos_ok()
    ok["weight_rollout"] = _rollout_drill()
    assert _problems_for("SERVE_CHAOS_x.json", ok, tmp_path) == []
    not_obj = _serve_chaos_ok()
    not_obj["weight_rollout"] = 7
    probs = _problems_for("SERVE_CHAOS_x.json", not_obj, tmp_path)
    assert any("must be an object" in p for p in probs)
    for phase in ("kill_mid_swap", "torn_checkpoint",
                  "controller_resume", "flight"):
        bad = _serve_chaos_ok()
        bad["weight_rollout"] = _rollout_drill()
        del bad["weight_rollout"][phase]
        probs = _problems_for("SERVE_CHAOS_x.json", bad, tmp_path)
        assert any(f"'{phase}'" in p for p in probs), phase


def test_serve_chaos_weight_rollout_rejects_unconverged_kill(tmp_path):
    bad = _serve_chaos_ok()
    bad["weight_rollout"] = _rollout_drill()
    bad["weight_rollout"]["kill_mid_swap"]["completed"] = False
    probs = _problems_for("SERVE_CHAOS_x.json", bad, tmp_path)
    assert any("did not complete after the mid-swap kill" in p
               for p in probs)
    bad = _serve_chaos_ok()
    bad["weight_rollout"] = _rollout_drill()
    bad["weight_rollout"]["kill_mid_swap"]["converged"] = False
    probs = _problems_for("SERVE_CHAOS_x.json", bad, tmp_path)
    assert any("did not converge" in p for p in probs)
    # one attempt means the swap never actually raced the kill — the
    # drill proved nothing about mid-swap recovery
    bad = _serve_chaos_ok()
    bad["weight_rollout"] = _rollout_drill()
    bad["weight_rollout"]["kill_mid_swap"]["swap_attempts"] = 1
    probs = _problems_for("SERVE_CHAOS_x.json", bad, tmp_path)
    assert any("kill never landed mid-swap" in p for p in probs)


def test_serve_chaos_weight_rollout_rejects_torn_acceptance(tmp_path):
    bad = _serve_chaos_ok()
    bad["weight_rollout"] = _rollout_drill()
    bad["weight_rollout"]["torn_checkpoint"]["refused_typed"] = False
    probs = _problems_for("SERVE_CHAOS_x.json", bad, tmp_path)
    assert any("not refused with the typed error" in p for p in probs)
    bad = _serve_chaos_ok()
    bad["weight_rollout"] = _rollout_drill()
    bad["weight_rollout"]["torn_checkpoint"]["fleet_untouched"] = False
    probs = _problems_for("SERVE_CHAOS_x.json", bad, tmp_path)
    assert any("mutated fleet weights" in p for p in probs)


def test_serve_chaos_weight_rollout_rejects_broken_resume(tmp_path):
    bad = _serve_chaos_ok()
    bad["weight_rollout"] = _rollout_drill()
    bad["weight_rollout"]["controller_resume"]["completed"] = False
    probs = _problems_for("SERVE_CHAOS_x.json", bad, tmp_path)
    assert any("resumed" in p and "did not complete" in p
               for p in probs)
    # zero resumed replicas: the fresh controller started from
    # scratch — controller-death resumability was never exercised
    bad = _serve_chaos_ok()
    bad["weight_rollout"] = _rollout_drill()
    bad["weight_rollout"]["controller_resume"]["resumed_replicas"] = 0
    probs = _problems_for("SERVE_CHAOS_x.json", bad, tmp_path)
    assert any("resume path was never exercised" in p for p in probs)


def test_serve_chaos_weight_rollout_rejects_losses_and_leaks(tmp_path):
    for key in ("lost", "mismatched"):
        bad = _serve_chaos_ok()
        bad["weight_rollout"] = _rollout_drill()
        bad["weight_rollout"]["requests"][key] = 1
        probs = _problems_for("SERVE_CHAOS_x.json", bad, tmp_path)
        assert any(key in p and "rollout drill" in p
                   for p in probs), key
    for key, what in (("kill_mid_swap_explained", "mid-swap kill"),
                      ("rollout_done_explained",
                       "completed rollout")):
        bad = _serve_chaos_ok()
        bad["weight_rollout"] = _rollout_drill()
        bad["weight_rollout"]["flight"][key] = False
        probs = _problems_for("SERVE_CHAOS_x.json", bad, tmp_path)
        assert any(f"no flight bundle explains the {what}" in p
                   for p in probs), key
    bad = _serve_chaos_ok()
    bad["weight_rollout"] = _rollout_drill()
    bad["weight_rollout"]["quiesced"] = False
    probs = _problems_for("SERVE_CHAOS_x.json", bad, tmp_path)
    assert any("rollout-drill pools" in p for p in probs)


# ---------------------------------------------------------------------------
# SERVE_TRACE family (serve_bench.py --trace artifacts)
# ---------------------------------------------------------------------------


def _serve_trace_ok():
    events = [
        {"seq": 0, "t": 10.0, "type": "submit", "rid": 1, "sid": None,
         "data": {"trace_id": "a" * 16}},
        {"seq": 1, "t": 10.1, "type": "admit", "rid": 1, "sid": 0,
         "data": None},
        {"seq": 2, "t": 10.15, "type": "prefill", "rid": [1],
         "sid": None, "data": [[0, 8]]},
        {"seq": 3, "t": 10.3, "type": "first_token", "rid": 1,
         "sid": 0, "data": {"ttft_s": 0.3}},
        {"seq": 4, "t": 10.6, "type": "retire", "rid": 1, "sid": 0,
         "data": None},
    ]
    return {
        "seed": 0,
        "mesh": {"tp": 1, "replicas": 1},
        "requests": {"1": {"trace_id": "a" * 16, "outcome": "retire",
                           "ttft_s": 0.3, "total_s": 0.6}},
        "events": events,
        "trace_events": [{"name": "process_name", "ph": "M", "pid": 1,
                          "tid": 0, "args": {"name": "engine"}}],
        "overhead": {"tokens_s_events_on": 100.0,
                     "tokens_s_events_off": 101.0, "ratio": 0.99},
        "report": {"ttft_check": {"n": 1, "max_abs_err_s": 0.0,
                                  "within_1ms": True}},
        "git_sha": "abc1234",
    }


def test_serve_trace_valid_artifact_passes(tmp_path):
    assert _problems_for("SERVE_TRACE_x.json", _serve_trace_ok(),
                         tmp_path) == []


def test_serve_trace_rejects_unordered_timestamps(tmp_path):
    bad = _serve_trace_ok()
    bad["events"][3]["t"] = 10.05       # earlier than its predecessor
    probs = _problems_for("SERVE_TRACE_x.json", bad, tmp_path)
    assert any("BACKWARDS" in p for p in probs)
    bad = _serve_trace_ok()
    bad["events"][2]["seq"] = 0         # seq must strictly increase
    probs = _problems_for("SERVE_TRACE_x.json", bad, tmp_path)
    assert any("not increasing" in p for p in probs)


def test_serve_trace_rejects_orphan_rids(tmp_path):
    scalar = _serve_trace_ok()
    scalar["events"][4]["rid"] = 99
    probs = _problems_for("SERVE_TRACE_x.json", scalar, tmp_path)
    assert any("orphan" in p and "'99'" in p for p in probs)
    # list rids (batched prefill) are checked element-wise
    batched = _serve_trace_ok()
    batched["events"][2]["rid"] = [1, 7]
    probs = _problems_for("SERVE_TRACE_x.json", batched, tmp_path)
    assert any("orphan" in p and "'7'" in p for p in probs)


def test_serve_trace_rejects_missing_seed_or_mesh(tmp_path):
    no_seed = _serve_trace_ok()
    del no_seed["seed"]
    probs = _problems_for("SERVE_TRACE_x.json", no_seed, tmp_path)
    assert any("seed" in p for p in probs)
    no_mesh = _serve_trace_ok()
    del no_mesh["mesh"]
    probs = _problems_for("SERVE_TRACE_x.json", no_mesh, tmp_path)
    assert any("mesh stamp" in p for p in probs)


def test_serve_trace_rejects_empty_capture(tmp_path):
    empty_req = _serve_trace_ok()
    empty_req["requests"] = {}
    probs = _problems_for("SERVE_TRACE_x.json", empty_req, tmp_path)
    assert any("captured no requests" in p for p in probs)
    empty_ev = _serve_trace_ok()
    empty_ev["events"] = []
    probs = _problems_for("SERVE_TRACE_x.json", empty_ev, tmp_path)
    assert any("events list is empty" in p for p in probs)


def test_serve_trace_rejects_failed_ttft_cross_check(tmp_path):
    bad = _serve_trace_ok()
    bad["report"]["ttft_check"] = {"n": 3, "max_abs_err_s": 0.01,
                                   "within_1ms": False}
    probs = _problems_for("SERVE_TRACE_x.json", bad, tmp_path)
    assert any("TTFT" in p and "1ms" in p for p in probs)
    # a report with zero cross-checked requests is a capture problem
    # handled elsewhere, not a cross-check failure
    ok = _serve_trace_ok()
    ok["report"]["ttft_check"] = {"n": 0, "max_abs_err_s": None,
                                  "within_1ms": False}
    assert _problems_for("SERVE_TRACE_x.json", ok, tmp_path) == []


def test_serve_trace_rejects_missing_overhead_fields(tmp_path):
    bad = _serve_trace_ok()
    del bad["overhead"]["ratio"]
    probs = _problems_for("SERVE_TRACE_x.json", bad, tmp_path)
    assert any("overhead" in p and "ratio" in p for p in probs)


# ---------------------------------------------------------------------------
# SERVE_FLEET_CHAOS family (tools/chaos_serve.py --fleet artifacts)
# ---------------------------------------------------------------------------


def _fleet_chaos_ok():
    return {
        "schema_version": 2,
        "seed": 47,
        "topology": {"agents": 3, "transport": "tcp-json-v1",
                     "processes": {"directory": 1, "standby": 1,
                                   "agents_spawned": 4},
                     "model": "fake", "lease_ttl_s": 1.0},
        "knobs": {"duration_s": 4.0},
        "schedule": [{"kind": "kill_agent", "at_s": 0.9,
                      "fired": True}],
        "injected": {"kill_agent": 1, "partition": 1,
                     "directory_restart": 1,
                     "torn_wal_restart": 1, "primary_kill": 1,
                     "autoscale_churn": 1},
        "requests": {"admitted": 250, "completed": 246,
                     "failed_typed": 2, "lost": 0, "mismatched": 0,
                     "shed": 9, "resubmitted_ok": 2},
        "attainment": 0.98, "attainment_floor": 0.5,
        "failover": {"promoted": True, "epoch_after": 1,
                     "fence_before": 7, "fence_after": 1031,
                     "canary": {"token_identical": True}},
        "fence_monotonic": True,
        "wal_recovery": {
            "directory_restarts": [
                {"recovered_from_wal": True,
                 "recovered_members": 3}],
            "torn_wal_restarts": [
                {"torn_records_truncated": 1,
                 "recovered_members": 3}],
        },
        "autoscale_churn": {"churns": [
            {"rid": "auto-0", "state": "retired",
             "absent_after_retire": True, "tombstoned": True}]},
        "flight_recorder": {"bundles": 5,
                            "kill_explained": True,
                            "partition_explained": True,
                            "directory_restart_explained": True,
                            "torn_wal_explained": True,
                            "failover_explained": True,
                            "faults_explained": True},
        "quiesced": True, "wall_s": 5.1, "git_sha": "abc1234",
    }


def test_fleet_chaos_valid_artifact_passes(tmp_path):
    assert _problems_for("SERVE_FLEET_CHAOS_x.json",
                         _fleet_chaos_ok(), tmp_path) == []


def test_fleet_chaos_rejects_lost_or_mismatched(tmp_path):
    bad = _fleet_chaos_ok()
    bad["requests"]["lost"] = 1
    probs = _problems_for("SERVE_FLEET_CHAOS_x.json", bad, tmp_path)
    assert any("LOST" in p for p in probs)
    bad = _fleet_chaos_ok()
    bad["requests"]["mismatched"] = 2
    probs = _problems_for("SERVE_FLEET_CHAOS_x.json", bad, tmp_path)
    assert any("mismatched" in p for p in probs)


def test_fleet_chaos_rejects_missing_seed_or_topology(tmp_path):
    bad = _fleet_chaos_ok()
    del bad["seed"]
    assert any("seed" in p for p in _problems_for(
        "SERVE_FLEET_CHAOS_x.json", bad, tmp_path))
    bad = _fleet_chaos_ok()
    del bad["topology"]
    assert any("topology" in p for p in _problems_for(
        "SERVE_FLEET_CHAOS_x.json", bad, tmp_path))
    bad = _fleet_chaos_ok()
    del bad["topology"]["processes"]
    assert any("processes" in p for p in _problems_for(
        "SERVE_FLEET_CHAOS_x.json", bad, tmp_path))
    bad = _fleet_chaos_ok()
    bad["topology"]["agents"] = 1   # one agent proves no failover
    assert _problems_for("SERVE_FLEET_CHAOS_x.json", bad, tmp_path)


def test_fleet_chaos_rejects_unfired_fault_kind(tmp_path):
    bad = _fleet_chaos_ok()
    bad["injected"]["directory_restart"] = 0
    probs = _problems_for("SERVE_FLEET_CHAOS_x.json", bad, tmp_path)
    assert any("directory_restart" in p for p in probs)
    bad = _fleet_chaos_ok()
    del bad["injected"]["partition"]
    assert any("partition" in p for p in _problems_for(
        "SERVE_FLEET_CHAOS_x.json", bad, tmp_path))


def test_fleet_chaos_rejects_unexplained_fault(tmp_path):
    bad = _fleet_chaos_ok()
    bad["flight_recorder"]["partition_explained"] = False
    probs = _problems_for("SERVE_FLEET_CHAOS_x.json", bad, tmp_path)
    assert any("partition" in p for p in probs)
    bad = _fleet_chaos_ok()
    del bad["flight_recorder"]
    assert any("flight_recorder" in p for p in _problems_for(
        "SERVE_FLEET_CHAOS_x.json", bad, tmp_path))


def test_fleet_chaos_rejects_no_resubmit_proof_or_unquiesced(tmp_path):
    bad = _fleet_chaos_ok()
    bad["requests"]["resubmitted_ok"] = 0
    probs = _problems_for("SERVE_FLEET_CHAOS_x.json", bad, tmp_path)
    assert any("resubmit" in p for p in probs)
    bad = _fleet_chaos_ok()
    bad["quiesced"] = False
    assert any("quiesce" in p for p in _problems_for(
        "SERVE_FLEET_CHAOS_x.json", bad, tmp_path))
    bad = _fleet_chaos_ok()
    bad["attainment"] = 0.4     # below its own recorded floor
    assert any("floor" in p for p in _problems_for(
        "SERVE_FLEET_CHAOS_x.json", bad, tmp_path))


def test_fleet_chaos_v2_rejects_unversioned_artifact(tmp_path):
    bad = _fleet_chaos_ok()
    del bad["schema_version"]
    probs = _problems_for("SERVE_FLEET_CHAOS_x.json", bad, tmp_path)
    assert any("schema_version" in p for p in probs)
    bad = _fleet_chaos_ok()
    bad["schema_version"] = 1
    probs = _problems_for("SERVE_FLEET_CHAOS_x.json", bad, tmp_path)
    assert any("schema_version" in p for p in probs)


def test_fleet_chaos_v2_rejects_missing_failover_proof(tmp_path):
    bad = _fleet_chaos_ok()
    del bad["failover"]
    probs = _problems_for("SERVE_FLEET_CHAOS_x.json", bad, tmp_path)
    assert any("failover" in p for p in probs)
    bad = _fleet_chaos_ok()
    bad["failover"]["promoted"] = False
    probs = _problems_for("SERVE_FLEET_CHAOS_x.json", bad, tmp_path)
    assert any("never promoted" in p for p in probs)
    bad = _fleet_chaos_ok()
    bad["failover"]["canary"]["token_identical"] = False
    probs = _problems_for("SERVE_FLEET_CHAOS_x.json", bad, tmp_path)
    assert any("canary" in p for p in probs)
    bad = _fleet_chaos_ok()
    bad["fence_monotonic"] = False
    probs = _problems_for("SERVE_FLEET_CHAOS_x.json", bad, tmp_path)
    assert any("fence_monotonic" in p for p in probs)


def test_fleet_chaos_v2_rejects_missing_wal_recovery_proof(tmp_path):
    bad = _fleet_chaos_ok()
    del bad["wal_recovery"]
    probs = _problems_for("SERVE_FLEET_CHAOS_x.json", bad, tmp_path)
    assert any("wal_recovery" in p for p in probs)
    bad = _fleet_chaos_ok()
    bad["wal_recovery"]["directory_restarts"][0][
        "recovered_from_wal"] = False
    probs = _problems_for("SERVE_FLEET_CHAOS_x.json", bad, tmp_path)
    assert any("re-advertisement" in p for p in probs)
    bad = _fleet_chaos_ok()
    bad["wal_recovery"]["torn_wal_restarts"][0][
        "torn_records_truncated"] = 0
    probs = _problems_for("SERVE_FLEET_CHAOS_x.json", bad, tmp_path)
    assert any("torn" in p for p in probs)
    bad = _fleet_chaos_ok()
    bad["wal_recovery"]["torn_wal_restarts"] = []
    probs = _problems_for("SERVE_FLEET_CHAOS_x.json", bad, tmp_path)
    assert any("truncate-don't-replay" in p for p in probs)


def test_fleet_chaos_v2_rejects_incomplete_churn_lifecycle(tmp_path):
    bad = _fleet_chaos_ok()
    del bad["autoscale_churn"]
    probs = _problems_for("SERVE_FLEET_CHAOS_x.json", bad, tmp_path)
    assert any("autoscale_churn" in p for p in probs)
    bad = _fleet_chaos_ok()
    bad["autoscale_churn"]["churns"][0]["tombstoned"] = False
    probs = _problems_for("SERVE_FLEET_CHAOS_x.json", bad, tmp_path)
    assert any("lifecycle" in p for p in probs)
    bad = _fleet_chaos_ok()
    bad["injected"]["primary_kill"] = 0
    probs = _problems_for("SERVE_FLEET_CHAOS_x.json", bad, tmp_path)
    assert any("primary_kill" in p for p in probs)


# ---------------------------------------------------------------------------
# SERVE_FLEET_TRACE family (serve_bench.py --fleet N --trace artifacts)
# ---------------------------------------------------------------------------


def _fleet_trace_ok():
    def member(role, pid, unc):
        return {"role": role, "up": True, "pid": pid,
                "generation": 0, "offset_s": 0.0001,
                "uncertainty_s": unc, "events_total": 4,
                "dropped": 0}

    def span(member_name, role, pid, s, e):
        return {"role": role, "replica_id": member_name, "pid": pid,
                "generation": 0, "start_s": s, "end_s": e,
                "offset_uncertainty_s": 0.0002,
                "etypes": ["submit"], "rids": []}

    tid = "f" * 16
    proof = {
        "trace_id": tid,
        "spans": [span("router", "router", 100, 10.0, 10.4),
                  span("tr0", "agent", 200, 10.01, 10.02),
                  span("tr1", "agent", 300, 10.2, 10.4)],
        "processes": [100, 200, 300], "n_processes": 3,
        "members": ["router", "tr0", "tr1"], "stitched": True,
        "events": 5, "outcome": "resubmitted", "n_tokens": 6,
    }
    events = [
        {"member": "router", "role": "router", "pid": 100,
         "generation": 0, "seq": 0, "t": 10.0, "local_t": 10.0,
         "offset_uncertainty_s": 0.0, "type": "submit", "rid": None,
         "data": {"trace_id": tid}},
        {"member": "tr0", "role": "agent", "pid": 200,
         "generation": 0, "seq": 0, "t": 10.011, "local_t": 10.01,
         "offset_uncertainty_s": 0.0002, "type": "submit",
         "rid": "tr0.g0.1", "data": {"trace_id": tid}},
        {"member": "tr1", "role": "agent", "pid": 300,
         "generation": 0, "seq": 0, "t": 10.19, "local_t": 10.2,
         "offset_uncertainty_s": 0.0002, "type": "submit",
         "rid": "tr1.g0.1", "data": {"trace_id": tid}},
    ]
    return {
        "fleet": {"transport": "tcp-json-v1", "agents": 2,
                  "lease_ttl_s": 0.6},
        "offset_bound_s": 0.05,
        "members": {"router": member("router", 100, 0.0),
                    "directory": member("directory", 50, 0.0003),
                    "tr0": member("agent", 200, 0.0002),
                    "tr1": member("agent", 300, 0.0002)},
        "collector": {"members": 4, "members_up": 4},
        "requests": {tid: proof},
        "stitch": {"traces": 1, "stitched_traces": 1,
                   "max_processes": 3, "proof_trace_id": tid,
                   "killed_replica": "tr0", "resubmits": 1,
                   "deaths_confirmed": 1},
        "events": events,
        "trace_events": [{"ph": "M", "name": "process_name",
                          "pid": 100, "tid": 0,
                          "args": {"name": "router"}}],
        "seed": 7,
        "mesh": {"tp": 1, "replicas": 2},
        "git_sha": "abc1234",
    }


def test_fleet_trace_valid_artifact_passes(tmp_path):
    assert _problems_for("SERVE_FLEET_TRACE_x.json",
                         _fleet_trace_ok(), tmp_path) == []


def test_fleet_trace_rejects_offset_uncertainty_above_bound(tmp_path):
    bad = _fleet_trace_ok()
    bad["members"]["tr0"]["uncertainty_s"] = 0.2
    probs = _problems_for("SERVE_FLEET_TRACE_x.json", bad, tmp_path)
    assert any("exceeds the stamped bound" in p for p in probs)
    # a span above the bound is refused even if the table passes
    bad = _fleet_trace_ok()
    tid = "f" * 16
    bad["requests"][tid]["spans"][1]["offset_uncertainty_s"] = 0.2
    probs = _problems_for("SERVE_FLEET_TRACE_x.json", bad, tmp_path)
    assert any("span uncertainty" in p for p in probs)
    # an up member with NO estimate cannot be placed at all
    bad = _fleet_trace_ok()
    bad["members"]["tr1"]["uncertainty_s"] = None
    probs = _problems_for("SERVE_FLEET_TRACE_x.json", bad, tmp_path)
    assert any("without a numeric offset uncertainty" in p
               for p in probs)


def test_fleet_trace_rejects_missing_member_coverage(tmp_path):
    bad = _fleet_trace_ok()
    del bad["members"]["directory"]
    probs = _problems_for("SERVE_FLEET_TRACE_x.json", bad, tmp_path)
    assert any("no 'directory' member" in p for p in probs)
    # spans naming a member absent from the offset table are orphans
    bad = _fleet_trace_ok()
    del bad["members"]["tr1"]
    probs = _problems_for("SERVE_FLEET_TRACE_x.json", bad, tmp_path)
    assert any("absent from the offset table" in p for p in probs)


def test_fleet_trace_rejects_unstitched_proof(tmp_path):
    tid = "f" * 16
    # proof trace collapsed to one process: refused
    bad = _fleet_trace_ok()
    req = bad["requests"][tid]
    req["spans"] = [req["spans"][0]]
    req["processes"], req["n_processes"] = [100], 1
    req["stitched"] = False
    probs = _problems_for("SERVE_FLEET_TRACE_x.json", bad, tmp_path)
    assert any("did not stitch across >= 3 processes" in p
               for p in probs)
    # max_processes below 3 proves nothing about cross-process work
    bad = _fleet_trace_ok()
    bad["stitch"]["max_processes"] = 2
    probs = _problems_for("SERVE_FLEET_TRACE_x.json", bad, tmp_path)
    assert any("max_processes" in p for p in probs)
    # a stitched flag disagreeing with the span pids is a lie
    bad = _fleet_trace_ok()
    bad["requests"][tid]["stitched"] = False
    probs = _problems_for("SERVE_FLEET_TRACE_x.json", bad, tmp_path)
    assert any("disagrees with" in p for p in probs)


def test_fleet_trace_rejects_unaligned_timebase(tmp_path):
    bad = _fleet_trace_ok()
    bad["events"][2]["local_t"] = 9.0     # before its predecessor
    probs = _problems_for("SERVE_FLEET_TRACE_x.json", bad, tmp_path)
    assert any("BACKWARDS" in p for p in probs)
    bad = _fleet_trace_ok()
    bad["events"][1]["local_t"] = None    # unplaced event
    probs = _problems_for("SERVE_FLEET_TRACE_x.json", bad, tmp_path)
    assert any("local_t" in p for p in probs)


def test_fleet_trace_rejects_empty_capture(tmp_path):
    bad = _fleet_trace_ok()
    bad["requests"] = {}
    probs = _problems_for("SERVE_FLEET_TRACE_x.json", bad, tmp_path)
    assert any("stitched nothing" in p for p in probs)
    bad = _fleet_trace_ok()
    bad["events"] = []
    probs = _problems_for("SERVE_FLEET_TRACE_x.json", bad, tmp_path)
    assert any("events list is empty" in p for p in probs)


# ---------------------------------------------------- kvq A/B family


def _kvq_capacity(n_pages, slots, page_bytes, sheds):
    return {"n_pages": n_pages, "effective_slots": slots,
            "page_bytes": page_bytes,
            "kv_bytes_total": n_pages * page_bytes,
            "burst": 20, "sheds": sheds, "completed": 20 - sheds,
            "prefix_cached_pages": 4, "prefix_hit_rate": 0.2}


def _kvq_ab():
    return {"kvq_ab": {"byte_budget": 98304, "page_size": 8,
                       "fp": {"parity": {"wall_s": 0.03,
                                         "requests": 8,
                                         "gen_tokens": 16},
                              "capacity": _kvq_capacity(
                                  48, 9, 2048, 11)},
                       "int8": {"parity": {"wall_s": 0.03,
                                           "requests": 8,
                                           "gen_tokens": 16},
                                "capacity": _kvq_capacity(
                                    93, 18, 1056, 2)},
                       "parity": {"token_agreement": 0.85,
                                  "token_agreement_floor": 0.8,
                                  "tokens_checked": 128,
                                  "spec_accept_rate_fp": 1.0,
                                  "spec_accept_rate_int8": 1.0,
                                  "spec_accept_noise": 0.15},
                       "capacity_ratio": 1.94,
                       "slots_ratio": 2.0,
                       "shed_delta": 9},
            "mesh": {"tp": 1, "replicas": 1}, "seed": 0,
            "model": "llama-tiny", "git_sha": "abc1234"}


def test_kvq_ab_artifact_validates(tmp_path):
    assert _problems_for("SERVE_BENCH_kvq_ab_cpu_smoke.json",
                         _kvq_ab(), tmp_path) == []


def test_kvq_ab_refuses_missing_stamp(tmp_path):
    no_mesh = {k: v for k, v in _kvq_ab().items() if k != "mesh"}
    probs = _problems_for("SERVE_BENCH_kvq_ab_cpu_smoke.json",
                          no_mesh, tmp_path)
    assert any("mesh stamp" in p for p in probs)
    no_seed = {k: v for k, v in _kvq_ab().items() if k != "seed"}
    probs = _problems_for("SERVE_BENCH_kvq_ab_cpu_smoke.json",
                          no_seed, tmp_path)
    assert any("seed" in p for p in probs)


def test_kvq_ab_refuses_missing_byte_budget(tmp_path):
    # a capacity claim without its budget proves nothing
    no_budget = _kvq_ab()
    del no_budget["kvq_ab"]["byte_budget"]
    probs = _problems_for("SERVE_BENCH_kvq_ab_cpu_smoke.json",
                          no_budget, tmp_path)
    assert any("byte-budget" in p for p in probs)
    typed = _kvq_ab()
    typed["kvq_ab"]["byte_budget"] = "98304"
    probs = _problems_for("SERVE_BENCH_kvq_ab_cpu_smoke.json",
                          typed, tmp_path)
    assert any("byte-budget" in p for p in probs)


def test_kvq_ab_refuses_pool_over_budget(tmp_path):
    over = _kvq_ab()
    over["kvq_ab"]["int8"]["capacity"]["kv_bytes_total"] = 98305
    probs = _problems_for("SERVE_BENCH_kvq_ab_cpu_smoke.json",
                          over, tmp_path)
    assert any("over the shared budget" in p for p in probs)


def test_kvq_ab_refuses_low_capacity_ratio(tmp_path):
    # int8 pages must buy ~2x the pages from the same bytes
    low = _kvq_ab()
    low["kvq_ab"]["capacity_ratio"] = 1.5
    probs = _problems_for("SERVE_BENCH_kvq_ab_cpu_smoke.json",
                          low, tmp_path)
    assert any("< 1.9" in p for p in probs)
    missing = _kvq_ab()
    del missing["kvq_ab"]["capacity_ratio"]
    probs = _problems_for("SERVE_BENCH_kvq_ab_cpu_smoke.json",
                          missing, tmp_path)
    assert any("capacity_ratio" in p for p in probs)


def test_kvq_ab_refuses_agreement_below_recorded_floor(tmp_path):
    low = _kvq_ab()
    low["kvq_ab"]["parity"]["token_agreement"] = 0.7
    probs = _problems_for("SERVE_BENCH_kvq_ab_cpu_smoke.json",
                          low, tmp_path)
    assert any("below the recorded floor" in p for p in probs)
    unchecked = _kvq_ab()
    unchecked["kvq_ab"]["parity"]["tokens_checked"] = 0
    probs = _problems_for("SERVE_BENCH_kvq_ab_cpu_smoke.json",
                          unchecked, tmp_path)
    assert any("checked nothing" in p for p in probs)
    no_floor = _kvq_ab()
    del no_floor["kvq_ab"]["parity"]["token_agreement_floor"]
    probs = _problems_for("SERVE_BENCH_kvq_ab_cpu_smoke.json",
                          no_floor, tmp_path)
    assert any("token_agreement_floor" in p for p in probs)


def test_kvq_ab_refuses_spec_accept_drop(tmp_path):
    drop = _kvq_ab()
    drop["kvq_ab"]["parity"]["spec_accept_rate_int8"] = 0.5
    probs = _problems_for("SERVE_BENCH_kvq_ab_cpu_smoke.json",
                          drop, tmp_path)
    assert any("accept-rate" in p for p in probs)


def test_kvq_ab_refuses_non_improving_sheds(tmp_path):
    # extra pages that don't absorb the burst bought no capacity
    flat = _kvq_ab()
    flat["kvq_ab"]["int8"]["capacity"]["sheds"] = 11
    probs = _problems_for("SERVE_BENCH_kvq_ab_cpu_smoke.json",
                          flat, tmp_path)
    assert any("strictly fewer" in p for p in probs)


def test_kvq_ab_requires_arms_and_fields(tmp_path):
    no_arm = _kvq_ab()
    del no_arm["kvq_ab"]["int8"]
    probs = _problems_for("SERVE_BENCH_kvq_ab_cpu_smoke.json",
                          no_arm, tmp_path)
    assert any("int8 arm" in p for p in probs)
    no_field = _kvq_ab()
    del no_field["kvq_ab"]["fp"]["capacity"]["n_pages"]
    probs = _problems_for("SERVE_BENCH_kvq_ab_cpu_smoke.json",
                          no_field, tmp_path)
    assert any("n_pages" in p for p in probs)


# ------------------------------------------ prefix-share A/B family


def _prefix_share_ab():
    return {
        "prefix_share_ab": {
            "page_size": 8, "prefix_len": 96, "prefix_pages": 12,
            "rounds": 5, "gen_tokens": 8,
            "local": {
                "ttft_s": [0.05, 0.05, 0.05, 0.05],
                "ttft_p50_s": 0.05,
                "cross_replica_hit_rate": 0.0, "pull_hints": 0,
                "kv_migration": {"pulls": 0, "pulled_pages": 0,
                                 "wire_bytes": 0, "aborts": 0,
                                 "fallbacks": 0},
                "tokens": 40},
            "shared": {
                "ttft_s": [0.04, 0.04, 0.04, 0.04],
                "ttft_p50_s": 0.04,
                "cross_replica_hit_rate": 1.0, "pull_hints": 5,
                "kv_migration": {"pulls": 5, "pulled_pages": 60,
                                 "wire_bytes": 85440, "aborts": 0,
                                 "fallbacks": 0},
                "tokens": 40},
            "token_identical": True,
            "ttft_p50_ratio": 0.8,
            "wire_bytes_int8": 85440,
            "wire_bytes_bf16_equiv": 122880,
            "wire_ratio": 0.7,
        },
        "mesh": {"tp": 1, "replicas": 2},
        "kv": {"kv_dtype": "int8", "paged_kernel": "gather"},
        "seed": 0, "git_sha": "abc1234",
    }


def test_prefix_share_ab_artifact_validates(tmp_path):
    assert _problems_for("SERVE_BENCH_prefix_share_cpu_smoke.json",
                         _prefix_share_ab(), tmp_path) == []


def test_prefix_share_ab_refuses_missing_stamps(tmp_path):
    no_mesh = _prefix_share_ab()
    del no_mesh["mesh"]
    probs = _problems_for("SERVE_BENCH_prefix_share_cpu_smoke.json",
                          no_mesh, tmp_path)
    assert any("mesh stamp" in p for p in probs)
    no_kv = _prefix_share_ab()
    del no_kv["kv"]
    probs = _problems_for("SERVE_BENCH_prefix_share_cpu_smoke.json",
                          no_kv, tmp_path)
    assert any("kv stamp" in p for p in probs)
    no_seed = _prefix_share_ab()
    del no_seed["seed"]
    probs = _problems_for("SERVE_BENCH_prefix_share_cpu_smoke.json",
                          no_seed, tmp_path)
    assert any("seed" in p for p in probs)


def test_prefix_share_ab_refuses_token_divergence(tmp_path):
    # a migration that changes greedy tokens is broken, whatever
    # its TTFT — this is the gate that matters most
    bad = _prefix_share_ab()
    bad["prefix_share_ab"]["token_identical"] = False
    probs = _problems_for("SERVE_BENCH_prefix_share_cpu_smoke.json",
                          bad, tmp_path)
    assert any("not token-identical" in p for p in probs)


def test_prefix_share_ab_refuses_unmeasured_sharing(tmp_path):
    # a shared arm whose hit rate is not strictly above the local
    # arm's never pulled a page the local arm lacked
    bad = _prefix_share_ab()
    bad["prefix_share_ab"]["shared"]["cross_replica_hit_rate"] = 0.0
    probs = _problems_for("SERVE_BENCH_prefix_share_cpu_smoke.json",
                          bad, tmp_path)
    assert any("not strictly above" in p for p in probs)
    for key in ("pulls", "pulled_pages", "wire_bytes"):
        bad = _prefix_share_ab()
        bad["prefix_share_ab"]["shared"]["kv_migration"][key] = 0
        probs = _problems_for(
            "SERVE_BENCH_prefix_share_cpu_smoke.json", bad, tmp_path)
        assert any("no migration actually happened" in p
                   for p in probs), key


def test_prefix_share_ab_refuses_non_improving_ttft(tmp_path):
    bad = _prefix_share_ab()
    bad["prefix_share_ab"]["ttft_p50_ratio"] = 1.0
    probs = _problems_for("SERVE_BENCH_prefix_share_cpu_smoke.json",
                          bad, tmp_path)
    assert any("did not beat re-prefilling" in p for p in probs)
    gone = _prefix_share_ab()
    del gone["prefix_share_ab"]["ttft_p50_ratio"]
    probs = _problems_for("SERVE_BENCH_prefix_share_cpu_smoke.json",
                          gone, tmp_path)
    assert any("ttft_p50_ratio" in p for p in probs)


def test_prefix_share_ab_refuses_wire_bytes_savings_loss(tmp_path):
    # int8 pages + scales must land below the bf16 cost of the same
    # pages, or the quantized payload saved nothing on the wire
    bad = _prefix_share_ab()
    bad["prefix_share_ab"]["wire_bytes_int8"] = 122880
    probs = _problems_for("SERVE_BENCH_prefix_share_cpu_smoke.json",
                          bad, tmp_path)
    assert any("saved nothing on the wire" in p for p in probs)
    for key in ("wire_bytes_int8", "wire_bytes_bf16_equiv"):
        gone = _prefix_share_ab()
        del gone["prefix_share_ab"][key]
        probs = _problems_for(
            "SERVE_BENCH_prefix_share_cpu_smoke.json", gone, tmp_path)
        assert any(key in p for p in probs), key


def test_prefix_share_ab_requires_arms_and_counters(tmp_path):
    no_arm = _prefix_share_ab()
    del no_arm["prefix_share_ab"]["local"]
    probs = _problems_for("SERVE_BENCH_prefix_share_cpu_smoke.json",
                          no_arm, tmp_path)
    assert any("local" in p and "arm" in p for p in probs)
    no_field = _prefix_share_ab()
    del no_field["prefix_share_ab"]["shared"]["ttft_p50_s"]
    probs = _problems_for("SERVE_BENCH_prefix_share_cpu_smoke.json",
                          no_field, tmp_path)
    assert any("ttft_p50_s" in p for p in probs)
    no_km = _prefix_share_ab()
    del no_km["prefix_share_ab"]["shared"]["kv_migration"]
    probs = _problems_for("SERVE_BENCH_prefix_share_cpu_smoke.json",
                          no_km, tmp_path)
    assert any("kv_migration counter block" in p for p in probs)


def _batch_ab():
    return {
        "batch_ab": {
            "prompt_len": 8, "gen_tokens": 8,
            "latency": {
                "profile": "latency",
                "engine_kwargs": {"chunk": 4, "prefill_chunk": 256,
                                  "max_run_ahead": 256,
                                  "max_queued": 2},
                "rows": 16, "tokens": 128, "batch_lane_tokens": 144,
                "wall_s": 0.02, "tokens_per_s": 6400.0},
            "throughput": {
                "profile": "throughput",
                "engine_kwargs": {"chunk": 16, "prefill_chunk": 512,
                                  "max_run_ahead": 512,
                                  "max_queued": None},
                "rows": 16, "tokens": 128, "batch_lane_tokens": 144,
                "wall_s": 0.04, "tokens_per_s": 3200.0},
            "token_identical": True,
            "tokens_per_s_ratio": 0.5,
        },
        "mesh": {"tp": 1, "replicas": 1},
        "kv": {"kv_dtype": "fp", "paged_kernel": "gather"},
        "seed": 0, "git_sha": "abc1234",
    }


def test_batch_ab_artifact_validates(tmp_path):
    assert _problems_for("SERVE_BENCH_batch_ab_cpu_smoke.json",
                         _batch_ab(), tmp_path) == []


def test_batch_ab_refuses_missing_stamps(tmp_path):
    no_mesh = _batch_ab()
    del no_mesh["mesh"]
    probs = _problems_for("SERVE_BENCH_batch_ab_cpu_smoke.json",
                          no_mesh, tmp_path)
    assert any("mesh stamp" in p for p in probs)
    no_seed = _batch_ab()
    del no_seed["seed"]
    probs = _problems_for("SERVE_BENCH_batch_ab_cpu_smoke.json",
                          no_seed, tmp_path)
    assert any("seed" in p for p in probs)


def test_batch_ab_refuses_token_divergence(tmp_path):
    bad = _batch_ab()
    bad["batch_ab"]["token_identical"] = False
    probs = _problems_for("SERVE_BENCH_batch_ab_cpu_smoke.json",
                          bad, tmp_path)
    assert any("not token-identical" in p for p in probs)


def test_batch_ab_refuses_idle_batch_lane(tmp_path):
    # a "batch" bench whose requests never rode the batch lane
    # measured the wrong thing
    for key in ("tokens", "batch_lane_tokens"):
        bad = _batch_ab()
        bad["batch_ab"]["throughput"][key] = 0
        probs = _problems_for("SERVE_BENCH_batch_ab_cpu_smoke.json",
                              bad, tmp_path)
        assert any("never generated on the batch lane" in p
                   for p in probs), key


def test_batch_ab_requires_arms_and_ratio(tmp_path):
    bad = _batch_ab()
    del bad["batch_ab"]["latency"]
    probs = _problems_for("SERVE_BENCH_batch_ab_cpu_smoke.json",
                          bad, tmp_path)
    assert any("missing latency arm" in p for p in probs)
    bad = _batch_ab()
    del bad["batch_ab"]["tokens_per_s_ratio"]
    probs = _problems_for("SERVE_BENCH_batch_ab_cpu_smoke.json",
                          bad, tmp_path)
    assert any("tokens_per_s_ratio" in p for p in probs)


def _mixed_ab():
    return {
        "mixed_ab": {
            "online_requests": 10, "gen_tokens": 8,
            "ttft_slo_ms": 1000.0,
            "attainment_noise_floor": 0.15,
            "baseline": {"ttft_p50_ms": 3.6, "ttft_p99_ms": 5.6,
                         "slo_attainment": 1.0},
            "mixed": {"ttft_p50_ms": 3.7, "ttft_p99_ms": 13.6,
                      "slo_attainment": 1.0, "batch_tokens": 120,
                      "batch_tokens_per_chip_s": 218.5,
                      "batch_preemptions": 0},
            "token_identical": True,
            "chaos": {"kill": "chaos kill", "batch_rows": 12,
                      "crash_after": 5, "committed_at_crash": 2,
                      "rows_resumed": 2, "resubmitted": 10,
                      "dup_rows": 0, "missing_rows": 0},
        },
        "mesh": {"tp": 1, "replicas": 1},
        "kv": {"kv_dtype": "fp", "paged_kernel": "gather"},
        "seed": 0, "git_sha": "abc1234",
    }


def test_mixed_ab_artifact_validates(tmp_path):
    assert _problems_for("SERVE_BENCH_mixed_ab_cpu_smoke.json",
                         _mixed_ab(), tmp_path) == []


def test_mixed_ab_refuses_sunk_online_attainment(tmp_path):
    # colocation must be ~free for the online lane: the mixed arm
    # may not fall more than the noise floor below the baseline
    bad = _mixed_ab()
    bad["mixed_ab"]["mixed"]["slo_attainment"] = 0.7
    probs = _problems_for("SERVE_BENCH_mixed_ab_cpu_smoke.json",
                          bad, tmp_path)
    assert any("not free for the online lane" in p for p in probs)
    low_base = _mixed_ab()
    low_base["mixed_ab"]["baseline"]["slo_attainment"] = 0.4
    low_base["mixed_ab"]["mixed"]["slo_attainment"] = 0.4
    probs = _problems_for("SERVE_BENCH_mixed_ab_cpu_smoke.json",
                          low_base, tmp_path)
    assert any("gates nothing" in p for p in probs)


def test_mixed_ab_refuses_idle_batch_tier(tmp_path):
    bad = _mixed_ab()
    bad["mixed_ab"]["mixed"]["batch_tokens"] = 0
    probs = _problems_for("SERVE_BENCH_mixed_ab_cpu_smoke.json",
                          bad, tmp_path)
    assert any("absorbed nothing" in p for p in probs)


def test_mixed_ab_refuses_exactly_once_violations(tmp_path):
    for key in ("dup_rows", "missing_rows"):
        bad = _mixed_ab()
        bad["mixed_ab"]["chaos"][key] = 1
        probs = _problems_for("SERVE_BENCH_mixed_ab_cpu_smoke.json",
                              bad, tmp_path)
        assert any("exactly-once resume violated" in p
                   for p in probs), key
    # the chaos ledger must reconcile: committed + resubmitted
    # covers every row exactly once
    bad = _mixed_ab()
    bad["mixed_ab"]["chaos"]["resubmitted"] = 11
    probs = _problems_for("SERVE_BENCH_mixed_ab_cpu_smoke.json",
                          bad, tmp_path)
    assert any("does not reconcile" in p for p in probs)


def test_mixed_ab_refuses_unmeasured_chaos_kill(tmp_path):
    # a kill before the first manifest commit (or after the last)
    # exercises no resume at all
    for committed, resub in ((0, 12), (12, 0)):
        bad = _mixed_ab()
        bad["mixed_ab"]["chaos"]["committed_at_crash"] = committed
        bad["mixed_ab"]["chaos"]["resubmitted"] = resub
        probs = _problems_for("SERVE_BENCH_mixed_ab_cpu_smoke.json",
                              bad, tmp_path)
        assert any("measures no resume" in p for p in probs), committed


def test_mixed_ab_refuses_token_divergence_and_missing_leg(tmp_path):
    bad = _mixed_ab()
    bad["mixed_ab"]["token_identical"] = False
    probs = _problems_for("SERVE_BENCH_mixed_ab_cpu_smoke.json",
                          bad, tmp_path)
    assert any("not token-identical" in p for p in probs)
    bad = _mixed_ab()
    del bad["mixed_ab"]["chaos"]
    probs = _problems_for("SERVE_BENCH_mixed_ab_cpu_smoke.json",
                          bad, tmp_path)
    assert any("chaos" in p for p in probs)


# ---------------------------------------------------------------------------
# disagg A/B family (serve_bench.py --disagg-ab)


def _disagg_arm(ttft, toks_s, handoffs):
    return {
        "ttft_p50_s": ttft, "ttft_steady_s": [ttft] * 4,
        "tokens": 1024, "wall_s": 2.5, "tok_per_s": toks_s,
        "handoffs": handoffs, "handoff_fallbacks": 0,
        "roles": ({"prefill": 1, "decode": 1} if handoffs
                  else {"unified": 2}),
        "kv_migration": {"pulls": handoffs, "pulled_pages": 96,
                         "wire_bytes": 525312, "aborts": 0,
                         "fallbacks": 0},
    }


def _disagg_ab():
    return {
        "disagg_ab": {
            "page_size": 8, "prompt_len": 48, "gen_tokens": 64,
            "requests": 16, "arrival_gap_s": 0.05, "max_slots": 12,
            "unified": _disagg_arm(2.17, 340.5, 0),
            "disagg": _disagg_arm(1.05, 522.7, 16),
            "token_identical": True,
            "ttft_p50_ratio": 0.48,
            "throughput_ratio": 1.54,
            "kv_pull": {"deadline_s": 5.0, "backoff_s": 0.02},
            "autoscale": {
                "prefill": {"start": 1, "final": 2,
                            "decisions": ["up", "up"],
                            "scale_ups": 2, "scale_downs": 0,
                            "ticks": 2},
                "decode": {"start": 1, "final": 1,
                           "decisions": ["hold", "hold"],
                           "scale_ups": 0, "scale_downs": 0,
                           "ticks": 2},
                "diverged": True},
            "chaos": {"faults_injected": 1, "handoff_fallbacks": 1,
                      "lost": 0, "mismatched": 0,
                      "token_identical": True},
        },
        "mesh": {"tp": 1, "replicas": 2},
        "kv": {"kv_dtype": "fp", "paged_kernel": "gather"},
        "seed": 0, "git_sha": "abc1234",
    }


def _rollout_arm(ttft_p50, ttft_p95, swaps=None):
    arm = {"requests": 24, "lost": 0, "mismatched": 0,
           "ttft_p50_s": ttft_p50, "ttft_p95_s": ttft_p95,
           "tokens": 384}
    if swaps is not None:
        arm["swaps"] = swaps
    return arm


def _rollout_ab():
    return {
        "rollout_ab": {
            "replicas": 3, "prompt_len": 32, "gen_tokens": 16,
            "baseline": _rollout_arm(0.08, 0.15),
            "rollout": _rollout_arm(0.09, 0.21, swaps=3),
            "token_identical": True,
            "ttft_p95_ratio": 1.4,
            "ttft_impact_limit": 3.0,
            "fence": {"monotonic": True,
                      "transitions": [
                          {"idx": 0, "from": 0, "to": 1},
                          {"idx": 1, "from": 0, "to": 1},
                          {"idx": 2, "from": 0, "to": 1}]},
            "generations": {"from": "aaaa00000000",
                            "to": "bbbb11111111"},
            "rollback": {"injected_regression": True,
                         "rolled_back": True, "converged": True,
                         "reason": "canary parity probe failed",
                         "probe_failures": 1,
                         "baseline_weights_id": "bbbb11111111",
                         "flight_bundle": "weight-rollback-000000"},
        },
        "mesh": {"tp": 1, "replicas": 3},
        "seed": 0, "git_sha": "abc1234",
    }


def test_rollout_ab_artifact_validates(tmp_path):
    assert _problems_for("SERVE_BENCH_rollout_ab_cpu_smoke.json",
                         _rollout_ab(), tmp_path) == []


def test_rollout_ab_refuses_missing_stamps(tmp_path):
    for key, needle in (("mesh", "mesh stamp"), ("seed", "seed")):
        bad = _rollout_ab()
        del bad[key]
        probs = _problems_for("SERVE_BENCH_rollout_ab_cpu_smoke.json",
                              bad, tmp_path)
        assert any(needle in p for p in probs), key
    no_gen = _rollout_ab()
    del no_gen["rollout_ab"]["generations"]
    probs = _problems_for("SERVE_BENCH_rollout_ab_cpu_smoke.json",
                          no_gen, tmp_path)
    assert any("payload-identity stamp" in p for p in probs)


def test_rollout_ab_refuses_lost_or_mismatched(tmp_path):
    for arm in ("baseline", "rollout"):
        for key in ("lost", "mismatched"):
            bad = _rollout_ab()
            bad["rollout_ab"][arm][key] = 1
            probs = _problems_for(
                "SERVE_BENCH_rollout_ab_cpu_smoke.json", bad,
                tmp_path)
            assert any("never correctness" in p for p in probs), \
                (arm, key)
    diverged = _rollout_ab()
    diverged["rollout_ab"]["token_identical"] = False
    probs = _problems_for("SERVE_BENCH_rollout_ab_cpu_smoke.json",
                          diverged, tmp_path)
    assert any("changed greedy tokens" in p for p in probs)


def test_rollout_ab_refuses_unbounded_ttft(tmp_path):
    over = _rollout_ab()
    over["rollout_ab"]["ttft_p95_ratio"] = 5.0
    probs = _problems_for("SERVE_BENCH_rollout_ab_cpu_smoke.json",
                          over, tmp_path)
    assert any("unbounded" in p for p in probs)
    no_limit = _rollout_ab()
    del no_limit["rollout_ab"]["ttft_impact_limit"]
    probs = _problems_for("SERVE_BENCH_rollout_ab_cpu_smoke.json",
                          no_limit, tmp_path)
    assert any("ttft_impact_limit" in p for p in probs)
    no_ratio = _rollout_ab()
    del no_ratio["rollout_ab"]["ttft_p95_ratio"]
    probs = _problems_for("SERVE_BENCH_rollout_ab_cpu_smoke.json",
                          no_ratio, tmp_path)
    assert any("ttft_p95_ratio" in p for p in probs)


def test_rollout_ab_refuses_missing_rollback_proof(tmp_path):
    gone = _rollout_ab()
    del gone["rollout_ab"]["rollback"]
    probs = _problems_for("SERVE_BENCH_rollout_ab_cpu_smoke.json",
                          gone, tmp_path)
    assert any("rollback" in p and "proof" in p for p in probs)
    for key, needle in (
            ("injected_regression", "no regression was injected"),
            ("rolled_back", "did not roll back"),
            ("converged", "did not converge")):
        bad = _rollout_ab()
        bad["rollout_ab"]["rollback"][key] = False
        probs = _problems_for("SERVE_BENCH_rollout_ab_cpu_smoke.json",
                              bad, tmp_path)
        assert any(needle in p for p in probs), key
    unexplained = _rollout_ab()
    del unexplained["rollout_ab"]["rollback"]["flight_bundle"]
    probs = _problems_for("SERVE_BENCH_rollout_ab_cpu_smoke.json",
                          unexplained, tmp_path)
    assert any("flight-explained" in p for p in probs)
    no_probe = _rollout_ab()
    no_probe["rollout_ab"]["rollback"]["probe_failures"] = 0
    probs = _problems_for("SERVE_BENCH_rollout_ab_cpu_smoke.json",
                          no_probe, tmp_path)
    assert any("zero failed parity probes" in p for p in probs)


def test_rollout_ab_refuses_swapless_rollout_and_broken_fence(
        tmp_path):
    swapless = _rollout_ab()
    swapless["rollout_ab"]["rollout"]["swaps"] = 0
    probs = _problems_for("SERVE_BENCH_rollout_ab_cpu_smoke.json",
                          swapless, tmp_path)
    assert any("zero weight swaps" in p for p in probs)
    unfenced = _rollout_ab()
    unfenced["rollout_ab"]["fence"]["monotonic"] = False
    probs = _problems_for("SERVE_BENCH_rollout_ab_cpu_smoke.json",
                          unfenced, tmp_path)
    assert any("fence proof" in p for p in probs)
    empty = _rollout_ab()
    empty["rollout_ab"]["fence"]["transitions"] = []
    probs = _problems_for("SERVE_BENCH_rollout_ab_cpu_smoke.json",
                          empty, tmp_path)
    assert any("never exercised" in p for p in probs)


def test_disagg_ab_artifact_validates(tmp_path):
    assert _problems_for("SERVE_BENCH_disagg_ab_cpu_smoke.json",
                         _disagg_ab(), tmp_path) == []


def test_disagg_ab_refuses_missing_stamps(tmp_path):
    for key, needle in (("mesh", "mesh stamp"), ("kv", "kv stamp"),
                        ("seed", "seed")):
        bad = _disagg_ab()
        del bad[key]
        probs = _problems_for("SERVE_BENCH_disagg_ab_cpu_smoke.json",
                              bad, tmp_path)
        assert any(needle in p for p in probs), key
    no_pull = _disagg_ab()
    del no_pull["disagg_ab"]["kv_pull"]
    probs = _problems_for("SERVE_BENCH_disagg_ab_cpu_smoke.json",
                          no_pull, tmp_path)
    assert any("kv_pull stamp" in p for p in probs)
    no_roles = _disagg_ab()
    del no_roles["disagg_ab"]["disagg"]["roles"]
    probs = _problems_for("SERVE_BENCH_disagg_ab_cpu_smoke.json",
                          no_roles, tmp_path)
    assert any("role stamp" in p for p in probs)


def test_disagg_ab_refuses_token_divergence(tmp_path):
    # a handoff that changes greedy tokens is broken, whatever its
    # TTFT — this is the gate that matters most
    bad = _disagg_ab()
    bad["disagg_ab"]["token_identical"] = False
    probs = _problems_for("SERVE_BENCH_disagg_ab_cpu_smoke.json",
                          bad, tmp_path)
    assert any("not token-identical" in p for p in probs)


def test_disagg_ab_refuses_zero_handoffs(tmp_path):
    bad = _disagg_ab()
    bad["disagg_ab"]["disagg"]["handoffs"] = 0
    probs = _problems_for("SERVE_BENCH_disagg_ab_cpu_smoke.json",
                          bad, tmp_path)
    assert any("zero handoffs" in p for p in probs)


def test_disagg_ab_refuses_non_improving_ttft(tmp_path):
    bad = _disagg_ab()
    bad["disagg_ab"]["ttft_p50_ratio"] = 1.0
    probs = _problems_for("SERVE_BENCH_disagg_ab_cpu_smoke.json",
                          bad, tmp_path)
    assert any("did not beat unified TTFT" in p for p in probs)
    gone = _disagg_ab()
    del gone["disagg_ab"]["ttft_p50_ratio"]
    probs = _problems_for("SERVE_BENCH_disagg_ab_cpu_smoke.json",
                          gone, tmp_path)
    assert any("ttft_p50_ratio" in p for p in probs)


def test_disagg_ab_refuses_throughput_loss(tmp_path):
    # equal chip count both arms: a disagg arm below 1.0 paid
    # tokens/chip-s for its TTFT
    bad = _disagg_ab()
    bad["disagg_ab"]["throughput_ratio"] = 0.9
    probs = _problems_for("SERVE_BENCH_disagg_ab_cpu_smoke.json",
                          bad, tmp_path)
    assert any("tokens/chip-s" in p for p in probs)
    gone = _disagg_ab()
    del gone["disagg_ab"]["throughput_ratio"]
    probs = _problems_for("SERVE_BENCH_disagg_ab_cpu_smoke.json",
                          gone, tmp_path)
    assert any("throughput_ratio" in p for p in probs)


def test_disagg_ab_refuses_undiverged_autoscale(tmp_path):
    bad = _disagg_ab()
    bad["disagg_ab"]["autoscale"]["diverged"] = False
    probs = _problems_for("SERVE_BENCH_disagg_ab_cpu_smoke.json",
                          bad, tmp_path)
    assert any("did not diverge" in p for p in probs)
    idle = _disagg_ab()
    idle["disagg_ab"]["autoscale"]["prefill"]["scale_ups"] = 0
    probs = _problems_for("SERVE_BENCH_disagg_ab_cpu_smoke.json",
                          idle, tmp_path)
    assert any("no scaler made a scale-up decision" in p
               for p in probs)
    gone = _disagg_ab()
    del gone["disagg_ab"]["autoscale"]
    probs = _problems_for("SERVE_BENCH_disagg_ab_cpu_smoke.json",
                          gone, tmp_path)
    assert any("autoscale" in p for p in probs)


def test_disagg_ab_refuses_faultless_or_lossy_chaos(tmp_path):
    faultless = _disagg_ab()
    faultless["disagg_ab"]["chaos"]["faults_injected"] = 0
    probs = _problems_for("SERVE_BENCH_disagg_ab_cpu_smoke.json",
                          faultless, tmp_path)
    assert any("injected no faults" in p for p in probs)
    no_fb = _disagg_ab()
    no_fb["disagg_ab"]["chaos"]["handoff_fallbacks"] = 0
    probs = _problems_for("SERVE_BENCH_disagg_ab_cpu_smoke.json",
                          no_fb, tmp_path)
    assert any("no typed handoff fallback" in p for p in probs)
    for key in ("lost", "mismatched"):
        bad = _disagg_ab()
        bad["disagg_ab"]["chaos"][key] = 1
        probs = _problems_for("SERVE_BENCH_disagg_ab_cpu_smoke.json",
                              bad, tmp_path)
        assert any("never correctness" in p for p in probs), key
    diverged = _disagg_ab()
    diverged["disagg_ab"]["chaos"]["token_identical"] = False
    probs = _problems_for("SERVE_BENCH_disagg_ab_cpu_smoke.json",
                          diverged, tmp_path)
    assert any("decode-in-place fallback" in p for p in probs)
    gone = _disagg_ab()
    del gone["disagg_ab"]["chaos"]
    probs = _problems_for("SERVE_BENCH_disagg_ab_cpu_smoke.json",
                          gone, tmp_path)
    assert any("chaos" in p for p in probs)


def test_disagg_ab_requires_arms_and_counters(tmp_path):
    no_arm = _disagg_ab()
    del no_arm["disagg_ab"]["unified"]
    probs = _problems_for("SERVE_BENCH_disagg_ab_cpu_smoke.json",
                          no_arm, tmp_path)
    assert any("unified" in p and "arm" in p for p in probs)
    no_field = _disagg_ab()
    del no_field["disagg_ab"]["disagg"]["ttft_p50_s"]
    probs = _problems_for("SERVE_BENCH_disagg_ab_cpu_smoke.json",
                          no_field, tmp_path)
    assert any("ttft_p50_s" in p for p in probs)
    no_km = _disagg_ab()
    del no_km["disagg_ab"]["disagg"]["kv_migration"]
    probs = _problems_for("SERVE_BENCH_disagg_ab_cpu_smoke.json",
                          no_km, tmp_path)
    assert any("kv_migration counter block" in p for p in probs)


# ---------------------------------------------------------------------------
# serve-chaos disagg drill block (validated-if-present)


def _chaos_disagg_block():
    return {
        "prefill_kill_mid_handoff": {
            "prompt_pages": 12, "aborts": 1, "fallbacks": 1,
            "completed_token_identical": True},
        "decode_kill_post_handoff": {
            "streamed_before_kill": 2, "resubmits": 1,
            "handoff_fallbacks": 1,
            "completed_token_identical": True},
        "requests": {"completed": 2, "failed_typed": 1, "lost": 0,
                     "mismatched": 0, "admitted": 3},
        "flight": {"prefill_kill_explained": True,
                   "decode_kill_explained": True},
        "quiesced": True,
    }


def test_serve_chaos_disagg_block_validates_when_present(tmp_path):
    ok = _serve_chaos_ok()
    ok["disagg"] = _chaos_disagg_block()
    assert _problems_for("SERVE_CHAOS_x.json", ok, tmp_path) == []
    # campaigns predating role-split pools carry no block: still fine
    assert _problems_for("SERVE_CHAOS_x.json", _serve_chaos_ok(),
                         tmp_path) == []


def test_serve_chaos_disagg_refuses_unexercised_fallbacks(tmp_path):
    bad = _serve_chaos_ok()
    bad["disagg"] = _chaos_disagg_block()
    bad["disagg"]["prefill_kill_mid_handoff"]["fallbacks"] = 0
    probs = _problems_for("SERVE_CHAOS_x.json", bad, tmp_path)
    assert any("no typed decode-in-place fallback" in p
               for p in probs)
    bad = _serve_chaos_ok()
    bad["disagg"] = _chaos_disagg_block()
    bad["disagg"]["decode_kill_post_handoff"]["resubmits"] = 0
    probs = _problems_for("SERVE_CHAOS_x.json", bad, tmp_path)
    assert any("no resubmit" in p for p in probs)


def test_serve_chaos_disagg_refuses_divergence_and_loss(tmp_path):
    for phase in ("prefill_kill_mid_handoff",
                  "decode_kill_post_handoff"):
        bad = _serve_chaos_ok()
        bad["disagg"] = _chaos_disagg_block()
        bad["disagg"][phase]["completed_token_identical"] = False
        probs = _problems_for("SERVE_CHAOS_x.json", bad, tmp_path)
        assert any("token-identically" in p for p in probs), phase
    bad = _serve_chaos_ok()
    bad["disagg"] = _chaos_disagg_block()
    bad["disagg"]["requests"]["lost"] = 1
    probs = _problems_for("SERVE_CHAOS_x.json", bad, tmp_path)
    assert any("disagg" in p and "lost" in p for p in probs)


def test_serve_chaos_disagg_requires_flight_and_quiesce(tmp_path):
    for key, what in (("prefill_kill_explained", "prefill kill"),
                      ("decode_kill_explained", "decode kill")):
        bad = _serve_chaos_ok()
        bad["disagg"] = _chaos_disagg_block()
        bad["disagg"]["flight"][key] = False
        probs = _problems_for("SERVE_CHAOS_x.json", bad, tmp_path)
        assert any(f"explains the {what}" in p for p in probs), key
    bad = _serve_chaos_ok()
    bad["disagg"] = _chaos_disagg_block()
    bad["disagg"]["quiesced"] = False
    probs = _problems_for("SERVE_CHAOS_x.json", bad, tmp_path)
    assert any("disagg" in p and "quiesce" in p for p in probs)


# ------------------------------------------------- rlhf A/B family


def _rlhf_arm(mode, util):
    rounds = 6
    return {
        "mode": mode,
        "rounds": rounds,
        "wall_s": 8.0,
        "gen_busy_s": util * 8.0,
        "generator_utilization": util,
        "staleness_bound": 1,
        "max_staleness": 1 if mode == "overlap" else 0,
        "overlap_observed": mode == "overlap",
        "reward_curve": [0.5 + 0.05 * i for i in range(rounds)],
        "ledger": [f"round-{i}" for i in range(rounds)],
        "batch_log": [
            {"batch_id": f"round-{i}", "round": i,
             "weights_id": f"wid{i:08d}aaaa", "generation": i + 1,
             "staleness": 1 if (mode == "overlap" and i) else 0,
             "reward_mean": 0.5 + 0.05 * i, "num_tokens": 128}
            for i in range(rounds)],
        "final_weights_id": "widfinal0000",
    }


def _rlhf_ab():
    return {
        "rlhf_ab": {
            "overlap": _rlhf_arm("overlap", 0.42),
            "serialized": _rlhf_arm("serialized", 0.35),
            "utilization_ratio": 1.2,
            "chaos": {
                "generator_kill": {"kill_round": 3, "restarts": 1,
                                   "rounds": 6, "ledger_len": 6,
                                   "duplicates": 0, "lost": 0},
                "learner_kill": {"kill_step": 3, "resumed": True,
                                 "recovered_weights_id": "widrec000000",
                                 "resync_weights_id": "widrec000000",
                                 "rounds": 6, "ledger_len": 6,
                                 "duplicates": 0, "lost": 0},
            },
        },
        "mesh": {"tp": 1, "replicas": 1},
        "seed": 0, "git_sha": "abc1234",
    }


def test_rlhf_ab_artifact_validates(tmp_path):
    assert _problems_for("SERVE_BENCH_rlhf_ab_cpu_smoke.json",
                         _rlhf_ab(), tmp_path) == []


def test_rlhf_ab_refuses_missing_stamps(tmp_path):
    for key, needle in (("mesh", "mesh stamp"), ("seed", "seed")):
        bad = _rlhf_ab()
        del bad[key]
        probs = _problems_for("SERVE_BENCH_rlhf_ab_cpu_smoke.json",
                              bad, tmp_path)
        assert any(needle in p for p in probs), key
    unstamped = _rlhf_ab()
    del unstamped["rlhf_ab"]["overlap"]["batch_log"][2]["weights_id"]
    probs = _problems_for("SERVE_BENCH_rlhf_ab_cpu_smoke.json",
                          unstamped, tmp_path)
    assert any("weights_id" in p and "batch_log" in p for p in probs)


def test_rlhf_ab_refuses_flat_or_declining_curve(tmp_path):
    rounds = 6
    for curve in ([0.5] * rounds,
                  [0.5 - 0.02 * i for i in range(rounds)]):
        bad = _rlhf_ab()
        bad["rlhf_ab"]["overlap"]["reward_curve"] = curve
        probs = _problems_for("SERVE_BENCH_rlhf_ab_cpu_smoke.json",
                              bad, tmp_path)
        assert any("did not" in p and "improve" in p for p in probs)
    missing = _rlhf_ab()
    del missing["rlhf_ab"]["overlap"]["reward_curve"]
    probs = _problems_for("SERVE_BENCH_rlhf_ab_cpu_smoke.json",
                          missing, tmp_path)
    assert any("reward_curve" in p for p in probs)


def test_rlhf_ab_refuses_unprofitable_overlap(tmp_path):
    for ratio in (1.0, 0.8):
        bad = _rlhf_ab()
        bad["rlhf_ab"]["utilization_ratio"] = ratio
        probs = _problems_for("SERVE_BENCH_rlhf_ab_cpu_smoke.json",
                              bad, tmp_path)
        assert any("utilization_ratio" in p for p in probs), ratio
    never = _rlhf_ab()
    never["rlhf_ab"]["overlap"]["overlap_observed"] = False
    probs = _problems_for("SERVE_BENCH_rlhf_ab_cpu_smoke.json",
                          never, tmp_path)
    assert any("overlap_observed" in p for p in probs)


def test_rlhf_ab_refuses_staleness_over_bound(tmp_path):
    bad = _rlhf_ab()
    bad["rlhf_ab"]["overlap"]["max_staleness"] = 2
    probs = _problems_for("SERVE_BENCH_rlhf_ab_cpu_smoke.json",
                          bad, tmp_path)
    assert any("max_staleness" in p and "bound" in p for p in probs)


def test_rlhf_ab_refuses_duplicate_ledger(tmp_path):
    bad = _rlhf_ab()
    bad["rlhf_ab"]["overlap"]["ledger"][2] = "round-1"
    probs = _problems_for("SERVE_BENCH_rlhf_ab_cpu_smoke.json",
                          bad, tmp_path)
    assert any("duplicate" in p and "ledger" in p for p in probs)


def test_rlhf_ab_refuses_lossy_or_unexercised_chaos(tmp_path):
    no_chaos = _rlhf_ab()
    del no_chaos["rlhf_ab"]["chaos"]
    probs = _problems_for("SERVE_BENCH_rlhf_ab_cpu_smoke.json",
                          no_chaos, tmp_path)
    assert any("chaos" in p for p in probs)
    unkilled = _rlhf_ab()
    unkilled["rlhf_ab"]["chaos"]["generator_kill"]["restarts"] = 0
    probs = _problems_for("SERVE_BENCH_rlhf_ab_cpu_smoke.json",
                          unkilled, tmp_path)
    assert any("nothing was killed" in p for p in probs)
    for drill in ("generator_kill", "learner_kill"):
        for key in ("duplicates", "lost"):
            bad = _rlhf_ab()
            bad["rlhf_ab"]["chaos"][drill][key] = 1
            probs = _problems_for(
                "SERVE_BENCH_rlhf_ab_cpu_smoke.json", bad, tmp_path)
            assert any(key in p and "0" in p for p in probs), \
                (drill, key)


def test_rlhf_ab_refuses_resync_mismatch(tmp_path):
    unresumed = _rlhf_ab()
    unresumed["rlhf_ab"]["chaos"]["learner_kill"]["resumed"] = False
    probs = _problems_for("SERVE_BENCH_rlhf_ab_cpu_smoke.json",
                          unresumed, tmp_path)
    assert any("did not" in p and "resume" in p for p in probs)
    wrong = _rlhf_ab()
    wrong["rlhf_ab"]["chaos"]["learner_kill"]["resync_weights_id"] = \
        "widother0000"
    probs = _problems_for("SERVE_BENCH_rlhf_ab_cpu_smoke.json",
                          wrong, tmp_path)
    assert any("wrong policy" in p for p in probs)
