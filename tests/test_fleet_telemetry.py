"""Fleet observability plane: clock-offset estimation, cursored
telemetry scrape, cross-process trace stitching, cluster bundles.

The estimator tests are pure arithmetic over synthetic round trips
(no sleeping, no real clocks): t0/t3 are collector-side send/receive
stamps, t1 the member's clock read mid-call. The collector tests run
over the loopback fleet (tests/test_fleet.py harness) so every seam —
telemetry RPC, cursor resume, incarnation keying, bundle dump/load —
is the real code path.
"""
import json
import os
import threading
import time

import pytest

from ray_tpu.serve import obs
from ray_tpu.serve.fleet.agent import (ReplicaAgent, ScriptedEngine,
                                       scripted_completion)
from ray_tpu.serve.fleet.directory import (DirectoryClient,
                                           FleetDirectory)
from ray_tpu.serve.fleet.router import FleetRouter
from ray_tpu.serve.fleet.telemetry import (ClockOffsetEstimator,
                                           TelemetryCollector,
                                           load_cluster_bundle,
                                           merge_prometheus_texts)
from ray_tpu.serve.fleet.transport import LoopbackTransport
from ray_tpu.util import metrics


# ------------------------------------------------ offset estimator


def test_estimator_skew_ahead_and_behind():
    # member clock 5s AHEAD of the collector, symmetric 10ms RTT:
    # t1 = true_mid + 5; the midpoint formula recovers +5 exactly
    ahead = ClockOffsetEstimator()
    ahead.add_sample(t0=100.0, t1=105.005, t3=100.010)
    assert ahead.offset_s == pytest.approx(5.0)
    assert ahead.uncertainty_s == pytest.approx(0.005)
    assert ahead.rtt_s == pytest.approx(0.010)
    # a member stamp maps BACK by the offset onto the local timebase
    assert ahead.to_local(105.005) == pytest.approx(100.005)

    behind = ClockOffsetEstimator()
    behind.add_sample(t0=100.0, t1=95.005, t3=100.010)
    assert behind.offset_s == pytest.approx(-5.0)
    assert behind.to_local(95.005) == pytest.approx(100.005)


def test_estimator_asymmetric_rtt_error_stays_inside_bound():
    # true offset +2.0s, request leg 1ms but response leg 9ms: the
    # midpoint is pulled off the truth by (a-b)/2 = -4ms — an error
    # the RTT/2 = 5ms uncertainty must bound, by construction
    a, b, true = 0.001, 0.009, 2.0
    est = ClockOffsetEstimator()
    t0 = 50.0
    est.add_sample(t0=t0, t1=t0 + a + true, t3=t0 + a + b)
    assert est.offset_s != pytest.approx(true)      # biased ...
    assert abs(est.offset_s - true) <= est.uncertainty_s  # ... bounded
    assert est.uncertainty_s == pytest.approx((a + b) / 2)


def test_estimator_min_rtt_sample_wins():
    est = ClockOffsetEstimator()
    est.add_sample(t0=0.0, t1=1.1, t3=0.2)      # rtt 200ms
    est.add_sample(t0=10.0, t1=11.0, t3=10.01)  # rtt 10ms <- best
    est.add_sample(t0=20.0, t1=21.3, t3=20.5)   # rtt 500ms, ignored
    assert est.offset_s == pytest.approx(11.0 - 10.005)
    assert est.uncertainty_s == pytest.approx(0.005)
    assert est.n_samples == 3


def test_estimator_drift_across_scrape_gap():
    est = ClockOffsetEstimator()
    # offset grows 1ms per 10s of local time: 1e-4 s/s drift
    est.add_sample(t0=0.0, t1=5.0005, t3=0.001)
    assert est.drift_s_per_s is None            # one sample: no slope
    est.add_sample(t0=10.0, t1=15.0015, t3=10.001)
    drift = est.drift_s_per_s
    assert drift == pytest.approx(1e-4, rel=0.05)


def test_estimator_drift_gated_below_min_window():
    # two samples 10ms apart: any slope is RTT-asymmetry noise, and
    # the estimator must refuse to report one
    est = ClockOffsetEstimator(min_drift_window_s=1.0)
    est.add_sample(t0=0.0, t1=5.0, t3=0.001)
    est.add_sample(t0=0.010, t1=5.5, t3=0.011)
    assert est.drift_s_per_s is None


def test_estimator_rejects_backwards_round_trip():
    est = ClockOffsetEstimator()
    with pytest.raises(ValueError):
        est.add_sample(t0=5.0, t1=7.0, t3=4.0)


def test_estimator_bounded_sample_memory():
    est = ClockOffsetEstimator(max_samples=4)
    for i in range(10):
        est.add_sample(t0=float(i), t1=float(i) + 3.0,
                       t3=float(i) + 0.001)
    assert len(est._samples) == 4
    # drift window now spans only the retained samples (6..9)
    assert est.drift_s_per_s == pytest.approx(0.0, abs=1e-9)


# --------------------------------------- cursored scrape + restarts


class _FakeMemberFeed:
    """A scriptable telemetry endpoint: one 'incarnation' at a time,
    each with its own pid/generation, seq space, and clock base."""

    def __init__(self):
        self.pid = 1000
        self.generation = 0
        self.clock_base = 1000.0
        self.events = []

    def restart(self, clock_base):
        self.pid += 1
        self.generation += 1
        self.clock_base = clock_base
        self.events = []

    def append(self, etype, **data):
        self.events.append(
            {"seq": len(self.events),
             "t": self.clock_base + 0.001 * len(self.events),
             "type": etype, "rid": data.pop("rid", None),
             "data": data})

    def telemetry(self, cursor=0, limit=256):
        window = [e for e in self.events if e["seq"] >= cursor]
        window = window[:limit]
        nxt = (window[-1]["seq"] + 1) if window \
            else max(cursor, len(self.events))
        return {"role": "agent", "replica_id": "m",
                "generation": self.generation, "pid": self.pid,
                "clock": {"mono": self.clock_base, "wall": 0.0},
                "metrics_text": "", "events": window,
                "cursor": nxt, "events_total": len(self.events),
                "dropped": max(0, min((e["seq"] for e in
                                       self.events), default=0)
                               - cursor)}


def _bare_collector(**kw):
    class _NoRouter:
        pass
    return TelemetryCollector(_NoRouter(), **kw)


def test_scrape_cursor_resume_never_rereads():
    col = _bare_collector()
    st = col._state("m", "agent")
    feed = _FakeMemberFeed()
    for i in range(5):
        feed.append("submit", rid=f"r{i}")
    assert len(col._scrape_remote(st, feed.telemetry)) == 5
    # nothing new: the resumed cursor hands back an empty window
    assert col._scrape_remote(st, feed.telemetry) == []
    feed.append("retire", rid="r0")
    new = col._scrape_remote(st, feed.telemetry)
    assert [e["type"] for e in new] == ["retire"]
    assert col.counters["events_ingested"] == 6


def test_member_restart_resets_monotonic_base_and_cursor():
    col = _bare_collector()
    st = col._state("m", "agent")
    feed = _FakeMemberFeed()
    for _ in range(8):
        feed.append("submit")
    col._scrape_remote(st, feed.telemetry)
    old_offset = st.estimator.offset_s
    assert st.cursor == 8

    # the process restarts: seqs AND the monotonic clock base reset.
    # Without per-incarnation keying the stale cursor (8) would skip
    # the new log entirely and the old offset would misplace its
    # events by ~990s on the aligned timebase.
    feed.restart(clock_base=10.0)
    feed.append("self_fence")
    feed.append("submit")
    new = col._scrape_remote(st, feed.telemetry)
    assert [e["type"] for e in new] == ["self_fence", "submit"]
    assert st.incarnations == 2
    assert st.cursor == 2
    # fresh estimator for the fresh clock: offset tracks the NEW base
    assert st.estimator.n_samples == 1
    assert st.estimator.offset_s != pytest.approx(old_offset)
    # events land on the collector timebase near "now", not at the
    # dead incarnation's offset
    t_scrape = time.monotonic()
    for ev in new:
        assert abs(ev["local_t"] - t_scrape) < 5.0


def test_scrape_counts_ring_overwrite_as_dropped():
    col = _bare_collector()
    st = col._state("m", "agent")
    feed = _FakeMemberFeed()
    for i in range(4):
        feed.append("submit")
    col._scrape_remote(st, feed.telemetry)
    # the member's ring overwrote seqs 4..9 before the next scrape
    feed.events = [{"seq": s, "t": feed.clock_base + s,
                    "type": "submit", "rid": None, "data": {}}
                   for s in range(10, 13)]
    new = col._scrape_remote(st, feed.telemetry)
    assert [e["seq"] for e in new] == [10, 11, 12]
    assert st.dropped == 6


# ------------------------------------------- collector over loopback


def _loopback_fleet(n=2, token_delay_s=0.0005, seed=7,
                    wrap_transport=None, **router_kw):
    d = FleetDirectory(lease_ttl_s=1.0)
    dc = DirectoryClient(LoopbackTransport(d.handle))
    agents = {}

    def tf(addr):
        t = LoopbackTransport(agents[addr[1]].handle)
        return wrap_transport(addr[1], t) if wrap_transport else t

    for i in range(n):
        rid = f"a{i}"
        agents[rid] = ReplicaAgent(
            rid,
            lambda g, _d=token_delay_s: ScriptedEngine(
                token_delay_s=_d),
            dc, renew_period_s=0.05).start()
    kw = dict(seed=seed, snapshot_ttl_s=0.01, poll_interval_s=0.002)
    kw.update(router_kw)
    return d, dc, agents, FleetRouter(dc, tf, **kw)


def test_collector_loopback_scrape_trace_and_metrics(tmp_path):
    metrics.clear_registry()
    d, dc, agents, r = _loopback_fleet()
    col = TelemetryCollector(r, cluster_dir=str(tmp_path),
                             offset_bound_s=0.5).attach()
    try:
        assert r.telemetry_collector is col
        first = col.scrape_once()
        assert set(first) == {"router", "directory", "a0", "a1"}
        assert all(v is not None for v in first.values())

        tid = obs.mint_trace_id()
        h = r.submit([3, 1, 4], max_new_tokens=6, trace_id=tid)
        assert h.result() == scripted_completion([3, 1, 4], 6)
        col.scrape_once()
        # idempotent: a third scrape with nothing new returns zeros
        assert all(v == 0 for v in col.scrape_once().values())

        members = col.members()
        # the router member is the collector's own process: the
        # "round trip" is a function call, so the sample is exact
        assert members["router"]["offset_s"] == 0.0
        assert members["router"]["uncertainty_s"] == 0.0
        for m in members.values():
            assert m["up"] is True
            assert m["uncertainty_s"] <= 0.5

        phases = col.request_phases()
        assert tid in phases
        ph = phases[tid]
        served = h.replica_idx
        assert served in ph["members"]
        assert "router" in ph["members"]
        # loopback fleet = one OS process: spans exist per member but
        # the pid set collapses (the >=3-process stitch is proven by
        # serve_bench --fleet --trace over real processes)
        assert ph["n_processes"] == 1 and ph["stitched"] is False
        for span in ph["spans"]:
            assert span["end_s"] >= span["start_s"]
            assert span["offset_uncertainty_s"] <= 0.5

        trace = col.chrome_trace()
        assert isinstance(trace, list)
        names = {ev.get("name") for ev in trace
                 if ev.get("ph") == "M"}
        assert "process_name" in names
        assert any(ev.get("ph") == "X"
                   and ev["args"].get("trace_id") == tid
                   for ev in trace)

        text = col.metrics_text()
        assert 'member="' in text
        assert "serve_fleet_members" in text

        health = col.health()
        assert health["members_up"] == 4
        assert health["offset_within_bound"] is True
        assert health["counters"]["scrapes"] >= 3
    finally:
        r.shutdown()
        for a in agents.values():
            a.shutdown()


def test_collector_fault_bundle_roundtrip(tmp_path):
    metrics.clear_registry()
    d, dc, agents, r = _loopback_fleet()
    col = TelemetryCollector(r, cluster_dir=str(tmp_path)).attach()
    try:
        h = r.submit([2, 7], max_new_tokens=4, trace_id="t-bundle")
        h.result()
        col.scrape_once()
        bdir = col.on_fault("unit-fault",
                            trigger={"kind": "test", "x": 1})
        assert bdir is not None and os.path.isdir(bdir)
        assert col.bundles[-1]["reason"] == "unit-fault"

        cb = load_cluster_bundle(bdir)
        assert cb["reason"] == "unit-fault"
        assert cb["trigger"] == {"kind": "test", "x": 1}
        assert set(cb["members"]) == {"router", "directory",
                                      "a0", "a1"}
        assert cb["coverage"]["unreachable"] == []
        assert cb["events_torn_truncated"] == 0
        assert cb["member_payloads"]
        # merged stream is sorted on the aligned timebase and the
        # traced request's submit made it in
        ts = [e["local_t"] for e in cb["events"]
              if e["local_t"] is not None]
        assert ts == sorted(ts)
        assert any((e.get("data") or {}).get("trace_id")
                   == "t-bundle" for e in cb["events"])
    finally:
        r.shutdown()
        for a in agents.values():
            a.shutdown()


def test_collector_auto_bundles_on_scraped_fault_event(tmp_path):
    metrics.clear_registry()
    d, dc, agents, r = _loopback_fleet()
    col = TelemetryCollector(r, cluster_dir=str(tmp_path)).attach()
    try:
        col.scrape_once()
        agents["a0"].events.append("self_fence",
                                   data={"lease_overdue_s": 0.4})
        col.scrape_once()
        reasons = [b["reason"] for b in col.bundles]
        assert "self_fence-a0" in reasons
        trig = [b for b in col.bundles
                if b["reason"] == "self_fence-a0"][0]["trigger"]
        assert trig["kind"] == "self_fence"
        assert trig["data"]["lease_overdue_s"] == 0.4
        # the SAME event never fires twice (seen-fault dedup)
        col.scrape_once()
        assert [b["reason"] for b in col.bundles] == reasons
    finally:
        r.shutdown()
        for a in agents.values():
            a.shutdown()


def test_cluster_bundle_torn_tail_tolerated_midfile_raises(tmp_path):
    metrics.clear_registry()
    d, dc, agents, r = _loopback_fleet(n=1)
    col = TelemetryCollector(r, cluster_dir=str(tmp_path)).attach()
    try:
        r.submit([5], max_new_tokens=3).result()
        col.scrape_once()
        bdir = col.dump_cluster_bundle("torn-check")
        epath = os.path.join(bdir, "events.jsonl")
        n_events = sum(1 for _ in open(epath))
        # the dumper died mid-append: a trailing fragment with no
        # newline must be truncated, never raised over
        with open(epath, "a") as f:
            f.write('{"member": "a0", "ty')
        cb = load_cluster_bundle(bdir)
        assert cb["events_torn_truncated"] == 1
        assert len(cb["events"]) == n_events
        # a torn line ANYWHERE else is real corruption
        lines = open(epath).read().splitlines(keepends=True)
        lines[0] = '{"broken": \n'
        with open(epath, "w") as f:
            f.writelines(lines)
        with pytest.raises(json.JSONDecodeError):
            load_cluster_bundle(bdir)
    finally:
        r.shutdown()
        for a in agents.values():
            a.shutdown()


def test_collector_marks_unreachable_member_down(tmp_path):
    from ray_tpu.serve.fleet.transport import TransportError

    metrics.clear_registry()
    down = set()

    class _Gate:
        def __init__(self, rid, inner):
            self.rid, self.inner = rid, inner

        def call(self, *a, **kw):
            if self.rid in down:
                raise TransportError(f"{self.rid} unreachable")
            return self.inner.call(*a, **kw)

    d, dc, agents, r = _loopback_fleet(n=2, wrap_transport=_Gate)
    col = TelemetryCollector(r, cluster_dir=str(tmp_path)).attach()
    try:
        col.scrape_once()
        down.add("a0")     # partition a0's telemetry path
        res = col.scrape_once()
        assert res["a0"] is None
        m = col.members()["a0"]
        assert m["up"] is False and m["last_error"]
        bdir = col.dump_cluster_bundle("with-down-member")
        cb = load_cluster_bundle(bdir)
        assert "a0" in cb["coverage"]["unreachable"]
        assert "a1" in cb["coverage"]["scraped"]
        # heal: the next scrape flips it back up
        down.clear()
        assert col.scrape_once()["a0"] is not None
        assert col.members()["a0"]["up"] is True
    finally:
        r.shutdown()
        for a in agents.values():
            a.shutdown()


# --------------------------------------------- prometheus merging


def test_merge_prometheus_texts_labels_and_sorts():
    a = ("# HELP serve_qps queries\n"
         "# TYPE serve_qps gauge\n"
         "serve_qps 3.0\n"
         'serve_qps{route="/v1"} 2.0\n')
    b = ("# HELP serve_qps queries\n"
         "# TYPE serve_qps gauge\n"
         "serve_qps 5.0\n")
    out = merge_prometheus_texts({"b": b, "a": a})
    lines = out.splitlines()
    # one HELP/TYPE per family, then member-labeled samples with the
    # member label injected FIRST so same-named samples can't collide
    assert lines[0] == "# HELP serve_qps queries"
    assert lines[1] == "# TYPE serve_qps gauge"
    assert 'serve_qps{member="a"} 3.0' in lines
    assert 'serve_qps{member="a",route="/v1"} 2.0' in lines
    assert 'serve_qps{member="b"} 5.0' in lines
    # deterministic: members sort, so a's samples precede b's
    assert lines.index('serve_qps{member="a"} 3.0') \
        < lines.index('serve_qps{member="b"} 5.0')
    # label values escape like the native exposition
    esc = merge_prometheus_texts({'we"ird\\': a})
    assert 'member="we\\"ird\\\\"' in esc


def test_merge_prometheus_texts_empty():
    assert merge_prometheus_texts({}) == ""
    assert merge_prometheus_texts({"m": ""}) == ""
