"""Fault-tooling tests: memory monitor, chaos injection, node killer,
object spilling under a real cluster (reference analogues:
python/ray/tests/test_chaos.py, memory monitor tests,
test_object_spilling.py)."""
import time

import pytest

import ray_tpu
from ray_tpu._private.config import GlobalConfig
from ray_tpu._private.memory_monitor import MemoryMonitor


# ---- memory monitor ------------------------------------------------------

def test_memory_monitor_thresholds():
    usage = {"used": 50, "total": 100}
    events = []
    mon = MemoryMonitor(
        threshold=0.9,
        usage_provider=lambda: (usage["used"], usage["total"]),
        on_threshold=lambda f: events.append(("above", round(f, 2))),
        on_recovered=lambda f: events.append(("below", round(f, 2))))
    assert mon.check_once() is False
    usage["used"] = 95
    assert mon.check_once() is True
    assert mon.check_once() is True   # no duplicate events
    usage["used"] = 40
    assert mon.check_once() is False
    assert events == [("above", 0.95), ("below", 0.4)]


def test_memory_monitor_pauses_dispatch():
    import ray_tpu._private.worker as worker_mod
    if worker_mod.is_initialized():
        worker_mod.shutdown()
    GlobalConfig.reset()
    ray_tpu.init(num_cpus=4, num_tpus=0,
                 _system_config={"memory_monitor_threshold": 0.99,
                                 "memory_monitor_interval_ms": 50})
    try:
        rt = worker_mod.global_worker().runtime
        mon = rt._memory_monitor
        assert mon is not None

        @ray_tpu.remote
        def f():
            return 1

        assert ray_tpu.get(f.remote()) == 1
        # Force "above watermark" via the usage provider: dispatch
        # must stall.
        usage = {"used": 100, "total": 100}
        mon._provider = lambda: (usage["used"], usage["total"])
        mon.check_once()
        assert mon.above_threshold
        ref = f.remote()
        ready, _ = ray_tpu.wait([ref], timeout=0.4)
        assert ready == []
        # Recover: scheduler resumes via on_recovered.
        usage["used"] = 10
        assert ray_tpu.get(ref, timeout=10) == 1
    finally:
        ray_tpu.shutdown()
        GlobalConfig.reset()


# ---- chaos delay + node killer ------------------------------------------

def test_chaos_delay_local(rt):
    GlobalConfig.apply_system_config({"testing_delay_us_max": 2000,
                                      "testing_delay_us_min": 500})
    try:
        @ray_tpu.remote
        def f(x):
            return x

        assert ray_tpu.get([f.remote(i) for i in range(20)]) == \
            list(range(20))
    finally:
        GlobalConfig.apply_system_config({"testing_delay_us_max": 0,
                                          "testing_delay_us_min": 0})


@pytest.mark.slow
def test_node_killer_with_retries():
    import ray_tpu._private.worker as worker_mod
    from ray_tpu.runtime import Cluster
    if worker_mod.is_initialized():
        worker_mod.shutdown()
    with Cluster(num_workers=3,
                 resources_per_worker={"CPU": 2}) as cluster:
        killer = cluster.start_node_killer(interval_s=0.5, max_kills=2,
                                           respawn=True)

        @ray_tpu.remote(max_retries=5)
        def slow_inc(x):
            import time as _t
            _t.sleep(0.25)
            return x + 1

        # 40 tasks across ~5s of chaos: retries must absorb the kills.
        refs = [slow_inc.remote(i) for i in range(40)]
        out = ray_tpu.get(refs, timeout=120)
        assert out == [i + 1 for i in range(40)]
        killer.stop()
        assert killer.num_kills >= 1


def test_chaos_delay_propagates_to_workers():
    """Flag overrides must reach worker processes via RAY_TPU_* env."""
    import ray_tpu._private.worker as worker_mod
    from ray_tpu.runtime import Cluster
    if worker_mod.is_initialized():
        worker_mod.shutdown()
    GlobalConfig.apply_system_config({"testing_delay_us_max": 1000})
    try:
        with Cluster(num_workers=1,
                     resources_per_worker={"CPU": 2}):
            @ray_tpu.remote
            def read_flag():
                from ray_tpu._private.config import GlobalConfig as GC
                return GC.testing_delay_us_max

            assert ray_tpu.get(read_flag.remote()) == 1000
    finally:
        GlobalConfig.apply_system_config({"testing_delay_us_max": 0})
