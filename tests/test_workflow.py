"""Workflow engine tests (parity: python/ray/workflow tests)."""
import pytest

import ray_tpu
from ray_tpu import workflow
from ray_tpu.dag import InputNode
from ray_tpu.workflow import WorkflowStatus


@pytest.fixture
def wf(rt, tmp_path):
    workflow.init(str(tmp_path))
    yield workflow


def test_run_simple(wf):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    @ray_tpu.remote
    def double(x):
        return 2 * x

    dag = double.bind(add.bind(1, 2))
    assert wf.run(dag, workflow_id="w1") == 6
    assert wf.get_status("w1") == WorkflowStatus.SUCCESSFUL
    assert wf.get_output("w1") == 6
    assert ("w1", WorkflowStatus.SUCCESSFUL) in wf.list_all()


def test_run_with_input(wf):
    @ray_tpu.remote
    def mul(x, y):
        return x * y

    with InputNode() as inp:
        dag = mul.bind(inp["a"], inp["b"])
    assert wf.run(dag, a=3, b=4, workflow_id="w2") == 12


def test_idempotent_rerun(wf):
    calls = []

    @ray_tpu.remote
    def f():
        calls.append(1)
        return 7

    dag = f.bind()
    assert wf.run(dag, workflow_id="w3") == 7
    # Re-running a SUCCESSFUL workflow returns the stored output.
    assert wf.run(f.bind(), workflow_id="w3") == 7


def test_failure_and_resume(wf, tmp_path):
    marker = tmp_path / "allow"

    @ray_tpu.remote
    def first():
        return 10

    @ray_tpu.remote
    def flaky(x):
        import os
        if not os.path.exists(str(marker)):
            raise RuntimeError("boom")
        return x + 1

    dag = flaky.bind(first.bind())
    with pytest.raises(Exception):
        wf.run(dag, workflow_id="w4")
    assert wf.get_status("w4") == WorkflowStatus.FAILED

    # Re-running a FAILED id is rejected (would orphan checkpoints).
    with pytest.raises(ValueError):
        wf.run(dag, workflow_id="w4")

    marker.write_text("ok")
    # resume skips the completed `first` step and reruns only `flaky`
    assert wf.resume("w4") == 11
    assert wf.get_status("w4") == WorkflowStatus.SUCCESSFUL


def test_completed_steps_not_rerun_on_resume(wf, tmp_path):
    count_file = tmp_path / "count"
    count_file.write_text("0")
    marker = tmp_path / "allow"

    @ray_tpu.remote
    def counted():
        n = int(count_file.read_text()) + 1
        count_file.write_text(str(n))
        return n

    @ray_tpu.remote
    def gate(x):
        import os
        if not os.path.exists(str(marker)):
            raise RuntimeError("not yet")
        return x

    dag = gate.bind(counted.bind())
    with pytest.raises(Exception):
        wf.run(dag, workflow_id="w5")
    marker.write_text("ok")
    assert wf.resume("w5") == 1
    assert count_file.read_text() == "1"  # counted ran exactly once


def test_run_async_and_get_output(wf):
    @ray_tpu.remote
    def slow():
        import time
        time.sleep(0.2)
        return 42

    ref = wf.run_async(slow.bind(), workflow_id="w6")
    assert ray_tpu.get(ref) == 42
    assert wf.get_output("w6", timeout=5) == 42


def test_resume_all(wf, tmp_path):
    marker = tmp_path / "go"

    @ray_tpu.remote
    def gated():
        import os
        if not os.path.exists(str(marker)):
            raise RuntimeError("down")
        return "done"

    with pytest.raises(Exception):
        wf.run(gated.bind(), workflow_id="wa")
    marker.write_text("x")
    results = dict(wf.resume_all())
    assert results["wa"] == "done"


def test_delete(wf):
    @ray_tpu.remote
    def f():
        return 1

    wf.run(f.bind(), workflow_id="wd")
    wf.delete("wd")
    assert wf.get_status("wd") is None


def test_actor_dag_rejected(wf):
    @ray_tpu.remote
    class A:
        def m(self):
            return 1

    node = A.bind()
    with pytest.raises(TypeError):
        wf.run(node.m.bind(), workflow_id="wx")


def test_parallel_fanout(wf):
    @ray_tpu.remote
    def part(i):
        return i * i

    @ray_tpu.remote
    def gather(parts):
        return sum(parts)

    dag = gather.bind([part.bind(i) for i in range(5)])
    assert wf.run(dag, workflow_id="wp") == sum(i * i for i in range(5))


def test_cancel_then_resume(rt, tmp_path):
    """workflow.cancel stops the driving loop (in-flight steps
    best-effort-cancelled, checkpoints KEPT); resume() continues from
    the completed prefix."""
    import threading
    import time
    import pytest
    from ray_tpu import workflow
    from ray_tpu.workflow import WorkflowCancelledError

    workflow.init(str(tmp_path))
    ran = []

    @ray_tpu.remote
    def quick(tag):
        return tag

    import os
    gate = str(tmp_path / "gate")

    @ray_tpu.remote
    def slow(x, gate_path):
        import os as _os
        import time as _t
        t0 = _t.time()
        while not _os.path.exists(gate_path) and \
                _t.time() - t0 < 20:
            _t.sleep(0.05)
        return x + "!"

    dag = slow.bind(quick.bind("a"), gate)
    wid = "wf-cancel-1"

    def canceller():
        time.sleep(1.0)
        workflow.cancel(wid)

    threading.Thread(target=canceller, daemon=True).start()
    t0 = time.time()
    with pytest.raises(WorkflowCancelledError):
        workflow.run(dag, workflow_id=wid)
    assert time.time() - t0 < 15          # stopped, didn't wait out
    assert workflow.get_status(wid) == workflow.WorkflowStatus.CANCELED

    # resume() re-runs only what's missing; the workflow completes
    open(gate, "w").write("go")      # let the slow step finish fast
    out = workflow.resume(wid)
    assert out == "a!"


def test_continuation_recursion(wf):
    """workflow.continuation: a step returning a sub-DAG expands in
    place — recursive factorial (reference: workflow.continuation)."""
    @ray_tpu.remote
    def mul(a, b):
        return a * b

    @ray_tpu.remote
    def fact(n):
        if n <= 1:
            return 1
        return workflow.continuation(mul.bind(n, fact.bind(n - 1)))

    assert wf.run(fact.bind(5), workflow_id="wc1") == 120
    assert wf.get_status("wc1") == WorkflowStatus.SUCCESSFUL


def test_continuation_output_feeds_consumers(wf):
    """A continuation in the MIDDLE of a DAG: its consumers receive
    the sub-DAG's output, not the continuation object."""
    @ray_tpu.remote
    def double(x):
        return 2 * x

    @ray_tpu.remote
    def expand(x):
        return workflow.continuation(double.bind(x))

    @ray_tpu.remote
    def add_one(x):
        return x + 1

    assert wf.run(add_one.bind(expand.bind(10)),
                  workflow_id="wc2") == 21


def test_continuation_resume_reuses_sub_checkpoints(wf, tmp_path):
    """Crash mid-sub-workflow: resume re-runs the expanding parent
    (never checkpointed), re-expands to the SAME sub-step ids, and
    adopts already-checkpointed sub-steps instead of re-running them."""
    marker = tmp_path / "allow"
    counter = tmp_path / "count"

    @ray_tpu.remote
    def base():
        import os
        n = int(counter.read_text()) if os.path.exists(
            str(counter)) else 0
        counter.write_text(str(n + 1))
        return 7

    @ray_tpu.remote
    def flaky(x):
        import os
        if not os.path.exists(str(marker)):
            raise RuntimeError("boom")
        return x * 10

    @ray_tpu.remote
    def expand():
        return workflow.continuation(flaky.bind(base.bind()))

    dag = expand.bind()
    with pytest.raises(Exception):
        wf.run(dag, workflow_id="wc3")
    assert wf.get_status("wc3") == WorkflowStatus.FAILED
    assert counter.read_text() == "1"        # base ran once

    marker.write_text("ok")
    assert wf.resume("wc3") == 70
    # base's checkpoint was adopted on re-expansion, not re-executed
    assert counter.read_text() == "1"


def test_continuation_type_check():
    with pytest.raises(TypeError, match="bound DAG"):
        workflow.continuation(42)


def test_continuation_deep_recursion_bounded_ids(wf):
    """Regression: sub-step ids once nested a path component per
    recursion level (ENAMETOOLONG ~depth 550); long parent ids now
    collapse to digests, so deep tail recursion just works."""
    @ray_tpu.remote
    def countdown(n, acc):
        if n == 0:
            return acc
        return workflow.continuation(countdown.bind(n - 1, acc + n))

    assert wf.run(countdown.bind(600, 0),
                  workflow_id="wc-deep") == 600 * 601 // 2
