"""Placement group tests (reference analogue:
python/ray/tests/test_placement_group.py)."""
import pytest

import ray_tpu
from ray_tpu.util import (PlacementGroupSchedulingStrategy, placement_group,
                          remove_placement_group)


def test_pg_create_and_ready(rt):
    pg = placement_group([{"CPU": 2}, {"CPU": 2}], strategy="PACK")
    assert rt.get(pg.ready.remote() if hasattr(pg.ready, "remote")
                  else pg.ready(), timeout=5) is pg
    assert pg.is_ready()
    assert pg.bundle_specs == [{"CPU": 2.0}, {"CPU": 2.0}]


def test_pg_reserves_resources(rt):
    pg = placement_group([{"CPU": 6}])
    assert pg.wait(5)
    avail = rt.available_resources()
    assert avail["CPU"] == pytest.approx(2.0)
    remove_placement_group(pg)
    assert rt.available_resources()["CPU"] == pytest.approx(8.0)


def test_task_in_pg(rt):
    pg = placement_group([{"CPU": 4}])
    assert pg.wait(5)

    @rt.remote(
        num_cpus=4,
        scheduling_strategy=PlacementGroupSchedulingStrategy(
            placement_group=pg, placement_group_bundle_index=0))
    def inside():
        return "ran-in-pg"

    # Node has only 4 CPUs left but the task runs inside the reservation.
    assert rt.get(inside.remote(), timeout=5) == "ran-in-pg"


def test_infeasible_pg_never_ready(rt):
    pg = placement_group([{"CPU": 10000}])
    assert not pg.wait(0.2)


def test_invalid_strategy_rejected(rt):
    with pytest.raises(ValueError):
        placement_group([{"CPU": 1}], strategy="DIAGONAL")
    with pytest.raises(ValueError):
        placement_group([])
