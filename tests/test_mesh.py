"""Mesh + sharding-rules tests on the virtual 8-device CPU mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from ray_tpu.mesh import (MeshSpec, batch_sharding, create_mesh,
                          infer_sharding, shard_params, ShardingRules)


def test_mesh_spec_resolve():
    spec = MeshSpec(data=-1, tensor=2).resolve(8)
    assert spec.data == 4 and spec.tensor == 2
    assert spec.num_devices() == 8


def test_mesh_spec_aliases():
    spec = MeshSpec.from_dict({"dp": 2, "tp": 2, "pp": 2})
    assert spec.data == 2 and spec.tensor == 2 and spec.pipeline == 2


def test_mesh_spec_errors():
    with pytest.raises(ValueError):
        MeshSpec(data=3).resolve(8)
    with pytest.raises(ValueError):
        MeshSpec(data=-1, tensor=-1).resolve(8)
    with pytest.raises(ValueError):
        MeshSpec.from_dict({"bogus": 2})


def test_create_mesh_all_axes_present(cpu_mesh_devices):
    mesh = create_mesh({"data": 4, "tensor": 2})
    assert mesh.shape["data"] == 4
    assert mesh.shape["tensor"] == 2
    assert mesh.shape["pipeline"] == 1
    assert set(mesh.axis_names) == {
        "dcn", "pipeline", "data", "fsdp", "expert", "sequence", "tensor"}


def test_sharded_matmul_runs(cpu_mesh_devices):
    mesh = create_mesh({"data": 4, "tensor": 2})
    x = jnp.ones((16, 32))
    w = jnp.ones((32, 64))
    xs = jax.device_put(x, jax.NamedSharding(mesh, P(("data",), None)))
    ws = jax.device_put(w, jax.NamedSharding(mesh, P(None, "tensor")))

    @jax.jit
    def f(x, w):
        return x @ w

    out = f(xs, ws)
    np.testing.assert_allclose(np.asarray(out), np.full((16, 64), 32.0))


def test_sharding_rules_first_match_and_scalar():
    rules = ShardingRules([
        (r"kernel$", P(None, "tensor")),
        (r"embedding", P("tensor", None)),
    ])
    params = {
        "dense": {"kernel": jnp.ones((8, 8)), "bias": jnp.ones((8,))},
        "embedding": jnp.ones((100, 16)),
        "scale": jnp.float32(1.0),
    }
    specs = rules.tree_specs(params)
    assert specs["dense"]["kernel"] == P(None, "tensor")
    assert specs["dense"]["bias"] == P()          # no match → replicate
    assert specs["embedding"] == P("tensor", None)
    assert specs["scale"] == P()                  # scalar → replicate


def test_logical_axis_map():
    rules = ShardingRules(
        [(r"kernel$", P("embed", "heads"))],
        axis_map={"embed": None, "heads": "tensor"})
    spec = rules.spec_for("layer/kernel", jnp.ones((8, 8)))
    assert spec == P(None, "tensor")


def test_shard_params_places_on_mesh(cpu_mesh_devices):
    mesh = create_mesh({"data": 2, "tensor": 4})
    rules = ShardingRules([(r".*", P(None, "tensor"))])
    params = {"w": jnp.ones((16, 16))}
    sharded = shard_params(params, rules, mesh)
    shard_shapes = {s.data.shape for s in sharded["w"].addressable_shards}
    assert shard_shapes == {(16, 4)}   # 16 split over tensor=4


def test_batch_sharding_composite_axis(cpu_mesh_devices):
    mesh = create_mesh({"data": 4, "fsdp": 2})
    x = jnp.ones((32, 10))
    xs = jax.device_put(x, batch_sharding(mesh, None))
    # batch split over data*fsdp = 8
    assert {s.data.shape for s in xs.addressable_shards} == {(4, 10)}


def test_rule_with_too_many_dims_errors():
    rules = ShardingRules([(r".*", P("data", "tensor", "sequence"))])
    with pytest.raises(ValueError):
        rules.spec_for("w", jnp.ones((4, 4)))


def test_psum_over_mesh_axis(cpu_mesh_devices):
    from functools import partial
    mesh = create_mesh({"data": 8})

    @partial(jax.shard_map, mesh=mesh,
             in_specs=P(("data",)), out_specs=P())
    def total(x):
        return jax.lax.psum(jnp.sum(x, keepdims=True), ("data",))

    out = total(jnp.arange(64, dtype=jnp.float32).reshape(64, 1))
    assert float(out[0, 0]) == pytest.approx(sum(range(64)))
