"""Job submission + CLI tests (reference analogues:
dashboard/modules/job/tests/test_job_manager.py, test_sdk.py, and
python/ray/tests/test_cli.py)."""
import sys
import textwrap

import pytest
from click.testing import CliRunner

from ray_tpu.job import JobStatus, JobSubmissionClient
from ray_tpu.runtime import Cluster
from ray_tpu.scripts.cli import cli


@pytest.fixture(scope="module")
def cluster():
    import ray_tpu._private.worker as worker_mod
    if worker_mod.is_initialized():
        worker_mod.shutdown()
    c = Cluster(num_workers=1, resources_per_worker={"CPU": 2},
                connect=False)
    yield c
    c.shutdown()


@pytest.fixture
def client(cluster):
    return JobSubmissionClient(cluster.node.head_address)


def test_submit_and_succeed(client):
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c 'print(6 * 7)'")
    assert client.wait_until_finished(job_id, 60) == JobStatus.SUCCEEDED
    assert "42" in client.get_job_logs(job_id)
    info = client.get_job_info(job_id)
    assert info["status"] == JobStatus.SUCCEEDED
    assert info["end_time"] is not None


def test_job_failure_reports_exit_code(client):
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c 'import sys; sys.exit(3)'")
    assert client.wait_until_finished(job_id, 60) == JobStatus.FAILED
    assert "exit code 3" in client.get_job_info(job_id)["message"]


def test_stop_running_job(client):
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c 'import time; time.sleep(60)'")
    assert client.get_job_status(job_id) == JobStatus.RUNNING
    assert client.stop_job(job_id)
    assert client.wait_until_finished(job_id, 30) == JobStatus.STOPPED
    assert not client.stop_job(job_id)   # already terminal


def test_duplicate_submission_id_rejected(client):
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c 'pass'", submission_id="dup-1")
    client.wait_until_finished(job_id, 60)
    with pytest.raises(Exception):
        client.submit_job(entrypoint="true", submission_id="dup-1")


def test_job_runs_tasks_on_cluster(client, tmp_path):
    script = tmp_path / "driver.py"
    script.write_text(textwrap.dedent("""
        import os, sys
        sys.path.insert(0, %r)
        import ray_tpu
        ray_tpu.init(address=os.environ["RAY_TPU_ADDRESS"])

        @ray_tpu.remote
        def cube(x):
            return x ** 3

        print("total:", sum(ray_tpu.get(
            [cube.remote(i) for i in range(4)])))
        ray_tpu.shutdown()
    """ % "/root/repo"))
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} {script}")
    assert client.wait_until_finished(job_id, 120) == \
        JobStatus.SUCCEEDED
    assert "total: 36" in client.get_job_logs(job_id)


def test_env_vars_runtime_env(client):
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c "
                   f"'import os; print(os.environ[\"MY_FLAG\"])'",
        runtime_env={"env_vars": {"MY_FLAG": "flag-value"}})
    assert client.wait_until_finished(job_id, 60) == JobStatus.SUCCEEDED
    assert "flag-value" in client.get_job_logs(job_id)


# ---- CLI -----------------------------------------------------------------

def test_cli_status_and_list(cluster):
    addr = cluster.node.head_address
    runner = CliRunner()
    res = runner.invoke(cli, ["status", "--address", addr])
    assert res.exit_code == 0, res.output
    assert "Workers (1)" in res.output
    res = runner.invoke(cli, ["list", "--address", addr, "workers"])
    assert res.exit_code == 0
    assert "worker-0" in res.output


def test_cli_submit(cluster):
    addr = cluster.node.head_address
    runner = CliRunner()
    res = runner.invoke(cli, [
        "submit", "--address", addr, "--",
        sys.executable, "-c", "print('cli-job-ok')"])
    assert res.exit_code == 0, res.output
    assert "cli-job-ok" in res.output
    assert "SUCCEEDED" in res.output


def test_cli_memory(cluster):
    addr = cluster.node.head_address
    runner = CliRunner()
    res = runner.invoke(cli, ["memory", "--address", addr])
    assert res.exit_code == 0, res.output
    assert "capacity" in res.output


def test_cli_registers_ops_commands():
    """`python -m ray_tpu` exposes the ops surface (reference: ray
    dashboard / client server entry points)."""
    import subprocess
    import sys
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu", "--help"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = proc.stdout
    for cmd in ("start", "stop", "status", "submit", "logs", "memory",
                "metrics", "list", "timeline", "dashboard",
                "client-proxy", "serve"):
        assert cmd in out, f"missing CLI command {cmd}"


def test_cli_serve_run_status_shutdown(cluster, tmp_path, monkeypatch):
    """serve run/status/shutdown CLI against a running cluster
    (reference: serve/scripts.py CLI)."""
    import textwrap as tw
    import ray_tpu._private.worker as worker_mod

    (tmp_path / "cli_app.py").write_text(tw.dedent("""
        from ray_tpu import serve

        @serve.deployment
        def hello(payload=None):
            return {"hello": payload}
    """))
    monkeypatch.chdir(tmp_path)
    addr = cluster.node.head_address
    runner = CliRunner()
    try:
        res = runner.invoke(cli, ["serve", "run", "cli_app:hello",
                                  "--address", addr, "--no-blocking",
                                  "--port", "0"])
        assert res.exit_code == 0, res.output
        assert "hello" in res.output and "Deployed" in res.output

        res = runner.invoke(cli, ["serve", "status",
                                  "--address", addr])
        assert res.exit_code == 0, res.output
        assert "hello" in res.output and "HEALTHY" in res.output

        res = runner.invoke(cli, ["serve", "shutdown", "-y",
                                  "--address", addr])
        assert res.exit_code == 0, res.output

        res = runner.invoke(cli, ["serve", "status",
                                  "--address", addr])
        assert res.exit_code != 0      # controller gone
    finally:
        from ray_tpu import serve as serve_api
        from ray_tpu.serve.http_proxy import stop_http
        try:
            stop_http()
        except Exception:
            pass
        try:
            serve_api.shutdown()
        except Exception:
            pass
        if worker_mod.is_initialized():
            worker_mod.shutdown()
