"""Prefill/decode disaggregation: role-aware pools over the KV
handoff path.

What is covered here (PR 18):

- ``role_plan_caps``: the pure planner-knob mapping — prefill
  replicas refuse decode-phase growth, decode replicas collapse the
  prefill lane to a handoff-tail budget, unified passes through,
  typos raise.
- ``EnginePool(roles=)`` validation: every replica must be named, the
  names must be real roles, and a disaggregated pool without
  ``share_prefixes=True`` (the KV handoff wiring) is a construction
  error, not a silent re-prefill.
- Routing policy on scripted fakes: the two-leg online split (leg 1
  one bridging token on the prefill side, leg 2 the rest on the
  decode side carrying the finished-prefill push hint), the typed
  decode-in-place fallback when the decode side is gone, and the two
  guardrails the satellites demand — the batch lane and session
  stickiness never target a prefill-only replica.
- Token parity on real engines: a role-split pool must produce the
  exact ``generate()`` stream through the handoff, and again through
  the decode-dead fallback ladder (disaggregation can cost time,
  never correctness).
- Per-role autoscaling: two ``PoolAutoscaler``s over ``RolePoolView``s
  of ONE pool reach different sizes on the same signals.
- ``validate_pull_knobs`` / ``LlamaDeployment`` knob validation: junk
  pull knobs and contradictory role splits fail at construction.
"""
import pytest

jnp = pytest.importorskip("jax.numpy")

from ray_tpu.models.llama import Llama, llama_tiny
from ray_tpu.serve import kv_migration
from ray_tpu.serve.engine import LLMEngine
from ray_tpu.serve.engine_pool import EnginePool, RolePoolView
from ray_tpu.serve.scheduler import (LANE_BATCH, ROLE_DECODE,
                                     ROLE_PREFILL, ROLE_UNIFIED,
                                     role_plan_caps)
from ray_tpu.serve.errors import EngineShutdown


# ----------------------------------------------------- planner knobs


def test_role_plan_caps_prefill_clamps_run_ahead():
    caps = role_plan_caps(ROLE_PREFILL, page_size=16, decode_chunk=4,
                          prefill_budget=512, max_run_ahead=256)
    assert caps == {"prefill_budget": 512, "max_run_ahead": 4}


def test_role_plan_caps_decode_collapses_prefill_budget():
    # page_size + 1: one residual page plus the bridging token — the
    # largest tail a handoff can leave unpulled
    caps = role_plan_caps(ROLE_DECODE, page_size=16, decode_chunk=4,
                          prefill_budget=512, max_run_ahead=256)
    assert caps == {"prefill_budget": 17, "max_run_ahead": 256}


def test_role_plan_caps_unified_passthrough():
    caps = role_plan_caps(ROLE_UNIFIED, page_size=16, decode_chunk=4,
                          prefill_budget=512, max_run_ahead=256)
    assert caps == {"prefill_budget": 512, "max_run_ahead": 256}


def test_role_plan_caps_floors_never_zero():
    # degenerate knobs still leave one unit of budget on each side
    caps = role_plan_caps(ROLE_PREFILL, page_size=1, decode_chunk=0,
                          prefill_budget=1, max_run_ahead=8)
    assert caps["max_run_ahead"] == 1
    caps = role_plan_caps(ROLE_DECODE, page_size=0, decode_chunk=4,
                          prefill_budget=0, max_run_ahead=8)
    assert caps["prefill_budget"] == 1


def test_role_plan_caps_unknown_role_raises():
    with pytest.raises(ValueError, match="unknown replica role"):
        role_plan_caps("prefil", page_size=16, decode_chunk=4,
                       prefill_budget=512, max_run_ahead=256)


# ------------------------------------------------- pull-knob typing


def test_validate_pull_knobs_defaults_and_overrides():
    assert kv_migration.validate_pull_knobs() == {}
    assert kv_migration.validate_pull_knobs(None, None) == {}
    assert kv_migration.validate_pull_knobs(2.5, 0.01) == {
        "deadline_s": 2.5, "backoff_s": 0.01}
    # one-sided override returns only the overridden knob
    assert kv_migration.validate_pull_knobs(backoff_s=1) == {
        "backoff_s": 1.0}


@pytest.mark.parametrize("bad", ["soon", 0, -1.0, float("inf"),
                                 float("nan"), [1.0]])
def test_validate_pull_knobs_rejects_junk(bad):
    with pytest.raises(ValueError, match="kv pull deadline_s"):
        kv_migration.validate_pull_knobs(deadline_s=bad)
    with pytest.raises(ValueError, match="kv pull backoff_s"):
        kv_migration.validate_pull_knobs(backoff_s=bad)


# ------------------------------------------------------ fake engines


class FakeHandle:
    def __init__(self, engine, tokens, exc=None):
        self._engine = engine
        self._tokens = list(tokens)
        self._exc = exc
        self.cancelled = False

    def stream(self):
        for t in self._tokens:
            yield t
        if self._exc is not None:
            raise self._exc

    def cancel(self):
        self.cancelled = True
        return True


class FakeEngine:
    """The pool-facing engine surface, scripted — accepts the full
    disaggregated submit signature (``pull=``, ``priority=``) and
    records every kwarg so tests can assert on what routing sent."""

    def __init__(self, idx, *, outstanding=0, page_size=16,
                 report_extra=None):
        self.idx = idx
        self.Pg = page_size
        self._stopped = False
        self._draining = False
        self.outstanding = outstanding
        self.report_extra = dict(report_extra or {})
        self.submits = []           # (prompt, max_new_tokens, kwargs)
        self.script = []            # queued submit outcomes
        self.started = False

    def start(self):
        self.started = True
        return self

    def submit(self, prompt, max_new_tokens=64, deadline_s=None, **kw):
        if self._stopped:
            raise EngineShutdown("engine stopped")
        self.submits.append((list(prompt), max_new_tokens, kw))
        out = self.script.pop(0) if self.script else [1, 2]
        if isinstance(out, BaseException):
            raise out
        return FakeHandle(self, out)

    def shutdown(self):
        self._stopped = True

    def drain(self):
        self._draining = True

    def wait_idle(self, timeout_s=30.0):
        return True

    def is_idle(self):
        return True

    def load_report(self):
        rpt = {"free_slots": 4, "free_pages": 100, "queue_depth": 0,
               "outstanding_tokens": self.outstanding,
               "max_queued": None, "shed_retry_after_s": 1.0,
               "draining": self._draining, "stopped": self._stopped,
               "prefix_digest": frozenset()}
        rpt.update(self.report_extra)
        return rpt

    def prefix_stats(self):
        return None

    def spec_stats(self):
        return None

    def lifecycle_stats(self):
        return {"max_queued": None, "max_retries": 2,
                "retry_backoff_s": 0.02, "shed": 0}


def _fake_disagg_pool(fakes, n=None, **kw):
    kw.setdefault("share_prefixes", True)
    kw.setdefault("roles", [ROLE_PREFILL, ROLE_DECODE])
    pool = EnginePool(lambda i: fakes[i], n or len(fakes), **kw)
    return pool


# -------------------------------------------- construction contracts


def test_roles_must_name_every_replica():
    fakes = [FakeEngine(0), FakeEngine(1)]
    with pytest.raises(ValueError, match="every replica"):
        EnginePool(lambda i: fakes[i], 2, share_prefixes=True,
                   roles=[ROLE_PREFILL])


def test_unknown_role_rejected_at_construction():
    fakes = [FakeEngine(0), FakeEngine(1)]
    with pytest.raises(ValueError, match="unknown replica role"):
        EnginePool(lambda i: fakes[i], 2, share_prefixes=True,
                   roles=[ROLE_PREFILL, "decoder"])


def test_disaggregated_pool_requires_share_prefixes():
    fakes = [FakeEngine(0), FakeEngine(1)]
    with pytest.raises(ValueError, match="share_prefixes"):
        EnginePool(lambda i: fakes[i], 2,
                   roles=[ROLE_PREFILL, ROLE_DECODE])
    # an all-unified roles list is NOT disaggregated: no wiring needed
    pool = EnginePool(lambda i: fakes[i], 2,
                      roles=[ROLE_UNIFIED, ROLE_UNIFIED])
    assert not pool.disaggregated()
    pool.shutdown()


def test_pool_kv_pull_knobs_validated_at_construction():
    fakes = [FakeEngine(0), FakeEngine(1)]
    with pytest.raises(ValueError, match="kv pull deadline_s"):
        _fake_disagg_pool(fakes, kv_pull_deadline_s=-1.0)


# ------------------------------------------------ routing on fakes


def test_two_leg_split_routes_prefill_then_decode_with_hint():
    prompt = list(range(1, 33))            # 2 full pages at Pg=16
    fakes = [FakeEngine(0), FakeEngine(1)]
    fakes[0].script = [[5]]                # leg 1: bridging token
    fakes[1].script = [[6, 7, 8]]          # leg 2: rest of stream
    pool = _fake_disagg_pool(fakes)
    try:
        assert pool.disaggregated()
        toks = pool.submit(prompt, max_new_tokens=4).result()
        assert toks == [5, 6, 7, 8]
        # leg 1 landed on the prefill replica for exactly one token
        (p1, mnt1, _), = fakes[0].submits
        assert (p1, mnt1) == (prompt, 1)
        # leg 2 resumed at full prompt length + bridging token on the
        # decode replica, carrying the donor's push hint
        (p2, mnt2, kw2), = fakes[1].submits
        assert (p2, mnt2) == (prompt + [5], 3)
        hint = kw2["pull"]
        assert hint["replica_idx"] == 0
        assert len(hint["hashes"]) == 2
        ps = pool.pool_stats()
        assert ps["disagg_handoffs"] == 1
        assert ps.get("disagg_handoff_fallbacks", 0) == 0
        names = [e[2] for e in pool.events.tail(64)]
        assert "handoff" in names
        assert "handoff_first_token" in names
    finally:
        pool.shutdown()


def test_dead_decode_side_falls_back_to_decode_in_place():
    prompt = list(range(1, 33))
    fakes = [FakeEngine(0), FakeEngine(1)]
    fakes[0].script = [[5], [6, 7, 8]]     # leg 1, then the fallback
    pool = _fake_disagg_pool(fakes)
    try:
        fakes[1]._stopped = True           # decode side dies
        toks = pool.submit(prompt, max_new_tokens=4).result()
        assert toks == [5, 6, 7, 8]
        # both legs served by the donor: leg 1, then decode-in-place
        assert [s[:2] for s in fakes[0].submits] == [
            (prompt, 1), (prompt + [5], 3)]
        # the fallback leg is a direct-target submit, no pull hint
        assert "pull" not in fakes[0].submits[1][2]
        ps = pool.pool_stats()
        assert ps["disagg_handoff_fallbacks"] == 1
        names = [e[2] for e in pool.events.tail(64)]
        assert "handoff_fallback" in names
    finally:
        pool.shutdown()


def test_single_token_requests_skip_the_handoff():
    fakes = [FakeEngine(0), FakeEngine(1)]
    fakes[0].script = [[9]]
    fakes[1].script = [[9]]
    pool = _fake_disagg_pool(fakes)
    try:
        pool.submit(list(range(1, 33)), max_new_tokens=1).result()
        assert pool.pool_stats().get("disagg_handoffs", 0) == 0
    finally:
        pool.shutdown()


def test_batch_lane_never_lands_on_prefill_replica():
    # the prefill replica is EMPTIER — batch must still skip it
    fakes = [FakeEngine(0, outstanding=0),
             FakeEngine(1, outstanding=900)]
    pool = _fake_disagg_pool(fakes)
    try:
        pool.submit(list(range(8)), max_new_tokens=4,
                    priority=LANE_BATCH).result()
        assert fakes[0].submits == []
        assert len(fakes[1].submits) == 1
    finally:
        pool.shutdown()


def test_batch_lane_with_only_prefill_capacity_fails_typed():
    fakes = [FakeEngine(0), FakeEngine(1)]
    pool = _fake_disagg_pool(fakes)
    try:
        fakes[1].shutdown()
        with pytest.raises(EngineShutdown):
            pool.submit(list(range(8)), max_new_tokens=4,
                        priority=LANE_BATCH).result()
    finally:
        pool.shutdown()


def test_sticky_session_pinned_to_prefill_is_dropped_not_followed():
    fakes = [FakeEngine(0, outstanding=900),
             FakeEngine(1, outstanding=0)]
    pool = _fake_disagg_pool(fakes)
    try:
        # a stale placement entry (e.g. written before the replica
        # was re-roled) pins the session to the prefill replica
        with pool._lock:
            pool._sticky["s"] = 0
        pool.submit(list(range(8)), max_new_tokens=1,
                    session_id="s").result()
        assert fakes[0].submits == []      # never followed to prefill
        assert pool._sticky["s"] == 1      # re-pinned where it landed
        assert pool.route_stats["sticky_hits"] == 0
    finally:
        pool.shutdown()


# --------------------------------------------- per-role autoscaling


def test_role_pool_views_scale_apart_on_the_same_pool():
    from ray_tpu.serve.pool_autoscaler import (
        ImmediateCapacityProvider, PoolAutoscaler, SLOPolicy)
    fakes = [FakeEngine(i) for i in range(4)]
    # the prefill side is breaching its TTFT SLO; the decode side is
    # comfortably idle on ITL + free slots
    fakes[0].report_extra = {"ttft_ewma_s": 0.5, "total_slots": 4}
    fakes[1].report_extra = {"itl_ewma_s": 0.001, "total_slots": 4}
    pool = _fake_disagg_pool(fakes, n=2)
    try:
        provider = ImmediateCapacityProvider()
        sc_pre = PoolAutoscaler(
            RolePoolView(pool, ROLE_PREFILL),
            SLOPolicy(min_replicas=1, max_replicas=2,
                      ttft_slo_s=0.001, cooldown_up_s=0.0),
            provider)
        sc_dec = PoolAutoscaler(
            RolePoolView(pool, ROLE_DECODE),
            SLOPolicy(min_replicas=1, max_replicas=2,
                      itl_slo_s=60.0, idle_stable_s=3600.0),
            provider)
        for _ in range(4):
            sc_pre.tick()
            sc_dec.tick()
            if pool.role_counts().get(ROLE_PREFILL, 0) > 1:
                break
        counts = pool.role_counts()
        assert counts[ROLE_PREFILL] == 2    # scaled up into fakes[2]
        assert counts[ROLE_DECODE] == 1     # held
        assert sc_pre.counts["scale_ups"] >= 1
        assert sc_dec.counts["scale_ups"] == 0
        # the new replica joined with the view's role
        ps = pool.pool_stats()
        roles = [r["role"] for r in ps["replicas"]]
        assert roles.count(ROLE_PREFILL) == 2
        assert "autoscale_by_role" in ps
        assert set(ps["autoscale_by_role"]) == {ROLE_PREFILL,
                                                ROLE_DECODE}
    finally:
        pool.shutdown()


def test_role_pool_view_rejects_unknown_role():
    fakes = [FakeEngine(0), FakeEngine(1)]
    pool = _fake_disagg_pool(fakes)
    try:
        with pytest.raises(ValueError, match="unknown replica role"):
            RolePoolView(pool, "prefil")
    finally:
        pool.shutdown()


# --------------------------------------------- real-engine parity


@pytest.fixture(scope="module")
def tiny_model():
    # fp32 so greedy decode is bit-identical across replicas
    cfg = llama_tiny(dtype=jnp.float32)
    model = Llama(cfg)
    import jax
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    return model, params


@pytest.fixture(autouse=True)
def _no_page_leaks(monkeypatch):
    """Every real engine built here — including replicas the pool
    added or killed — must end with allocator occupancy equal to
    prefix-cache residency."""
    created = []
    orig = LLMEngine.__init__

    def record(self, *args, **kwargs):
        orig(self, *args, **kwargs)
        created.append(self)

    monkeypatch.setattr(LLMEngine, "__init__", record)
    yield
    for eng in created:
        cached = (eng.prefix_cache.cached_pages
                  if eng.prefix_cache is not None else 0)
        occ = eng.alloc.occupancy()
        assert occ == cached, (
            f"engine leaked pages at teardown: occupancy {occ} != "
            f"prefix-cache residency {cached}")


def _reference_completion(model, params, prompt, n):
    import numpy as np
    from ray_tpu.models.llama import generate
    out = generate(model, params, jnp.asarray([prompt], jnp.int32),
                   max_new_tokens=n, temperature=0.0)
    return np.asarray(out)[0, len(prompt):].tolist()


def _real_disagg_pool(model, params):
    def factory(idx):
        return LLMEngine(model, params, max_slots=2, page_size=8,
                         n_pages=48, chunk=2, prefill_chunk=8,
                         temperature=0.0, eos_id=-1, seed=0,
                         prefix_cache=True)
    return EnginePool(factory, 2, share_prefixes=True,
                      roles=[ROLE_PREFILL, ROLE_DECODE], seed=0)


def test_disagg_handoff_is_token_identical(tiny_model):
    import numpy as np
    model, params = tiny_model
    rng = np.random.RandomState(0)
    prompt = rng.randint(1, llama_tiny().vocab_size - 1,
                         size=24).tolist()
    want = _reference_completion(model, params, prompt, 8)
    pool = _real_disagg_pool(model, params)
    try:
        toks = pool.submit(list(prompt), max_new_tokens=8).result()
        assert toks == want
        ps = pool.pool_stats()
        assert ps["disagg_handoffs"] >= 1
        assert ps.get("disagg_handoff_fallbacks", 0) == 0
        # the decode leg actually pulled the donor's pages instead of
        # re-prefilling: the prompt is 3 full pages at Pg=8
        decode_eng = next(
            e for e, r in zip(pool.engines(), ps["replicas"])
            if r["role"] == ROLE_DECODE)
        assert decode_eng.kv_migration_stats["pulled_pages"] >= 3
    finally:
        pool.shutdown()


def test_disagg_decode_dead_recovers_token_identical(tiny_model):
    import numpy as np
    model, params = tiny_model
    rng = np.random.RandomState(1)
    prompt = rng.randint(1, llama_tiny().vocab_size - 1,
                         size=24).tolist()
    want = _reference_completion(model, params, prompt, 8)
    pool = _real_disagg_pool(model, params)
    try:
        ps = pool.pool_stats()
        decode_idx = next(i for i, r in enumerate(ps["replicas"])
                          if r["role"] == ROLE_DECODE)
        pool.engines()[decode_idx].shutdown()
        toks = pool.submit(list(prompt), max_new_tokens=8).result()
        assert toks == want
        assert pool.pool_stats()["disagg_handoff_fallbacks"] >= 1
    finally:
        pool.shutdown()


# ------------------------------------------- deployment-level knobs


def test_deployment_role_knobs_require_disaggregate():
    from ray_tpu.serve.llm import LlamaDeployment
    with pytest.raises(ValueError, match="require"):
        LlamaDeployment(params=object(), prefill_replicas=2)


def test_deployment_disaggregate_excludes_fleet():
    from ray_tpu.serve.llm import LlamaDeployment
    with pytest.raises(ValueError, match="exclusive"):
        LlamaDeployment(params=object(), disaggregate=True,
                        prefix_cache=True, fleet=2)


def test_deployment_disaggregate_requires_prefix_cache():
    from ray_tpu.serve.llm import LlamaDeployment
    with pytest.raises(ValueError, match="prefix_cache"):
        LlamaDeployment(params=object(), disaggregate=True)


def test_deployment_replica_count_must_match_role_split():
    from ray_tpu.serve.llm import LlamaDeployment
    with pytest.raises(ValueError, match="conflicts"):
        LlamaDeployment(params=object(), disaggregate=True,
                        prefix_cache=True, prefill_replicas=2,
                        decode_replicas=2, num_engine_replicas=3)
    d = LlamaDeployment(params=object(), disaggregate=True,
                        prefix_cache=True, prefill_replicas=1,
                        decode_replicas=2)
    assert d.num_engine_replicas == 3


def test_deployment_rejects_junk_pull_knobs():
    from ray_tpu.serve.llm import LlamaDeployment
    with pytest.raises(ValueError, match="kv pull"):
        LlamaDeployment(params=object(), kv_pull_deadline_s=0)
