"""Regression tests for defects found in code review (resource accounting,
PG removal leak, fire-and-forget leak, actor-in-task creation, @method)."""
import time

import pytest

import ray_tpu
from ray_tpu._private.worker import global_worker
from ray_tpu.exceptions import GetTimeoutError
from ray_tpu.util import placement_group, remove_placement_group


def test_resource_accounting_exact_after_blocking_get(rt):
    # A task that blocks in get() releases and then RE-acquires its CPU;
    # availability must return to exactly the full capacity at the end.
    @rt.remote(num_cpus=2)
    def child():
        return 1

    @rt.remote(num_cpus=2)
    def parent():
        return ray_tpu.get(child.remote()) + 1

    assert rt.get(parent.remote()) == 2
    deadline = time.time() + 5
    while time.time() < deadline:
        if rt.available_resources()["CPU"] == pytest.approx(8.0):
            break
        time.sleep(0.01)
    assert rt.available_resources()["CPU"] == pytest.approx(8.0)


def test_remove_pending_pg_does_not_leak(rt):
    # Reserve most of the node, create a PG that can't fit yet, remove it
    # while pending, then free the hog: full capacity must come back.
    hog = placement_group([{"CPU": 6}])
    assert hog.wait(5)
    pending = placement_group([{"CPU": 6}])
    assert not pending.wait(0.2)
    remove_placement_group(pending)
    remove_placement_group(hog)
    deadline = time.time() + 5
    while time.time() < deadline:
        if rt.available_resources()["CPU"] == pytest.approx(8.0):
            break
        time.sleep(0.01)
    assert rt.available_resources()["CPU"] == pytest.approx(8.0)


def test_fire_and_forget_result_not_leaked(rt):
    runtime = global_worker().runtime

    @rt.remote
    def produce():
        return list(range(1000))

    for _ in range(10):
        produce.remote()   # ref discarded immediately
    time.sleep(0.5)
    stats = runtime.store.stats()
    assert stats["num_ready"] == 0, stats


def test_actor_creation_inside_task_no_deadlock(rt):
    # Task holds all CPUs, then creates an actor needing CPUs: must not
    # self-deadlock (caller releases while blocked).
    @rt.remote
    class Helper:
        def ping(self):
            return "pong"

    @rt.remote(num_cpus=8)
    def spawns_actor():
        h = Helper.remote()
        return ray_tpu.get(h.ping.remote())

    assert rt.get(spawns_actor.remote(), timeout=30) == "pong"


def test_get_overall_timeout(rt):
    @rt.remote
    def never():
        time.sleep(60)

    refs = [never.remote() for _ in range(3)]
    start = time.time()
    with pytest.raises(GetTimeoutError):
        rt.get(refs, timeout=0.5)
    # Overall deadline, not per-ref (would be ~1.5s+ if per-ref).
    assert time.time() - start < 1.2


def test_method_decorator_num_returns(rt):
    @rt.remote
    class Splitter:
        @ray_tpu.method(num_returns=2)
        def split(self, pair):
            return pair[0], pair[1]

    s = Splitter.remote()
    a, b = s.split.remote((1, 2))
    assert rt.get(a) == 1
    assert rt.get(b) == 2


def test_concurrent_get_if_exists(rt):
    import threading

    @rt.remote
    class S:
        def pid(self):
            return id(self)

    handles = []
    errs = []

    def make():
        try:
            handles.append(
                S.options(name="race", get_if_exists=True).remote())
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=make) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    pids = {rt.get(h.pid.remote()) for h in handles}
    assert len(pids) == 1  # everyone got the same actor
