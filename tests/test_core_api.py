"""Core task/object API tests (reference analogues:
python/ray/tests/test_basic.py, test_advanced.py)."""
import time

import pytest

import ray_tpu
from ray_tpu.exceptions import (GetTimeoutError, TaskCancelledError,
                                TaskError)


def test_put_get(rt):
    ref = rt.put({"a": 1})
    assert rt.get(ref) == {"a": 1}


def test_put_objectref_rejected(rt):
    ref = rt.put(1)
    with pytest.raises(TypeError):
        rt.put(ref)


def test_simple_task(rt):
    @rt.remote
    def add(a, b):
        return a + b

    assert rt.get(add.remote(1, 2)) == 3


def test_task_with_kwargs_and_options(rt):
    @rt.remote(num_cpus=0.5)
    def f(a, b=10):
        return a * b

    assert rt.get(f.remote(3)) == 30
    assert rt.get(f.options(name="named").remote(2, b=4)) == 8


def test_task_dependency_chain(rt):
    @rt.remote
    def inc(x):
        return x + 1

    ref = inc.remote(0)
    for _ in range(9):
        ref = inc.remote(ref)
    assert rt.get(ref) == 10


def test_nested_tasks_no_deadlock(rt):
    # More nesting depth than CPU capacity: blocked parents must release
    # their resources (reference: worker leasing prevents this deadlock).
    @rt.remote(num_cpus=1)
    def fib(n):
        if n < 2:
            return n
        return sum(rt.get([fib.remote(n - 1), fib.remote(n - 2)]))

    assert rt.get(fib.remote(10)) == 55


def test_multiple_returns(rt):
    @rt.remote(num_returns=3)
    def three():
        return 1, 2, 3

    r1, r2, r3 = three.remote()
    assert rt.get([r1, r2, r3]) == [1, 2, 3]


def test_num_returns_mismatch_is_error(rt):
    @rt.remote(num_returns=2)
    def wrong():
        return (1, 2, 3)

    refs = wrong.remote()
    with pytest.raises(TaskError):
        rt.get(refs[0])


def test_task_exception_propagates(rt):
    @rt.remote
    def boom():
        raise ValueError("kapow")

    with pytest.raises(TaskError) as ei:
        rt.get(boom.remote())
    assert "kapow" in str(ei.value)
    assert isinstance(ei.value.cause, ValueError)


def test_get_timeout(rt):
    @rt.remote
    def slow():
        time.sleep(5)
        return 1

    with pytest.raises(GetTimeoutError):
        rt.get(slow.remote(), timeout=0.1)


def test_wait(rt):
    @rt.remote
    def sleepy(t):
        time.sleep(t)
        return t

    fast = sleepy.remote(0.01)
    slow = sleepy.remote(2.0)
    ready, not_ready = rt.wait([fast, slow], num_returns=1, timeout=1.0)
    assert ready == [fast]
    assert not_ready == [slow]


def test_wait_timeout_returns_partial(rt):
    @rt.remote
    def never():
        time.sleep(30)

    ready, not_ready = rt.wait([never.remote()], num_returns=1,
                               timeout=0.05)
    assert ready == []
    assert len(not_ready) == 1


def test_object_ref_as_arg_resolved(rt):
    @rt.remote
    def double(x):
        return 2 * x

    assert rt.get(double.remote(rt.put(21))) == 42


def test_retry_on_exception(rt):
    import itertools
    counter = itertools.count()

    @rt.remote(max_retries=3, retry_exceptions=True)
    def flaky():
        if next(counter) < 2:
            raise RuntimeError("transient")
        return "ok"

    assert rt.get(flaky.remote()) == "ok"


def test_no_retry_by_default_on_app_error(rt):
    import itertools
    counter = itertools.count()

    @rt.remote(max_retries=5)
    def flaky():
        next(counter)
        raise RuntimeError("app error")

    with pytest.raises(TaskError):
        rt.get(flaky.remote())
    assert next(counter) == 1  # ran exactly once


def test_cancel_pending_task(rt):
    @rt.remote(num_cpus=8)
    def hog():
        time.sleep(3)

    @rt.remote(num_cpus=8)
    def victim():
        return 1

    h = hog.remote()
    v = victim.remote()   # queued behind the hog
    rt.cancel(v)
    with pytest.raises(TaskCancelledError):
        rt.get(v, timeout=5)
    del h


def test_infeasible_task_errors(rt):
    @rt.remote(num_cpus=10000)
    def big():
        return 1

    with pytest.raises(TaskError):
        rt.get(big.remote(), timeout=5)


def test_cluster_resources(rt):
    res = rt.cluster_resources()
    assert res["CPU"] == 8.0


def test_fractional_resources(rt):
    @rt.remote(num_cpus=0.25)
    def tiny(i):
        time.sleep(0.05)
        return i

    assert sorted(rt.get([tiny.remote(i) for i in range(32)])) == \
        list(range(32))


def test_custom_resources(rt):
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4, resources={"accel_slice": 2})

    @ray_tpu.remote(resources={"accel_slice": 1})
    def uses_slice():
        return "ok"

    assert ray_tpu.get(uses_slice.remote()) == "ok"


def test_lineage_reconstruction(rt):
    @rt.remote
    def produce():
        return list(range(100))

    ref = produce.remote()
    assert rt.get(ref) == list(range(100))
    runtime = ray_tpu._private.worker.global_worker().runtime
    runtime.simulate_object_loss(ref)
    assert runtime.reconstruct_object(ref)
    assert rt.get(ref, timeout=5) == list(range(100))


def test_timeline_records_tasks(rt):
    @rt.remote
    def traced():
        return 1

    rt.get(traced.remote())
    events = rt.timeline()
    assert any("traced" in e["name"] for e in events)


def test_runtime_context_surface(rt):
    """ray_tpu.get_runtime_context() (reference parity): identity is
    queryable from the driver AND inside tasks/actors."""
    ctx = ray_tpu.get_runtime_context()
    assert ctx.get_job_id()
    assert ctx.get_task_id() is None          # driver: no task

    @ray_tpu.remote
    def who():
        c = ray_tpu.get_runtime_context()
        return {"task": c.get_task_id(), "job": c.get_job_id(),
                "node": c.get_node_id()}

    info = ray_tpu.get(who.remote())
    assert info["task"]
    assert info["job"]


def test_runtime_context_in_multiprocess_worker():
    import ray_tpu
    import ray_tpu._private.worker as worker_mod
    from ray_tpu.runtime import Cluster
    if worker_mod.is_initialized():
        worker_mod.shutdown()
    with Cluster(num_workers=1, resources_per_worker={"CPU": 2}):
        @ray_tpu.remote
        def who():
            c = ray_tpu.get_runtime_context()
            return c.get_task_id(), c.get_worker_id(), c.get_node_id()

        tid, wid, nid = ray_tpu.get(who.remote())
        assert tid and len(tid) == 40        # 20-byte task id hex
        assert nid


def test_request_resources_demand_floor():
    """autoscaler.sdk.request_resources pins a standing demand the
    load snapshot carries even with an empty queue."""
    import ray_tpu
    import ray_tpu._private.worker as worker_mod
    from ray_tpu.autoscaler import request_resources
    from ray_tpu.runtime import Cluster
    if worker_mod.is_initialized():
        worker_mod.shutdown()
    with Cluster(num_workers=1, resources_per_worker={"CPU": 2}):
        from ray_tpu._private.worker import global_worker
        head = global_worker().runtime.head
        request_resources(bundles=[{"CPU": 4.0}, {"TPU": 8.0}])
        snap = head.call("load_metrics_snapshot")
        assert {"CPU": 4.0} in snap["pending_demands"]
        assert {"TPU": 8.0} in snap["pending_demands"]
        request_resources(bundles=[])         # clears the floor
        snap = head.call("load_metrics_snapshot")
        assert {"TPU": 8.0} not in snap["pending_demands"]


def test_runtime_context_in_actor():
    import ray_tpu
    import ray_tpu._private.worker as worker_mod
    from ray_tpu.runtime import Cluster
    if worker_mod.is_initialized():
        worker_mod.shutdown()
    with Cluster(num_workers=1, resources_per_worker={"CPU": 2}):
        @ray_tpu.remote
        class A:
            def ident(self):
                c = ray_tpu.get_runtime_context()
                return c.get_actor_id(), c.get_task_id()

        a = A.remote()
        aid, tid = ray_tpu.get(a.ident.remote())
        assert aid == a.actor_id.hex()
        assert tid
