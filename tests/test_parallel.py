"""Parallelism-strategy tests on the 8-device CPU mesh (topology-
parameterized, the reference's collective-test pattern:
util/collective/tests/single_node_cpu)."""
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from ray_tpu.mesh import create_mesh
from ray_tpu.ops.attention import xla_attention
from ray_tpu.parallel import (SwitchMoE, pipeline_apply, ring_attention,
                              sequence_sharded_attention, ulysses_attention)


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.RandomState(0)
    B, T, H, D = 2, 64, 4, 16
    q = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_full(qkv, causal, cpu_mesh_devices):
    q, k, v = qkv
    mesh = create_mesh({"sequence": 8})
    expected = xla_attention(q, k, v, causal=causal,
                             precision="highest")
    out = sequence_sharded_attention(q, k, v, mesh, causal=causal,
                                     impl="ring")
    np.testing.assert_allclose(np.asarray(expected), np.asarray(out),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_attention_matches_full(qkv, causal, cpu_mesh_devices):
    q, k, v = qkv
    mesh = create_mesh({"sequence": 4, "data": 2})  # H=4 divisible by 4
    expected = xla_attention(q, k, v, causal=causal,
                             precision="highest")
    out = sequence_sharded_attention(q, k, v, mesh, causal=causal,
                                     impl="ulysses")
    np.testing.assert_allclose(np.asarray(expected), np.asarray(out),
                               rtol=1e-4, atol=1e-5)


def test_ring_attention_grads_flow(qkv, cpu_mesh_devices):
    q, k, v = qkv
    mesh = create_mesh({"sequence": 8})

    def loss_ring(q, k, v):
        return jnp.sum(
            sequence_sharded_attention(q, k, v, mesh, causal=True) ** 2)

    def loss_full(q, k, v):
        return jnp.sum(
            xla_attention(q, k, v, causal=True, precision="highest") ** 2)

    g_ring = jax.grad(loss_ring)(q, k, v)
    g_full = jax.grad(loss_full)(q, k, v)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_full),
                               rtol=1e-3, atol=1e-4)


def test_pipeline_matches_sequential(cpu_mesh_devices):
    from ray_tpu.parallel.pipeline import stack_stage_params
    S, B, D = 4, 8, 16
    mesh = create_mesh({"pipeline": S})
    rng = np.random.RandomState(1)
    per_stage = [{"w": jnp.asarray(rng.randn(D, D) / np.sqrt(D),
                                   jnp.float32),
                  "b": jnp.asarray(rng.randn(D) * 0.1, jnp.float32)}
                 for _ in range(S)]
    x = jnp.asarray(rng.randn(B, D), jnp.float32)

    def stage_fn(p, a):
        return jnp.tanh(a @ p["w"] + p["b"])

    expected = x
    for p in per_stage:
        expected = stage_fn(p, expected)

    stacked = stack_stage_params(per_stage)
    out = pipeline_apply(stage_fn, stacked, x, num_microbatches=4,
                         mesh=mesh)
    np.testing.assert_allclose(np.asarray(expected), np.asarray(out),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_microbatch_validation(cpu_mesh_devices):
    mesh = create_mesh({"pipeline": 4})
    with pytest.raises(ValueError):
        pipeline_apply(lambda p, a: a, {"w": jnp.ones((4, 1))},
                       jnp.ones((7, 1)), num_microbatches=3, mesh=mesh)


def test_moe_routes_and_matches_manual(cpu_mesh_devices):
    B, T, D, E, FF = 2, 16, 8, 4, 32
    moe = SwitchMoE(num_experts=E, d_model=D, d_ff=FF,
                    capacity_factor=4.0,   # no drops at this size
                    use_sharding_constraint=False)
    rng = jax.random.PRNGKey(0)
    x = jax.random.normal(jax.random.PRNGKey(1), (B, T, D))
    variables = moe.init(rng, x)
    out, aux = moe.apply(variables, x, mutable=["losses"])
    assert out.shape == (B, T, D)

    # Manual reference: route each token to its argmax expert.
    p = variables["params"]
    tokens = np.asarray(x).reshape(-1, D)
    logits = tokens @ np.asarray(p["router"])
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    idx = probs.argmax(-1)
    expected = np.zeros_like(tokens)
    for n, e in enumerate(idx):
        h = np.maximum(tokens[n] @ np.asarray(p["w1"])[e], 0)
        expected[n] = (h @ np.asarray(p["w2"])[e]) * probs[n, e]
    np.testing.assert_allclose(np.asarray(out).reshape(-1, D), expected,
                               rtol=1e-4, atol=1e-5)
    assert float(aux["losses"]["load_balance"][0]) > 0


def test_moe_sharded_execution(cpu_mesh_devices):
    mesh = create_mesh({"expert": 4, "data": 2})
    moe = SwitchMoE(num_experts=4, d_model=8, d_ff=16, dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(2), (4, 8, 8))
    variables = moe.init(jax.random.PRNGKey(0), x)
    with jax.set_mesh(mesh):
        out = jax.jit(lambda v, x: moe.apply(v, x))(variables, x)
    assert out.shape == x.shape
    # Same numbers as unsharded execution.
    expected = moe.apply(variables, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=1e-4, atol=1e-5)
