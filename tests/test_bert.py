"""BERT encoder family: forward semantics, masking, MLM training,
sharding (same test strategy as test_models.py for the decoders)."""
import numpy as np
import pytest


def test_forward_shapes_and_padding_mask():
    import jax
    import jax.numpy as jnp
    from ray_tpu.models import Bert, bert_tiny
    cfg = bert_tiny()
    model = Bert(cfg)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(1, cfg.vocab_size, (2, 16)))
    params = model.init(jax.random.PRNGKey(0), ids,
                    return_mlm_logits=True)
    h = model.apply(params, ids)
    assert h.shape == (2, 16, cfg.dim)
    logits = model.apply(params, ids, return_mlm_logits=True)
    assert logits.shape == (2, 16, cfg.vocab_size)
    # padding positions must not influence unpadded outputs
    mask = jnp.asarray([[1] * 16, [1] * 8 + [0] * 8])
    h_masked = model.apply(params, ids, attention_mask=mask)
    ids_trunc = ids[1:, :8]
    h_trunc = model.apply(params, ids_trunc,
                          attention_mask=jnp.ones((1, 8), jnp.int32))
    np.testing.assert_allclose(np.asarray(h_masked[1, :8]),
                               np.asarray(h_trunc[0]), atol=2e-4)


def test_mask_tokens_contract():
    from ray_tpu.models import mask_tokens
    rng = np.random.RandomState(0)
    ids = rng.randint(5, 1000, (8, 64))
    masked, labels = mask_tokens(rng, ids, vocab_size=1024,
                                 mask_token=3)
    picked = labels != -100
    frac = picked.mean()
    assert 0.08 < frac < 0.25                  # ~15% of positions
    # labels hold the ORIGINAL ids at picked positions
    assert (labels[picked] == ids[picked]).all()
    # most picked positions became [MASK]
    assert (masked[picked] == 3).mean() > 0.6
    # unpicked positions are untouched
    assert (masked[~picked] == ids[~picked]).all()


def test_mlm_training_learns_and_shards():
    """MLM loss decreases on a learnable toy stream, with params
    sharded by bert_sharding_rules on the 8-device mesh (the spmd
    step builder — same path JaxTrainer uses)."""
    import jax
    import jax.numpy as jnp
    import optax
    from ray_tpu.mesh.device_mesh import create_mesh
    from ray_tpu.models import (Bert, bert_sharding_rules, bert_tiny,
                                mask_tokens, mlm_loss)
    from ray_tpu.train.spmd import (TrainState, make_train_step,
                                    put_batch, shard_state)
    cfg = bert_tiny(vocab_size=64, dim=64, n_layers=2, n_heads=2,
                    hidden_dim=128)
    mesh = create_mesh({"data": 2, "fsdp": 2, "tensor": 2})
    model = Bert(cfg)
    rng = np.random.RandomState(0)
    init_ids = jnp.asarray(rng.randint(4, cfg.vocab_size, (2, 16)))
    params = model.init(jax.random.PRNGKey(0), init_ids,
                    return_mlm_logits=True)
    # structured data: token at t+1 == token at t (copy pattern), so
    # masked positions are predictable from neighbors
    def batch_ids(n=16):
        base = rng.randint(4, cfg.vocab_size, (n, 1))
        return np.repeat(base, 16, axis=1)

    optimizer = optax.adam(1e-2)
    rules = bert_sharding_rules()
    state = shard_state(TrainState.create(params, optimizer), rules,
                        mesh)

    def loss_fn(p, batch):
        logits = model.apply(p, batch["ids"],
                             return_mlm_logits=True)
        return mlm_loss(logits, batch["labels"])

    step = make_train_step(loss_fn, optimizer)
    losses = []
    with jax.set_mesh(mesh):
        for _ in range(100):
            ids = batch_ids()
            masked, labels = mask_tokens(rng, ids, cfg.vocab_size,
                                         mask_token=3)
            batch = put_batch({"ids": masked.astype(np.int32),
                               "labels": labels.astype(np.int32)},
                              mesh)
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
    # sharded as declared: qkv kernels split over tensor
    qkv = state.params["params"]["layer_0"]["attn"]["qkv"]["kernel"]
    assert len(qkv.sharding.device_set) > 1


def test_pooled_output():
    import jax
    import jax.numpy as jnp
    from ray_tpu.models import Bert, bert_tiny
    cfg = bert_tiny()
    model = Bert(cfg)
    ids = jnp.ones((2, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids,
                        return_pooled=True)
    hidden, pooled = model.apply(params, ids, return_pooled=True)
    assert hidden.shape == (2, 8, cfg.dim)
    assert pooled.shape == (2, cfg.dim)
    assert float(abs(pooled).max()) <= 1.0      # tanh-bounded
