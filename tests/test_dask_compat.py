"""Dask-on-ray_tpu graph scheduler tests.

Mirrors the reference's dask scheduler tests
(python/ray/util/dask/tests/test_dask_scheduler.py): graph execution
through the runtime, shared-node deduplication, nested containers,
and the delayed API — all against the plain dask graph PROTOCOL, no
dask package needed.
"""
import operator

import pytest

import ray_tpu
from ray_tpu.util.dask_compat import compute, delayed, ray_dask_get


def test_basic_graph(rt):
    dsk = {
        "x": 1,
        "y": 2,
        "z": (operator.add, "x", "y"),
        "w": (sum, ["x", "y", "z"]),
    }
    assert ray_dask_get(dsk, "w") == 6
    assert ray_dask_get(dsk, ["z", ["x", "w"]]) == [3, [1, 6]]


def test_shared_node_computed_once(rt):
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

        def total(self):
            return self.n

    c = Counter.remote()

    def expensive():
        return ray_tpu.get(c.bump.remote())

    dsk = {
        "base": (expensive,),
        "a": (operator.add, "base", 10),
        "b": (operator.add, "base", 20),
        "out": (operator.add, "a", "b"),
    }
    assert ray_dask_get(dsk, "out") == 1 + 10 + 1 + 20
    assert ray_tpu.get(c.total.remote()) == 1     # base ran ONCE


def test_inline_task_and_literals(rt):
    dsk = {"out": (operator.mul, (operator.add, 2, 3), 4)}
    assert ray_dask_get(dsk, "out") == 20
    dsk2 = {"lit": [1, 2, 3], "out": (sum, "lit")}
    assert ray_dask_get(dsk2, "out") == 6


def test_cycle_detected(rt):
    dsk = {"a": (operator.add, "b", 1), "b": (operator.add, "a", 1)}
    with pytest.raises(ValueError, match="cycle"):
        ray_dask_get(dsk, "a")


def test_error_propagates(rt):
    def boom():
        raise RuntimeError("graph kaboom")

    dsk = {"x": (boom,), "y": (operator.add, "x", 1)}
    with pytest.raises(Exception, match="graph kaboom"):
        ray_dask_get(dsk, "y")


def test_delayed_api(rt):
    @delayed
    def add(a, b):
        return a + b

    @delayed
    def double(x):
        return 2 * x

    shared = add(1, 2)
    tree = add(double(shared), shared)       # 2*3 + 3
    assert tree.compute() == 9
    a, b = compute(add(1, 1), double(5))
    assert (a, b) == (2, 10)


def test_delayed_kwargs_and_containers(rt):
    @delayed
    def weighted(xs, scale=1):
        return sum(xs) * scale

    @delayed
    def one():
        return 1

    assert weighted([one(), 2, 3], scale=10).compute() == 60


def test_distributed_runtime_graph():
    import ray_tpu._private.worker as worker_mod
    from ray_tpu.runtime import Cluster
    if worker_mod.is_initialized():
        worker_mod.shutdown()
    with Cluster(num_workers=2, resources_per_worker={"CPU": 2}):
        dsk = {"x": 10, "y": (operator.mul, "x", "x"),
               "z": (operator.add, "y", (operator.neg, "x"))}
        assert ray_dask_get(dsk, "z") == 90
