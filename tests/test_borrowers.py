"""Distributed borrower protocol (VERDICT r5 #6).

Escaped refs (pickled into task args / actor state) used to revert to
LRU-pressure lifetime; now the head tracks borrows
(reference: reference_count.h:39-61 — the owner frees only after every
borrow drops), so:
- escaped-then-dropped objects free eagerly (churn test), and
- a borrower's live ref keeps the object alive across nodes after the
  owner dropped its own ref.
"""
import gc
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.runtime import Cluster

GRACE = 0.5          # shrink the protocol's grace window for tests


@pytest.fixture(scope="module")
def cluster():
    import os
    import ray_tpu._private.worker as worker_mod
    if worker_mod.is_initialized():
        worker_mod.shutdown()
    os.environ["RAY_TPU_borrow_grace_s"] = str(GRACE)
    from ray_tpu._private.config import GlobalConfig
    GlobalConfig.reset()
    c = Cluster(num_workers=1,
                resources_per_worker={"CPU": 2, "node0": 10},
                store_capacity=256 * 1024 * 1024)
    c.add_node(num_workers=1,
               resources_per_worker={"CPU": 2, "node1": 10},
               store_capacity=256 * 1024 * 1024)
    yield c
    c.shutdown()
    os.environ.pop("RAY_TPU_borrow_grace_s", None)
    GlobalConfig.reset()


def _store():
    from ray_tpu._private.worker import global_worker
    return global_worker().runtime.plane.store


def _wait_gone(oid, timeout=15.0):
    deadline = time.time() + timeout
    store = _store()
    while time.time() < deadline:
        if not store.contains(oid):
            return True
        time.sleep(0.25)
    return False


def test_escaped_then_dropped_frees_eagerly(cluster):
    """Churn of escaped objects must free without LRU pressure: pass
    each ref through a task (escape + borrow + drop), then drop the
    owner ref — the object disappears within the grace window, long
    before the 256MB store would force eviction."""
    @ray_tpu.remote(resources={"node1": 1})
    def touch(a):
        return a.nbytes

    oids = []
    for _ in range(4):
        ref = ray_tpu.put(np.ones((32 << 20) // 8))   # 32MB each
        assert ray_tpu.get(touch.remote(ref)) == 32 << 20
        oids.append(ref.id)
        del ref
    gc.collect()
    for oid in oids:
        assert _wait_gone(oid), f"{oid.hex()[:12]} not freed eagerly"


def test_borrower_keeps_alive_across_nodes(cluster):
    """An actor on the other node holds a borrowed ref in its state:
    after the owner drops its ref the object must survive (the borrow
    pins it) and remain resolvable; freeing happens only after the
    borrower lets go."""
    @ray_tpu.remote(resources={"node1": 1})
    class Holder:
        def __init__(self):
            self.ref = None

        def hold(self, boxed):
            # boxed=[ref]: a nested ref stays a ref (top-level args
            # auto-resolve to values)
            self.ref = boxed[0]
            return True

        def peek(self):
            return float(ray_tpu.get(self.ref)[0])

        def drop(self):
            self.ref = None
            import gc as _gc
            _gc.collect()
            return True

    h = Holder.remote()
    ref = ray_tpu.put(np.full((8 << 20) // 8, 7.0))
    assert ray_tpu.get(h.hold.remote([ref]))
    oid = ref.id
    # give the borrow registration a beat to land before dropping
    time.sleep(1.0)
    del ref
    gc.collect()
    # well past the grace window, the borrow still pins the object
    time.sleep(GRACE * 4 + 1.0)
    assert ray_tpu.get(h.peek.remote()) == 7.0
    # borrower drops -> freed within grace + flusher lag
    assert ray_tpu.get(h.drop.remote())
    assert _wait_gone(oid), "object not freed after last borrow drop"
