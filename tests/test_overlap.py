"""Overlapped hot-loop tests (the engine's double-buffered
plan/dispatch pipeline, serve/engine.py ``overlap=``).

The load-bearing property is EXACT greedy parity: the overlapped loop
plans round N+1 from the STALE token frontier (dispatched-but-
undrained steps) while round N executes, so every correctness path
that reads tokens — eos detection, speculation, prefix-cache resume,
cancellation, fault containment — is re-proven token-identical
against the lockstep loop (``overlap=False``: full readback drain
before every plan, the pre-overlap behavior). Plus the pipeline
mechanics themselves: the stale-cap discard bound in the planner, the
depth-2 in-flight fence, the heartbeat contract of the blocking
drain, and the per-round host-gap accounting the ``--overlap-ab``
bench artifact is built from.
"""
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models.llama import Llama, generate, llama_tiny
from ray_tpu.serve.engine import LLMEngine
from ray_tpu.serve.scheduler import SlotView, plan_step


@pytest.fixture(scope="module")
def tiny_model():
    # fp32 so both arms agree bit-for-bit (bf16 rounding could flip
    # greedy argmax on ties and fake a pipeline bug).
    cfg = llama_tiny(dtype=jnp.float32)
    model = Llama(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    return model, params


@pytest.fixture(autouse=True)
def _no_page_leaks(monkeypatch):
    """Same invariant net as test_llm_engine.py: every engine built
    in this file must end with its allocator back at baseline —
    an overlapped round that loses track of an undrained rider's
    pages shows up here, with the leaked ids named."""
    created = []
    orig = LLMEngine.__init__

    def record(self, *args, **kwargs):
        orig(self, *args, **kwargs)
        created.append(self)

    monkeypatch.setattr(LLMEngine, "__init__", record)
    yield
    for eng in created:
        cached = (eng.prefix_cache.cached_pages
                  if eng.prefix_cache is not None else 0)
        occ = eng.alloc.occupancy()
        assert occ == cached, (
            f"engine leaked pages at teardown: occupancy {occ} != "
            f"prefix-cache residency {cached}; leaked ids "
            f"{sorted(eng.alloc.leak_report())[:16]}")


def _reference_completion(model, params, prompt, n):
    out = generate(model, params, jnp.asarray([prompt], jnp.int32),
                   max_new_tokens=n, temperature=0.0)
    return np.asarray(out)[0, len(prompt):].tolist()


def _run(eng, prompts, n):
    hs = [eng.submit(p, max_new_tokens=n) for p in prompts]
    while eng.step():
        pass
    return [h.result() for h in hs]


def _both_arms(tiny_model, prompts, n, **kw):
    """The file's workhorse: the identical engine + load under
    overlap=False and overlap=True; returns (lockstep, overlapped)
    outputs for the caller's parity assert."""
    model, params = tiny_model
    outs = []
    for overlap in (False, True):
        eng = LLMEngine(model, params, overlap=overlap, **kw)
        outs.append(_run(eng, [list(p) for p in prompts], n))
    return outs


REP_PROMPT = ([7, 8, 9, 10] * 6)[:20]


# ------------------------------------------------------- knob resolution


def test_overlap_default_on_and_kwarg(tiny_model, monkeypatch):
    model, params = tiny_model
    monkeypatch.delenv("RAY_TPU_OVERLAP", raising=False)
    assert LLMEngine(model, params, max_slots=1, page_size=8,
                     n_pages=16).overlap is True
    assert LLMEngine(model, params, max_slots=1, page_size=8,
                     n_pages=16, overlap=False).overlap is False


def test_overlap_env_override_beats_kwarg(tiny_model, monkeypatch):
    """RAY_TPU_OVERLAP pins the mode for a live deployment bisect:
    it must win over whatever the code passed."""
    model, params = tiny_model
    monkeypatch.setenv("RAY_TPU_OVERLAP", "0")
    assert LLMEngine(model, params, max_slots=1, page_size=8,
                     n_pages=16, overlap=True).overlap is False
    monkeypatch.setenv("RAY_TPU_OVERLAP", "1")
    assert LLMEngine(model, params, max_slots=1, page_size=8,
                     n_pages=16, overlap=False).overlap is True
    monkeypatch.setenv("RAY_TPU_OVERLAP", "bogus")
    assert LLMEngine(model, params, max_slots=1, page_size=8,
                     n_pages=16, overlap=False).overlap is False


# ----------------------------------------------------- planner stale cap


_PLAN = dict(total_slots=2, prefill_budget=16, decode_chunk=4,
             max_run_ahead=128, prefill_batch=4, eos_bounded=True)


def test_stale_rider_caps_eos_dispatch_at_one_chunk():
    """The discard bound: an eos-bounded rider with undrained steps
    may already be past its eos — the next dispatch shrinks from the
    usual 2*decode_chunk run-ahead to ONE decode_chunk."""
    fresh = [SlotView(sid=i, admit_seq=i, prompt_remaining=0,
                      owed=50, seeded=True) for i in range(2)]
    assert plan_step(fresh, **_PLAN).decode_steps == 8
    stale = [SlotView(sid=0, admit_seq=0, prompt_remaining=0,
                      owed=50, seeded=True, stale=4),
             SlotView(sid=1, admit_seq=1, prompt_remaining=0,
                      owed=50, seeded=True)]
    assert plan_step(stale, **_PLAN).decode_steps == 4


def test_stale_cap_only_binds_eos_bounded_plans():
    """Without an eos there is nothing to discard — staleness must
    not cost deferred-mode run-ahead."""
    views = [SlotView(sid=i, admit_seq=i, prompt_remaining=0,
                      owed=24, seeded=True, stale=4)
             for i in range(2)]
    plan = plan_step(views, **dict(_PLAN, eos_bounded=False))
    assert plan.decode_steps == 24


# --------------------------------------------------------- token parity


def test_plain_eos_parity(tiny_model):
    """Late-revealed eos: the overlapped loop learns about the eos
    one round late, discards the overshoot, and must still emit the
    exact lockstep truncation."""
    model, params = tiny_model
    prompt = [5, 9, 2]
    ref = _reference_completion(model, params, prompt, 16)
    eos = ref[3]                   # a token that actually samples
    lock, over = _both_arms(tiny_model, [prompt], 16, max_slots=2,
                            page_size=8, n_pages=32, chunk=4,
                            eos_id=eos)
    assert over == lock == [ref[:ref.index(eos) + 1]]


def test_multi_slot_eos_bounded_parity(tiny_model):
    """eos configured but never sampled (eos_id=-1): every slot runs
    to budget through the stale-frontier scheduler; full-length
    streams must match the lockstep arm exactly."""
    rng = np.random.default_rng(7)
    prompts = [rng.integers(1, 255, size=9 + i).tolist()
               for i in range(4)]
    lock, over = _both_arms(tiny_model, prompts, 20, max_slots=2,
                            page_size=8, n_pages=64, chunk=4,
                            eos_id=-1)
    assert over == lock


def test_deferred_mode_parity(tiny_model):
    """No eos at all (deferred emission): overlap unifies with the
    old opportunistic path and must change nothing."""
    rng = np.random.default_rng(11)
    prompts = [rng.integers(1, 255, size=12).tolist()
               for _ in range(3)]
    lock, over = _both_arms(tiny_model, prompts, 16, max_slots=2,
                            page_size=8, n_pages=64, chunk=4)
    assert over == lock


def test_spec_oracle_parity(tiny_model):
    """Speculation from a stale frontier, accept path: drafts from
    the n-gram proposer over a repetitive prompt fire and verify —
    outputs token-identical across modes, spec lane engaged in both.
    """
    model, params = tiny_model
    prompt = list(REP_PROMPT)
    outs, engines = [], []
    for overlap in (False, True):
        eng = LLMEngine(model, params, max_slots=2, page_size=8,
                        n_pages=64, chunk=4, spec_len=4,
                        spec_ngram=2, eos_id=-1, overlap=overlap)
        outs.append(_run(eng, [prompt, list(REP_PROMPT[2:])], 24))
        engines.append(eng)
    assert outs[0] == outs[1]
    for eng in engines:
        st = eng.spec_stats()
        assert st["rounds"] > 0 and st["accepted_tokens"] > 0


def test_spec_anti_oracle_full_rejection_parity(tiny_model):
    """Stale-frontier drafts are only hints: a proposer that is
    ALWAYS wrong forces every verify to reject everything and roll
    back the KV frontier — under the overlapped loop the rollback
    machinery and the stale planner compose, and the output is still
    the exact greedy stream."""
    model, params = tiny_model
    prompt = [5, 9, 2, 7, 11]
    ref = _reference_completion(model, params, prompt, 16)
    wrong = [(t + 1) % 256 for t in ref]

    class _Anti:
        def __init__(self):
            self._done = 0

        def sync(self, context):
            self._done = len(context) - len(prompt)

        def propose(self, k):
            return wrong[self._done:self._done + k]

    outs = []
    for overlap in (False, True):
        eng = LLMEngine(model, params, max_slots=2, page_size=8,
                        n_pages=32, chunk=4, spec_len=4,
                        spec_proposer=_Anti, eos_id=-1,
                        overlap=overlap)
        outs.append(_run(eng, [prompt], 16))
        st = eng.spec_stats()
        assert st["proposed_tokens"] > 0 and st["accept_rate"] == 0.0
    assert outs[0] == outs[1] == [ref]


def test_prefix_cache_hit_resume_parity(tiny_model):
    """A cache-hit admission enters mid-prompt; under overlap its
    first decode rides behind undrained neighbors. Sequential runs so
    the second request HITS the pages the first inserted."""
    model, params = tiny_model
    prefix = list(REP_PROMPT)
    prompts = [prefix + [3, 1], prefix + [4, 2]]
    outs = []
    for overlap in (False, True):
        eng = LLMEngine(model, params, max_slots=2, page_size=8,
                        n_pages=32, chunk=4, prefix_cache=True,
                        eos_id=-1, overlap=overlap)
        got = _run(eng, [prompts[0]], 16) + _run(eng, [prompts[1]], 16)
        assert eng.prefix_cache.stats()["hit_tokens"] > 0
        eng.prefix_cache.check_invariants()
        outs.append(got)
    assert outs[0] == outs[1]


def test_cancel_mid_round_overlap(tiny_model):
    """Cancel while the pipeline holds undrained dispatches: the
    victim's slot frees NOW, late readbacks carrying the dead rider
    are discarded (req.closed guard), the survivor stays exact, and
    the engine quiesces leak-free."""
    from ray_tpu.serve import engine as engine_mod
    from ray_tpu.serve.errors import RequestCancelled
    from ray_tpu.serve.faults import check_quiesced
    model, params = tiny_model
    p1, p2 = [3, 1, 4, 1, 5], [2, 7, 1, 8]
    want1 = _reference_completion(model, params, p1, 24)
    eng = LLMEngine(model, params, max_slots=2, page_size=8,
                    n_pages=64, chunk=4, eos_id=-1, overlap=True)
    h1 = eng.submit(p1, max_new_tokens=24)
    h2 = eng.submit(p2, max_new_tokens=24)
    # a CPU "device" finishes each dispatch before the next step, so
    # the opportunistic drains would empty the pipeline every round;
    # report every buffer still-computing to hold the cancel window
    # open the way a real accelerator does
    real_ready = engine_mod._dev_ready
    engine_mod._dev_ready = lambda buf: False
    try:
        # step until the victim is live and the pipeline actually
        # holds an undrained dispatch (the overlapped-loop-specific
        # window)
        for _ in range(64):
            eng.step()
            if (eng.slots[1] is not None
                    and eng.slots[1].req is h2._req and eng._fetchq):
                break
        else:
            raise AssertionError("pipeline never held in-flight work")
        assert h2.cancel() is True
    finally:
        engine_mod._dev_ready = real_ready
    assert eng.slots[1] is None          # slot + pages freed NOW
    while eng.step():
        pass
    assert h1.result() == want1
    with pytest.raises(RequestCancelled):
        h2.result()
    assert eng.stats["cancelled"] == 1
    check_quiesced(eng)


def test_contained_fault_requeue_parity(tiny_model):
    """Fault containment under overlap: a decode dispatch fault fails
    ONLY the culprit; the innocent co-rider requeues (its stale
    pipeline state discarded with the fault) and re-decodes to the
    exact greedy stream."""
    from ray_tpu.serve.faults import FaultInjector, check_quiesced
    model, params = tiny_model
    inj = FaultInjector()
    inj.inject("dispatch_decode", sid=1, round=3)
    eng = LLMEngine(model, params, max_slots=4, page_size=8,
                    n_pages=64, chunk=2, eos_id=-1, overlap=True,
                    fault_injector=inj, retry_backoff_s=0.005)
    p1, p2 = [3, 1, 4, 1, 5], [2, 7, 1, 8]
    want1 = _reference_completion(model, params, p1, 16)
    h1 = eng.submit(p1, max_new_tokens=16)   # slot 0: innocent
    h2 = eng.submit(p2, max_new_tokens=16)   # slot 1: culprit
    while eng.step():
        pass
    with pytest.raises(RuntimeError, match="injected fault"):
        h2.result()
    assert h1.result() == want1
    assert eng.stats["contained_faults"] == 1
    assert eng.stats["fault_failed"] == 1
    assert eng.stats["failed_all"] == 0
    check_quiesced(eng)


# --------------------------------------------------- pipeline mechanics


def test_fetchq_depth_never_exceeds_two(tiny_model):
    """The trailing drain (limit=1, keep=1) is the discard bound's
    other half: after every step the pipeline holds at most two
    undrained dispatches."""
    model, params = tiny_model
    eng = LLMEngine(model, params, max_slots=2, page_size=8,
                    n_pages=64, chunk=4, eos_id=-1, overlap=True)
    hs = [eng.submit([5, 9, 2, 7], max_new_tokens=32),
          eng.submit([1, 8, 3], max_new_tokens=32)]
    while eng.step():
        assert len(eng._fetchq) <= 2
    assert all(len(h.result()) == 32 for h in hs)


def test_heartbeat_touched_before_blocking_readback(tiny_model):
    """The watchdog contract: the blocking drain must refresh the
    heartbeat BEFORE each device_get, so a slow-but-progressing
    multi-buffer readback never reads as one long stall."""
    from ray_tpu.serve import engine as engine_mod
    model, params = tiny_model
    eng = LLMEngine(model, params, max_slots=2, page_size=8,
                    n_pages=64, chunk=4, eos_id=-1, overlap=True)
    h = eng.submit([5, 9, 2, 7], max_new_tokens=16)
    # hold undrained work in the pipeline (a warm CPU jit finishes
    # each dispatch before the next step, emptying the queue)
    real_ready = engine_mod._dev_ready
    engine_mod._dev_ready = lambda buf: False
    seen = []
    real_get = jax.device_get

    def spy(x):
        seen.append(eng._hb)
        return real_get(x)

    try:
        for _ in range(8):
            eng.step()
            if eng._fetchq:
                break
        else:
            raise AssertionError("pipeline never held in-flight work")
        eng._hb = time.monotonic() - 1000.0  # pretend: ancient
        jax.device_get = spy
        with eng._lock:
            eng._drain_fetches_locked()      # full blocking drain
    finally:
        jax.device_get = real_get
        engine_mod._dev_ready = real_ready
    assert seen, "drain performed no readback"
    now = time.monotonic()
    assert all(now - hb < 10.0 for hb in seen), (
        "device_get saw a stale heartbeat — a slow readback would "
        "ride the watchdog ladder to SUSPECT/WEDGED")
    assert now - eng._hb < 10.0              # touched after, too
    while eng.step():
        pass
    assert len(h.result()) == 16


def test_round_events_and_histogram_crosscheck(tiny_model):
    """The obs satellite: every round appends a typed "round" event
    whose host_gap_s sums to what the serve_phase_host_gap_s
    histogram accumulated — the bench artifact and trace report
    derive from the events, the dashboard from the histogram, and
    they must tell the same story."""
    from ray_tpu.serve import obs
    from ray_tpu.util import metrics
    model, params = tiny_model
    metrics.clear_registry()
    eng = LLMEngine(model, params, max_slots=2, page_size=8,
                    n_pages=64, chunk=4, eos_id=-1, overlap=True,
                    events=True)
    _run(eng, [[5, 9, 2, 7], [1, 8, 3]], 16)
    rounds = [e for e in eng.events.snapshot() if e[2] == "round"]
    assert rounds, "no round events recorded"
    for e in rounds:
        d = e[5]
        assert d["overlap"] is True
        assert 0.0 <= d["host_gap_s"] <= d["wall_s"]
    gap_total = sum(e[5]["host_gap_s"] for e in rounds)
    hist = metrics.registry()[obs.HOST_GAP]
    samples = hist._samples()
    assert len(samples) == 1
    _tags, s = samples[0]
    assert s["count"] == len(rounds)
    # events round to 6dp; the histogram holds raw observations
    assert abs(s["sum"] - gap_total) < 1e-4


def test_load_report_exposes_pipeline_state(tiny_model):
    model, params = tiny_model
    eng = LLMEngine(model, params, max_slots=2, page_size=8,
                    n_pages=64, chunk=4, eos_id=-1, overlap=True)
    rep = eng.load_report()
    assert rep["overlap"] is True
    assert rep["fetchq_depth"] == 0
    assert rep["pending_prefills"] == 0
    h = eng.submit([5, 9, 2], max_new_tokens=8)
    for _ in range(4):
        eng.step()
    rep = eng.load_report()
    assert isinstance(rep["fetchq_depth"], int)
    assert 0 <= rep["fetchq_depth"] <= 2
    while eng.step():
        pass
    assert len(h.result()) == 8
    # drained and idle: nothing in flight may linger
    rep = eng.load_report()
    assert rep["fetchq_depth"] == 0 and rep["pending_prefills"] == 0


def test_drain_then_is_idle_accounts_inflight_work(tiny_model):
    """is_idle must stay False while undrained dispatches hold
    emittable tokens — a pool drain that trusts it would otherwise
    drop tail tokens on shutdown."""
    model, params = tiny_model
    eng = LLMEngine(model, params, max_slots=1, page_size=8,
                    n_pages=32, chunk=4, eos_id=-1, overlap=True)
    h = eng.submit([5, 9, 2], max_new_tokens=12)
    for _ in range(3):
        eng.step()
    if eng._fetchq:
        assert not eng.is_idle()
    while eng.step():
        pass
    assert eng.is_idle()
    assert len(h.result()) == 12
