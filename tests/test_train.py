"""JaxTrainer / DataParallelTrainer tests (reference analogues:
python/ray/train/tests/test_data_parallel_trainer.py,
test_backend.py failure handling)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.air import Checkpoint, FailureConfig, RunConfig, ScalingConfig
from ray_tpu.air import session
from ray_tpu.train import DataParallelTrainer, JaxTrainer


def test_single_worker_loop_reports(rt):
    def loop(config):
        for step in range(3):
            session.report({"step": step, "loss": 1.0 / (step + 1)})

    result = DataParallelTrainer(
        loop, scaling_config=ScalingConfig(num_workers=1)).fit()
    assert result.ok
    assert result.metrics["step"] == 2
    assert len(result.metrics_history) == 3


def test_multi_worker_ranks(rt):
    def loop():
        session.report({
            "rank": session.get_world_rank(),
            "world": session.get_world_size()})

    result = DataParallelTrainer(
        loop, scaling_config=ScalingConfig(num_workers=4)).fit()
    assert result.ok
    # Driver keeps rank-0 metrics.
    assert result.metrics == {"rank": 0, "world": 4}


def test_loop_config_passed(rt):
    def loop(config):
        session.report({"lr": config["lr"]})

    result = DataParallelTrainer(
        loop, train_loop_config={"lr": 0.1}).fit()
    assert result.metrics["lr"] == 0.1


def test_checkpoint_flows_to_result(rt):
    def loop(config):
        session.report({"step": 0},
                       checkpoint=Checkpoint.from_dict({"weights": [1, 2]}))

    result = DataParallelTrainer(loop).fit()
    assert result.checkpoint is not None
    assert result.checkpoint["weights"] == [1, 2]


def test_failure_without_retries_surfaces_error(rt):
    def loop(config):
        raise RuntimeError("train crash")

    result = DataParallelTrainer(loop).fit()
    assert not result.ok
    assert "train crash" in str(result.error)


def test_elastic_restart_resumes_from_checkpoint(rt):
    def loop(config):
        ckpt = session.get_checkpoint()
        start = ckpt["step"] + 1 if ckpt else 0
        for step in range(start, 4):
            session.report(
                {"step": step},
                checkpoint=Checkpoint.from_dict({"step": step}))
            if step == 1 and ckpt is None:
                raise RuntimeError("mid-training crash")

    result = DataParallelTrainer(
        loop,
        run_config=RunConfig(
            failure_config=FailureConfig(max_failures=1))).fit()
    assert result.ok, result.error
    assert result.metrics["step"] == 3
    # Restart resumed from step 1's checkpoint, not from scratch:
    steps = [m["step"] for m in result.metrics_history]
    assert steps == [0, 1, 2, 3]


def test_jax_trainer_spmd_gang(rt, cpu_mesh_devices):
    """The end-to-end slice: pjit train step over the gang's mesh."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def loop(config):
        mesh = session.get_mesh()
        assert mesh is not None
        assert mesh.shape["data"] == 8

        @jax.jit
        def step(w, x, y):
            def loss_fn(w):
                pred = x @ w
                return jnp.mean((pred - y) ** 2)
            loss, g = jax.value_and_grad(loss_fn)(w)
            return w - 0.1 * g, loss

        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(64, 16), jnp.float32)
        true_w = jnp.asarray(rng.randn(16, 4), jnp.float32)
        y = x @ true_w
        x = jax.device_put(x, NamedSharding(mesh, P(("data",), None)))
        w = jax.device_put(jnp.zeros((16, 4)),
                           NamedSharding(mesh, P()))
        losses = []
        for _ in range(100):
            w, loss = step(w, x, y)
            losses.append(float(loss))
        session.report({"first_loss": losses[0],
                        "last_loss": losses[-1]})

    result = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1,
                                     mesh={"data": -1})).fit()
    assert result.ok, result.error
    assert result.metrics["last_loss"] < result.metrics["first_loss"] * 0.1
