"""JaxTrainer / DataParallelTrainer tests (reference analogues:
python/ray/train/tests/test_data_parallel_trainer.py,
test_backend.py failure handling)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.air import Checkpoint, FailureConfig, RunConfig, ScalingConfig
from ray_tpu.air import session
from ray_tpu.train import DataParallelTrainer, JaxTrainer


def test_single_worker_loop_reports(rt):
    def loop(config):
        for step in range(3):
            session.report({"step": step, "loss": 1.0 / (step + 1)})

    result = DataParallelTrainer(
        loop, scaling_config=ScalingConfig(num_workers=1)).fit()
    assert result.ok
    assert result.metrics["step"] == 2
    assert len(result.metrics_history) == 3


def test_multi_worker_ranks(rt):
    def loop():
        session.report({
            "rank": session.get_world_rank(),
            "world": session.get_world_size()})

    result = DataParallelTrainer(
        loop, scaling_config=ScalingConfig(num_workers=4)).fit()
    assert result.ok
    # Driver keeps rank-0 metrics.
    assert result.metrics == {"rank": 0, "world": 4}


def test_loop_config_passed(rt):
    def loop(config):
        session.report({"lr": config["lr"]})

    result = DataParallelTrainer(
        loop, train_loop_config={"lr": 0.1}).fit()
    assert result.metrics["lr"] == 0.1


def test_checkpoint_flows_to_result(rt):
    def loop(config):
        session.report({"step": 0},
                       checkpoint=Checkpoint.from_dict({"weights": [1, 2]}))

    result = DataParallelTrainer(loop).fit()
    assert result.checkpoint is not None
    assert result.checkpoint["weights"] == [1, 2]


def test_failure_without_retries_surfaces_error(rt):
    def loop(config):
        raise RuntimeError("train crash")

    result = DataParallelTrainer(loop).fit()
    assert not result.ok
    assert "train crash" in str(result.error)


def test_elastic_restart_resumes_from_checkpoint(rt):
    def loop(config):
        ckpt = session.get_checkpoint()
        start = ckpt["step"] + 1 if ckpt else 0
        for step in range(start, 4):
            session.report(
                {"step": step},
                checkpoint=Checkpoint.from_dict({"step": step}))
            if step == 1 and ckpt is None:
                raise RuntimeError("mid-training crash")

    result = DataParallelTrainer(
        loop,
        run_config=RunConfig(
            failure_config=FailureConfig(max_failures=1))).fit()
    assert result.ok, result.error
    assert result.metrics["step"] == 3
    # Restart resumed from step 1's checkpoint, not from scratch:
    steps = [m["step"] for m in result.metrics_history]
    assert steps == [0, 1, 2, 3]


def test_jax_trainer_spmd_gang(rt, cpu_mesh_devices):
    """The end-to-end slice: pjit train step over the gang's mesh."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def loop(config):
        mesh = session.get_mesh()
        assert mesh is not None
        assert mesh.shape["data"] == 8

        @jax.jit
        def step(w, x, y):
            def loss_fn(w):
                pred = x @ w
                return jnp.mean((pred - y) ** 2)
            loss, g = jax.value_and_grad(loss_fn)(w)
            return w - 0.1 * g, loss

        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(64, 16), jnp.float32)
        true_w = jnp.asarray(rng.randn(16, 4), jnp.float32)
        y = x @ true_w
        x = jax.device_put(x, NamedSharding(mesh, P(("data",), None)))
        w = jax.device_put(jnp.zeros((16, 4)),
                           NamedSharding(mesh, P()))
        losses = []
        for _ in range(100):
            w, loss = step(w, x, y)
            losses.append(float(loss))
        session.report({"first_loss": losses[0],
                        "last_loss": losses[-1]})

    result = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1,
                                     mesh={"data": -1})).fit()
    assert result.ok, result.error
    assert result.metrics["last_loss"] < result.metrics["first_loss"] * 0.1


# ---- widened surface: torch backend, predictors, estimator trainers -------

def test_torch_trainer_ddp_gloo():
    """TorchTrainer on a multiprocess cluster: gloo process group spans
    gang members in distinct worker processes; gradients allreduce."""
    import ray_tpu._private.worker as worker_mod
    from ray_tpu.runtime import Cluster
    if worker_mod.is_initialized():
        worker_mod.shutdown()
    with Cluster(num_workers=2, resources_per_worker={"CPU": 2}):
        from ray_tpu.train import ScalingConfig, TorchTrainer
        from ray_tpu.air import session

        def loop(config):
            import numpy as np
            import torch
            import torch.distributed as dist
            from ray_tpu.train.torch import prepare_model
            torch.manual_seed(0)
            model = prepare_model(torch.nn.Linear(4, 1))
            opt = torch.optim.SGD(model.parameters(), lr=0.1)
            rank = session.get_world_rank()
            rng = np.random.RandomState(rank)
            for _ in range(5):
                x = torch.tensor(rng.randn(8, 4), dtype=torch.float32)
                y = x.sum(dim=1, keepdim=True)
                loss = ((model(x) - y) ** 2).mean()
                opt.zero_grad()
                loss.backward()
                opt.step()
            # All ranks must hold identical (DDP-synced) weights.
            w = list(model.parameters())[0].detach().numpy().ravel()
            session.report({"w0": float(w[0]),
                            "world": dist.get_world_size(),
                            "loss": float(loss)})

        trainer = TorchTrainer(
            loop, scaling_config=ScalingConfig(
                num_workers=2, placement_strategy="STRICT_SPREAD"))
        result = trainer.fit()
        assert result.error is None
        assert result.metrics["world"] == 2


def test_jax_predictor_and_batch_predictor(rt):
    import jax.numpy as jnp
    import numpy as np
    from ray_tpu import data
    from ray_tpu.air.checkpoint import Checkpoint
    from ray_tpu.train import BatchPredictor, JaxPredictor

    params = {"w": jnp.asarray([[2.0]]), "b": jnp.asarray([1.0])}

    def apply_fn(p, x):
        return x @ p["w"] + p["b"]

    ckpt = Checkpoint.from_dict({"params": params})
    pred = JaxPredictor.from_checkpoint(ckpt, apply_fn=apply_fn)
    out = pred.predict(np.asarray([[1.0], [3.0]], np.float32))
    np.testing.assert_allclose(out, [[3.0], [7.0]])

    ds = data.from_items([{"x": [float(i)]} for i in range(8)],
                         parallelism=4)
    bp = BatchPredictor.from_checkpoint(ckpt, JaxPredictor,
                                        apply_fn=apply_fn)
    preds = bp.predict(ds, feature_key="x", compute="actors",
                       num_actors=2)
    vals = sorted(float(r["prediction"][0]) for r in preds.take_all())
    assert vals == [1.0 + 2.0 * i for i in range(8)]


def test_sklearn_trainer_and_predictor(rt):
    import numpy as np
    from sklearn.tree import DecisionTreeRegressor
    from ray_tpu import data
    from ray_tpu.train import SklearnTrainer, SklearnPredictor

    rows = [{"a": float(i), "b": float(i % 3), "y": 2.0 * i}
            for i in range(40)]
    ds = data.from_items(rows)
    trainer = SklearnTrainer(
        estimator=DecisionTreeRegressor(max_depth=5),
        datasets={"train": ds, "valid": ds}, label_column="y")
    result = trainer.fit()
    assert result.metrics["train_score"] > 0.9
    pred = SklearnPredictor.from_checkpoint(result.checkpoint)
    out = pred.predict(np.asarray([[10.0, 1.0]]))
    assert out.shape == (1,)


def test_gbdt_trainers_fit_and_predict(rt):
    """XGBoost/LightGBM-API trainers run on the histogram-GBDT engine
    even without the native packages: regression + classification,
    metrics, and model recovery from the checkpoint."""
    import numpy as np
    from ray_tpu.data import from_items
    from ray_tpu.train import LightGBMTrainer, XGBoostTrainer

    rng = np.random.RandomState(0)
    reg_rows = [{"x0": float(a), "x1": float(b),
                 "y": float(3 * a - 2 * b)}
                for a, b in rng.randn(300, 2)]
    ds = from_items(reg_rows, parallelism=4)
    res = XGBoostTrainer(
        params={"objective": "reg:squarederror", "eta": 0.3,
                "max_depth": 4},
        num_boost_round=80,
        datasets={"train": ds, "valid": ds},
        label_column="y").fit()
    assert res.metrics["train-rmse"] < 0.5
    assert res.metrics["valid-rmse"] < 0.5
    model = XGBoostTrainer.get_model(res.checkpoint)
    pred = model.predict(np.asarray([[1.0, 1.0]]))
    assert abs(float(pred[0]) - 1.0) < 1.0

    cls_rows = [{"x0": float(a), "x1": float(b),
                 "y": int(a + b > 0)}
                for a, b in rng.randn(300, 2)]
    dsc = from_items(cls_rows, parallelism=4)
    res = LightGBMTrainer(
        params={"objective": "binary", "num_leaves": 15,
                "learning_rate": 0.2},
        num_boost_round=60,
        datasets={"train": dsc}, label_column="y").fit()
    assert res.metrics["train-error"] < 0.1


def test_jax_trainer_multihost_gang():
    """VERDICT r1 #2: a JaxTrainer gang spanning SEPARATE OS processes
    bootstraps jax.distributed (coordinator from rank 0) and builds ONE
    mesh over every member's devices — the multi-host training model
    (SURVEY §7 step 6), exercised with 2 virtual CPU hosts x 8 devices."""
    import ray_tpu._private.worker as worker_mod
    from ray_tpu.runtime import Cluster
    if worker_mod.is_initialized():
        worker_mod.shutdown()
    with Cluster(num_workers=2, resources_per_worker={"CPU": 2}):
        from ray_tpu.train import JaxTrainer, ScalingConfig
        from ray_tpu.air import session

        def loop(config):
            import jax
            import jax.numpy as jnp
            import numpy as np
            from jax.sharding import NamedSharding, PartitionSpec as P
            from ray_tpu.train.spmd import put_batch

            mesh = session.get_mesh()
            rank = session.get_world_rank()
            # The mesh must span BOTH hosts' devices.
            n_global = int(np.prod(list(mesh.shape.values())))

            @jax.jit
            def step(w, batch):
                x, y = batch["x"], batch["y"]

                def loss_fn(w):
                    return jnp.mean((x @ w - y) ** 2)
                loss, g = jax.value_and_grad(loss_fn)(w)
                return w - 0.1 * g, loss

            rng = np.random.RandomState(0)
            true_w = np.asarray(rng.randn(16, 4), np.float32)
            local_rng = np.random.RandomState(100 + rank)
            w = jax.device_put(jnp.zeros((16, 4)),
                               NamedSharding(mesh, P()))
            losses = []
            for _ in range(60):
                # Per-host local batch: each host contributes its own
                # shard of the global batch (no cross-host copies).
                xl = np.asarray(local_rng.randn(32, 16), np.float32)
                yl = xl @ true_w
                batch = put_batch({"x": xl, "y": yl}, mesh)
                w, loss = step(w, batch)
                losses.append(float(loss))
            session.report({
                "first_loss": losses[0], "last_loss": losses[-1],
                "n_global_devices": n_global,
                "process_count": jax.process_count(),
                "process_index": jax.process_index(),
            })

        result = JaxTrainer(
            loop,
            scaling_config=ScalingConfig(
                num_workers=2, mesh={"data": -1},
                jax_distributed=True,
                placement_strategy="STRICT_SPREAD")).fit()
        assert result.ok, result.error
        m = result.metrics
        assert m["process_count"] == 2
        assert m["n_global_devices"] == 16
        assert m["last_loss"] < m["first_loss"] * 0.1


def test_gbdt_fit_never_materializes_in_driver():
    """VERDICT r3 #9: GBDT fit streams dataset blocks into the FIT
    WORKER; the driver holds only refs (ref: train/gbdt_trainer.py
    distributed data loading). Blocks are produced by remote tasks and
    consumed by the remote fit — the driver process never assembles
    the rows."""
    import os

    import numpy as np

    import ray_tpu
    import ray_tpu._private.worker as worker_mod
    from ray_tpu.runtime import Cluster
    if worker_mod.is_initialized():
        worker_mod.shutdown()
    with Cluster(num_workers=2, resources_per_worker={"CPU": 2}):
        from ray_tpu.data import Dataset
        from ray_tpu.train import XGBoostTrainer

        @ray_tpu.remote
        def make_block(seed):
            rng = np.random.RandomState(seed)
            rows = []
            for _ in range(200):
                x0, x1 = rng.randn(), rng.randn()
                rows.append({"x0": x0, "x1": x1,
                             "y": 3.0 * x0 - 2.0 * x1})
            return rows

        # blocks live in worker-side object stores, never the driver
        ds = Dataset([make_block.remote(s) for s in range(5)])
        res = XGBoostTrainer(
            params={"objective": "reg:squarederror", "eta": 0.3},
            num_boost_round=60,
            datasets={"train": ds}, label_column="y").fit()
        assert res.metrics["train-rmse"] < 0.5
        # the fit ran in a worker process, not the driver
        assert res.metrics["fit_pid"] != os.getpid()
        model = XGBoostTrainer.get_model(res.checkpoint)
        pred = model.predict(np.asarray([[1.0, 1.0]]))
        assert abs(pred[0] - 1.0) < 1.0


def test_jax_trainer_multihost_dcn_mesh():
    """VERDICT r3 #8: a {dcn, data} mesh whose dcn axis crosses the
    OS-process boundary of a 2-process gang — the multi-slice model
    (DCN between slices, ICI within). Asserts the dcn rows map 1:1 to
    processes and that a reduction over 'dcn' crosses the boundary."""
    import ray_tpu._private.worker as worker_mod
    from ray_tpu.runtime import Cluster
    if worker_mod.is_initialized():
        worker_mod.shutdown()
    with Cluster(num_workers=2, resources_per_worker={"CPU": 2}):
        from ray_tpu.train import JaxTrainer, ScalingConfig
        from ray_tpu.air import session

        def loop(config):
            import jax
            import jax.numpy as jnp
            import numpy as np
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P
            from ray_tpu.mesh.device_mesh import AXIS_ORDER
            from ray_tpu.train.spmd import put_batch

            mesh = session.get_mesh()
            rank = session.get_world_rank()
            dcn_ix = AXIS_ORDER.index("dcn")
            # each dcn row must live entirely on ONE process
            rows_procs = []
            dev = np.moveaxis(mesh.devices, dcn_ix, 0)
            for i in range(mesh.shape["dcn"]):
                rows_procs.append(sorted(
                    {d.process_index for d in dev[i].flat}))
            # cross-dcn reduction: one scalar per process, summed over
            # the dcn axis — the collective rides the process boundary
            marker = np.full((1,), float(rank + 1), np.float32)
            g = jax.make_array_from_process_local_data(
                NamedSharding(mesh, P("dcn")), marker)
            dcn_sum = float(jax.jit(jnp.sum)(g))

            # data-parallel training over BOTH axes: gradient sync is
            # an allreduce spanning dcn (inter-process) and data
            @jax.jit
            def step(w, batch):
                x, y = batch["x"], batch["y"]

                def loss_fn(w):
                    return jnp.mean((x @ w - y) ** 2)
                loss, grad = jax.value_and_grad(loss_fn)(w)
                return w - 0.1 * grad, loss

            rng = np.random.RandomState(0)
            true_w = np.asarray(rng.randn(16, 4), np.float32)
            local = np.random.RandomState(100 + rank)
            w = jax.device_put(jnp.zeros((16, 4)),
                               NamedSharding(mesh, P()))
            losses = []
            for _ in range(50):
                xl = np.asarray(local.randn(32, 16), np.float32)
                batch = put_batch({"x": xl, "y": xl @ true_w}, mesh)
                w, loss = step(w, batch)
                losses.append(float(loss))
            session.report({
                "dcn_size": mesh.shape["dcn"],
                "data_size": mesh.shape["data"],
                "rows_procs": rows_procs,
                "dcn_sum": dcn_sum,
                "process_count": jax.process_count(),
                "first_loss": losses[0], "last_loss": losses[-1],
            })

        result = JaxTrainer(
            loop,
            scaling_config=ScalingConfig(
                num_workers=2, mesh={"dcn": 2, "data": -1},
                jax_distributed=True,
                placement_strategy="STRICT_SPREAD")).fit()
        assert result.ok, result.error
        m = result.metrics
        assert m["process_count"] == 2
        assert m["dcn_size"] == 2 and m["data_size"] == 8
        # dcn row i == process i: the axis IS the process boundary
        assert m["rows_procs"] == [[0], [1]]
        assert m["dcn_sum"] == pytest.approx(3.0)   # 1 + 2 across dcn
        assert m["last_loss"] < m["first_loss"] * 0.1


def test_jax_trainer_gang_elastic_restart():
    """Gang elastic restart re-bootstraps jax.distributed cleanly: each
    attempt gets FRESH dedicated worker processes (a process can join
    only one coordinator), so attempt 2 succeeds after attempt 1's gang
    fails mid-run."""
    import os
    import tempfile
    import ray_tpu._private.worker as worker_mod
    from ray_tpu.runtime import Cluster
    if worker_mod.is_initialized():
        worker_mod.shutdown()
    marker = os.path.join(tempfile.mkdtemp(), "attempt1_failed")
    with Cluster(num_workers=2, resources_per_worker={"CPU": 2}):
        from ray_tpu.train import (FailureConfig, JaxTrainer, RunConfig,
                                   ScalingConfig)
        from ray_tpu.air import session

        def loop(config):
            import jax
            import os
            # Join the mesh first — proves bootstrap worked this attempt.
            n = jax.device_count()
            if session.get_world_rank() == 1 and \
                    not os.path.exists(config["marker"]):
                open(config["marker"], "w").close()
                raise RuntimeError("injected gang failure")
            session.report({"devices": n,
                            "procs": jax.process_count()})

        result = JaxTrainer(
            loop, train_loop_config={"marker": marker},
            scaling_config=ScalingConfig(
                num_workers=2, mesh={"data": -1}, jax_distributed=True),
            run_config=RunConfig(
                failure_config=FailureConfig(max_failures=2))).fit()
        assert result.ok, result.error
        assert result.metrics["procs"] == 2
        assert result.metrics["devices"] == 16
        assert os.path.exists(marker)   # attempt 1 really failed


def test_torch_helpers_and_checkpoint_roundtrip():
    """TorchConfig/prepare_data_loader/checkpoint helpers (reference:
    train/torch/train_loop_utils.py + torch_checkpoint.py)."""
    import torch
    from torch.utils.data import DataLoader, TensorDataset
    from ray_tpu.train.torch import (TorchConfig, checkpoint_from_model,
                                     load_model_from_checkpoint,
                                     prepare_data_loader, prepare_model)
    tc = TorchConfig()
    assert tc.backend == "gloo"
    model = torch.nn.Linear(4, 2)
    # outside a gang both prepares are no-ops
    assert prepare_model(model) is model
    dl = DataLoader(TensorDataset(torch.zeros(8, 4)), batch_size=4)
    assert prepare_data_loader(dl) is dl
    # checkpoint round trip restores exact weights
    with torch.no_grad():
        model.weight.fill_(1.5)
    ckpt = checkpoint_from_model(model, epoch=3)
    fresh = torch.nn.Linear(4, 2)
    load_model_from_checkpoint(ckpt, fresh)
    assert torch.equal(fresh.weight, model.weight)
    assert ckpt.to_dict()["epoch"] == 3


def test_huggingface_trainer_distributed():
    """HuggingFaceTrainer: each gang member builds a transformers
    Trainer; accelerate adopts the gloo group, gradients sync, rank 0
    streams HF logs as reports and the final checkpoint carries the
    model state (reference: train/huggingface/huggingface_trainer.py)."""
    import ray_tpu._private.worker as worker_mod
    from ray_tpu.runtime import Cluster
    if worker_mod.is_initialized():
        worker_mod.shutdown()

    def init_trainer(config):
        import numpy as np
        import torch
        from transformers import (BertConfig,
                                  BertForSequenceClassification,
                                  Trainer, TrainingArguments)
        cfg = BertConfig(vocab_size=64, hidden_size=32,
                         num_hidden_layers=2, num_attention_heads=2,
                         intermediate_size=64,
                         max_position_embeddings=32, num_labels=2)
        torch.manual_seed(0)
        model = BertForSequenceClassification(cfg)

        class DS(torch.utils.data.Dataset):
            def __len__(self):
                return 32

            def __getitem__(self, i):
                rng = np.random.RandomState(i)
                ids = rng.randint(0, 64, 8)
                return {"input_ids": torch.tensor(ids),
                        "attention_mask": torch.ones(
                            8, dtype=torch.long),
                        "labels": torch.tensor(int(ids[0] % 2))}

        args = TrainingArguments(
            output_dir=f"/tmp/hf_gang_{config.get('run', 0)}",
            max_steps=4, per_device_train_batch_size=4,
            logging_steps=2, report_to=[], use_cpu=True,
            disable_tqdm=True, save_strategy="no")
        return Trainer(model=model, args=args, train_dataset=DS())

    with Cluster(num_workers=2, resources_per_worker={"CPU": 2}):
        from ray_tpu.train import HuggingFaceTrainer, ScalingConfig
        result = HuggingFaceTrainer(
            init_trainer,
            scaling_config=ScalingConfig(
                num_workers=2,
                placement_strategy="STRICT_SPREAD")).fit()
        assert result.error is None, result.error
        assert result.metrics["global_step"] == 4
        assert result.metrics["train_loss"] > 0
        # accelerate actually adopted the 2-rank gloo group (DDP on,
        # per-rank sharded data) rather than running 2 solo trainers
        assert result.metrics["world_size"] == 2
        assert result.checkpoint is not None
        state = result.checkpoint.to_dict()["model_state"]
        assert any("bert" in k for k in state)
        # intermediate HF logs streamed through session.report
        # (rank 0 only -> one stream)
        hist = [r for r in result.metrics_history if "step" in r]
        assert hist, result.metrics_history


def test_trainer_honors_run_config_stop(rt):
    """RunConfig(stop=...) applies to plain trainer fits, not just
    Tuner experiments."""
    from ray_tpu.air import RunConfig, session
    from ray_tpu.train import DataParallelTrainer, ScalingConfig

    def loop(config):
        for it in range(200):
            session.report({"score": it})

    result = DataParallelTrainer(
        loop, scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(stop={"score": 5})).fit()
    assert result.error is None
    assert result.metrics["score"] >= 5
    assert len(result.metrics_history) < 100   # cut well short of 200


def test_datasets_sharded_to_workers(rt):
    """datasets={...} + session.get_dataset_shard: equal-row shards,
    disjoint and complete across the gang (reference:
    DataParallelTrainer datasets kwarg)."""
    from ray_tpu import data
    from ray_tpu.train import DataParallelTrainer, ScalingConfig

    ds = data.from_items(list(range(100)), parallelism=8)
    val = data.from_items([{"x": i} for i in range(10)],
                          parallelism=2)

    def loop(config):
        shard = session.get_dataset_shard("train")
        vshard = session.get_dataset_shard("val")
        rows = shard.take_all()
        session.report({"n": len(rows), "sum": sum(rows),
                        "vn": vshard.count(),
                        "rank": session.get_world_rank()})

    result = DataParallelTrainer(
        loop, scaling_config=ScalingConfig(num_workers=4),
        datasets={"train": ds, "val": val}).fit()
    assert result.ok, result.error
    # rank 0's shard: 25 rows; the driver only sees rank 0 metrics,
    # so run again collecting from all ranks via history? Instead:
    assert result.metrics["n"] == 25
    assert result.metrics["vn"] in (2, 3)

    # completeness/disjointness across ranks: gather via an actor
    import ray_tpu as rtpu

    @rtpu.remote
    class Collect:
        def __init__(self):
            self.rows = []

        def add(self, rows):
            self.rows.extend(rows)

        def all(self):
            return self.rows

    c = Collect.remote()

    def loop2(config):
        shard = session.get_dataset_shard("train")
        rtpu.get(c.add.remote(shard.take_all()))
        session.report({"ok": 1})

    result = DataParallelTrainer(
        loop2, scaling_config=ScalingConfig(num_workers=4),
        datasets={"train": ds}).fit()
    assert result.ok, result.error
    got = sorted(rtpu.get(c.all.remote()))
    assert got == list(range(100))


def test_get_dataset_shard_unknown_name(rt):
    from ray_tpu.train import DataParallelTrainer, ScalingConfig

    def loop(config):
        session.get_dataset_shard("nope")

    result = DataParallelTrainer(
        loop, scaling_config=ScalingConfig(num_workers=1)).fit()
    assert not result.ok
    assert "no dataset" in str(result.error)
