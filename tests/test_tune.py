"""Tune tests (reference analogues: tune/tests/test_tune_*.py,
test_trial_scheduler.py)."""
import pytest

import ray_tpu
from ray_tpu.air import session, Checkpoint
from ray_tpu.tune import (AsyncHyperBandScheduler, MedianStoppingRule,
                          PopulationBasedTraining, TuneConfig, Tuner,
                          choice, grid_search, uniform)


def _trainable_quadratic(config):
    # Minimum at x=3.
    loss = (config["x"] - 3.0) ** 2
    for step in range(3):
        session.report({"loss": loss + 0.1 / (step + 1)})


def test_grid_search_runs_all(rt):
    tuner = Tuner(
        _trainable_quadratic,
        param_space={"x": grid_search([0.0, 1.0, 3.0, 5.0])},
        tune_config=TuneConfig(metric="loss", mode="min"))
    grid = tuner.fit()
    assert len(grid) == 4
    best = grid.get_best_result("loss", "min")
    assert best.metrics is not None
    # x=3 wins.
    assert abs(best.metrics["loss"] - 0.1 / 3) < 1e-6


def test_num_samples_with_domains(rt):
    tuner = Tuner(
        _trainable_quadratic,
        param_space={"x": uniform(-1, 1), "tag": choice(["a", "b"])},
        tune_config=TuneConfig(num_samples=5))
    grid = tuner.fit()
    assert len(grid) == 5
    assert not grid.errors


def test_trial_error_captured(rt):
    def bad(config):
        raise RuntimeError("boom-" + str(config["x"]))

    grid = Tuner(bad, param_space={"x": grid_search([1, 2])}).fit()
    assert len(grid.errors) == 2


def test_trial_retry_on_failure(rt):
    def flaky(config):
        ckpt = session.get_checkpoint()
        if ckpt is None:
            session.report(
                {"loss": 1.0},
                checkpoint=Checkpoint.from_dict({"seen": True}))
            raise RuntimeError("first attempt dies")
        session.report({"loss": 0.5})

    grid = Tuner(
        flaky, param_space={"x": grid_search([1])},
        tune_config=TuneConfig(max_failures=1)).fit()
    assert not grid.errors
    assert grid[0].metrics["loss"] == 0.5


def test_asha_stops_bad_trials_early(rt):
    reports_made = {}

    def trainable(config):
        for step in range(1, 17):
            # Bad configs have high loss; good configs low.
            session.report({"loss": config["badness"] + 1.0 / step,
                            "training_iteration": step})

    tuner = Tuner(
        trainable,
        param_space={"badness": grid_search(
            [0.0, 0.0, 5.0, 5.0, 5.0, 5.0, 5.0, 5.0])},
        tune_config=TuneConfig(
            metric="loss", mode="min", max_concurrent_trials=8,
            scheduler=AsyncHyperBandScheduler(
                metric="loss", mode="min", grace_period=2,
                reduction_factor=2, max_t=16)))
    grid = tuner.fit()
    stopped = [t for t in grid.trials if t.state == "STOPPED"]
    finished = [t for t in grid.trials if t.state == "TERMINATED"]
    assert stopped, "ASHA should stop some bad trials early"
    assert finished, "good trials should run to completion"
    # No stopped trial ran all 16 iterations.
    assert all(len(t.results) < 16 for t in stopped)


def test_median_stopping(rt):
    def trainable(config):
        for step in range(1, 9):
            session.report({"loss": config["level"],
                            "training_iteration": step})

    grid = Tuner(
        trainable,
        param_space={"level": grid_search([1.0, 1.0, 1.0, 50.0])},
        tune_config=TuneConfig(
            max_concurrent_trials=4,
            scheduler=MedianStoppingRule(
                metric="loss", mode="min", grace_period=2,
                min_samples_required=2))).fit()
    worst = [t for t in grid.trials if t.config["level"] == 50.0][0]
    assert worst.state == "STOPPED"


def test_pbt_exploits_checkpoint(rt):
    def trainable(config):
        ckpt = session.get_checkpoint()
        score = ckpt["score"] if ckpt else 0.0
        for step in range(1, 21):
            score += config["lr"]
            session.report(
                {"score": score, "training_iteration": step},
                checkpoint=Checkpoint.from_dict({"score": score}))

    scheduler = PopulationBasedTraining(
        metric="score", mode="max", perturbation_interval=5,
        hyperparam_mutations={"lr": [0.1, 1.0]}, seed=0)
    grid = Tuner(
        trainable,
        param_space={"lr": grid_search([0.1, 0.1, 1.0, 1.0])},
        tune_config=TuneConfig(metric="score", mode="max",
                               max_concurrent_trials=4,
                               scheduler=scheduler)).fit()
    best = grid.get_best_result("score", "max")
    # With exploitation, the best score should reflect mostly lr=1.0
    # progress: > 20 * 0.5.
    assert best.metrics["score"] > 10.0


# ---- widened surface: TPE, HyperBand, experiment resume -------------------

def test_tpe_searcher_improves_over_random(rt):
    """TPE should concentrate samples near the optimum of a smooth
    1-d objective after startup trials."""
    from ray_tpu.air import session
    from ray_tpu.tune import TPESearcher, TuneConfig, Tuner, uniform

    space = {"x": uniform(-10.0, 10.0)}

    def objective(config):
        session.report({"loss": (config["x"] - 3.0) ** 2})

    searcher = TPESearcher(space, metric="loss", mode="min",
                           num_samples=20, n_startup=6, seed=1)
    tuner = Tuner(objective,
                  tune_config=TuneConfig(metric="loss", mode="min",
                                         search_alg=searcher,
                                         max_concurrent_trials=2))
    grid = tuner.fit()
    assert len(grid) == 20
    best = grid.get_best_result("loss", "min")
    assert best.metrics["loss"] < 2.0
    # Model-based phase trials must on average beat the random phase.
    startup = [t.last_result["loss"] for t in grid.trials[:6]]
    guided = [t.last_result["loss"] for t in grid.trials[12:]]
    assert sum(guided) / len(guided) < sum(startup) / len(startup)


def test_hyperband_stops_losers(rt):
    from ray_tpu.air import session
    from ray_tpu.tune import (HyperBandScheduler, TuneConfig, Tuner,
                              grid_search)

    def trainable(config):
        for i in range(9):
            session.report({"loss": config["q"] + i * 0.01})

    tuner = Tuner(
        trainable,
        param_space={"q": grid_search([1.0, 2.0, 3.0, 4.0])},
        tune_config=TuneConfig(
            metric="loss", mode="min", num_samples=1,
            max_concurrent_trials=4,
            scheduler=HyperBandScheduler(metric="loss", mode="min",
                                         max_t=9,
                                         reduction_factor=2)))
    grid = tuner.fit()
    from ray_tpu.tune.trial import STOPPED
    stopped = [t for t in grid.trials if t.state == STOPPED]
    assert stopped, "HyperBand should stop at least one loser"
    best_trial = min(
        (t for t in grid.trials if t.last_result),
        key=lambda t: min(t.metric_history("loss")))
    assert best_trial.config["q"] == 1.0


def test_experiment_state_resume(rt, tmp_path):
    from ray_tpu.air import session
    from ray_tpu.air.config import RunConfig
    from ray_tpu.tune import TuneConfig, Tuner, grid_search
    from ray_tpu.tune.trial import TERMINATED

    calls_file = tmp_path / "calls.txt"

    def trainable(config):
        with open(calls_file, "a") as f:
            f.write(f"{config['v']}\n")
        if config["v"] == 99 and \
                len(open(calls_file).readlines()) < 4:
            raise RuntimeError("boom")   # fails on the first pass
        session.report({"loss": float(config["v"])})

    run_cfg = RunConfig(name="exp1", storage_path=str(tmp_path))
    tuner = Tuner(trainable,
                  param_space={"v": grid_search([1, 2, 99])},
                  tune_config=TuneConfig(metric="loss", mode="min",
                                         max_concurrent_trials=1),
                  run_config=run_cfg)
    grid = tuner.fit()
    assert any(t.error is not None for t in grid.trials)
    state_dir = tmp_path / "exp1"
    assert (state_dir / "experiment_state.pkl").exists()

    # Resume: finished trials keep results; the failed one re-runs.
    tuner2 = Tuner.restore(str(state_dir), trainable,
                           tune_config=TuneConfig(
                               metric="loss", mode="min",
                               max_concurrent_trials=1),
                           run_config=run_cfg)
    grid2 = tuner2.fit()
    assert len(grid2) == 3
    done = [t for t in grid2.trials if t.state == TERMINATED]
    assert len(done) == 3   # all complete after resume
    vals = sorted(t.last_result["loss"] for t in grid2.trials)
    assert vals == [1.0, 2.0, 99.0]


def test_functional_tune_run(rt):
    """tune.run functional alias (reference call shape)."""
    from ray_tpu import tune
    from ray_tpu.air import session

    def trainable(config):
        session.report({"loss": (config["x"] - 1) ** 2})

    grid = tune.run(trainable,
                    config={"x": tune.uniform(-2, 2)},
                    num_samples=8, metric="loss", mode="min",
                    search_alg=tune.BasicVariantGenerator(
                        {"x": tune.uniform(-2, 2)}, num_samples=8,
                        seed=7),
                    max_concurrent_trials=2)
    assert len(grid) == 8
    assert grid.get_best_result().metrics["loss"] < 2.0


def test_stopper_dict_and_max_iteration(rt):
    """RunConfig(stop=...): the dict threshold form and
    MaximumIterationStopper both cut trials short (reference:
    tune/stopper/)."""
    from ray_tpu.air import RunConfig, session
    from ray_tpu.tune import (MaximumIterationStopper, TuneConfig,
                              Tuner)

    def loop(config):
        for it in range(50):
            session.report({"score": it})

    grid = Tuner(loop, param_space={"x": 1},
                 tune_config=TuneConfig(metric="score", mode="max"),
                 run_config=RunConfig(stop={"score": 5})).fit()
    t = grid.trials[0]
    assert t.last_result["score"] == 5          # stopped at threshold
    assert len(t.results) <= 7

    grid = Tuner(loop, param_space={"x": 1},
                 tune_config=TuneConfig(metric="score", mode="max"),
                 run_config=RunConfig(
                     stop=MaximumIterationStopper(3))).fit()
    assert grid.trials[0].last_result["training_iteration"] == 3


def test_trial_plateau_and_experiment_stoppers(rt):
    from ray_tpu.air import RunConfig, session
    from ray_tpu.tune import (CombinedStopper,
                              ExperimentPlateauStopper,
                              TrialPlateauStopper, TuneConfig, Tuner)

    def plateau(config):
        for it in range(60):
            session.report({"loss": 1.0 if it > 4 else 10.0 - it})

    grid = Tuner(plateau, param_space={"x": 1},
                 tune_config=TuneConfig(metric="loss", mode="min"),
                 run_config=RunConfig(stop=TrialPlateauStopper(
                     "loss", std=1e-6, num_results=3,
                     grace_period=3))).fit()
    assert len(grid.trials[0].results) < 20     # plateau detected

    stopper = CombinedStopper(
        ExperimentPlateauStopper("loss", mode="min", patience=4))
    grid = Tuner(plateau, param_space={"x": 1},
                 tune_config=TuneConfig(metric="loss", mode="min"),
                 run_config=RunConfig(stop=stopper)).fit()
    assert len(grid.trials[0].results) < 30     # experiment ended


def test_stopper_callable_form(rt):
    from ray_tpu.air import RunConfig, session
    from ray_tpu.tune import TuneConfig, Tuner

    def loop(config):
        for it in range(50):
            session.report({"v": it})

    grid = Tuner(loop, param_space={"x": 1},
                 tune_config=TuneConfig(metric="v", mode="max"),
                 run_config=RunConfig(
                     stop=lambda tid, r: r["v"] >= 2)).fit()
    assert grid.trials[0].last_result["v"] == 2


def test_trial_plateau_metric_threshold(rt):
    """mode+metric_threshold pairing (reference semantics,
    tune/stopper/trial_plateau.py): the plateau stop applies only to
    trials that CONVERGED PAST the threshold; a plateaued-but-bad
    trial keeps running."""
    from ray_tpu.air import RunConfig, session
    from ray_tpu.tune import TrialPlateauStopper, TuneConfig, Tuner

    def flat(val):
        def loop(config):
            for it in range(20):
                session.report({"loss": val})
        return loop

    def run(val):
        return Tuner(flat(val), param_space={"x": 1},
                     tune_config=TuneConfig(metric="loss",
                                            mode="min"),
                     run_config=RunConfig(stop=TrialPlateauStopper(
                         "loss", std=1e-6, num_results=3,
                         grace_period=3, mode="min",
                         metric_threshold=0.5))).fit()

    # converged past the threshold and flat -> stopped early
    assert len(run(0.01).trials[0].results) < 20
    # flat but BAD (never reached 0.5) -> keeps its budget
    assert len(run(2.0).trials[0].results) == 20
    import pytest as _pytest
    with _pytest.raises(ValueError, match="metric_threshold"):
        TrialPlateauStopper("loss", metric_threshold=0.5)
