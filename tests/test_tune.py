"""Tune tests (reference analogues: tune/tests/test_tune_*.py,
test_trial_scheduler.py)."""
import pytest

import ray_tpu
from ray_tpu.air import session, Checkpoint
from ray_tpu.tune import (AsyncHyperBandScheduler, MedianStoppingRule,
                          PopulationBasedTraining, TuneConfig, Tuner,
                          choice, grid_search, uniform)


def _trainable_quadratic(config):
    # Minimum at x=3.
    loss = (config["x"] - 3.0) ** 2
    for step in range(3):
        session.report({"loss": loss + 0.1 / (step + 1)})


def test_grid_search_runs_all(rt):
    tuner = Tuner(
        _trainable_quadratic,
        param_space={"x": grid_search([0.0, 1.0, 3.0, 5.0])},
        tune_config=TuneConfig(metric="loss", mode="min"))
    grid = tuner.fit()
    assert len(grid) == 4
    best = grid.get_best_result("loss", "min")
    assert best.metrics is not None
    # x=3 wins.
    assert abs(best.metrics["loss"] - 0.1 / 3) < 1e-6


def test_num_samples_with_domains(rt):
    tuner = Tuner(
        _trainable_quadratic,
        param_space={"x": uniform(-1, 1), "tag": choice(["a", "b"])},
        tune_config=TuneConfig(num_samples=5))
    grid = tuner.fit()
    assert len(grid) == 5
    assert not grid.errors


def test_trial_error_captured(rt):
    def bad(config):
        raise RuntimeError("boom-" + str(config["x"]))

    grid = Tuner(bad, param_space={"x": grid_search([1, 2])}).fit()
    assert len(grid.errors) == 2


def test_trial_retry_on_failure(rt):
    def flaky(config):
        ckpt = session.get_checkpoint()
        if ckpt is None:
            session.report(
                {"loss": 1.0},
                checkpoint=Checkpoint.from_dict({"seen": True}))
            raise RuntimeError("first attempt dies")
        session.report({"loss": 0.5})

    grid = Tuner(
        flaky, param_space={"x": grid_search([1])},
        tune_config=TuneConfig(max_failures=1)).fit()
    assert not grid.errors
    assert grid[0].metrics["loss"] == 0.5


def test_asha_stops_bad_trials_early(rt):
    reports_made = {}

    def trainable(config):
        for step in range(1, 17):
            # Bad configs have high loss; good configs low.
            session.report({"loss": config["badness"] + 1.0 / step,
                            "training_iteration": step})

    tuner = Tuner(
        trainable,
        param_space={"badness": grid_search(
            [0.0, 0.0, 5.0, 5.0, 5.0, 5.0, 5.0, 5.0])},
        tune_config=TuneConfig(
            metric="loss", mode="min", max_concurrent_trials=8,
            scheduler=AsyncHyperBandScheduler(
                metric="loss", mode="min", grace_period=2,
                reduction_factor=2, max_t=16)))
    grid = tuner.fit()
    stopped = [t for t in grid.trials if t.state == "STOPPED"]
    finished = [t for t in grid.trials if t.state == "TERMINATED"]
    assert stopped, "ASHA should stop some bad trials early"
    assert finished, "good trials should run to completion"
    # No stopped trial ran all 16 iterations.
    assert all(len(t.results) < 16 for t in stopped)


def test_median_stopping(rt):
    def trainable(config):
        for step in range(1, 9):
            session.report({"loss": config["level"],
                            "training_iteration": step})

    grid = Tuner(
        trainable,
        param_space={"level": grid_search([1.0, 1.0, 1.0, 50.0])},
        tune_config=TuneConfig(
            max_concurrent_trials=4,
            scheduler=MedianStoppingRule(
                metric="loss", mode="min", grace_period=2,
                min_samples_required=2))).fit()
    worst = [t for t in grid.trials if t.config["level"] == 50.0][0]
    assert worst.state == "STOPPED"


def test_pbt_exploits_checkpoint(rt):
    def trainable(config):
        ckpt = session.get_checkpoint()
        score = ckpt["score"] if ckpt else 0.0
        for step in range(1, 21):
            score += config["lr"]
            session.report(
                {"score": score, "training_iteration": step},
                checkpoint=Checkpoint.from_dict({"score": score}))

    scheduler = PopulationBasedTraining(
        metric="score", mode="max", perturbation_interval=5,
        hyperparam_mutations={"lr": [0.1, 1.0]}, seed=0)
    grid = Tuner(
        trainable,
        param_space={"lr": grid_search([0.1, 0.1, 1.0, 1.0])},
        tune_config=TuneConfig(metric="score", mode="max",
                               max_concurrent_trials=4,
                               scheduler=scheduler)).fit()
    best = grid.get_best_result("score", "max")
    # With exploitation, the best score should reflect mostly lr=1.0
    # progress: > 20 * 0.5.
    assert best.metrics["score"] > 10.0
