"""Tensor-parallel sharded serving engine (serve/sharding.py).

Parity discipline: the SAME model + params served by a 1-chip engine
and a 4-way tensor-parallel engine (forced multi-device CPU host
mesh) must emit token-IDENTICAL greedy outputs on every serving path
— plain decode, prefix-cache hit resume, and spec-decode
accept/rollback. fp32 tiny configs on purpose: the TP psum splits
each layer's reduction, and under bf16 output rounding a borderline
argmax tie could flip a token without anything being wrong; at fp32
ties are vanishingly unlikely, so any mismatch is a real bug.

Plus the placement/validation units: head-sharded KV pool layout,
strict match_partition_rules unmatched-path reporting, divisibility
errors, replica device groups, and paged_append's typed shape errors.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ray_tpu.models.llama import (Llama, llama_tiny,
                                  llama_sharding_rules,
                                  llama_tp_validate)
from ray_tpu.serve.engine import LLMEngine
from ray_tpu.serve.sharding import (EngineSharding,
                                    ShardingConfigError,
                                    replica_device_groups)


@pytest.fixture(scope="module")
def tiny():
    # n_kv_heads=4 so heads divide tp=4 (llama_tiny defaults to 2)
    cfg = llama_tiny(n_kv_heads=4, dtype=jnp.float32)
    model = Llama(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    return cfg, model, params


@pytest.fixture(scope="module")
def tp4(tiny, cpu_mesh_devices):
    cfg, _, _ = tiny
    return EngineSharding.build(cfg, tp=4,
                                devices=cpu_mesh_devices[:4])


def _engine(tiny, sharding, **kw):
    _, model, params = tiny
    opts = dict(max_slots=4, page_size=8, n_pages=96, chunk=4,
                prefill_chunk=16, temperature=0.0, seed=0)
    opts.update(kw)
    eng = LLMEngine(model, params, sharding=sharding, **opts)
    eng.start()
    return eng


# ------------------------------------------------------ parity paths

def test_plain_decode_parity_tp1_vs_tp4(tiny, tp4):
    cfg = tiny[0]
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, cfg.vocab_size - 1, size=12).tolist()
               for _ in range(6)]

    def run(sh):
        eng = _engine(tiny, sh)
        hs = [eng.submit(p, max_new_tokens=16) for p in prompts]
        outs = [h.result() for h in hs]
        eng.shutdown()
        return outs

    assert run(None) == run(tp4)


def test_prefix_cache_hit_resume_parity(tiny, tp4):
    """Request 1 warms the radix cache; later requests resume
    mid-prompt off shared pages. The hit path (boundary-page COW copy
    + mid-offset prefill) must be token-identical across tp widths —
    and must actually HIT on both, or the test proves nothing."""
    cfg = tiny[0]
    rng = np.random.RandomState(1)
    shared = rng.randint(1, cfg.vocab_size - 1, size=32).tolist()
    tails = [rng.randint(1, cfg.vocab_size - 1, size=6).tolist()
             for _ in range(3)]

    def run(sh):
        eng = _engine(tiny, sh, prefix_cache=True)
        outs = [eng.submit(shared + t, max_new_tokens=12).result()
                for t in tails]  # sequential: later ones hit
        hits = eng.stats.get("cache_hit_admissions", 0)
        eng.shutdown()
        return outs, hits

    base, base_hits = run(None)
    tp, tp_hits = run(tp4)
    assert base == tp
    assert base_hits >= 1 and tp_hits == base_hits


class _Scripted:
    """Proposer seam (same as tests/test_spec_decode.py): proposes a
    fixed continuation script keyed on tokens generated so far. Host-
    side and identical across tp widths, so it isolates the DEVICE
    side of speculation — the sharded verify + KV-frontier
    rollback."""

    def __init__(self, prompt_len, script):
        self.prompt_len = prompt_len
        self.script = script
        self._done = 0

    def sync(self, context):
        self._done = len(context) - self.prompt_len

    def propose(self, k):
        return self.script[self._done:self._done + k]


def test_spec_decode_accept_parity(tiny, tp4):
    """Repetitive prompt: prompt-lookup drafts get accepted. The
    verify argmax runs through the sharded psum path; accept counters
    must agree exactly across tp widths."""
    rep = ([5, 6, 7, 8] * 8)[:24]

    def run(sh):
        eng = _engine(tiny, sh, spec_len=4)
        outs = [eng.submit(rep, max_new_tokens=16).result()]
        stats = {k: eng.stats.get(k, 0)
                 for k in ("spec_accepted", "spec_rejected",
                           "spec_proposed")}
        eng.shutdown()
        return outs, stats

    base, base_stats = run(None)
    tp, tp_stats = run(tp4)
    assert base == tp
    assert base_stats == tp_stats
    assert base_stats["spec_accepted"] >= 1


def test_spec_decode_full_rejection_rollback_parity(tiny, tp4):
    """Anti-oracle proposer: every draft is guaranteed wrong, so
    every verify rejects everything and clamps the KV write frontier
    back. Under tp=4 the rollback is a host-side position clamp over
    the head-sharded pool (device-local, no collectives) — the
    continuation must still be token-identical to the 1-chip
    engine."""
    cfg = tiny[0]
    prompt = [5, 9, 2, 7, 11]

    def run(sh, proposer):
        eng = _engine(tiny, sh, spec_len=4, spec_proposer=proposer)
        out = eng.submit(prompt, max_new_tokens=16).result()
        stats = {k: eng.stats.get(k, 0)
                 for k in ("spec_accepted", "spec_rejected",
                           "spec_proposed")}
        eng.shutdown()
        return out, stats

    ref, _ = run(None, None)   # n-gram default, plain reference
    wrong = [(t + 1) % cfg.vocab_size for t in ref]
    base, base_stats = run(
        None, lambda: _Scripted(len(prompt), wrong))
    tp, tp_stats = run(tp4, lambda: _Scripted(len(prompt), wrong))
    assert base == ref         # rollback preserved greedy output
    assert tp == ref
    assert base_stats == tp_stats
    assert base_stats["spec_rejected"] >= 4
    assert base_stats["spec_accepted"] == 0


def test_mixtral_expert_parallel_parity(cpu_mesh_devices):
    """Mixtral on a 2-D expert x tensor mesh (ep=2 x tp=2): routing
    and the drop-free dispatch/combine run expert-sharded, attention
    head-sharded — still token-identical to the 1-chip engine."""
    from ray_tpu.models.mixtral import Mixtral, mixtral_tiny
    cfg = mixtral_tiny(dtype=jnp.float32)
    model = Mixtral(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    sh = EngineSharding.build(cfg, tp=2, ep=2,
                              devices=cpu_mesh_devices[:4])
    prompts = [np.random.RandomState(3).randint(
        1, cfg.vocab_size - 1, size=10).tolist()]

    def run(sharding):
        eng = LLMEngine(model, params, max_slots=2, page_size=8,
                        n_pages=32, chunk=4, prefill_chunk=16,
                        temperature=0.0, seed=0, sharding=sharding)
        eng.start()
        outs = [eng.submit(p, max_new_tokens=12).result()
                for p in prompts]
        eng.shutdown()
        return outs

    assert run(None) == run(sh)


# ------------------------------------------------- placement + units

def test_kv_pool_is_head_sharded(tiny, tp4):
    """The engine's page pool must shard axis 0 (kv heads) over
    ``tensor`` and nothing else — the invariant that keeps
    paged_append / decode / page copies collective-free."""
    eng = _engine(tiny, tp4)
    try:
        for pk, pv in eng.pages:
            for t in (pk, pv):
                spec = t.sharding.spec
                assert spec[0] == "tensor"
                assert all(s is None for s in spec[1:])
                # per-device shard holds KH/tp heads, ALL pages
                shard_shape = t.sharding.shard_shape(t.shape)
                assert shard_shape[0] == t.shape[0] // 4
                assert shard_shape[1:] == t.shape[1:]
    finally:
        eng.shutdown()


def test_dispatch_state_replicated(tiny, tp4):
    eng = _engine(tiny, tp4)
    try:
        for t in (eng._dev_cur, eng._dev_pos, eng._rng):
            assert t.sharding.is_fully_replicated
    finally:
        eng.shutdown()


def test_load_report_carries_tp(tiny, tp4):
    eng = _engine(tiny, tp4)
    try:
        assert eng.load_report()["tp"] == 4
    finally:
        eng.shutdown()
    eng = _engine(tiny, None)
    try:
        assert eng.load_report()["tp"] == 1
    finally:
        eng.shutdown()


def test_divisibility_errors():
    cfg = llama_tiny()           # n_kv_heads=2: tp=4 can't divide
    with pytest.raises(ShardingConfigError, match="n_kv_heads"):
        EngineSharding.build(cfg, tp=4)
    llama_tp_validate(cfg, 2)    # 2 divides everything: fine
    with pytest.raises(ValueError, match="n_heads|n_kv_heads"):
        llama_tp_validate(cfg, 3)
    with pytest.raises(ShardingConfigError, match="devices"):
        EngineSharding.build(llama_tiny(n_kv_heads=4), tp=4,
                             devices=jax.devices()[:2])
    with pytest.raises(ShardingConfigError, match="MoE"):
        EngineSharding.build(cfg, tp=2, ep=2)  # ep on a dense model


def test_replica_device_groups(cpu_mesh_devices):
    groups = replica_device_groups(2, 4, cpu_mesh_devices)
    assert [len(g) for g in groups] == [4, 4]
    assert set(groups[0]).isdisjoint(groups[1])
    # exhausted devices wrap around (CPU host-mesh pool testing)
    groups = replica_device_groups(3, 4, cpu_mesh_devices)
    assert groups[2] == groups[0]
    with pytest.raises(ShardingConfigError):
        replica_device_groups(1, 16, cpu_mesh_devices)


def test_match_partition_rules_unmatched_raises(tiny):
    """A >=2-D tensor no rule covers must raise (silent replication
    is the failure mode this gate exists for); 1-D norm scales fall
    through legitimately."""
    from ray_tpu.mesh.sharding import (ShardingRules,
                                       match_partition_rules)
    _, _, params = tiny
    rules = ShardingRules([(r"attention/w[qkv]/kernel",
                            P(None, "tensor"))])
    with pytest.raises(ValueError) as ei:
        match_partition_rules(rules, params)
    assert "feed_forward" in str(ei.value)   # names the culprits
    assert "REPLICATED" in str(ei.value)
    # warn mode still returns specs
    with pytest.warns(UserWarning, match="REPLICATED"):
        specs = match_partition_rules(rules, params,
                                      on_unmatched="warn")
    assert specs is not None
    # full serving rules cover every matrix: strict mode passes
    match_partition_rules(llama_sharding_rules(fsdp=False), params)


def test_match_partition_rules_covers_mixtral():
    from ray_tpu.mesh.sharding import match_partition_rules
    from ray_tpu.models.mixtral import (Mixtral, mixtral_tiny,
                                        mixtral_sharding_rules)
    cfg = mixtral_tiny()
    params = Mixtral(cfg).init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 8), jnp.int32))
    match_partition_rules(mixtral_sharding_rules(fsdp=False), params)


def test_paged_append_typed_shape_errors():
    from ray_tpu.ops.paged_attention import (PagedShapeError,
                                             paged_append)
    KH, n_pages, Pg, D = 2, 8, 4, 8
    pk = jnp.zeros((KH, n_pages, Pg, D))
    pv = jnp.zeros((KH, n_pages, Pg, D))
    pt = jnp.zeros((2, 4), jnp.int32)
    pos = jnp.zeros((2,), jnp.int32)
    ok_k = jnp.zeros((2, 3, KH, D))
    # control: valid shapes pass
    paged_append(pk, pv, pt, pos, ok_k, ok_k)
    with pytest.raises(PagedShapeError, match="kv heads"):
        paged_append(pk, pv, pt, pos,
                     jnp.zeros((2, 3, KH + 2, D)),
                     jnp.zeros((2, 3, KH + 2, D)))
    with pytest.raises(PagedShapeError, match="head_dim"):
        paged_append(pk, pv, pt, pos,
                     jnp.zeros((2, 3, KH, D * 2)),
                     jnp.zeros((2, 3, KH, D * 2)))
    with pytest.raises(PagedShapeError, match="rank-4"):
        paged_append(pk, pv, pt, pos, jnp.zeros((2, 3, KH)),
                     jnp.zeros((2, 3, KH)))
    with pytest.raises(PagedShapeError, match="disagree"):
        paged_append(pk, pv, pt, pos, ok_k,
                     jnp.zeros((2, 3, KH, D + 1)))
    with pytest.raises(PagedShapeError, match="rows"):
        paged_append(pk, pv, jnp.zeros((5, 4), jnp.int32), pos,
                     ok_k, ok_k)
    with pytest.raises(PagedShapeError, match="integer"):
        paged_append(pk, pv, jnp.zeros((2, 4), jnp.float32), pos,
                     ok_k, ok_k)
    with pytest.raises(PagedShapeError, match="pos"):
        paged_append(pk, pv, pt, jnp.zeros((3,), jnp.int32),
                     ok_k, ok_k)
    # the checks fire at TRACE time (inside jit), not just eagerly
    with pytest.raises(PagedShapeError, match="kv heads"):
        jax.jit(paged_append)(pk, pv, pt, pos,
                              jnp.zeros((2, 3, KH * 2, D)),
                              jnp.zeros((2, 3, KH * 2, D)))


def test_deployment_tensor_parallel_knob(cpu_mesh_devices):
    """LlamaDeployment(tensor_parallel=4): the lazy engine comes up
    sharded; generation matches the tp=1 deployment token-for-token.
    Also: a non-dividing config fails at CONSTRUCTION."""
    from ray_tpu.serve.llm import LlamaDeployment
    cfg = llama_tiny(n_kv_heads=4, dtype=jnp.float32)
    prompt = list(range(1, 11))

    dep1 = LlamaDeployment(config=cfg, max_new_tokens=12,
                           max_slots=2, page_size=8)
    dep4 = LlamaDeployment(config=cfg, max_new_tokens=12,
                           max_slots=2, page_size=8,
                           tensor_parallel=4)
    try:
        assert dep1(prompt) == dep4(prompt)
        assert dep4.engine().load_report()["tp"] == 4
    finally:
        dep1.engine().shutdown()
        dep4.engine().shutdown()

    with pytest.raises(ShardingConfigError, match="n_kv_heads"):
        LlamaDeployment(config=llama_tiny(), tensor_parallel=4)


@pytest.mark.slow
def test_pool_of_sharded_replicas(cpu_mesh_devices):
    """2-D scale-out: num_engine_replicas=2 x tensor_parallel=2 on
    the 8-device host mesh — pool routing, per-replica load_report,
    and the aggregate tp stamp all compose unchanged."""
    from ray_tpu.serve.llm import LlamaDeployment
    cfg = llama_tiny(n_kv_heads=4, dtype=jnp.float32)
    prompt = list(range(1, 11))
    dep = LlamaDeployment(config=cfg, max_new_tokens=12,
                          max_slots=2, page_size=8,
                          num_engine_replicas=2, tensor_parallel=2)
    ref = LlamaDeployment(config=cfg, max_new_tokens=12,
                          max_slots=2, page_size=8)
    try:
        assert dep(prompt) == ref(prompt)
        rpt = dep.engine().load_report()
        assert rpt["tp"] == 2
        assert rpt["n_replicas"] == 2
    finally:
        dep.engine().shutdown()
        ref.engine().shutdown()
