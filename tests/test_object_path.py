"""Fast object path: memory tier, promotion-on-escape, eager GC,
streamed cross-node pulls.

Reference capabilities pinned here: in-process memory store for small
owned objects (core_worker/store_provider/memory_store/memory_store.h:43,
100KiB threshold ray_config_def.h:181), owner-based eager object
lifetime (reference_count.h:39-61), and O(chunk) streamed transfer
(object_manager pull_manager.h:47 / push_manager.h:29).
"""
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.runtime import Cluster


@pytest.fixture(scope="module")
def cluster():
    import ray_tpu._private.worker as worker_mod
    if worker_mod.is_initialized():
        worker_mod.shutdown()
    c = Cluster(num_workers=2, resources_per_worker={"CPU": 2},
                store_capacity=256 * 1024 * 1024)
    yield c
    c.shutdown()


def _plane():
    from ray_tpu._private.worker import global_worker
    return global_worker().runtime.plane


def test_small_put_stays_in_memory_tier(cluster):
    """A small owned put never touches shm (no create/seal/registration)
    and still resolves locally."""
    plane = _plane()
    ref = ray_tpu.put({"x": 1, "y": [2, 3]})
    assert ref.id in plane.memory
    assert not plane.store.contains(ref.id)
    assert ray_tpu.get(ref) == {"x": 1, "y": [2, 3]}


def test_big_put_goes_to_shm(cluster):
    plane = _plane()
    arr = np.arange(1 << 18)          # 2MB > 100KiB threshold
    ref = ray_tpu.put(arr)
    assert ref.id not in plane.memory
    assert plane.store.contains(ref.id)
    np.testing.assert_array_equal(ray_tpu.get(ref), arr)


def test_escape_promotes_to_shm(cluster):
    """Passing a memory-tier ref to a task promotes the object so the
    worker process can resolve it."""
    plane = _plane()

    @ray_tpu.remote
    def consume(x):
        return x * 2

    ref = ray_tpu.put(21)
    assert ref.id in plane.memory
    assert ray_tpu.get(consume.remote(ref), timeout=15) == 42
    # escape moved it out of the private tier into shm
    assert ref.id not in plane.memory
    assert plane.store.contains(ref.id)


def test_contained_ref_escape_promotes(cluster):
    """A ref nested inside a put value escapes via the serializer's
    persistent_id hook, not just via direct task args."""
    plane = _plane()
    inner = ray_tpu.put("payload")
    assert inner.id in plane.memory
    outer = ray_tpu.put({"inner": inner})
    assert inner.id not in plane.memory       # escaped
    got = ray_tpu.get(outer)
    assert ray_tpu.get(got["inner"]) == "payload"


def test_eager_free_on_ref_drop(cluster):
    """Dropping the last ref of an owned, never-escaped object deletes
    it from shm immediately — no LRU pressure needed."""
    plane = _plane()
    ref = ray_tpu.put(np.ones(1 << 18))
    oid = ref.id
    assert plane.store.contains(oid)
    del ref
    deadline = time.time() + 5
    while plane.store.contains(oid) and time.time() < deadline:
        time.sleep(0.01)
    assert not plane.store.contains(oid)


def test_escaped_ref_not_eagerly_freed(cluster):
    """An escaped ref may have external holders: zero local refs must
    NOT delete it."""
    import cloudpickle
    plane = _plane()
    ref = ray_tpu.put(np.ones(1 << 18))
    oid = ref.id
    blob = cloudpickle.dumps(ref)          # escape
    del ref, blob
    time.sleep(0.3)
    assert plane.store.contains(oid)


def test_task_return_eagerly_freed(cluster):
    """Task returns are owned by the caller: put-use-drop churn above
    store capacity must hold steady shm usage with ZERO spills."""
    plane = _plane()

    @ray_tpu.remote
    def make(n):
        return np.ones(n)

    spilled_before = plane.store.stats()["num_spilled"]
    # 20 x 64MB through a 256MB store: without eager free this MUST
    # spill; with it, usage stays bounded.
    for _ in range(20):
        r = make.remote(8 << 20)
        arr = ray_tpu.get(r, timeout=30)
        assert arr.nbytes == 64 << 20
        del arr, r
    # The free flusher polls at 1s and deferred (pinned) deletes run
    # at pin release: poll instead of racing a fixed sleep.
    deadline = time.time() + 6
    while time.time() < deadline and \
            plane.store.stats()["bytes_in_use"] >= 200 * 1024 * 1024:
        time.sleep(0.25)
    stats = plane.store.stats()
    assert stats["num_spilled"] == spilled_before
    assert stats["bytes_in_use"] < 200 * 1024 * 1024


@pytest.fixture(scope="module")
def two_nodes():
    import ray_tpu._private.worker as worker_mod
    if worker_mod.is_initialized():
        worker_mod.shutdown()
    c = Cluster(num_workers=1,
                resources_per_worker={"CPU": 2, "node0": 10},
                store_capacity=256 * 1024 * 1024)
    nid = c.add_node(num_workers=1,
                     resources_per_worker={"CPU": 2, "node1": 10},
                     store_capacity=256 * 1024 * 1024)
    yield c, nid
    c.shutdown()


def test_cross_node_eager_free(two_nodes):
    """del of the owner's ref removes the object from BOTH nodes'
    stores (owner-driven free broadcast), not just the local one."""
    c, nid = two_nodes

    @ray_tpu.remote(resources={"node1": 1})
    def produce():
        return np.ones(4 << 20)        # 32MB

    @ray_tpu.remote(resources={"node1": 1})
    def node1_has(oid_hex):
        from ray_tpu._private.ids import ObjectID
        from ray_tpu._private.worker import global_worker
        store = global_worker().runtime._ex.store
        return store.contains(ObjectID.from_hex(oid_hex))

    plane = _plane()
    ref = produce.remote()
    arr = ray_tpu.get(ref, timeout=30)     # pulled + cached locally
    oid = ref.id
    oid_hex = oid.hex()
    assert plane.store.contains(oid)
    assert ray_tpu.get(node1_has.remote(oid_hex), timeout=15)
    del arr, ref
    deadline = time.time() + 10
    while time.time() < deadline:
        local_gone = not plane.store.contains(oid)
        remote_gone = not ray_tpu.get(node1_has.remote(oid_hex),
                                      timeout=15)
        if local_gone and remote_gone:
            break
        time.sleep(0.2)
    assert local_gone and remote_gone


def test_streamed_pull_O_chunk_memory(two_nodes):
    """The chunked fetch buffers O(in-flight chunks) of host RAM, not
    O(object): peak Python allocations during a 64MB transfer stay
    under a few chunks."""
    import tracemalloc

    from ray_tpu.runtime import object_plane as op

    c, nid = two_nodes

    @ray_tpu.remote(resources={"node1": 1})
    def produce():
        return np.ones(8 << 20)        # 64MB

    plane = _plane()
    ref = produce.remote()
    deadline = time.time() + 30
    locs = []
    while not locs and time.time() < deadline:
        time.sleep(0.1)
        locs = plane.head.call("locate_object", ref.id.hex(),
                               probe=True, reconstruct=False)
    size = plane._peer(locs[0]["object_addr"]).call(
        "object_size", ref.id.hex())
    assert size >= 64 << 20
    view = plane.store.create_for_write(ref.id, size)
    assert view is not None
    tracemalloc.start()
    plane._fetch_into(view, ref.id.hex(), locs[0]["object_addr"], size)
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    view.release()
    plane.store.seal_raw(ref.id)
    # transfer buffering stays within a few chunks, never O(object)
    assert peak < 3 * op.CHUNK
    got = plane.store.get_bytes(ref.id, timeout_ms=0)
    assert len(got) == size
