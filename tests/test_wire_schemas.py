"""Typed wire-message schemas + protocol version negotiation.

Reference capability: the 21 proto files (src/ray/protobuf/
gcs_service.proto etc.) give every control-plane method a declared
signature, reject unknown fields, and make version skew fail closed.
"""
import pytest

import ray_tpu.runtime.rpc as rpc
from ray_tpu.runtime.rpc import RpcClient, RpcError, RpcServer
from ray_tpu.runtime.schemas import (CODEC_VERSION, SchemaError,
                                     validate_request)


class _Handler:
    def locate_object(self, oid_hex, probe=False, reconstruct=False):
        return [{"oid": oid_hex, "probe": probe}]

    def free_text(self, anything):          # unschema'd: passthrough
        return anything


@pytest.fixture()
def server():
    s = RpcServer(_Handler())
    yield s
    s.stop()


def test_validate_request_unit():
    validate_request("locate_object", ("ab",), {"probe": True})
    with pytest.raises(SchemaError, match="unknown field 'bogus'"):
        validate_request("locate_object", ("ab",), {"bogus": 1})
    with pytest.raises(SchemaError, match="expects str"):
        validate_request("locate_object", (123,), {})
    with pytest.raises(SchemaError, match="missing required"):
        validate_request("register_objects", (), {})
    with pytest.raises(SchemaError, match="at most"):
        validate_request("kv_get", ("a", "b", "c"), {})
    validate_request("not_a_known_method", (1, 2), {"x": 3})  # legacy


def test_server_rejects_unknown_field(server):
    client = RpcClient(server.address)
    assert client.call("locate_object", "abcd")[0]["oid"] == "abcd"
    with pytest.raises(SchemaError, match="unknown field 'shiny'"):
        client.call("locate_object", "abcd", shiny=True)
    # error names the server's codec version (skew diagnosis)
    try:
        client.call("locate_object", "abcd", shiny=True)
    except SchemaError as e:
        assert f"codec {CODEC_VERSION}" in str(e)
    client.close()


def test_server_rejects_bad_type(server):
    client = RpcClient(server.address)
    with pytest.raises(SchemaError, match="expects str, got int"):
        client.call("locate_object", 42)
    client.close()


def test_codec_version_exchanged(server):
    client = RpcClient(server.address)
    client.call("free_text", "hi")
    assert client.peer_codec == CODEC_VERSION
    client.close()


def test_old_client_fails_closed(server):
    """Version skew (old client, new server): the connection is
    rejected at handshake with a clear both-versions error — no
    request payload is ever deserialized. Simulated with a raw
    previous-version HELLO (client and server share this process, so
    monkeypatching the module global would downgrade both ends)."""
    import pickle
    import socket
    import struct

    from ray_tpu._private.config import GlobalConfig
    host, port = server.address.split(":")
    tok = GlobalConfig.cluster_token.encode()
    with socket.create_connection((host, int(port)), timeout=10) as s:
        s.sendall(struct.pack("<4sHH", b"RAYT",
                              rpc.PROTO_VERSION - 1, len(tok)) + tok)
        # old clients wait for a length-prefixed reply frame
        n = struct.unpack("<I", _recv(s, 4))[0]
        reply = pickle.loads(_recv(s, n))
    err = reply["err"]
    assert isinstance(err, RpcError)
    assert "protocol version mismatch" in str(err)
    assert f"server {rpc.PROTO_VERSION}" in str(err)


def _recv(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("closed")
        buf += chunk
    return buf


def test_unschema_d_methods_still_flow(server):
    client = RpcClient(server.address)
    assert client.call("free_text", {"arbitrary": ["payload"]}) == \
        {"arbitrary": ["payload"]}
    client.close()
