"""conda + container runtime-env types (VERDICT r5 #8).

Both ride the pip/venv machinery: env-keyed dedicated workers, cached
staging, env_setup_failed surfacing. conda tests run only where a
conda/mamba binary exists (this image has none — they skip cleanly,
the VERDICT's stated done bar); the container command builder is a
pure function and is tested engine-free.
Reference: python/ray/_private/runtime_env/{conda,container}.py.
"""
import pytest

from ray_tpu._private.runtime_env import (conda_available,
                                          container_command_prefix,
                                          find_container_engine,
                                          runtime_env_key,
                                          validate_runtime_env)


def test_validation_accepts_and_rejects():
    validate_runtime_env({"conda": "base"})
    validate_runtime_env({"conda": {"dependencies": ["numpy"]}})
    validate_runtime_env({"container": {"image": "python:3.12"}})
    with pytest.raises(TypeError):
        validate_runtime_env({"conda": 42})
    with pytest.raises(TypeError):
        validate_runtime_env({"container": {"run_options": []}})
    with pytest.raises(TypeError):
        validate_runtime_env({"container": {"image": "x",
                                            "run_options": [1]}})
    with pytest.raises(ValueError):
        validate_runtime_env({"conda": "x", "pip": ["y"]})


def test_env_keys_distinct_per_type():
    ks = {runtime_env_key(e) for e in (
        {"conda": "a"}, {"conda": "b"},
        {"conda": {"dependencies": ["numpy"]}},
        {"container": {"image": "img:1"}},
        {"container": {"image": "img:2"}},
        {"pip": ["pkg"]},
    )}
    assert len(ks) == 6        # every env maps to its own worker pool


def test_container_prefix_construction():
    prefix = container_command_prefix(
        {"container": {"image": "img:1",
                       "run_options": ["--cpus=2", "--memory=1g"]}},
        engine="podman")
    assert prefix[0] == "podman" and prefix[-1] == "img:1"
    assert prefix[1:3] == ["run", "--rm"]
    # worker must reach the head's loopback ports and the shm store
    assert "host" in prefix[prefix.index("--network") + 1]
    assert "/dev/shm:/dev/shm" in prefix
    assert "--cpus=2" in prefix and "--memory=1g" in prefix
    # run options come before the image (engine args, not cmd args)
    assert prefix.index("--cpus=2") < prefix.index("img:1")


def test_container_prefix_requires_engine(monkeypatch):
    import ray_tpu._private.runtime_env as m
    monkeypatch.setattr(m, "find_container_engine", lambda: None)
    with pytest.raises(RuntimeError, match="podman"):
        m.container_command_prefix({"container": {"image": "x"}})


def test_conda_missing_binary_fails_closed(monkeypatch):
    import ray_tpu._private.runtime_env as m
    monkeypatch.setattr(m, "find_conda", lambda: None)
    with pytest.raises(RuntimeError, match="conda"):
        m.conda_env_python({"conda": "base"})


def test_conda_env_setup_failure_surfaces_to_caller():
    """Without conda on the node, a task pinned to a conda env must
    FAIL with the real staging error (env_setup_failed path), not
    hang. If conda exists, the same submission must instead run inside
    the env — both outcomes are asserted."""
    import ray_tpu
    import ray_tpu._private.worker as worker_mod
    from ray_tpu.runtime import Cluster
    if worker_mod.is_initialized():
        worker_mod.shutdown()
    with Cluster(num_workers=1, resources_per_worker={"CPU": 2}):
        @ray_tpu.remote(runtime_env={"conda": "raytpu-does-not-exist"})
        def probe():
            import sys
            return sys.executable

        if conda_available():
            with pytest.raises(Exception, match="not found"):
                ray_tpu.get(probe.remote(), timeout=120)
        else:
            with pytest.raises(Exception, match="conda"):
                ray_tpu.get(probe.remote(), timeout=120)


@pytest.mark.skipif(not conda_available(),
                    reason="no conda/mamba on this image")
def test_conda_named_env_task_runs_in_env():
    """Task executes under the named conda env's interpreter (done bar:
    'task runs in a conda env the driver lacks')."""
    import sys
    import ray_tpu
    import ray_tpu._private.worker as worker_mod
    from ray_tpu.runtime import Cluster
    if worker_mod.is_initialized():
        worker_mod.shutdown()
    with Cluster(num_workers=1, resources_per_worker={"CPU": 2}):
        @ray_tpu.remote(runtime_env={"conda": "base"})
        def interp():
            import sys as s
            return s.executable

        exe = ray_tpu.get(interp.remote(), timeout=600)
        assert exe != sys.executable


@pytest.mark.skipif(find_container_engine() is None,
                    reason="no podman/docker on this image")
def test_container_env_task_runs_in_image():
    import ray_tpu
    import ray_tpu._private.worker as worker_mod
    from ray_tpu.runtime import Cluster
    if worker_mod.is_initialized():
        worker_mod.shutdown()
    with Cluster(num_workers=1, resources_per_worker={"CPU": 2}):
        @ray_tpu.remote(
            runtime_env={"container": {"image": "python:3.12-slim"}})
        def hostname_ns():
            import os
            return os.path.exists("/.dockerenv") or \
                os.path.exists("/run/.containerenv")

        assert ray_tpu.get(hostname_ns.remote(), timeout=600)
