"""C++ user API end-to-end (reference role: cpp/ user API +
cross_language tests): builds the native demo client and runs it
against a live multi-process cluster — authenticated RPC handshake,
KV, shm-data-plane put/get, cross-language task submission (C++
submits an import path, a Python worker executes it), and error
propagation. The pickle codec is cross-checked against CPython in both
directions through the pickle_bridge tool."""
import os
import pickle
import shutil
import struct
import subprocess

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_ROOT, "src")

needs_gxx = pytest.mark.skipif(shutil.which("g++") is None,
                               reason="no C++ toolchain")


def _build(target: str) -> str:
    proc = subprocess.run(["make", "-C", _SRC, target],
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    return os.path.join(_ROOT, "build", os.path.basename(target))


@needs_gxx
def test_cpp_demo_against_live_cluster():
    import ray_tpu._private.worker as worker_mod
    from ray_tpu.runtime import Cluster
    _build("demo")
    demo = os.path.join(_ROOT, "build", "raytpu_cpp_demo")

    if worker_mod.is_initialized():
        worker_mod.shutdown()
    c = Cluster(num_workers=2, resources_per_worker={"CPU": 2})
    try:
        addr = c.node.head_address
        out = subprocess.run([demo, addr], capture_output=True,
                             text=True, timeout=120)
        assert out.returncode == 0, out.stdout + out.stderr
        assert "CPP API DEMO PASSED" in out.stdout
        for line in ("kv: OK", "put/get: OK",
                     "cross-language tasks: OK",
                     "error propagation: OK"):
            assert line in out.stdout
    finally:
        c.shutdown()


@needs_gxx
def test_cpp_pickle_interop_with_cpython():
    """True cross-boundary round trips: CPython protocol-5 pickles go
    through the C++ decoder+encoder and come back equal."""
    bridge = _build("../build/pickle_bridge")

    samples = [None, True, False, 0, 255, 256, -1, 2 ** 40, -(2 ** 40),
               2 ** 62, 1.5, -3.25e100, "snake", "x" * 1000, "unié",
               b"\x00\x01", b"y" * 500, [], (), {},
               [1, [2, 3]], (1, "two", 3.0),
               {"k": [1, 2], 7: b"blob"},
               {"nested": {"deep": (None, True)}},
               [{"a": i} for i in range(50)]]
    for v in samples:
        blob = pickle.dumps(v, protocol=5)
        proc = subprocess.run(
            [bridge], input=struct.pack("<I", len(blob)) + blob,
            capture_output=True, timeout=30)
        assert proc.returncode == 0, (v, proc.stderr.decode())
        (olen,) = struct.unpack("<I", proc.stdout[:4])
        back = pickle.loads(proc.stdout[4:4 + olen])
        assert back == v, (v, back)

    # exception objects (error replies) decode to a representation
    # rather than failing the whole parse
    import cloudpickle
    err_blob = cloudpickle.dumps(("err", RuntimeError("kaboom")))
    proc = subprocess.run(
        [bridge], input=struct.pack("<I", len(err_blob)) + err_blob,
        capture_output=True, timeout=30)
    assert proc.returncode == 0, proc.stderr.decode()
    (olen,) = struct.unpack("<I", proc.stdout[:4])
    back = pickle.loads(proc.stdout[4:4 + olen])
    assert back[0] == "err"
    assert "RuntimeError" in str(back[1]) and "kaboom" in str(back[1])
