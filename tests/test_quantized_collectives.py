"""EQuARX-style int8 quantized psum (ops/quantized_collectives.py)
on the forced 8-device CPU mesh.

The op is groundwork — NOT wired into the serving engine — so these
tests pin the numerics contract it must keep to ever be wired in:
error within the analytic per-rank rounding bound (not a loose rtol),
exact zeros for all-zero shards, dtype preservation, and a typed
refusal of non-dividing shapes.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh

from ray_tpu.ops.quantized_collectives import (
    dequantize_rowwise, quantize_rowwise, quantized_psum_error_bound,
    quantized_psum_sharded)


@pytest.fixture(scope="module")
def mesh(cpu_mesh_devices):
    return Mesh(np.array(cpu_mesh_devices[:8]), ("tensor",))


def test_rowwise_roundtrip_half_step():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((4, 256)), jnp.float32)
    q, s = quantize_rowwise(x)
    assert q.dtype == jnp.int8 and s.shape == (4, 1)
    err = np.abs(np.asarray(dequantize_rowwise(q, s)) - np.asarray(x))
    assert (err <= np.asarray(s) / 2.0 + 1e-7).all()


def test_all_zero_rows_are_exact():
    x = jnp.zeros((3, 64), jnp.float32)
    q, s = quantize_rowwise(x)
    assert (np.asarray(q) == 0).all() and (np.asarray(s) == 0).all()
    assert (np.asarray(dequantize_rowwise(q, s)) == 0).all()


def test_psum_within_analytic_bound(mesh):
    rng = np.random.default_rng(1)
    x = rng.standard_normal((8, 4, 128)).astype(np.float32)
    out = np.asarray(quantized_psum_sharded(jnp.asarray(x), mesh))
    exact = x.sum(axis=0)
    bound = quantized_psum_error_bound(x)
    err = np.abs(out - exact)
    assert (err <= bound + 1e-6).all()
    # and the bound is TIGHT enough to mean something: the observed
    # error should be the same order, not 1000x smaller
    assert err.max() > bound.max() / 100.0


def test_psum_multiple_rows_per_rank(mesh):
    # leading dim 16 over 8 ranks: each rank locally sums 2 rows
    # before quantizing — one wire payload per rank, not per row
    rng = np.random.default_rng(2)
    x = rng.standard_normal((16, 64)).astype(np.float32)
    out = np.asarray(quantized_psum_sharded(jnp.asarray(x), mesh))
    exact = x.sum(axis=0)
    local = x.reshape(8, 2, 64).sum(axis=1)   # per-rank partials
    bound = quantized_psum_error_bound(local)
    assert (np.abs(out - exact) <= bound + 1e-6).all()


def test_dtype_preserved(mesh):
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((8, 32)), jnp.bfloat16)
    out = quantized_psum_sharded(x, mesh)
    assert out.dtype == jnp.bfloat16


def test_non_dividing_leading_dim_raises(mesh):
    with pytest.raises(ValueError, match="does not shard"):
        quantized_psum_sharded(jnp.zeros((7, 32), jnp.float32), mesh)


def test_zero_shards_contribute_exactly_zero(mesh):
    # one hot rank, seven zero ranks: the zero ranks' guarded divide
    # must contribute exact zeros, so the sum equals the hot shard
    # within ITS OWN rounding only
    rng = np.random.default_rng(4)
    x = np.zeros((8, 4, 64), np.float32)
    x[3] = rng.standard_normal((4, 64))
    out = np.asarray(quantized_psum_sharded(jnp.asarray(x), mesh))
    bound = quantized_psum_error_bound(x[3:4])
    assert (np.abs(out - x[3]) <= bound + 1e-7).all()
