"""Serialization layer tests (reference analogue:
python/ray/tests/test_serialization.py)."""
import numpy as np

import ray_tpu
from ray_tpu._private import serialization


def test_roundtrip_basic():
    for v in [1, "s", {"a": [1, 2]}, (None, True), b"bytes"]:
        assert serialization.deserialize(serialization.serialize(v)) == v


def test_numpy_zero_copy_buffers():
    arr = np.arange(100000, dtype=np.float32)
    so = serialization.serialize(arr)
    assert len(so.buffers) == 1  # out-of-band, not folded into the pickle
    out = serialization.deserialize(so)
    np.testing.assert_array_equal(arr, out)


def test_flat_dumps_loads():
    payload = {"x": np.ones((256, 256)), "y": list(range(10))}
    out = serialization.loads(serialization.dumps(payload))
    np.testing.assert_array_equal(out["x"], payload["x"])
    assert out["y"] == payload["y"]


def test_closure_serialization():
    factor = 7

    def mul(x):
        return x * factor

    out = serialization.deserialize(serialization.serialize(mul))
    assert out(6) == 42


def test_objectref_capture_and_restore(rt):
    ref = rt.put("inner-value")
    so = serialization.serialize({"nested": [ref]})
    assert len(so.contained_refs) == 1
    restored = serialization.deserialize(so)
    inner = restored["nested"][0]
    assert rt.get(inner) == "inner-value"
