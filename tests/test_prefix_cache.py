"""Radix-tree prefix KV cache tests (serve/prefix_cache.py) and its
engine integration.

Two layers, mirroring how the reference tests its object store:
pure-host tests drive PrefixCache + BlockAllocator directly (refcount,
LRU, dedupe, invariants — no device), and engine tests prove the
user-visible contract: cache-hit decode is TOKEN-IDENTICAL to a cold
prefill, the pool always balances (free + cached == usable), eviction
reclaims cache residency before admission fails, and preemption never
frees a shared page.
"""
import dataclasses
import types

import jax.numpy as jnp
import pytest

from ray_tpu.models.kv_cache import BlockAllocator
from ray_tpu.models.llama import Llama, generate, llama_tiny
from ray_tpu.serve.engine import LLMEngine, _Slot
from ray_tpu.serve.prefix_cache import PrefixCache

import numpy as np


@pytest.fixture(scope="module")
def tiny_model():
    # fp32 so paged vs contiguous decode agree bit-for-bit (see
    # test_llm_engine.py).
    import jax
    cfg = llama_tiny(dtype=jnp.float32)
    model = Llama(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    return model, params


def _reference_completion(model, params, prompt, n):
    out = generate(model, params, jnp.asarray([prompt], jnp.int32),
                   max_new_tokens=n, temperature=0.0)
    return np.asarray(out)[0, len(prompt):].tolist()


def _drain(eng):
    while eng.step():
        pass


def _balanced(eng):
    """Pool conservation: every usable page is either free or cached
    (no slot holds any after a drain)."""
    return (eng.alloc.n_free + eng.prefix_cache.cached_pages
            == eng.alloc.n_pages - 1)


# ------------------------------------------------------- pure cache


def test_match_insert_roundtrip():
    alloc = BlockAllocator(16)
    pc = PrefixCache(alloc, page_size=4)
    toks = list(range(1, 11))              # 10 tokens: 2 full pages
    pages = alloc.alloc(2)
    pc.insert(toks, pages, n_shared=0)
    assert pc.cached_pages == 2

    got, n = pc.match(toks)
    assert got == pages and n == 8         # page-granular, not 10
    assert [pc.ref_of(p) for p in pages] == [1, 1]
    # shorter query matches only the covered prefix
    got2, n2 = pc.match(toks[:6])
    assert got2 == pages[:1] and n2 == 4
    # divergent second chunk matches one page
    got3, n3 = pc.match(toks[:4] + [99, 99, 99, 99])
    assert got3 == pages[:1] and n3 == 4
    pc.release(got + got2 + got3)
    assert [pc.ref_of(p) for p in pages] == [0, 0]
    pc.check_invariants()


def test_refcount_blocks_eviction():
    alloc = BlockAllocator(16)
    pc = PrefixCache(alloc, page_size=4)
    pages = alloc.alloc(2)
    pc.insert(list(range(8)), pages, n_shared=0)
    held, _ = pc.match(list(range(8)))
    assert pc.evict(10) == 0               # everything referenced
    assert pc.cached_pages == 2
    pc.release(held)
    assert pc.evict(10) == 2               # now reclaimable
    assert pc.cached_pages == 0
    assert alloc.n_free == 15
    pc.check_invariants()


def test_lru_evicts_leaf_first_oldest_first():
    alloc = BlockAllocator(16)
    pc = PrefixCache(alloc, page_size=2)
    # two chains sharing a root page: root -> a -> a2, root -> b
    root_a_a2 = alloc.alloc(3)
    pc.insert([1, 2, 3, 4, 5, 6], root_a_a2, n_shared=0)
    # second sequence matched the root, computed one private page (b):
    # exactly what the engine hands insert at retirement
    held, n = pc.match([1, 2, 9, 9])
    assert held == root_a_a2[:1] and n == 2
    b = alloc.alloc(1)
    pc.insert([1, 2, 9, 9], held + b, n_shared=1)
    assert pc.ref_of(root_a_a2[0]) == 0    # insert released the ref
    # first eviction: the LRU LEAF (a2) — never the shared root, even
    # though the root is older than everything
    assert pc.evict(1) == 1
    assert root_a_a2[2] not in pc._nodes
    assert root_a_a2[0] in pc._nodes
    # next: leaf a (branch a older than b)
    assert pc.evict(1) == 1
    assert root_a_a2[1] not in pc._nodes
    assert b[0] in pc._nodes
    # root only evictable once childless
    assert pc.evict(2) == 2
    assert pc.cached_pages == 0
    assert alloc.n_free == 15
    pc.check_invariants()


def test_insert_dedupes_duplicate_compute():
    """Two sequences miss on the same prefix concurrently and both
    compute it; the second insert must keep the incumbent page (other
    readers may reference it) and recycle its own."""
    alloc = BlockAllocator(16)
    pc = PrefixCache(alloc, page_size=4)
    first = alloc.alloc(1)
    dup = alloc.alloc(1)
    pc.insert([1, 2, 3, 4], first, n_shared=0)
    free_before = alloc.n_free
    pc.insert([1, 2, 3, 4], dup, n_shared=0)
    assert pc.cached_pages == 1
    assert pc._nodes[first[0]].chunk == (1, 2, 3, 4)
    assert alloc.n_free == free_before + 1     # dup went back
    pc.check_invariants()


def test_release_errors():
    alloc = BlockAllocator(16)
    pc = PrefixCache(alloc, page_size=4)
    pages = alloc.alloc(1)
    pc.insert([1, 2, 3, 4], pages, n_shared=0)
    with pytest.raises(RuntimeError):
        pc.release([pages[0]])                 # never matched: underflow
    with pytest.raises(RuntimeError):
        pc.release([13])                       # not cache-held
    held, _ = pc.match([1, 2, 3, 4])
    pc.release(held)                           # balanced: fine
    pc.check_invariants()


def test_account_and_stats():
    alloc = BlockAllocator(16)
    pc = PrefixCache(alloc, page_size=4)
    pc.account(24, 8)
    s = pc.stats()
    assert s["hit_tokens"] == 24 and s["miss_tokens"] == 8
    assert s["hit_rate"] == 0.75


# --------------------------------------------------- engine: parity


def test_cache_hit_output_token_identical(tiny_model):
    """THE correctness contract: a request admitted off cached prefix
    KV must produce exactly the tokens a cold prefill produces."""
    model, params = tiny_model
    eng = LLMEngine(model, params, max_slots=2, page_size=8,
                    n_pages=32, chunk=4, prefix_cache=True)
    prefix = list(range(1, 25))                # 3 full pages
    p1 = prefix + [30, 31]
    p2 = prefix + [40, 41, 42]
    w1 = _reference_completion(model, params, p1, 10)
    w2 = _reference_completion(model, params, p2, 10)
    h1 = eng.submit(p1, max_new_tokens=10)
    _drain(eng)
    assert eng.stats["cache_hit_tokens"] == 0  # cold
    h2 = eng.submit(p2, max_new_tokens=10)
    _drain(eng)
    assert h1.result() == w1
    assert h2.result() == w2                   # hit == cold, exactly
    assert eng.stats["cache_hit_tokens"] == 24
    assert eng.stats["cache_hit_admissions"] == 1
    assert ("cache_hit", (0, 24)) in list(eng.sched_trace)
    assert _balanced(eng)
    eng.prefix_cache.check_invariants()


def test_fully_cached_prompt_boundary_copy(tiny_model):
    """An exact page-aligned repeat: every prompt page is cached, yet
    the model still needs the last position's logits — the engine
    copies the boundary page and re-prefills one token. Output must
    still match the cold run."""
    model, params = tiny_model
    eng = LLMEngine(model, params, max_slots=2, page_size=8,
                    n_pages=32, chunk=4, prefix_cache=True)
    p = list(range(1, 17))                     # exactly 2 pages
    w = _reference_completion(model, params, p, 8)
    h1 = eng.submit(p, max_new_tokens=8)
    _drain(eng)
    h2 = eng.submit(p, max_new_tokens=8)       # 100% cached
    _drain(eng)
    assert h1.result() == w
    assert h2.result() == w
    # matched both pages but paid one back for the boundary re-prefill
    assert eng.stats["cache_hit_tokens"] == 15
    assert _balanced(eng)
    eng.prefix_cache.check_invariants()


def test_hit_skips_prefill_compute(tiny_model):
    """The point of the cache: prefill dispatches only pay for the
    uncached suffix."""
    model, params = tiny_model
    eng = LLMEngine(model, params, max_slots=2, page_size=8,
                    n_pages=64, chunk=4, prefill_chunk=8,
                    prefix_cache=True)
    prefix = list(range(1, 33))                # 4 pages: 4 chunks cold
    h1 = eng.submit(prefix + [50], max_new_tokens=4)
    _drain(eng)
    cold_tokens = eng.stats["prefill_tokens"]
    h2 = eng.submit(prefix + [60, 61], max_new_tokens=4)
    _drain(eng)
    assert eng.stats["prefill_tokens"] - cold_tokens == 2  # suffix only
    assert h1.result() == _reference_completion(
        model, params, prefix + [50], 4)
    assert h2.result() == _reference_completion(
        model, params, prefix + [60, 61], 4)


# ---------------------------------------------------- engine: churn


def test_churn_returns_pool_to_baseline(tiny_model):
    """Submit/retire loops: pages migrate between slots, the tree and
    the free list, but every usable page is always accounted for."""
    model, params = tiny_model
    eng = LLMEngine(model, params, max_slots=2, page_size=8,
                    n_pages=32, chunk=4, prefix_cache=True)
    prefix = list(range(1, 17))
    for i in range(6):
        tail = [100 + i, 200 + i]
        h = eng.submit(prefix + tail, max_new_tokens=4)
        _drain(eng)
        assert h.result() == _reference_completion(
            model, params, prefix + tail, 4)
        assert _balanced(eng), (i, eng.alloc.n_free,
                                eng.prefix_cache.stats())
        assert eng.prefix_cache.evictable_pages() \
            == eng.prefix_cache.cached_pages   # no refs leak
        eng.prefix_cache.check_invariants()
    assert eng.stats["cache_hit_tokens"] == 5 * 16


def test_eviction_under_pressure_before_admission_fails(tiny_model):
    """Pool small enough that cached pages crowd out a new admission:
    the engine must reclaim LRU refcount-0 cache pages instead of
    rejecting/preempting."""
    model, params = tiny_model
    # 7 usable pages; each retired request caches its full prompt
    # pages, so a few distinct prompts fill the pool with cache.
    eng = LLMEngine(model, params, max_slots=1, page_size=8,
                    n_pages=8, chunk=4, prefix_cache=True)
    for i in range(3):
        p = [10 * (i + 1) + j for j in range(16)]   # 2 pages each
        h = eng.submit(p, max_new_tokens=4)
        _drain(eng)
        assert h.result() == _reference_completion(model, params, p, 4)
        assert _balanced(eng)
    assert eng.prefix_cache.cached_pages >= 4
    # next distinct request needs 3 pages; free list alone can't cover
    assert eng.alloc.n_free < 3
    p = [77 + j for j in range(17)]
    h = eng.submit(p, max_new_tokens=4)
    _drain(eng)
    assert h.result() == _reference_completion(model, params, p, 4)
    assert eng.prefix_cache.evictions > 0
    assert eng.prefix_cache.stats()["evictions"] > 0
    assert _balanced(eng)
    eng.prefix_cache.check_invariants()


def test_preemption_never_frees_shared_pages(tiny_model):
    """A cache-hit slot preempted MID-PREFILL: its shared pages must
    stay in the tree (refs back to 0, never on the free list), its
    private pages return to the allocator, and the recomputed request
    still matches the reference."""
    model, params = tiny_model
    eng = LLMEngine(model, params, max_slots=2, page_size=8,
                    n_pages=64, chunk=2, prefill_chunk=8,
                    prefix_cache=True)
    prefix = list(range(1, 17))                # 2 pages
    h0 = eng.submit(prefix + [90], max_new_tokens=4)
    _drain(eng)
    assert h0.result() == _reference_completion(
        model, params, prefix + [90], 4)
    shared = [p for p in eng.prefix_cache._nodes][:2]

    long_tail = prefix + list(range(200, 224))     # 24-token suffix
    want = _reference_completion(model, params, long_tail, 4)
    h = eng.submit(long_tail, max_new_tokens=4)
    eng.step()                                 # admit + first chunk
    with eng._lock:
        ixs = [i for i, s in enumerate(eng.slots)
               if s is not None and s.shared > 0]
        assert ixs, "expected a mid-prefill cache-hit slot"
        slot = eng.slots[ixs[0]]
        assert 0 < slot.prefilled < len(long_tail)
        held = slot.pages[:slot.shared]
        assert all(eng.prefix_cache.ref_of(p) == 1 for p in held)
        eng._preempt_locked(ixs[0])
        # shared pages survived the preemption, unreferenced
        assert all(p in eng.prefix_cache._nodes for p in held)
        assert all(eng.prefix_cache.ref_of(p) == 0 for p in held)
        assert all(p not in eng.alloc._free_set for p in held)
    assert eng.stats["preemptions"] == 1
    _drain(eng)                                # re-admits, re-matches
    assert h.result() == want
    assert _balanced(eng)
    eng.prefix_cache.check_invariants()
    assert set(shared) <= set(eng.prefix_cache._nodes)


# ------------------------------------------------ engine: invariants


def test_cow_check_rejects_shared_page_writes(tiny_model):
    model, params = tiny_model
    eng = LLMEngine(model, params, max_slots=1, page_size=8,
                    n_pages=16, chunk=2, prefix_cache=True)
    slot = _Slot(req=types.SimpleNamespace(rid=7), pages=[1, 2, 3],
                 pos=16, cur=None, admit_seq=0,
                 prompt=list(range(20)), prefilled=16, shared=2)
    eng._check_cow_locked(slot, 16)            # frontier: legal
    with pytest.raises(RuntimeError, match="COW violation"):
        eng._check_cow_locked(slot, 15)        # inside shared page 1
    with pytest.raises(RuntimeError, match="COW violation"):
        eng._check_cow_locked(slot, 0)


def test_prefix_metrics_exported(tiny_model):
    model, params = tiny_model
    from ray_tpu.util import metrics
    eng = LLMEngine(model, params, max_slots=2, page_size=8,
                    n_pages=32, chunk=4, prefix_cache=True)
    prefix = list(range(1, 17))
    eng.submit(prefix + [9], max_new_tokens=4)
    _drain(eng)
    eng.submit(prefix + [8], max_new_tokens=4)
    _drain(eng)
    text = metrics.prometheus_text()
    assert "serve_prefix_cache_hit_tokens" in text
    assert "serve_prefix_cache_miss_tokens" in text
    assert "serve_prefix_cache_pages" in text
    st = eng.prefix_stats()
    assert st["hit_tokens"] == 16
    assert st["cached_pages"] >= 2


def test_cache_off_by_default(tiny_model):
    model, params = tiny_model
    eng = LLMEngine(model, params, max_slots=2, page_size=8,
                    n_pages=32, chunk=4)
    assert eng.prefix_cache is None
    assert eng.prefix_stats() is None
    h = eng.submit([1, 2, 3], max_new_tokens=4)
    _drain(eng)
    assert h.result() == _reference_completion(model, params,
                                               [1, 2, 3], 4)
    # legacy accounting: everything back on the free list
    assert eng.alloc.n_free == eng.alloc.n_pages - 1


# ------------------------------------------- digest advertisement cap


def _digest_fixture():
    """Three disjoint chains: A is 4 pages deep, B is 2, C is 1."""
    from ray_tpu.serve.prefix_cache import path_hashes
    alloc = BlockAllocator(32)
    pc = PrefixCache(alloc, page_size=4)
    A = [1] * 4 + [2] * 4 + [3] * 4 + [4] * 4
    B = [5] * 4 + [6] * 4
    C = [7] * 4
    for toks, n in ((A, 4), (B, 2), (C, 1)):
        pc.insert(toks, alloc.alloc(n), n_shared=0)
    hA = frozenset(path_hashes(A, 4))
    hB = frozenset(path_hashes(B, 4))
    hC = frozenset(path_hashes(C, 4))
    return pc, hA, hB, hC


def test_digest_cap_is_prefix_closed_longest_first():
    """The bounded advertisement keeps whole root->node paths,
    longest prefix first, backfilling with shorter paths that still
    fit — never a deep node without its ancestors (which affinity
    matching, walking root-first, could not see at all)."""
    from ray_tpu.serve.prefix_cache import path_hashes
    pc, hA, hB, hC = _digest_fixture()
    assert pc.digest() == hA | hB | hC            # uncapped: all
    assert pc.digest(7) == hA | hB | hC           # cap >= nodes: all
    assert pc.digest(4) == hA                     # deepest path wins
    # budget 5: B's 2-hash path no longer fits after A; the 1-hash
    # C path backfills instead of wasting the slot
    assert pc.digest(5) == hA | hC
    assert pc.digest(6) == hA | hB                # next-deepest fits
    assert pc.digest(0) == frozenset()
    # every capped advertisement is PREFIX-CLOSED: each kept hash's
    # whole root path is kept too
    chains = {tuple(path_hashes(t, 4)) for t in
              ([1] * 4 + [2] * 4 + [3] * 4 + [4] * 4,
               [5] * 4 + [6] * 4, [7] * 4)}
    for limit in range(8):
        d = pc.digest(limit)
        assert len(d) <= limit
        for chain in chains:
            for i, h in enumerate(chain):
                if h in d:
                    assert set(chain[:i]) <= d, (
                        f"limit {limit}: hash at depth {i} kept "
                        f"without its ancestors")


def test_digest_cap_prefers_hotter_chain_on_depth_tie():
    from ray_tpu.serve.prefix_cache import path_hashes
    alloc = BlockAllocator(16)
    pc = PrefixCache(alloc, page_size=4)
    D = [11] * 4 + [12] * 4
    E = [13] * 4 + [14] * 4
    pc.insert(D, alloc.alloc(2), n_shared=0)
    pc.insert(E, alloc.alloc(2), n_shared=0)
    # equal depth; E inserted later so it starts hotter
    assert pc.digest(2) == frozenset(path_hashes(E, 4))
    # touching D (a cache hit) makes it the hotter chain
    got, _ = pc.match(D)
    pc.release(got)
    assert pc.digest(2) == frozenset(path_hashes(D, 4))


def test_engine_load_report_bounds_digest(tiny_model):
    model, params = tiny_model
    eng = LLMEngine(model, params, max_slots=2, page_size=8,
                    n_pages=32, chunk=4, temperature=0.0,
                    prefix_cache=True, prefix_digest_max=2)
    try:
        h = eng.submit(list(range(1, 41)), max_new_tokens=2)
        _drain(eng)
        h.result()
        assert eng.prefix_cache.cached_pages > 2
        rpt = eng.load_report()
        digest = rpt["prefix_digest"]
        assert len(digest) == 2
        # the bounded digest is the prompt's LEADING pages — the
        # prefix-closed head, not an arbitrary sample
        from ray_tpu.serve.prefix_cache import path_hashes
        assert digest == frozenset(
            path_hashes(list(range(1, 41)), 8)[:2])
    finally:
        eng.shutdown()
