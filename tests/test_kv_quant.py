"""Int8 paged KV cache: quantization contract, scale lifecycle, and
engine-level tolerance parity.

Op level (ops/paged_attention.py): symmetric absmax int8 round-trips
within scale/254 per element, per-PAGE scales isolate magnitude across
page boundaries, the reset-on-offset-0 rule retires a freed page's
stale scale with no host bookkeeping, spec-rollback garbage past
``pos`` is precision-only (masked at read, never attended), and the
pallas kernel (interpret mode) dequantizes in-register to the same
numbers as the gather fallback.

Engine level (serve/engine.py kv_dtype="int8"): deterministic given a
write history (same engine + load twice -> identical tokens; prefix
hits replay the SAME quantized bytes -> identical tokens), tolerance-
equal vs fp (token agreement gated at the same floor the kvq A/B
artifact records — quantized bytes are write-history dependent, see
docs/serving.md), spec accept-rate preserved, tp-sharded pools with
scale columns pinned alongside their heads, and the bytes view
(kv_pool_page_bytes -> BlockAllocator -> load_report -> gauge).
"""
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from ray_tpu.models.kv_cache import (BlockAllocator, init_kv_pool,
                                     kv_layer_store, kv_layer_view,
                                     kv_pool_page_bytes, PagedKVLayer)
from ray_tpu.models.llama import Llama, llama_tiny
from ray_tpu.ops.paged_attention import (dequantize_pages,
                                         paged_append,
                                         paged_decode_attention,
                                         PagedShapeError)
from ray_tpu.serve.engine import LLMEngine
from ray_tpu.serve.faults import check_quiesced
from ray_tpu.util.envknobs import (EnvKnobError, parse_kv_dtype_env,
                                   parse_paged_kernel_env,
                                   resolve_kv_dtype)

KH, PG, D = 2, 8, 16


def _fresh(n_pages=8, B=1, max_pages=4):
    pk = jnp.zeros((KH, n_pages, PG, D), jnp.int8)
    pv = jnp.zeros((KH, n_pages, PG, D), jnp.int8)
    sk = jnp.zeros((KH, n_pages, 1), jnp.float32)
    sv = jnp.zeros((KH, n_pages, 1), jnp.float32)
    pt = jnp.asarray(
        np.arange(1, 1 + B * max_pages).reshape(B, max_pages),
        jnp.int32)
    return pk, pv, sk, sv, pt


def _kv(rng, B, T, scale=1.0):
    k = (rng.standard_normal((B, T, KH, D)) * scale).astype(np.float32)
    v = (rng.standard_normal((B, T, KH, D)) * scale).astype(np.float32)
    return jnp.asarray(k), jnp.asarray(v)


# ------------------------------------------------- quantize round-trip

def test_bulk_roundtrip_within_half_step():
    rng = np.random.default_rng(0)
    pk, pv, sk, sv, pt = _fresh()
    k, v = _kv(rng, 1, 2 * PG)            # fills pages 1 and 2
    pk, pv, sk, sv = paged_append(pk, pv, pt, jnp.zeros(1, jnp.int32),
                                  k, v, sk, sv)
    deq = np.asarray(dequantize_pages(pk, sk))
    ref = np.asarray(k)[0].transpose(1, 0, 2)      # [KH, T, D]
    for page, lo in ((1, 0), (2, PG)):
        # int8 rounding error is at most half a quantization step:
        # scale (= page absmax) / 254 per element
        tol = np.asarray(sk)[:, page] / 254.0 + 1e-6
        err = np.abs(deq[:, page] - ref[:, lo:lo + PG])
        assert (err <= tol[..., None]).all()


def test_per_page_scales_isolate_magnitude():
    # A huge page must not destroy a small page's resolution: that is
    # the entire point of per-PAGE (not per-pool) scales.
    rng = np.random.default_rng(1)
    pk, pv, sk, sv, pt = _fresh()
    k_big, v_big = _kv(rng, 1, PG, scale=100.0)
    k_small, v_small = _kv(rng, 1, PG, scale=0.01)
    k = jnp.concatenate([k_big, k_small], axis=1)   # spans 2 pages
    v = jnp.concatenate([v_big, v_small], axis=1)
    pk, pv, sk, sv = paged_append(pk, pv, pt, jnp.zeros(1, jnp.int32),
                                  k, v, sk, sv)
    sk_np = np.asarray(sk)
    assert (sk_np[:, 1] > 1.0).all()       # big page's absmax
    assert (sk_np[:, 2] < 0.1).all()       # small page kept its own
    deq = np.asarray(dequantize_pages(pk, sk))
    small_ref = np.asarray(k_small)[0].transpose(1, 0, 2)
    err = np.abs(deq[:, 2] - small_ref)
    # resolution follows the SMALL page's scale; under one shared
    # scale the error would be ~100/254, four orders worse
    assert err.max() <= sk_np[:, 2].max() / 254.0 + 1e-7


def test_incremental_scale_matches_bulk_and_is_monotone():
    rng = np.random.default_rng(2)
    pk, pv, sk, sv, pt = _fresh()
    k, v = _kv(rng, 1, PG)
    bk, bv, bsk, bsv = paged_append(pk, pv, pt,
                                    jnp.zeros(1, jnp.int32), k, v,
                                    sk, sv)
    ik, iv, isk, isv = pk, pv, sk, sv
    last = np.zeros((KH, 1))
    for t in range(PG):
        ik, iv, isk, isv = paged_append(
            ik, iv, pt, jnp.full((1,), t, jnp.int32),
            k[:, t:t + 1], v[:, t:t + 1], isk, isv)
        cur = np.asarray(isk)[:, 1]
        assert (cur >= last - 1e-7).all()  # monotone while page live
        last = cur
    # same tokens -> same final absmax, both build orders
    np.testing.assert_allclose(np.asarray(isk), np.asarray(bsk),
                               rtol=1e-6)
    # BYTES may differ (write-history dependent re-rounding: the
    # incremental build re-codes earlier tokens at each scale growth,
    # double-rounding them) but values stay within one extra step
    deq_b = np.asarray(dequantize_pages(bk, bsk))[:, 1]
    deq_i = np.asarray(dequantize_pages(ik, isk))[:, 1]
    step = np.asarray(bsk)[:, 1][..., None] / 127.0
    assert (np.abs(deq_b - deq_i) <= 1.5 * step + 1e-7).all()


def test_scale_resets_on_offset_zero_rewrite():
    # Allocator reuses page ids: the first write a fresh LOGICAL page
    # receives is always at offset 0, which must retire the previous
    # owner's scale — no host-side bookkeeping exists to do it.
    rng = np.random.default_rng(3)
    pk, pv, sk, sv, pt = _fresh()
    k_big, v_big = _kv(rng, 1, PG, scale=50.0)
    pk, pv, sk, sv = paged_append(pk, pv, pt, jnp.zeros(1, jnp.int32),
                                  k_big, v_big, sk, sv)
    assert np.asarray(sk)[:, 1].max() > 10.0
    k_small, v_small = _kv(rng, 1, PG, scale=0.02)
    pk, pv, sk, sv = paged_append(pk, pv, pt, jnp.zeros(1, jnp.int32),
                                  k_small, v_small, sk, sv)
    sk_np = np.asarray(sk)
    assert sk_np[:, 1].max() < 0.1         # old owner's scale is gone
    deq = np.asarray(dequantize_pages(pk, sk))[:, 1]
    ref = np.asarray(k_small)[0].transpose(1, 0, 2)
    assert np.abs(deq - ref).max() <= sk_np[:, 1].max() / 254.0 + 1e-7


def test_mid_page_append_grows_scale_without_reset():
    # A mid-page append (offset != 0) must KEEP earlier tokens
    # representable: scale grows, earlier bytes are re-coded.
    rng = np.random.default_rng(4)
    pk, pv, sk, sv, pt = _fresh()
    k1, v1 = _kv(rng, 1, 4, scale=0.5)
    pk, pv, sk, sv = paged_append(pk, pv, pt, jnp.zeros(1, jnp.int32),
                                  k1, v1, sk, sv)
    s1 = np.asarray(sk)[:, 1].copy()
    k2, v2 = _kv(rng, 1, 4, scale=20.0)    # same page, offsets 4..7
    pk, pv, sk, sv = paged_append(pk, pv, pt,
                                  jnp.full((1,), 4, jnp.int32),
                                  k2, v2, sk, sv)
    s2 = np.asarray(sk)[:, 1]
    assert (s2 >= s1 - 1e-7).all() and s2.max() > 5.0
    deq = np.asarray(dequantize_pages(pk, sk))[:, 1, :4]
    ref = np.asarray(k1)[0].transpose(1, 0, 2)
    # earlier tokens survived the re-code at the grown scale: error
    # is one step of the NEW scale (coarser, but never garbage)
    assert np.abs(deq - ref).max() <= s2.max() / 127.0 + 1e-6


# ------------------------------------- masking, kernel, shape errors

def _dense_ref_deq(q, pk, sk, pv, sv, pt, pos):
    kg = np.asarray(dequantize_pages(pk, sk))
    vg = np.asarray(dequantize_pages(pv, sv))
    B, H, Dh = q.shape
    kh = kg.shape[0]
    L = pt.shape[1] * pk.shape[2]
    kq = kg[:, np.asarray(pt)].reshape(kh, B, L, Dh)
    vq = vg[:, np.asarray(pt)].reshape(kh, B, L, Dh)
    qg = np.asarray(q).reshape(B, kh, H // kh, Dh).astype(np.float32)
    s = np.einsum("bkrd,kbsd->bkrs", qg, kq) / np.sqrt(Dh)
    valid = np.arange(L)[None] <= np.asarray(pos)[:, None]
    s = np.where(valid[:, None, None, :], s, -1e30)
    s = s - s.max(axis=-1, keepdims=True)
    p = np.exp(s)
    p /= p.sum(axis=-1, keepdims=True)
    return np.einsum("bkrs,kbsd->bkrd", p, vq).reshape(B, H, Dh)


def test_rollback_garbage_is_masked_and_precision_only():
    # Spec rollback is a position clamp: rejected drafts stay in the
    # pool past ``pos``. They may inflate the page scale (precision)
    # but must never be ATTENDED (correctness).
    rng = np.random.default_rng(5)
    pk, pv, sk, sv, pt = _fresh()
    n_real = 6
    k, v = _kv(rng, 1, n_real)
    pk, pv, sk, sv = paged_append(pk, pv, pt, jnp.zeros(1, jnp.int32),
                                  k, v, sk, sv)
    kg, vg = _kv(rng, 1, 2, scale=30.0)    # rejected drafts, big
    pk2, pv2, sk2, sv2 = paged_append(
        pk, pv, pt, jnp.full((1,), n_real, jnp.int32), kg, vg,
        sk, sv)
    assert np.asarray(sk2)[:, 1].max() > np.asarray(sk)[:, 1].max()
    q = jnp.asarray(rng.standard_normal((1, 2 * KH, D)),
                    jnp.float32)
    pos = jnp.full((1,), n_real - 1, jnp.int32)
    out = np.asarray(paged_decode_attention(q, pk2, pv2, pt, pos,
                                            sk2, sv2,
                                            interpret=True))
    # reference over the dequantized REAL window of the garbage pool:
    # the garbage positions are masked, so only the re-rounding of
    # the real tokens (scale growth) can move the output
    ref = _dense_ref_deq(q, pk2, sk2, pv2, sv2, pt, pos)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)
    # and vs the garbage-free pool: bounded by one re-rounding step
    clean = _dense_ref_deq(q, pk, sk, pv, sv, pt, pos)
    assert np.abs(out - clean).max() < 0.5


def test_kernel_matches_gather_dequant_int8():
    rng = np.random.default_rng(6)
    B, max_pages, n_pages = 3, 4, 32
    pk = jnp.asarray(rng.integers(-127, 128, (KH, n_pages, PG, D)),
                     jnp.int8)
    pv = jnp.asarray(rng.integers(-127, 128, (KH, n_pages, PG, D)),
                     jnp.int8)
    sk = jnp.asarray(rng.uniform(0.1, 2.0, (KH, n_pages, 1)),
                     jnp.float32)
    sv = jnp.asarray(rng.uniform(0.1, 2.0, (KH, n_pages, 1)),
                     jnp.float32)
    pt = jnp.asarray(rng.permutation(n_pages - 1)[:B * max_pages]
                     .reshape(B, max_pages) + 1, jnp.int32)
    pos = jnp.asarray(rng.integers(0, max_pages * PG, B), jnp.int32)
    q = jnp.asarray(rng.standard_normal((B, 2 * KH, D)), jnp.float32)
    out = np.asarray(paged_decode_attention(q, pk, pv, pt, pos,
                                            sk, sv, interpret=True))
    ref = _dense_ref_deq(q, pk, sk, pv, sv, pt, pos)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_shape_errors():
    rng = np.random.default_rng(7)
    pk, pv, sk, sv, pt = _fresh()
    k, v = _kv(rng, 1, 2)
    pos = jnp.zeros(1, jnp.int32)
    with pytest.raises(PagedShapeError, match="without its per-page"):
        paged_append(pk, pv, pt, pos, k, v)     # int8 pool, no scales
    with pytest.raises(PagedShapeError, match="supplied together"):
        paged_append(pk, pv, pt, pos, k, v, sk, None)
    with pytest.raises(PagedShapeError):
        paged_append(pk, pv, pt, pos, k, v,     # bad scale shape
                     sk[:, :, 0], sv[:, :, 0])
    fpk = jnp.zeros(pk.shape, jnp.float32)
    with pytest.raises(PagedShapeError, match="int8"):
        paged_append(fpk, fpk, pt, pos, k, v, sk, sv)


# --------------------------------------------- pool shapes and bytes

def test_init_pool_shapes_and_layer_views():
    cfg = llama_tiny()
    pool = init_kv_pool(cfg, n_pages=16, page_size=8,
                        kv_dtype="int8")
    assert len(pool) == cfg.n_layers
    pk, pv, sk, sv = pool[0]
    assert pk.dtype == jnp.int8 and pv.dtype == jnp.int8
    assert sk.shape == (cfg.n_kv_heads, 16, 1)
    assert sk.dtype == jnp.float32
    pt = jnp.zeros((2, 4), jnp.int32)
    cache = kv_layer_view(pool[0], pt)
    assert isinstance(cache, PagedKVLayer) and cache.quantized
    assert kv_layer_store(cache) == pool[0]
    fp = init_kv_pool(cfg, n_pages=16, page_size=8)
    assert len(fp[0]) == 2                 # fp pytree layout unchanged
    fpc = kv_layer_view(fp[0], pt)
    assert not fpc.quantized and fpc.scales_k is None
    with pytest.raises(ValueError):
        init_kv_pool(cfg, 16, 8, kv_dtype="int4")


def test_page_bytes_ratio_funds_the_capacity_claim():
    cfg = llama_tiny()                     # bf16 pages
    fp = kv_pool_page_bytes(cfg, 8, "fp")
    q = kv_pool_page_bytes(cfg, 8, "int8")
    # bf16: 2 bytes payload; int8: 1 byte + 2*KH fp32 scales/layer
    assert fp == cfg.n_layers * 2 * cfg.n_kv_heads * 8 * cfg.head_dim * 2
    assert q == cfg.n_layers * (
        2 * cfg.n_kv_heads * 8 * cfg.head_dim + 2 * cfg.n_kv_heads * 4)
    assert fp / q >= 1.9                   # the kvq A/B schema gate


def test_allocator_bytes_view():
    a = BlockAllocator(8)
    assert a.bytes_in_use() is None and a.bytes_total() is None
    a = BlockAllocator(8, page_bytes=100)
    assert a.bytes_total() == 800          # null page is real memory
    pages = a.alloc(3)
    assert a.bytes_in_use() == 300
    a.free(pages)
    assert a.bytes_in_use() == 0


# ------------------------------------------------------ env knobs

def test_env_knobs_reject_junk(monkeypatch):
    monkeypatch.setenv("RAY_TPU_KV_DTYPE", "bogus")
    with pytest.raises(EnvKnobError) as ei:
        parse_kv_dtype_env()
    assert ei.value.name == "RAY_TPU_KV_DTYPE"
    monkeypatch.setenv("RAY_TPU_PAGED_KERNEL", "yes")
    with pytest.raises(EnvKnobError):
        parse_paged_kernel_env()
    monkeypatch.setenv("RAY_TPU_PAGED_KERNEL", "1")
    assert parse_paged_kernel_env() is True
    monkeypatch.setenv("RAY_TPU_PAGED_KERNEL", "")
    assert parse_paged_kernel_env() is False


def test_kv_dtype_resolution_precedence(monkeypatch):
    monkeypatch.delenv("RAY_TPU_KV_DTYPE", raising=False)
    assert resolve_kv_dtype(None) == "fp"
    assert resolve_kv_dtype("int8") == "int8"
    monkeypatch.setenv("RAY_TPU_KV_DTYPE", "int8")
    assert resolve_kv_dtype("fp") == "int8"     # env wins over arg
    monkeypatch.setenv("RAY_TPU_KV_DTYPE", "")
    assert resolve_kv_dtype("int8") == "int8"   # empty = unset
    with pytest.raises(ValueError):             # bad ARG: plain error
        resolve_kv_dtype("fp16")
    monkeypatch.setenv("RAY_TPU_KV_DTYPE", "int4")
    with pytest.raises(EnvKnobError):           # bad ENV: typed error
        resolve_kv_dtype(None)


# ----------------------------------------------------- engine level

@pytest.fixture(scope="module")
def tiny():
    cfg = llama_tiny(dtype=jnp.float32)
    model = Llama(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    return cfg, model, params


def _engine(tiny, **kw):
    _, model, params = tiny
    opts = dict(max_slots=4, page_size=8, n_pages=64, chunk=4,
                prefill_chunk=16, temperature=0.0, seed=0,
                eos_id=-1, overlap=False)
    opts.update(kw)
    return LLMEngine(model, params, **opts)


def _run(eng, prompts, n=12):
    hs = [eng.submit(list(p), max_new_tokens=n) for p in prompts]
    while eng.step():
        pass
    return [h.result() for h in hs]


def _prompts(cfg, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, cfg.vocab_size - 1, size=10).tolist()
            for _ in range(4)]


def test_engine_int8_deterministic(tiny):
    cfg = tiny[0]
    outs = []
    for _ in range(2):
        eng = _engine(tiny, kv_dtype="int8")
        outs.append(_run(eng, _prompts(cfg)[:2], n=8))
        eng.shutdown()
    assert outs[0] == outs[1]


def test_engine_int8_fp_token_agreement(tiny):
    # tolerance parity: the same floor the kvq A/B artifact records.
    # A random-weight 256-vocab model is the WORST case (near-uniform
    # logits, flips compound down the stream); real checkpoints with
    # peaked logits agree far higher.
    cfg = tiny[0]
    eng = _engine(tiny)
    fp = _run(eng, _prompts(cfg), n=16)
    eng.shutdown()
    eng = _engine(tiny, kv_dtype="int8")
    q = _run(eng, _prompts(cfg), n=16)
    eng.shutdown()
    total = sum(len(o) for o in fp)
    agree = sum(x == y for a, b in zip(fp, q) for x, y in zip(a, b))
    assert agree / total >= 0.8, (agree, total)


def test_prefix_hit_replays_identical_quantized_pages(tiny):
    # A radix-cache hit REUSES the quantized bytes + scale columns
    # the first request wrote (COW copies the scale column with the
    # page), so the replay is bit-exact — not merely tolerance-equal.
    cfg = tiny[0]
    rng = np.random.RandomState(7)
    prompt = rng.randint(1, cfg.vocab_size - 1, size=24).tolist()
    eng = _engine(tiny, kv_dtype="int8", prefix_cache=True)
    first = _run(eng, [prompt], n=12)[0]
    assert eng.prefix_stats()["cached_pages"] > 0
    second = _run(eng, [prompt], n=12)[0]
    assert eng.prefix_stats()["hit_tokens"] > 0
    assert first == second
    check_quiesced(eng)
    eng.shutdown()


def test_int8_eviction_under_pressure_leak_free(tiny):
    # Small pool + many distinct prefixes: eviction must cycle
    # quantized pages through free/realloc (scale reset-on-offset-0
    # is what keeps reused pages honest) and quiesce leak-free.
    cfg = tiny[0]
    rng = np.random.RandomState(11)
    prompts = [rng.randint(1, cfg.vocab_size - 1, size=20).tolist()
               for _ in range(6)]
    # 11 usable pages: each request transiently needs 4 (32 tokens)
    # and retires 2 into the cache, so request 5 must evict
    eng = _engine(tiny, kv_dtype="int8", n_pages=12, max_slots=2,
                  prefix_cache=True)
    first = _run(eng, [prompts[0]], n=12)[0]
    for p in prompts[1:]:
        _run(eng, [p], n=12)
    assert eng.prefix_stats()["evictions"] > 0
    # re-run prompt 0 after its pages were evicted: a fresh prefill
    # replays the identical write history -> identical tokens
    again = _run(eng, [prompts[0]], n=12)[0]
    assert again == first
    check_quiesced(eng)
    eng.shutdown()


def test_spec_accept_rate_survives_int8(tiny):
    # Self-consistency gate: each arm's proposer drafts from its OWN
    # stream and its verify re-derives its OWN argmax — int8 rounding
    # must not break that loop (noise bound matches the kvq artifact)
    def accept(dt):
        eng = _engine(tiny, kv_dtype=dt, spec_len=4, max_slots=2)
        h = eng.submit([5, 6, 7, 8] * 5, max_new_tokens=40)
        while eng.step():
            pass
        h.result()
        sp = eng.spec_stats()
        eng.shutdown()
        assert sp["rounds"] > 0            # speculation engaged
        return sp["accept_rate"]

    fp, q = accept(None), accept("int8")
    assert q >= fp - 0.15, (fp, q)


def test_int8_load_report_bytes_and_gauge(tiny):
    from ray_tpu.serve.engine import KV_BYTES_TOTAL
    from ray_tpu.util import metrics
    cfg = tiny[0]
    eng = _engine(tiny, kv_dtype="int8", n_pages=32)
    rpt = eng.load_report()
    assert rpt["kv_dtype"] == "int8"
    assert rpt["kv_page_bytes"] == kv_pool_page_bytes(cfg, 8, "int8")
    assert rpt["kv_bytes_total"] == 32 * rpt["kv_page_bytes"]
    assert rpt["kv_bytes_in_use"] == 0
    _run(eng, _prompts(cfg))
    assert KV_BYTES_TOTAL in metrics.prometheus_text()
    eng.shutdown()


def test_tp4_int8_agreement(tiny, cpu_mesh_devices):
    # int8 under tensor parallelism: pools shard on the head axis,
    # scale columns ride P("tensor", None, None) beside their heads.
    # tp=4 reduction order perturbs pre-quantization activations, so
    # the gate is agreement, not identity (unlike fp tp A/B).
    from ray_tpu.serve.sharding import EngineSharding
    cfg = llama_tiny(n_kv_heads=4, dtype=jnp.float32)
    model = Llama(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    rng = np.random.RandomState(3)
    prompts = [rng.randint(1, cfg.vocab_size - 1, size=12).tolist()
               for _ in range(4)]

    def run(sh):
        eng = LLMEngine(model, params, max_slots=4, page_size=8,
                        n_pages=64, chunk=4, prefill_chunk=16,
                        temperature=0.0, seed=0, eos_id=-1,
                        overlap=False, kv_dtype="int8", sharding=sh)
        outs = _run(eng, prompts, n=12)
        eng.shutdown()
        return outs

    tp1 = run(None)
    tp4 = run(EngineSharding.build(cfg, tp=4,
                                   devices=cpu_mesh_devices[:4]))
    total = sum(len(o) for o in tp1)
    agree = sum(x == y for a, b in zip(tp1, tp4)
                for x, y in zip(a, b))
    assert agree / total >= 0.9, (agree, total)


def test_engine_env_kv_dtype_override(tiny, monkeypatch):
    monkeypatch.setenv("RAY_TPU_KV_DTYPE", "int8")
    eng = _engine(tiny, kv_dtype="fp")
    assert eng.kv_dtype == "int8"          # env wins over kwarg
    eng.shutdown()
    monkeypatch.setenv("RAY_TPU_KV_DTYPE", "int4")
    with pytest.raises(EnvKnobError):
        _engine(tiny)
