"""Head fault tolerance (VERDICT r2 #6): the head process is killed and
restarted at the same address from its persisted snapshot; workers
re-attach via heartbeats, named actors resolve with their in-worker
state intact, KV survives, and work keeps flowing (reference: GCS
restart with Redis-persisted tables, gcs/gcs_table_storage.h:261,
store_client/redis_store_client.h:28)."""
import time

import pytest

import ray_tpu
from ray_tpu.runtime import Cluster


@pytest.fixture()
def cluster():
    import ray_tpu._private.worker as worker_mod
    if worker_mod.is_initialized():
        worker_mod.shutdown()
    c = Cluster(num_workers=2, resources_per_worker={"CPU": 4})
    yield c
    c.shutdown()


def _retry(fn, timeout=30.0):
    deadline = time.time() + timeout
    while True:
        try:
            return fn()
        except Exception:
            if time.time() > deadline:
                raise
            time.sleep(0.25)


def test_head_restart_recovers_actors_kv_and_tasks(cluster):
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

    c = Counter.options(name="survivor").remote()
    assert ray_tpu.get(c.inc.remote()) == 1
    assert ray_tpu.get(c.inc.remote()) == 2
    cluster.runtime.head.call("kv_put", "persist-key",
                              b"persist-value")

    # Let the debounced snapshot land.
    time.sleep(0.8)

    # Kill + restart the head at the same address.
    cluster.node.restart_head()

    # Workers re-attach within ~1 heartbeat; KV restored from snapshot.
    assert _retry(lambda: cluster.runtime.head.call(
        "kv_get", "persist-key")) == b"persist-value"

    # The named actor resolves on the restarted head and its IN-WORKER
    # state survived (the worker process never died).
    h = _retry(lambda: ray_tpu.get_actor("survivor"))
    assert _retry(lambda: ray_tpu.get(h.inc.remote(), timeout=30)) == 3

    # New tasks flow through the recovered scheduler.
    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert _retry(lambda: ray_tpu.get(add.remote(20, 22),
                                      timeout=30)) == 42


def test_training_style_actor_survives_head_restart(cluster):
    """An actor mid 'training' (stateful stepping) keeps its progress
    across a head restart — the gang-keeps-training property at actor
    granularity (the compute loop lives in worker processes and never
    depends on head liveness)."""
    @ray_tpu.remote
    class Stepper:
        def __init__(self):
            self.steps = 0

        def step_many(self, k):
            for _ in range(k):
                self.steps += 1
            return self.steps

    s = Stepper.options(name="trainer").remote()
    assert ray_tpu.get(s.step_many.remote(5)) == 5
    time.sleep(0.8)            # snapshot
    cluster.node.restart_head()
    h = _retry(lambda: ray_tpu.get_actor("trainer"))
    # Progress resumes exactly where it was: 5 + 7.
    assert _retry(lambda: ray_tpu.get(h.step_many.remote(7),
                                      timeout=30)) == 12
