"""Actor semantics tests (reference analogues:
python/ray/tests/test_actor.py, test_actor_failures.py,
test_asyncio_actor.py)."""
import asyncio
import time

import pytest

import ray_tpu
from ray_tpu.exceptions import ActorDiedError, TaskError


def test_basic_actor(rt):
    @rt.remote
    class Counter:
        def __init__(self, start=0):
            self.n = start

        def inc(self, by=1):
            self.n += by
            return self.n

    c = Counter.remote(10)
    assert rt.get(c.inc.remote()) == 11
    assert rt.get(c.inc.remote(5)) == 16


def test_actor_call_ordering(rt):
    @rt.remote
    class Appender:
        def __init__(self):
            self.items = []

        def add(self, x):
            self.items.append(x)

        def snapshot(self):
            return list(self.items)

    a = Appender.remote()
    for i in range(50):
        a.add.remote(i)
    assert rt.get(a.snapshot.remote()) == list(range(50))


def test_actor_method_exception_does_not_kill(rt):
    @rt.remote
    class Fragile:
        def bad(self):
            raise RuntimeError("oops")

        def good(self):
            return "fine"

    f = Fragile.remote()
    with pytest.raises(TaskError):
        rt.get(f.bad.remote())
    assert rt.get(f.good.remote()) == "fine"


def test_actor_init_failure(rt):
    @rt.remote
    class Broken:
        def __init__(self):
            raise ValueError("cannot construct")

        def ping(self):
            return 1

    b = Broken.remote()
    with pytest.raises(ActorDiedError):
        rt.get(b.ping.remote(), timeout=5)


def test_kill_actor(rt):
    @rt.remote
    class Victim:
        def ping(self):
            return "pong"

    v = Victim.remote()
    assert rt.get(v.ping.remote()) == "pong"
    rt.kill(v)
    with pytest.raises(ActorDiedError):
        rt.get(v.ping.remote(), timeout=5)


def test_actor_restart(rt):
    @rt.remote(max_restarts=2)
    class Phoenix:
        def __init__(self):
            self.state = 0

        def set(self, v):
            self.state = v

        def get(self):
            return self.state

    p = Phoenix.remote()
    rt.get(p.set.remote(42))
    assert rt.get(p.get.remote()) == 42
    # Simulate a crash (not an intentional kill): restart policy applies,
    # state resets.
    rt.kill(p, no_restart=False)
    time.sleep(0.2)
    assert rt.get(p.get.remote(), timeout=5) == 0


def test_named_actor(rt):
    @rt.remote
    class Registry:
        def whoami(self):
            return "registry"

    Registry.options(name="the-registry").remote()
    h = rt.get_actor("the-registry")
    assert rt.get(h.whoami.remote()) == "registry"
    with pytest.raises(ValueError):
        rt.get_actor("missing")


def test_named_actor_duplicate_rejected(rt):
    @rt.remote
    class A:
        def ping(self):
            return 1

    A.options(name="dup").remote()
    with pytest.raises(ValueError):
        A.options(name="dup").remote()


def test_get_if_exists(rt):
    @rt.remote
    class Singleton:
        def __init__(self):
            self.t = time.time()

        def created_at(self):
            return self.t

    a = Singleton.options(name="s", get_if_exists=True).remote()
    b = Singleton.options(name="s", get_if_exists=True).remote()
    assert rt.get(a.created_at.remote()) == rt.get(b.created_at.remote())


def test_actor_handle_passing(rt):
    @rt.remote
    class Store:
        def __init__(self):
            self.v = None

        def set(self, v):
            self.v = v

        def get(self):
            return self.v

    @rt.remote
    def writer(store, value):
        return ray_tpu.get(store.set.remote(value))

    s = Store.remote()
    rt.get(writer.remote(s, "written-by-task"))
    assert rt.get(s.get.remote()) == "written-by-task"


def test_async_actor(rt):
    @rt.remote
    class AsyncWorker:
        async def work(self, x):
            await asyncio.sleep(0.01)
            return x * 2

    w = AsyncWorker.remote()
    refs = [w.work.remote(i) for i in range(10)]
    assert rt.get(refs) == [i * 2 for i in range(10)]


def test_async_actor_concurrency(rt):
    @rt.remote
    class Sleeper:
        async def nap(self):
            await asyncio.sleep(0.2)
            return 1

    s = Sleeper.remote()
    start = time.time()
    refs = [s.nap.remote() for _ in range(10)]
    assert sum(rt.get(refs)) == 10
    # Concurrent: 10 naps of 0.2s must not serialize to 2s.
    assert time.time() - start < 1.5


def test_threaded_actor_max_concurrency(rt):
    @rt.remote(max_concurrency=4)
    class Parallel:
        def block(self):
            time.sleep(0.2)
            return 1

    p = Parallel.remote()
    start = time.time()
    assert sum(rt.get([p.block.remote() for _ in range(4)])) == 4
    assert time.time() - start < 0.7  # ran in parallel


def test_actor_num_restarts_visible_in_state(rt):
    @rt.remote(max_restarts=1)
    class R:
        def ping(self):
            return 1

    r = R.remote()
    rt.get(r.ping.remote())
    runtime = ray_tpu._private.worker.global_worker().runtime
    rt.kill(r, no_restart=False)
    time.sleep(0.2)
    rt.get(r.ping.remote(), timeout=5)
    actors = runtime.list_actors()
    assert any(a["num_restarts"] == 1 for a in actors)


def test_concurrency_groups_sync_actor(rt):
    """Methods in different groups run concurrently with per-group
    limits; within a group FIFO order holds (reference: actor
    concurrency groups)."""
    import threading
    import time as _time

    @ray_tpu.remote(concurrency_groups={"io": 2, "compute": 1})
    class Worker:
        def __init__(self):
            self.active_io = 0
            self.peak_io = 0
            self.lock = threading.Lock()

        @ray_tpu.method(concurrency_group="io")
        def io_task(self):
            with self.lock:
                self.active_io += 1
                self.peak_io = max(self.peak_io, self.active_io)
            _time.sleep(0.15)
            with self.lock:
                self.active_io -= 1
            return "io"

        @ray_tpu.method(concurrency_group="compute")
        def compute_task(self, i):
            return i

        def default_task(self):
            return "default"

        def peak(self):
            return self.peak_io

    w = Worker.remote()
    refs = [w.io_task.remote() for _ in range(4)]
    assert ray_tpu.get(refs, timeout=30) == ["io"] * 4
    # the peak-concurrency counter proves group parallelism without
    # wall-clock assertions (which flake on loaded machines)
    assert ray_tpu.get(w.peak.remote(), timeout=10) == 2
    # compute group (size 1) stays ordered
    assert ray_tpu.get([w.compute_task.remote(i) for i in range(5)],
                       timeout=10) == list(range(5))
    assert ray_tpu.get(w.default_task.remote(), timeout=10) == \
        "default"
    # per-call override routes into a declared group
    assert ray_tpu.get(
        w.default_task.options(concurrency_group="io").remote(),
        timeout=10) == "default"


def test_concurrency_group_unknown_rejected(rt):
    @ray_tpu.remote(concurrency_groups={"io": 1})
    class A:
        def f(self):
            return 1

    a = A.remote()
    with pytest.raises(ValueError, match="no concurrency group"):
        a.f.options(concurrency_group="nope").remote()


def test_concurrency_groups_async_actor(rt):
    import time as _time

    @ray_tpu.remote(concurrency_groups={"slow": 2})
    class AsyncA:
        @ray_tpu.method(concurrency_group="slow")
        async def slow(self):
            import asyncio
            await asyncio.sleep(0.15)
            return "s"

        async def fast(self):
            return "f"

    a = AsyncA.remote()
    t0 = _time.time()
    refs = [a.slow.remote() for _ in range(4)]
    # fast default-group call is not blocked behind the slow group:
    # 4 x 0.15s at concurrency 2 means the group is busy >= 0.3s
    assert ray_tpu.get(a.fast.remote(), timeout=5) == "f"
    fast_dt = _time.time() - t0
    assert ray_tpu.get(refs, timeout=30) == ["s"] * 4
    total_dt = _time.time() - t0
    assert fast_dt < total_dt   # fast beat the slow group's drain
