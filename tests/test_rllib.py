"""PPO tests (reference analogue: rllib/algorithms/ppo/tests/test_ppo.py
learning tests on toy envs)."""
import numpy as np
import pytest

from ray_tpu.rllib import CartPoleEnv, PPO, PPOConfig, SignEnv


def test_cartpole_env_physics():
    env = CartPoleEnv()
    obs = env.reset(seed=0)
    assert obs.shape == (4,)
    total = 0
    done = False
    while not done:
        obs, r, done, _ = env.step(0)   # constant push -> falls fast
        total += r
    assert 5 < total < 200


def test_ppo_single_iteration_metrics(rt):
    algo = PPOConfig(env="Sign", num_rollout_workers=2,
                     rollout_fragment_length=64).build()
    try:
        result = algo.train()
        assert result["training_iteration"] == 1
        assert result["timesteps_this_iter"] == 128
        assert "loss" in result
    finally:
        algo.stop()


def test_ppo_learns_sign_env(rt):
    algo = PPOConfig(env="Sign", num_rollout_workers=2,
                     rollout_fragment_length=256,
                     minibatch_size=128, lr=1e-2, entropy_coef=0.0,
                     seed=1).build()
    try:
        first = algo.train()
        last = None
        for _ in range(7):
            last = algo.train()
        # Random policy: ~0 mean reward. Learned: ~16 (all correct).
        assert last["episode_reward_mean"] > 8.0, last
    finally:
        algo.stop()


def test_ppo_under_tune(rt):
    from ray_tpu.tune import TuneConfig, Tuner, grid_search
    trainable = PPO.as_trainable({"env": "Sign",
                                  "num_rollout_workers": 1,
                                  "rollout_fragment_length": 64})
    grid = Tuner(
        trainable,
        param_space={"lr": grid_search([1e-3, 1e-2]),
                     "training_iterations": 2},
        tune_config=TuneConfig(metric="episode_reward_mean",
                               mode="max")).fit()
    assert len(grid) == 2
    assert not grid.errors
