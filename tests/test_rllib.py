"""PPO tests (reference analogue: rllib/algorithms/ppo/tests/test_ppo.py
learning tests on toy envs)."""
import numpy as np
import pytest

from ray_tpu.rllib import CartPoleEnv, PPO, PPOConfig, SignEnv


def test_cartpole_env_physics():
    env = CartPoleEnv()
    obs = env.reset(seed=0)
    assert obs.shape == (4,)
    total = 0
    done = False
    while not done:
        obs, r, done, _ = env.step(0)   # constant push -> falls fast
        total += r
    assert 5 < total < 200


def test_ppo_single_iteration_metrics(rt):
    algo = PPOConfig(env="Sign", num_rollout_workers=2,
                     rollout_fragment_length=64).build()
    try:
        result = algo.train()
        assert result["training_iteration"] == 1
        assert result["timesteps_this_iter"] == 128
        assert "loss" in result
    finally:
        algo.stop()


def test_ppo_learns_sign_env(rt):
    algo = PPOConfig(env="Sign", num_rollout_workers=2,
                     rollout_fragment_length=256,
                     minibatch_size=128, lr=1e-2, entropy_coef=0.0,
                     seed=1).build()
    try:
        first = algo.train()
        last = None
        for _ in range(7):
            last = algo.train()
        # Random policy: ~0 mean reward. Learned: ~16 (all correct).
        assert last["episode_reward_mean"] > 8.0, last
    finally:
        algo.stop()


def test_ppo_under_tune(rt):
    from ray_tpu.tune import TuneConfig, Tuner, grid_search
    trainable = PPO.as_trainable({"env": "Sign",
                                  "num_rollout_workers": 1,
                                  "rollout_fragment_length": 64})
    grid = Tuner(
        trainable,
        param_space={"lr": grid_search([1e-3, 1e-2]),
                     "training_iterations": 2},
        tune_config=TuneConfig(metric="episode_reward_mean",
                               mode="max")).fit()
    assert len(grid) == 2
    assert not grid.errors


# ---- AlgorithmConfig builder + DQN + IMPALA -------------------------------

def test_algorithm_config_builder():
    from ray_tpu.rllib import DQNConfig
    cfg = (DQNConfig()
           .environment(env="Sign")
           .rollouts(num_rollout_workers=1, rollout_fragment_length=32)
           .training(lr=1e-3, train_batch_size=32)
           .debugging(seed=7))
    assert cfg.env == "Sign"
    assert cfg.num_rollout_workers == 1
    assert cfg.lr == 1e-3
    assert cfg.seed == 7
    with pytest.raises(ValueError, match="no training field"):
        cfg.training(not_a_field=1)


def test_register_env(rt):
    from ray_tpu.rllib import register_env
    from ray_tpu.rllib.env import ENV_REGISTRY, SignEnv

    class TinySign(SignEnv):
        def __init__(self):
            super().__init__(episode_len=4)

    register_env("TinySign", TinySign)
    assert ENV_REGISTRY["TinySign"] is TinySign


def test_dqn_learns_sign_env(rt):
    from ray_tpu.rllib import DQNConfig
    algo = (DQNConfig()
            .environment(env="Sign")
            .rollouts(num_rollout_workers=2,
                      rollout_fragment_length=128)
            .training(lr=5e-3, learning_starts=200,
                      num_sgd_iter_per_step=16,
                      epsilon_decay_iters=6)
            .debugging(seed=0)
            .build())
    try:
        reward = float("nan")
        for _ in range(12):
            result = algo.train()
            reward = result["episode_reward_mean"]
            if reward == reward and reward > 12:
                break
        # Sign episodes are 16 steps; random ~0, optimal 16.
        assert reward > 8, f"DQN failed to learn Sign: {reward}"
        assert result["buffer_size"] > 0
    finally:
        algo.stop()


def test_dqn_checkpoint_roundtrip(rt, tmp_path):
    from ray_tpu.rllib import DQNConfig
    algo = (DQNConfig().environment(env="Sign")
            .rollouts(num_rollout_workers=1,
                      rollout_fragment_length=32)
            .training(learning_starts=16).build())
    try:
        algo.train()
        path = algo.save(str(tmp_path / "ckpt.pkl"))
    finally:
        algo.stop()
    algo2 = (DQNConfig().environment(env="Sign")
             .rollouts(num_rollout_workers=1,
                       rollout_fragment_length=32)
             .training(learning_starts=16).build())
    try:
        algo2.restore(path)
        assert algo2.iteration == 1
        result = algo2.train()
        assert result["training_iteration"] == 2
    finally:
        algo2.stop()


def test_impala_learns_sign_env(rt):
    from ray_tpu.rllib import ImpalaConfig
    algo = (ImpalaConfig()
            .environment(env="Sign")
            .rollouts(num_rollout_workers=2,
                      rollout_fragment_length=128)
            .training(lr=5e-3, max_batches_per_step=4)
            .debugging(seed=0)
            .build())
    try:
        reward = float("nan")
        for _ in range(25):
            result = algo.train()
            reward = result["episode_reward_mean"]
            if reward == reward and reward > 12:
                break
        assert reward > 8, f"IMPALA failed to learn Sign: {reward}"
        assert result["num_batches_consumed"] >= 1
    finally:
        algo.stop()


def test_a2c_improves(rt):
    """A2C (VERDICT r5: RLlib breadth) learns CartPole."""
    from ray_tpu.rllib import A2CConfig
    algo = A2CConfig(num_rollout_workers=2,
                     rollout_fragment_length=256, seed=0).build()
    try:
        first = None
        for _ in range(12):
            m = algo.train()
            if first is None and m["episode_reward_mean"] == \
                    m["episode_reward_mean"]:
                first = m["episode_reward_mean"]
        assert m["episode_reward_mean"] > 30, m
    finally:
        algo.stop()


def test_offline_bc_and_cql_from_rollouts(rt):
    """Offline RL: rollouts -> transition Dataset -> BC clones the
    behavior policy; CQL learns Q-values with a positive conservative
    gap. Both train purely from the dataset (no env interaction)."""
    import numpy as np
    from ray_tpu.rllib import (BCConfig, CQLConfig, PPOConfig,
                               episodes_to_dataset)
    # competent-ish behavior data: a few PPO iterations
    ppo = PPOConfig(num_rollout_workers=2,
                    rollout_fragment_length=256, seed=0).build()
    try:
        for _ in range(8):
            ppo.train()
        import ray_tpu as rtpu
        wref = rtpu.put(ppo.get_policy_params())
        rtpu.get([w.set_weights.remote(wref) for w in ppo.workers])
        rollouts = rtpu.get([w.sample.remote(512)
                             for w in ppo.workers])
    finally:
        ppo.stop()
    ds = episodes_to_dataset(rollouts)
    assert ds.count() == 1024

    bc = BCConfig(seed=0, lr=3e-3).build(ds)
    losses = [bc.train()["loss"] for _ in range(150)]
    # the behavior policy is stochastic, so the NLL floor is its
    # entropy — assert real progress toward it, not an absolute level
    assert losses[-1] < losses[0] - 0.03, (losses[0], losses[-1])
    act = bc.compute_action(np.zeros(4, np.float32))
    assert act in (0, 1)

    cql = CQLConfig(seed=0).build(ds)
    metrics = [cql.train() for _ in range(60)]
    assert metrics[-1]["td_loss"] < metrics[2]["td_loss"] * 2
    # the conservative penalty is driving OOD actions down
    assert metrics[-1]["conservative_gap"] < \
        metrics[0]["conservative_gap"]
    assert cql.compute_action(np.zeros(4, np.float32)) in (0, 1)


def test_multi_agent_ppo_trains(rt):
    """Multi-agent env + per-policy mapping: two agents, two separate
    policies, both learn; policy params stay distinct."""
    import numpy as np
    from ray_tpu.rllib import MultiAgentPPOConfig
    algo = MultiAgentPPOConfig(
        policies=("p0", "p1"),
        policy_mapping={"agent_0": "p0", "agent_1": "p1"},
        num_rollout_workers=2, rollout_fragment_length=128,
        seed=0).build()
    try:
        first = algo.train()["episode_reward_mean"]
        for _ in range(20):
            m = algo.train()
        assert set(m["policy_loss"]) == {"p0", "p1"}
        # combined (2-agent) episode reward: random ~= 40. The mean
        # includes early random episodes, so assert clear LEARNING
        # (improvement over iteration 1) plus an absolute bar.
        assert m["episode_reward_mean"] > max(52.0, first + 8), \
            (first, m)
        l0 = jax_leaf_sum(algo.params["p0"])
        l1 = jax_leaf_sum(algo.params["p1"])
        assert l0 != l1      # independent policies actually diverged
    finally:
        algo.stop()


def jax_leaf_sum(params):
    import jax
    return float(sum(float(x.sum())
                     for x in jax.tree_util.tree_leaves(params)))


def test_pendulum_env_physics():
    from ray_tpu.rllib import PendulumEnv
    env = PendulumEnv()
    obs = env.reset(seed=0)
    assert obs.shape == (3,)
    assert abs(float(np.hypot(obs[0], obs[1])) - 1.0) < 1e-5
    total = 0.0
    done = False
    while not done:
        obs, r, done, _ = env.step(np.array([0.0], np.float32))
        assert r <= 0.0
        total += r
    # 200 steps of zero torque from a random start: cost is bounded by
    # the per-step max (pi^2 + 0.1*64 ~= 16.3).
    assert -200 * 17 < total < 0


def test_sac_learns_reach_env(rt):
    from ray_tpu.rllib import SACConfig
    algo = (SACConfig()
            .environment(env="Reach")
            .rollouts(num_rollout_workers=2,
                      rollout_fragment_length=128)
            .training(lr=3e-3, learning_starts=256,
                      num_sgd_iter_per_step=32)
            .debugging(seed=0)
            .build())
    try:
        reward = float("nan")
        for _ in range(10):
            result = algo.train()
            reward = result["episode_reward_mean"]
            if reward == reward and reward > -0.5:
                break
        # Reach episodes are 8 steps; random ~ -8*2/3, optimal ~ 0.
        assert reward > -2.0, f"SAC failed to learn Reach: {reward}"
        # Automatic temperature tuning actually moved alpha off its
        # initial value (0.1).
        assert abs(result["alpha"] - 0.1) > 1e-3, result["alpha"]
    finally:
        algo.stop()


def test_sac_rejects_discrete_env(rt):
    from ray_tpu.rllib import SACConfig
    with pytest.raises(ValueError, match="continuous"):
        SACConfig().environment(env="Sign").build()


def test_sac_checkpoint_roundtrip(rt, tmp_path):
    from ray_tpu.rllib import SACConfig
    algo = (SACConfig().environment(env="Reach")
            .rollouts(num_rollout_workers=1,
                      rollout_fragment_length=32)
            .training(learning_starts=16).build())
    try:
        algo.train()
        path = algo.save(str(tmp_path / "sac.pkl"))
    finally:
        algo.stop()
    algo2 = (SACConfig().environment(env="Reach")
             .rollouts(num_rollout_workers=1,
                       rollout_fragment_length=32)
             .training(learning_starts=16).build())
    try:
        algo2.restore(path)
        assert algo2.iteration == 1
        result = algo2.train()
        assert result["training_iteration"] == 2
    finally:
        algo2.stop()


def test_sac_compute_action(rt):
    from ray_tpu.rllib import SACConfig
    algo = (SACConfig().environment(env="Reach")
            .rollouts(num_rollout_workers=1,
                      rollout_fragment_length=16)
            .training(learning_starts=8).build())
    try:
        algo.train()
        import numpy as np
        a = algo.compute_action(np.array([0.5], np.float32))
        assert a.shape == (1,) and -1.0 <= float(a[0]) <= 1.0
        # deterministic is repeatable; stochastic varies
        b = algo.compute_action(np.array([0.5], np.float32))
        assert np.array_equal(a, b)
        s1 = algo.compute_action(np.array([0.5], np.float32),
                                 deterministic=False)
        s2 = algo.compute_action(np.array([0.5], np.float32),
                                 deterministic=False)
        assert not np.array_equal(s1, s2)
    finally:
        algo.stop()


def test_evaluate_across_algorithms(rt):
    """compute_action + Algorithm.evaluate parity surface: greedy
    rollouts work for the on-policy (PPO), value-based (DQN), and
    continuous (SAC) families (reference: Algorithm.evaluate)."""
    from ray_tpu.rllib import DQNConfig, PPOConfig, SACConfig

    ppo = PPOConfig(env="Sign", num_rollout_workers=1,
                    rollout_fragment_length=256, lr=1e-2,
                    entropy_coef=0.0, seed=1).build()
    try:
        for _ in range(4):
            ppo.train()
        ev = ppo.evaluate(num_episodes=3)["evaluation"]
        # trained PPO on Sign: near-perfect (16); random is ~0
        assert ev["episode_reward_mean"] > 8, ev
        assert ev["episodes_this_iter"] == 3
        assert ev["episode_len_mean"] == 16.0
    finally:
        ppo.stop()

    dqn = (DQNConfig().environment(env="Sign")
           .rollouts(num_rollout_workers=1,
                     rollout_fragment_length=64)
           .training(learning_starts=32).build())
    try:
        dqn.train()
        a = dqn.compute_action(np.array([0.7], np.float32))
        assert a in (0, 1)
        ev = dqn.evaluate(num_episodes=2)["evaluation"]
        assert -16 <= ev["episode_reward_mean"] <= 16
    finally:
        dqn.stop()

    sac = (SACConfig().environment(env="Reach")
           .rollouts(num_rollout_workers=1,
                     rollout_fragment_length=32)
           .training(learning_starts=16).build())
    try:
        sac.train()
        ev = sac.evaluate(num_episodes=2)["evaluation"]
        assert ev["episode_reward_mean"] <= 0     # Reach rewards <= 0
    finally:
        sac.stop()
