"""RLHF loop tests (ray_tpu/rl/loop.py + learner.py).

The async-sampling contract (folds the APPO carry-over): round N+1's
generation provably overlaps round N's learner step when the
staleness bound allows it, the bound is enforced on both sides
(generator blocks; consumption raises), and both chaos kills —
generator mid-round, learner pre-commit — recover with exactly-once
rollout accounting and the generator re-synced to the recovered
payload.
"""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models.llama import Llama, llama_tiny
from ray_tpu.rl import (DuplicateRollout, GeneratorKilled, RLHFLoop,
                        RolloutBatch, RolloutGenerator, RolloutLearner,
                        StalenessViolation)
from ray_tpu.serve.engine import LLMEngine

ROUNDS = 4
N_PROMPTS = 4
PROMPT_LEN = 6
MAX_NEW = 4
DELAY_S = 0.2


@pytest.fixture(scope="module")
def tiny_model():
    cfg = llama_tiny(dtype=jnp.float32)
    model = Llama(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, PROMPT_LEN), jnp.int32))
    return model, params


@pytest.fixture()
def stack(tiny_model):
    model, params = tiny_model
    eng = LLMEngine(model, params, max_slots=4, page_size=16,
                    n_pages=128, chunk=4, prefill_chunk=16,
                    temperature=1.0, eos_id=-1, seed=0,
                    capture_logprobs=True).start()
    gen = RolloutGenerator(eng, max_new_tokens=MAX_NEW)
    learner = RolloutLearner(model, params, algo="ppo", lr=1e-2,
                             sgd_epochs=1)
    yield eng, gen, learner
    eng.shutdown()


def _prompts_fn(round_idx):
    rng = np.random.RandomState(1000 + round_idx)
    return [rng.randint(1, 128, size=PROMPT_LEN).tolist()
            for _ in range(N_PROMPTS)]


def _reward_fn(prompt, completion):
    if not completion:
        return 0.0
    return sum(1 for t in completion if t >= 128) / len(completion)


def _loop(gen, learner, tmp_path, **kw):
    args = dict(rounds=ROUNDS, staleness_bound=1, overlap=True,
                ckpt_dir=str(tmp_path / "ckpt"),
                publish_dir=str(tmp_path / "pub"),
                learner_delay_s=DELAY_S)
    args.update(kw)
    return RLHFLoop(gen, learner, _reward_fn, _prompts_fn, **args)


def _audit(ledger, rounds):
    expected = [f"round-{i}" for i in range(rounds)]
    assert sorted(ledger) == expected, \
        f"ledger must hold every round exactly once: {ledger}"


# --------------------------------------------- async-sampling unit


def test_generation_overlaps_slow_learner_step(stack, tmp_path):
    """With a deliberately slow learner and staleness bound 1, round
    N+1's decode must START before round N's learner step ENDS — the
    sebulba overlap — while every consumed batch still lags the
    learner by at most the bound."""
    _eng, gen, learner = stack
    stats = _loop(gen, learner, tmp_path).run()
    assert stats["overlap_observed"], \
        "round N+1 generation never ran during round N's learner step"
    tl = stats["timeline"]
    assert any(b["gen_start"] < a["learn_end"]
               for a, b in zip(tl, tl[1:]))
    assert stats["max_staleness"] <= 1
    assert all(b["weights_id"] for b in stats["batch_log"])
    _audit(stats["ledger"], ROUNDS)
    # The engine ends on the last published payload.
    assert stats["final_weights_id"] == \
        stats["batch_log"][-1]["weights_id"] or stats["final_weights_id"]


def test_staleness_bound_zero_degenerates_to_serialized(stack,
                                                        tmp_path):
    """Bound 0 = the generator blocks until the previous round is
    consumed: no overlap may be observed and staleness stays 0."""
    _eng, gen, learner = stack
    stats = _loop(gen, learner, tmp_path, staleness_bound=0).run()
    assert not stats["overlap_observed"]
    assert stats["max_staleness"] == 0
    _audit(stats["ledger"], ROUNDS)


def test_consume_refuses_duplicate_and_stale_batches(stack):
    """_consume is the invariant wall: a ledgered batch id raises
    DuplicateRollout, a batch lagging the learner past the bound
    raises StalenessViolation — neither may pass silently."""
    _eng, gen, learner = stack
    loop = RLHFLoop(gen, learner, _reward_fn, _prompts_fn,
                    rounds=2, staleness_bound=1,
                    ckpt_dir="/tmp/unused-rl-ck",
                    publish_dir="/tmp/unused-rl-pub")
    batch = RolloutBatch(
        batch_id="round-0", round_idx=0,
        prompts=[[1, 2]], completions=[[3, 4]],
        logprobs=[[-1.0, -1.0]], weights_id="w0", generation=1)
    loop.ledger.append("round-0")
    with pytest.raises(DuplicateRollout):
        loop._consume(0, batch, synced_update=0)
    batch.batch_id = "round-1"
    with pytest.raises(StalenessViolation):
        loop._consume(1, batch,
                      synced_update=learner.update_count - 2)


# ------------------------------------------------------ chaos kills


def test_generator_kill_mid_round_resumes_exactly_once(stack,
                                                       tmp_path):
    """A generator death after submit, before collection: the loop
    restarts it at exactly the unconsumed round; deterministic batch
    ids make the regeneration a single ledger entry — 0 duplicated,
    0 lost."""
    _eng, gen, learner = stack
    killed = []

    def mid_round(r):
        if r == 2 and not killed:
            killed.append(r)
            raise GeneratorKilled("chaos: died mid-round 2")

    stats = _loop(gen, learner, tmp_path,
                  generator_mid_round_hook=mid_round).run()
    assert killed == [2]
    assert stats["generator_restarts"] == 1
    _audit(stats["ledger"], ROUNDS)
    assert stats["max_staleness"] <= 1


def test_learner_kill_precommit_resumes_from_last_complete(
        stack, tiny_model, tmp_path):
    """A learner death on the commit path: the round's checkpoint
    never lands, run() raises, and a fresh attempt resumes from the
    last COMPLETE checkpoint — replaying only the uncommitted round —
    with the generator provably re-synced to the recovered
    weights_id (same bytes => same id)."""
    eng, gen, learner = stack

    def kill(step):
        if step == 2:
            raise RuntimeError("chaos: learner killed pre-commit")

    ctl = str(tmp_path / "ctl")
    with pytest.raises(RuntimeError, match="pre-commit"):
        _loop(gen, learner, tmp_path, control_dir=ctl, attempt=1,
              learner_kill_hook=kill).run()

    model, params = tiny_model
    learner2 = RolloutLearner(model, params, algo="ppo", lr=1e-2,
                              sgd_epochs=1)
    stats = _loop(gen, learner2, tmp_path, control_dir=ctl,
                  attempt=2).run()
    assert stats["resumed"]
    assert stats["start_round"] == 2, \
        "resume must replay exactly the uncommitted round"
    assert stats["recovered_weights_id"] == stats["resync_weights_id"]
    _audit(stats["ledger"], ROUNDS)
    assert learner2.update_count == ROUNDS


def test_superseded_attempt_cannot_commit(stack, tmp_path):
    """AttemptFence: once attempt 2 fences the control dir, attempt
    1's next commit attempt dies StaleGeneration instead of
    overwriting its successor's checkpoints."""
    from ray_tpu.train.chaos import AttemptFence, StaleGeneration
    _eng, gen, learner = stack
    ctl = str(tmp_path / "ctl")
    loop = _loop(gen, learner, tmp_path, control_dir=ctl, attempt=1)
    with AttemptFence(ctl, 2):
        with pytest.raises(StaleGeneration):
            loop.run()
