"""log_to_driver pipeline tests (VERDICT r2 #9): worker prints stream
to the driver over pub/sub, tagged with their task/actor origin
(reference: python/ray/_private/log_monitor.py:100 + GCS pub/sub)."""
import time

import pytest

import ray_tpu
from ray_tpu.runtime import Cluster


@pytest.fixture(scope="module")
def log_cluster():
    import ray_tpu._private.worker as worker_mod
    if worker_mod.is_initialized():
        worker_mod.shutdown()
    c = Cluster(num_workers=2, resources_per_worker={"CPU": 2})
    yield c
    c.shutdown()


def _collect_until(records, predicate, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        hits = [r for r in records if predicate(r)]
        if hits:
            return hits
        time.sleep(0.05)
    return []


def test_task_print_reaches_driver_with_tag(log_cluster):
    records = []
    log_cluster.runtime.start_log_streaming(sink=records.append)

    @ray_tpu.remote
    def chatty():
        print("hello-from-task-xyz")
        return 1

    ref = chatty.remote()
    assert ray_tpu.get(ref) == 1
    hits = _collect_until(
        records, lambda r: r["line"] == "hello-from-task-xyz")
    assert hits, f"print never reached driver; got {records[-5:]}"
    rec = hits[0]
    assert rec["stream"] == "out"
    assert rec["tag"] and "chatty" in rec["tag"] and "task=" in rec["tag"]
    assert rec["worker_id"]
    # The task id in the tag matches the submitted task.
    assert ref.id.task_id().hex()[:12] in rec["tag"]


def test_actor_print_tagged_with_actor_id(log_cluster):
    records = []
    log_cluster.runtime.start_log_streaming(sink=records.append)

    @ray_tpu.remote
    class Talker:
        def say(self):
            print("actor-speaking-abc")
            return "ok"

    t = Talker.remote()
    assert ray_tpu.get(t.say.remote()) == "ok"
    hits = _collect_until(
        records, lambda r: r["line"] == "actor-speaking-abc")
    assert hits
    assert hits[0]["tag"].startswith("actor=")


def test_stderr_stream_marked(log_cluster):
    records = []
    log_cluster.runtime.start_log_streaming(sink=records.append)

    @ray_tpu.remote
    def warns():
        import sys
        print("to-stderr-123", file=sys.stderr)

    ray_tpu.get(warns.remote())
    hits = _collect_until(
        records, lambda r: r["line"] == "to-stderr-123")
    assert hits and hits[0]["stream"] == "err"


def test_tee_stream_concurrent_writes_lose_nothing():
    """_TeeStream replaces the process-wide sys.stdout while the worker
    executor runs tasks on a thread pool: concurrent writers must not
    lose or mangle lines."""
    import io
    import threading

    from ray_tpu._private.log_streaming import _TeeStream

    collected = []
    lock = threading.Lock()

    def collect(stream, line):
        with lock:
            collected.append(line)

    tee = _TeeStream(io.StringIO(), "out", collect)
    n_threads, n_lines = 8, 200

    def writer(tid):
        for i in range(n_lines):
            tee.write(f"t{tid}-line{i}\n")

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(collected) == n_threads * n_lines
    assert sorted(collected) == sorted(
        f"t{t}-line{i}" for t in range(n_threads)
        for i in range(n_lines))
