"""Live weight rollout tests (serve/weight_rollout.py + the engine/
pool fence hooks).

Three layers: the per-engine generation fence (swap under traffic is
token-identical, monotonic, cache-invalidating), the checkpoint
publish/load edge (torn payloads refused typed before any replica is
touched), and the fleet controller (canary -> advance -> done, parity-
probe rollback, resume-after-controller-death, rebuild re-stamping).
"""
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.air import InvalidCheckpointError
from ray_tpu.models.llama import Llama, llama_tiny
from ray_tpu.serve.engine import LLMEngine
from ray_tpu.serve.engine_pool import HEALTHY, EnginePool
from ray_tpu.serve.weight_rollout import (WeightRolloutController,
                                          load_weights, publish_weights)


@pytest.fixture(scope="module")
def tiny_model():
    # fp32 so greedy decode is bit-identical across replicas and
    # across a same-tensor weight swap (the parity proofs below)
    cfg = llama_tiny(dtype=jnp.float32)
    model = Llama(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    return model, params


def _reference_completion(model, params, prompt, n):
    from ray_tpu.models.llama import generate
    out = generate(model, params, jnp.asarray([prompt], jnp.int32),
                   max_new_tokens=n, temperature=0.0)
    return np.asarray(out)[0, len(prompt):].tolist()


def _engine(model, params, **kw):
    args = dict(max_slots=2, page_size=8, n_pages=64, chunk=4,
                temperature=0.0, seed=0, prefix_cache=True)
    args.update(kw)
    eng = LLMEngine(model, params, **args)
    eng.start()
    return eng


def _perturb(params):
    return jax.tree_util.tree_map(lambda x: x + 0.25, params)


# ------------------------------------------------- engine-level fence


def test_preempt_swap_is_token_identical_and_fenced(tiny_model):
    """A preempt-mode swap mid-request: the straddling request
    resubmits through the replica-death path and still produces the
    reference completion (the swap installs the SAME tensors under a
    new id, so token identity is provable); the fence advances; the
    prefix cache is invalidated."""
    model, params = tiny_model
    eng = _engine(model, params)
    try:
        prompt = [3, 1, 4, 1, 5, 9, 2, 6]
        want = _reference_completion(model, params, prompt, 12)
        # warm the prefix cache so invalidation is observable
        assert eng.submit(list(prompt), max_new_tokens=4).result() \
            == want[:4]
        assert eng.prefix_cache.cached_pages > 0
        h = eng.submit(list(prompt), max_new_tokens=12)
        # consume two tokens so the request provably OCCUPIES a slot
        # when the flip lands — the swap preempts it mid-decode
        it = h.stream()
        got = [next(it), next(it)]
        gen = eng.swap_weights(params, weights_id="same-bytes-v2")
        assert gen == 1
        assert eng.weight_generation == 1
        assert eng.weights_id == "same-bytes-v2"
        got.extend(it)
        assert got == want, \
            "request straddling a same-tensor swap must stay " \
            "token-identical"
        assert eng.stats["weight_swaps"] == 1
        rpt = eng.load_report()
        assert rpt["weight_generation"] == 1
        assert rpt["weights_id"] == "same-bytes-v2"
        swaps = [e for e in eng.events.snapshot()
                 if e[2] == "weight_swap"]
        assert swaps, "the flip must be evented"
        # the warmed old-weight KV was evicted AT the flip (pages
        # cached afterwards were computed under the new payload)
        assert swaps[0][5]["prefix_pages_evicted"] >= 1
        assert swaps[0][5]["preempted"] >= 1
    finally:
        eng.shutdown()


def test_fence_is_strictly_monotonic(tiny_model):
    model, params = tiny_model
    eng = _engine(model, params)
    try:
        assert eng.swap_weights(params, weights_id="a") == 1
        with pytest.raises(ValueError):
            eng.swap_weights(params, generation=1, weights_id="b")
        with pytest.raises(ValueError):
            eng.swap_weights(params, generation=0, weights_id="b")
        # rollback shape: OLD payload under a NEW generation
        assert eng.swap_weights(params, weights_id="a") == 2
        assert eng.weights_id == "a"
    finally:
        eng.shutdown()


def test_drain_mode_swap_waits_for_idle(tiny_model):
    """Drain mode: the flip waits for the engine to settle between
    rounds — the in-flight request finishes ON OLD WEIGHTS, then the
    swap applies."""
    model, params = tiny_model
    eng = _engine(model, params)
    try:
        prompt = [5, 3, 8, 13, 2]
        want = _reference_completion(model, params, prompt, 10)
        h = eng.submit(list(prompt), max_new_tokens=10)
        done = {}

        def swapper():
            done["gen"] = eng.swap_weights(
                params, weights_id="v2", mode="drain", timeout_s=60)

        t = threading.Thread(target=swapper, daemon=True)
        t.start()
        assert h.result() == want
        t.join(60)
        assert done.get("gen") == 1
        assert eng.weights_id == "v2"
        kinds = [e[2] for e in eng.events.snapshot()]
        assert "weight_swap_pending" in kinds and "weight_swap" in kinds
    finally:
        eng.shutdown()


def test_engine_handle_weights_tag(tiny_model):
    model, params = tiny_model
    eng = _engine(model, params)
    try:
        h = eng.submit([1, 2, 3], max_new_tokens=2)
        h.result()
        assert h.weights_tag == "0:g0"
        eng.swap_weights(params, weights_id="abc")
        h2 = eng.submit([1, 2, 3], max_new_tokens=2)
        h2.result()
        assert h2.weights_tag == "1:abc"
    finally:
        eng.shutdown()


def test_shutdown_releases_pending_drain_swap(tiny_model):
    """An engine stopped with a drain swap pending must fail the
    waiter typed, not hang it."""
    from ray_tpu.serve.errors import EngineShutdown
    model, params = tiny_model
    eng = _engine(model, params)
    prompt = [7, 7, 7, 7]
    eng.submit(list(prompt), max_new_tokens=64, deadline_s=30)
    err = {}

    def swapper():
        try:
            eng.swap_weights(params, weights_id="v2", mode="drain",
                             timeout_s=60)
        except BaseException as e:  # noqa: BLE001
            err["e"] = e

    t = threading.Thread(target=swapper, daemon=True)
    t.start()
    eng.shutdown()
    t.join(30)
    assert isinstance(err.get("e"), EngineShutdown)


# --------------------------------------------- checkpoint publish/load


def test_publish_load_roundtrip_and_payload_identity(tmp_path,
                                                     tiny_model):
    model, params = tiny_model
    p1, wid1 = publish_weights(params, str(tmp_path / "v1"), step=1)
    p2, wid2 = publish_weights(params, str(tmp_path / "v2"), step=2,
                               extra={"release": "v2"})
    assert wid1 != wid2, \
        "metadata must distinguish byte-identical tensor payloads"
    loaded, wid = load_weights(p1)
    assert wid == wid1, "weights_id derives from the manifest alone"
    np.testing.assert_array_equal(
        np.asarray(jax.tree_util.tree_leaves(loaded)[0]),
        np.asarray(jax.tree_util.tree_leaves(params)[0]))


def test_torn_checkpoint_refused_typed(tmp_path, tiny_model):
    """A bit-flipped payload deep-fails its manifest hash and is
    refused InvalidCheckpointError before any replica is touched."""
    from ray_tpu.air.checkpoint import load_manifest
    model, params = tiny_model
    path, _wid = publish_weights(params, str(tmp_path / "bad"))
    rel = sorted(load_manifest(path)["files"])[0]
    full = os.path.join(path, rel)
    with open(full, "r+b") as f:
        first = f.read(1)
        f.seek(0)
        f.write(bytes([first[0] ^ 0xFF]))
    with pytest.raises(InvalidCheckpointError):
        load_weights(path)


def test_checkpoint_without_params_refused(tmp_path):
    from ray_tpu.air import Checkpoint
    out = Checkpoint.from_dict({"note": "no tensors"}).to_directory(
        str(tmp_path / "empty"))
    with pytest.raises(InvalidCheckpointError):
        load_weights(out)


# ------------------------------------------------ fleet controller


def _pool(model, params, n=3):
    return EnginePool(
        lambda i: LLMEngine(model, params, max_slots=2, page_size=8,
                            n_pages=64, chunk=4, temperature=0.0,
                            seed=i, prefix_cache=True),
        n)


def test_rollout_completes_and_serves_token_identically(
        tmp_path, tiny_model):
    model, params = tiny_model
    pool = _pool(model, params)
    try:
        _p2, wid2 = publish_weights(params, str(tmp_path / "v2"),
                                    extra={"release": "v2"})
        prompt = [2, 7, 1, 8, 2, 8]
        want = _reference_completion(model, params, prompt, 8)
        ctl = WeightRolloutController(
            pool, canary_fraction=0.3,      # ceil(0.9) = 1 canary of 3
            probes=[(prompt, want[:4])],
            flight_dir=str(tmp_path / "flight"))
        report = ctl.rollout(params, weights_id=wid2,
                             baseline_params=params,
                             baseline_weights_id="g0")
        assert report["status"] == "completed"
        assert report["generation"] >= 1
        assert len(report["canary"]) == 1
        assert sorted(sum(report["waves"], report["canary"])) \
            == [0, 1, 2]
        assert {wid for _g, wid in ctl.fleet_weights().values()} \
            == {wid2}
        # generation transitions are monotonic per replica
        seen = {}
        for tr in report["transitions"]:
            assert tr["to"] > tr["from"]
            assert tr["to"] > seen.get(tr["idx"], -1)
            seen[tr["idx"]] = tr["to"]
        # traffic after the rollout is still token-identical
        assert pool.submit(list(prompt),
                           max_new_tokens=8).result() == want
        assert pool.route_stats["weight_swaps"] == 3
        agg = pool.load_report()
        assert agg["weight_generation"] >= 1
        assert agg["weights_mixed"] is False
        # completion is flight-explained
        bundles = os.listdir(str(tmp_path / "flight"))
        assert any("weight-rollout-done" in b for b in bundles)
    finally:
        pool.shutdown()


def test_canary_parity_failure_auto_rolls_back(tmp_path, tiny_model):
    """An injected regression (perturbed tensors) fails the canary's
    output-parity probe; the controller rolls the fleet back onto the
    baseline payload and flight-explains the decision."""
    model, params = tiny_model
    pool = _pool(model, params)
    try:
        prompt = [3, 1, 4, 1, 5]
        want = _reference_completion(model, params, prompt, 6)
        bad = _perturb(params)
        flight = str(tmp_path / "flight")
        ctl = WeightRolloutController(
            pool, canary_fraction=0.34,
            probes=[(prompt, want)], flight_dir=flight)
        report = ctl.rollout(bad, weights_id="bad-widXXXX",
                             baseline_params=params,
                             baseline_weights_id="g0")
        assert report["status"] == "rolled_back"
        assert "parity" in report["rollback_reason"]
        assert report["probe_failures"]
        rb = report["rollback"]
        assert rb["converged"] is True
        assert rb["failed_replicas"] == []
        assert {wid for _g, wid in ctl.fleet_weights().values()} \
            == {"g0"}
        # the canary's fence still advanced (rollback = old payload
        # under a NEW generation; the fence never retreats)
        canary_idx = report["canary"][0]
        assert pool.replica(canary_idx).engine.weight_generation == 2
        # untouched replicas never swapped
        assert pool.route_stats["weight_rollbacks"] == 1
        # post-rollback traffic is token-identical to baseline
        assert pool.submit(list(prompt),
                           max_new_tokens=6).result() == want
        bundles = os.listdir(flight)
        assert any("weight-rollback" in b for b in bundles)
    finally:
        pool.shutdown()


def test_rollout_resumes_after_controller_death(tmp_path, tiny_model):
    """Controller killed mid-rollout: per-replica weights_id is the
    durable state. A fresh rollout() skips already-converged replicas
    and converges the rest."""
    model, params = tiny_model
    pool = _pool(model, params)
    try:
        _p2, wid2 = publish_weights(params, str(tmp_path / "v2"),
                                    extra={"release": "v2"})
        # the "dead" controller got exactly one replica swapped
        pool.swap_replica_weights(0, params, weights_id=wid2)
        ctl = WeightRolloutController(pool, canary_fraction=0.34,
                                      flight_dir=str(tmp_path / "f"))
        report = ctl.rollout(params, weights_id=wid2,
                             baseline_params=params,
                             baseline_weights_id="g0")
        assert report["status"] == "completed"
        assert report["resumed"] == [0]
        assert 0 not in sum(report["waves"], report["canary"]), \
            "already-converged replicas must not re-swap"
        assert {wid for _g, wid in ctl.fleet_weights().values()} \
            == {wid2}
    finally:
        pool.shutdown()


def test_rebuilt_and_added_replicas_are_restamped(tmp_path,
                                                  tiny_model):
    """The kill-mid-swap hole: a replica rebuilt (or added) AFTER a
    completed rollout must rejoin on the fleet's current payload, not
    the engine factory's generation-0 weights."""
    model, params = tiny_model
    pool = _pool(model, params, n=2)
    try:
        _p2, wid2 = publish_weights(params, str(tmp_path / "v2"),
                                    extra={"release": "v2"})
        ctl = WeightRolloutController(pool, canary_fraction=0.5)
        assert ctl.rollout(params, weights_id=wid2,
                           baseline_params=params,
                           baseline_weights_id="g0"
                           )["status"] == "completed"
        # rebuild path (drain -> factory -> restamp)
        assert pool.drain(0)
        rep = pool.replica(0)
        assert rep.state == HEALTHY and rep.generation == 1
        assert rep.engine.weights_id == wid2
        assert rep.engine.weight_generation >= 1
        # scale-up path
        idx = pool.add_replica()
        assert pool.replica(idx).engine.weights_id == wid2
        kinds = [e[2] for e in pool.events.snapshot()]
        assert "weight_restamp" in kinds
    finally:
        pool.shutdown()


def test_swap_refused_on_dead_replica(tiny_model):
    model, params = tiny_model
    pool = _pool(model, params, n=2)
    try:
        pool.replica(1).state = "dead"
        with pytest.raises(RuntimeError):
            pool.swap_replica_weights(1, params, weights_id="x")
        pool.replica(1).state = HEALTHY
    finally:
        pool.shutdown()


def test_pull_hint_respects_weight_fence(tiny_model):
    """Cross-replica fence half: a donor serving a DIFFERENT payload
    must never be picked as a KV-pull source — its pages were
    computed under weights the target does not run."""
    model, params = tiny_model
    pool = _pool(model, params, n=2)
    try:
        from ray_tpu.serve.prefix_cache import path_hashes
        prompt = [9, 8, 7, 6, 5, 4, 3, 2] * 4
        # replica 1 caches the prefix
        pool.replica(1).engine.submit(
            list(prompt), max_new_tokens=2).result()
        reports = {i: pool.replica(i).engine.load_report()
                   for i in (0, 1)}
        chain = path_hashes(prompt, pool.replica(0).engine.Pg)
        assert any(h in reports[1]["prefix_digest"] for h in chain)
        hint = pool._pull_hint(list(prompt), pool.replica(0), reports)
        assert hint is not None, "same payload: pull is offered"
        # now replica 1 is mid-rollout on a different payload
        pool.swap_replica_weights(1, params, weights_id="other")
        pool.replica(1).engine.submit(
            list(prompt), max_new_tokens=2).result()
        reports = {i: pool.replica(i).engine.load_report()
                   for i in (0, 1)}
        hint = pool._pull_hint(list(prompt), pool.replica(0), reports)
        assert hint is None, \
            "cross-payload KV pull must be fenced off"
    finally:
        pool.shutdown()


def test_pool_handle_weights_tag(tiny_model):
    model, params = tiny_model
    pool = _pool(model, params, n=1)
    try:
        h = pool.submit([1, 2, 3], max_new_tokens=2)
        h.result()
        assert h.weights_tag == "0:g0"
        pool.swap_replica_weights(0, params, weights_id="w2")
        h2 = pool.submit([1, 2, 3], max_new_tokens=2)
        h2.result()
        assert h2.weights_tag == "1:w2"
    finally:
        pool.shutdown()
