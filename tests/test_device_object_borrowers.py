"""Payload borrower protocol for device objects (PR 20 satellite).

An escaped device object's host spill (`payload_oid`) used to revert
to shm-LRU lifetime once the owner dropped its ref. Now consumers
register a borrow on the payload id at resolve time and the owner's
release hands the spill to the head's borrower protocol, so the host
copy frees on the LAST borrow drop — the drop-order matrix:

- owner drops first: the borrower's live ref keeps the payload
  resolvable well past the grace window; it frees after the borrower
  lets go.
- borrower drops first: the payload survives (owner still holds);
  it frees within the grace window of the owner's own drop.
- escaped but never resolved: no payload borrow exists, so the
  owner's drop frees the spill eagerly after the grace window — not
  under LRU pressure.
"""
import gc
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.runtime import Cluster

GRACE = 0.5


@pytest.fixture(scope="module")
def cluster():
    import os
    import ray_tpu._private.worker as worker_mod
    if worker_mod.is_initialized():
        worker_mod.shutdown()
    os.environ["RAY_TPU_borrow_grace_s"] = str(GRACE)
    from ray_tpu._private.config import GlobalConfig
    GlobalConfig.reset()
    c = Cluster(num_workers=1,
                resources_per_worker={"CPU": 2, "node0": 10},
                store_capacity=256 * 1024 * 1024)
    c.add_node(num_workers=1,
               resources_per_worker={"CPU": 2, "node1": 10},
               store_capacity=256 * 1024 * 1024)
    yield c
    c.shutdown()
    os.environ.pop("RAY_TPU_borrow_grace_s", None)
    GlobalConfig.reset()


def _store():
    from ray_tpu._private.worker import global_worker
    return global_worker().runtime.plane.store


def _wait_gone(oid, timeout=15.0):
    deadline = time.time() + timeout
    store = _store()
    while time.time() < deadline:
        if not store.contains(oid):
            return True
        time.sleep(0.25)
    return False


def _put_device_array(value=3.0, n=1024):
    import jax.numpy as jnp
    return ray_tpu.put(jnp.full((n,), value, jnp.float32))


@ray_tpu.remote(resources={"node1": 1})
class Holder:
    """Borrower on the other node; resolve/hold/drop are separated so
    each drop-order arm controls exactly when the payload borrow is
    registered and when it drops."""

    def __init__(self):
        self.ref = None

    def hold(self, boxed):
        self.ref = boxed[0]        # nested ref stays a ref
        return True

    def resolve(self):
        import numpy as _np
        return float(_np.asarray(ray_tpu.get(self.ref))[0])

    def drop(self):
        self.ref = None
        import gc as _gc
        _gc.collect()
        return True


def test_owner_drops_first_borrower_pins_payload(cluster):
    from ray_tpu.mesh.device_objects import payload_oid

    h = Holder.remote()
    ref = _put_device_array(7.0)
    oid = ref.id
    poid = payload_oid(oid)
    assert ray_tpu.get(h.hold.remote([ref]))      # escape -> spill
    assert ray_tpu.get(h.resolve.remote()) == 7.0  # payload borrow
    assert _store().contains(poid)
    time.sleep(1.0)            # let the borrow registration land
    del ref
    gc.collect()
    # Well past the grace window the payload borrow still pins the
    # host spill, and the borrower can still resolve the array.
    time.sleep(GRACE * 4 + 1.0)
    assert _store().contains(poid), \
        "payload freed while a borrow was registered"
    assert ray_tpu.get(h.resolve.remote()) == 7.0
    # Last borrow drops -> payload freed within grace + flusher lag.
    assert ray_tpu.get(h.drop.remote())
    assert _wait_gone(poid), "payload not freed after last borrow drop"
    assert _wait_gone(oid), "descriptor not freed after borrow drop"
    ray_tpu.kill(h)


def test_borrower_drops_first_owner_keeps_payload(cluster):
    from ray_tpu.mesh.device_objects import payload_oid

    h = Holder.remote()
    ref = _put_device_array(5.0)
    poid = payload_oid(ref.id)
    assert ray_tpu.get(h.hold.remote([ref]))
    assert ray_tpu.get(h.resolve.remote()) == 5.0
    time.sleep(1.0)
    assert ray_tpu.get(h.drop.remote())           # borrower lets go
    # The owner still holds its ref: the payload must survive the
    # borrow drop (the head forgets the borrow entry, nothing frees).
    time.sleep(GRACE * 4 + 1.0)
    assert _store().contains(poid), \
        "payload freed while the owner still held its ref"
    del ref
    gc.collect()
    assert _wait_gone(poid), "payload not freed after owner drop"
    ray_tpu.kill(h)


def test_escaped_never_resolved_frees_eagerly(cluster):
    from ray_tpu.mesh.device_objects import payload_oid

    @ray_tpu.remote(resources={"node1": 1})
    def touch(boxed):
        # Deserializes the ref (escape happened at pickling) but never
        # resolves it: no payload borrow is ever registered.
        return boxed[0] is not None

    ref = _put_device_array(1.0)
    poid = payload_oid(ref.id)
    assert ray_tpu.get(touch.remote([ref]))
    assert _store().contains(poid)                # spill happened
    del ref
    gc.collect()
    # No borrows: the owner's release frees the spill after the grace
    # window — eagerly, not under LRU pressure.
    assert _wait_gone(poid), "unborrowed payload not freed eagerly"
