"""Proxied remote driver (Ray Client parity, python/ray/util/client/):
ray_tpu.init(address="ray://host:port") drives a live cluster through
one proxy endpoint — tasks, actors, objects, named actors, waits,
errors — without shm or head access from the client side."""
import pytest

import ray_tpu
from ray_tpu.runtime import Cluster
from ray_tpu.runtime.client_proxy import start_proxy


@pytest.fixture(scope="module")
def proxied():
    import ray_tpu._private.worker as worker_mod
    if worker_mod.is_initialized():
        worker_mod.shutdown()
    c = Cluster(num_workers=2, resources_per_worker={"CPU": 2},
                connect=False)
    server, rt = start_proxy(c.node.head_address)
    ray_tpu.init(address=f"ray://{server.address}")
    yield c
    ray_tpu.shutdown()
    server.stop()
    c.shutdown()


def test_proxied_tasks_and_objects(proxied):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert ray_tpu.get(add.remote(2, 3), timeout=60) == 5
    # object refs round-trip symbolically through the proxy
    big = ray_tpu.put(list(range(1000)))
    assert ray_tpu.get(add.remote(0, 0), timeout=60) == 0

    @ray_tpu.remote
    def length(xs):
        return len(xs)
    assert ray_tpu.get(length.remote(big), timeout=60) == 1000


def test_proxied_wait_and_errors(proxied):
    from ray_tpu.exceptions import TaskError

    @ray_tpu.remote
    def boom():
        raise ValueError("proxied kaboom")

    with pytest.raises(TaskError, match="proxied kaboom") as ei:
        ray_tpu.get(boom.remote(), timeout=60)
    assert isinstance(ei.value.cause, ValueError)

    @ray_tpu.remote
    def one():
        return 1
    refs = [one.remote() for _ in range(4)]
    ready, rest = ray_tpu.wait(refs, num_returns=4, timeout=30)
    assert len(ready) == 4 and not rest


def test_proxied_actors(proxied):
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def add(self, k):
            self.n += k
            return self.n

    c = Counter.options(name="proxy-counter").remote()
    assert ray_tpu.get(c.add.remote(2), timeout=60) == 2
    assert ray_tpu.get(c.add.remote(3), timeout=60) == 5
    # named lookup through the proxy
    again = ray_tpu.get_actor("proxy-counter")
    assert ray_tpu.get(again.add.remote(1), timeout=60) == 6
    ray_tpu.kill(c)


def test_proxied_state_and_resources(proxied):
    assert ray_tpu.cluster_resources()["CPU"] >= 4
    from ray_tpu import state
    assert isinstance(state.list_tasks(), list)


def test_proxied_placement_group(proxied):
    from ray_tpu.util import placement_group, remove_placement_group

    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.wait(10)
    assert pg.is_ready()
    assert pg.bundle_specs == [{"CPU": 1.0}]
    rec = ray_tpu.get(pg.ready(), timeout=30)
    assert rec["ready"] is True
    remove_placement_group(pg)
