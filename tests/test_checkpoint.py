"""Checkpoint tests (reference analogue: python/ray/air/tests/test_checkpoints.py)."""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.air import Checkpoint


def test_dict_roundtrip():
    ckpt = Checkpoint.from_dict({"step": 3, "note": "hi"})
    assert ckpt.to_dict() == {"step": 3, "note": "hi"}
    assert ckpt["step"] == 3
    assert "note" in ckpt
    assert ckpt.get("missing", 7) == 7


def test_directory_roundtrip_with_arrays(tmp_path):
    params = {"w": jnp.arange(8.0), "b": np.ones((4,), np.float32)}
    ckpt = Checkpoint.from_dict({
        "params": params, "step": 42, "name": "trial-1"})
    path = ckpt.to_directory(str(tmp_path / "ckpt"))
    restored = Checkpoint.from_directory(path).to_dict()
    assert restored["step"] == 42
    assert restored["name"] == "trial-1"
    np.testing.assert_array_equal(np.asarray(restored["params"]["w"]),
                                  np.arange(8.0))
    np.testing.assert_array_equal(np.asarray(restored["params"]["b"]),
                                  np.ones((4,)))


def test_constructor_validation():
    with pytest.raises(ValueError):
        Checkpoint()
    with pytest.raises(FileNotFoundError):
        Checkpoint.from_directory("/nonexistent/path")


def test_sharded_restore(tmp_path, cpu_mesh_devices):
    from jax.sharding import PartitionSpec as P
    from ray_tpu.air.checkpoint import restore_sharded
    from ray_tpu.mesh import ShardingRules, create_mesh

    mesh = create_mesh({"data": 8})
    w = jnp.arange(64.0).reshape(8, 8)
    path = Checkpoint.from_dict({"params": {"w": w}}).to_directory(
        str(tmp_path / "s"))
    rules = ShardingRules([(r"w$", P("data", None))])
    restored = restore_sharded(
        path, {"params": {"w": jax.ShapeDtypeStruct((8, 8), jnp.float32)}},
        mesh=mesh, rules=rules)
    rw = restored["params"]["w"]
    np.testing.assert_array_equal(np.asarray(rw), np.asarray(w))
    # Restored shards are placed per the rules (8-way split on dim 0).
    assert {s.data.shape for s in rw.addressable_shards} == {(1, 8)}
