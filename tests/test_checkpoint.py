"""Checkpoint tests (reference analogue: python/ray/air/tests/test_checkpoints.py)."""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.air import Checkpoint


def test_dict_roundtrip():
    ckpt = Checkpoint.from_dict({"step": 3, "note": "hi"})
    assert ckpt.to_dict() == {"step": 3, "note": "hi"}
    assert ckpt["step"] == 3
    assert "note" in ckpt
    assert ckpt.get("missing", 7) == 7


def test_directory_roundtrip_with_arrays(tmp_path):
    params = {"w": jnp.arange(8.0), "b": np.ones((4,), np.float32)}
    ckpt = Checkpoint.from_dict({
        "params": params, "step": 42, "name": "trial-1"})
    path = ckpt.to_directory(str(tmp_path / "ckpt"))
    restored = Checkpoint.from_directory(path).to_dict()
    assert restored["step"] == 42
    assert restored["name"] == "trial-1"
    np.testing.assert_array_equal(np.asarray(restored["params"]["w"]),
                                  np.arange(8.0))
    np.testing.assert_array_equal(np.asarray(restored["params"]["b"]),
                                  np.ones((4,)))


def test_constructor_validation():
    with pytest.raises(ValueError):
        Checkpoint()
    with pytest.raises(FileNotFoundError):
        Checkpoint.from_directory("/nonexistent/path")


def test_sharded_restore(tmp_path, cpu_mesh_devices):
    from jax.sharding import PartitionSpec as P
    from ray_tpu.air.checkpoint import restore_sharded
    from ray_tpu.mesh import ShardingRules, create_mesh

    mesh = create_mesh({"data": 8})
    w = jnp.arange(64.0).reshape(8, 8)
    path = Checkpoint.from_dict({"params": {"w": w}}).to_directory(
        str(tmp_path / "s"))
    rules = ShardingRules([(r"w$", P("data", None))])
    restored = restore_sharded(
        path, {"params": {"w": jax.ShapeDtypeStruct((8, 8), jnp.float32)}},
        mesh=mesh, rules=rules)
    rw = restored["params"]["w"]
    np.testing.assert_array_equal(np.asarray(rw), np.asarray(w))
    # Restored shards are placed per the rules (8-way split on dim 0).
    assert {s.data.shape for s in rw.addressable_shards} == {(1, 8)}


# ---------------------------------------------------------------------------
# Durability: manifests, torn-checkpoint detection, atomic commit
# ---------------------------------------------------------------------------


def _commit(tmp_path, name="c", step=7, extra=None):
    data = {"w": np.arange(4.0), "step": step}
    data.update(extra or {})
    return Checkpoint.from_dict(data).to_directory(
        str(tmp_path / name), step=step)


def test_to_directory_writes_manifest(tmp_path):
    from ray_tpu.air.checkpoint import (MANIFEST_FILE, load_manifest,
                                        verify_checkpoint_dir)
    path = _commit(tmp_path, step=42)
    manifest = load_manifest(path)
    assert manifest["step"] == 42
    assert manifest["files"], "manifest must list the payload files"
    for rel, rec in manifest["files"].items():
        assert rel != MANIFEST_FILE
        assert len(rec["sha256"]) == 64
        assert rec["bytes"] == os.path.getsize(os.path.join(path, rel))
    assert verify_checkpoint_dir(path)[0]
    assert verify_checkpoint_dir(path, deep=True)[0]


def test_from_directory_refuses_missing_manifest(tmp_path):
    from ray_tpu.air import InvalidCheckpointError
    bogus = tmp_path / "not_a_ckpt"
    bogus.mkdir()
    (bogus / "meta.pkl").write_bytes(b"whatever")
    with pytest.raises(InvalidCheckpointError) as ei:
        Checkpoint.from_directory(str(bogus))
    assert "manifest" in str(ei.value)


def test_from_directory_refuses_invalid_manifest(tmp_path):
    from ray_tpu.air import InvalidCheckpointError
    from ray_tpu.air.checkpoint import MANIFEST_FILE
    bogus = tmp_path / "bad_manifest"
    bogus.mkdir()
    (bogus / MANIFEST_FILE).write_text("{not json")
    with pytest.raises(InvalidCheckpointError):
        Checkpoint.from_directory(str(bogus))
    (bogus / MANIFEST_FILE).write_text('{"format": 99, "files": {}}')
    with pytest.raises(InvalidCheckpointError):
        Checkpoint.from_directory(str(bogus))


def test_from_directory_refuses_torn_payload(tmp_path):
    """Truncating a payload file after commit = torn copy; the shallow
    size check already refuses it."""
    from ray_tpu.air import InvalidCheckpointError
    from ray_tpu.air.checkpoint import load_manifest
    path = _commit(tmp_path)
    rel = sorted(load_manifest(path)["files"])[0]
    full = os.path.join(path, rel)
    with open(full, "rb") as f:
        content = f.read()
    with open(full, "wb") as f:
        f.write(content[: max(0, len(content) - 1)])
    with pytest.raises(InvalidCheckpointError):
        Checkpoint.from_directory(str(path))


def test_deep_verify_catches_same_size_corruption(tmp_path):
    """Bit rot that preserves file size passes shallow verification
    but MUST fail the deep (re-hash) pass latest_complete() uses."""
    from ray_tpu.air.checkpoint import load_manifest, verify_checkpoint_dir
    path = _commit(tmp_path)
    rel = sorted(load_manifest(path)["files"])[0]
    full = os.path.join(path, rel)
    with open(full, "r+b") as f:
        first = f.read(1)
        f.seek(0)
        f.write(bytes([first[0] ^ 0xFF]))
    ok_shallow, _, manifest = verify_checkpoint_dir(path)
    ok_deep, reason, _ = verify_checkpoint_dir(path, deep=True)
    assert ok_shallow
    assert manifest["files"], "verify must return the parsed manifest"
    assert not ok_deep
    assert "hash" in reason


def test_commit_displaces_existing_directory(tmp_path):
    """Re-saving over an old checkpoint swaps it atomically — the
    target is never a half-written mix of the two."""
    from ray_tpu.air.checkpoint import load_manifest
    target = tmp_path / "slot"
    Checkpoint.from_dict({"v": 1, "step": 1}).to_directory(
        str(target), step=1)
    Checkpoint.from_dict({"v": 2, "step": 2}).to_directory(
        str(target), step=2)
    assert load_manifest(str(target))["step"] == 2
    assert Checkpoint.from_directory(str(target)).to_dict()["v"] == 2
