"""Device-array (HBM) object layer tests.

Prove the zero-copy contract the README advertises (replacing the
reference's plasma contract, src/ray/common/ray_object.h:28): a put of a
jax Array never copies it, same-process gets return the identical living
Array, cross-process consumers resolve via the one escape-time spill,
and SPMD gangs share sharded arrays by handle with zero data motion.
"""
import gc
import time

import numpy as np
import pytest


def _fresh_cluster(num_workers=1, cpus=4):
    import ray_tpu._private.worker as worker_mod
    from ray_tpu.runtime import Cluster
    if worker_mod.is_initialized():
        worker_mod.shutdown()
    return Cluster(num_workers=num_workers,
                   resources_per_worker={"CPU": cpus})


def _sharded_array():
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ray_tpu.mesh.device_mesh import create_mesh
    mesh = create_mesh({"data": 8})
    x = jnp.arange(64 * 16, dtype=jnp.float32).reshape(64, 16)
    return jax.device_put(x, NamedSharding(mesh, P("data"))), mesh


def test_put_get_identity_no_device_host_copy():
    """The round-trip returns the *identical* Array object — no
    device->host transfer, no new buffers (buffer identity via `is`)."""
    import ray_tpu
    from ray_tpu.mesh import device_objects
    with _fresh_cluster():
        arr, _ = _sharded_array()
        ref = ray_tpu.put(arr)
        out = ray_tpu.get(ref)
        assert out is arr          # the living HBM array, not a copy
        # and no host spill happened: the payload object must not exist
        oid = ref.id
        assert not device_objects.table().was_spilled(oid)
        # repeated gets keep returning the same object
        assert ray_tpu.get(ref) is arr


def test_handle_metadata_carries_mesh_sharding_buffers():
    import ray_tpu
    from ray_tpu._private.serialization import loads
    from ray_tpu._private.worker import global_worker
    with _fresh_cluster():
        arr, mesh = _sharded_array()
        ref = ray_tpu.put(arr)
        plane = global_worker().runtime.plane
        status, handle = loads(plane.get_bytes(ref.id, timeout_ms=1000))
        assert status == "devobj"
        assert handle.shape == (64, 16)
        assert handle.dtype == "float32"
        assert dict(handle.mesh_axes)["data"] == 8
        assert handle.pspec[0] == "data"
        assert len(handle.buffers) == 8           # one per device
        total = sum(b[2] for b in handle.buffers)
        assert total == 64 * 16 * 4               # bytes accounted
        assert handle.fully_addressable


def test_cross_process_get_via_escape_spill():
    """Passing the ref to a task spills exactly one host copy; the
    worker re-materializes with the handle's sharding."""
    import ray_tpu
    from ray_tpu.mesh import device_objects
    with _fresh_cluster(num_workers=1):
        arr, _ = _sharded_array()
        ref = ray_tpu.put(arr)
        assert not device_objects.table().was_spilled(ref.id)

        @ray_tpu.remote
        def consume(x):
            import jax
            assert isinstance(x, jax.Array)
            # the worker re-materialized on its own devices with the
            # advertised sharding (8-way over 'data' on the cpu mesh)
            return (float(x.sum()),
                    len(x.sharding.device_set),
                    type(x.sharding).__name__)

        total, ndev, kind = ray_tpu.get(consume.remote(ref))
        assert total == float(np.arange(64 * 16, dtype=np.float32).sum())
        assert ndev == 8
        assert kind == "NamedSharding"
        # escape happened at submission: the spill now exists
        assert device_objects.table().was_spilled(ref.id)


def test_owner_get_still_zero_copy_after_escape():
    import ray_tpu
    with _fresh_cluster(num_workers=1):
        arr, _ = _sharded_array()
        ref = ray_tpu.put(ref_arr := arr)

        @ray_tpu.remote
        def touch(x):
            return float(x[0, 0])

        assert ray_tpu.get(touch.remote(ref)) == 0.0
        # the owner's get is STILL the living array after the spill
        assert ray_tpu.get(ref) is ref_arr


def test_eager_free_drops_hbm_pin():
    import ray_tpu
    from ray_tpu.mesh import device_objects
    with _fresh_cluster():
        arr, _ = _sharded_array()
        ref = ray_tpu.put(arr)
        oid = ref.id
        assert device_objects.table().is_registered(oid)
        del ref
        gc.collect()
        deadline = time.time() + 5
        while time.time() < deadline and \
                device_objects.table().is_registered(oid):
            time.sleep(0.05)
        assert not device_objects.table().is_registered(oid)


def test_reshard_device_to_device():
    import jax
    from ray_tpu.mesh.device_objects import reshard
    arr, mesh = _sharded_array()
    out = reshard(arr, axes={"data": 2, "tensor": 4},
                  spec=("data", "tensor"))
    assert isinstance(out, jax.Array)
    assert len(out.sharding.device_set) == 8
    np.testing.assert_array_equal(np.asarray(out), np.asarray(arr))


def test_gang_put_local_runtime_identity(rt):
    """gang_put on the local runtime: table + in-process store."""
    from ray_tpu.mesh.device_objects import gang_drop, gang_put
    import ray_tpu
    arr, _ = _sharded_array()
    ref = gang_put(arr, "weights-epoch-0")
    assert ray_tpu.get(ref) is arr
    gang_drop("weights-epoch-0")


def test_gang_put_cross_process_shared_by_handle():
    """A 2-process SPMD gang shares a sharded array by handle: each
    rank's get resolves to its LOCAL living Array (no data motion;
    only the descriptor crossed processes)."""
    import ray_tpu
    import ray_tpu._private.worker as worker_mod
    from ray_tpu.runtime import Cluster
    if worker_mod.is_initialized():
        worker_mod.shutdown()
    with Cluster(num_workers=2, resources_per_worker={"CPU": 2}):
        from ray_tpu.train import JaxTrainer, ScalingConfig
        from ray_tpu.air import session

        def loop(config):
            import jax
            import jax.numpy as jnp
            import numpy as onp
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P
            from ray_tpu.mesh.device_objects import gang_put, table
            mesh = session.get_mesh()
            rank = session.get_world_rank()
            # every rank holds its view of the same global array (its
            # addressable shards live in ITS devices)
            x = jax.make_array_from_process_local_data(
                NamedSharding(mesh, P("dcn")),
                onp.full((1, 4), float(rank + 1), onp.float32))
            ref = gang_put(x, "gang-shared")
            got = ray_tpu.get(ref)
            ok = 1.0 if (got is x and
                         table().is_registered(ref.id)) else 0.0
            # cross-rank proof: sum each rank's ok flag over dcn so
            # rank 0's report certifies BOTH ranks resolved locally
            g = jax.make_array_from_process_local_data(
                NamedSharding(mesh, P("dcn")),
                onp.full((1,), ok, onp.float32))
            session.report({
                "rank": rank,
                "ok_sum": float(jax.jit(jnp.sum)(g)),
                "value_sum": float(jax.jit(jnp.sum)(got)),
                "n_procs": jax.process_count(),
            })

        res = JaxTrainer(
            loop,
            scaling_config=ScalingConfig(
                num_workers=2, mesh={"dcn": 2, "data": -1},
                jax_distributed=True,
                placement_strategy="STRICT_SPREAD")).fit()
        assert res.ok, res.error
        m = res.metrics
        assert m["n_procs"] == 2
        assert m["ok_sum"] == 2.0          # both ranks: local identity
        assert m["value_sum"] == 1.0 * 4 + 2.0 * 4


def test_non_array_puts_unaffected():
    import ray_tpu
    with _fresh_cluster():
        ref = ray_tpu.put({"a": np.ones(4), "b": [1, 2]})
        out = ray_tpu.get(ref)
        np.testing.assert_array_equal(out["a"], np.ones(4))
        assert out["b"] == [1, 2]
