"""Engine replica pool tests (serve/engine_pool.py).

Two layers, mirroring the reference's replica-set tests
(python/ray/serve/tests/test_replica_scheduler.py): routing policy
and lifecycle state machine against scripted fake engines
(deterministic load reports, no model in the loop), then the
end-to-end contract against real tiny-Llama engines — token parity
across replicas, replica-kill recovery with zero lost requests,
drain, and pool-wide quiescence (no replica, dead or alive, may
leak a page).
"""
import threading
import time

import jax.numpy as jnp
import pytest

from ray_tpu.models.llama import Llama, llama_tiny
from ray_tpu.serve.engine import LLMEngine
from ray_tpu.serve.engine_pool import (DEAD, DRAINING, HEALTHY,
                                       EnginePool)
from ray_tpu.serve.errors import (DeadlineExceeded, EngineDraining,
                                  EngineOverloaded, EngineShutdown)
from ray_tpu.serve.faults import (FaultInjector, check_pool_quiesced,
                                  check_quiesced)
from ray_tpu.serve.prefix_cache import path_hashes


@pytest.fixture(scope="module")
def tiny_model():
    # fp32 so greedy decode is bit-identical across replicas (the
    # parity tests compare pool output against generate())
    cfg = llama_tiny(dtype=jnp.float32)
    model = Llama(cfg)
    import jax
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    return model, params


@pytest.fixture(autouse=True)
def _no_page_leaks(monkeypatch):
    """Same invariant net as test_llm_engine.py, pool-wide: every
    real engine built in a test — including ones the pool killed or
    rebuilt — must end with allocator occupancy == prefix-cache
    residency."""
    created = []
    orig = LLMEngine.__init__

    def record(self, *args, **kwargs):
        orig(self, *args, **kwargs)
        created.append(self)

    monkeypatch.setattr(LLMEngine, "__init__", record)
    yield
    for eng in created:
        cached = (eng.prefix_cache.cached_pages
                  if eng.prefix_cache is not None else 0)
        occ = eng.alloc.occupancy()
        assert occ == cached, (
            f"engine leaked pages at teardown: occupancy {occ} != "
            f"prefix-cache residency {cached}; leaked ids "
            f"{sorted(eng.alloc.leak_report())[:16]}")


def _reference_completion(model, params, prompt, n):
    import numpy as np
    from ray_tpu.models.llama import generate
    out = generate(model, params, jnp.asarray([prompt], jnp.int32),
                   max_new_tokens=n, temperature=0.0)
    return np.asarray(out)[0, len(prompt):].tolist()


# ------------------------------------------------------- fake engines


class FakeHandle:
    """Scripted request handle: streams ``tokens``, then optionally
    raises ``exc`` (set ``engine._stopped`` first to model a replica
    death rather than a request failure)."""

    def __init__(self, engine, tokens, exc=None):
        self._engine = engine
        self._tokens = list(tokens)
        self._exc = exc
        self.cancelled = False

    def stream(self):
        for t in self._tokens:
            yield t
        if self._exc is not None:
            if self._engine.die_on_failure:
                self._engine._stopped = True
            raise self._exc

    def cancel(self):
        self.cancelled = True
        return True


class FakeEngine:
    """A replica engine reduced to the surface the pool touches:
    load_report + submit + lifecycle flags, all scripted."""

    def __init__(self, idx, *, outstanding=0, digest=frozenset(),
                 max_queued=None, queue_depth=0, retry_after=1.0,
                 page_size=16):
        self.idx = idx
        self.Pg = page_size
        self._stopped = False
        self._draining = False
        self.die_on_failure = False
        self.outstanding = outstanding
        self.digest = digest
        self.max_queued = max_queued
        self.queue_depth = queue_depth
        self.retry_after = retry_after
        self.stats = {"submitted": 0}
        self.ttfts_s = []
        self.submits = []           # (prompt, max_new_tokens, deadline)
        self.script = []            # queued submit outcomes
        self.started = False
        self.shutdowns = 0

    def start(self):
        self.started = True
        return self

    def submit(self, prompt, max_new_tokens=64, deadline_s=None):
        if self._stopped:
            raise EngineShutdown("engine stopped")
        if self._draining:
            raise EngineDraining("draining")
        self.submits.append((list(prompt), max_new_tokens, deadline_s))
        self.stats["submitted"] += 1
        out = self.script.pop(0) if self.script else [1, 2]
        if isinstance(out, BaseException):
            raise out
        if isinstance(out, FakeHandle):
            return out
        return FakeHandle(self, out)

    def shutdown(self):
        self.shutdowns += 1
        self._stopped = True

    def drain(self):
        self._draining = True

    def wait_idle(self, timeout_s=30.0):
        return True

    def is_idle(self):
        return True

    def load_report(self):
        return {"free_slots": 4, "free_pages": 100,
                "queue_depth": self.queue_depth,
                "outstanding_tokens": self.outstanding,
                "max_queued": self.max_queued,
                "shed_retry_after_s": self.retry_after,
                "draining": self._draining,
                "stopped": self._stopped,
                "prefix_digest": self.digest}

    def prefix_stats(self):
        return None

    def spec_stats(self):
        return None

    def lifecycle_stats(self):
        return {"max_queued": self.max_queued, "max_retries": 2,
                "retry_backoff_s": 0.02, "shed": 0}


def _fake_pool(fakes, **kw):
    pool = EnginePool(lambda i: fakes[i], len(fakes), **kw)
    assert all(f.started for f in fakes)
    return pool


# --------------------------------------------------- routing (fakes)


def test_pool_rejects_zero_replicas():
    with pytest.raises(ValueError):
        EnginePool(lambda i: FakeEngine(i), 0)


def test_p2c_routes_least_outstanding():
    fakes = [FakeEngine(0, outstanding=500),
             FakeEngine(1, outstanding=5)]
    pool = _fake_pool(fakes)
    h = pool.submit([1, 2, 3])
    assert h.replica_idx == 1
    assert pool.route_stats["route_p2c"] == 1
    assert pool.route_stats["affinity_hits"] == 0
    pool.shutdown()


def test_affinity_routes_longest_cached_prefix():
    prompt = list(range(1, 65))           # 4 pages at Pg=16
    hashes = path_hashes(prompt, 16)
    fakes = [FakeEngine(0, outstanding=0,
                        digest=frozenset(hashes[:1])),
             FakeEngine(1, outstanding=900,     # busier, but hotter
                        digest=frozenset(hashes[:3]))]
    pool = _fake_pool(fakes)
    h = pool.submit(prompt)
    assert h.replica_idx == 1
    assert pool.route_stats["route_affinity"] == 1
    assert pool.route_stats["affinity_hits"] == 1
    assert pool.route_stats["affinity_hit_pages"] == 3
    pool.shutdown()


def test_sticky_session_rehomes_after_death():
    fakes = [FakeEngine(0), FakeEngine(1)]
    pool = _fake_pool(fakes)
    first = pool.submit([1, 2], session_id="s").replica_idx
    again = pool.submit([3, 4], session_id="s").replica_idx
    assert again == first
    assert pool.route_stats["sticky_hits"] >= 1
    # the sticky replica dies: the session must re-home, not 404
    fakes[first]._stopped = True
    pool._note_replica_death(pool.replica(first))
    assert pool.replica(first).state == DEAD
    rehomed = pool.submit([5, 6], session_id="s").replica_idx
    assert rehomed == 1 - first
    pool.shutdown()


def test_spill_when_affinity_target_saturated():
    prompt = list(range(1, 33))
    hashes = path_hashes(prompt, 16)
    fakes = [FakeEngine(0, digest=frozenset(hashes),
                        max_queued=2, queue_depth=2),   # full
             FakeEngine(1)]
    pool = _fake_pool(fakes)
    h = pool.submit(prompt)
    assert h.replica_idx == 1
    assert pool.route_stats["spills"] == 1
    assert pool.route_stats["route_p2c"] == 1
    assert pool.pool_stats()["spill_rate"] == 1.0
    pool.shutdown()


def test_all_shed_aggregates_max_retry_after():
    fakes = [FakeEngine(0, retry_after=2.0),
             FakeEngine(1, retry_after=5.0)]
    fakes[0].script.append(EngineOverloaded("full",
                                            retry_after_s=2.0))
    fakes[1].script.append(EngineOverloaded("full",
                                            retry_after_s=5.0))
    pool = _fake_pool(fakes)
    with pytest.raises(EngineOverloaded) as ei:
        pool.submit([1, 2, 3])
    # the pool's Retry-After hint must be honest for the WHOLE pool:
    # max over replicas, never the first shed's smaller hint
    assert ei.value.retry_after_s == 5.0
    assert pool.route_stats["all_shed"] == 1
    pool.shutdown()


def test_saturated_everywhere_sheds_with_report_hints():
    fakes = [FakeEngine(0, max_queued=1, queue_depth=1,
                        retry_after=0.5),
             FakeEngine(1, max_queued=1, queue_depth=3,
                        retry_after=4.0)]
    pool = _fake_pool(fakes)
    with pytest.raises(EngineOverloaded) as ei:
        pool.submit([1])
    assert ei.value.retry_after_s == 4.0
    assert fakes[0].submits == [] and fakes[1].submits == []
    pool.shutdown()


def test_no_healthy_replicas_is_typed_shutdown():
    fakes = [FakeEngine(0), FakeEngine(1)]
    pool = _fake_pool(fakes)
    for f in fakes:
        f._stopped = True
    with pytest.raises(EngineShutdown):
        pool.submit([1, 2])
    pool.shutdown()


def test_submit_routes_around_replica_that_died_racing():
    # replica 0 dies AFTER the routing snapshot: submit raises
    # EngineShutdown, the pool marks it dead and retries replica 1
    fakes = [FakeEngine(0, outstanding=0),
             FakeEngine(1, outstanding=10)]
    fakes[0].script.append(EngineShutdown("died mid-submit"))
    fakes[0]._make_stopped_on_script = True
    orig_submit = FakeEngine.submit

    def dying_submit(self, prompt, **kw):
        if self.script and isinstance(self.script[0], EngineShutdown):
            self._stopped = True
        return orig_submit(self, prompt, **kw)

    fakes[0].submit = dying_submit.__get__(fakes[0])
    pool = _fake_pool(fakes)
    h = pool.submit([1, 2])
    assert h.replica_idx == 1
    assert pool.replica(0).state == DEAD
    assert pool.route_stats["replica_deaths"] == 1
    pool.shutdown()


# ------------------------------------------- recovery + handle (fakes)


def test_unstreamed_death_resubmits_token_identically():
    fakes = [FakeEngine(0, outstanding=0),
             FakeEngine(1, outstanding=10)]
    # replica 0 accepts, then dies before emitting anything
    fakes[0].die_on_failure = True
    fakes[0].script.append(FakeHandle(fakes[0], [],
                                      RuntimeError("device lost")))
    fakes[1].script.append([7, 8, 9])
    pool = _fake_pool(fakes)
    h = pool.submit([1, 2])
    assert h.replica_idx == 0
    assert h.result() == [7, 8, 9]
    assert h.replica_idx == 1
    assert pool.route_stats["requeues"] == 1
    assert pool.route_stats["replica_deaths"] == 1
    assert h.ttft_s is not None
    pool.shutdown()


def test_partially_streamed_death_fails_typed():
    fakes = [FakeEngine(0, outstanding=0),
             FakeEngine(1, outstanding=10)]
    fakes[0].die_on_failure = True
    fakes[0].script.append(FakeHandle(fakes[0], [7, 8],
                                      RuntimeError("device lost")))
    pool = _fake_pool(fakes)
    h = pool.submit([1, 2])
    got = []
    with pytest.raises(EngineShutdown, match="cannot be replayed"):
        for t in h.stream():
            got.append(t)
    assert got == [7, 8]           # delivered tokens stay delivered
    assert h.error is not None and h.done
    assert pool.route_stats["requeues"] == 0
    assert fakes[1].submits == []  # at-most-once: no resubmission
    pool.shutdown()


def test_request_level_failure_is_not_a_replica_death():
    fakes = [FakeEngine(0), FakeEngine(1)]
    fakes[0].script.append(FakeHandle(fakes[0], [],
                                      DeadlineExceeded("too slow")))
    fakes[1].script.append(FakeHandle(fakes[1], [],
                                      DeadlineExceeded("too slow")))
    pool = _fake_pool(fakes)
    h = pool.submit([1, 2])
    with pytest.raises(DeadlineExceeded):
        h.result()
    assert pool.route_stats["replica_deaths"] == 0
    assert pool.route_stats["requeues"] == 0
    assert pool.replica(0).state == HEALTHY
    assert pool.replica(1).state == HEALTHY
    pool.shutdown()


def test_resubmit_cap_fails_typed():
    # every replica dies on first use; with max_resubmits=1 the
    # request gets exactly one more try, then a typed failure
    fakes = [FakeEngine(i) for i in range(3)]
    for f in fakes:
        f.die_on_failure = True
        f.script.append(FakeHandle(f, [], RuntimeError("boom")))
    pool = _fake_pool(fakes, max_resubmits=1)
    h = pool.submit([1, 2])
    with pytest.raises(EngineShutdown):
        h.result()
    assert pool.route_stats["requeues"] == 1
    pool.shutdown()


def test_deadline_shrinks_across_resubmit():
    fakes = [FakeEngine(0, outstanding=0),
             FakeEngine(1, outstanding=10)]
    fakes[0].die_on_failure = True
    fakes[0].script.append(FakeHandle(fakes[0], [],
                                      RuntimeError("boom")))
    fakes[1].script.append([5])
    pool = _fake_pool(fakes)
    h = pool.submit([1, 2], deadline_s=30.0)
    assert h.result() == [5]
    # replica 0 saw the full deadline; the resubmission to replica 1
    # must carry only what REMAINS of it
    assert fakes[0].submits[0][2] == 30.0
    remaining = fakes[1].submits[0][2]
    assert remaining is not None and 0 < remaining < 30.0
    pool.shutdown()


# --------------------------------------------------- lifecycle (fakes)


def test_drain_rebuilds_replica_with_new_generation():
    built = []

    def factory(i):
        f = FakeEngine(i)
        built.append(f)
        return f

    pool = EnginePool(factory, 2)
    old = pool.replica(0).engine
    assert pool.drain(0) is True
    rep = pool.replica(0)
    assert rep.state == HEALTHY
    assert rep.generation == 1
    assert rep.engine is not old
    assert old._draining and old.shutdowns >= 1
    assert pool.route_stats["drains"] == 1
    assert pool.route_stats["restarts"] == 1
    # only a healthy replica may drain
    pool.replica(1).state = DRAINING
    with pytest.raises(RuntimeError):
        pool.drain(1)
    pool.replica(1).state = HEALTHY
    pool.shutdown()


def test_restart_dead_rebuilds_only_dead_replicas():
    fakes = {0: FakeEngine(0), 1: FakeEngine(1)}

    def factory(i):
        f = FakeEngine(i)
        fakes[i] = f
        return f

    pool = EnginePool(lambda i: fakes[i], 2)
    fakes[0]._stopped = True
    pool._note_replica_death(pool.replica(0))
    pool._factory = factory
    assert pool.restart_dead() == 1
    assert pool.replica(0).state == HEALTHY
    assert pool.replica(0).generation == 1
    assert pool.replica(1).generation == 0
    assert pool.healthy_count() == 2
    pool.shutdown()


def test_pool_shutdown_is_typed_and_idempotent():
    fakes = [FakeEngine(0), FakeEngine(1)]
    pool = _fake_pool(fakes)
    pool.shutdown()
    pool.shutdown()
    assert all(r.state == DEAD for r in [pool.replica(0),
                                         pool.replica(1)])
    with pytest.raises(EngineShutdown):
        pool.submit([1])


def test_pool_load_report_aggregates_and_maxes_hint():
    fakes = [FakeEngine(0, outstanding=10, queue_depth=1,
                        retry_after=0.5),
             FakeEngine(1, outstanding=30, queue_depth=2,
                        retry_after=3.5)]
    pool = _fake_pool(fakes)
    rpt = pool.load_report()
    assert rpt["free_slots"] == 8
    assert rpt["queue_depth"] == 3
    assert rpt["outstanding_tokens"] == 40
    assert rpt["shed_retry_after_s"] == 3.5
    assert rpt["healthy_replicas"] == 2 and rpt["n_replicas"] == 2
    assert rpt["stopped"] is False
    pool.shutdown()
    assert pool.load_report()["stopped"] is True


def test_pool_stats_rates_and_replica_rows():
    fakes = [FakeEngine(0), FakeEngine(1)]
    pool = _fake_pool(fakes)
    for _ in range(4):
        pool.submit([1, 2]).result()
    ps = pool.pool_stats()
    assert ps["routed"] == 4
    assert ps["affinity_hit_rate"] == 0.0     # no digests anywhere
    assert ps["spill_rate"] == 0.0
    assert ps["n_replicas"] == 2
    assert [r["idx"] for r in ps["replicas"]] == [0, 1]
    assert pool.stats["submitted"] == 4       # summed engine counters
    pool.shutdown()


# ----------------------------------------------------- real engines


def test_pool_parity_and_affinity_compounding(tiny_model):
    """Two replicas, shared-prefix prompts, two waves: every
    completion token-identical to generate(); the second wave routes
    by affinity (each prompt re-hits the replica that cached it)."""
    model, params = tiny_model
    pool = EnginePool(
        lambda i: LLMEngine(model, params, max_slots=2, page_size=8,
                            n_pages=64, chunk=4, temperature=0.0,
                            seed=i, prefix_cache=True),
        2)
    shared = [3, 1, 4, 1, 5, 9, 2, 6]
    prompts = [shared + [10 + i, 20 + i, 30 + i] for i in range(4)]
    want = {i: _reference_completion(model, params, p, 10)
            for i, p in enumerate(prompts)}
    for wave in range(2):
        handles = [(i, pool.submit(p, max_new_tokens=10))
                   for i, p in enumerate(prompts)]
        for i, h in handles:
            assert h.result() == want[i], (wave, i)
    assert pool.route_stats["affinity_hits"] > 0
    assert pool.pool_stats()["affinity_hit_rate"] > 0
    pool.shutdown()
    check_pool_quiesced(pool)


def test_replica_kill_recovers_unstreamed_requests(tiny_model):
    """FaultInjector kills replica 0 mid-run: every request either
    completes token-identically (resubmitted to the survivor if it
    had not streamed) or fails typed EngineShutdown. Nothing hangs,
    nothing is lost, no replica leaks pages."""
    model, params = tiny_model
    inj = FaultInjector()
    inj.kill_replica(round=6)

    def factory(idx):
        return LLMEngine(model, params, max_slots=2, page_size=16,
                         n_pages=64, chunk=2, prefill_chunk=16,
                         temperature=0.0, eos_id=-1, seed=idx,
                         fault_injector=inj if idx == 0 else None)

    pool = EnginePool(factory, 2)
    import numpy as np
    rng = np.random.RandomState(11)
    prompts = [rng.randint(1, 50, size=10).tolist() for _ in range(6)]
    want = [_reference_completion(model, params, p, 16)
            for p in prompts]
    results = [None] * len(prompts)

    def consume(i, h):
        try:
            results[i] = ("ok", h.result())
        except EngineShutdown:
            results[i] = ("typed", None)

    handles = [pool.submit(p, max_new_tokens=16) for p in prompts]
    threads = [threading.Thread(target=consume, args=(i, h))
               for i, h in enumerate(handles)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert all(not t.is_alive() for t in threads), "request hung"
    assert all(r is not None for r in results), "request lost"
    ok = [i for i, r in enumerate(results) if r[0] == "ok"]
    for i in ok:
        assert results[i][1] == want[i], i
    assert pool.route_stats["replica_deaths"] == 1
    assert pool.replica(0).state == DEAD
    # the kill actually interrupted work: something was resubmitted
    # or failed typed (a no-op kill would prove nothing)
    assert pool.route_stats["requeues"] + (len(results) - len(ok)) > 0
    pool.shutdown()
    check_pool_quiesced(pool)


def test_mid_stream_kill_fails_typed_after_tokens(tiny_model):
    """A request that already streamed tokens when its replica died
    must surface EngineShutdown — not silently resubmit (duplicate
    tokens) and not hang."""
    model, params = tiny_model
    inj = FaultInjector()
    inj.kill_replica(round=6)
    pool = EnginePool(
        lambda i: LLMEngine(model, params, max_slots=1, page_size=16,
                            n_pages=32, chunk=2, prefill_chunk=16,
                            temperature=0.0, eos_id=-1, seed=i,
                            fault_injector=inj),
        1)
    h = pool.submit([5, 9, 2, 7], max_new_tokens=32)
    got = []
    with pytest.raises(EngineShutdown):
        for t in h.stream():
            got.append(t)
    # rounds are deterministic on CPU: round 6 lands mid-decode, so
    # tokens streamed before the kill and the typed partial-stream
    # path (not the resubmit path) is what fired
    assert got, "kill landed before first token; expected mid-stream"
    assert got == _reference_completion(model, params,
                                        [5, 9, 2, 7], 32)[:len(got)]
    assert h.error is not None
    pool.shutdown()
    check_pool_quiesced(pool)


def test_drain_completes_inflight_and_rebuilds(tiny_model):
    model, params = tiny_model
    pool = EnginePool(
        lambda i: LLMEngine(model, params, max_slots=2, page_size=8,
                            n_pages=32, chunk=4, temperature=0.0,
                            seed=i),
        2)
    prompt = [5, 9, 2, 7, 11]
    want = _reference_completion(model, params, prompt, 8)
    h = pool.submit(prompt, max_new_tokens=8)
    idx = h.replica_idx
    assert pool.drain(idx) is True      # waits for the request
    assert h.result() == want           # finished, not axed
    rep = pool.replica(idx)
    assert rep.state == HEALTHY and rep.generation == 1
    # the rebuilt replica serves
    h2 = pool.submit(prompt, max_new_tokens=8)
    assert h2.result() == want
    pool.shutdown()
    check_pool_quiesced(pool)


def test_draining_engine_rejects_direct_submits(tiny_model):
    model, params = tiny_model
    eng = LLMEngine(model, params, max_slots=2, page_size=8,
                    n_pages=32, chunk=4, temperature=0.0).start()
    eng.drain()
    assert eng.load_report()["draining"] is True
    with pytest.raises(EngineDraining):
        eng.submit([1, 2, 3], max_new_tokens=4)
    assert eng.wait_idle(5.0) is True
    eng.shutdown()
    check_quiesced(eng)


def test_engine_load_report_shape(tiny_model):
    model, params = tiny_model
    eng = LLMEngine(model, params, max_slots=2, page_size=8,
                    n_pages=32, chunk=4, temperature=0.0,
                    prefix_cache=True).start()
    prompt = [(i % 50) + 1 for i in range(16)]   # two full pages
    h = eng.submit(prompt, max_new_tokens=6)
    h.result()
    rpt = eng.load_report()
    for key in ("free_slots", "free_pages", "queue_depth",
                "outstanding_tokens", "max_queued",
                "shed_retry_after_s", "draining", "stopped",
                "prefix_digest"):
        assert key in rpt, key
    assert rpt["stopped"] is False and rpt["draining"] is False
    assert rpt["free_slots"] == 2
    # retirement (prompt pages -> radix cache) trails the stream by
    # one readback; poll briefly, then the digest must advertise the
    # prompt's page path for affinity routing
    deadline = time.monotonic() + 5.0
    while not rpt["prefix_digest"] and time.monotonic() < deadline:
        time.sleep(0.01)
        rpt = eng.load_report()
    assert rpt["prefix_digest"]
    hashes = path_hashes(prompt, eng.Pg)
    assert hashes[0] in rpt["prefix_digest"]
    eng.shutdown()
    check_quiesced(eng, expect_cached_pages=eng.prefix_cache
                   .cached_pages)


def test_cancel_through_pool_handle(tiny_model):
    model, params = tiny_model
    pool = EnginePool(
        lambda i: LLMEngine(model, params, max_slots=1, page_size=8,
                            n_pages=32, chunk=2, temperature=0.0,
                            eos_id=-1, seed=i),
        1)
    h = pool.submit([5, 9, 2, 7], max_new_tokens=64)
    assert h.cancel() is True
    from ray_tpu.serve.errors import RequestCancelled
    with pytest.raises(RequestCancelled):
        h.result()
    pool.shutdown()
    check_pool_quiesced(pool)


# -------------------------------- auto-restart backoff + crash loops


def test_auto_restart_backoff_doubles_and_caps(monkeypatch):
    """Each successive death of the same replica doubles the rebuild
    backoff until the cap — a crash-looping factory must not spin
    hot. The sleep itself is spied out so the test is timing-free."""
    backoffs = []
    orig = EnginePool._backoff_rebuild

    def spy(self, rep, backoff_s):
        backoffs.append(backoff_s)
        orig(self, rep, 0.0)          # skip the real sleep

    monkeypatch.setattr(EnginePool, "_backoff_rebuild", spy)
    fakes = {}

    def factory(i):
        f = FakeEngine(i)
        fakes[i] = f
        return f

    pool = EnginePool(factory, 2, auto_restart=True,
                      restart_backoff_s=0.1,
                      restart_backoff_max_s=0.4,
                      max_restarts=None)
    for _ in range(4):
        rep = pool.replica(0)
        rep.engine._stopped = True
        pool._note_replica_death(rep)
        deadline = time.monotonic() + 5.0
        while pool.replica(0).state != HEALTHY \
                and time.monotonic() < deadline:
            time.sleep(0.005)
        assert pool.replica(0).state == HEALTHY
    assert backoffs == pytest.approx([0.1, 0.2, 0.4, 0.4])
    assert pool.replica(0).deaths == 4
    pool.shutdown()


def test_crash_loop_cap_parks_replica_degraded():
    """Past max_restarts the pool stops feeding the factory: the
    replica parks DEGRADED (skipped by routing), a full-pool outage
    surfaces as typed PoolDegraded (HTTP 503), and restart_dead() is
    the manual override that clears the state."""
    from ray_tpu.serve.engine_pool import DEGRADED
    from ray_tpu.serve.errors import (PoolDegraded,
                                      classify_http_status)
    fakes = {}

    def factory(i):
        f = FakeEngine(i)
        fakes[i] = f
        return f

    pool = EnginePool(factory, 1, auto_restart=True,
                      restart_backoff_s=0.0, max_restarts=1)
    # death 1: within budget, auto-rebuilds
    rep = pool.replica(0)
    rep.engine._stopped = True
    pool._note_replica_death(rep)
    deadline = time.monotonic() + 5.0
    while pool.replica(0).state != HEALTHY \
            and time.monotonic() < deadline:
        time.sleep(0.005)
    assert pool.replica(0).state == HEALTHY
    # death 2: budget burned -> DEGRADED, no rebuild
    rep = pool.replica(0)
    rep.engine._stopped = True
    pool._note_replica_death(rep)
    assert pool.replica(0).state == DEGRADED
    assert pool.degraded is True
    assert pool.route_stats["crash_loops"] == 1
    assert pool.pool_stats()["degraded"] is True
    with pytest.raises(PoolDegraded) as ei:
        pool.submit([1, 2])
    assert classify_http_status(ei.value) == 503
    # PoolDegraded IS an EngineShutdown: existing handlers still match
    assert isinstance(ei.value, EngineShutdown)
    # manual intervention: restart_dead rebuilds DEGRADED replicas too
    assert pool.restart_dead() == 1
    assert pool.replica(0).state == HEALTHY
    assert pool.submit([1, 2]).result() == [1, 2]
    pool.shutdown()


def test_backoff_rebuild_aborts_when_world_moved():
    """A rebuild sleeping out its backoff must re-check the world:
    if the pool stopped meanwhile, no zombie replica may be built."""
    fakes = {}

    def factory(i):
        f = FakeEngine(i)
        fakes[i] = f
        return f

    pool = EnginePool(factory, 1, auto_restart=True,
                      restart_backoff_s=0.2, max_restarts=None)
    rep = pool.replica(0)
    rep.engine._stopped = True
    pool._note_replica_death(rep)     # restart thread now sleeping
    pool.shutdown()                   # ... and the pool stops
    time.sleep(0.4)
    assert pool.replica(0).state == DEAD
    assert pool.route_stats["restarts"] == 0


# ------------------------------------------ drain racing with death


def test_resubmit_after_death_skips_draining_replica():
    """The satellite race, deterministic at the fakes layer: replica
    2 is mid-drain when replica 0 dies; the orphaned request must
    resubmit to the remaining HEALTHY replica — a draining replica
    is finishing its last requests, never accepting new ones."""
    fakes = [FakeEngine(0, outstanding=0),
             FakeEngine(1, outstanding=50),
             FakeEngine(2, outstanding=5)]
    fakes[0].die_on_failure = True
    fakes[0].script.append(FakeHandle(fakes[0], [],
                                      RuntimeError("device lost")))
    fakes[1].script.append([7, 8])
    pool = _fake_pool(fakes)
    pool.replica(2).state = DRAINING
    fakes[2]._draining = True
    h = pool.submit([1, 2])           # least loaded: replica 0
    assert h.replica_idx == 0
    assert h.result() == [7, 8]
    assert h.replica_idx == 1         # NOT the draining replica
    assert fakes[2].submits == []
    assert pool.route_stats["requeues"] == 1
    pool.replica(2).state = HEALTHY
    pool.shutdown()


def test_drain_racing_replica_death_quiesces_leak_free(tiny_model):
    """End-to-end race: replica 1 drains WHILE replica 0 dies
    mid-decode. Every in-flight request either completes
    token-identically to the single-engine reference or fails typed
    EngineShutdown (post-stream deaths) — none lost, none landed on
    the draining replica's corpse, and every engine ever built
    quiesces with zero leaked pages (autouse fixture + explicit
    check)."""
    import numpy as np
    model, params = tiny_model
    inj = FaultInjector()
    inj.kill_replica(round=6)

    def factory(idx):
        return LLMEngine(model, params, max_slots=2, page_size=16,
                         n_pages=64, chunk=2, prefill_chunk=16,
                         temperature=0.0, eos_id=-1, seed=idx,
                         fault_injector=inj if idx == 0 else None)

    pool = EnginePool(factory, 3)
    rng = np.random.RandomState(23)
    prompts = [rng.randint(1, 1000, size=10).tolist()
               for _ in range(8)]
    want = [_reference_completion(model, params, p, 20)
            for p in prompts]
    handles = [pool.submit(p, max_new_tokens=20) for p in prompts]
    drainer = threading.Thread(target=lambda: pool.drain(1))
    drainer.start()
    completed = typed = 0
    for h, w in zip(handles, want):
        try:
            assert h.result() == w    # token-identical or typed
            completed += 1
        except EngineShutdown:
            typed += 1
    drainer.join(timeout=60)
    assert not drainer.is_alive()
    assert completed + typed == len(handles)   # lost == 0
    assert completed >= 1
    assert pool.route_stats["replica_deaths"] >= 1
    assert pool.route_stats["drains"] == 1
    pool.shutdown()
    check_pool_quiesced(pool)


def test_idle_replica_death_detected_at_route_time():
    """A replica that dies with NO in-flight requests has no handle
    around to trip the death path — routing is where the corpse
    becomes visible. The next submit must note the death (DEAD state,
    auto-restart scheduled) instead of leaving a 'healthy' zombie the
    router silently skips forever."""
    built = []

    def factory(i):
        eng = FakeEngine(i)
        built.append(eng)
        return eng

    pool = EnginePool(factory, 2, auto_restart=True,
                      restart_backoff_s=0.0)
    # replica 0's engine dies while idle: nothing in flight, nobody
    # observes it
    built[0]._stopped = True
    h = pool.submit([1, 2, 3])          # routes around the corpse
    assert h.replica_idx == 1
    assert pool.route_stats["replica_deaths"] == 1
    deadline = time.monotonic() + 5.0
    while (pool.replica(0).generation == 0
           and time.monotonic() < deadline):
        time.sleep(0.01)
    assert pool.replica(0).state == HEALTHY
    assert pool.replica(0).generation == 1
    assert len(built) == 3              # rebuild used the factory
    pool.shutdown()


def test_scale_down_drain_vs_kill_vs_resubmit_three_way():
    """The full three-way race, deterministic at the fakes layer:
    the autoscaler's scale_down is mid-drain on replica 2 (wait_idle
    gated open) when replica 0 dies with an unstreamed request in
    flight — the resubmit must land on replica 1, the only remaining
    HEALTHY replica. Replica 2 is then killed WHILE draining: a
    drained-and-killed replica must never receive a resubmission,
    and the retire converges instead of wedging the scale-down."""
    import threading as _t
    gate = _t.Event()
    fakes = [FakeEngine(0, outstanding=5),
             FakeEngine(1, outstanding=50),
             FakeEngine(2, outstanding=0)]
    fakes[2].wait_idle = lambda timeout_s=30.0: (
        gate.wait(timeout_s), True)[1]
    fakes[0].die_on_failure = True
    fakes[0].script.append(FakeHandle(fakes[0], [],
                                      RuntimeError("device lost")))
    fakes[1].script.append([7, 8])
    pool = _fake_pool(fakes)
    # arm the scale-down: least-loaded healthy replica is 2
    retired = []
    scaler = _t.Thread(target=lambda: retired.extend(
        pool.scale_down(1, timeout_s=10.0)))
    scaler.start()
    deadline = time.monotonic() + 5.0
    while (pool.replica(2).state != DRAINING
           and time.monotonic() < deadline):
        time.sleep(0.005)
    assert pool.replica(2).state == DRAINING
    # replica 0 (5 outstanding vs 50) takes the request and dies;
    # the resubmit races the in-progress drain
    h = pool.submit([1, 2])
    assert h.replica_idx == 0
    assert h.result() == [7, 8]
    assert h.replica_idx == 1          # NOT the draining replica
    # now the draining replica is killed mid-drain
    fakes[2]._stopped = True
    pool._note_replica_death(pool.replica(2))
    gate.set()
    scaler.join(timeout=10.0)
    assert not scaler.is_alive()
    assert retired == [2]
    # the drained-and-killed replica saw zero submissions, ever
    assert fakes[2].submits == []
    assert pool.route_stats["requeues"] == 1
    assert pool.route_stats["replica_deaths"] == 2
    assert pool.route_stats["replicas_retired"] == 1
    assert pool.replica(1).state == HEALTHY
    from ray_tpu.serve.engine_pool import RETIRED
    assert pool.replica(2).state == RETIRED
    pool.shutdown()
