"""util + observability tests (reference analogues:
tests for ray.util.{actor_pool,queue,metrics,collective}, state API
tests, dashboard module tests)."""
import json
import urllib.request

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.collective import (CollectiveGroup,
                                     create_collective_group,
                                     destroy_collective_group)
from ray_tpu.util.metrics import (Counter, Gauge, Histogram,
                                  clear_registry, prometheus_text)
from ray_tpu.util.queue import Empty, Queue


def test_actor_pool_map(rt):
    @ray_tpu.remote
    class Sq:
        def compute(self, x):
            return x * x

    pool = ActorPool([Sq.remote() for _ in range(3)])
    out = pool.map(lambda a, v: a.compute.remote(v), range(10))
    assert out == [i * i for i in range(10)]


def test_actor_pool_unordered(rt):
    @ray_tpu.remote
    class Echo:
        def compute(self, x):
            return x

    pool = ActorPool([Echo.remote() for _ in range(2)])
    out = sorted(pool.map_unordered(
        lambda a, v: a.compute.remote(v), range(8)))
    assert out == list(range(8))


def test_queue(rt):
    q = Queue(maxsize=8)
    q.put("a")
    q.put("b")
    assert q.qsize() == 2
    assert q.get() == "a"
    assert q.get() == "b"
    with pytest.raises(Empty):
        q.get_nowait()

    @ray_tpu.remote
    def producer(q):
        for i in range(5):
            q.put(i)
        return "done"

    ray_tpu.get(producer.remote(q))
    assert [q.get() for _ in range(5)] == list(range(5))
    q.shutdown()


def test_collective_allreduce_between_actors(rt):
    create_collective_group(world_size=3, group_name="g1")

    @ray_tpu.remote
    class Member:
        def __init__(self, rank):
            from ray_tpu.util.collective import CollectiveGroup
            self.rank = rank
            self.group = CollectiveGroup(rank, "g1")

        def run(self):
            reduced = self.group.allreduce(
                np.full(4, float(self.rank + 1)))
            gathered = self.group.allgather(np.array([self.rank]))
            bcast = self.group.broadcast(
                np.array([42.0]) if self.rank == 0 else None,
                src_rank=0)
            self.group.barrier()
            return (reduced.tolist(), [g.tolist() for g in gathered],
                    bcast.tolist())

    members = [Member.remote(r) for r in range(3)]
    results = ray_tpu.get([m.run.remote() for m in members])
    for reduced, gathered, bcast in results:
        assert reduced == [6.0] * 4          # 1+2+3
        assert gathered == [[0], [1], [2]]
        assert bcast == [42.0]
    destroy_collective_group("g1")


def test_metrics_and_prometheus(rt):
    clear_registry()
    c = Counter("reqs_total", "requests", tag_keys=("route",))
    c.inc(tags={"route": "/a"})
    c.inc(2, tags={"route": "/a"})
    g = Gauge("temp", "temperature")
    g.set(21.5)
    h = Histogram("latency_s", "latency", boundaries=[0.1, 1.0])
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = prometheus_text()
    assert 'reqs_total{route="/a"} 3.0' in text
    assert "temp 21.5" in text
    assert 'latency_s_bucket{le="0.1"} 1' in text
    assert 'latency_s_bucket{le="1.0"} 2' in text
    assert 'latency_s_bucket{le="+Inf"} 3' in text
    assert "latency_s_count 3" in text
    with pytest.raises(ValueError):
        c.inc(tags={"bogus": "x"})
    clear_registry()


def test_prometheus_histogram_tags_sum_and_count():
    """Tagged Histogram exposition: per-tag cumulative le buckets with
    the +Inf terminator, plus _sum/_count per tag set."""
    clear_registry()
    h = Histogram("phase_s", "phase latency", boundaries=[0.1, 1.0],
                  tag_keys=("stage",))
    h.observe(0.25, tags={"stage": "plan"})
    h.observe(0.5, tags={"stage": "plan"})
    h.observe(0.05, tags={"stage": "readback"})
    text = prometheus_text()
    assert 'phase_s_bucket{stage="plan",le="0.1"} 0' in text
    assert 'phase_s_bucket{stage="plan",le="1.0"} 2' in text
    assert 'phase_s_bucket{stage="plan",le="+Inf"} 2' in text
    assert 'phase_s_sum{stage="plan"} 0.75' in text
    assert 'phase_s_count{stage="plan"} 2' in text
    assert 'phase_s_bucket{stage="readback",le="0.1"} 1' in text
    assert 'phase_s_count{stage="readback"} 1' in text
    clear_registry()


def test_prometheus_label_escaping():
    """Label values with quotes/backslashes/newlines must be escaped
    per the exposition format or they corrupt every following line."""
    from ray_tpu.util.metrics import _escape_label
    assert _escape_label('a\\b "c"\nd') == 'a\\\\b \\"c\\"\\nd'
    clear_registry()
    c = Counter("weird_total", "w", tag_keys=("q",))
    c.inc(tags={"q": 'a\\b "c"\nd'})
    text = prometheus_text()
    line = next(ln for ln in text.splitlines()
                if ln.startswith("weird_total{"))
    assert line == 'weird_total{q="a\\\\b \\"c\\"\\nd"} 1.0'
    clear_registry()


def test_state_api(rt):
    from ray_tpu import state

    @ray_tpu.remote
    class A:
        def ping(self):
            return 1

    a = A.remote()
    ray_tpu.get(a.ping.remote())
    actors = state.list_actors()
    assert any(x["state"] == "ALIVE" for x in actors)
    alive_only = state.list_actors(filters=[("state", "=", "ALIVE")])
    assert all(x["state"] == "ALIVE" for x in alive_only)
    summary = state.cluster_summary()
    assert summary["resources_total"]["CPU"] == 8.0
    assert summary["actors"].get("ALIVE", 0) >= 1


def test_node_hw_reporter_to_dashboard():
    """Per-node hardware reporter (reporter_agent.py parity): psutil
    snapshots ride agent heartbeats into the head; /api/nodes and the
    UI surface live per-node cpu/mem/store rows."""
    import time as _time

    import ray_tpu._private.worker as worker_mod
    from ray_tpu.dashboard import Dashboard
    from ray_tpu.runtime import Cluster
    if worker_mod.is_initialized():
        worker_mod.shutdown()
    c = Cluster(num_workers=1, resources_per_worker={"CPU": 2})
    c.add_node(num_workers=1, resources_per_worker={"CPU": 2})
    dash = Dashboard(port=0).start()
    try:
        def fetch(path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{dash.port}{path}",
                    timeout=15) as r:
                return r.read().decode()

        deadline = _time.time() + 20
        nodes = []
        while _time.time() < deadline:
            nodes = json.loads(fetch("/api/nodes"))
            with_hw = [n for n in nodes if n.get("hw")]
            if len(with_hw) >= 2:      # head + agent node both report
                break
            _time.sleep(0.3)
        assert len(nodes) >= 2
        with_hw = [n for n in nodes if n.get("hw")]
        assert len(with_hw) >= 2, nodes
        for n in with_hw:
            hw = n["hw"]
            assert hw["mem"]["total"] > 0
            assert "cpu_percent" in hw and "load_avg" in hw
        agent = [n for n in nodes if n["node_id"] != "head"][0]
        assert agent["hw"]["object_store"]["capacity"] > 0
        # frontend renders the nodes section
        index = fetch("/")
        assert "/api/nodes" in index and ">Nodes</h2>" in index
    finally:
        dash.stop()
        c.shutdown()


def test_dashboard_endpoints(rt):
    from ray_tpu.dashboard import Dashboard
    from ray_tpu.util.metrics import Counter, clear_registry

    clear_registry()
    Counter("dash_metric", "x").inc(5)

    @ray_tpu.remote
    def traced_task():
        return 1

    ray_tpu.get(traced_task.remote())
    dash = Dashboard(port=0).start()
    try:
        def fetch(path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{dash.port}{path}", timeout=15) as r:
                return r.read().decode()

        summary = json.loads(fetch("/api/cluster_summary"))
        assert summary["resources_total"]["CPU"] == 8.0
        tasks = json.loads(fetch("/api/tasks"))
        assert any("traced_task" in t["name"] for t in tasks)
        assert "dash_metric 5.0" in fetch("/metrics")
        timeline = json.loads(fetch("/api/timeline"))
        assert isinstance(timeline, list)
        index = fetch("/")
        assert "<!DOCTYPE html>" in index
        assert "/api/cluster_summary" in index   # frontend polls APIs
    finally:
        dash.stop()
        clear_registry()


def test_cross_lang_descriptor_registry(rt):
    """registry:// and import:// descriptors resolve on workers; the
    plain-data contract fails fast (VERDICT r4 weak: cross_lang was
    examples-only, now a descriptor registry)."""
    import pytest
    from ray_tpu.util import cross_lang as cl
    # registry hit + miss
    assert cl.resolve_descriptor("registry://square")(7) == 49
    with pytest.raises(LookupError, match="known"):
        cl.resolve_descriptor("registry://nope")
    # import forms
    assert cl.resolve_descriptor(
        "import://ray_tpu.util.cross_lang:square")(3) == 9
    assert cl.resolve_descriptor(
        "ray_tpu.util.cross_lang:describe")([1.0, 2.0])["n"] == 2
    with pytest.raises(ValueError):
        cl.resolve_descriptor("no-colon")
    # plain-data contract
    cl.validate_args({"a": [1, 2.0, "x", b"y", None, True]})
    with pytest.raises(TypeError, match="plain data"):
        cl.validate_args({"fn": lambda: 1})
    # custom registration round-trips
    cl.register_function("triple", lambda x: 3 * x)
    assert "triple" in cl.registered_functions()
    assert cl.resolve_descriptor("registry://triple")(4) == 12


def test_dashboard_serve_endpoint(rt):
    """/api/serve: deployment statuses + per-replica stats, including
    the serve_stats() user-metrics hook."""
    from ray_tpu import serve
    from ray_tpu.dashboard import Dashboard

    @serve.deployment(num_replicas=1)
    class Hello:
        def __call__(self, x):
            return x + 1

        def serve_stats(self):
            return {"custom": 7}

    try:
        h = serve.run(Hello.bind())
        assert ray_tpu.get(h.remote(1)) == 2
        dash = Dashboard(port=0).start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{dash.port}/api/serve",
                    timeout=30) as resp:
                body = json.loads(resp.read())
            d = body["deployments"]["Hello"]
            assert d["status"] == "HEALTHY"
            assert d["replica_stats"], d
            rs = d["replica_stats"][0]
            assert rs["total"] >= 1
            assert rs["user"] == {"custom": 7}
        finally:
            dash.stop()
    finally:
        serve.shutdown()


def test_prometheus_text_is_deterministic_and_sorted():
    """The exposition is a merge input (fleet telemetry re-labels
    and concatenates per-member scrapes): families must sort by name
    and samples by tag tuple so two scrapes of the same state are
    byte-identical and a multi-process merge is diffable."""
    clear_registry()
    # register out of order, touch tag sets out of order
    Gauge("zz_last", "z").set(1.0)
    c = Counter("aa_first_total", "a", tag_keys=("k",))
    c.inc(tags={"k": "zebra"})
    c.inc(tags={"k": "apple"})
    Gauge("mm_mid", "m").set(2.0)
    t1 = prometheus_text()
    t2 = prometheus_text()
    assert t1 == t2
    fams = [ln.split()[2] for ln in t1.splitlines()
            if ln.startswith("# HELP ")]
    assert fams == sorted(fams) == ["aa_first_total", "mm_mid",
                                    "zz_last"]
    lines = t1.splitlines()
    assert lines.index('aa_first_total{k="apple"} 1.0') \
        < lines.index('aa_first_total{k="zebra"} 1.0')
    clear_registry()


def test_metric_rejects_label_name_collisions():
    """One name must map to ONE family shape: re-registering with a
    different type or tag schema would make a merged scrape expose
    two families under one name."""
    clear_registry()
    Counter("col_total", "c", tag_keys=("route",))
    # same name, same type, same tags: legal re-registration
    Counter("col_total", "c", tag_keys=("route",))
    with pytest.raises(ValueError):
        Counter("col_total", "c", tag_keys=("path",))   # tag schema
    with pytest.raises(ValueError):
        Gauge("col_total", "c", tag_keys=("route",))    # type
    with pytest.raises(ValueError):
        Counter("dup_tags_total", "d", tag_keys=("a", "a"))
    with pytest.raises(ValueError):
        # "le" belongs to the histogram exposition itself
        Histogram("h_s", "h", boundaries=[1.0], tag_keys=("le",))
    clear_registry()
