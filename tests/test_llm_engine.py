"""Continuous-batching engine + paged KV cache tests.

Strategy mirrors the reference's serve batching tests
(python/ray/serve/tests/test_batching.py): correctness of batched
results vs unbatched, join/leave under staggered arrival, and
resource-pressure behavior — here preemption instead of queue
backpressure, since the engine schedules at token granularity.
"""
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models.kv_cache import BlockAllocator
from ray_tpu.models.llama import Llama, generate, llama_tiny
from ray_tpu.serve.engine import LLMEngine, RequestError
from ray_tpu.serve.scheduler import PrefillGrant, SlotView, plan_step


@pytest.fixture(scope="module")
def tiny_model():
    # fp32 params/activations so paged vs contiguous decode agree
    # bit-for-bit (bf16 rounding could flip greedy argmax on ties).
    cfg = llama_tiny(dtype=jnp.float32)
    model = Llama(cfg)
    import jax
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    return model, params


@pytest.fixture(autouse=True)
def _no_page_leaks(monkeypatch):
    """Invariant net under EVERY scenario in this file: once a test
    ends, each engine it built must have its allocator back at
    baseline — occupied pages exactly the prefix-cache residents
    (zero without a cache). A cancelled/failed/preempted path that
    drops a page shows up here, with the leaked ids named."""
    created = []
    orig = LLMEngine.__init__

    def record(self, *args, **kwargs):
        orig(self, *args, **kwargs)
        created.append(self)

    monkeypatch.setattr(LLMEngine, "__init__", record)
    yield
    for eng in created:
        cached = (eng.prefix_cache.cached_pages
                  if eng.prefix_cache is not None else 0)
        occ = eng.alloc.occupancy()
        assert occ == cached, (
            f"engine leaked pages at teardown: occupancy {occ} != "
            f"prefix-cache residency {cached}; leaked ids "
            f"{sorted(eng.alloc.leak_report())[:16]}")


def _reference_completion(model, params, prompt, n):
    out = generate(model, params, jnp.asarray([prompt], jnp.int32),
                   max_new_tokens=n, temperature=0.0)
    return np.asarray(out)[0, len(prompt):].tolist()


# ---------------------------------------------------------------- allocator


def test_allocator_basics():
    a = BlockAllocator(8)          # 7 usable, page 0 reserved
    got = a.alloc(3)
    assert len(got) == 3 and 0 not in got
    assert a.n_free == 4
    assert a.alloc(5) is None      # all-or-nothing
    assert a.n_free == 4
    a.free(got)
    assert a.n_free == 7
    with pytest.raises(ValueError):
        a.free(got)                # double free detected
    with pytest.raises(ValueError):
        a.free([0])                # null page is never freeable


def test_allocator_free_validation_is_atomic():
    a = BlockAllocator(8)
    got = a.alloc(4)
    with pytest.raises(ValueError):
        a.free([got[0], got[0]])   # same page twice in one call
    with pytest.raises(ValueError):
        a.free([got[1], 99])       # out-of-range id
    with pytest.raises(ValueError):
        a.free([got[2], 2.5])      # non-int id
    # nothing was accepted from the rejected calls: freeing the batch
    # cleanly still works (no partial state)
    assert a.n_free == 3
    a.free(got)
    assert a.n_free == 7
    with pytest.raises(ValueError):
        a.alloc(-1)


# ------------------------------------------------------------------ parity


def test_paged_decode_matches_generate(tiny_model):
    model, params = tiny_model
    eng = LLMEngine(model, params, max_slots=2, page_size=8,
                    n_pages=32, chunk=4)
    prompt = [5, 9, 2, 7, 11]
    want = _reference_completion(model, params, prompt, 12)
    h = eng.submit(prompt, max_new_tokens=12)
    while eng.step():
        pass
    assert h.result() == want


def test_parity_across_prompt_lengths(tiny_model):
    """Prompt lengths off and on page boundaries, decoded together."""
    model, params = tiny_model
    eng = LLMEngine(model, params, max_slots=4, page_size=8,
                    n_pages=64, chunk=4)
    prompts = [[3], [1, 2, 3, 4, 5, 6, 7, 8],      # exactly one page
               [4, 4, 4, 4, 4, 4, 4, 4, 4],        # one page + 1
               list(range(1, 14))]
    want = [_reference_completion(model, params, p, 9)
            for p in prompts]
    hs = [eng.submit(p, max_new_tokens=9) for p in prompts]
    while eng.step():
        pass
    assert [h.result() for h in hs] == want


# ------------------------------------------------- continuous batching


def test_join_leave_mid_decode(tiny_model):
    """A request arriving mid-decode joins the running batch (admitted
    into a free slot at a chunk boundary) and both finish correctly —
    the capability decode-to-completion batching lacks."""
    model, params = tiny_model
    eng = LLMEngine(model, params, max_slots=2, page_size=8,
                    n_pages=64, chunk=2)
    p1, p2 = [5, 6, 7], [9, 8, 7, 6]
    want1 = _reference_completion(model, params, p1, 16)
    want2 = _reference_completion(model, params, p2, 8)
    h1 = eng.submit(p1, max_new_tokens=16)
    for _ in range(3):             # decode a few chunks solo
        eng.step()
    h2 = eng.submit(p2, max_new_tokens=8)   # joins mid-flight
    while eng.step():
        pass
    assert h1.result() == want1
    assert h2.result() == want2
    assert eng.stats["admitted"] == 2
    # 2nd request admitted while 1st was still decoding
    assert eng.stats["completed"] == 2


def test_slot_reuse_after_completion(tiny_model):
    """More requests than slots: finished requests free their slot and
    pages for waiting ones."""
    model, params = tiny_model
    eng = LLMEngine(model, params, max_slots=2, page_size=8,
                    n_pages=32, chunk=4)
    prompts = [[i + 1, i + 2] for i in range(6)]
    want = [_reference_completion(model, params, p, 6)
            for p in prompts]
    hs = [eng.submit(p, max_new_tokens=6) for p in prompts]
    while eng.step():
        pass
    assert [h.result() for h in hs] == want
    assert eng.alloc.n_free == eng.alloc.n_pages - 1   # all pages back


def test_eos_frees_slot_early(tiny_model):
    model, params = tiny_model
    prompt = [5, 9, 2]
    ref = _reference_completion(model, params, prompt, 16)
    eos = ref[3]                   # force an early stop on a real token
    eng = LLMEngine(model, params, max_slots=2, page_size=8,
                    n_pages=32, chunk=4, eos_id=eos)
    h = eng.submit(prompt, max_new_tokens=16)
    while eng.step():
        pass
    got = h.result()
    assert got == ref[:ref.index(eos) + 1]   # truncated at first eos
    assert eng.alloc.n_free == eng.alloc.n_pages - 1


# ---------------------------------------------------------- preemption


def test_preemption_under_memory_pressure(tiny_model):
    """Pool too small for both requests at full length: the younger
    slot is evicted (pages freed, request requeued) and recomputed
    after the elder completes — both streams still correct."""
    model, params = tiny_model
    # each request needs ceil((4+28)/8)=4 pages; pool has 6 usable ->
    # both admit early (1-2 pages each) but cannot both finish.
    eng = LLMEngine(model, params, max_slots=2, page_size=8,
                    n_pages=7, chunk=4)
    p1, p2 = [1, 2, 3, 4], [9, 8, 7, 6]
    want1 = _reference_completion(model, params, p1, 28)
    want2 = _reference_completion(model, params, p2, 28)
    h1 = eng.submit(p1, max_new_tokens=28)
    h2 = eng.submit(p2, max_new_tokens=28)
    while eng.step():
        pass
    assert h1.result() == want1
    assert h2.result() == want2
    assert eng.stats["preemptions"] >= 1
    assert eng.alloc.n_free == eng.alloc.n_pages - 1


def test_oversized_request_rejected(tiny_model):
    model, params = tiny_model
    eng = LLMEngine(model, params, max_slots=1, page_size=8,
                    n_pages=4, chunk=2)
    with pytest.raises(RequestError):
        eng.submit([1] * 20, max_new_tokens=20)   # needs 5 > 3 pages
    with pytest.raises(RequestError):
        eng.submit([], max_new_tokens=4)
    with pytest.raises(RequestError):
        eng.submit([1], max_new_tokens=0)


# ----------------------------------------------------------- threaded


def test_background_thread_streaming(tiny_model):
    """start() mode: concurrent submitters stream tokens while the
    engine thread schedules continuously."""
    model, params = tiny_model
    eng = LLMEngine(model, params, max_slots=4, page_size=8,
                    n_pages=64, chunk=2).start()
    prompts = [[i + 2, i + 5] for i in range(8)]
    want = [_reference_completion(model, params, p, 8)
            for p in prompts]
    results = [None] * len(prompts)

    def run(i):
        results[i] = list(eng.submit(prompts[i],
                                     max_new_tokens=8).stream())

    threads = [threading.Thread(target=run, args=(i,))
               for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    eng.shutdown()
    assert results == want


def test_mixtral_through_engine():
    """MoE family shares LlamaAttention, so paged decode must work
    unchanged."""
    import jax
    from ray_tpu.models.mixtral import Mixtral, mixtral_tiny
    cfg = mixtral_tiny(dtype=jnp.float32)
    model = Mixtral(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    prompt = [3, 1, 4, 1, 5]
    want = _reference_completion(model, params, prompt, 8)
    eng = LLMEngine(model, params, max_slots=2, page_size=8,
                    n_pages=32, chunk=4)
    h = eng.submit(prompt, max_new_tokens=8)
    while eng.step():
        pass
    assert h.result() == want


def test_run_ahead_dispatch_coalescing(tiny_model):
    """Device-paced scheduling: with a full batch and no eos, the
    engine runs ahead to the next completion event instead of syncing
    every `chunk` steps — the whole generation should take a handful
    of dispatches, not max_new/chunk of them."""
    model, params = tiny_model
    eng = LLMEngine(model, params, max_slots=2, page_size=8,
                    n_pages=32, chunk=4)
    p1, p2 = [3, 1, 4, 1, 5], [2, 7, 1, 8]
    h1 = eng.submit(p1, max_new_tokens=24)
    h2 = eng.submit(p2, max_new_tokens=24)
    while eng.step():
        pass
    assert h1.result() == _reference_completion(model, params, p1, 24)
    assert h2.result() == _reference_completion(model, params, p2, 24)
    # 2 slots x 24 tokens with aligned budgets: one quick chunk while
    # admission fills, then run-ahead to the completion boundary.
    # Chunked pacing would need ~6 dispatches per request stream.
    assert eng.stats["chunks"] <= 4, dict(eng.stats)
    assert eng.stats["decode_steps"] >= 23


def test_shutdown_delivers_trailing_readbacks(tiny_model):
    """No-eos mode retires slots at dispatch time while their tokens
    are still in flight; shutdown must deliver every computed token
    before the scheduler exits, or clients hang on result()."""
    model, params = tiny_model
    eng = LLMEngine(model, params, max_slots=2, page_size=8,
                    n_pages=32, chunk=4).start()
    p = [11, 3, 5]
    want = _reference_completion(model, params, p, 12)
    h = eng.submit(p, max_new_tokens=12)
    got = h.result()
    eng.shutdown()
    assert got == want


def test_mixed_budgets_retire_independently(tiny_model):
    """A short and a long request share the batch; the short one's
    slot retires by arithmetic mid-run and is reusable while the long
    one keeps decoding — both streams exact."""
    model, params = tiny_model
    eng = LLMEngine(model, params, max_slots=2, page_size=8,
                    n_pages=32, chunk=4)
    p1, p2, p3 = [5, 1], [7, 2, 9], [4, 4, 8]
    want1 = _reference_completion(model, params, p1, 4)
    want2 = _reference_completion(model, params, p2, 30)
    want3 = _reference_completion(model, params, p3, 6)
    h1 = eng.submit(p1, max_new_tokens=4)
    h2 = eng.submit(p2, max_new_tokens=30)
    h3 = eng.submit(p3, max_new_tokens=6)   # reuses p1's retired slot
    while eng.step():
        pass
    assert h1.result() == want1
    assert h2.result() == want2
    assert h3.result() == want3


# ----------------------------------------------------- chunked prefill


def test_prompt_shorter_than_chunk(tiny_model):
    """A prompt under prefill_chunk finishes in ONE chunk: admitted,
    prefilled, and seeded in a single round, with TTFT stamped at the
    first emission."""
    model, params = tiny_model
    eng = LLMEngine(model, params, max_slots=2, page_size=8,
                    n_pages=32, chunk=4, prefill_chunk=16)
    prompt = [5, 9, 2, 7, 11]
    want = _reference_completion(model, params, prompt, 10)
    h = eng.submit(prompt, max_new_tokens=10)
    eng.step()
    assert eng.stats["prefills"] == 1
    assert eng.stats["prefilled_seqs"] == 1
    while eng.step():
        pass
    assert h.result() == want
    assert h.ttft_s is not None and h.ttft_s > 0
    assert len(eng.ttfts_s) == 1


def test_prompt_spanning_many_chunks(tiny_model):
    """A prompt of 3+ chunks prefills over several rounds and still
    matches the dense reference exactly (append-at-offset + causal
    masking make chunk boundaries invisible)."""
    model, params = tiny_model
    eng = LLMEngine(model, params, max_slots=2, page_size=8,
                    n_pages=64, chunk=4, prefill_chunk=8)
    prompt = list(range(1, 29))           # 28 tokens: chunks 8/8/8/4
    want = _reference_completion(model, params, prompt, 8)
    h = eng.submit(prompt, max_new_tokens=8)
    while eng.step():
        pass
    assert h.result() == want
    assert eng.stats["prefills"] >= 4     # one dispatch per chunk
    assert eng.stats["prefill_tokens"] == 28
    assert eng.alloc.n_free == eng.alloc.n_pages - 1


def test_slot_exhaustion_mid_prefill(tiny_model):
    """Every slot busy while a long prompt is mid-prefill: the extra
    request waits for a completion, then admits; all streams exact."""
    model, params = tiny_model
    eng = LLMEngine(model, params, max_slots=2, page_size=8,
                    n_pages=64, chunk=2, prefill_chunk=8)
    pa, pb, pc = [1, 2], list(range(1, 25)), [7, 3]
    wa = _reference_completion(model, params, pa, 6)
    wb = _reference_completion(model, params, pb, 10)
    wc = _reference_completion(model, params, pc, 6)
    ha = eng.submit(pa, max_new_tokens=6)
    hb = eng.submit(pb, max_new_tokens=10)
    hc = eng.submit(pc, max_new_tokens=6)
    eng.step()
    # both slots taken (pa seeded-or-prefilling, pb mid-prefill);
    # pc has nowhere to go yet
    assert all(s is not None for s in eng.slots)
    assert len(eng._wait) == 1
    assert any(s is not None and s.prefill_remaining > 0
               for s in eng.slots)
    while eng.step():
        pass
    assert ha.result() == wa
    assert hb.result() == wb
    assert hc.result() == wc
    assert eng.alloc.n_free == eng.alloc.n_pages - 1


def test_preempt_partially_prefilled_recompute(tiny_model):
    """A request evicted MID-PREFILL requeues with its untouched
    prompt (nothing generated yet) and recomputes to the exact
    reference stream."""
    model, params = tiny_model
    eng = LLMEngine(model, params, max_slots=2, page_size=8,
                    n_pages=64, chunk=2, prefill_chunk=8)
    prompt = list(range(1, 25))           # 24 tokens: 3 chunks
    want = _reference_completion(model, params, prompt, 6)
    h = eng.submit(prompt, max_new_tokens=6)
    eng.step()                            # admit + FIRST chunk only
    with eng._lock:
        (ix,) = [i for i, s in enumerate(eng.slots) if s is not None]
        slot = eng.slots[ix]
        assert 0 < slot.prefilled < len(prompt)
        eng._preempt_locked(ix)
        # recompute path: nothing was generated, so the requeued
        # prompt is the original, whole
        assert list(eng._wait)[0].recompute_prompt == prompt
    assert eng.stats["preemptions"] == 1
    while eng.step():
        pass
    assert h.result() == want
    assert h._req.preemptions == 1
    assert eng.alloc.n_free == eng.alloc.n_pages - 1


def test_decode_interleaved_between_prefill_chunks(tiny_model):
    """THE chunked-prefill property: while a long prompt prefills
    chunk by chunk, decode dispatches for the active stream land
    BETWEEN its chunks — the in-flight stream never stalls for the
    whole prompt. Asserted on the engine's dispatch-order trace."""
    model, params = tiny_model
    eng = LLMEngine(model, params, max_slots=2, page_size=8,
                    n_pages=64, chunk=2, prefill_chunk=8)
    p1 = [1, 2]
    w1 = _reference_completion(model, params, p1, 40)
    h1 = eng.submit(p1, max_new_tokens=40)
    for _ in range(3):                    # h1 decoding solo
        eng.step()
    p2 = list(range(1, 33))               # 32 tokens: 4 chunks of 8
    w2 = _reference_completion(model, params, p2, 4)
    h2 = eng.submit(p2, max_new_tokens=4)
    while eng.step():
        pass
    assert h1.result() == w1
    assert h2.result() == w2
    trace = list(eng.sched_trace)
    pf = [i for i, (kind, _) in enumerate(trace) if kind == "prefill"]
    assert len(pf) >= 5                   # p1's one + p2's four
    # between every pair of consecutive prefill chunks there is at
    # least one decode dispatch
    for a, b in zip(pf, pf[1:]):
        assert any(trace[i][0] == "decode" for i in range(a + 1, b)), \
            trace[a:b + 1]


# ------------------------------------------------------- pure planner


_PLAN = dict(total_slots=4, prefill_budget=16, decode_chunk=4,
             max_run_ahead=128, prefill_batch=4, eos_bounded=False)


def test_planner_long_prompt_takes_whole_budget():
    views = [SlotView(sid=0, admit_seq=0, prompt_remaining=100,
                      owed=0, seeded=False),
             SlotView(sid=1, admit_seq=1, prompt_remaining=3,
                      owed=0, seeded=False)]
    plan = plan_step(views, **_PLAN)
    assert plan.prefill == (PrefillGrant(0, 16),)   # FIFO, all budget
    assert plan.decode_steps == 0                   # nothing seeded


def test_planner_packs_short_prompts_into_one_round():
    views = [SlotView(sid=i, admit_seq=i, prompt_remaining=n,
                      owed=0, seeded=False)
             for i, n in enumerate([5, 6, 9])]
    plan = plan_step(views, **_PLAN)
    assert plan.prefill == (PrefillGrant(0, 5), PrefillGrant(1, 6),
                            PrefillGrant(2, 5))     # 16-token budget


def test_planner_decode_rides_behind_prefill():
    views = [SlotView(sid=0, admit_seq=0, prompt_remaining=0,
                      owed=50, seeded=True),
             SlotView(sid=1, admit_seq=1, prompt_remaining=40,
                      owed=0, seeded=False)]
    plan = plan_step(views, **_PLAN)
    assert plan.prefill == (PrefillGrant(1, 16),)
    assert plan.decode_steps == 4         # quick cadence, no run-ahead


def test_planner_run_ahead_when_full_and_seeded():
    views = [SlotView(sid=0, admit_seq=0, prompt_remaining=0,
                      owed=50, seeded=True),
             SlotView(sid=1, admit_seq=1, prompt_remaining=0,
                      owed=20, seeded=True)]
    plan = plan_step(views, **dict(_PLAN, total_slots=2))
    assert plan.prefill == ()
    assert plan.decode_steps == 20        # to the next completion
    bounded = plan_step(views, **dict(_PLAN, total_slots=2,
                                      eos_bounded=True))
    assert bounded.decode_steps == 8      # 2 x decode_chunk cap


def test_planner_prefill_batch_width_cap():
    views = [SlotView(sid=i, admit_seq=i, prompt_remaining=1,
                      owed=0, seeded=False) for i in range(6)]
    plan = plan_step(views, **dict(_PLAN, total_slots=8))
    assert len(plan.prefill) == 4         # prefill_batch
    assert [g.sid for g in plan.prefill] == [0, 1, 2, 3]


def test_planner_validates_budgets():
    with pytest.raises(ValueError):
        plan_step([], **dict(_PLAN, prefill_budget=0))
    with pytest.raises(ValueError):
        plan_step([], **dict(_PLAN, decode_chunk=0))
    assert plan_step([], **_PLAN).idle


def test_planner_unbounded_run_ahead_clamps_to_ceiling():
    """eos_bounded=False tail: with a full seeded batch the plan runs
    ahead to the next completion, but never past max_run_ahead — the
    device token buffer is [KMAX, S]-sized."""
    views = [SlotView(sid=0, admit_seq=0, prompt_remaining=0,
                      owed=500, seeded=True),
             SlotView(sid=1, admit_seq=1, prompt_remaining=0,
                      owed=400, seeded=True)]
    plan = plan_step(views, **dict(_PLAN, total_slots=2))
    assert plan.decode_steps == 128     # max_run_ahead, not min(owed)


def test_planner_unbounded_tail_never_below_one():
    """owed can reach 0 mid-flight in no-eos mode (deferred
    retirement waits on a trailing readback); the lane must still
    dispatch >= 1 step, never 0 or negative."""
    views = [SlotView(sid=0, admit_seq=0, prompt_remaining=0,
                      owed=0, seeded=True)]
    plan = plan_step(views, **dict(_PLAN, total_slots=1))
    assert plan.decode_steps >= 1
    views = [SlotView(sid=0, admit_seq=0, prompt_remaining=0,
                      owed=0, seeded=True),
             SlotView(sid=1, admit_seq=1, prompt_remaining=0,
                      owed=9, seeded=True)]
    plan = plan_step(views, **dict(_PLAN, total_slots=2))
    assert plan.decode_steps >= 1


def test_planner_all_slots_mid_prefill_decode_lane_empty():
    """A round where every slot is still prefilling: the decode lane
    must be EMPTY (0 steps), not negative, and the round must not
    read as idle — prefill work was granted."""
    views = [SlotView(sid=i, admit_seq=i, prompt_remaining=r,
                      owed=0, seeded=False)
             for i, r in enumerate([10, 20, 30, 40])]
    plan = plan_step(views, **_PLAN)
    assert plan.decode_steps == 0
    assert plan.spec == ()
    assert plan.prefill
    assert not plan.idle
