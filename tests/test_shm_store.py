"""C++ shared-memory object store tests (reference analogues:
src/ray/object_manager/plasma tests + python/ray/tests/test_object_store.py).
Cross-process tests use multiprocessing with the 'spawn' method."""
import multiprocessing as mp
import os
import time

import numpy as np
import pytest

from ray_tpu._private.ids import ObjectID
from ray_tpu._private.shm_store import (ShmObjectStore, ShmStoreError,
                                        ShmTimeout)


@pytest.fixture
def store():
    name = f"/raytpu_test_{os.getpid()}_{time.monotonic_ns() % 100000}"
    s = ShmObjectStore.create(name, 4 * 1024 * 1024)
    yield s
    s.close()


def test_put_get_bytes(store):
    oid = ObjectID.from_random()
    store.put_bytes(oid, b"hello shm")
    assert store.contains(oid)
    assert store.get_bytes(oid) == b"hello shm"


def test_put_get_object_with_numpy(store):
    oid = ObjectID.from_random()
    value = {"arr": np.arange(10000, dtype=np.float32), "tag": "x"}
    store.put_object(oid, value)
    out = store.get_object(oid)
    np.testing.assert_array_equal(out["arr"], value["arr"])
    assert out["tag"] == "x"


def test_duplicate_create_rejected(store):
    oid = ObjectID.from_random()
    store.put_bytes(oid, b"1")
    with pytest.raises(ShmStoreError):
        store.put_bytes(oid, b"2")


def test_get_timeout(store):
    with pytest.raises(ShmTimeout):
        store.get_bytes(ObjectID.from_random(), timeout_ms=50)


def test_delete_and_refcount(store):
    """delete() of a pinned object DEFERS to the last release (the
    plasma delete-on-release contract) — the entry survives while a
    view is live and vanishes the moment the pin drops."""
    oid = ObjectID.from_random()
    store.put_bytes(oid, b"data")
    view = store.get_view(oid)   # hold a reference
    store.delete(oid)            # refcount > 0 -> deferred
    assert store.contains(oid)   # still readable under the live pin
    assert bytes(view) == b"data"
    del view
    store.release(oid)           # pin drops -> deferred delete runs
    assert not store.contains(oid)


def test_spill_under_pressure(store):
    # Capacity 4 MiB; insert 8 x 1 MiB unreferenced objects: cold LRU
    # objects spill to disk (never silently dropped), the hottest stay
    # in shm, and every object remains retrievable.
    oids = []
    payloads = []
    for i in range(8):
        oid = ObjectID.from_random()
        data = bytes([i]) * (1024 * 1024)
        store.put_bytes(oid, data)
        oids.append(oid)
        payloads.append(data)
    stats = store.stats()
    assert stats["num_evictions"] == 0
    assert stats["num_spilled"] >= 4
    for oid, data in zip(oids, payloads):
        assert store.contains(oid)
        assert store.get_bytes(oid, timeout_ms=1000) == data


def test_stats(store):
    before = store.stats()
    oid = ObjectID.from_random()
    store.put_bytes(oid, bytes(1000))
    after = store.stats()
    assert after["num_objects"] == before["num_objects"] + 1
    assert after["bytes_in_use"] > before["bytes_in_use"]


def _writer_proc(store_name, oid_bin, payload):
    s = ShmObjectStore.attach(store_name)
    time.sleep(0.2)
    s.put_bytes(ObjectID(oid_bin), payload)
    s.close()


def test_cross_process_blocking_get(store):
    oid = ObjectID.from_random()
    ctx = mp.get_context("spawn")
    p = ctx.Process(target=_writer_proc,
                    args=(store.name, oid.binary(), b"from-child"))
    p.start()
    try:
        # Blocks until the child seals the object.
        assert store.get_bytes(oid, timeout_ms=30000) == b"from-child"
    finally:
        p.join(timeout=30)
    assert p.exitcode == 0


def _reader_proc(store_name, oid_bin, q):
    s = ShmObjectStore.attach(store_name)
    data = s.get_bytes(ObjectID(oid_bin), timeout_ms=30000)
    q.put(len(data))
    s.close()


def test_cross_process_read(store):
    oid = ObjectID.from_random()
    store.put_bytes(oid, bytes(123456))
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    p = ctx.Process(target=_reader_proc,
                    args=(store.name, oid.binary(), q))
    p.start()
    try:
        assert q.get(timeout=30) == 123456
    finally:
        p.join(timeout=30)
