"""Speculative decoding tests (serve/spec_decode.py + the engine's
spec lane).

The load-bearing property is EXACT greedy parity: at temperature 0
the spec engine's output must be token-identical to non-speculative
decode — drafts only decide how many argmaxes one dispatch keeps,
never what they are. Proposer quality is exercised through the
``spec_proposer`` seam: an oracle (always right) pins the accept
path, an anti-oracle (always wrong) pins rollback-then-continue, and
the real n-gram proposer runs over repetitive and random prompts.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models.llama import Llama, generate, llama_tiny
from ray_tpu.serve.engine import LLMEngine
from ray_tpu.serve.scheduler import SlotView, SpecGrant, plan_step
from ray_tpu.serve.spec_decode import NGramIndex


@pytest.fixture(scope="module")
def tiny_model():
    # fp32 so paged vs contiguous decode agree bit-for-bit (bf16
    # rounding could flip greedy argmax on ties).
    cfg = llama_tiny(dtype=jnp.float32)
    model = Llama(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    return model, params


def _reference_completion(model, params, prompt, n):
    out = generate(model, params, jnp.asarray([prompt], jnp.int32),
                   max_new_tokens=n, temperature=0.0)
    return np.asarray(out)[0, len(prompt):].tolist()


def _run(eng, prompts, n):
    hs = [eng.submit(p, max_new_tokens=n) for p in prompts]
    while eng.step():
        pass
    return [h.result() for h in hs]


REP_PROMPT = ([7, 8, 9, 10] * 6)[:20]


# ------------------------------------------------------ n-gram proposer


def test_ngram_proposes_continuation_of_previous_occurrence():
    idx = NGramIndex(2)
    idx.sync([1, 2, 3, 1, 2])
    # tail gram (1, 2) last occurred at the start; what followed it
    # is the draft
    assert idx.propose(3) == [3, 1, 2]
    assert idx.propose(1) == [3]


def test_ngram_no_match_and_short_context():
    idx = NGramIndex(3)
    idx.sync([1, 2])
    assert idx.propose(4) == []        # shorter than the gram
    idx.sync([1, 2, 3, 4])
    assert idx.propose(4) == []        # tail gram never seen before
    assert idx.propose(0) == []


def test_ngram_incremental_sync_matches_one_shot():
    ctx = [5, 6, 5, 6, 5, 6, 7]
    a, b = NGramIndex(2), NGramIndex(2)
    a.sync(ctx)
    b.sync(ctx[:3])
    b.sync(ctx)                        # only the tail is consumed
    assert a.propose(4) == b.propose(4)
    with pytest.raises(ValueError):
        b.sync(ctx[:2])                # context can never shrink


def test_ngram_validates_order():
    with pytest.raises(ValueError):
        NGramIndex(0)


# ------------------------------------------------------ planner spec lane


_PLAN = dict(total_slots=4, prefill_budget=16, decode_chunk=4,
             max_run_ahead=128, prefill_batch=4, eos_bounded=False,
             spec_enabled=True)


def test_spec_lane_replaces_decode_and_covers_all_seeded():
    views = [SlotView(sid=0, admit_seq=0, prompt_remaining=0,
                      owed=50, seeded=True, spec_drafts=3),
             SlotView(sid=1, admit_seq=1, prompt_remaining=0,
                      owed=50, seeded=True, spec_drafts=0)]
    plan = plan_step(views, **dict(_PLAN, total_slots=2))
    assert plan.decode_steps == 0      # lanes are exclusive per round
    # zero-draft slots still ride the batched verify (plain one-token
    # rows), so speculation never forks the device schedule
    assert plan.spec == (SpecGrant(0, 3), SpecGrant(1, 0))


def test_spec_lane_degrades_to_quick_decode_without_proposals():
    views = [SlotView(sid=i, admit_seq=i, prompt_remaining=0,
                      owed=50, seeded=True, spec_drafts=0)
             for i in range(2)]
    plan = plan_step(views, **dict(_PLAN, total_slots=2))
    assert plan.spec == ()
    # quick cadence, NOT run-ahead: running ahead would decode past
    # every future proposal window before the host proposes again
    assert plan.decode_steps == 4


def test_spec_lane_clamps_drafts_to_owed_and_run_ahead():
    views = [SlotView(sid=0, admit_seq=0, prompt_remaining=0,
                      owed=2, seeded=True, spec_drafts=8),
             SlotView(sid=1, admit_seq=1, prompt_remaining=0,
                      owed=50, seeded=True, spec_drafts=8)]
    plan = plan_step(views, **dict(_PLAN, total_slots=2,
                                   max_run_ahead=4))
    # a verify emits drafts+1 tokens: clamp to owed-1 and to
    # max_run_ahead-1 so one dispatch never overshoots either bound
    assert plan.spec == (SpecGrant(0, 1), SpecGrant(1, 3))


def test_spec_lane_never_starves_prefill():
    views = [SlotView(sid=0, admit_seq=0, prompt_remaining=40,
                      owed=0, seeded=False),
             SlotView(sid=1, admit_seq=1, prompt_remaining=0,
                      owed=50, seeded=True, spec_drafts=4)]
    plan = plan_step(views, **_PLAN)
    assert plan.prefill and plan.prefill[0].sid == 0
    assert plan.spec == (SpecGrant(1, 4),)


def test_spec_disabled_ignores_drafts():
    views = [SlotView(sid=0, admit_seq=0, prompt_remaining=0,
                      owed=50, seeded=True, spec_drafts=4)]
    plan = plan_step(views, **dict(_PLAN, spec_enabled=False))
    assert plan.spec == ()
    assert plan.decode_steps > 0


# ------------------------------------------------------ engine parity


def test_spec_parity_repetitive_and_random_prompts(tiny_model):
    """The acceptance-criteria test: temperature-0 output with
    speculation on is token-identical to speculation off, across
    repetitive (spec-friendly) and random (spec-hostile) prompts."""
    model, params = tiny_model
    rng = np.random.default_rng(0)
    prompts = ([list(REP_PROMPT) for _ in range(2)]
               + [rng.integers(1, 255, size=14).tolist()
                  for _ in range(2)])
    base = _run(LLMEngine(model, params, max_slots=4, page_size=8,
                          n_pages=64, chunk=4), prompts, 24)
    eng = LLMEngine(model, params, max_slots=4, page_size=8,
                    n_pages=64, chunk=4, spec_len=4, spec_ngram=2)
    spec = _run(eng, prompts, 24)
    assert spec == base
    st = eng.spec_stats()
    assert st["rounds"] > 0            # the spec lane actually ran
    assert (st["accepted_tokens"] + st["rejected_tokens"]
            == st["proposed_tokens"])
    # every emitted token is accounted: spec emissions + decode-lane
    # emissions + prefill firsts cover all requests
    markers = [t for t in eng.sched_trace if t[0] == "spec"]
    assert markers, "no ('spec', ...) trace markers"
    for _tag, sid, proposed, accepted in markers:
        assert 0 <= accepted <= proposed <= 4
        assert 0 <= sid < 4


class _Scripted:
    """Proposer seam: proposes a fixed continuation script keyed on
    how many tokens the slot has generated (context beyond the
    prompt). An oracle scripts the true reference completion; an
    anti-oracle scripts guaranteed-wrong tokens."""

    def __init__(self, prompt_len, script):
        self.prompt_len = prompt_len
        self.script = script
        self._done = 0

    def sync(self, context):
        self._done = len(context) - self.prompt_len

    def propose(self, k):
        return self.script[self._done:self._done + k]


def test_spec_oracle_proposer_accepts_everything(tiny_model):
    model, params = tiny_model
    prompt = [5, 9, 2, 7, 11]
    ref = _reference_completion(model, params, prompt, 16)
    eng = LLMEngine(model, params, max_slots=2, page_size=8,
                    n_pages=32, chunk=4, spec_len=4,
                    spec_proposer=lambda: _Scripted(len(prompt), ref))
    out = _run(eng, [prompt], 16)
    assert out == [ref]
    st = eng.spec_stats()
    assert st["accept_rate"] == 1.0
    assert st["tokens_per_dispatch"] > 1.0
    # trace shows multi-token verifies, all fully accepted
    for _tag, _sid, proposed, accepted in (
            t for t in eng.sched_trace if t[0] == "spec"):
        assert accepted == proposed


def test_spec_full_rejection_rolls_back_then_continues(tiny_model):
    """Anti-oracle: every draft is guaranteed wrong, so every verify
    rejects everything, clamps the KV frontier back, and emits only
    the correction token — output must still be exact."""
    model, params = tiny_model
    prompt = [5, 9, 2, 7, 11]
    ref = _reference_completion(model, params, prompt, 16)
    wrong = [(t + 1) % 256 for t in ref]
    eng = LLMEngine(model, params, max_slots=2, page_size=8,
                    n_pages=32, chunk=4, spec_len=4,
                    spec_proposer=lambda: _Scripted(len(prompt),
                                                    wrong))
    out = _run(eng, [prompt], 16)
    assert out == [ref]
    st = eng.spec_stats()
    assert st["proposed_tokens"] > 0
    assert st["accept_rate"] == 0.0
    # full rejection degrades to exactly one (correction) token per
    # rider per dispatch — never zero, never stuck
    assert st["tokens_per_dispatch"] == 1.0


def test_spec_with_prefix_cache_parity_and_cow(tiny_model):
    """Spec verifies write at the slot's frontier, which sits past
    any cache-shared pages — parity must hold through a cache-hit
    admission and the radix tree must stay sound (a COW violation
    raises inside the dispatch)."""
    model, params = tiny_model
    prefix = list(REP_PROMPT)
    prompts = [prefix + [3, 1], prefix + [4, 2]]
    base = _run(LLMEngine(model, params, max_slots=2, page_size=8,
                          n_pages=32, chunk=4), prompts, 16)
    eng = LLMEngine(model, params, max_slots=2, page_size=8,
                    n_pages=32, chunk=4, prefix_cache=True,
                    spec_len=4, spec_ngram=2)
    # sequential so the second admission hits the first's inserted
    # prefix pages
    out0 = _run(eng, [prompts[0]], 16)
    out1 = _run(eng, [prompts[1]], 16)
    assert out0 + out1 == base
    assert eng.prefix_cache.stats()["hit_tokens"] > 0
    eng.prefix_cache.check_invariants()
    assert eng.spec_stats()["rounds"] > 0


def test_spec_preemption_mid_speculation(tiny_model):
    """A page pool too small for both requests forces preemption
    while speculation is active; recompute must land on the exact
    greedy stream (the victim's proposer dies with its slot)."""
    model, params = tiny_model
    # each request needs ceil((4+28)/8)=4 pages; pool has 6 usable ->
    # both admit early but cannot both finish (the shape
    # test_preemption_under_memory_pressure pins, now with spec on)
    prompts = [[1, 2, 1, 2], [9, 8, 9, 8]]
    want = [_reference_completion(model, params, p, 28)
            for p in prompts]
    eng = LLMEngine(model, params, max_slots=2, page_size=8,
                    n_pages=7, chunk=4, spec_len=4, spec_ngram=2)
    out = _run(eng, prompts, 28)
    assert out == want
    assert eng.stats["preemptions"] > 0
    assert eng.spec_stats()["rounds"] > 0
    assert eng.alloc.n_free == eng.alloc.n_pages - 1


def test_spec_eos_truncation_parity(tiny_model):
    """With an eos id, a verify that emits past the eos must truncate
    exactly where plain decode does."""
    model, params = tiny_model
    prompt = list(REP_PROMPT)
    ref = _reference_completion(model, params, prompt, 24)
    eos = ref[len(ref) // 2]           # an id that actually occurs
    base = _run(LLMEngine(model, params, max_slots=2, page_size=8,
                          n_pages=32, chunk=4, eos_id=eos),
                [prompt], 24)
    spec = _run(LLMEngine(model, params, max_slots=2, page_size=8,
                          n_pages=32, chunk=4, eos_id=eos,
                          spec_len=4, spec_ngram=2), [prompt], 24)
    assert spec == base
    assert base[0][-1] == eos


def test_spec_disabled_under_sampling(tiny_model):
    """Verification accepts against the argmax, so with sampling it
    would skew the output distribution: spec silently disables."""
    model, params = tiny_model
    eng = LLMEngine(model, params, max_slots=2, page_size=8,
                    n_pages=32, chunk=4, temperature=0.8, spec_len=4)
    assert eng.spec_len == 0
    assert eng.spec_stats() is None
    _run(eng, [[5, 9, 2]], 8)          # still serves, just no spec
    assert not [t for t in eng.sched_trace if t[0] == "spec"]


def test_spec_cancel_mid_speculation(tiny_model):
    """Cancelling a slot while the spec lane is active: its freed
    pages must never be touched by the in-flight verify's rollback
    (stream ordering — the same argument as retire-at-dispatch), the
    surviving slot stays token-identical to greedy decode, and the
    allocator returns to baseline."""
    from ray_tpu.serve.errors import RequestCancelled
    from ray_tpu.serve.faults import check_quiesced
    model, params = tiny_model
    p1 = list(REP_PROMPT)
    p2 = list(REP_PROMPT[2:])
    want1 = _reference_completion(model, params, p1, 24)
    eng = LLMEngine(model, params, max_slots=4, page_size=8,
                    n_pages=64, chunk=2, spec_len=4, spec_ngram=2)
    h1 = eng.submit(p1, max_new_tokens=24)      # slot 0: survivor
    h2 = eng.submit(p2, max_new_tokens=24)      # slot 1: cancelled
    # step until speculation has actually dispatched and the victim
    # is mid-flight (slot live, verify rounds running)
    for _ in range(64):
        eng.step()
        if ([t for t in eng.sched_trace if t[0] == "spec"]
                and eng.slots[1] is not None
                and eng.slots[1].req is h2._req):
            break
    else:
        raise AssertionError("spec lane never engaged")
    assert h2.cancel() is True
    assert eng.slots[1] is None                 # slot + pages freed NOW
    while eng.step():
        pass
    assert h1.result() == want1
    with pytest.raises(RequestCancelled):
        h2.result()
    assert len(h2._req.generated) < 24
    assert eng.stats["cancelled"] == 1
    assert eng.spec_stats()["rounds"] > 0
    check_quiesced(eng)


def test_spec_off_by_default_and_validates(tiny_model):
    model, params = tiny_model
    eng = LLMEngine(model, params, max_slots=2, page_size=8,
                    n_pages=32, chunk=4)
    assert eng.spec_stats() is None
    with pytest.raises(ValueError):
        LLMEngine(model, params, spec_len=-1)
    with pytest.raises(ValueError):
        LLMEngine(model, params, spec_len=2, spec_ngram=0)
