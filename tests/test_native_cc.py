"""Builds and runs the native C++ test suite under ASan+UBSan
(`make -C src test`), the role of the reference's *_test.cc files +
.bazelrc asan config. The tsan variant (`make -C src test-tsan`) is
exercised too; both must pass cleanly for the shm store and metrics
registry — the runtime's two native components."""
import os
import shutil
import subprocess

import pytest

_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "src")


needs_gxx = pytest.mark.skipif(shutil.which("g++") is None,
                               reason="no C++ toolchain")


@needs_gxx
@pytest.mark.parametrize("target", ["test", "test-tsan"])
def test_native_suite(target):
    proc = subprocess.run(
        ["make", "-C", _SRC, target],
        capture_output=True, text=True, timeout=600)
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, out[-4000:]
    assert "ALL STORE TESTS PASSED" in out
    assert "ALL METRICS TESTS PASSED" in out
    for bad in ("AddressSanitizer", "ThreadSanitizer",
                "UndefinedBehaviorSanitizer", "runtime error"):
        assert bad not in out, out[-4000:]
