"""Multi-node object/control plane tests.

Two "nodes" = two shm store segments + two node manager process trees on
one machine (the reference tests multi-node the same way: multiple real
raylets via ray.cluster_utils.Cluster, python/ray/cluster_utils.py:165).
Covers: cross-node task/object flow, 100MB transfers both directions,
pub/sub delivery, node death + lineage reconstruction of lost results.
"""
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.runtime import Cluster


@pytest.fixture(scope="module")
def two_node_cluster():
    import ray_tpu._private.worker as worker_mod
    if worker_mod.is_initialized():
        worker_mod.shutdown()
    c = Cluster(num_workers=1,
                resources_per_worker={"CPU": 2, "node0": 10},
                store_capacity=512 * 1024 * 1024)
    node_id = c.add_node(num_workers=1,
                         resources_per_worker={"CPU": 2, "node1": 10},
                         store_capacity=512 * 1024 * 1024)
    yield c, node_id
    c.shutdown()


def test_two_nodes_registered(two_node_cluster):
    c, node_id = two_node_cluster
    nodes = {n["node_id"]: n for n in c.nodes()}
    assert "head" in nodes and node_id in nodes
    assert nodes[node_id]["alive"]
    # Two distinct store segments.
    assert nodes["head"]["store_name"] != nodes[node_id]["store_name"]


def test_cross_node_task_chain(two_node_cluster):
    """A task on node1 consumes the output of a task on node0."""

    @ray_tpu.remote(resources={"node0": 1})
    def produce():
        return np.arange(1000, dtype=np.int64)

    @ray_tpu.remote(resources={"node1": 1})
    def consume(a):
        return int(a.sum())

    ref = produce.remote()
    assert ray_tpu.get(consume.remote(ref)) == 499500


def test_100mb_both_directions(two_node_cluster):
    """100MB array moves node0 -> node1 and node1 -> node0."""
    nbytes = 100 * 1024 * 1024

    @ray_tpu.remote(resources={"node0": 1})
    def big_on_0():
        return np.ones(nbytes // 8, dtype=np.float64)

    @ray_tpu.remote(resources={"node1": 1})
    def big_on_1():
        return np.full(nbytes // 8, 2.0, dtype=np.float64)

    @ray_tpu.remote(resources={"node0": 1})
    def sum_on_0(a):
        return float(a.sum())

    @ray_tpu.remote(resources={"node1": 1})
    def sum_on_1(a):
        return float(a.sum())

    n = nbytes // 8
    t0 = time.time()
    assert ray_tpu.get(sum_on_1.remote(big_on_0.remote())) == n * 1.0
    assert ray_tpu.get(sum_on_0.remote(big_on_1.remote())) == n * 2.0
    elapsed = time.time() - t0
    assert elapsed < 60, f"200MB of transfers took {elapsed:.1f}s"


def test_driver_get_from_remote_node(two_node_cluster):
    @ray_tpu.remote(resources={"node1": 1})
    def produce():
        return {"payload": np.arange(500000, dtype=np.float32)}

    out = ray_tpu.get(produce.remote())
    assert float(out["payload"][-1]) == 499999.0


def test_driver_put_read_on_remote_node(two_node_cluster):
    arr = np.arange(250000, dtype=np.float32)
    ref = ray_tpu.put(arr)

    @ray_tpu.remote(resources={"node1": 1})
    def total(a):
        return float(a.sum())

    assert ray_tpu.get(total.remote(ref)) == pytest.approx(
        float(arr.sum()))


def test_pubsub_state_and_stream(two_node_cluster):
    c, _ = two_node_cluster
    hub_client = c.runtime.head
    hub_client.call("publish", "test_chan", {"v": 1})
    out = hub_client.call("psub_poll", {"test_chan": 0}, {},
                          poll_timeout=5)
    assert out["state"]["test_chan"][1] == {"v": 1}
    version = out["state"]["test_chan"][0]
    # Long-poll blocks until the next publish, then delivers fast.
    import threading
    got = {}

    def waiter():
        got.update(hub_client.call(
            "psub_poll", {"test_chan": version}, {}, poll_timeout=10))

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.2)
    t0 = time.time()
    hub_client.call("publish", "test_chan", {"v": 2})
    t.join(timeout=5)
    latency = time.time() - t0
    assert got["state"]["test_chan"][1] == {"v": 2}
    assert latency < 1.0, f"long-poll delivery took {latency:.2f}s"
    # Stream channel: ordered batch delivery.
    for i in range(5):
        hub_client.call("publish", "test_stream", {"i": i}, stream=True)
    out = hub_client.call("psub_poll", {}, {"test_stream": 0},
                          poll_timeout=5)
    assert [it["i"] for _, it in out["streams"]["test_stream"]] == \
        list(range(5))


def test_node_death_lineage_reconstruction():
    """A result living only on a dead node is rebuilt from lineage."""
    import ray_tpu._private.worker as worker_mod
    if worker_mod.is_initialized():
        worker_mod.shutdown()
    with Cluster(num_workers=1,
                 resources_per_worker={"CPU": 2, "node0": 10},
                 store_capacity=128 * 1024 * 1024) as c:
        node_id = c.add_node(
            num_workers=1, resources_per_worker={"CPU": 2, "big": 10},
            store_capacity=128 * 1024 * 1024)

        # Runs on node1 the first time (needs "big"); after node1 dies
        # reconstruction must land it elsewhere, so make the resource
        # requirement soft: use plain CPU but force first placement via
        # a value marker instead.
        @ray_tpu.remote(max_retries=2)
        def produce(tag):
            import os
            return ("value", tag, os.getpid())

        # Pin the first run to node1 via its marker resource.
        ref = produce.options(resources={"big": 1}).remote("x")
        first = ray_tpu.get(ref)
        assert first[0] == "value"

        # Kill node1's process tree and tell the head immediately
        # (tests shouldn't wait out the 30s heartbeat timeout).
        c.kill_node(node_id)
        c.node.head_service.mark_node_dead(node_id)

        # The object's only copy is gone. A fresh get must trigger
        # lineage reconstruction... but the spec needs {"big": 1},
        # which no longer exists — so reconstruction must requeue and
        # then time out OR we re-add capacity. Re-add capacity:
        c.add_node(num_workers=1,
                   resources_per_worker={"CPU": 2, "big": 10},
                   store_capacity=128 * 1024 * 1024)
        rebuilt = ray_tpu.get(ref, timeout=60)
        assert rebuilt[0] == "value" and rebuilt[1] == "x"


def test_serve_replica_concurrency_on_worker_process():
    """Serve on the MULTIPROCESS runtime: the replica is an asyncio
    actor inside a worker process, whose event-loop default executor
    (worker_main._actor_asyncio_main) must be sized to the actor's
    max_concurrency — the stock min(32, cpus+4) pool silently capped
    replicas at ~5 concurrent requests on small hosts."""
    import threading
    import time as _time

    import ray_tpu._private.worker as worker_mod
    from ray_tpu import serve
    if worker_mod.is_initialized():
        worker_mod.shutdown()
    c = Cluster(num_workers=1,
                resources_per_worker={"CPU": 8},
                store_capacity=256 * 1024 * 1024)
    try:
        @serve.deployment(max_ongoing_requests=32)
        class Sleepy:
            def __call__(self, x):
                _time.sleep(0.3)
                return x

        handle = serve.run(Sleepy.bind())
        ray_tpu.get(handle.remote(0), timeout=60)   # warm
        results = []
        lock = threading.Lock()

        def call():
            r = ray_tpu.get(handle.remote(1), timeout=60)
            with lock:
                results.append(r)

        t0 = _time.time()
        threads = [threading.Thread(target=call) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = _time.time() - t0
        assert results == [1] * 8, results
        # serial = 2.4s; real overlap keeps it far below half
        assert wall < 1.2, f"8 parallel 0.3s calls took {wall:.2f}s"
        serve.shutdown()
    finally:
        c.shutdown()
