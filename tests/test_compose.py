"""Composed-parallelism tests (SURVEY §7 step 7: PP/SP/EP/DP as
mesh-axis configs on JaxTrainer).

The single-process tests build {pipeline, sequence, data} meshes on the
8-device CPU fixture and check (a) the composed forward matches a
dense single-device reference, (b) training decreases the loss with
gradients flowing through the pipeline ppermutes AND the ring
attention rotation. The gang test runs the same composition across a
2-process jax.distributed gang with a dcn axis — the VERDICT r5 done
bar: a mixed {dcn, pipeline, data, sequence} mesh, loss decreasing,
via the public JaxTrainer API.
"""
import numpy as np
import pytest


def _mesh(axes):
    from ray_tpu.mesh.device_mesh import create_mesh
    return create_mesh(axes)


def _toy_stage_fn(with_ring=True):
    """One pipeline stage: linear mix + (optionally) ring attention
    over the sequence axis + residual."""
    import jax
    import jax.numpy as jnp

    def stage_fn(params, x):              # x: [B, T, D] local
        h = jnp.einsum("btd,de->bte", x, params["w"]) + params["b"]
        h = jax.nn.gelu(h)
        if with_ring:
            from ray_tpu.parallel.sequence import ring_attention
            B, T, D = h.shape
            qkv = h.reshape(B, T, 1, D)   # one head
            a = ring_attention(qkv, qkv, qkv, axis_name="sequence",
                               causal=True)
            h = h + a.reshape(B, T, D)
        return x + h

    return stage_fn


def _make_params(rng, S, D):
    import jax.numpy as jnp
    return {
        "w": jnp.asarray(rng.randn(S, D, D) * 0.05, jnp.float32),
        "b": jnp.zeros((S, D), jnp.float32),
    }


def _dense_reference(params, x, S):
    """Single-device replay of the composed program."""
    import jax
    import jax.numpy as jnp
    h = jnp.asarray(x)
    for s in range(S):
        p = {"w": params["w"][s], "b": params["b"][s]}
        z = jnp.einsum("btd,de->bte", h, p["w"]) + p["b"]
        z = jax.nn.gelu(z)
        B, T, D = z.shape
        q = z.reshape(B, T, 1, D)
        scale = 1.0 / (D ** 0.5)
        sco = jnp.einsum("bqhd,bkhd->bhqk", q, q) * scale
        mask = jnp.tril(jnp.ones((T, T), bool))
        sco = jnp.where(mask[None, None], sco, -1e30)
        a = jax.nn.softmax(sco, axis=-1)
        att = jnp.einsum("bhqk,bkhd->bqhd", a, q).reshape(B, T, D)
        h = h + (z + att)
    return h


def test_composed_forward_matches_dense():
    import jax
    import jax.numpy as jnp
    from ray_tpu.train.compose import (make_composed_loss,
                                       put_composed_batch,
                                       shard_stage_params)
    mesh = _mesh({"pipeline": 2, "sequence": 2, "data": 2})
    S, B, T, D, M = 2, 4, 8, 16, 2
    rng = np.random.RandomState(0)
    params = _make_params(rng, S, D)
    x = np.asarray(rng.randn(B, T, D), np.float32)
    y = np.asarray(rng.randn(B, T, D), np.float32)

    def loss_fn(out, batch):
        d = (out - batch[1]) ** 2
        return jnp.sum(d), jnp.asarray(d.size, jnp.float32)

    loss = make_composed_loss(_toy_stage_fn(), loss_fn, mesh,
                              num_microbatches=M)
    got = float(loss(shard_stage_params(params, mesh),
                     put_composed_batch((x, y), mesh)))

    ref_out = _dense_reference(params, x, S)
    want = float(jnp.mean((ref_out - y) ** 2))
    assert got == pytest.approx(want, rel=2e-4), (got, want)


def test_composed_training_loss_decreases():
    import jax.numpy as jnp
    import optax
    from ray_tpu.train.compose import (make_composed_train_step,
                                       put_composed_batch)
    mesh = _mesh({"pipeline": 2, "sequence": 2, "data": 2})
    S, B, T, D, M = 2, 8, 8, 8, 2
    rng = np.random.RandomState(1)
    params = _make_params(rng, S, D)
    x = np.asarray(rng.randn(B, T, D), np.float32)
    y = x * 0.5 + 0.1

    def loss_fn(out, batch):
        d = (out - batch[1]) ** 2
        return jnp.sum(d), jnp.asarray(d.size, jnp.float32)

    step, state = make_composed_train_step(
        _toy_stage_fn(), loss_fn, optax.adam(3e-3), mesh, params,
        num_microbatches=M)
    batch = put_composed_batch((x, y), mesh)
    losses = []
    for _ in range(40):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.5, losses[::8]


def test_composed_gang_dcn_pipeline_sequence():
    """VERDICT r5 #5 done bar: JaxTrainer with a mixed
    {dcn, pipeline, data, sequence} mesh spanning a 2-process gang;
    the composed step trains and the loss decreases."""
    import ray_tpu._private.worker as worker_mod
    from ray_tpu.runtime import Cluster
    if worker_mod.is_initialized():
        worker_mod.shutdown()
    with Cluster(num_workers=2, resources_per_worker={"CPU": 2}):
        from ray_tpu.air import session
        from ray_tpu.train import JaxTrainer, ScalingConfig

        def loop(config):
            import jax
            import jax.numpy as jnp
            import numpy as onp
            import optax
            from ray_tpu.train.compose import (make_composed_train_step,
                                               put_composed_batch)
            mesh = session.get_mesh()
            rank = session.get_world_rank()
            S, D, M = int(mesh.shape["pipeline"]), 8, 2
            rng = onp.random.RandomState(7)
            params = {
                "w": jnp.asarray(rng.randn(S, D, D) * 0.05, jnp.float32),
                "b": jnp.zeros((S, D), jnp.float32),
            }

            def stage_fn(p, x):
                from ray_tpu.parallel.sequence import ring_attention
                h = jnp.einsum("btd,de->bte", x, p["w"]) + p["b"]
                h = jax.nn.gelu(h)
                B, T, Dm = h.shape
                qkv = h.reshape(B, T, 1, Dm)
                a = ring_attention(qkv, qkv, qkv,
                                   axis_name="sequence", causal=True)
                return x + h + a.reshape(B, T, Dm)

            def loss_fn(out, batch):
                d = (out - batch[1]) ** 2
                return jnp.sum(d), jnp.asarray(d.size, jnp.float32)

            step, state = make_composed_train_step(
                stage_fn, loss_fn, optax.adam(3e-3), mesh, params,
                num_microbatches=M)
            # per-host local batch shard (B_local x T_local layout)
            local = onp.random.RandomState(100 + rank)
            xl = onp.asarray(local.randn(8, 8, D), onp.float32)
            yl = xl * 0.5 + 0.1
            losses = []
            for _ in range(60):
                batch = put_composed_batch((xl, yl), mesh)
                state, m = step(state, batch)
                losses.append(float(m["loss"]))
            session.report({
                "first_loss": losses[0], "last_loss": losses[-1],
                "n_procs": jax.process_count(),
                "mesh": {k: int(v) for k, v in mesh.shape.items()
                         if v > 1},
            })

        result = JaxTrainer(
            loop,
            scaling_config=ScalingConfig(
                num_workers=2,
                mesh={"dcn": 2, "pipeline": 2, "data": 2,
                      "sequence": 2},
                jax_distributed=True,
                placement_strategy="STRICT_SPREAD")).fit()
        assert result.ok, result.error
        m = result.metrics
        assert m["n_procs"] == 2
        assert m["mesh"] == {"dcn": 2, "pipeline": 2, "data": 2,
                             "sequence": 2}
        assert m["last_loss"] < m["first_loss"] * 0.5, m


def test_composed_with_expert_all_to_all():
    """EP inside the composed step: the stage function routes tokens
    through experts sharded over the `expert` axis with a manual
    all_to_all — proving the fourth strategy composes in the same
    shard_map'd train step (PP x EP x DP here)."""
    import jax
    import jax.numpy as jnp
    import optax
    from ray_tpu.train.compose import (make_composed_train_step,
                                       put_composed_batch)
    mesh = _mesh({"pipeline": 2, "expert": 2, "data": 2})
    S, B, T, D, M, E = 2, 8, 4, 8, 2, 2
    rng = np.random.RandomState(3)
    params = {
        "w": jnp.asarray(rng.randn(S, D, D) * 0.05, jnp.float32),
        # per-stage, per-LOCAL-expert FFN weight [S, E_local=1, D, D]
        "we": jnp.asarray(rng.randn(S, 1, D, D) * 0.05, jnp.float32),
    }

    def stage_fn(p, x):
        # x: [b_local, T, D]; one expert per `expert`-axis member.
        h = jax.nn.gelu(jnp.einsum("btd,de->bte", x, p["w"]))
        b, t, d = h.shape
        # static round-robin routing: split local tokens in two, send
        # half to each expert via all_to_all (capacity-1 routing; the
        # collective plumbing + grads are what this test exercises)
        toks = h.reshape(b * t, d)
        half = toks.shape[0] // 2
        send = toks.reshape(2, half, d)
        recv = jax.lax.all_to_all(send, "expert", split_axis=0,
                                  concat_axis=0, tiled=False)
        # apply THIS member's expert FFN to everything it received
        out = jax.nn.gelu(
            jnp.einsum("shd,df->shf", recv, p["we"][0]))
        back = jax.lax.all_to_all(out, "expert", split_axis=0,
                                  concat_axis=0, tiled=False)
        return x + back.reshape(b, t, d)

    def loss_fn(out, batch):
        diff = (out - batch[1]) ** 2
        return jnp.sum(diff), jnp.asarray(diff.size, jnp.float32)

    x = np.asarray(rng.randn(B, T, D), np.float32)
    step, state = make_composed_train_step(
        stage_fn, loss_fn, optax.adam(5e-3), mesh, params,
        num_microbatches=M)
    batch = put_composed_batch((x, x * 0.5), mesh)
    losses = []
    for _ in range(40):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])
