"""Daemon-mode CLI e2e: start --head spawns a detached head daemon;
external CLI invocations in FRESH processes authenticate via the
token persisted in the address file (regression: the daemon minted a
random cluster token but never persisted it, so every external CLI
call — status, submit, stop — died with 'authentication failed' and
stop leaked the daemon).

Reference analogue: `ray start --head` + `ray status` from another
shell (python/ray/tests/test_cli.py).
"""
import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cli(*args, timeout=120):
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.run(
        [sys.executable, "-m", "ray_tpu", *args],
        capture_output=True, text=True, timeout=timeout, cwd=REPO,
        env=env)


@pytest.fixture
def daemon():
    from ray_tpu.scripts.head_daemon import address_file_path
    if os.path.exists(address_file_path()):
        pytest.skip("another head daemon is already running")
    res = _cli("start", "--head", "--num-workers", "1")
    assert res.returncode == 0, res.stdout + res.stderr
    try:
        yield
    finally:
        _cli("stop")
        deadline = time.time() + 15
        while time.time() < deadline and os.path.exists(
                address_file_path()):
            time.sleep(0.2)
        subprocess.run(["pkill", "-f", "ray_tpu.scripts.head_daemon"],
                       capture_output=True)


def test_daemon_cli_auth_roundtrip(daemon):
    from ray_tpu.scripts.head_daemon import (address_file_path,
                                             read_address_file)
    # token persisted, file private
    addr, token, pid = read_address_file()
    assert addr and token and pid
    assert os.stat(address_file_path()).st_mode & 0o777 == 0o600

    # status from a FRESH process authenticates via the file token
    res = _cli("status")
    assert res.returncode == 0, res.stdout + res.stderr
    assert "Workers (1)" in res.stdout

    # a job runs end-to-end through the daemon
    res = _cli("submit", "--", sys.executable, "-c",
               "print('daemon-job-ok')")
    assert res.returncode == 0, res.stdout + res.stderr
    assert "daemon-job-ok" in res.stdout

    # stop actually reaches the daemon (auth ok) and removes the file
    res = _cli("stop")
    assert res.returncode == 0, res.stdout + res.stderr
    deadline = time.time() + 15
    while time.time() < deadline:
        if not os.path.exists(address_file_path()):
            break
        time.sleep(0.2)
    probe = subprocess.run(
        ["pgrep", "-f", "ray_tpu.scripts.head_daemon"],
        capture_output=True, text=True)
    assert probe.returncode != 0, "daemon survived stop"
