"""Direct actor-task dispatch (caller -> worker, head bypassed).

Reference capability: CoreWorker direct actor transport
(src/ray/core_worker/transport/ — actor calls skip the GCS/raylet
after the first address resolution). These tests pin the two
properties the fast path must keep: per-caller ordering on the direct
pipe, and reroute-not-error when the cached route goes stale across
an actor restart.
"""
import time

import pytest

import ray_tpu
from ray_tpu.runtime import Cluster


@pytest.fixture(scope="module")
def cluster():
    import ray_tpu._private.worker as worker_mod
    if worker_mod.is_initialized():
        worker_mod.shutdown()
    c = Cluster(num_workers=2, resources_per_worker={"CPU": 2})
    yield c
    c.shutdown()


def test_direct_calls_ordered_and_correct(cluster):
    @ray_tpu.remote
    class Seq:
        def __init__(self):
            self.log = []

        def add(self, i):
            self.log.append(i)
            return i

        def log_all(self):
            return self.log

    s = Seq.remote()
    refs = [s.add.remote(i) for i in range(200)]
    assert ray_tpu.get(refs, timeout=30) == list(range(200))
    # per-caller ordering must survive the pipelined one-way batches
    assert ray_tpu.get(s.log_all.remote(), timeout=10) == \
        list(range(200))


def test_direct_route_is_cached(cluster):
    """After the first call, subsequent calls must not re-resolve the
    address (one head RPC per TTL window, not per call)."""
    from ray_tpu._private.worker import global_worker
    rt = global_worker().runtime

    @ray_tpu.remote
    class A:
        def f(self):
            return 1

    a = A.remote()
    ray_tpu.get(a.f.remote(), timeout=10)
    st = getattr(rt.head, "_direct_actor_state", None)
    assert st is not None, "direct dispatch never engaged"
    assert a._actor_id.hex() in st["addrs"]
    assert len(st["senders"]) >= 1


def test_stale_route_reroutes_after_restart(cluster):
    """Kill the actor's worker; the very next call rides the STALE
    cached route, must bounce through the head's reroute path, and
    must still return a value (no ActorDiedError for a live actor)."""
    @ray_tpu.remote(max_restarts=1)
    class Phoenix:
        def pid(self):
            import os
            return os.getpid()

    cluster.add_worker()
    p = Phoenix.remote()
    pid = ray_tpu.get(p.pid.remote(), timeout=15)   # caches the route
    victim = None
    for wid, proc in list(cluster.node.procs.items()):
        if proc.pid == pid:
            victim = wid
    assert victim is not None
    cluster.kill_worker(victim)
    # Single shot, no retry loop: the stale direct send must be
    # rerouted (head waits out the rebind), not failed.
    new_pid = ray_tpu.get(p.pid.remote(), timeout=25)
    assert new_pid != pid
    cluster.add_worker()


def test_dead_actor_still_raises(cluster):
    from ray_tpu.exceptions import ActorDiedError

    @ray_tpu.remote
    class V:
        def ping(self):
            return "pong"

    v = V.remote()
    assert ray_tpu.get(v.ping.remote(), timeout=10) == "pong"
    ray_tpu.kill(v)
    time.sleep(0.2)
    with pytest.raises(ActorDiedError):
        ray_tpu.get(v.ping.remote(), timeout=15)
