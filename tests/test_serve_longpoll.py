"""Serve long-poll push tests (VERDICT r2 #3): replica-table changes
reach handles by pub/sub push on the distributed runtime — no steady-
state polling, scale events visible fast (reference long-poll push,
serve/_private/long_poll.py:63,179)."""
import time

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.runtime import Cluster


def _make_echo():
    # Defined inside a function so cloudpickle serializes it by value
    # (workers can't import test modules).
    class Echo:
        def __call__(self, x):
            return f"echo:{x}"
    return Echo


@pytest.fixture(scope="module")
def serve_cluster():
    import ray_tpu._private.worker as worker_mod
    if worker_mod.is_initialized():
        worker_mod.shutdown()
    c = Cluster(num_workers=2, resources_per_worker={"CPU": 4})
    yield c
    serve.shutdown()
    c.shutdown()


def test_push_replica_table_and_zero_polling(serve_cluster):
    app = serve.deployment(_make_echo(), name="echo", num_replicas=1)
    handle = serve.run(app.bind())
    assert ray_tpu.get(handle.remote("hi"), timeout=30) == "echo:hi"

    # Push mode must engage on the distributed runtime.
    deadline = time.time() + 5
    while not handle._push_active and time.time() < deadline:
        handle.remote("warm")
        time.sleep(0.05)
    assert handle._push_active, "handle never received a push"

    # Steady state: requests must not poll the controller.
    before = handle._poll_count
    for _ in range(20):
        ray_tpu.get(handle.remote("x"), timeout=30)
    assert handle._poll_count == before, \
        f"{handle._poll_count - before} polling RPCs in steady state"


def test_scale_up_visible_by_push(serve_cluster):
    app = serve.deployment(_make_echo(), name="echo2", num_replicas=1)
    handle = serve.run(app.bind())
    ray_tpu.get(handle.remote("a"), timeout=30)
    deadline = time.time() + 5
    while not handle._push_active and time.time() < deadline:
        time.sleep(0.02)
    assert handle._push_active

    # Scale up; the handle must see 2 replicas WITHOUT any poll.
    before_polls = handle._poll_count
    app2 = serve.deployment(_make_echo(), name="echo2", num_replicas=2)
    serve.run(app2.bind(), wait_for_ready=True)
    deadline = time.time() + 10
    while len(handle._replicas) < 2 and time.time() < deadline:
        time.sleep(0.005)
    assert len(handle._replicas) == 2, "scale-up never reached handle"
    assert handle._poll_count == before_polls


def test_push_latency_under_50ms(serve_cluster):
    """Raw hub->subscriber latency for the serve channel shape."""
    import threading

    import cloudpickle

    head = serve_cluster.runtime.head
    chan = "serve:replicas:latency_probe"
    head.call("publish", chan, cloudpickle.dumps({"v": 0}))
    seen = threading.Event()

    from ray_tpu.runtime.pubsub import Subscriber
    from ray_tpu.runtime.rpc import RpcClient
    sub = Subscriber(RpcClient(f"{head.host}:{head.port}"))
    sub.subscribe_state(chan, lambda v, b: seen.set()
                        if cloudpickle.loads(b)["v"] == 1 else None)
    time.sleep(0.3)            # let the long-poll attach
    t0 = time.perf_counter()
    head.call("publish", chan, cloudpickle.dumps({"v": 1}))
    assert seen.wait(timeout=2.0)
    latency = time.perf_counter() - t0
    sub.stop()
    assert latency < 0.05, f"push latency {latency * 1000:.0f}ms"
