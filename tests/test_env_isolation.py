"""Runtime-env worker-process isolation (VERDICT r2 #7): env tasks run
ONLY in dedicated workers keyed by their env, and concurrent no-env
tasks can never observe a task's env (reference: env-keyed worker
pools, src/ray/raylet/worker_pool.h:149)."""
import time

import pytest

import ray_tpu
from ray_tpu.runtime import Cluster


@pytest.fixture(scope="module")
def cluster():
    import ray_tpu._private.worker as worker_mod
    if worker_mod.is_initialized():
        worker_mod.shutdown()
    c = Cluster(num_workers=2, resources_per_worker={"CPU": 4})
    yield c
    c.shutdown()


def test_concurrent_env_and_plain_tasks_are_isolated(cluster):
    """Interleave many env / no-env executions; assert NO plain task
    ever sees the env var — isolation, not just restoration."""
    @ray_tpu.remote(runtime_env={"env_vars": {"ISO_FLAG": "secret"}})
    def env_task():
        import os
        import time as _t
        _t.sleep(0.01)          # widen the overlap window
        return os.environ.get("ISO_FLAG"), os.getpid()

    @ray_tpu.remote
    def plain_task():
        import os
        import time as _t
        _t.sleep(0.005)
        return os.environ.get("ISO_FLAG"), os.getpid()

    refs = []
    for _ in range(15):
        refs.append(("env", env_task.remote()))
        refs.append(("plain", plain_task.remote()))
    env_pids, plain_pids = set(), set()
    for kind, ref in refs:
        val, pid = ray_tpu.get(ref, timeout=60)
        if kind == "env":
            assert val == "secret", "env task missing its env"
            env_pids.add(pid)
        else:
            assert val is None, \
                f"no-env task observed ISO_FLAG={val!r} (pid {pid})"
            plain_pids.add(pid)
    # The env ran in dedicated worker processes, disjoint from the
    # plain pool.
    assert env_pids and plain_pids
    assert env_pids.isdisjoint(plain_pids)


def test_same_env_reuses_worker_different_env_does_not(cluster):
    @ray_tpu.remote(runtime_env={"env_vars": {"POOL_A": "1"}})
    def in_a():
        import os
        return os.getpid()

    @ray_tpu.remote(runtime_env={"env_vars": {"POOL_B": "1"}})
    def in_b():
        import os
        return os.getpid()

    a1 = ray_tpu.get(in_a.remote(), timeout=60)
    a2 = ray_tpu.get(in_a.remote(), timeout=60)
    b1 = ray_tpu.get(in_b.remote(), timeout=60)
    assert a1 == a2, "same env must reuse its dedicated worker"
    assert b1 != a1, "different envs must use different processes"


def test_env_actor_runs_in_dedicated_worker(cluster):
    @ray_tpu.remote(runtime_env={"env_vars": {"ACTOR_ENV": "on"}})
    class EnvActor:
        def read(self):
            import os
            return os.environ.get("ACTOR_ENV"), os.getpid()

    @ray_tpu.remote
    def plain_pid():
        import os
        return os.getpid()

    a = EnvActor.remote()
    val, apid = ray_tpu.get(a.read.remote(), timeout=60)
    assert val == "on"
    plain = {ray_tpu.get(plain_pid.remote(), timeout=30)
             for _ in range(6)}
    assert apid not in plain


def test_pg_never_reserves_on_env_workers(cluster):
    """PG bundles must skip dedicated runtime-env workers: a bundle
    there would run env-less PG work inside a mutated environment and
    pin a worker the idle reaper may stop."""
    from ray_tpu.util import placement_group, remove_placement_group

    @ray_tpu.remote(runtime_env={"env_vars": {"PG_ENV": "1"}})
    def spawn_env_worker():
        import os
        return os.getpid()

    ray_tpu.get(spawn_env_worker.remote(), timeout=60)

    # 2 plain workers + 1 env worker are alive. STRICT_SPREAD over 3
    # bundles can only succeed by using the env worker — it must not.
    pg3 = placement_group([{"CPU": 1}] * 3, strategy="STRICT_SPREAD")
    assert not pg3.wait(1.5), \
        "PG reserved a bundle on a dedicated env worker"
    remove_placement_group(pg3)

    # Positive control: 2 bundles fit on the plain workers.
    pg2 = placement_group([{"CPU": 1}] * 2, strategy="STRICT_SPREAD")
    assert pg2.wait(10)
    remove_placement_group(pg2)
