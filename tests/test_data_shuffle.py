"""Distributed Data reorganization: repartition/sort/groupby/split/zip
run as task graphs only — no row ever materializes in the driver
(reference shape: python/ray/data/_internal/push_based_shuffle.py).
"""
import contextlib

import numpy as np
import pytest

import ray_tpu
from ray_tpu.data import from_items, range_dataset
from ray_tpu.data.dataset import Dataset


@contextlib.contextmanager
def no_driver_rows():
    """Ban driver-side row materialization: both take_all and
    re-putting rows from the driver (the old materialize() get+put
    pattern) explode while a reorganization op runs."""
    def _boom(self):
        raise AssertionError(
            "take_all() called during a reorganization op")

    real_take_all = Dataset.take_all
    real_put = ray_tpu.put

    def _no_put(obj, **kw):
        raise AssertionError(
            "driver-side put() during a reorganization op")
    Dataset.take_all = _boom
    ray_tpu.put = _no_put
    try:
        yield
    finally:
        Dataset.take_all = real_take_all
        ray_tpu.put = real_put


def _rows(ds):
    # consumption (allowed to materialize) without going through take_all
    out = []
    for ref in ds.materialize()._block_refs:
        out.extend(ray_tpu.get(ref))
    return out


def test_repartition_no_driver_rows(rt):
    src = range_dataset(100, parallelism=7)
    with no_driver_rows():
        ds = src.repartition(4)
        assert ds.num_blocks() == 4
        rows = _rows(ds)
    assert rows == list(range(100))            # order preserved
    lens = [len(ray_tpu.get(b)) for b in ds._block_refs]
    assert max(lens) - min(lens) <= 1


def test_sort_multiblock_no_driver_rows(rt):
    rng = np.random.RandomState(0)
    vals = [int(v) for v in rng.randint(0, 10_000, size=500)]
    src = from_items(vals, parallelism=8)
    with no_driver_rows():
        rows = _rows(src.sort())
    assert rows == sorted(vals)


def test_sort_descending_by_key(rt):
    src = from_items([{"k": i % 17, "v": i} for i in range(200)],
                     parallelism=6)
    with no_driver_rows():
        keys = [r["k"] for r in _rows(src.sort("k", descending=True))]
    assert keys == sorted(keys, reverse=True)


def test_groupby_shuffle_no_driver_rows(rt):
    src = from_items([{"g": i % 5, "v": i} for i in range(100)],
                     parallelism=8)
    with no_driver_rows():
        rows = _rows(src.groupby("g").sum("v"))
    assert {r["key"]: r["sum"] for r in rows} == {
        g: sum(i for i in range(100) if i % 5 == g) for g in range(5)}
    assert [r["key"] for r in rows] == sorted(r["key"] for r in rows)


def test_groupby_count_sorted(rt):
    src = from_items([chr(ord("a") + (i % 3)) for i in range(30)],
                     parallelism=4)
    with no_driver_rows():
        rows = _rows(src.groupby(lambda r: r).count())
    assert rows == [{"key": "a", "count": 10},
                    {"key": "b", "count": 10},
                    {"key": "c", "count": 10}]


def test_split_no_driver_rows(rt):
    src = range_dataset(103, parallelism=5)
    with no_driver_rows():
        shards = src.split(4)
        assert len(shards) == 4
        all_rows = [r for s in shards for r in _rows(s)]
        sizes = [len(_rows(s)) for s in shards]
    assert all_rows == list(range(103))
    assert max(sizes) - min(sizes) <= 1


def test_zip_no_driver_rows(rt):
    a = range_dataset(60, parallelism=4)
    b = from_items([i * 10 for i in range(60)], parallelism=7)
    with no_driver_rows():
        rows = _rows(a.zip(b))
    assert rows == [(i, i * 10) for i in range(60)]


def test_zip_unequal_raises(rt):
    with pytest.raises(ValueError):
        range_dataset(10).zip(range_dataset(11))


def test_sum_mean_min_max_remote(rt):
    src = from_items([{"v": i} for i in range(50)], parallelism=6)
    with no_driver_rows():
        assert src.sum("v") == sum(range(50))
        assert src.mean("v") == pytest.approx(24.5)
        assert src.min("v") == 0
        assert src.max("v") == 49


def test_limit_truncates_remotely(rt):
    src = range_dataset(100, parallelism=10)
    with no_driver_rows():
        ds = src.limit(37)
        rows = _rows(ds)
    assert rows == list(range(37))
    # whole blocks past the cutoff were dropped, not copied
    assert ds.num_blocks() <= 4


def test_unique_remote(rt):
    src = from_items([i % 7 for i in range(70)], parallelism=5)
    with no_driver_rows():
        uniq = src.unique()
    assert sorted(uniq) == list(range(7))


def test_lazy_stages_stay_in_store(rt):
    # pending map stages must execute as tasks whose outputs stay in
    # the object store — not get+put through the driver
    src = (range_dataset(120, parallelism=6)
           .map(lambda x: x * 2)
           .filter(lambda x: x % 4 == 0))
    with no_driver_rows():
        rows = _rows(src.repartition(3))
    assert rows == [x * 2 for x in range(120) if (x * 2) % 4 == 0]


def test_groupby_string_keys_stable_hash(rt):
    # str keys exercise _stable_hash (process-randomized hash() would
    # split a key across partitions on distributed workers)
    src = from_items([{"g": f"key-{i % 4}"} for i in range(80)],
                     parallelism=8)
    with no_driver_rows():
        rows = _rows(src.groupby("g").count())
    assert {r["key"]: r["count"] for r in rows} == {
        f"key-{i}": 20 for i in range(4)}


def test_aggregate_non_dict_rows_no_silent_loss(rt):
    # agg rows without a "key" column: result arrives unsorted but
    # complete, and no error escapes
    src = from_items([{"g": i % 3} for i in range(30)], parallelism=4)
    with no_driver_rows():
        rows = _rows(src.groupby("g").aggregate(
            lambda k, rs: (k, len(rs))))
    assert sorted(rows) == [(0, 10), (1, 10), (2, 10)]


def test_min_handles_none_values(rt):
    ds = from_items([{"v": None}], parallelism=1)
    assert ds.min("v") is None


def test_aggregate_larger_than_any_block(rt):
    # aggregate data (1000 rows) far exceeds any single block (~84 rows)
    src = from_items([{"g": i % 3, "v": 1} for i in range(1000)],
                     parallelism=12)
    with no_driver_rows():
        rows = _rows(src.groupby("g").count())
    assert {r["key"]: r["count"] for r in rows} == {
        0: 334, 1: 333, 2: 333}


def test_write_dir_mode_one_file_per_block(rt, tmp_path):
    """Directory sinks write one part file per block via remote tasks;
    rows never pass through the driver."""
    import csv
    import json
    import os

    from ray_tpu.data import datasources as rd
    src = from_items([{"a": i, "b": f"s{i}"} for i in range(40)],
                     parallelism=4)
    with no_driver_rows():
        out = rd.write_csv(src, str(tmp_path / "csvdir") + os.sep)
    parts = sorted(os.listdir(out))
    assert parts == [f"part-0000{i}.csv" for i in range(4)]
    rows = []
    for p in parts:
        with open(os.path.join(out, p)) as f:
            rows.extend(csv.DictReader(f))
    assert len(rows) == 40

    with no_driver_rows():
        jout = rd.write_json(src, str(tmp_path / "jsondir") + os.sep)
    jrows = []
    for p in sorted(os.listdir(jout)):
        with open(os.path.join(jout, p)) as f:
            jrows.extend(json.loads(l) for l in f)
    assert sorted(r["a"] for r in jrows) == list(range(40))


def test_read_tasks_per_file(rt, tmp_path):
    """Readers are one remote task per file; file bytes never pass
    through the driver."""
    from ray_tpu.data.dataset import read_csv
    for i in range(3):
        (tmp_path / f"f{i}.csv").write_text(
            "a,b\n" + "".join(f"{i * 10 + j},x\n" for j in range(5)))
    with no_driver_rows():
        ds = read_csv(str(tmp_path / "*.csv"), parallelism=2)
        rows = _rows(ds)
    assert sorted(r["a"] for r in rows) == \
        sorted(i * 10 + j for i in range(3) for j in range(5))


def test_random_access_distributed_build(rt):
    from ray_tpu.data.datasources import RandomAccessDataset
    src = from_items([{"k": i, "v": i * i}
                      for i in range(200)][::-1], parallelism=8)
    with no_driver_rows():
        rad = RandomAccessDataset(src, "k")
    assert rad.get(7)["v"] == 49
    assert rad.get(199)["v"] == 199 * 199
    assert rad.get(1000) is None


def test_write_csv_unions_heterogeneous_schemas(rt, tmp_path):
    """CSV schema is the dataset-wide field union — a column appearing
    only in later rows/blocks is never silently dropped, and every
    part file shares one header."""
    import csv
    import os

    from ray_tpu.data import datasources as rd
    src = from_items([{"a": 1}, {"a": 2, "b": 9}] * 10, parallelism=2)
    single = rd.write_csv(src, str(tmp_path / "one.csv"))
    with open(single) as f:
        rows = list(csv.DictReader(f))
    assert set(rows[0]) == {"a", "b"}
    assert sum(1 for r in rows if r["b"] == "9") == 10

    out = rd.write_csv(src, str(tmp_path / "parts") + os.sep)
    headers = set()
    for p in sorted(os.listdir(out)):
        with open(os.path.join(out, p)) as f:
            headers.add(f.readline().strip())
    assert headers == {"a,b"}       # one schema across all parts


def test_push_shuffle_repartition_matches_pull(rt):
    """Push-based shuffle (VERDICT r5 missing #6): large-block-count
    repartition via the pipelined merge path preserves global row
    order and content exactly like the pull path."""
    from ray_tpu.data import Dataset
    blocks = [[i * 10 + j for j in range(10)] for i in range(40)]
    ds = Dataset.from_blocks(blocks) if hasattr(Dataset, "from_blocks") \
        else Dataset([ray_tpu.put(b) for b in blocks])
    pull = ds.repartition(8, strategy="pull").take_all()
    push = ds.repartition(8, strategy="push").take_all()
    assert push == pull == [i for i in range(400)]
    # auto picks push above the threshold
    auto = ds.repartition(8).take_all()
    assert auto == pull


def test_push_random_shuffle_is_permutation(rt):
    from ray_tpu.data import Dataset
    blocks = [[i * 5 + j for j in range(5)] for i in range(40)]
    ds = Dataset([ray_tpu.put(b) for b in blocks])
    out = ds.random_shuffle(seed=7, strategy="push").take_all()
    assert sorted(out) == list(range(200))
    assert out != list(range(200))          # actually shuffled
    # deterministic per seed
    out2 = ds.random_shuffle(seed=7, strategy="push").take_all()
    assert out == out2


def test_push_shuffle_bounded_inflight(rt):
    """The pipeline bounds live intermediates: with 48 input blocks and
    round size 16, at no point do O(N^2) part objects exist. Proxied by
    asserting the fold chain depth equals ceil(N/round)."""
    from ray_tpu.data import dataset as dmod
    calls = []
    orig = dmod._fold_concat.remote

    class Counting:
        def remote(self, *a, **k):
            calls.append(len(a) - 1)
            return orig(*a, **k)

    old = dmod._fold_concat
    try:
        dmod._fold_concat = Counting()
        from ray_tpu.data import Dataset
        blocks = [[i] for i in range(48)]
        ds = Dataset([ray_tpu.put(b) for b in blocks])
        out = ds.repartition(4, strategy="push").take_all()
        assert sorted(out) == list(range(48))
    finally:
        dmod._fold_concat = old
    # 48 blocks / round 16 = 3 folds per output partition, 4 partitions
    assert len(calls) == 12
    assert max(calls) <= dmod._PUSH_ROUND
