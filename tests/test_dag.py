"""DAG API tests (parity: python/ray/dag/tests)."""
import pytest

import ray_tpu
from ray_tpu.dag import InputNode, MultiOutputNode


def test_function_dag(rt):
    @ray_tpu.remote
    def a(x):
        return x + 1

    @ray_tpu.remote
    def b(x, y):
        return x * y

    dag = b.bind(a.bind(1), a.bind(2))
    assert ray_tpu.get(dag.execute()) == 2 * 3


def test_input_node(rt):
    @ray_tpu.remote
    def double(x):
        return 2 * x

    @ray_tpu.remote
    def add(x, y):
        return x + y

    with InputNode() as inp:
        dag = add.bind(double.bind(inp), inp)
    assert ray_tpu.get(dag.execute(5)) == 15
    assert ray_tpu.get(dag.execute(1)) == 3


def test_input_attribute_access(rt):
    @ray_tpu.remote
    def combine(a, b):
        return a - b

    with InputNode() as inp:
        dag = combine.bind(inp["hi"], inp["lo"])
    assert ray_tpu.get(dag.execute({"hi": 10, "lo": 4})) == 6
    # kwargs-style execute
    assert ray_tpu.get(dag.execute(hi=3, lo=1)) == 2


def test_shared_node_executes_once(rt):
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

    @ray_tpu.remote
    def add(x, y):
        return x + y

    counter = Counter.remote()

    @ray_tpu.remote
    def record(c):
        return ray_tpu.get(c.bump.remote())

    shared = record.bind(counter)
    dag = add.bind(shared, shared)
    # diamond: the shared node must run once, so total = 1+1
    assert ray_tpu.get(dag.execute()) == 2
    assert ray_tpu.get(counter.bump.remote()) == 2  # only one prior bump


def test_actor_dag(rt):
    @ray_tpu.remote
    class Accum:
        def __init__(self, start):
            self.v = start

        def add(self, x):
            self.v += x
            return self.v

    node = Accum.bind(10)
    dag = node.add.bind(5)
    assert ray_tpu.get(dag.execute()) == 15
    # Same ClassNode reuses the same actor across executions.
    assert ray_tpu.get(dag.execute()) == 20


def test_multi_output(rt):
    @ray_tpu.remote
    def f(x):
        return x + 1

    with InputNode() as inp:
        dag = MultiOutputNode([f.bind(inp), f.bind(f.bind(inp))])
    r1, r2 = dag.execute(1)
    assert ray_tpu.get(r1) == 2
    assert ray_tpu.get(r2) == 3


def test_nested_structure_args(rt):
    @ray_tpu.remote
    def one():
        return 1

    @ray_tpu.remote
    def total(values):
        return sum(ray_tpu.get(list(values)))

    dag = total.bind([one.bind(), one.bind(), one.bind()])
    assert ray_tpu.get(dag.execute()) == 3


def test_dag_node_not_serializable(rt):
    @ray_tpu.remote
    def f():
        return 1

    with pytest.raises(TypeError):
        import pickle
        pickle.dumps(f.bind())
