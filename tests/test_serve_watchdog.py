"""Serving watchdog tests (serve/watchdog.py) + chaos campaign smoke.

Two layers, mirroring test_engine_pool.py: the escalation ladder
(HEALTHY -> SUSPECT -> WEDGED), progress judgment, and capacity
exclusion against scripted heartbeat fakes under a fake clock — then
the end-to-end contract against real tiny-Llama engines: a wedge
injected with a `hang` fault plan is detected within the stall
deadline, escalated hang -> death without touching healthy replicas,
unstreamed requests complete token-identically on survivors, and the
released zombie is generation-fenced (no token commit, no
prefix-cache touch, leak-free quiescence). The chaos campaign itself
(tools/chaos_serve.py) runs once as a smoke and must pass its own
schema family.
"""
import json
import os
import sys
import threading
import time
from pathlib import Path

import jax.numpy as jnp
import pytest

from ray_tpu.models.llama import Llama, llama_tiny
from ray_tpu.serve.engine import LLMEngine
from ray_tpu.serve.engine_pool import (DEAD, HEALTHY, SUSPECT,
                                       EnginePool)
from ray_tpu.serve.errors import EngineShutdown
from ray_tpu.serve.faults import (FaultInjector, check_pool_quiesced,
                                  check_quiesced)
from ray_tpu.serve.watchdog import PoolWatchdog, ReplicaWedged

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


@pytest.fixture(scope="module")
def tiny_model():
    # fp32 so greedy decode is bit-identical across replicas
    cfg = llama_tiny(dtype=jnp.float32)
    model = Llama(cfg)
    import jax
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    return model, params


@pytest.fixture(autouse=True)
def _no_page_leaks(monkeypatch):
    """Every real engine built in a test — including force-killed
    corpses — must end with allocator occupancy == prefix-cache
    residency."""
    created = []
    orig = LLMEngine.__init__

    def record(self, *args, **kwargs):
        orig(self, *args, **kwargs)
        created.append(self)

    monkeypatch.setattr(LLMEngine, "__init__", record)
    yield
    for eng in created:
        cached = (eng.prefix_cache.cached_pages
                  if eng.prefix_cache is not None else 0)
        occ = eng.alloc.occupancy()
        assert occ == cached, (
            f"engine leaked pages at teardown: occupancy {occ} != "
            f"prefix-cache residency {cached}")


def _reference_completion(model, params, prompt, n):
    import numpy as np
    from ray_tpu.models.llama import generate
    out = generate(model, params, jnp.asarray([prompt], jnp.int32),
                   max_new_tokens=n, temperature=0.0)
    return np.asarray(out)[0, len(prompt):].tolist()


# ------------------------------------------ heartbeat fakes + clock


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class HBFakeEngine:
    """A replica engine reduced to the surface the watchdog touches:
    a load report carrying heartbeat_age_s/has_work driven by a fake
    clock, plus the lifecycle the pool's death path needs."""

    def __init__(self, idx, clock):
        self.idx = idx
        self._clock = clock
        self._stopped = False
        self._draining = False
        self._hb = clock()
        self.has_work = False
        self.force_kills = 0
        self.force_kill_err = None
        self.stats = {"submitted": 0}
        self.submits = []
        self.started = False

    def start(self):
        self.started = True
        return self

    def touch(self):
        self._hb = self._clock()

    def submit(self, prompt, max_new_tokens=64, deadline_s=None):
        if self._stopped:
            raise EngineShutdown("engine stopped")
        self.submits.append(list(prompt))
        self.stats["submitted"] += 1

        class _H:
            def stream(_self):
                yield from [1, 2]

            def cancel(_self):
                return True
        return _H()

    def shutdown(self):
        self._stopped = True

    def force_kill(self, err=None):
        self.force_kills += 1
        self.force_kill_err = err
        self._stopped = True

    def drain(self):
        self._draining = True

    def wait_idle(self, timeout_s=30.0):
        return True

    def is_idle(self):
        return True

    def load_report(self):
        return {"free_slots": 4, "free_pages": 100, "queue_depth": 0,
                "outstanding_tokens": 0, "max_queued": None,
                "shed_retry_after_s": 1.0,
                "draining": self._draining, "stopped": self._stopped,
                "prefix_digest": frozenset(),
                "heartbeat_age_s": self._clock() - self._hb,
                "has_work": self.has_work}

    def prefix_stats(self):
        return None

    def spec_stats(self):
        return None


def _wd_pool(clock, n=2, **kw):
    fakes = [HBFakeEngine(i, clock) for i in range(n)]
    pool = EnginePool(lambda i: fakes[i], n)
    # keep fake-clock tests hermetic: no flight bundles under /tmp
    # unless a test opts in with an explicit dir
    kw.setdefault("flight_dir", False)
    wd = PoolWatchdog(pool, time_fn=clock, **kw)
    return fakes, pool, wd


# --------------------------------------------- ladder (fake clock)


def test_ladder_suspect_then_wedge_drives_death_path():
    clock = FakeClock()
    fakes, pool, wd = _wd_pool(clock, stall_deadline_s=10.0)
    assert wd.suspect_after_s == 5.0       # default: half the deadline
    fakes[0].has_work = True
    clock.advance(3.0)
    wd.tick()                              # age 3 < 5: nothing
    assert pool.replica(0).state == HEALTHY
    clock.advance(3.0)
    wd.tick()                              # age 6 >= 5: quarantine
    assert pool.replica(0).state == SUSPECT
    assert pool.replica(1).state == HEALTHY
    assert wd.counts["suspected"] == 1
    clock.advance(5.0)
    wd.tick()                              # age 11 >= 10: wedged
    assert wd.counts["wedged"] == 1
    assert pool.replica(0).state == DEAD
    assert fakes[0].force_kills == 1
    assert isinstance(fakes[0].force_kill_err, ReplicaWedged)
    assert pool.route_stats["wedged"] == 1
    assert pool.route_stats["replica_deaths"] == 1
    # flight recording was disabled: the escalation still carries
    # the (absent) bundle path rather than failing
    assert fakes[0].force_kill_err.bundle_path is None
    # the healthy replica was never probed into a restart
    assert fakes[1].force_kills == 0
    assert pool.replica(1).state == HEALTHY
    assert pool.replica(1).generation == 0
    pool.shutdown()


def test_wedge_dumps_flight_bundle_before_kill(tmp_path):
    """Escalation with recording on: the watchdog dumps a postmortem
    bundle BEFORE force-killing, stamps its path on the ReplicaWedged
    error and the log entry, and the bundle tolerates a fake engine
    (best-effort probes)."""
    from ray_tpu.serve import obs
    clock = FakeClock()
    fakes, pool, wd = _wd_pool(clock, stall_deadline_s=10.0,
                               flight_dir=str(tmp_path))
    fakes[0].has_work = True
    clock.advance(6.0)
    wd.tick()
    clock.advance(5.0)
    wd.tick()
    err = fakes[0].force_kill_err
    assert isinstance(err, ReplicaWedged)
    assert err.bundle_path is not None and \
        os.path.isdir(err.bundle_path)
    (wedge,) = [e for e in wd.log if e["event"] == "wedged"]
    assert wedge["bundle"] == err.bundle_path
    b = obs.load_flight_bundle(err.bundle_path)
    assert b["reason"] == "wedged-r0"
    assert b["extra"]["replica"] == 0
    assert b["extra"]["stall_deadline_s"] == 10.0
    # HBFakeEngine has no event log; load_report still lands and the
    # recorded heartbeat gap explains the escalation
    assert b["engine"]["heartbeat_gap_s"] >= 10.0 * 0.9
    # the dump precedes the kill: the pool snapshot still shows the
    # replica alive — the bundle is the last look at the wedged state
    assert b["pool"]["pool_stats"].get("replica_deaths", 0) == 0
    assert pool.route_stats["replica_deaths"] == 1
    pool.shutdown()


def test_suspect_recovers_on_heartbeat_progress():
    clock = FakeClock()
    fakes, pool, wd = _wd_pool(clock, stall_deadline_s=10.0)
    fakes[0].has_work = True
    clock.advance(6.0)
    wd.tick()
    assert pool.replica(0).state == SUSPECT
    # the heartbeat moves (a long-but-moving prefill): age shrinks
    # below what the watchdog recorded at suspicion
    fakes[0].touch()
    clock.advance(1.0)
    wd.tick()
    assert pool.replica(0).state == HEALTHY
    assert wd.counts["recovered"] == 1
    assert fakes[0].force_kills == 0
    # ... and a FRESH stall re-enters the ladder from the top
    clock.advance(6.0)
    wd.tick()
    assert pool.replica(0).state == SUSPECT
    pool.shutdown()


def test_progressing_readback_drain_never_escalates():
    """The overlapped hot loop's blocking readback drain touches the
    heartbeat BEFORE each device_get as well as after
    (engine._drain_fetches_locked), so a slow-but-PROGRESSING
    multi-buffer readback presents as a stream of sub-threshold
    heartbeat ages — it must ride the ladder nowhere, for as long as
    it keeps moving. The moment the touches stop (a genuine hang
    inside one get) the normal ladder takes over."""
    clock = FakeClock()
    fakes, pool, wd = _wd_pool(clock, stall_deadline_s=10.0)
    fakes[0].has_work = True
    # each buffer of the drain costs 4s of wall — slow, but every
    # iteration boundary refreshes the heartbeat the way the
    # pre-get touch does
    for _ in range(8):                     # 32s >> stall deadline
        clock.advance(4.0)                 # 4 < suspect_after (5)
        fakes[0].touch()
        wd.tick()
        assert pool.replica(0).state == HEALTHY
    assert wd.counts["suspected"] == 0
    assert fakes[0].force_kills == 0
    # the readback genuinely hangs: touches stop, ladder engages
    clock.advance(6.0)
    wd.tick()
    assert pool.replica(0).state == SUSPECT
    clock.advance(5.0)
    wd.tick()
    assert pool.replica(0).state == DEAD
    assert fakes[0].force_kills == 1
    pool.shutdown()


def test_suspect_recovers_when_work_drains():
    clock = FakeClock()
    fakes, pool, wd = _wd_pool(clock, stall_deadline_s=10.0)
    fakes[0].has_work = True
    clock.advance(6.0)
    wd.tick()
    assert pool.replica(0).state == SUSPECT
    fakes[0].has_work = False              # drained; hb still stale
    clock.advance(1.0)
    wd.tick()
    assert pool.replica(0).state == HEALTHY
    assert wd.counts["recovered"] == 1
    pool.shutdown()


def test_idle_stale_heartbeat_is_never_suspected():
    # an idle engine parks on its condition variable with a stale
    # heartbeat and NO work: silence without work is not a wedge
    clock = FakeClock()
    fakes, pool, wd = _wd_pool(clock, stall_deadline_s=10.0)
    for _ in range(5):
        clock.advance(100.0)
        wd.tick()
    assert pool.replica(0).state == HEALTHY
    assert pool.replica(1).state == HEALTHY
    assert wd.counts["suspected"] == 0
    pool.shutdown()


def test_suspect_excluded_from_routing_and_capacity():
    clock = FakeClock()
    fakes, pool, wd = _wd_pool(clock, stall_deadline_s=10.0)
    fakes[0].has_work = True
    clock.advance(6.0)
    wd.tick()
    assert pool.replica(0).state == SUSPECT
    # a maybe-dead replica must not count as capacity anywhere
    assert pool.healthy_count() == 1
    assert pool.load_report()["healthy_replicas"] == 1
    assert pool.pool_stats()["suspect_replicas"] == 1
    for _ in range(4):
        h = pool.submit([1, 2, 3])
        assert h.replica_idx == 1
    assert fakes[0].submits == []
    pool.shutdown()


def test_engines_without_heartbeat_surface_are_skipped():
    # a report lacking heartbeat_age_s/has_work (older engine, plain
    # FakeEngine) must never be judged — compat, not a wedge
    clock = FakeClock()
    fakes, pool, wd = _wd_pool(clock, stall_deadline_s=10.0)
    orig = fakes[0].load_report

    def bare_report():
        rpt = orig()
        rpt.pop("heartbeat_age_s")
        rpt.pop("has_work")
        return rpt

    fakes[0].load_report = bare_report
    fakes[0].has_work = True
    clock.advance(100.0)
    wd.tick()
    assert pool.replica(0).state == HEALTHY
    assert wd.counts["suspected"] == 0
    pool.shutdown()


def test_watchdog_stats_block_in_pool_stats():
    clock = FakeClock()
    fakes, pool, wd = _wd_pool(clock, stall_deadline_s=8.0,
                               suspect_after_s=2.0,
                               poll_interval_s=0.5)
    wd.tick()
    blk = pool.pool_stats()["watchdog"]
    assert blk["ticks"] == 1
    assert blk["stall_deadline_s"] == 8.0
    assert blk["suspect_after_s"] == 2.0
    assert blk["poll_interval_s"] == 0.5
    assert blk["active_suspects"] == 0
    pool.shutdown()


def test_watchdog_validates_knobs():
    clock = FakeClock()
    fakes = [HBFakeEngine(0, clock)]
    pool = EnginePool(lambda i: fakes[i], 1)
    with pytest.raises(ValueError):
        PoolWatchdog(pool, stall_deadline_s=0.0)
    with pytest.raises(ValueError):
        PoolWatchdog(pool, stall_deadline_s=1.0, suspect_after_s=2.0)
    pool.shutdown()


# ------------------------------------------------------ real engines


def _warm_engine_factory(model, params, inj_for):
    """Factory building warmed real engines: the first dispatch
    compiles for seconds while holding the scheduler lock (frozen
    heartbeat) — warming BEFORE the engine joins the pool keeps the
    watchdog's stall judgment about wedges, not XLA."""

    def factory(idx):
        eng = LLMEngine(model, params, max_slots=2, page_size=8,
                        n_pages=64, chunk=4, temperature=0.0,
                        seed=idx, prefix_cache=True,
                        admit_timeout_s=0.5,
                        fault_injector=inj_for(idx))
        eng.start()
        try:
            eng.submit([3, 1, 4, 1], max_new_tokens=4).result()
            eng.submit([3, 1, 4, 1, 5, 9], max_new_tokens=4).result()
        except EngineShutdown:
            pass
        eng.reset_latency_stats()
        return eng

    return factory


def test_injected_hang_escalates_to_death_within_deadline(
        tiny_model, tmp_path):
    """The tentpole end-to-end: a `hang` fault plan parks replica 0's
    scheduler thread mid-step (lock held, heartbeat frozen, work
    pending). The watchdog must declare it wedged within the stall
    deadline, force-kill it out-of-band, leave the healthy replica
    untouched, and the pool must land every in-flight request either
    token-identically on the survivor or typed. The escalation must
    leave a flight bundle — dumped lock-free while the wedged thread
    still HOLDS the engine lock — that explains the hang."""
    model, params = tiny_model
    stall = 1.0
    inj = FaultInjector()
    factory = _warm_engine_factory(
        model, params, lambda idx: inj if idx == 0 else None)
    pool = EnginePool(factory, 2)
    watchdog = PoolWatchdog(pool, stall_deadline_s=stall,
                            poll_interval_s=0.05,
                            flight_dir=str(tmp_path)).run()
    try:
        prompts = [[3, 1, 4, 1, 10 + i, 20 + i] for i in range(6)]
        want = [_reference_completion(model, params, p, 12)
                for p in prompts]
        # arm the wedge, then load the pool: whichever requests land
        # on replica 0 freeze with it
        inj.hang("step")
        t0 = time.monotonic()
        results = [None] * len(prompts)

        def consume(i, h):
            try:
                results[i] = ("ok", h.result())
            except EngineShutdown:
                results[i] = ("typed", None)

        handles = [pool.submit(p, max_new_tokens=12)
                   for p in prompts]
        threads = [threading.Thread(target=consume, args=(i, h))
                   for i, h in enumerate(handles)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + stall + 10.0
        while (watchdog.counts["wedged"] < 1
               and time.monotonic() < deadline):
            time.sleep(0.01)
        detect_s = time.monotonic() - t0
        assert watchdog.counts["wedged"] == 1, \
            f"wedge undetected after {detect_s:.1f}s"
        # detection within the deadline (+ scheduling slack: one
        # poll interval and the probe ladder)
        assert detect_s < stall + 3.0
        wedge_events = [e for e in watchdog.log
                        if e["event"] == "wedged"]
        assert wedge_events and \
            wedge_events[0]["heartbeat_age_s"] >= stall * 0.9
        # the postmortem bundle was written BEFORE the force-kill,
        # with the wedged scheduler still holding the engine lock,
        # and its heartbeat gap explains the escalation
        from ray_tpu.serve import obs
        bundle_path = wedge_events[0]["bundle"]
        assert bundle_path is not None and os.path.isdir(bundle_path)
        bundle = obs.load_flight_bundle(bundle_path)
        assert bundle["reason"].startswith("wedged-r0")
        assert bundle["engine"]["heartbeat_gap_s"] >= stall * 0.9
        # the event tail survived the death: the typed log shows the
        # engine was mid-flight (admits/prefills), then went silent
        assert bundle["engine"]["events"], "bundle lost the event tail"
        for t in threads:
            t.join(timeout=60)
        assert all(not t.is_alive() for t in threads), "request hung"
        assert all(r is not None for r in results), "request lost"
        ok = [i for i, r in enumerate(results) if r[0] == "ok"]
        for i in ok:
            assert results[i][1] == want[i], i
        assert ok, "no request completed on the survivor"
        # hang -> death: the wedged replica took the existing death
        # path; the healthy one was never killed or restarted. It MAY
        # be transiently SUSPECT (a survivor recompiling under the
        # resubmit burst is a false alarm the ladder recovers from) —
        # with its work drained the next tick must clear it.
        assert pool.replica(0).state == DEAD
        deadline = time.monotonic() + 5.0
        while (pool.replica(1).state != HEALTHY
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert pool.replica(1).state == HEALTHY
        assert pool.replica(1).generation == 0
        assert watchdog.counts["wedged"] == 1
        assert pool.route_stats["wedged"] == 1
    finally:
        watchdog.stop()
        inj.release_all()
        pool.shutdown()
    check_pool_quiesced(pool)


def test_released_zombie_is_fenced(tiny_model):
    """Generation fencing: a force-killed engine whose wedged thread
    later wakes (hang plan released) must not commit tokens or touch
    the prefix cache — it drains and exits, and a second shutdown()
    completes the deferred cleanup leak-free."""
    model, params = tiny_model
    inj = FaultInjector()
    eng = _warm_engine_factory(
        model, params, lambda idx: inj)(0)
    try:
        cached_before = eng.prefix_cache.cached_pages
        inj.hang("step")
        h = eng.submit([7, 1, 8, 2], max_new_tokens=32)
        # wait for the scheduler thread to park inside step() with
        # the lock held: heartbeat freezes while work is pending
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            rpt = eng.load_report()
            if rpt["has_work"] and rpt["heartbeat_age_s"] > 0.3:
                break
            time.sleep(0.01)
        else:
            pytest.fail("hang plan never engaged")
        eng.force_kill(ReplicaWedged("test wedge"))
        # consumers unblock typed immediately — no waiting on the
        # parked thread
        with pytest.raises(EngineShutdown):
            h.result()
        assert eng.stats["force_killed"] == 1
        # release the zombie: it wakes inside step(), finds the
        # fence, and must not commit anything
        inj.release_all()
        t = eng._thread
        if t is not None:
            t.join(timeout=10.0)
            assert not t.is_alive(), "released zombie never exited"
        # the prefix cache was never touched by the zombie: the
        # fenced slot frees its pages instead of retiring them
        assert eng.prefix_cache.cached_pages == cached_before
    finally:
        inj.release_all()
        eng.shutdown()     # second shutdown: deferred cleanup runs
    check_quiesced(eng, expect_cached_pages=eng.prefix_cache
                   .cached_pages)


# ----------------------------------------------- chaos campaign smoke


def test_chaos_campaign_smoke_and_schema(tmp_path):
    """The seeded campaign (tools/chaos_serve.py) end-to-end: all six
    fault kinds fire against a live 3-replica pool under client load,
    the run's own hard asserts pass (zero lost, wedge within
    deadline, quiesced, attainment above floor), and the artifact
    validates under its schema family."""
    from tools import chaos_serve
    from tools import check_bench_schema as cbs
    art = chaos_serve.run_chaos(seed=47, replicas=3, duration_s=3.0,
                                clients=3, stall_deadline_s=1.0)
    assert art["requests"]["lost"] == 0
    assert art["requests"]["mismatched"] == 0
    assert art["wedge"]["detected"] is True
    assert art["wedge"]["within_deadline"] is True
    assert all(art["injected"][k] >= 1
               for k in ("kill", "hang", "stockout"))
    assert art["attainment"] >= art["attainment_floor"]
    p = tmp_path / "SERVE_CHAOS_test.json"
    p.write_text(json.dumps(art))
    problems = []
    cbs.check_file(str(p), problems)
    assert problems == [], problems
