"""Wire-protocol handshake: version negotiation + cluster-token auth
(the schema'd/authenticated-protocol role of src/ray/protobuf/ + the
Redis-password gate). An arbitrary connecting process must not be able
to drive the handler (pickle RCE) or even get its payload unpickled.
"""
import pickle
import socket
import struct
import threading
import time

import pytest

from ray_tpu._private.config import GlobalConfig
from ray_tpu.runtime import rpc
from ray_tpu.runtime.rpc import (MAGIC, PROTO_VERSION, RpcClient,
                                 RpcError, RpcServer, _HELLO, _LEN)


class _Recorder:
    def __init__(self):
        self.calls = []

    def touch(self, x=None):
        self.calls.append(x)
        return "touched"


@pytest.fixture
def server():
    GlobalConfig.apply_system_config(
        {"cluster_token": "secret-token-123"})
    handler = _Recorder()
    srv = RpcServer(handler)
    yield srv, handler
    srv.stop()
    GlobalConfig.apply_system_config({"cluster_token": ""})


def test_authed_call_works(server):
    srv, handler = server
    c = RpcClient(srv.address, timeout=5)
    assert c.call("touch", 42) == "touched"
    assert handler.calls == [42]
    c.close()


def test_no_hello_never_reaches_handler(server):
    srv, handler = server
    sock = socket.create_connection((srv.host, srv.port), timeout=5)
    # A raw attacker frame: length-prefixed pickle calling touch().
    evil = pickle.dumps({"rid": 1, "method": "touch",
                         "args": ("pwned",), "kwargs": {}})
    sock.sendall(_LEN.pack(len(evil)) + evil)
    # Server reads those bytes AS a HELLO, sees bad magic, closes.
    sock.settimeout(5)
    try:
        while True:
            if not sock.recv(4096):
                break
    except ConnectionResetError:
        pass                            # server dropped us: also fine
    except socket.timeout:
        pytest.fail("server kept the unauthenticated connection open")
    time.sleep(0.1)
    assert handler.calls == []          # payload never executed
    sock.close()


def test_wrong_token_rejected(server):
    srv, handler = server
    sock = socket.create_connection((srv.host, srv.port), timeout=5)
    tok = b"WRONG-token"
    sock.sendall(_HELLO.pack(MAGIC, PROTO_VERSION, len(tok)) + tok)
    req = pickle.dumps({"rid": 1, "method": "touch", "args": ("x",),
                       "kwargs": {}})
    sock.sendall(_LEN.pack(len(req)) + req)
    sock.settimeout(5)
    (n,) = _LEN.unpack(_recv_exact(sock, 4))
    reply = pickle.loads(_recv_exact(sock, n))
    assert "err" in reply and "authentication failed" in \
        str(reply["err"])
    time.sleep(0.1)
    assert handler.calls == []
    sock.close()


def test_version_mismatch_rejected(server):
    srv, handler = server
    sock = socket.create_connection((srv.host, srv.port), timeout=5)
    tok = b"secret-token-123"
    sock.sendall(_HELLO.pack(MAGIC, PROTO_VERSION + 7, len(tok)) + tok)
    req = pickle.dumps({"rid": 1, "method": "touch", "args": (),
                       "kwargs": {}})
    sock.sendall(_LEN.pack(len(req)) + req)
    sock.settimeout(5)
    (n,) = _LEN.unpack(_recv_exact(sock, 4))
    reply = pickle.loads(_recv_exact(sock, n))
    assert "err" in reply and "version mismatch" in str(reply["err"])
    assert handler.calls == []
    sock.close()


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("closed")
        buf += chunk
    return buf


def test_empty_token_mode_still_requires_magic(server):
    """Even with auth disabled (empty token), garbage bytes never get
    unpickled."""
    srv, handler = server
    GlobalConfig.apply_system_config({"cluster_token": ""})
    try:
        sock = socket.create_connection((srv.host, srv.port),
                                        timeout=5)
        sock.sendall(b"GET / HTTP/1.1\r\nHost: x\r\n\r\n")
        sock.settimeout(5)
        try:
            while sock.recv(4096):
                pass
        except ConnectionResetError:
            pass
        except socket.timeout:
            pytest.fail("server kept a non-protocol connection open")
        assert handler.calls == []
    finally:
        GlobalConfig.apply_system_config(
            {"cluster_token": "secret-token-123"})
