"""Attention ops with pluggable implementations.

impl:
- "xla":   einsum attention; XLA fuses mask+softmax well on TPU.
- "flash": pallas blockwise flash-attention kernel (TPU only, falls back
           to xla off-TPU) — ray_tpu.ops.flash_attention.
- "ring":  sequence-parallel ring attention over the mesh `sequence` axis —
           ray_tpu.parallel.sequence (callers use it via shard_map).
- "auto":  flash on TPU when shapes allow, else xla.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def xla_attention(q, k, v, causal: bool = True,
                  bias: Optional[jax.Array] = None,
                  precision: str = "default") -> jax.Array:
    """Reference attention, [B, T, H, D] layout.

    precision="default": scores materialize in the input dtype (bf16 on
    TPU) and only the softmax runs in fp32 — halves the dominant HBM
    traffic of the [B,H,T,T] scores tensor (measured +3.8% MFU on GPT-2
    124M / v5e vs fp32 scores). "highest": fp32 scores throughout.
    """
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = 1.0 / (D ** 0.5)
    if precision == "highest":
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                            preferred_element_type=jnp.float32)
        scores = scores * scale
    else:
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k)
        scores = scores * jnp.asarray(scale, scores.dtype)
    if bias is not None:
        scores = scores + bias.astype(scores.dtype)
    if causal:
        mask = jnp.tril(jnp.ones((Tq, Tk), dtype=bool), k=Tk - Tq)
        scores = jnp.where(mask[None, None], scores,
                           jnp.asarray(-1e30, scores.dtype))
    probs = jax.nn.softmax(scores.astype(jnp.float32),
                           axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def multi_head_attention(q, k, v, causal: bool = True,
                         impl: str = "auto",
                         bias: Optional[jax.Array] = None) -> jax.Array:
    was_auto = impl == "auto"
    if impl == "auto":
        # Measured on v5e (fwd+bwd, H=12 D=64): at T=1024 the pallas
        # kernel wins for B>=8 (B=24: 43.2% vs 34.3% MFU — XLA's
        # [B,H,T,T] scores are pure HBM traffic in the backward); tiny
        # batches favor XLA. At T>=2048 flash always wins and at
        # T>=8192 it is the only option (scores exhaust HBM).
        T, B = q.shape[1], q.shape[0]
        impl = "flash" if (_on_tpu() and bias is None and
                           T % 128 == 0 and
                           (T >= 2048 or (T >= 1024 and B >= 8))) \
            else "xla"
    if impl == "flash":
        from ray_tpu.ops.flash_attention import flash_attention
        if was_auto:
            # auto picked flash opportunistically: a pallas/libtpu
            # hiccup falls back to XLA rather than failing the model.
            try:
                return flash_attention(q, k, v, causal=causal)
            except Exception:
                return xla_attention(q, k, v, causal=causal,
                                     bias=bias)
        # Explicitly requested flash must not silently become XLA
        # (benchmarks and kernel tests would record the wrong path).
        return flash_attention(q, k, v, causal=causal)
    if impl == "ring":
        raise ValueError(
            "impl='ring' must be invoked through "
            "ray_tpu.parallel.sequence.ring_attention inside shard_map")
    return xla_attention(q, k, v, causal=causal, bias=bias)


def padding_bias(attention_mask):
    """[B, T] 1/0 mask -> additive [B, 1, 1, T] fp32 bias (0 keep,
    -1e30 drop) broadcast over heads and query positions. The shared
    mask convention for encoder models (bert, t5)."""
    return jnp.where(attention_mask[:, None, None, :] > 0, 0.0, -1e30)
