"""Pallas paged-attention decode kernel (the vLLM kernel, TPU-style).

The continuous-batching engine's decode step attends each slot's
single query against its KV pages. The XLA fallback (models/llama.py
paged branch) GATHERS the whole page window into a dense
[B, L, KH, D] tensor every step — at L=2048 that is the dominant HBM
traffic of the decode loop. This kernel never materializes the
window: the page table rides scalar prefetch
(pltpu.PrefetchScalarGridSpec) and each grid step DMAs exactly one
physical page per (slot, kv-head), accumulating flash-style online
softmax in VMEM. Per-step traffic drops from O(B * L) gathered copies
to O(B * L) page READS only — no gathered intermediate, no scatter of
it back.

Layout contract (matches models/kv_cache.py):
  pages_k/pages_v: [n_kv_heads, n_pages, page_size, head_dim] —
                   HEAD-MAJOR so each grid step's block is one
                   contiguous [page_size, head_dim] tile, which
                   Mosaic can tile (page-major would put a size-1
                   slice of n_kv_heads in the sublane dim)
  page_table:      [n_slots, max_pages] int32 (0 = null page)
  positions:       [n_slots]            int32 — current decode
                   position; the step attends keys 0..pos inclusive
  q:               [n_slots, n_heads, head_dim] (grouped-query: head
                   h uses kv head h // (n_heads // n_kv_heads))

Grid (B, n_pages_per_slot): the page dimension is innermost, so TPU
executes it sequentially per slot and the online-softmax scratch
carries across pages. Each grid step processes ONE physical page for
ALL kv heads at once — the block ``[KH, 1, Pg, D]`` is a strided but
Mosaic-expressible slice of the head-major pool, so one step moves
KH*(Pg*D) bytes per tensor (64KB at 1.1B shapes) instead of a 4KB
single-head page, and the [KH, rep, Pg] score tile fills the VPU
sublanes. (A first cut used grid (B, KH, pages) with one head-page
per step: 4096 serialized 4KB DMAs measured 31ms/step at 1.1B-16-slot
shapes vs 8.2ms for XLA's dense gather — DMA-issue latency-bound.)
Inactive slots point at the null page and mask everything — their
outputs are ignored host-side.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30

# Int8 pages use a symmetric absmax code: value = q * scale / 127 with
# q in [-127, 127] (-128 unused so the code is symmetric). One fp32
# scale per (kv_head, physical page) — coarse enough to cost 4 bytes
# per page per head, fine enough that one outlier page cannot poison
# the whole pool's precision.
_QMAX = 127.0


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


class PagedShapeError(ValueError):
    """Typed shape/dtype mismatch between a KV chunk and the page pool.

    Raised at trace time by ``paged_append`` — shapes are static under
    jit, so every check below fires before lowering, replacing the
    opaque XLA scatter errors (dimension-numbers mismatches deep in
    HLO) these bugs used to surface as. The message names the operand
    and both shapes so a head-count or head-dim mismatch (the classic
    tensor-parallel wiring bug: sharded pool, unsharded chunk) reads
    as what it is.
    """


def _check_append_shapes(pages_k, pages_v, page_table, pos, k, v):
    if pages_k.ndim != 4 or pages_v.ndim != 4:
        raise PagedShapeError(
            f"pages_k/pages_v must be rank-4 [KH, n_pages, Pg, D]; "
            f"got pages_k {pages_k.shape}, pages_v {pages_v.shape}")
    if pages_k.shape != pages_v.shape:
        raise PagedShapeError(
            f"pages_k and pages_v disagree: {pages_k.shape} vs "
            f"{pages_v.shape}")
    if k.ndim != 4 or v.ndim != 4:
        raise PagedShapeError(
            f"k/v chunks must be rank-4 [B, T, KH, D]; got k "
            f"{k.shape}, v {v.shape}")
    if k.shape != v.shape:
        raise PagedShapeError(
            f"k and v chunks disagree: {k.shape} vs {v.shape}")
    KH, _, _, D = pages_k.shape
    if k.shape[2] != KH:
        raise PagedShapeError(
            f"chunk has {k.shape[2]} kv heads but the page pool holds "
            f"{KH} (pool {pages_k.shape}, chunk {k.shape}) — under "
            f"tensor parallelism both must be the per-device count")
    if k.shape[3] != D:
        raise PagedShapeError(
            f"chunk head_dim {k.shape[3]} != pool head_dim {D} "
            f"(pool {pages_k.shape}, chunk {k.shape})")
    if page_table.ndim != 2:
        raise PagedShapeError(
            f"page_table must be rank-2 [B, max_pages]; got "
            f"{page_table.shape}")
    if page_table.shape[0] != k.shape[0]:
        raise PagedShapeError(
            f"page_table has {page_table.shape[0]} rows but the chunk "
            f"has batch {k.shape[0]}")
    if not jnp.issubdtype(page_table.dtype, jnp.integer):
        raise PagedShapeError(
            f"page_table must be integer, got {page_table.dtype}")
    if pos.shape != (k.shape[0],):
        raise PagedShapeError(
            f"pos must be [B]={k.shape[0]}; got shape {pos.shape}")


def _check_scale_shapes(pages_k, scales_k, scales_v):
    KH, n_pages = pages_k.shape[:2]
    want = (KH, n_pages, 1)
    for name, s in (("scales_k", scales_k), ("scales_v", scales_v)):
        if s.shape != want:
            raise PagedShapeError(
                f"{name} must be [KH, n_pages, 1]={want} to pair with "
                f"pool {pages_k.shape}; got {s.shape}")
    if pages_k.dtype != jnp.int8:
        raise PagedShapeError(
            f"per-page scales supplied but the pool is {pages_k.dtype}"
            f", not int8 — scales only pair with quantized pools")


def paged_append(pages_k, pages_v, page_table, pos, k, v,
                 scales_k=None, scales_v=None):
    """Scatter a [B, T] chunk of new K/V into the head-major page pool
    at each slot's current write offset (append-at-offset: the chunk
    may START mid-page and SPAN page boundaries — the partial-prompt
    case chunked prefill creates).

    pages_k/pages_v: [KH, n_pages, Pg, D] (head-major pool)
    page_table:      [B, max_pages] int32 (0 = null page)
    pos:             [B] int32 — first token of the chunk lands at
                     logical position ``pos[b]``
    k/v:             [B, T, KH, D] new keys/values

    Token t of row b goes to physical page
    ``page_table[b, (pos[b]+t) // Pg]`` at offset ``(pos[b]+t) % Pg``.
    Positions past the row's allocated pages resolve to page-table
    entries of 0 (the null page), so oversized/padding tails scatter
    harmlessly — the same null-page discipline the decode step uses
    for inactive slots. Logical positions are clamped to the
    addressable window so a padded tail can never alias another
    slot's pages through index clamping.

    Int8 pools pass ``scales_k``/``scales_v`` ([KH, n_pages, 1] fp32
    per-page absmax) and get a 4-tuple back (pages + updated scales).
    The append then does three scatters per tensor:

    1. SCALE RESET: any token landing at in-page offset 0 marks its
       page "starting over" — its old scale contribution came from a
       previous owner (the allocator reuses page ids) and is zeroed.
       This is the whole scale lifecycle: no host-side bookkeeping on
       free/realloc, because the first write a fresh logical page ever
       receives is always at offset 0.
    2. RUNNING ABSMAX: per-token absmax is scatter-MAXed into the
       (reset-adjusted) page scales — the page scale only grows while
       a page is live, so earlier tokens stay representable.
    3. REQUANTIZE + STORE: pages the chunk touches are re-coded from
       the old scale to the new one (``round(q_old * s_old/s_new)``,
       0 where the page was reset), then the chunk tokens are
       quantized at the new scale and scattered on top. Duplicate
       page entries write byte-identical values, so scatter order
       cannot matter.

    Quantized bytes are WRITE-HISTORY dependent: appending one token
    at a time re-rounds earlier tokens at each scale growth, so an
    incrementally-built page need not match a bulk-built one bit for
    bit. That is why engine-level parity with fp KV is tolerance-gated
    (docs/serving.md) while replica failover stays bit-exact (same
    write history on every replica).

    Raises :class:`PagedShapeError` at trace time on any rank / head /
    head-dim / batch mismatch between the chunk and the pool, or when
    scales are supplied for a non-int8 pool (and vice versa).
    """
    _check_append_shapes(pages_k, pages_v, page_table, pos, k, v)
    quantized = scales_k is not None or scales_v is not None
    if quantized and (scales_k is None or scales_v is None):
        raise PagedShapeError(
            "scales_k and scales_v must be supplied together")
    if not quantized and pages_k.dtype == jnp.int8:
        raise PagedShapeError(
            "int8 pool appended without its per-page scales — pass "
            "scales_k/scales_v (kv_dtype='int8' wiring bug)")
    if quantized:
        _check_scale_shapes(pages_k, scales_k, scales_v)
    B, T = k.shape[:2]
    Pg = pages_k.shape[2]
    max_pages = page_table.shape[1]
    tpos = pos[:, None] + jnp.arange(T, dtype=jnp.int32)[None]  # [B, T]
    tpos = jnp.minimum(tpos, max_pages * Pg - 1)
    pidx = jnp.take_along_axis(page_table, tpos // Pg, axis=1)  # [B, T]
    off = tpos % Pg
    flat_p = pidx.reshape(-1)
    flat_o = off.reshape(-1)
    # [B, T, KH, D] -> [KH, B*T, D] to match the head-major pool.
    kT = k.reshape(B * T, -1, k.shape[-1]).transpose(1, 0, 2)
    vT = v.reshape(B * T, -1, v.shape[-1]).transpose(1, 0, 2)
    if not quantized:
        return (pages_k.at[:, flat_p, flat_o].set(
                    kT.astype(pages_k.dtype)),
                pages_v.at[:, flat_p, flat_o].set(
                    vT.astype(pages_v.dtype)))

    n_pages = pages_k.shape[1]
    # (1) pages whose offset-0 slot this chunk writes start over.
    reset = jnp.zeros((n_pages,), jnp.bool_).at[flat_p].max(
        flat_o == 0)                                   # [n_pages]

    def _one(pages, scales, xT):
        xT32 = xT.astype(jnp.float32)                  # [KH, B*T, D]
        s_base = jnp.where(reset[None, :, None], 0.0,
                           scales.astype(jnp.float32))
        # (2) running absmax, monotone while the page is live.
        amax = jnp.max(jnp.abs(xT32), axis=2)          # [KH, B*T]
        s_new = s_base.at[:, flat_p, 0].max(amax)      # [KH, n_pages, 1]
        # (3a) re-code touched pages old-scale -> new-scale. Gathering
        # per token (not per unique page) keeps this jit-static;
        # duplicates recompute identical bytes.
        old_q = pages[:, flat_p].astype(jnp.float32)   # [KH, BT, Pg, D]
        sb = s_base[:, flat_p]                         # [KH, BT, 1]
        sn = s_new[:, flat_p]
        ratio = jnp.where(sn > 0.0, sb / jnp.maximum(sn, 1e-30), 0.0)
        req = jnp.clip(jnp.round(old_q * ratio[..., None]),
                       -_QMAX, _QMAX).astype(jnp.int8)
        pages = pages.at[:, flat_p].set(req)
        # (3b) quantize the chunk tokens at the new scale. A zero page
        # scale implies the token itself is all-zero (absmax was maxed
        # in above), so the guarded divide is exact, not a fudge.
        inv = jnp.where(sn > 0.0, _QMAX / jnp.maximum(sn, 1e-30), 0.0)
        q_tok = jnp.clip(jnp.round(xT32 * inv), -_QMAX, _QMAX
                         ).astype(jnp.int8)
        pages = pages.at[:, flat_p, flat_o].set(q_tok)
        return pages, s_new.astype(scales.dtype)

    new_pk, new_sk = _one(pages_k, scales_k, kT)
    new_pv, new_sv = _one(pages_v, scales_v, vT)
    return new_pk, new_pv, new_sk, new_sv


def _attend_page(b, p, pos_ref, q_ref, k, v, o_ref,
                 m_sc, l_sc, acc_sc, *, page_size: int, scale: float):
    """Shared flash-style online-softmax body: one physical page of
    already-dequantized fp32 K/V for all kv heads."""
    n_p = pl.num_programs(1)

    @pl.when(p == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    q = q_ref[0].astype(jnp.float32)             # [KH, rep, D]
    s = jax.lax.dot_general(
        q, k, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32) * scale   # [KH, rep, Pg]
    pos = pos_ref[b]
    kpos = p * page_size + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, 2)
    s = jnp.where(kpos <= pos, s, _NEG_INF)

    m_prev = m_sc[...]                            # [KH, rep, 1]
    m_cur = jnp.max(s, axis=2, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    # Fully-masked pages keep exp() finite.
    m_safe = jnp.maximum(m_new, -1e29)
    alpha = jnp.exp(m_prev - m_safe)
    pexp = jnp.exp(s - m_safe)                    # [KH, rep, Pg]
    l_sc[...] = l_sc[...] * alpha + \
        jnp.sum(pexp, axis=2, keepdims=True)
    acc_sc[...] = acc_sc[...] * alpha + jax.lax.dot_general(
        pexp, v, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)       # [KH, rep, D]
    m_sc[...] = m_new

    @pl.when(p == n_p - 1)
    def _fin():
        l = jnp.maximum(l_sc[...], 1e-30)
        o_ref[0] = (acc_sc[...] / l).astype(o_ref.dtype)


def _kernel(pt_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
            m_sc, l_sc, acc_sc, *, page_size: int, scale: float):
    b = pl.program_id(0)
    p = pl.program_id(1)
    k = k_ref[:, 0].astype(jnp.float32)          # [KH, Pg, D]
    v = v_ref[:, 0].astype(jnp.float32)          # [KH, Pg, D]
    _attend_page(b, p, pos_ref, q_ref, k, v, o_ref,
                 m_sc, l_sc, acc_sc, page_size=page_size, scale=scale)


def _kernel_q(pt_ref, pos_ref, q_ref, k_ref, v_ref, sk_ref, sv_ref,
              o_ref, m_sc, l_sc, acc_sc, *, page_size: int,
              scale: float):
    """Int8 variant: the page's fp32 absmax scale rides its own tiny
    block (chosen by the same scalar-prefetched page-table entry) and
    the dequantize happens IN REGISTER right after the page DMA — the
    fp window never exists in HBM or VMEM, so the kernel's memory
    footprint is the halved int8 one."""
    b = pl.program_id(0)
    p = pl.program_id(1)
    inv = 1.0 / _QMAX
    sk = sk_ref[:, 0].astype(jnp.float32) * inv   # [KH, 1]
    sv = sv_ref[:, 0].astype(jnp.float32) * inv
    k = k_ref[:, 0].astype(jnp.float32) * sk[:, :, None]  # [KH, Pg, D]
    v = v_ref[:, 0].astype(jnp.float32) * sv[:, :, None]
    _attend_page(b, p, pos_ref, q_ref, k, v, o_ref,
                 m_sc, l_sc, acc_sc, page_size=page_size, scale=scale)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_decode_attention(q, pages_k, pages_v, page_table, positions,
                           scales_k=None, scales_v=None,
                           interpret: bool | None = None):
    """One decode step of paged attention.

    q: [B, H, D]; returns [B, H, D] in q.dtype. See module docstring
    for the pool layout. Falls back transparently to interpreter mode
    off-TPU (tests). Int8 pools pass scales_k/scales_v
    ([KH, n_pages, 1] fp32) and get in-register dequantization.
    """
    B, H, D = q.shape
    KH, n_pages, Pg, Dk = pages_k.shape
    assert D == Dk, (D, Dk)
    rep = H // KH
    max_pages = page_table.shape[1]
    qg = q.reshape(B, KH, rep, D)
    scale = 1.0 / (D ** 0.5)
    quantized = scales_k is not None
    if quantized:
        _check_scale_shapes(pages_k, scales_k, scales_v)

    grid = (B, max_pages)
    page_spec = [
        # ONE physical page of K/V across ALL kv heads, chosen by
        # the scalar-prefetched page table: [KH, 1, Pg, D]
        pl.BlockSpec((KH, 1, Pg, D),
                     lambda b, p, pt, pos: (0, pt[b, p], 0, 0)),
        pl.BlockSpec((KH, 1, Pg, D),
                     lambda b, p, pt, pos: (0, pt[b, p], 0, 0)),
    ]
    in_specs = [
        # q block for this slot, every head: [1, KH, rep, D]
        pl.BlockSpec((1, KH, rep, D),
                     lambda b, p, pt, pos: (b, 0, 0, 0)),
    ] + page_spec
    operands = [qg, pages_k, pages_v]
    kern = _kernel
    if quantized:
        # the page's scale column follows the same page-table index
        in_specs += [
            pl.BlockSpec((KH, 1, 1),
                         lambda b, p, pt, pos: (0, pt[b, p], 0)),
            pl.BlockSpec((KH, 1, 1),
                         lambda b, p, pt, pos: (0, pt[b, p], 0)),
        ]
        operands += [scales_k, scales_v]
        kern = _kernel_q
    kernel = functools.partial(kern, page_size=Pg, scale=scale)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, KH, rep, D),
                lambda b, p, pt, pos: (b, 0, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((KH, rep, 1), jnp.float32),    # m
                pltpu.VMEM((KH, rep, 1), jnp.float32),    # l
                pltpu.VMEM((KH, rep, D), jnp.float32),    # acc
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, KH, rep, D), q.dtype),
        interpret=_interpret() if interpret is None else interpret,
    )(page_table, positions, *operands)
    return out.reshape(B, H, D)


def dequantize_pages(pages, scales):
    """Debug/test helper: materialize the fp view of an int8 pool
    (``q * s / 127``). NEVER used on the serving path — the whole
    point of the int8 mode is that this tensor never exists there."""
    return pages.astype(jnp.float32) * (
        scales.astype(jnp.float32) / _QMAX)[..., None]
