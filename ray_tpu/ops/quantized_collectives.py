"""EQuARX-style int8 quantized psum (two-sided scale exchange).

EQuARX (arXiv:2506.17615, PAPERS.md) shows that all-reduce traffic —
the tensor-parallel serving stack's only cross-device KV-adjacent
cost — tolerates aggressive in-flight quantization at negligible
quality loss. This module is the GROUNDWORK half of the ROADMAP item
"Quantized KV cache + quantized collectives": a standalone shard_map
collective that moves int8 payloads instead of fp, with the absmax
scales exchanged ALONGSIDE the payloads (two-sided: every rank both
sends its own (q, scale) pair and dequantizes every peer's with the
peer's scale — no rank ever applies its local scale to remote bytes).

NOT wired into the serving engine: the engine's two per-layer psums
(row-parallel wo/w2 reductions) stay exact until an engine-level A/B
proves the accept-rate/parity budget tolerates quantized reductions.
Wiring it in is a one-line swap at the `psum` call sites precisely
because this op is already a drop-in shard_map collective.

Numerics: symmetric absmax int8 (q = round(x * 127 / amax), value =
q * amax / 127), one fp32 scale per row of the LAST axis — the same
code the int8 KV pages use (ops/paged_attention.py), so both halves
of the ROADMAP item share one quantization contract. Error per
element is bounded by n_ranks * (amax_r / 254) summed over ranks'
scales; the unit tests assert that bound, not a loose rtol.

Byte math: a bf16 psum moves 2 bytes/element each way; this moves
1 byte/element plus 4 bytes per row of the last axis — ~2x less for
any realistic hidden dim (the scale amortizes over >= 128 lanes).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:                                   # jax >= 0.6 top-level export
    _shard_map = jax.shard_map
except AttributeError:                 # 0.4/0.5 experimental home
    from jax.experimental.shard_map import shard_map as _shard_map

_QMAX = 127.0


def quantize_rowwise(x):
    """Symmetric absmax int8 over the LAST axis: returns (q int8,
    scale fp32 with a keepdims 1 in the last axis). All-zero rows get
    scale 0 and quantize to 0 — the guarded divide is exact for them,
    not an approximation."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scale = amax / _QMAX
    inv = jnp.where(scale > 0.0, 1.0 / jnp.maximum(scale, 1e-30), 0.0)
    q = jnp.clip(jnp.round(xf * inv), -_QMAX, _QMAX).astype(jnp.int8)
    return q, scale


def dequantize_rowwise(q, scale):
    return q.astype(jnp.float32) * scale


def quantized_psum(x, axis_name: str):
    """Drop-in `jax.lax.psum(x, axis_name)` with int8 wire format.
    Call INSIDE shard_map. Returns the (approximate) full sum in
    x.dtype on every rank.

    Each rank quantizes its local partial, all-gathers the int8
    payloads AND their scales (the two-sided exchange), then
    dequantizes each peer contribution with that peer's own scale
    before summing in fp32. Accumulation is fp32 regardless of
    x.dtype so the only loss is the per-rank rounding, never the
    reduction order.
    """
    q, scale = quantize_rowwise(x)
    qg = jax.lax.all_gather(q, axis_name)          # [n, ...] int8
    sg = jax.lax.all_gather(scale, axis_name)      # [n, ..., 1] fp32
    out = jnp.sum(dequantize_rowwise(qg, sg), axis=0)
    return out.astype(x.dtype)


def quantized_psum_error_bound(x_shards):
    """Worst-case |quantized_psum - psum| per element: each rank's
    rounding error is <= scale_r / 2 = amax_r / 254. Host-side helper
    for tests and for sizing the engine-integration tolerance budget;
    x_shards is the per-rank stacked array [n, ...]."""
    import numpy as np
    amax = np.max(np.abs(np.asarray(x_shards, np.float32)), axis=-1,
                  keepdims=True)
    return np.sum(amax / (2.0 * _QMAX), axis=0)


def quantized_psum_sharded(x, mesh: Mesh, axis: str = "tensor"):
    """Outside-jit convenience wrapper for tests/benchmarks: shard x
    over ``axis`` along its FIRST dimension and quantized-psum the
    shards back to a replicated sum."""
    n = mesh.shape[axis]
    if x.shape[0] % n:
        raise ValueError(
            f"leading dim {x.shape[0]} does not shard over "
            f"{axis}={n}")
    spec = P(axis, *([None] * (x.ndim - 1)))
    x = jax.device_put(x, NamedSharding(mesh, spec))

    # check_rep=False: the output IS replicated (every rank computes
    # the identical gathered sum) but the static rep-checker cannot
    # infer that through all_gather-then-sum
    @jax.jit
    @functools.partial(
        _shard_map, mesh=mesh, in_specs=spec, out_specs=P(),
        check_rep=False)
    def run(xs):
        # sum over the local shard first so each rank contributes ONE
        # quantized partial (the EQuARX shape), then exchange
        local = jnp.sum(xs, axis=0)
        return quantized_psum(local, axis)

    return run(x)
