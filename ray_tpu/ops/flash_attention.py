"""Flash attention: custom pallas TPU kernels (forward + backward).

The framework's own blockwise-attention kernel (SURVEY.md §7 hard part 5 —
"the only place we write kernels"), used for long sequences where XLA
attention materializes the [B,H,T,T] score tensor in HBM. Design notes:

- Online softmax: running (m, l, acc) in VMEM scratch, revisited across the
  kv grid dimension (innermost, "arbitrary" semantics); scores never touch
  HBM. fp32 accumulation, bf16 MXU matmuls everywhere
  (preferred_element_type=f32 — fp32 MXU operands run at a fraction of
  bf16 rate).
- Causal blocks kj > qi are predicated off with @pl.when (the grid still
  visits them; the MXU work is skipped).
- Backward is two kernels: dq (grid over q blocks, accumulate over kv) and
  dk/dv (grid over kv blocks, accumulate over q), using the saved
  logsumexp; delta = rowsum(do * o) is computed in-kernel from o — no
  separate delta pass, no broadcast materialization in HBM (measured: the
  precomputed-delta version spent ~22 ms/step of the GPT-2-124M b24 body
  in multiply_reduce + broadcast_in_dim + copies).
- Layout: kernels read q/k/v straight from the model's natural
  [B, T, H*D] activation layout, packing 128/D heads per grid program
  (TPU lane width 128 — for GPT-2's D=64 each program handles 2 heads,
  for Llama's D=128 exactly 1). No [B,T,H,D] <-> [B*H,T,D] transpose
  copies on either side of the op (measured ~16 ms/step of copies on the
  b24 GPT-2 body with the folded layout). Shapes that don't tile the
  lane blocks (odd H, D not a power of two) are zero-padded to the
  nearest packable (H', D') in flash_attention — see its docstring for
  why that is sound.
"""
from __future__ import annotations

import functools
import os
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
_LANES = 128


def _pick_block(t: int, target: int = 0) -> int:
    """Measured on v5e (GPT-2-124M fwd+bwd, B=24 T=1024): target 1024
    gives the best step time — bigger blocks amortize grid overhead and
    keep the MXU busy; the 1024x1024 fp32 score block (4 MiB) still
    fits VMEM comfortably. Override with RAY_TPU_FLASH_BLOCK for
    sweeps."""
    if not target:
        target = int(os.environ.get("RAY_TPU_FLASH_BLOCK", "1024"))
    blk = min(t, target)
    while t % blk:
        blk //= 2
    return max(blk, min(t, _LANES))


def _interpret() -> bool:
    """Pallas TPU kernels run natively on TPU; everywhere else (the CPU
    test mesh) they run in interpreter mode."""
    return jax.default_backend() != "tpu"


def _causal_mask(s, qi, kj, blk_q, blk_k):
    qpos = qi * blk_q + jax.lax.broadcasted_iota(
        jnp.int32, (blk_q, blk_k), 0)
    kpos = kj * blk_k + jax.lax.broadcasted_iota(
        jnp.int32, (blk_q, blk_k), 1)
    return jnp.where(kpos <= qpos, s, _NEG_INF)


def _pack_factor(H: int, D: int):
    """How many heads each grid program covers in the packed layout,
    or 0 if the packed layout doesn't apply."""
    C = H * D
    if C <= _LANES:
        return H                      # whole C fits one lane block
    if D <= _LANES and _LANES % D == 0 and H % (_LANES // D) == 0:
        return _LANES // D
    if D % _LANES == 0:
        return 1                      # wide heads: one per program,
    return 0                          # lane block = D (128-divisible)


# --------------------------------------------------------------------------
# Forward (packed layout: q/k/v/o are [B, T, C], one program handles
# `npack` heads living in one lane block)
# --------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale: float, causal: bool,
                blk_q: int, blk_k: int, num_kv: int, npack: int, d: int):
    qi = pl.program_id(2)
    kj = pl.program_id(3)

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _compute():
        q = q_ref[0]                   # [blk_q, npack*d]
        k = k_ref[0]                   # [blk_k, npack*d]
        v = v_ref[0]
        for p in range(npack):
            sl = slice(p * d, (p + 1) * d)
            s = jax.lax.dot_general(
                q[:, sl], k[:, sl], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            if causal:
                s = _causal_mask(s, qi, kj, blk_q, blk_k)
            m_prev = m_scr[p, :, :1]   # [blk_q, 1]
            m_blk = jnp.max(s, axis=-1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_blk)
            alpha = jnp.exp(m_prev - m_new)
            pp = jnp.exp(s - m_new)    # [blk_q, blk_k] f32
            l_new = l_scr[p, :, :1] * alpha + \
                jnp.sum(pp, -1, keepdims=True)
            pv = jax.lax.dot_general(
                pp.astype(v.dtype), v[:, sl], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            acc_scr[p] = acc_scr[p] * alpha + pv
            m_scr[p] = jnp.broadcast_to(m_new, m_scr.shape[1:])
            l_scr[p] = jnp.broadcast_to(l_new, l_scr.shape[1:])

    if causal:
        pl.when(kj <= qi * (blk_q // blk_k) + (blk_q // blk_k) - 1)(
            _compute)
    else:
        _compute()

    last_kj = (qi * (blk_q // blk_k) + (blk_q // blk_k) - 1) \
        if causal else num_kv - 1

    @pl.when(kj == last_kj)
    def _finalize():
        outs, lses = [], []
        for p in range(npack):
            l = jnp.maximum(l_scr[p, :, :1], 1e-30)
            outs.append((acc_scr[p] / l).astype(o_ref.dtype))
            lses.append(m_scr[p, :, :1] + jnp.log(l))
        o_ref[0] = jnp.concatenate(outs, axis=1)
        # Head p's lse lives in lane p of the 128-lane block
        # (npack <= 128 always; readers index [:, p:p+1]).
        lse = jnp.concatenate(lses, axis=1)       # [blk_q, npack]
        lse_ref[0, 0] = jnp.pad(
            lse, ((0, 0), (0, _LANES - npack)))


def _flash_fwd(q, k, v, causal: bool, H: int, D: int,
               scale: float) -> Tuple[jax.Array, jax.Array]:
    """q/k/v: [B, T, C] with C = H*D in packed-lane layout."""
    B, T, C = q.shape
    Tk = k.shape[1]
    npack = _pack_factor(H, D)
    lane_blk = npack * D
    G = H // npack
    blk_q = _pick_block(T)
    blk_k = _pick_block(Tk)
    if causal and blk_q % blk_k:
        blk_k = blk_q = min(blk_q, blk_k)
    num_kv = Tk // blk_k

    grid = (B, G, T // blk_q, num_kv)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, blk_q=blk_q,
        blk_k=blk_k, num_kv=num_kv, npack=npack, d=D)
    qo_spec = pl.BlockSpec((1, blk_q, lane_blk),
                           lambda b, g, i, j: (b, i, g))
    kv_spec = pl.BlockSpec((1, blk_k, lane_blk),
                           lambda b, g, i, j: (b, j, g))
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[qo_spec, kv_spec, kv_spec],
        out_specs=[
            qo_spec,
            pl.BlockSpec((1, 1, blk_q, _LANES),
                         lambda b, g, i, j: (b, g, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, T, C), q.dtype),
            jax.ShapeDtypeStruct((B, G, T, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((npack, blk_q, _LANES), jnp.float32),   # m
            pltpu.VMEM((npack, blk_q, _LANES), jnp.float32),   # l
            pltpu.VMEM((npack, blk_q, D), jnp.float32),        # acc
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=_interpret(),
    )(q, k, v)
    return o, lse


# --------------------------------------------------------------------------
# Backward
# --------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                   dq_ref, acc_scr, *, scale: float, causal: bool,
                   blk_q: int, blk_k: int, num_kv: int, npack: int,
                   d: int):
    qi = pl.program_id(2)
    kj = pl.program_id(3)

    @pl.when(kj == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]                  # bf16: MXU operand
        o = o_ref[0]
        for p in range(npack):
            sl = slice(p * d, (p + 1) * d)
            lse = lse_ref[0, 0][:, p:p + 1]
            # delta = rowsum(do * o), computed here instead of a
            # separate HBM pass.
            delta = jnp.sum(
                do[:, sl].astype(jnp.float32) *
                o[:, sl].astype(jnp.float32), axis=-1, keepdims=True)
            s = jax.lax.dot_general(
                q[:, sl], k[:, sl], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            if causal:
                s = _causal_mask(s, qi, kj, blk_q, blk_k)
            pp = jnp.exp(s - lse)
            dp = jax.lax.dot_general(
                do[:, sl], v[:, sl], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            ds = pp * (dp - delta)
            acc_scr[p] += jax.lax.dot_general(
                ds.astype(k.dtype), k[:, sl], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) * scale

    if causal:
        pl.when(kj <= qi * (blk_q // blk_k) + (blk_q // blk_k) - 1)(
            _compute)
    else:
        _compute()

    last_kj = (qi * (blk_q // blk_k) + (blk_q // blk_k) - 1) \
        if causal else num_kv - 1

    @pl.when(kj == last_kj)
    def _finalize():
        dq_ref[0] = jnp.concatenate(
            [acc_scr[p].astype(dq_ref.dtype) for p in range(npack)],
            axis=1)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, scale: float,
                    causal: bool, blk_q: int, blk_k: int, num_q: int,
                    npack: int, d: int):
    kj = pl.program_id(2)
    qi = pl.program_id(3)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]                  # bf16: MXU operand
        o = o_ref[0]
        for p in range(npack):
            sl = slice(p * d, (p + 1) * d)
            lse = lse_ref[0, 0][:, p:p + 1]
            delta = jnp.sum(
                do[:, sl].astype(jnp.float32) *
                o[:, sl].astype(jnp.float32), axis=-1, keepdims=True)
            s = jax.lax.dot_general(
                q[:, sl], k[:, sl], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            if causal:
                s = _causal_mask(s, qi, kj, blk_q, blk_k)
            pp = jnp.exp(s - lse)                 # [blk_q, blk_k] f32
            # dv += p^T do — bf16 operands, fp32 accumulation.
            dv_scr[p] += jax.lax.dot_general(
                pp.astype(do.dtype), do[:, sl],
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(
                do[:, sl], v[:, sl], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            ds = pp * (dp - delta)                # [blk_q, blk_k]
            dk_scr[p] += jax.lax.dot_general(
                ds.astype(q.dtype), q[:, sl], (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) * scale

    if causal:
        # Only q blocks at/after this kv block contribute.
        pl.when(qi * blk_q + blk_q - 1 >= kj * blk_k)(_compute)
    else:
        _compute()

    @pl.when(qi == num_q - 1)
    def _finalize():
        dk_ref[0] = jnp.concatenate(
            [dk_scr[p].astype(dk_ref.dtype) for p in range(npack)],
            axis=1)
        dv_ref[0] = jnp.concatenate(
            [dv_scr[p].astype(dv_ref.dtype) for p in range(npack)],
            axis=1)


def _flash_bwd_packed(causal, H, D, scale, res, g):
    q, k, v, o, lse = res
    do = g
    B, T, C = q.shape
    Tk = k.shape[1]
    npack = _pack_factor(H, D)
    lane_blk = npack * D
    G = H // npack
    blk_q = _pick_block(T)
    blk_k = _pick_block(Tk)
    if causal and blk_q % blk_k:
        blk_k = blk_q = min(blk_q, blk_k)
    num_kv = Tk // blk_k
    num_q = T // blk_q

    q_spec = pl.BlockSpec((1, blk_q, lane_blk),
                          lambda b, g, i, j: (b, i, g))
    k_spec = pl.BlockSpec((1, blk_k, lane_blk),
                          lambda b, g, i, j: (b, j, g))
    lse_spec = pl.BlockSpec((1, 1, blk_q, _LANES),
                            lambda b, g, i, j: (b, g, i, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          blk_q=blk_q, blk_k=blk_k, num_kv=num_kv,
                          npack=npack, d=D),
        grid=(B, G, num_q, num_kv),
        in_specs=[q_spec, k_spec, k_spec, q_spec, q_spec, lse_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((B, T, C), q.dtype),
        scratch_shapes=[pltpu.VMEM((npack, blk_q, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=_interpret(),
    )(q, k, v, o, do, lse)

    # dkv grid: kv blocks in the third slot, q blocks innermost.
    kv_q_spec = pl.BlockSpec((1, blk_q, lane_blk),
                             lambda b, g, j, i: (b, i, g))
    kv_k_spec = pl.BlockSpec((1, blk_k, lane_blk),
                             lambda b, g, j, i: (b, j, g))
    kv_lse_spec = pl.BlockSpec((1, 1, blk_q, _LANES),
                               lambda b, g, j, i: (b, g, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          blk_q=blk_q, blk_k=blk_k, num_q=num_q,
                          npack=npack, d=D),
        grid=(B, G, num_kv, num_q),
        in_specs=[kv_q_spec, kv_k_spec, kv_k_spec, kv_q_spec,
                  kv_q_spec, kv_lse_spec],
        out_specs=[kv_k_spec, kv_k_spec],
        out_shape=[
            jax.ShapeDtypeStruct((B, Tk, C), k.dtype),
            jax.ShapeDtypeStruct((B, Tk, C), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((npack, blk_k, D), jnp.float32),
            pltpu.VMEM((npack, blk_k, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=_interpret(),
    )(q, k, v, o, do, lse)
    return dq, dk, dv


# --------------------------------------------------------------------------
# custom_vjp wrapper over the packed [B, T, C] layout
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_packed(q, k, v, causal, H, D, scale):
    o, _ = _flash_fwd(q, k, v, causal, H, D, scale)
    return o


def _flash_packed_fwd(q, k, v, causal, H, D, scale):
    o, lse = _flash_fwd(q, k, v, causal, H, D, scale)
    return o, (q, k, v, o, lse)


_flash_packed.defvjp(_flash_packed_fwd, _flash_bwd_packed)


def _pad_to_packable(H: int, D: int):
    """Smallest (H', D') >= (H, D) that _pack_factor accepts: D' is the
    next divisor (or multiple) of 128, H' pads to a whole lane group."""
    if D <= _LANES:
        Dp = next(d for d in (1, 2, 4, 8, 16, 32, 64, _LANES) if d >= D)
    else:
        Dp = -(-D // _LANES) * _LANES
    if H * Dp <= _LANES:
        return H, Dp
    npack = max(1, _LANES // Dp)
    Hp = -(-H // npack) * npack
    return Hp, Dp


def flash_attention(q, k, v, causal: bool = True) -> jax.Array:
    """Pallas flash attention. q/k/v: [B, T, H, D]; returns [B, T, H, D].
    T must be a multiple of 128; causal requires equal q/kv lengths.
    Differentiable (custom pallas backward).

    The [B,T,H,D] -> [B,T,H*D] reshape below is layout-free (same memory
    order); the kernels block the packed layout directly. Shapes that
    don't tile the 128-lane blocks (odd H, D not a power of two) are
    zero-padded up to the nearest packable (H', D') — sound because the
    softmax scale is passed explicitly (1/sqrt of the REAL D), zero
    padding adds zero to every q.k dot, and the padded output
    heads/dims are sliced away (autodiff routes gradients through the
    pad/slice, outside the kernel's custom_vjp).
    """
    B, T, H, D = q.shape
    Tk = k.shape[1]
    if T % _LANES or Tk % _LANES:
        raise ValueError(
            f"flash_attention requires T % {_LANES} == 0, got {T}/{Tk}")
    if causal and T != Tk:
        # The kernel's causal mask aligns position 0 of q and kv; with
        # Tq != Tk its last-block finalize bookkeeping would also skip
        # writes. Cross-length causal (decode) goes through the xla path.
        raise ValueError(
            f"causal flash_attention requires equal q/kv lengths, "
            f"got {T} vs {Tk}")
    scale = 1.0 / (D ** 0.5)
    Hp, Dp = _pad_to_packable(H, D)
    if (Hp, Dp) != (H, D):
        pad = [(0, 0), (0, 0), (0, Hp - H), (0, Dp - D)]
        q, k, v = (jnp.pad(x, pad) for x in (q, k, v))

    def pack(x):
        return x.reshape(x.shape[0], x.shape[1], Hp * Dp)

    o = _flash_packed(pack(q), pack(k), pack(v), causal, Hp, Dp, scale)
    o = o.reshape(B, T, Hp, Dp)
    if (Hp, Dp) != (H, D):
        o = o[:, :, :H, :D]
    return o
