"""Flash attention: custom pallas TPU kernels (forward + backward).

The framework's own blockwise-attention kernel (SURVEY.md §7 hard part 5 —
"the only place we write kernels"), used for long sequences where XLA
attention materializes the [B,H,T,T] score tensor in HBM. Design notes:

- Online softmax: running (m, l, acc) in VMEM scratch, revisited across the
  kv grid dimension (innermost, "arbitrary" semantics); scores never touch
  HBM. fp32 accumulation, bf16 MXU matmuls.
- Causal blocks kj > qi are predicated off with @pl.when (the grid still
  visits them; the MXU work is skipped).
- Backward is two kernels: dq (grid over q blocks, accumulate over kv) and
  dk/dv (grid over kv blocks, accumulate over q), using the saved
  logsumexp and delta = rowsum(do * o) — no recomputed softmax
  normalization passes.
- Layout contract: [B, T, H, D] externally; folded to [B*H, T, D] for the
  kernels so the grid's leading dimension is embarrassingly parallel.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
_LANES = 128


def _pick_block(t: int, target: int = 1024) -> int:
    """Measured on v5e (GPT-2-124M fwd+bwd, B=24 T=1024): target 1024
    gives 43.2% MFU vs 39.0% at 512 and 31.1% at 256 — bigger blocks
    amortize grid overhead and keep the MXU busy; the 1024x1024 fp32
    score block (4 MiB) still fits VMEM comfortably."""
    blk = min(t, target)
    while t % blk:
        blk //= 2
    return max(blk, min(t, _LANES))



def _interpret() -> bool:
    """Pallas TPU kernels run natively on TPU; everywhere else (the CPU
    test mesh) they run in interpreter mode."""
    return jax.default_backend() != "tpu"

# --------------------------------------------------------------------------
# Forward
# --------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale: float, causal: bool,
                blk_q: int, blk_k: int, num_kv: int):
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _compute():
        q = q_ref[0]                       # [blk_q, D]
        k = k_ref[0]                       # [blk_k, D]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = qi * blk_q + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 0)
            kpos = kj * blk_k + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 1)
            s = jnp.where(kpos <= qpos, s, _NEG_INF)
        m_prev = m_scr[:, :1]              # [blk_q, 1]
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_blk)
        alpha = jnp.exp(m_prev - m_new)    # [blk_q, 1]
        p = jnp.exp(s - m_new)             # [blk_q, blk_k] f32
        l_new = l_scr[:, :1] * alpha + jnp.sum(p, -1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        pl.when(kj <= qi * (blk_q // blk_k) + (blk_q // blk_k) - 1)(
            _compute)
    else:
        _compute()

    last_kj = (qi * (blk_q // blk_k) + (blk_q // blk_k) - 1) \
        if causal else num_kv - 1

    @pl.when(kj == last_kj)
    def _finalize():
        l = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse = m_scr[:, :1] + jnp.log(l)
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _flash_fwd(q, k, v, causal: bool) -> Tuple[jax.Array, jax.Array]:
    BH, T, D = q.shape
    Tk = k.shape[1]
    scale = 1.0 / (D ** 0.5)
    blk_q = _pick_block(T)
    blk_k = _pick_block(Tk)
    if causal and blk_q % blk_k:
        blk_k = blk_q = min(blk_q, blk_k)
    num_kv = Tk // blk_k

    grid = (BH, T // blk_q, num_kv)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, blk_q=blk_q,
        blk_k=blk_k, num_kv=num_kv)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, blk_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, blk_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, blk_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, blk_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, blk_q, _LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, T, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk_q, _LANES), jnp.float32),   # m
            pltpu.VMEM((blk_q, _LANES), jnp.float32),   # l
            pltpu.VMEM((blk_q, D), jnp.float32),        # acc
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(q, k, v)
    return o, lse[:, :, 0]


# --------------------------------------------------------------------------
# Backward
# --------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, acc_scr, *, scale: float, causal: bool,
                   blk_q: int, blk_k: int, num_kv: int):
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = qi * blk_q + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 0)
            kpos = kj * blk_k + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 1)
            s = jnp.where(kpos <= qpos, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        acc_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    if causal:
        pl.when(kj <= qi * (blk_q // blk_k) + (blk_q // blk_k) - 1)(
            _compute)
    else:
        _compute()

    last_kj = (qi * (blk_q // blk_k) + (blk_q // blk_k) - 1) \
        if causal else num_kv - 1

    @pl.when(kj == last_kj)
    def _finalize():
        dq_ref[0] = acc_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, scale: float,
                    causal: bool, blk_q: int, blk_k: int, num_q: int):
    kj = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = qi * blk_q + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 0)
            kpos = kj * blk_k + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 1)
            s = jnp.where(kpos <= qpos, s, _NEG_INF)
        p = jnp.exp(s - lse)                      # [blk_q, blk_k]
        # dv += p^T do
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do_ref.dtype).astype(jnp.float32), do,
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)                     # [blk_q, blk_k]
        dk_scr[:] += jax.lax.dot_general(
            ds, q.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    if causal:
        # Only q blocks at/after this kv block contribute.
        pl.when(qi * blk_q + blk_q - 1 >= kj * blk_k)(_compute)
    else:
        _compute()

    @pl.when(qi == num_q - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_bwd(causal, res, g):
    q, k, v, o, lse = res
    do = g
    BH, T, D = q.shape
    Tk = k.shape[1]
    scale = 1.0 / (D ** 0.5)
    blk_q = _pick_block(T)
    blk_k = _pick_block(Tk)
    if causal and blk_q % blk_k:
        blk_k = blk_q = min(blk_q, blk_k)
    num_kv = Tk // blk_k
    num_q = T // blk_q

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)                       # [BH, T]
    lse_b = jnp.broadcast_to(lse[..., None], (BH, T, _LANES))
    delta_b = jnp.broadcast_to(delta[..., None], (BH, T, _LANES))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          blk_q=blk_q, blk_k=blk_k, num_kv=num_kv),
        grid=(BH, num_q, num_kv),
        in_specs=[
            pl.BlockSpec((1, blk_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, blk_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, blk_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, blk_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, blk_q, _LANES), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, blk_q, _LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, blk_q, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((blk_q, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(q, k, v, do, lse_b, delta_b)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          blk_q=blk_q, blk_k=blk_k, num_q=num_q),
        grid=(BH, num_kv, num_q),
        in_specs=[
            pl.BlockSpec((1, blk_q, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, blk_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, blk_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, blk_q, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, blk_q, _LANES), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, blk_q, _LANES), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, blk_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, blk_k, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Tk, D), k.dtype),
            jax.ShapeDtypeStruct((BH, Tk, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk_k, D), jnp.float32),
            pltpu.VMEM((blk_k, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(q, k, v, do, lse_b, delta_b)
    return dq, dk, dv


# --------------------------------------------------------------------------
# custom_vjp wrapper, [B, T, H, D] public layout
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash_bhtd(q, k, v, causal):
    o, _ = _flash_fwd(q, k, v, causal)
    return o


def _flash_bhtd_fwd(q, k, v, causal):
    o, lse = _flash_fwd(q, k, v, causal)
    return o, (q, k, v, o, lse)


_flash_bhtd.defvjp(_flash_bhtd_fwd, _flash_bwd)


def flash_attention(q, k, v, causal: bool = True) -> jax.Array:
    """Pallas flash attention. q/k/v: [B, T, H, D]; returns [B, T, H, D].
    T must be a multiple of 128. Differentiable (custom pallas backward).
    """
    B, T, H, D = q.shape
    Tk = k.shape[1]
    if T % _LANES or Tk % _LANES:
        raise ValueError(
            f"flash_attention requires T % {_LANES} == 0, got {T}/{Tk}")

    def fold(x):
        return x.swapaxes(1, 2).reshape(B * H, x.shape[1], D)

    o = _flash_bhtd(fold(q), fold(k), fold(v), causal)
    return o.reshape(B, H, T, D).swapaxes(1, 2)
