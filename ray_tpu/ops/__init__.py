from ray_tpu.ops.attention import multi_head_attention

__all__ = ["multi_head_attention"]
