"""`python -m ray_tpu` → the CLI (reference: the `ray` console script,
python/ray/scripts/scripts.py)."""
from ray_tpu.scripts.cli import main

main()
