"""Lazy task/actor call graphs: ``.bind()`` / ``.execute()``.

Capability parity with the reference DAG API (python/ray/dag/dag_node.py:23,
function_node.py, class_node.py, input_node.py): functions and actor classes
gain ``.bind(*args)`` which returns a lazy node; nodes compose into a DAG
that ``.execute(input)`` submits as real tasks/actor calls. This is the
substrate for Serve deployment graphs and the Workflow engine.

Fresh design: a DAG is an immutable tree of ``DAGNode``s; execution walks it
once per call with a per-execution memo table so diamond-shaped graphs run
each shared node exactly once, and passes ``ObjectRef``s (never materialized
values) between nodes so the scheduler sees real data dependencies.
"""
from __future__ import annotations

import threading
import uuid
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu._private.object_ref import ObjectRef

__all__ = [
    "DAGNode", "FunctionNode", "ClassNode", "ClassMethodNode",
    "InputNode", "InputAttributeNode", "MultiOutputNode",
]


def _scan(value, visit):
    """Apply ``visit`` to every DAGNode nested in ``value`` (lists, tuples,
    dicts); returns the transformed structure."""
    if isinstance(value, DAGNode):
        return visit(value)
    if isinstance(value, list):
        return [_scan(v, visit) for v in value]
    if isinstance(value, tuple):
        return tuple(_scan(v, visit) for v in value)
    if isinstance(value, dict):
        return {k: _scan(v, visit) for k, v in value.items()}
    return value


class _ExecutionContext:
    """Per-execute() state: the DAG input and the node → result memo."""

    def __init__(self, input_args, input_kwargs):
        self.input_args = input_args
        self.input_kwargs = input_kwargs
        self.memo: Dict[str, Any] = {}


class DAGNode:
    """A node in a lazy call graph.

    ``_bound_args``/``_bound_kwargs`` may contain plain values, other
    DAGNodes, or DAGNodes nested inside lists/tuples/dicts.
    """

    def __init__(self, args: Tuple, kwargs: Dict[str, Any],
                 options: Optional[Dict[str, Any]] = None):
        self._bound_args = tuple(args or ())
        self._bound_kwargs = dict(kwargs or {})
        self._bound_options = dict(options or {})
        self._stable_uuid = uuid.uuid4().hex

    # -- traversal ---------------------------------------------------------

    def _children(self) -> List["DAGNode"]:
        found: List[DAGNode] = []

        def visit(node):
            found.append(node)
            return node

        _scan(self._bound_args, visit)
        _scan(self._bound_kwargs, visit)
        return found

    def walk(self) -> List["DAGNode"]:
        """All nodes reachable from this one (post-order, deduped)."""
        seen: Dict[str, DAGNode] = {}

        def rec(node):
            if node._stable_uuid in seen:
                return
            for c in node._children():
                rec(c)
            seen[node._stable_uuid] = node

        rec(self)
        return list(seen.values())

    # -- execution ---------------------------------------------------------

    def execute(self, *input_args, **input_kwargs):
        """Run the DAG; returns an ObjectRef (or an ActorHandle for a bare
        ClassNode, or a list for MultiOutputNode)."""
        ctx = _ExecutionContext(input_args, input_kwargs)
        return self._resolve(ctx)

    def _resolve(self, ctx: _ExecutionContext):
        hit = ctx.memo.get(self._stable_uuid)
        if hit is None:
            args = _scan(self._bound_args, lambda n: n._resolve(ctx))
            kwargs = _scan(self._bound_kwargs, lambda n: n._resolve(ctx))
            hit = self._execute_impl(args, kwargs, ctx)
            ctx.memo[self._stable_uuid] = hit
        return hit

    def _execute_impl(self, args, kwargs, ctx):
        raise NotImplementedError

    def __reduce__(self):
        raise TypeError("DAGNode cannot be serialized; execute() it and "
                        "pass the resulting ObjectRef instead")


class FunctionNode(DAGNode):
    """Lazy ``fn.bind(...)``; executes as ``fn.options(...).remote(...)``."""

    def __init__(self, remote_fn, args, kwargs, options=None):
        super().__init__(args, kwargs, options)
        self._remote_fn = remote_fn

    def options(self, **opts) -> "FunctionNode":
        return FunctionNode(self._remote_fn, self._bound_args,
                            self._bound_kwargs,
                            {**self._bound_options, **opts})

    def _execute_impl(self, args, kwargs, ctx):
        fn = self._remote_fn
        if self._bound_options:
            fn = fn.options(**self._bound_options)
        return fn.remote(*args, **kwargs)

    def __repr__(self):
        return f"FunctionNode({getattr(self._remote_fn, '__name__', '?')})"


class ClassNode(DAGNode):
    """Lazy ``ActorClass.bind(...)``; executes by instantiating the actor
    (once per DAG execution) and yields its handle."""

    def __init__(self, actor_cls, args, kwargs, options=None):
        super().__init__(args, kwargs, options)
        self._actor_cls = actor_cls
        # Persistent handle cache so repeated .execute() on a Serve-style
        # graph reuses replica actors rather than leaking one per request.
        self._cached_handle = None
        self._lock = threading.Lock()

    def options(self, **opts) -> "ClassNode":
        return ClassNode(self._actor_cls, self._bound_args,
                         self._bound_kwargs,
                         {**self._bound_options, **opts})

    def __getattr__(self, name: str) -> "_UnboundClassMethod":
        if name.startswith("_"):
            raise AttributeError(name)
        return _UnboundClassMethod(self, name)

    def _resolve(self, ctx):
        # Skip constructor-arg resolution entirely once the actor exists —
        # re-submitting those upstream tasks would waste work and repeat
        # their side effects for a dead result.
        with self._lock:
            if self._cached_handle is not None:
                ctx.memo[self._stable_uuid] = self._cached_handle
                return self._cached_handle
        return super()._resolve(ctx)

    def _execute_impl(self, args, kwargs, ctx):
        with self._lock:
            if self._cached_handle is None:
                cls = self._actor_cls
                if self._bound_options:
                    cls = cls.options(**self._bound_options)
                self._cached_handle = cls.remote(*args, **kwargs)
        return self._cached_handle

    def __repr__(self):
        return f"ClassNode({getattr(self._actor_cls, '__name__', '?')})"


class _UnboundClassMethod:
    """``class_node.method`` — call ``.bind()`` to get a ClassMethodNode."""

    def __init__(self, class_node: ClassNode, method_name: str,
                 options: Optional[Dict[str, Any]] = None):
        self._class_node = class_node
        self._method_name = method_name
        self._options = dict(options or {})

    def options(self, **opts) -> "_UnboundClassMethod":
        # ActorMethod.options only understands num_returns; reject anything
        # else here, at build time, rather than deep inside execute().
        bad = set(opts) - {"num_returns"}
        if bad:
            raise TypeError(
                f"unsupported actor-method option(s): {sorted(bad)}")
        return _UnboundClassMethod(self._class_node, self._method_name,
                                   {**self._options, **opts})

    def bind(self, *args, **kwargs) -> "ClassMethodNode":
        return ClassMethodNode(self._class_node, self._method_name,
                               args, kwargs, self._options)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Method {self._method_name!r} on a ClassNode is lazy; "
            f"use .bind(...)")


class ClassMethodNode(DAGNode):
    """Lazy actor method call bound to a ClassNode."""

    def __init__(self, class_node, method_name, args, kwargs, options=None):
        super().__init__(args, kwargs, options)
        self._class_node = class_node
        self._method_name = method_name

    def _children(self):
        return [self._class_node] + super()._children()

    def _resolve(self, ctx):
        hit = ctx.memo.get(self._stable_uuid)
        if hit is None:
            handle = self._class_node._resolve(ctx)
            args = _scan(self._bound_args, lambda n: n._resolve(ctx))
            kwargs = _scan(self._bound_kwargs, lambda n: n._resolve(ctx))
            method = getattr(handle, self._method_name)
            if self._bound_options:
                method = method.options(**self._bound_options)
            hit = method.remote(*args, **kwargs)
            ctx.memo[self._stable_uuid] = hit
        return hit

    def _execute_impl(self, args, kwargs, ctx):  # handled in _resolve
        raise AssertionError("unreachable")

    def __repr__(self):
        return (f"ClassMethodNode({self._class_node!r}."
                f"{self._method_name})")


class InputNode(DAGNode):
    """Placeholder for the runtime input to ``execute()``.

    Usable as a context manager for scoping clarity (parity with the
    reference's ``with InputNode() as inp:`` idiom,
    python/ray/dag/input_node.py), though the scope is not enforced.
    """

    def __init__(self):
        super().__init__((), {})

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return InputAttributeNode(self, name, "attr")

    def __getitem__(self, key):
        return InputAttributeNode(self, key, "item")

    def _execute_impl(self, args, kwargs, ctx):
        if ctx.input_kwargs:
            raise TypeError("execute() kwargs require InputAttributeNode "
                            "access (inp.key), not bare InputNode")
        if len(ctx.input_args) == 1:
            return ctx.input_args[0]
        if len(ctx.input_args) == 0:
            return None
        return ctx.input_args

    def __repr__(self):
        return "InputNode()"


class InputAttributeNode(DAGNode):
    """``inp.field`` / ``inp[key]`` — projects the runtime input."""

    def __init__(self, input_node: InputNode, key, kind: str):
        super().__init__((), {})
        self._input_node = input_node
        self._key = key
        self._kind = kind

    def _children(self):
        return [self._input_node]

    def _execute_impl(self, args, kwargs, ctx):
        if self._kind == "item":
            if ctx.input_kwargs and isinstance(self._key, str) \
                    and self._key in ctx.input_kwargs:
                return ctx.input_kwargs[self._key]
            base = self._input_node._resolve(ctx)
            return base[self._key]
        if ctx.input_kwargs and self._key in ctx.input_kwargs:
            return ctx.input_kwargs[self._key]
        base = self._input_node._resolve(ctx)
        return getattr(base, self._key)

    def __repr__(self):
        return f"InputAttributeNode({self._key!r})"


class MultiOutputNode(DAGNode):
    """Terminal node returning a list of results (one per bound output)."""

    def __init__(self, outputs: List[DAGNode]):
        super().__init__((list(outputs),), {})

    def _execute_impl(self, args, kwargs, ctx):
        return list(args[0])

    def __repr__(self):
        return f"MultiOutputNode(n={len(self._bound_args[0])})"
