"""Driver-side distributed runtime client + shared object-resolution
helpers (used by both the driver client and worker runtimes).

Capability parity with the reference's driver path (CoreWorker submit +
GCS client): tasks/actors go to the head scheduler; objects live in the
node's C++ shm store.
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from types import SimpleNamespace
from typing import Any, Dict, List, Optional

import cloudpickle

from ray_tpu._private.ids import ActorID, JobID, ObjectID
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.object_store import ReferenceCounter
from ray_tpu._private.serialization import dumps, loads
from ray_tpu._private.task_spec import (ActorCreationSpec,
                                        PlacementGroupSchedulingStrategy,
                                        PlacementGroupSpec, TaskSpec)
from ray_tpu.exceptions import GetTimeoutError
from ray_tpu.runtime.rpc import RpcClient, RpcError


# --------------------------------------------------------------------------
# Shared object helpers
# --------------------------------------------------------------------------

import io as _io
import pickle as _pickle


class _FastSpecPickler(_pickle.Pickler):
    """C pickler for spec envelopes. Anything cloudpickle would have
    to serialize by VALUE (lambdas, closures, __main__/local classes)
    raises here so the caller falls back — plain pickle would either
    fail at load time (__main__ refs resolve to worker_main) or not at
    all, which is worse."""

    def reducer_override(self, obj):
        if getattr(obj, "__module__", None) == "__main__":
            raise _pickle.PicklingError("__main__ object: cloudpickle")
        return NotImplemented


def _dumps_spec(obj) -> bytes:
    """Serialize a task-spec envelope: C pickler (≈2x faster than
    cloudpickle's Python dispatch) with cloudpickle fallback for
    by-value captures. Loads is shared — both produce pickle streams."""
    try:
        f = _io.BytesIO()
        _FastSpecPickler(f, protocol=5).dump(obj)
        return f.getvalue()
    except Exception:
        return cloudpickle.dumps(obj)


def _maybe_put_device(plane, oid: ObjectID, value, node_id: str) -> bool:
    """Device-array put interception (zero-copy HBM object layer).
    Guarded so jax-free processes never import jax."""
    import sys
    if "jax" not in sys.modules:
        return False
    from ray_tpu.mesh.device_objects import maybe_put_device
    return maybe_put_device(plane, oid, value, node_id)


def _read_one(store, oid: ObjectID, timeout_ms: int):
    from ray_tpu._private.shm_store import ShmTimeout
    read = getattr(store, "get_blob", None) or store.get_bytes
    try:
        status, value = loads(read(oid, timeout_ms=timeout_ms))
    except ShmTimeout:
        raise GetTimeoutError(
            f"Get timed out waiting for {oid.hex()[:16]}…") from None
    if status == "err":
        raise value
    if status == "devobj":
        # Descriptor of an HBM-resident device object: resolve to the
        # living Array (same-process: buffer identity; cross-process:
        # spilled-payload pull + device_put).
        from ray_tpu.mesh.device_objects import resolve_handle
        return resolve_handle(value, store, timeout_ms)
    return value


def resolve_refs(store, refs, timeout: Optional[float]):
    single = isinstance(refs, ObjectRef)
    ref_list = [refs] if single else list(refs)
    for r in ref_list:
        if not isinstance(r, ObjectRef):
            raise TypeError(
                f"get() expects ObjectRef(s), got {type(r).__name__}")
    if len(ref_list) > 1:
        prefetch = getattr(store, "prefetch", None)
        if prefetch is not None:
            prefetch([r.id for r in ref_list])
    deadline = None if timeout is None else time.time() + timeout
    values = []
    for r in ref_list:
        if deadline is None:
            tmo = -1
        else:
            tmo = max(1, int((deadline - time.time()) * 1000))
        values.append(_read_one(store, r.id, tmo))
    return values[0] if single else values


def wait_refs(store, refs, num_returns: int, timeout: Optional[float]):
    if num_returns > len(refs):
        raise ValueError("num_returns > len(refs)")
    deadline = None if timeout is None else time.time() + timeout
    ready: List[ObjectRef] = []
    remaining = list(refs)
    # Exponential poll backoff: contains() on the multinode plane costs
    # a head locate RPC per missing ref, so a fixed 2 ms poll turns one
    # slow wait into thousands of control RPCs that steal CPU from the
    # work being waited on. 2 ms keeps fast tasks snappy; 50 ms bounds
    # the churn for long waits.
    poll = 0.002
    while True:
        still = []
        for r in remaining:
            if store.contains(r.id):
                ready.append(r)
            else:
                still.append(r)
        remaining = still
        if len(ready) >= num_returns or not remaining:
            return ready, remaining
        if deadline is not None and time.time() >= deadline:
            return ready, remaining
        time.sleep(poll)
        poll = min(poll * 1.5, 0.05)


def object_future(store, oid: ObjectID) -> Future:
    f: Future = Future()

    def _wait():
        try:
            value = _read_one(store, oid, -1)
        except BaseException as e:  # noqa: BLE001
            if f.set_running_or_notify_cancel():
                f.set_exception(e)
            return
        if f.set_running_or_notify_cancel():
            f.set_result(value)

    threading.Thread(target=_wait, daemon=True).start()
    return f


# --------------------------------------------------------------------------
# Shared submission helpers
# --------------------------------------------------------------------------

def _function_ref(head: RpcClient, func) -> str:
    """Register `func` in the head's function table once and return its
    content hash (the GCS function-table pattern,
    python/ray/_private/function_manager.py — per-task payloads carry
    the hash, not a fresh pickle of the function)."""
    fn_id = getattr(func, "__raytpu_fn_id__", None)
    registered = getattr(head, "_fn_registered", None)
    if registered is None:
        registered = head._fn_registered = set()
    if fn_id is None:
        import hashlib
        blob = cloudpickle.dumps(func)
        fn_id = hashlib.sha1(blob).hexdigest()
        try:
            func.__raytpu_fn_id__ = fn_id
        except (AttributeError, TypeError):
            pass      # unsettable (builtin/bound): re-hash next time
        if fn_id not in registered:
            head.call("register_function", fn_id, blob)
            registered.add(fn_id)
        return fn_id
    if fn_id not in registered:
        head.call("register_function", fn_id, cloudpickle.dumps(func))
        registered.add(fn_id)
    return fn_id


class _SubmitBuffer:
    """Client-side submission coalescing: .remote() appends and returns
    immediately; a flusher ships batches as ONE one-way RPC (one head
    lock acquire + one scheduler wake per window). Submission outcome
    surfaces through the return objects, so no reply is needed —
    failure to flush only happens if this whole process dies, taking
    any would-be getter with it."""

    FLUSH_AT = 256            # tasks per batch before an eager flush
    WINDOW_S = 0.0005

    def __init__(self, head: RpcClient):
        self._head = head
        self._buf: list = []
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def add(self, meta, payload):
        eager = None
        with self._lock:
            self._buf.append((meta, payload))
            if len(self._buf) >= self.FLUSH_AT:
                eager, self._buf = self._buf, []
            elif self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, daemon=True,
                    name="submit-flusher")
                self._thread.start()
        if eager is not None:
            self._ship(eager)
        else:
            self._wake.set()

    def _ship(self, batch):
        """Deliver a batch, surviving transient socket failures — a
        silently dropped batch would hang every get() on its refs. The
        one-way send reconnects once; the request/reply fallback proves
        delivery; if the head is truly gone we requeue and keep trying
        (the whole runtime is down anyway until it returns)."""
        for _attempt in range(2):
            try:
                self._head.call_oneway("submit_tasks", batch, fast=True)
                return
            except Exception:
                continue
        try:
            self._head.call("submit_tasks", batch)
            return
        except Exception:
            with self._lock:
                self._buf = batch + self._buf
            self._wake.set()
            time.sleep(0.2)

    def _loop(self):
        while True:
            self._wake.wait(timeout=1.0)
            self._wake.clear()
            time.sleep(self.WINDOW_S)
            with self._lock:
                batch, self._buf = self._buf, []
            if batch:
                self._ship(batch)


def _submit_buffer(head: RpcClient) -> _SubmitBuffer:
    buf = getattr(head, "_submit_buffer", None)
    if buf is None:
        buf = head._submit_buffer = _SubmitBuffer(head)
    return buf


def submit_task_via_head(head: RpcClient, spec: TaskSpec,
                         ret_addr: Optional[str] = None):
    from ray_tpu._private.task_spec import (
        NodeAffinitySchedulingStrategy, SpreadSchedulingStrategy)
    refs = [ObjectRef(oid) for oid in spec.return_ids]
    pg_id = None
    strat_meta = None
    strat = spec.scheduling_strategy
    if isinstance(strat, PlacementGroupSchedulingStrategy) and \
            strat.placement_group is not None:
        pg_id = strat.placement_group.id.hex()
    elif isinstance(strat, SpreadSchedulingStrategy):
        strat_meta = {"type": "spread"}
    elif isinstance(strat, NodeAffinitySchedulingStrategy):
        strat_meta = {"type": "node_affinity",
                      "node_id": strat.node_id,
                      "soft": bool(strat.soft)}
    payload = _dumps_spec({
        "task_id": spec.task_id.hex(),
        "name": spec.name,
        "fn_ref": _function_ref(head, spec.func),
        "args": spec.args,
        "kwargs": spec.kwargs,
        "num_returns": spec.num_returns,
        "return_ids": [oid.binary() for oid in spec.return_ids],
        "resources": spec.resources,
        "runtime_env": spec.runtime_env,
        "trace_ctx": spec.trace_ctx,
        # Owner-direct returns: small results push straight to the
        # caller's node store (worker_main._write_returns).
        "ret_addr": ret_addr,
    })
    meta = {
        "task_id": spec.task_id.hex(),
        "name": spec.name,
        "return_ids": [oid.binary() for oid in spec.return_ids],
        "resources": spec.resources,
        "max_retries": spec.max_retries,
        "pg_id": pg_id,
    }
    if spec.runtime_env:
        # Env-keyed worker-pool routing (isolation): the head sends
        # this task only to a dedicated worker for this env.
        from ray_tpu._private.runtime_env import runtime_env_key
        meta["env_key"] = runtime_env_key(spec.runtime_env)
        meta["runtime_env"] = spec.runtime_env
    ref_args = [a.id.hex() for a in spec.args
                if isinstance(a, ObjectRef)]
    if ref_args:
        # Queue-time arg pinning: the head holds these against the
        # borrower protocol's eager free until the task leaves the
        # system — a caller dropping its own ref right after a burst
        # submit must not free an argument out from under tasks still
        # queued (head._pin_args_locked).
        meta["pin_oids"] = ref_args[:64]
    if strat_meta is not None:
        meta["strategy"] = strat_meta
    elif ref_args:
        # Locality hints: schedule where the argument objects live
        # (lease_policy.cc locality path). Hex ids only — cheap.
        meta["arg_oids"] = ref_args[:16]
    _submit_buffer(head).add(meta, payload)
    return refs


def create_actor_via_head(head: RpcClient, spec: ActorCreationSpec):
    payload = cloudpickle.dumps({
        "cls": spec.cls,
        "args": spec.args,
        "kwargs": spec.kwargs,
        "max_concurrency": spec.max_concurrency,
        "concurrency_groups": spec.concurrency_groups,
        "runtime_env": spec.runtime_env,
    })
    pg_id = None
    bundle_index = -1
    strat = spec.scheduling_strategy
    if isinstance(strat, PlacementGroupSchedulingStrategy) and \
            strat.placement_group is not None:
        pg_id = strat.placement_group.id.hex()
        bundle_index = getattr(strat, "placement_group_bundle_index",
                               -1)
    meta = {
        "actor_id": spec.actor_id.hex(),
        "resources": spec.resources,
        "max_restarts": spec.max_restarts,
        "pg_id": pg_id,
        "bundle_index": bundle_index,
        "name": spec.name,
        "namespace": spec.namespace,
        "get_if_exists": spec.get_if_exists,
        "concurrency_groups": spec.concurrency_groups,
    }
    if spec.runtime_env:
        from ray_tpu._private.runtime_env import runtime_env_key
        meta["env_key"] = runtime_env_key(spec.runtime_env)
        meta["runtime_env"] = spec.runtime_env
    out = head.call("create_actor", meta, payload)
    final_spec = spec
    if out["actor_id"] != spec.actor_id.hex():
        import dataclasses
        final_spec = dataclasses.replace(
            spec, actor_id=ActorID.from_hex(out["actor_id"]))
    return SimpleNamespace(spec=final_spec)


_ACTOR_ADDR_TTL = 10.0      # bounds the stale-route window post-restart


class _DirectActorSender:
    """Per-worker-address direct actor-task pipe (reference: the
    CoreWorker direct actor transport, core_worker/transport/ —
    actor calls skip the control plane entirely). Calls enqueue and
    return; a flusher ships batches as ONE one-way RPC straight to the
    actor's worker. Per-caller ordering rides this dedicated socket.
    If the worker is unreachable the batch bounces through the head's
    reroute path (which waits out restarts or fails the returns), so
    no call is ever silently dropped."""

    FLUSH_AT = 128
    WINDOW_S = 0.0005

    def __init__(self, head: RpcClient, addr: str):
        self._head = head
        self._addr = addr
        self._client = RpcClient(addr, timeout=30)
        self._buf: list = []
        self._lock = threading.Lock()
        self._ship_lock = threading.Lock()   # serializes deliveries
        self._wake = threading.Event()
        self._stopped = False
        self._thread: Optional[threading.Thread] = None

    def add(self, actor_id_hex: str, payload: bytes) -> bool:
        eager = False
        with self._lock:
            if self._stopped:
                return False     # route was torn down: caller re-routes
            self._buf.append((actor_id_hex, payload, 0))
            if len(self._buf) >= self.FLUSH_AT:
                eager = True
            elif self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, daemon=True,
                    name="actor-direct-send")
                self._thread.start()
        if eager:
            self._ship_pending()
        else:
            self._wake.set()
        return True

    def _ship_pending(self):
        """Drain-and-deliver under the ship lock. The buffer is popped
        INSIDE the lock, so two concurrent shippers (the flusher and
        an eager caller thread) can never deliver out of enqueue order
        — whoever wins the lock takes everything buffered so far."""
        with self._ship_lock:
            with self._lock:
                batch, self._buf = self._buf, []
            if not batch:
                return
            self._ship_locked(batch)

    def _ship_locked(self, batch):
        # Request/reply (not one-way): a one-way send to a freshly
        # killed worker disappears into the TCP buffer with no error,
        # silently dropping calls. The reply is the delivery ack; its
        # cost is one RTT per BATCH (callers never block here — the
        # flusher thread pays it). Duplicate delivery on a timed-
        # out-but-delivered batch is suppressed worker-side by task-id
        # dedup.
        for _attempt in range(2):
            try:
                self._client.call("push_actor_tasks", batch)
                return
            except Exception:
                continue
        # Worker unreachable: invalidate the route and hand every
        # call to the head, which re-resolves (or fails the
        # return objects).
        _drop_actor_route(self._head, self._addr)
        self._reroute(batch)

    def _reroute(self, batch):
        for actor_id_hex, payload, attempts in batch:
            try:
                self._head.call("reroute_actor_task", actor_id_hex,
                                payload, attempts)
            except Exception:
                pass    # head down: the whole runtime is down anyway

    def stop(self):
        """Tear down after a route invalidation: reroute anything
        still buffered, stop the flusher, close the sockets."""
        with self._lock:
            self._stopped = True
            batch, self._buf = self._buf, []
        self._wake.set()
        if batch:
            self._reroute(batch)
        try:
            self._client.close()
        except Exception:
            pass

    def _loop(self):
        while not self._stopped:
            self._wake.wait(timeout=1.0)
            self._wake.clear()
            time.sleep(self.WINDOW_S)
            self._ship_pending()


def _direct_state(head: RpcClient):
    st = getattr(head, "_direct_actor_state", None)
    if st is None:
        st = head._direct_actor_state = {
            "addrs": {},       # actor_id_hex -> (addr, expires_at)
            "senders": {},     # addr -> _DirectActorSender
            "lock": threading.Lock(),
        }
    return st


def _resolve_actor_route(head: RpcClient, actor_id_hex: str):
    """Worker address for the actor, None while it rebinds. Raises
    ActorDiedError for known-dead actors (submit-time semantics)."""
    st = _direct_state(head)
    now = time.time()
    with st["lock"]:
        ent = st["addrs"].get(actor_id_hex)
        if ent is not None and ent[1] > now:
            return ent[0]
    addr = head.call("actor_address", actor_id_hex)
    if addr is not None:
        with st["lock"]:
            st["addrs"][actor_id_hex] = (addr, now + _ACTOR_ADDR_TTL)
    return addr


def _drop_actor_route(head: RpcClient, addr: str):
    st = _direct_state(head)
    with st["lock"]:
        sender = st["senders"].pop(addr, None)
        st["addrs"] = {a: e for a, e in st["addrs"].items()
                       if e[0] != addr}
    if sender is not None:
        # Off-lock: stop() reroutes buffered items through the head.
        threading.Thread(target=sender.stop, daemon=True).start()


def _direct_sender(head: RpcClient, addr: str) -> _DirectActorSender:
    st = _direct_state(head)
    with st["lock"]:
        s = st["senders"].get(addr)
        if s is None:
            s = st["senders"][addr] = _DirectActorSender(head, addr)
        return s


def submit_actor_task_via_head(head: RpcClient, actor_id: ActorID,
                               spec: TaskSpec,
                               ret_addr: Optional[str] = None):
    refs = [ObjectRef(oid, owner_hint="actor")
            for oid in spec.return_ids]
    payload = _dumps_spec({
        "task_id": spec.task_id.hex(),
        "name": spec.name,
        "method": spec.method_name,
        "args": spec.args,
        "kwargs": spec.kwargs,
        "num_returns": spec.num_returns,
        "return_ids": [oid.binary() for oid in spec.return_ids],
        "concurrency_group": spec.concurrency_group,
        "trace_ctx": spec.trace_ctx,
        "ret_addr": ret_addr,
    })
    aid = actor_id.hex()
    # Direct dispatch fast path: pipelined one-way pushes straight to
    # the actor's worker. Group'd calls keep the head path so an
    # unknown concurrency group still raises at submission.
    if spec.concurrency_group is None:
        addr = None
        try:
            addr = _resolve_actor_route(head, aid)
        except RpcError:
            addr = None      # head hiccup: blocking path will surface it
        if addr is not None and \
                _direct_sender(head, addr).add(aid, payload):
            return refs
    head.call("submit_actor_task", aid,
              {"task_id": spec.task_id.hex(),
               "concurrency_group": spec.concurrency_group}, payload)
    return refs


def actor_state_from_head(head: RpcClient, actor_id: ActorID):
    payload = head.call("actor_class_payload", actor_id.hex())
    spec = cloudpickle.loads(payload)
    return SimpleNamespace(spec=SimpleNamespace(
        actor_id=actor_id, cls=spec["cls"], max_task_retries=0))


class DistPlacementGroup:
    def __init__(self, spec: PlacementGroupSpec, head: RpcClient,
                 created: bool):
        self.spec = spec
        self._head = head
        self._created = created

    @property
    def id(self):
        return self.spec.pg_id

    @property
    def bundle_specs(self):
        return [dict(b.resources) for b in self.spec.bundles]

    def is_ready(self) -> bool:
        return self._created

    def wait(self, timeout_seconds: float = 30) -> bool:
        deadline = time.time() + timeout_seconds
        while not self._created:
            if time.time() > deadline:
                return False
            self._created = self._head.call(
                "create_placement_group", self.spec.pg_id.hex(),
                [dict(b.resources) for b in self.spec.bundles],
                self.spec.strategy)
            if not self._created:
                time.sleep(0.05)
        return True

    def ready(self) -> ObjectRef:
        oid = ObjectID.from_random()
        ref = ObjectRef(oid)
        pg = self

        def _wait():
            pg.wait(300)
            from ray_tpu._private.worker import global_worker
            global_worker().runtime.put_at(oid, pg)

        threading.Thread(target=_wait, daemon=True).start()
        return ref


def create_pg_via_head(head: RpcClient, spec: PlacementGroupSpec):
    created = head.call(
        "create_placement_group", spec.pg_id.hex(),
        [dict(b.resources) for b in spec.bundles], spec.strategy)
    return DistPlacementGroup(spec, head, created)


# --------------------------------------------------------------------------
# Driver runtime
# --------------------------------------------------------------------------

def connect_to_cluster(address: str) -> "DistributedRuntime":
    """Attach this process as a driver to a running head by address
    (the Ray Client analogue, python/ray/util/client/ — same-protocol
    attach rather than a gRPC proxy; requires same-host shm access)."""
    head = RpcClient(address, timeout=10)
    info = head.call("cluster_info")
    return DistributedRuntime(address, info["store_name"])


class DistributedRuntime:
    """Runtime interface backed by the head + node workers + shm store."""

    def __init__(self, head_address: str, store_name: str,
                 node_manager=None):
        self.head = RpcClient(head_address)
        from ray_tpu._private.shm_store import ShmObjectStore
        self.store = ShmObjectStore.attach(store_name)
        self.node_manager = node_manager
        # Drivers colocate with the head node: their puts/gets go through
        # the head node's object plane (remote pulls on miss).
        from ray_tpu.runtime.object_plane import ObjectPlane
        self.plane = ObjectPlane(self.store, self.head, node_id="head")
        self.plane.refresh_multinode()
        from ray_tpu.runtime.pubsub import Subscriber
        self._subscriber = Subscriber(RpcClient(head_address))
        self._subscriber.subscribe_state("nodes",
                                         self.plane.on_nodes_update)
        # Resource syncer view (ray_syncer role): the head pushes its
        # resource snapshot; resource queries serve from this cache —
        # zero polling RPCs in steady state.
        self._resource_view: Optional[Dict[str, Any]] = None
        self._resource_view_ts = 0.0
        self._subscriber.subscribe_state("resources",
                                         self._on_resources)
        # Eager local GC: zero-ref owned objects delete immediately
        # instead of waiting for LRU pressure/spill (the plane keeps
        # escaped refs pinned, so this is safe without a cross-process
        # borrow protocol).
        self.ref_counter = ReferenceCounter(
            on_object_released=self.plane.release_owned)
        self.job_id = JobID.next()
        self._actor_handles: Dict[Any, Any] = {}

    # objects
    def put(self, value):
        oid = ObjectID.from_random()
        if _maybe_put_device(self.plane, oid, value, "head"):
            # jax Arrays stay in HBM, referenced by a handle — the
            # plane stores only a descriptor (mesh/device_objects.py).
            return ObjectRef(oid, owner_hint="put")
        # owned: small puts live in the process memory tier until
        # their ref escapes (promotion on ref pickling); owned objects
        # are eagerly freed when their last local ref drops
        self.plane.put_obj(oid, ("ok", value), owned=True)
        return ObjectRef(oid, owner_hint="put")

    def put_at(self, oid: ObjectID, value):
        self.plane.put_bytes(oid, dumps(("ok", value)))

    def get(self, refs, timeout=None):
        return resolve_refs(self.plane, refs, timeout)

    def submit_task(self, spec: TaskSpec):
        refs = submit_task_via_head(self.head, spec,
                                    ret_addr=self.plane.ret_addr())
        self.plane.mark_owned([r.id for r in refs])
        return refs

    def submit_actor_task(self, actor_id, spec):
        refs = submit_actor_task_via_head(
            self.head, actor_id, spec, ret_addr=self.plane.ret_addr())
        self.plane.mark_owned([r.id for r in refs])
        return refs

    def wait(self, refs, num_returns=1, timeout=None):
        return wait_refs(self.plane, refs, num_returns, timeout)

    def object_future(self, oid):
        return object_future(self.plane, oid)

    # tasks / actors
    def create_actor(self, spec: ActorCreationSpec):
        return create_actor_via_head(self.head, spec)

    def kill_actor(self, actor_id, no_restart=True):
        self.head.call("kill_actor", actor_id.hex(), no_restart)

    def lookup_named_actor(self, name, namespace):
        return ActorID.from_hex(
            self.head.call("lookup_named_actor", name,
                           namespace or "default"))

    def get_actor_state(self, actor_id):
        return actor_state_from_head(self.head, actor_id)

    def cancel(self, ref, force=False, recursive=True):
        """Cancel the task producing `ref` (reference: ray.cancel).
        Queued tasks fail immediately with TaskCancelledError; running
        tasks are interrupted only with force=True (async exception in
        the executing thread — C-blocked tasks interrupt when the call
        returns). `recursive` child cancellation is not yet honored.
        put() refs and actor-task refs raise TypeError, matching the
        reference's contract (actor calls need kill, not cancel)."""
        hint = getattr(ref, "owner_hint", None)
        if hint == "put":
            raise TypeError("ray_tpu.cancel() on a put() ref: only "
                            "task returns are cancellable")
        if hint == "actor":
            raise TypeError("ray_tpu.cancel() on an actor-task ref: "
                            "use ray_tpu.kill(actor) to interrupt "
                            "actor work")
        return self.head.call("cancel_task",
                              ref.id.task_id().hex(), force)

    # placement groups
    def create_placement_group(self, spec):
        return create_pg_via_head(self.head, spec)

    def remove_placement_group(self, pg):
        self.head.call("remove_placement_group", pg.id.hex())

    # introspection
    def _on_resources(self, version: int, snap):
        if snap:
            self._resource_view = snap
            self._resource_view_ts = time.time()

    # Serve from the pushed view only while it is demonstrably live;
    # a dead/restarting head must surface as an RPC error, not as a
    # frozen pre-outage snapshot.
    _RESOURCE_VIEW_TTL_S = 15.0

    def cluster_resources(self):
        view = self._resource_view
        if view is not None and \
                time.time() - self._resource_view_ts < \
                self._RESOURCE_VIEW_TTL_S:
            return dict(view["cluster_resources"])
        return self.head.call("cluster_resources")

    def available_resources(self):
        # Availability is a freshness query (callers assert right
        # after a reservation); the pushed view lags by up to one sync
        # period, so this one stays an RPC. The synced snapshot still
        # carries availability for monitors that prefer push.
        return self.head.call("available_resources")

    def list_actors(self):
        return self.head.call("list_actors")

    def list_tasks(self):
        return self.head.call("list_tasks")

    def list_objects(self):
        return self.head.call("list_objects")

    def list_workers(self):
        return self.head.call("list_workers")

    def list_nodes(self):
        return self.head.call("list_nodes")

    def start_log_streaming(self, sink=None):
        """Stream worker stdout/stderr records to this driver
        (log_to_driver=True). Additional calls add sinks."""
        if getattr(self, "_log_streamer", None) is None:
            from ray_tpu._private.log_streaming import DriverLogStreamer
            self._log_streamer = DriverLogStreamer(
                f"{self.head.host}:{self.head.port}", sink=sink)
        elif sink is not None:
            self._log_streamer.add_sink(sink)
        return self._log_streamer

    def shutdown(self):
        if getattr(self, "_log_streamer", None) is not None:
            self._log_streamer.stop()
        self._subscriber.stop()
        if self.node_manager is None:
            # Attached driver (connect_to_cluster): disconnecting must
            # not take the shared cluster down with it.
            return
        try:
            self.head.call("shutdown", timeout=5)
        except Exception:
            pass
        self.node_manager.stop()
