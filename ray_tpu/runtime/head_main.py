"""Head (control-plane) process entry point.

The head runs in its OWN process, like the reference's `gcs_server`
binary (spawned by services.py start_gcs_server): the driver talks to it
over RPC, so scheduler loops, dispatch senders, and pub/sub handlers
never contend with driver Python for one GIL — moving the head out of
the driver process took the single-client task benchmark from ~1.6k/s
to the PERF_r03 numbers.

Run: python -m ray_tpu.runtime.head_main --store NAME [--port P]
Prints one line "head ready address=H:P" on stdout when serving.
"""
from __future__ import annotations

import argparse
import time


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--store", required=True)
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--state-dir", default=None)
    args = ap.parse_args()

    import os

    from ray_tpu.runtime.head import HeadService
    from ray_tpu.runtime.rpc import RpcServer

    profile_out = os.environ.get("RAY_TPU_PROFILE_HEAD", "")
    if profile_out:
        # All-threads frame sampler (cProfile only sees one thread).
        import atexit
        import collections
        import sys
        import threading
        samples: collections.Counter = collections.Counter()

        def _sampler():
            while True:
                time.sleep(0.002)
                for frame in list(
                        sys._current_frames().values()):
                    f = frame
                    stack = []
                    for _ in range(3):
                        if f is None:
                            break
                        stack.append(
                            f"{f.f_code.co_filename.rsplit('/', 1)[-1]}"
                            f":{f.f_lineno}:{f.f_code.co_name}")
                        f = f.f_back
                    samples[" < ".join(stack)] += 1

        threading.Thread(target=_sampler, daemon=True).start()

        def _dump():
            with open(profile_out, "w") as fh:
                for line, n in samples.most_common(60):
                    fh.write(f"{n:8d}  {line}\n")
        atexit.register(_dump)

    service = HeadService(args.store, state_dir=args.state_dir)
    server = RpcServer(service, port=args.port)
    service._address = server.address    # job manager needs it
    print(f"head ready address={server.address}", flush=True)
    try:
        while not service._shutdown:
            time.sleep(0.1)
        time.sleep(0.3)    # let the final RPC replies flush
    except KeyboardInterrupt:
        pass
    server.stop()


if __name__ == "__main__":
    main()
