"""Proxied remote driver — the Ray Client role
(python/ray/util/client/: ray.init("ray://host:port") drives a cluster
through ONE proxy endpoint, no cluster network or shm access needed).

Server side (`ClientProxyService`, run next to the head via
`python -m ray_tpu.runtime.client_proxy --head H:P`): holds a real
driver-grade `DistributedRuntime` and executes every API op on behalf
of remote clients. Objects stay server-side; clients hold ObjectRefs
whose backing values are pinned per client session until the session
is released.

Client side (`ProxyRuntime`): the runtime installed by
`ray_tpu.init(address="ray://host:port")` — each op ships as one
authenticated RPC whose payload crosses with the framework serializer
(ObjectRefs stay symbolic; task specs carry their cloudpickled
functions exactly as the in-cluster driver path does)."""
from __future__ import annotations

import threading
import uuid
from typing import Any, Dict, List, Optional

from ray_tpu._private.ids import ActorID, JobID, ObjectID
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.object_store import ReferenceCounter
from ray_tpu._private.serialization import dumps, loads
from ray_tpu.runtime.rpc import RpcClient, RpcServer


class ClientProxyService:
    """RPC handler executing driver ops against an in-cluster runtime."""

    def __init__(self, runtime):
        self.rt = runtime
        self._lock = threading.Lock()
        # session id -> OrderedDict{ref hex -> ObjectRef}: pins keep
        # the server-side GC from collecting values a remote client
        # still references. Bounded per session (oldest pins drop
        # first — an evicted-then-needed object comes back via lineage
        # reconstruction) and reaped whole when a session goes silent
        # (crashed client with no release_session).
        import collections
        self._sessions: Dict[str, "collections.OrderedDict"] = {}
        self._last_seen: Dict[str, float] = {}
        self.max_pins_per_session = 100_000
        self.session_ttl_s = 600.0

    # -- plumbing ------------------------------------------------------

    def _pin(self, session: str, refs) -> None:
        import collections
        with self._lock:
            pins = self._sessions.setdefault(
                session, collections.OrderedDict())
            one = [refs] if isinstance(refs, ObjectRef) else refs
            for r in one:
                if isinstance(r, ObjectRef):
                    pins[r.id.hex()] = r
            while len(pins) > self.max_pins_per_session:
                pins.popitem(last=False)

    def _touch(self, session: str) -> None:
        import time
        now = time.time()
        with self._lock:
            self._last_seen[session] = now
            dead = [s for s, t in self._last_seen.items()
                    if now - t > self.session_ttl_s]
            for s in dead:
                self._sessions.pop(s, None)
                self._last_seen.pop(s, None)

    def proxy(self, session: str, op: str, blob: bytes) -> bytes:
        """One driver op: blob = serialized (args, kwargs); returns
        serialized ("ok", result) / ("err", exception)."""
        try:
            self._touch(session)
            args, kwargs = loads(blob)
            result = getattr(self, "_op_" + op)(session, *args,
                                                **kwargs)
            return dumps(("ok", result))
        except BaseException as e:   # noqa: BLE001
            try:
                return dumps(("err", e))
            except Exception:        # unpicklable exception
                return dumps(("err", RuntimeError(repr(e))))

    def release_session(self, session: str) -> int:
        with self._lock:
            pins = self._sessions.pop(session, {})
            self._last_seen.pop(session, None)
        return len(pins)

    # -- ops -----------------------------------------------------------

    def _op_put(self, session, value):
        ref = self.rt.put(value)
        self._pin(session, ref)
        return ref

    def _op_get(self, session, refs, timeout=None):
        return self.rt.get(refs, timeout=timeout)

    def _op_wait(self, session, refs, num_returns=1, timeout=None):
        return self.rt.wait(refs, num_returns=num_returns,
                            timeout=timeout)

    def _op_submit_task(self, session, spec):
        refs = self.rt.submit_task(spec)
        self._pin(session, refs)
        return refs

    def _op_create_actor(self, session, spec):
        return self.rt.create_actor(spec)

    def _op_submit_actor_task(self, session, actor_id, spec):
        refs = self.rt.submit_actor_task(actor_id, spec)
        self._pin(session, refs)
        return refs

    def _op_kill_actor(self, session, actor_id, no_restart=True):
        return self.rt.kill_actor(actor_id, no_restart=no_restart)

    def _op_lookup_named_actor(self, session, name, namespace):
        return self.rt.lookup_named_actor(name, namespace)

    def _op_get_actor_state(self, session, actor_id):
        return self.rt.get_actor_state(actor_id)

    def _op_cancel(self, session, ref, force=False, recursive=True):
        return self.rt.cancel(ref, force=force, recursive=recursive)

    def _op_create_placement_group(self, session, spec):
        # ship only the created flag: the server-side PG object holds
        # sockets/locks; the client builds its own handle from the spec
        pg = self.rt.create_placement_group(spec)
        return pg.is_ready()

    def _op_pg_wait(self, session, spec, timeout_seconds):
        pg = self.rt.create_placement_group(spec)   # idempotent
        return pg.wait(timeout_seconds)

    def _op_remove_placement_group(self, session, pg_id_hex):
        return self.rt.head.call("remove_placement_group", pg_id_hex)

    def _op_cluster_resources(self, session):
        return self.rt.cluster_resources()

    def _op_available_resources(self, session):
        return self.rt.available_resources()

    def _op_list_actors(self, session):
        return self.rt.list_actors()

    def _op_list_tasks(self, session):
        return self.rt.list_tasks()

    def _op_list_objects(self, session):
        return self.rt.list_objects()

    def _op_list_workers(self, session):
        return self.rt.list_workers()

    def _op_list_nodes(self, session):
        return self.rt.list_nodes()




class ProxyPlacementGroup:
    """Client-side placement-group handle (same surface as the
    in-cluster DistPlacementGroup, but proxy-backed: the spec is plain
    data, readiness queries go through the proxy)."""

    def __init__(self, spec, runtime: "ProxyRuntime", created: bool):
        self.spec = spec
        self._rt = runtime
        self._created = created

    @property
    def id(self):
        return self.spec.pg_id

    @property
    def bundle_specs(self):
        return [dict(b.resources) for b in self.spec.bundles]

    def is_ready(self) -> bool:
        return self._created

    def wait(self, timeout_seconds: float = 30) -> bool:
        if not self._created:
            self._created = self._rt._call("pg_wait", self.spec,
                                           timeout_seconds)
        return self._created

    def ready(self) -> ObjectRef:
        """Proxied semantics: waits for readiness, then returns a ref
        to a plain readiness record (the in-cluster variant resolves
        to the pg object itself; this handle holds sockets and cannot
        cross the wire)."""
        ok = self.wait(300)
        return self._rt.put({"pg_id": self.spec.pg_id.hex(),
                             "ready": ok})


class ProxyRuntime:
    """Client-side runtime: every op is one RPC to the proxy."""

    def __init__(self, address: str):
        self.address = address
        self.client = RpcClient(address, timeout=None)
        self.session = uuid.uuid4().hex
        # Remote refs are symbolic on this side; no local ref counting.
        self.ref_counter = ReferenceCounter()
        self.ref_counter.enabled = False
        self.job_id = JobID.next()
        self._actor_handles: Dict[Any, Any] = {}

    def _call(self, op: str, *args, **kwargs):
        blob = dumps((args, kwargs))
        status, value = loads(
            self.client.call("proxy", self.session, op, blob))
        if status == "err":
            raise value
        return value

    # -- objects -------------------------------------------------------
    def put(self, value):
        return self._call("put", value)

    def get(self, refs, timeout=None):
        return self._call("get", refs, timeout=timeout)

    def wait(self, refs, num_returns=1, timeout=None):
        return self._call("wait", refs, num_returns=num_returns,
                          timeout=timeout)

    def object_future(self, oid: ObjectID):
        from concurrent.futures import Future
        f: Future = Future()

        def _wait():
            try:
                v = self._call("get", ObjectRef(oid))
            except BaseException as e:   # noqa: BLE001
                if f.set_running_or_notify_cancel():
                    f.set_exception(e)
                return
            if f.set_running_or_notify_cancel():
                f.set_result(v)
        threading.Thread(target=_wait, daemon=True).start()
        return f

    # -- tasks / actors ------------------------------------------------
    def submit_task(self, spec):
        return self._call("submit_task", spec)

    def create_actor(self, spec):
        return self._call("create_actor", spec)

    def submit_actor_task(self, actor_id, spec):
        return self._call("submit_actor_task", actor_id, spec)

    def kill_actor(self, actor_id, no_restart=True):
        return self._call("kill_actor", actor_id,
                          no_restart=no_restart)

    def lookup_named_actor(self, name, namespace):
        return self._call("lookup_named_actor", name, namespace)

    def get_actor_state(self, actor_id):
        return self._call("get_actor_state", actor_id)

    def cancel(self, ref, force=False, recursive=True):
        return self._call("cancel", ref, force=force,
                          recursive=recursive)

    # -- placement groups ---------------------------------------------
    def create_placement_group(self, spec):
        created = self._call("create_placement_group", spec)
        return ProxyPlacementGroup(spec, self, created)

    def remove_placement_group(self, pg):
        return self._call("remove_placement_group", pg.id.hex())

    # -- state ---------------------------------------------------------
    def cluster_resources(self):
        return self._call("cluster_resources")

    def available_resources(self):
        return self._call("available_resources")

    def list_actors(self):
        return self._call("list_actors")

    def list_tasks(self):
        return self._call("list_tasks")

    def list_objects(self):
        return self._call("list_objects")

    def list_workers(self):
        return self._call("list_workers")

    def list_nodes(self):
        return self._call("list_nodes")

    def start_log_streaming(self, sink=None):
        pass     # logs stay cluster-side for proxied drivers (v1)

    def shutdown(self):
        try:
            self.client.call("release_session", self.session,
                             timeout=5)
        except Exception:
            pass
        self.client.close()


def start_proxy(head_address: str, port: int = 0):
    """Run a proxy endpoint next to the head; returns (server, runtime).
    The proxy machine needs head + shm access (it IS the in-cluster
    driver for its clients)."""
    from ray_tpu.runtime.client import DistributedRuntime
    info = RpcClient(head_address, timeout=30).call("cluster_info")
    rt = DistributedRuntime(head_address, info["store_name"])
    server = RpcServer(ClientProxyService(rt), port=port)
    return server, rt


def serve_forever(head_address: str, port: int = 10001,
                  echo=print) -> None:
    """Run a proxy endpoint until interrupted (shared by the module
    entry point and the CLI `client-proxy` command)."""
    import time
    server, _rt = start_proxy(head_address, port)
    echo(f"client proxy ready on ray://{server.address}")
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        server.stop()


def main():
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--head", required=True)
    ap.add_argument("--port", type=int, default=10001)
    args = ap.parse_args()
    serve_forever(args.head, args.port)


if __name__ == "__main__":
    main()
