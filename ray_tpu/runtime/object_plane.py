"""Multi-node object plane: per-node chunked transfer + location-aware get.

Role parity with the reference's object layer — node-to-node transfer
(ObjectManager chunked push/pull, src/ray/object_manager/object_manager.h:114,
push_manager.h:29), location lookup (ownership_based_object_directory.cc),
and the pull retry machinery (pull_manager.h:47). TPU-first deltas: each
node's C++ shm store is the single local tier, the location directory is
centralized in the head (which also drives lineage reconstruction when
every replica died), and transfer is puller-driven chunked reads over the
framed-socket RPC layer — no standalone object-manager daemon.

Pieces:
- ObjectService: served inside each node's manager process; chunked
  zero-copy reads out of that node's shm store.
- ObjectPlane: what workers/drivers hold instead of a bare store —
  local store fast path, head-directed remote pull on miss, batched
  async location registration for puts.
"""
from __future__ import annotations

import collections
import threading
import time
import weakref
from typing import Dict, List, Optional

from ray_tpu._private.ids import ObjectID
from ray_tpu.runtime.rpc import RpcClient, RpcError

# Swept on the 1-core rig (see round-4 notes): 16MB chunks x 2 lanes
# beat 4MB x 3 (0.94 vs 0.61 GB/s raw) — per-chunk RPC overhead
# dominates below 16MB, and with one host core extra lanes just add
# GIL churn.
CHUNK = 16 * 1024 * 1024
# Owned objects at or below this stay in the owner's process memory
# (reference: memory_store.h:43 in-process store +
# ray_config_def.h:181 100KiB inline threshold) until something needs
# them cross-process (promotion happens when their ref is pickled).
INLINE_THRESHOLD = 100 * 1024
_MEMORY_TIER_BUDGET = 64 * 1024 * 1024
# Streamed-pull knobs: parallel chunk streams per pull and a process-
# wide cap on in-flight pulled bytes (reference: push_manager.h:29
# rate-limited chunked transfer, pull_manager.h:47 admission).
PULL_STREAMS = 2
_INFLIGHT_PULL_BYTES = 128 * 1024 * 1024

# Sentinel: remote copies exist but every replica is at its pull-slot
# budget (head admission) — back off instead of spinning.
_SOURCES_BUSY = object()


class _MemoryTier:
    """Per-process LRU of small OWNED objects. Overflow does not drop:
    the coldest entry is promoted to shm (other processes may later
    borrow a ref), so the tier is a pure fast path, never a lifetime
    hazard."""

    def __init__(self, budget: int = _MEMORY_TIER_BUDGET):
        self._d: "collections.OrderedDict[ObjectID, bytes]" = \
            collections.OrderedDict()
        self._bytes = 0
        self.budget = budget
        self._lock = threading.Lock()

    def put(self, oid: ObjectID, data: bytes):
        evicted = []
        with self._lock:
            old = self._d.pop(oid, None)
            if old is not None:
                self._bytes -= len(old)
            self._d[oid] = data
            self._bytes += len(data)
            while self._bytes > self.budget and len(self._d) > 1:
                k, v = self._d.popitem(last=False)
                self._bytes -= len(v)
                evicted.append((k, v))
        return evicted      # caller promotes these to shm

    def get(self, oid: ObjectID) -> Optional[bytes]:
        with self._lock:
            data = self._d.get(oid)
            if data is not None:
                self._d.move_to_end(oid)
            return data

    def pop(self, oid: ObjectID) -> Optional[bytes]:
        with self._lock:
            data = self._d.pop(oid, None)
            if data is not None:
                self._bytes -= len(data)
            return data

    def __contains__(self, oid: ObjectID) -> bool:
        with self._lock:
            return oid in self._d


# Every live plane in this process. Promotion-on-pickle must reach the
# plane that OWNS the object — which is not always the global worker's
# runtime (e.g. the client-proxy server holds its own DistributedRuntime
# while the process-global runtime is the proxy client).
_ALL_PLANES: "weakref.WeakSet" = weakref.WeakSet()


def promote_everywhere(oid: ObjectID) -> None:
    """Called when a ref is pickled: whichever plane owns the object
    moves it out of its memory tier and pins it against eager free."""
    for plane in list(_ALL_PLANES):
        plane.promote(oid)


def prewarm_transfer_path(store, self_addr: str) -> None:
    """Background-warm this node's transfer path at startup.

    On shared/virtualized hosts a process's FIRST bulk receive runs
    ~13x slower than steady state (measured 0.15 vs 2.0 GB/s for the
    identical pull — fresh sockets, fresh arena pages, and host-level
    per-process bandwidth shaping all warm with traffic). The transfer
    daemon pays that cost ONCE here, against scratch data, off the
    critical path — so the first real broadcast hits a warm node.
    Sized to the store (never more than 1/8 of capacity) and skipped
    for tiny test stores."""
    from ray_tpu._private.config import GlobalConfig
    try:
        # src + dst scratch together stay within 1/8 of the store.
        cap_mb = int(store.stats()["capacity"] // (16 << 20))
    except Exception:
        cap_mb = 64
    mb = min(GlobalConfig.transfer_prewarm_mb, cap_mb)
    if mb < 16:
        return

    def _warm():
        src = ObjectID.from_random()
        dst = ObjectID.from_random()
        n = mb << 20
        try:
            sview = store.create_for_write(src, n)
            if sview is None:
                return
            one_mb = b"\1" * (1 << 20)       # reusable 1MB pattern
            for off in range(0, n, 1 << 20):
                sview[off:off + (1 << 20)] = one_mb
            sview.release()
            store.seal_raw(src)
            view = store.create_for_write(dst, n)
            if view is None:
                store.delete(src)
                return
            client = RpcClient(self_addr, timeout=60)
            try:
                for off in range(0, n, CHUNK):
                    c = min(CHUNK, n - off)
                    client.call_into("raw_pull_chunk", src.hex(), off,
                                     c, dest=view[off:off + c])
            finally:
                view.release()
                client.close()
            store.abort_raw(dst)
            store.delete(src)
        except Exception:
            for oid in (src, dst):
                try:
                    store.delete(oid)
                except Exception:
                    pass

    threading.Thread(target=_warm, daemon=True,
                     name="transfer-prewarm").start()


class ObjectService:
    """Per-node RPC endpoint exposing the local shm store to peers.

    With a plane attached it is also this node's TRANSFER DAEMON:
    workers delegate remote fetches here (fetch_object) instead of
    pulling themselves — the reference's split exactly (the per-node
    ObjectManager daemon performs transfers, object_manager.h:114;
    workers only read the local store). One long-lived process does
    every bulk receive, so per-process transfer warmup (sockets,
    arena pages, host bandwidth shaping) is paid once per node, not
    once per worker."""

    def __init__(self, store, plane: "ObjectPlane" = None):
        self.store = store
        self.plane = plane
        self._fetch_lock = threading.Lock()
        self._fetching: Dict[ObjectID, threading.Event] = {}

    def fetch_object(self, oid_hex: str, reconstruct: bool = False) -> str:
        """Pull a remote object into this node's store. Returns:
        "ok"    — object is now locally readable;
        "busy"  — replicas exist but transfer slots are saturated
                  (caller backs off and retries);
        "miss"  — no known copy (caller keeps its producer-wait loop).
        Concurrent fetches of one object coalesce into a single pull.
        """
        if self.plane is None:
            return "miss"
        # A delegated fetch only happens on multinode clusters; the
        # service plane has no pub/sub feed, so flip the flag here
        # (it gates the pulled copy's location registration).
        self.plane.multinode = True
        oid = ObjectID.from_hex(oid_hex)
        if self.store.contains(oid):
            return "ok"
        while True:
            with self._fetch_lock:
                ev = self._fetching.get(oid)
                if ev is None:
                    ev = self._fetching[oid] = threading.Event()
                    leader = True
                else:
                    leader = False
            if not leader:
                ev.wait(timeout=600)
                if self.store.contains(oid):
                    return "ok"
                # Leader failed (busy/miss): take over on the retry.
                continue
            try:
                r = self.plane._try_remote_fetch(
                    oid, reconstruct=reconstruct, want_data=False)
            finally:
                with self._fetch_lock:
                    self._fetching.pop(oid, None)
                ev.set()
            if r is _SOURCES_BUSY:
                return "busy"
            return "ok" if r is not None else "miss"

    def push_object(self, oid_hex: str, data) -> None:
        """Owner-directed push: a worker on another node delivers a
        small task return straight into the CALLER's node store (the
        reference's owner-direct return path — the caller receives
        values without a locate/pull dance). Idempotent: a duplicate
        push of a sealed object is a no-op."""
        oid = ObjectID.from_hex(oid_hex)
        try:
            self.store.put_bytes(oid, bytes(data))
        except Exception:
            return          # already present (raced with a pull): fine
        if self.plane is not None and \
                getattr(self.plane, "multinode", False):
            self.plane._register_async(oid_hex)

    def has_object(self, oid_hex: str) -> bool:
        return self.store.contains(ObjectID.from_hex(oid_hex))

    def object_size(self, oid_hex: str) -> int:
        """Size in bytes, or -1 if absent."""
        oid = ObjectID.from_hex(oid_hex)
        try:
            view = self.store.get_view(oid, timeout_ms=0)
        except Exception:
            # Spilled objects still serve (restore-on-read).
            try:
                data = self.store.get_bytes(oid, timeout_ms=0)
            except Exception:
                return -1
            return len(data)
        try:
            return len(view)
        finally:
            self.store.release(oid)

    def pull_chunk(self, oid_hex: str, offset: int, length: int) -> bytes:
        oid = ObjectID.from_hex(oid_hex)
        try:
            view = self.store.get_view(oid, timeout_ms=0)
        except Exception:
            data = self.store.get_bytes(oid, timeout_ms=0)
            return bytes(data[offset:offset + length])
        try:
            return bytes(view[offset:offset + length])
        finally:
            self.store.release(oid)

    def raw_pull_chunk(self, oid_hex: str, offset: int, length: int):
        """Raw-framed chunk read: returns (view-slice, release) so the
        RPC server sends the bytes STRAIGHT out of the shm mapping —
        the hot transfer path has zero server-side copies (the pinned
        object is released after the send completes)."""
        oid = ObjectID.from_hex(oid_hex)
        try:
            view = self.store.get_view(oid, timeout_ms=0)
        except Exception:
            data = self.store.get_bytes(oid, timeout_ms=0)
            return memoryview(data)[offset:offset + length]
        return (memoryview(view)[offset:offset + length],
                lambda: self.store.release(oid))


class ObjectPlane:
    """Location-aware object access for one process.

    Single-node clusters never touch the head: the `multinode` flag
    only flips on when a second node registers (pushed over the `nodes`
    pub/sub channel), so the fast path stays one shm call.
    """

    def __init__(self, store, head: RpcClient, node_id: str = "head",
                 is_node_service: bool = False):
        self.store = store
        self.head = head
        self.node_id = node_id
        self.multinode = False
        # The node-service plane (inside the agent's transfer daemon)
        # performs pulls itself; every other plane on the node
        # delegates bulk fetches to it (ObjectService.fetch_object).
        self.is_node_service = is_node_service
        self._self_service_addr: Optional[str] = None
        self._self_resolve_at = 0.0
        self._fetch_client: Optional[RpcClient] = None
        self.memory = _MemoryTier()
        # Eager local GC bookkeeping: `owned` = put by THIS process via
        # the put() API; `escaped` = the ref was pickled at least once
        # (another process may hold it). Zero-ref release deletes the
        # local copy only for owned-and-never-escaped objects — that is
        # provably safe without a cross-process borrow protocol, and it
        # is the overwhelmingly common put-use-drop pattern.
        self._owned: set = set()
        self._escaped: set = set()
        self._escape_ts: Dict[ObjectID, float] = {}
        # Refs THIS process borrowed (deserialized from another
        # owner): registered with the head so escaped objects free on
        # last-borrow-drop instead of lingering under LRU (the
        # head-brokered borrower protocol, head.py add_borrows).
        self._borrowed: set = set()
        self._own_lock = threading.Lock()
        self._pull_sem = threading.BoundedSemaphore(
            max(1, _INFLIGHT_PULL_BYTES // CHUNK))
        self._peers: Dict[str, RpcClient] = {}
        self._peers_lock = threading.Lock()
        # Batched async put registration + owner-driven frees +
        # borrower-protocol traffic.
        self._pending_reg: List[str] = []
        self._pending_free: List[str] = []
        self._pending_borrow: List[str] = []
        self._pending_borrow_drop: List[str] = []
        self._pending_owner_released: List = []
        self._reg_lock = threading.Lock()
        self._reg_wake = threading.Event()
        self._reg_thread: Optional[threading.Thread] = None
        # Zero-ref releases land here from ObjectRef.__del__ (possibly
        # inside a GC pause): deque.append is atomic, so the finalizer
        # never takes a lock — the flusher thread does the actual free
        # (an inline free could self-deadlock on _own_lock if GC fired
        # under it).
        self._release_q: "collections.deque" = collections.deque()
        _ALL_PLANES.add(self)
        # The flusher starts NOW so the zero-lock release_owned never
        # has to (thread creation takes locks a finalizer must avoid).
        self._ensure_reg_thread()

    # ---- membership -------------------------------------------------------

    def on_nodes_update(self, version: int, nodes) -> None:
        """Subscriber callback for the `nodes` state channel."""
        alive = [n for n in (nodes or []) if n.get("alive", True)]
        self.multinode = len(alive) > 1
        for n in alive:
            if n.get("node_id") == self.node_id and \
                    n.get("object_addr"):
                self._self_service_addr = n["object_addr"]

    def refresh_multinode(self) -> None:
        try:
            self.multinode = self.head.call("node_count") > 1
        except Exception:
            pass

    # ---- put --------------------------------------------------------------

    def put_bytes(self, oid: ObjectID, data: bytes) -> None:
        self.store.put_bytes(oid, data)
        if self.multinode:
            self._register_async(oid.hex())

    def put_obj(self, oid: ObjectID, value, owned: bool = False):
        """Serialize + store. Small OWNED objects stay in this
        process's memory tier — no shm create/seal, no location
        registration — until promote() moves them out. Large objects
        stream their serialized parts straight into shm (one copy)."""
        from ray_tpu._private.serialization import serialize_parts
        if self._release_q:
            # Safe-context wake (we are NOT in a finalizer here): put
            # churn must not outrun the 1s-poll free flusher, or the
            # store fills with dead objects and starts spilling.
            self._reg_wake.set()
        parts, total, _ = serialize_parts(value)
        if owned:
            with self._own_lock:
                self._owned.add(oid)
        if owned and total <= INLINE_THRESHOLD:
            blob = b"".join(bytes(p) if isinstance(p, memoryview)
                            else p for p in parts)
            for k, v in self.memory.put(oid, blob):
                self._promote_blob(k, v)
            return
        self.put_serialized(oid, parts, total)

    def put_serialized(self, oid: ObjectID, parts, total: int) -> None:
        """Store pre-serialized parts (single copy into shm) +
        register. The one shared implementation for put_obj's store
        path and the worker's owner-direct return writes."""
        if self._release_q:
            self._reg_wake.set()     # put churn must drain frees too
        self.store.put_parts(oid, parts, total)
        if self.multinode:
            self._register_async(oid.hex())

    def promote(self, oid: ObjectID) -> None:
        """The object's ref got pickled (it is escaping this process):
        move it out of the memory tier into shm, and pin it against
        eager release — an external holder may now exist. No-op for
        objects this plane doesn't own (borrowed refs re-pickled here),
        which also keeps the escape set bounded by owned objects."""
        with self._own_lock:
            if oid not in self._owned:
                return
            self._escaped.add(oid)
            self._escape_ts[oid] = time.time()
        data = self.memory.pop(oid)
        if data is not None:
            self._promote_blob(oid, data)

    def mark_owned(self, oids) -> None:
        """Claim ownership of task-return objects at submission: the
        caller is their owner, so dropping its last ref eagerly frees
        the local copy (return ids travel as raw bytes inside specs,
        never as pickled refs, so they can't self-escape)."""
        with self._own_lock:
            self._owned.update(oids)

    def note_borrow(self, oid: ObjectID) -> None:
        """A ref owned ELSEWHERE was deserialized in this process:
        register the borrow with the head (batched). Called from
        ObjectRef creation via the borrow-notifier hook. (Also on
        single-node clusters — the owner may be another process on
        this node.)"""
        with self._own_lock:
            if oid in self._owned or oid in self._borrowed:
                return          # own object, or borrow already noted
            self._borrowed.add(oid)
        with self._reg_lock:
            self._pending_borrow.append(oid.hex())
        self._ensure_reg_thread()
        self._reg_wake.set()

    def drop_borrow(self, oid: ObjectID) -> None:
        """Explicitly drop a borrow registered via ``note_borrow`` for
        an id whose lifetime rides a COMPANION object's release —
        device-object payload borrows (mesh/device_objects) drop when
        the main ref's release drains, not from their own finalizer."""
        with self._own_lock:
            self._borrowed.discard(oid)
        with self._reg_lock:
            self._pending_borrow_drop.append(oid.hex())
        self._ensure_reg_thread()
        self._reg_wake.set()

    def release_owned(self, oid: ObjectID) -> None:
        """Zero-ref notification (called from ObjectRef.__del__, which
        can run inside a GC pause): deque.append ONLY — it is atomic
        and takes no lock, so a finalizer firing on a thread that
        already holds any plane lock (even the Event's internal one)
        cannot self-deadlock. The flusher polls at 1s, so a free is
        delayed at most a second; hot paths (put churn) wake it via
        their own registration traffic."""
        self._release_q.append(oid)

    def _ensure_reg_thread(self):
        with self._reg_lock:
            if self._reg_thread is None or \
                    not self._reg_thread.is_alive():
                self._reg_thread = threading.Thread(
                    target=self._reg_loop, daemon=True,
                    name="objplane-register")
                self._reg_thread.start()

    def _drain_releases(self):
        """Eagerly drop local copies of owned objects whose refs never
        escaped (reference: owner-based object lifetime,
        reference_count.h — the full borrower protocol is unnecessary
        for never-borrowed objects). Escaped objects stay for
        LRU/spill to manage, and their bookkeeping is dropped here so
        the owned/escaped sets stay bounded by LIVE refs."""
        while True:
            try:
                oid = self._release_q.popleft()
            except IndexError:
                return
            borrow_dropped = False
            with self._own_lock:
                not_owned = oid not in self._owned
                if not_owned:
                    if oid in self._borrowed:
                        # Last local ref of a BORROWED object: tell
                        # the owner-side protocol (batched).
                        self._borrowed.discard(oid)
                        with self._reg_lock:
                            self._pending_borrow_drop.append(oid.hex())
                        borrow_dropped = True
            if not_owned:
                if borrow_dropped:
                    # Outside _own_lock: the device-object layer may
                    # re-enter the plane to drop a payload borrow.
                    self._device_borrow_released(oid)
                continue
            with self._own_lock:
                self._owned.discard(oid)
                escaped = oid in self._escaped
                esc_age = None
                if escaped:
                    self._escaped.discard(oid)
                    esc_age = time.time() -                         self._escape_ts.pop(oid, time.time())
            self._device_released(oid, escaped)
            if escaped:
                # External holders may exist: keep the object for now
                # and hand lifetime to the head's borrower protocol —
                # it frees the copies once every registered borrow
                # drops (plus a grace window for in-flight handoffs).
                with self._reg_lock:
                    self._pending_owner_released.append(
                        (oid.hex(), esc_age))
                continue
            was_inline = self.memory.pop(oid) is not None
            try:
                self.store.delete(oid)
            except Exception:
                pass    # spilled-only, already evicted, not in shm
            if self.multinode and not was_inline:
                # Remote copies (task ran on a peer node, or neighbors
                # cached a pull) free eagerly too — the head
                # broadcasts the delete to every node agent. Inline
                # objects never left this process: no broadcast.
                with self._reg_lock:
                    self._pending_free.append(oid.hex())

    def _device_released(self, oid: ObjectID, escaped: bool) -> None:
        """Free the HBM pin of a released device object (and, for
        never-escaped ones, any manually-spilled host payload). Guarded
        by sys.modules so jax-free processes skip the import."""
        import sys
        if "ray_tpu.mesh.device_objects" not in sys.modules:
            return
        try:
            from ray_tpu.mesh.device_objects import on_ref_released
            on_ref_released(oid, self, escaped=escaped)
        except Exception:
            pass

    def _device_borrow_released(self, oid: ObjectID) -> None:
        """Borrower-side companion of ``_device_released``: this
        process's last ref to a BORROWED object dropped. If it was a
        device object resolved here, the payload borrow registered at
        resolve time drops with it (head frees the owner's host spill
        on last-borrow-drop). Same sys.modules guard: jax-free
        processes never borrowed a device object."""
        import sys
        if "ray_tpu.mesh.device_objects" not in sys.modules:
            return
        try:
            from ray_tpu.mesh.device_objects import on_borrow_released
            on_borrow_released(oid, self)
        except Exception:
            pass

    def _promote_blob(self, oid: ObjectID, data: bytes) -> None:
        try:
            self.store.put_bytes(oid, data)
        except Exception:
            return   # already there (concurrent promote): fine
        if self.multinode:
            self._register_async(oid.hex())

    def _register_async(self, oid_hex: str) -> None:
        with self._reg_lock:
            self._pending_reg.append(oid_hex)
        self._ensure_reg_thread()
        self._reg_wake.set()

    def _reg_loop(self):
        while True:
            self._reg_wake.wait(timeout=1.0)
            self._reg_wake.clear()
            self._drain_releases()
            with self._reg_lock:
                batch, self._pending_reg = self._pending_reg, []
                # Bound each free RPC: dropping a million refs at once
                # (deep-queue churn) must not serialize into one giant
                # frame that stalls the head for seconds.
                frees = self._pending_free[:20000]
                del self._pending_free[:20000]
                borrows, self._pending_borrow = \
                    self._pending_borrow, []
                drops, self._pending_borrow_drop = \
                    self._pending_borrow_drop, []
                released, self._pending_owner_released = \
                    self._pending_owner_released, []
            if batch:
                try:
                    self.head.call("register_objects", self.node_id,
                                   batch)
                except Exception:
                    pass    # locate falls back to probing nodes
            if frees:
                try:
                    self.head.call("free_objects", frees)
                except Exception:
                    pass    # LRU/spill still bounds remote copies
                with self._reg_lock:
                    if self._pending_free:
                        self._reg_wake.set()    # keep draining
            if borrows:
                try:
                    self.head.call("add_borrows", borrows,
                                   self.node_id)
                except Exception:
                    pass    # worst case: LRU bounds the object
            if drops:
                try:
                    self.head.call("drop_borrows", drops,
                                   self.node_id)
                except Exception:
                    pass
            if released:
                try:
                    self.head.call("owner_released", released)
                except Exception:
                    pass

    def flush_registrations(self) -> None:
        with self._reg_lock:
            batch, self._pending_reg = self._pending_reg, []
        if batch:
            self.head.call("register_objects", self.node_id, batch)

    # ---- get --------------------------------------------------------------

    def contains(self, oid: ObjectID) -> bool:
        if oid in self.memory or self.store.contains(oid):
            return True
        if not self.multinode:
            return False
        try:
            return bool(self.head.call("locate_object", oid.hex()))
        except Exception:
            return False

    def get_bytes(self, oid: ObjectID, timeout_ms: int = -1) -> bytes:
        """Heap-copy read (callers that mutate or outlive the store)."""
        return self._get(oid, timeout_ms, self.store.get_bytes)

    def get_blob(self, oid: ObjectID, timeout_ms: int = -1):
        """Zero-copy read: large shm objects come back as read-only
        pinned views (shm_store.get_blob); small ones as bytes."""
        return self._get(oid, timeout_ms, self.store.get_blob)

    def _get(self, oid: ObjectID, timeout_ms: int, read):
        from ray_tpu._private.shm_store import ShmTimeout
        data = self.memory.get(oid)
        if data is not None:
            return data
        if not self.multinode:
            return read(oid, timeout_ms=timeout_ms)
        deadline = None if timeout_ms < 0 else \
            time.time() + timeout_ms / 1000.0
        # Grace period before asking the head to rebuild lost objects:
        # normal pipelines have objects appearing as tasks finish.
        reconstruct_after = time.time() + 1.0
        # Short local waits first: an object completing on a PEER node
        # never seals locally, so blocking 100 ms before the first
        # location lookup would serialize remote-result gets at 10/s.
        local_wait = 2
        while True:
            wait = local_wait
            if deadline is not None:
                rem = int((deadline - time.time()) * 1000)
                if rem <= 0:
                    # Deadline hit: one zero-wait local attempt so an
                    # object that IS here isn't reported as a timeout.
                    return read(oid, timeout_ms=0)
                wait = min(wait, max(rem, 1))
            try:
                return read(oid, timeout_ms=wait)
            except ShmTimeout:
                pass
            data = self._try_remote_fetch(
                oid, reconstruct=time.time() > reconstruct_after)
            if data is _SOURCES_BUSY:
                # Peers hold the object but every replica is serving
                # its slot budget: wait a long beat (blocking on the
                # local store, where the object may appear anyway).
                # Aggressive re-polling here steals the very CPU the
                # in-flight transfers need on a contended host.
                local_wait = 300
                continue
            if data is not None and isinstance(data, memoryview) and \
                    read == self.store.get_bytes:
                # get_bytes contract: remote pulls of big objects come
                # back pinned; copy out for the bytes-typed API.
                data = bytes(data)
            if data is not None:
                return data
            local_wait = min(local_wait * 2, 100)

    def prefetch(self, oids) -> None:
        """Batch-pull any of `oids` that live only on peer nodes into
        the local store (one locate RPC for the whole batch). Misses
        are fine — the caller's per-object get loop handles them."""
        if not self.multinode:
            return
        missing = [o for o in oids
                   if o not in self.memory and not self.store.contains(o)]
        if not missing:
            return
        try:
            locs = self.head.call("locate_objects",
                                  [o.hex() for o in missing])
        except Exception:
            return
        for oid in missing:
            loc_list = locs.get(oid.hex()) or []
            for loc in loc_list:
                if loc["node_id"] == self.node_id:
                    continue
                if self._pull(oid, loc, want_bytes=False) is not None:
                    break     # _pull cached it into the local store

    def ret_addr(self) -> Optional[str]:
        """This node's object-service address (None off-multinode or
        while unresolved). Shipped with task specs so remote workers
        can push small returns straight to the caller's node; lookups
        are bounded to one head RPC per 5s while unresolved."""
        if not self.multinode:
            return None
        return self._resolved_self_addr()

    def _resolved_self_addr(self) -> Optional[str]:
        addr = self._self_service_addr
        if addr is None:
            now = time.time()
            if now >= self._self_resolve_at:
                self._self_resolve_at = now + 5.0   # bound lookups
                addr = self._resolve_self_service()
        return addr

    def _delegate_bulk_fetch(self, oid: ObjectID, reconstruct: bool):
        """Route one bulk fetch through the node's transfer daemon.
        Returns "ok"/"busy"/"miss", or None when no daemon is usable
        (caller pulls directly)."""
        if self.is_node_service:
            return None
        addr = self._resolved_self_addr()
        if addr is None:
            return None
        client = self._fetch_client
        if client is None or \
                f"{client.host}:{client.port}" != addr:
            client = self._fetch_client = RpcClient(addr, timeout=600)
        try:
            return client.call("fetch_object", oid.hex(),
                               reconstruct=reconstruct)
        except Exception:
            return None    # daemon unreachable: pull directly

    def _resolve_self_service(self) -> Optional[str]:
        try:
            for n in self.head.call("list_nodes"):
                if n.get("node_id") == self.node_id and \
                        n.get("alive", True):
                    self._self_service_addr = n.get("object_addr")
                    return self._self_service_addr
        except Exception:
            pass
        return None

    def _try_remote_fetch(self, oid: ObjectID, reconstruct: bool,
                          want_data: bool = True):
        from ray_tpu._private.config import GlobalConfig
        try:
            locs = self.head.call("locate_object", oid.hex(),
                                  probe=True, reconstruct=reconstruct)
        except Exception:
            return None
        peers = [l for l in locs if l["node_id"] != self.node_id]
        if not peers:
            return None
        import random
        random.shuffle(peers)
        # One size probe decides the tier: small pulls run unthrottled
        # (replica shuffle alone spreads them); bulk pulls go through
        # head slot admission so a broadcast disseminates as a
        # doubling tree and concurrent transfers stay within the
        # host's effective memory bandwidth (begin_pull docstring).
        size = -1
        for loc in peers:
            try:
                size = self._peer(loc["object_addr"]).call(
                    "object_size", oid.hex())
            except Exception:
                continue
            if size >= 0:
                break
        if size < 0:
            return None
        if size < GlobalConfig.bulk_pull_threshold_bytes:
            for loc in peers:
                data = self._pull(oid, loc, want_bytes=want_data,
                                  known_size=size)
                if data is not None:
                    return data
                size = -1     # stale probe: let _pull re-query
            return None
        # Bulk tier: hand the transfer to the node's warm daemon when
        # one exists; otherwise pull here under head admission.
        r = self._delegate_bulk_fetch(oid, reconstruct)
        if r == "busy":
            return _SOURCES_BUSY
        if r == "ok":
            try:
                got = self.store.get_blob(oid, timeout_ms=0)
            except Exception:
                return None    # raced free: caller's loop retries
            return got if want_data else len(got)
        if r == "miss":
            return None
        try:
            loc = self.head.call("begin_pull", oid.hex(), self.node_id)
        except Exception:
            return None
        if not loc:
            return None
        if loc.get("busy"):
            return _SOURCES_BUSY
        if loc["node_id"] == self.node_id:
            return None
        try:
            data = self._pull(oid, loc, want_bytes=want_data)
        finally:
            try:
                self.head.call_oneway("end_pull", oid.hex(),
                                      self.node_id, loc["node_id"],
                                      loc.get("slot_ts", 0.0))
            except Exception:
                pass    # slot TTL reclaims it
        # On success _pull streamed the object into the local store
        # (repeated gets and neighbor pulls now hit shm) and
        # registered the new copy.
        return data

    def _peer(self, addr: str, lane: int = 0) -> RpcClient:
        key = f"{addr}#{lane}"
        with self._peers_lock:
            client = self._peers.get(key)
            if client is None:
                client = self._peers[key] = RpcClient(addr, timeout=30)
            return client

    def _pull(self, oid: ObjectID, loc: Dict, want_bytes: bool = True,
              known_size: int = -1):
        """Pull a remote object INTO the local store, streaming chunks
        straight into a pre-created shm allocation over PULL_STREAMS
        parallel connections. Transfer memory overhead is O(in-flight
        chunks), never O(object). A process-wide semaphore caps total
        in-flight pulled bytes (admission control).

        Returns the object bytes (or, with want_bytes=False, the size
        — prefetchers don't need a heap copy of what just landed in
        shm), or None on failure. Only REMOTE failures unregister the
        location: a local store race must not erase the head's record
        of a healthy remote copy."""
        oid_hex = oid.hex()
        addr = loc["object_addr"]
        view = None
        try:
            size = known_size
            if size < 0:
                size = self._peer(addr).call("object_size", oid_hex)
            if size < 0:
                raise RpcError("object gone")
            view = self.store.create_for_write(oid, size)
            if view is None:
                # Can't allocate (store full beyond spill, or a racing
                # pull already created it): buffered fallback.
                data = self._pull_buffered(oid_hex, addr, size)
                try:
                    self.store.put_bytes(oid, data)
                    if self.multinode:
                        self._register_async(oid_hex)
                except Exception:
                    pass        # store full / raced: still return it
                return data if want_bytes else len(data)
            try:
                self._fetch_into(view, oid_hex, addr, size)
            except BaseException:
                self.store.abort_raw(oid)
                raise
        except (RpcError, Exception):
            # Stale location (evicted or node died): tell the head.
            try:
                self.head.call("unregister_object", oid_hex,
                               loc["node_id"])
            except Exception:
                pass
            return None
        # Local finishing steps: failures here are OUR store racing
        # (concurrent free/evict), not evidence against the remote.
        view.release()
        try:
            self.store.seal_raw(oid)
        except Exception:
            return None
        if self.multinode:
            self._register_async(oid_hex)
        if not want_bytes:
            return size
        try:
            # Pinned view for big objects: the consumer deserializes
            # straight over the mapping — no heap copy of what we just
            # streamed in (critical under host memory-bandwidth
            # contention, see shm_store.get_blob).
            return self.store.get_blob(oid, timeout_ms=0)
        except Exception:
            return None     # raced delete: caller retries the loop

    def _fetch_into(self, view, oid_hex: str, addr: str, size: int):
        offsets = list(range(0, size, CHUNK))
        n_streams = min(PULL_STREAMS, max(1, len(offsets)))
        errors: List[BaseException] = []

        def stream(lane: int):
            client = self._peer(addr, lane)
            for off in offsets[lane::n_streams]:
                n = min(CHUNK, size - off)
                with self._pull_sem:
                    try:
                        client.call_into("raw_pull_chunk", oid_hex,
                                         off, n,
                                         dest=view[off:off + n])
                    except BaseException as e:  # noqa: BLE001
                        errors.append(e)
                        return

        if n_streams == 1:
            stream(0)
        else:
            threads = [threading.Thread(target=stream, args=(i,),
                                        daemon=True)
                       for i in range(n_streams)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        if errors:
            raise errors[0]

    def _pull_buffered(self, oid_hex: str, addr: str,
                       size: int) -> bytes:
        client = self._peer(addr)
        buf = bytearray(size)
        for off in range(0, size, CHUNK):
            n = min(CHUNK, size - off)
            buf[off:off + n] = client.call("pull_chunk", oid_hex,
                                           off, n)
        return bytes(buf)
