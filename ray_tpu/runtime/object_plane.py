"""Multi-node object plane: per-node chunked transfer + location-aware get.

Role parity with the reference's object layer — node-to-node transfer
(ObjectManager chunked push/pull, src/ray/object_manager/object_manager.h:114,
push_manager.h:29), location lookup (ownership_based_object_directory.cc),
and the pull retry machinery (pull_manager.h:47). TPU-first deltas: each
node's C++ shm store is the single local tier, the location directory is
centralized in the head (which also drives lineage reconstruction when
every replica died), and transfer is puller-driven chunked reads over the
framed-socket RPC layer — no standalone object-manager daemon.

Pieces:
- ObjectService: served inside each node's manager process; chunked
  zero-copy reads out of that node's shm store.
- ObjectPlane: what workers/drivers hold instead of a bare store —
  local store fast path, head-directed remote pull on miss, batched
  async location registration for puts.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ray_tpu._private.ids import ObjectID
from ray_tpu.runtime.rpc import RpcClient, RpcError

CHUNK = 4 * 1024 * 1024


class ObjectService:
    """Per-node RPC endpoint exposing the local shm store to peers."""

    def __init__(self, store):
        self.store = store

    def has_object(self, oid_hex: str) -> bool:
        return self.store.contains(ObjectID.from_hex(oid_hex))

    def object_size(self, oid_hex: str) -> int:
        """Size in bytes, or -1 if absent."""
        oid = ObjectID.from_hex(oid_hex)
        try:
            view = self.store.get_view(oid, timeout_ms=0)
        except Exception:
            # Spilled objects still serve (restore-on-read).
            try:
                data = self.store.get_bytes(oid, timeout_ms=0)
            except Exception:
                return -1
            return len(data)
        try:
            return len(view)
        finally:
            self.store.release(oid)

    def pull_chunk(self, oid_hex: str, offset: int, length: int) -> bytes:
        oid = ObjectID.from_hex(oid_hex)
        try:
            view = self.store.get_view(oid, timeout_ms=0)
        except Exception:
            data = self.store.get_bytes(oid, timeout_ms=0)
            return bytes(data[offset:offset + length])
        try:
            return bytes(view[offset:offset + length])
        finally:
            self.store.release(oid)


class ObjectPlane:
    """Location-aware object access for one process.

    Single-node clusters never touch the head: the `multinode` flag
    only flips on when a second node registers (pushed over the `nodes`
    pub/sub channel), so the fast path stays one shm call.
    """

    def __init__(self, store, head: RpcClient, node_id: str = "head"):
        self.store = store
        self.head = head
        self.node_id = node_id
        self.multinode = False
        self._peers: Dict[str, RpcClient] = {}
        self._peers_lock = threading.Lock()
        # Batched async put registration.
        self._pending_reg: List[str] = []
        self._reg_lock = threading.Lock()
        self._reg_wake = threading.Event()
        self._reg_thread: Optional[threading.Thread] = None

    # ---- membership -------------------------------------------------------

    def on_nodes_update(self, version: int, nodes) -> None:
        """Subscriber callback for the `nodes` state channel."""
        alive = [n for n in (nodes or []) if n.get("alive", True)]
        self.multinode = len(alive) > 1

    def refresh_multinode(self) -> None:
        try:
            self.multinode = self.head.call("node_count") > 1
        except Exception:
            pass

    # ---- put --------------------------------------------------------------

    def put_bytes(self, oid: ObjectID, data: bytes) -> None:
        self.store.put_bytes(oid, data)
        if self.multinode:
            self._register_async(oid.hex())

    def _register_async(self, oid_hex: str) -> None:
        with self._reg_lock:
            self._pending_reg.append(oid_hex)
            if self._reg_thread is None or \
                    not self._reg_thread.is_alive():
                self._reg_thread = threading.Thread(
                    target=self._reg_loop, daemon=True,
                    name="objplane-register")
                self._reg_thread.start()
        self._reg_wake.set()

    def _reg_loop(self):
        while True:
            self._reg_wake.wait(timeout=1.0)
            self._reg_wake.clear()
            with self._reg_lock:
                batch, self._pending_reg = self._pending_reg, []
            if batch:
                try:
                    self.head.call("register_objects", self.node_id,
                                   batch)
                except Exception:
                    pass    # locate falls back to probing nodes

    def flush_registrations(self) -> None:
        with self._reg_lock:
            batch, self._pending_reg = self._pending_reg, []
        if batch:
            self.head.call("register_objects", self.node_id, batch)

    # ---- get --------------------------------------------------------------

    def contains(self, oid: ObjectID) -> bool:
        if self.store.contains(oid):
            return True
        if not self.multinode:
            return False
        try:
            return bool(self.head.call("locate_object", oid.hex()))
        except Exception:
            return False

    def get_bytes(self, oid: ObjectID, timeout_ms: int = -1) -> bytes:
        from ray_tpu._private.shm_store import ShmTimeout
        if not self.multinode:
            return self.store.get_bytes(oid, timeout_ms=timeout_ms)
        deadline = None if timeout_ms < 0 else \
            time.time() + timeout_ms / 1000.0
        # Grace period before asking the head to rebuild lost objects:
        # normal pipelines have objects appearing as tasks finish.
        reconstruct_after = time.time() + 1.0
        # Short local waits first: an object completing on a PEER node
        # never seals locally, so blocking 100 ms before the first
        # location lookup would serialize remote-result gets at 10/s.
        local_wait = 2
        while True:
            wait = local_wait
            if deadline is not None:
                rem = int((deadline - time.time()) * 1000)
                if rem <= 0:
                    # Deadline hit: one zero-wait local attempt so an
                    # object that IS here isn't reported as a timeout.
                    return self.store.get_bytes(oid, timeout_ms=0)
                wait = min(wait, max(rem, 1))
            try:
                return self.store.get_bytes(oid, timeout_ms=wait)
            except ShmTimeout:
                pass
            data = self._try_remote_fetch(
                oid, reconstruct=time.time() > reconstruct_after)
            if data is not None:
                return data
            local_wait = min(local_wait * 2, 100)

    def prefetch(self, oids) -> None:
        """Batch-pull any of `oids` that live only on peer nodes into
        the local store (one locate RPC for the whole batch). Misses
        are fine — the caller's per-object get loop handles them."""
        if not self.multinode:
            return
        missing = [o for o in oids if not self.store.contains(o)]
        if not missing:
            return
        try:
            locs = self.head.call("locate_objects",
                                  [o.hex() for o in missing])
        except Exception:
            return
        for oid in missing:
            loc_list = locs.get(oid.hex()) or []
            for loc in loc_list:
                if loc["node_id"] == self.node_id:
                    continue
                data = self._pull(oid, loc)
                if data is not None:
                    try:
                        self.store.put_bytes(oid, data)
                        self._register_async(oid.hex())
                    except Exception:
                        pass
                    break

    def _try_remote_fetch(self, oid: ObjectID,
                          reconstruct: bool) -> Optional[bytes]:
        try:
            locs = self.head.call("locate_object", oid.hex(),
                                  probe=True, reconstruct=reconstruct)
        except Exception:
            return None
        for loc in locs:
            if loc["node_id"] == self.node_id:
                continue        # it's local (or about to be): retry shm
            data = self._pull(oid, loc)
            if data is not None:
                # Cache locally so repeated gets (and neighbors pulling
                # from us) hit shm; registration advertises the copy.
                try:
                    self.store.put_bytes(oid, data)
                    self._register_async(oid.hex())
                except Exception:
                    pass        # store full: still return the bytes
                return data
        return None

    def _peer(self, addr: str) -> RpcClient:
        with self._peers_lock:
            client = self._peers.get(addr)
            if client is None:
                client = self._peers[addr] = RpcClient(addr, timeout=30)
            return client

    def _pull(self, oid: ObjectID, loc: Dict) -> Optional[bytes]:
        client = self._peer(loc["object_addr"])
        oid_hex = oid.hex()
        try:
            size = client.call("object_size", oid_hex)
            if size < 0:
                raise RpcError("object gone")
            buf = bytearray(size)
            for off in range(0, size, CHUNK):
                n = min(CHUNK, size - off)
                buf[off:off + n] = client.call(
                    "pull_chunk", oid_hex, off, n)
            return bytes(buf)
        except (RpcError, Exception):
            # Stale location (evicted or node died): tell the head.
            try:
                self.head.call("unregister_object", oid_hex,
                               loc["node_id"])
            except Exception:
                pass
            return None
