"""Head-hosted pub/sub: versioned channels + long-poll delivery.

Role parity with the reference's GCS-hosted pub/sub (long-poll
publisher/subscriber, src/ray/pubsub/publisher.h:298, subscriber.h:329;
channels gcs_service.proto:568) and the serve config-push layer built on
it (python/ray/serve/_private/long_poll.py:63,179). TPU-first deltas:
one hub lives inside the head service (no separate pubsub server), and
delivery is long-poll over the framed-socket RPC layer — a blocked
``psub_poll`` call holds only its handler thread, and every state channel
is versioned so a reconnecting subscriber resyncs with one round trip.

Two channel kinds:
- **state channels** hold one versioned value (serve route tables, node
  membership). Subscribers poll with their last-seen version and get the
  latest value the moment it differs — no event history is kept.
- **stream channels** hold an append-only sequence (log records, worker
  events) with a bounded replay buffer; subscribers get batches ordered
  by sequence number.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple


class PubSubHub:
    """In-head hub. All methods are thread-safe."""

    def __init__(self, stream_buffer: int = 4096):
        import uuid
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # Epoch: identifies THIS hub instance. A restarted head builds
        # a fresh hub whose versions restart at 1 — subscribers compare
        # epochs and reset their cursors instead of silently dropping
        # every post-restart update as "old".
        self.epoch = uuid.uuid4().hex
        # state channels: name -> (version, value)
        self._state: Dict[str, Tuple[int, Any]] = {}
        # stream channels: name -> deque[(seq, item)], next_seq
        self._streams: Dict[str, collections.deque] = {}
        self._next_seq: Dict[str, int] = {}
        self._stream_buffer = stream_buffer

    def next_seq(self, channel: str) -> int:
        with self._lock:
            return self._next_seq.get(channel, 0)

    # ---- publish ----------------------------------------------------------

    def publish_state(self, channel: str, value: Any) -> int:
        with self._cv:
            version = self._state.get(channel, (0, None))[0] + 1
            self._state[channel] = (version, value)
            self._cv.notify_all()
            return version

    def publish_stream(self, channel: str, item: Any) -> int:
        with self._cv:
            seq = self._next_seq.get(channel, 0)
            self._next_seq[channel] = seq + 1
            buf = self._streams.get(channel)
            if buf is None:
                buf = self._streams[channel] = collections.deque(
                    maxlen=self._stream_buffer)
            buf.append((seq, item))
            self._cv.notify_all()
            return seq

    def drop_channel(self, channel: str):
        with self._cv:
            self._state.pop(channel, None)
            self._streams.pop(channel, None)
            self._next_seq.pop(channel, None)

    # ---- long-poll --------------------------------------------------------

    def _collect(self, state_versions: Dict[str, int],
                 stream_seqs: Dict[str, int]):
        out_state, out_streams = {}, {}
        for chan, last in state_versions.items():
            cur = self._state.get(chan)
            if cur is not None and cur[0] > last:
                out_state[chan] = cur
        for chan, last in stream_seqs.items():
            buf = self._streams.get(chan)
            if buf and buf[-1][0] >= last:
                out_streams[chan] = [(s, it) for s, it in buf
                                     if s >= last]
        return out_state, out_streams

    def poll(self, state_versions: Optional[Dict[str, int]] = None,
             stream_seqs: Optional[Dict[str, int]] = None,
             timeout: float = 30.0):
        """Block until any subscribed channel moves past the given
        version/sequence, then return {"state": {chan: (version, value)},
        "streams": {chan: [(seq, item), ...]}}. Empty dicts on timeout.

        state_versions: channel -> last seen version (0 = send current).
        stream_seqs:    channel -> next wanted sequence number.
        """
        state_versions = state_versions or {}
        stream_seqs = stream_seqs or {}
        deadline = time.time() + timeout
        with self._cv:
            while True:
                out_state, out_streams = self._collect(
                    state_versions, stream_seqs)
                if out_state or out_streams:
                    return {"state": out_state,
                            "streams": out_streams,
                            "epoch": self.epoch}
                remaining = deadline - time.time()
                if remaining <= 0:
                    return {"state": {}, "streams": {},
                            "epoch": self.epoch}
                self._cv.wait(timeout=min(remaining, 1.0))

    def state_snapshot(self, channel: str):
        with self._lock:
            return self._state.get(channel, (0, None))


class Subscriber:
    """Client-side long-poll loop delivering updates to callbacks.

    subscribe_state(chan, cb): cb(version, value) on every change (and
    once immediately with the current value, if any).
    subscribe_stream(chan, cb): cb(seq, item) per item, in order.
    """

    def __init__(self, head_client, poll_timeout: float = 30.0):
        self._head = head_client
        self._poll_timeout = poll_timeout
        self._lock = threading.Lock()
        self._state_cbs: Dict[str, List[Callable]] = {}
        self._stream_cbs: Dict[str, List[Callable]] = {}
        self._state_versions: Dict[str, int] = {}
        self._stream_seqs: Dict[str, int] = {}
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._epoch: Optional[str] = None

    def subscribe_state(self, channel: str, callback: Callable):
        with self._lock:
            self._state_cbs.setdefault(channel, []).append(callback)
            self._state_versions.setdefault(channel, 0)
        self._ensure_running()
        self._wake.set()

    def subscribe_stream(self, channel: str, callback: Callable,
                         from_seq: int = 0):
        with self._lock:
            self._stream_cbs.setdefault(channel, []).append(callback)
            self._stream_seqs.setdefault(channel, from_seq)
        self._ensure_running()
        self._wake.set()

    def unsubscribe(self, channel: str):
        with self._lock:
            self._state_cbs.pop(channel, None)
            self._stream_cbs.pop(channel, None)
            self._state_versions.pop(channel, None)
            self._stream_seqs.pop(channel, None)

    def _ensure_running(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="pubsub-subscriber")
            self._thread.start()

    def stop(self):
        self._stop.set()
        self._wake.set()

    def _loop(self):
        while not self._stop.is_set():
            with self._lock:
                sv = dict(self._state_versions)
                ss = dict(self._stream_seqs)
            if not sv and not ss:
                self._wake.wait(timeout=1.0)
                self._wake.clear()
                continue
            try:
                out = self._head.call(
                    "psub_poll", sv, ss,
                    timeout=self._poll_timeout + 10,
                    poll_timeout=self._poll_timeout)
            except Exception:
                if self._stop.wait(timeout=0.5):
                    return
                continue
            epoch = out.get("epoch")
            if epoch is not None:
                if self._epoch is not None and epoch != self._epoch:
                    # Head restarted: its channels restart at version 1
                    # while we hold higher cursors — reset so current
                    # state re-delivers and streams resume from the
                    # fresh hub's start.
                    with self._lock:
                        for chan in self._state_versions:
                            self._state_versions[chan] = 0
                        for chan in self._stream_seqs:
                            self._stream_seqs[chan] = 0
                    self._epoch = epoch
                    continue
                self._epoch = epoch
            for chan, (version, value) in out.get("state", {}).items():
                with self._lock:
                    if self._state_versions.get(chan, 0) >= version:
                        continue
                    self._state_versions[chan] = version
                    cbs = list(self._state_cbs.get(chan, ()))
                for cb in cbs:
                    try:
                        cb(version, value)
                    except Exception:  # noqa: BLE001 — keep delivering
                        pass
            for chan, items in out.get("streams", {}).items():
                for seq, item in items:
                    with self._lock:
                        if self._stream_seqs.get(chan, 0) > seq:
                            continue
                        self._stream_seqs[chan] = seq + 1
                        cbs = list(self._stream_cbs.get(chan, ()))
                    for cb in cbs:
                        try:
                            cb(seq, item)
                        except Exception:  # noqa: BLE001
                            pass
