"""Framed-socket RPC.

Role parity with the reference's RPC layer (src/ray/rpc/grpc_server.h,
grpc_client.h, client_call.h): typed service endpoints, concurrent calls,
retrying clients, per-connection threads.

Wire protocol (the schema'd-protocol role of src/ray/protobuf/ — here a
versioned binary framing instead of 21 protos, since both ends are this
codebase):

  HELLO (once per TCP connection, client -> server):
      magic  b"RAYT"         (4 bytes)
      version u16 LE          — PROTO_VERSION; mismatch is rejected
      tlen    u16 LE          — auth token length
      token   tlen bytes      — cluster secret (GlobalConfig.cluster_token)
  FRAME (both directions, after a successful HELLO):
      length  u32 LE + cloudpickle payload
      request:  {"rid", "method", "args", "kwargs"} (rid None = one-way)
      response: {"rid", "ok": result} | {"rid", "err", "tb"}

The server verifies magic/version/token BEFORE deserializing anything, so
an arbitrary connecting process can no longer feed pickle to the handler
(the reference gets the same property from gRPC framing + Redis password).
"""
from __future__ import annotations

import hmac
import pickle
import socket
import struct
import threading
import traceback
from typing import Any, Callable, Dict, Optional

import cloudpickle

_LEN = struct.Struct("<I")

MAGIC = b"RAYT"
PROTO_VERSION = 2
_HELLO = struct.Struct("<4sHH")
# v2 handshake ACK (server -> client after a successful HELLO): the
# codec version rides back so both ends know exactly what the peer
# speaks (the proto-file version-negotiation role).
_HELLO_ACK = struct.Struct("<4sH")
_HANDSHAKE_TIMEOUT_S = 10.0


def _token_bytes() -> bytes:
    from ray_tpu._private.config import GlobalConfig
    return GlobalConfig.cluster_token.encode()


def _send_hello(sock: socket.socket):
    tok = _token_bytes()
    sock.sendall(_HELLO.pack(MAGIC, PROTO_VERSION, len(tok)) + tok)
    # v2: read the server's handshake ack (codec version exchange).
    # A server that rejected us sends an error FRAME instead — its
    # first 4 bytes are a little-endian length, never b"RAYT", so the
    # magic check below distinguishes the two without ambiguity.
    head = _recv_exact(sock, _HELLO_ACK.size)
    magic, codec = _HELLO_ACK.unpack(head)
    if magic != MAGIC:
        # rejection frame: reassemble it and surface the server's
        # reason as the error
        rest_len = _LEN.unpack(head[:4])[0]
        body = head[4:] + _recv_exact(
            sock, rest_len - (len(head) - 4))
        reply = pickle.loads(body)
        raise reply.get("err") or RpcError("handshake rejected")
    return codec


def _check_hello(sock: socket.socket) -> Optional[str]:
    """Server side: returns None on success, else a rejection reason."""
    magic, version, tlen = _HELLO.unpack(
        _recv_exact(sock, _HELLO.size))
    if magic != MAGIC:
        return "bad magic (not a ray_tpu client)"
    if version != PROTO_VERSION:
        return (f"protocol version mismatch: peer {version}, "
                f"server {PROTO_VERSION}")
    token = _recv_exact(sock, tlen) if tlen else b""
    if not hmac.compare_digest(token, _token_bytes()):
        return "authentication failed (bad cluster token)"
    return None


def _send_msg(sock: socket.socket, obj: Any, fast: bool = False):
    # fast=True: the caller asserts the message tree is plain-picklable
    # (bytes/str/numbers/dict/list/tuple) — plain pickle skips the
    # CloudPickler construction on hot paths. Loads is shared: pickle
    # output is always cloudpickle-loadable.
    data = pickle.dumps(obj, protocol=5) if fast else \
        cloudpickle.dumps(obj)
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def _recv_msg(sock: socket.socket) -> Any:
    (n,) = _LEN.unpack(_recv_exact(sock, 4))
    return cloudpickle.loads(_recv_exact(sock, n))


class RpcServer:
    """Serves a handler object's public methods over TCP."""

    def __init__(self, handler: Any, host: str = "127.0.0.1",
                 port: int = 0):
        self.handler = handler
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(128)
        self.host, self.port = self._sock.getsockname()
        self._running = True
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"rpc-server-{self.port}")
        self._accept_thread.start()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def _accept_loop(self):
        while self._running:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # Pre-size the send buffer (bulk chunk replies): TCP
            # buffer autotuning starts small and warms up slowly under
            # the lock-step request/reply pattern — a FRESH connection
            # pair otherwise serves its first bulk pull ~13x slower
            # than a warmed one (measured 0.15 vs 2.0 GB/s).
            try:
                conn.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF,
                                4 * 1024 * 1024)
            except OSError:
                pass
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket):
        send_lock = threading.Lock()
        try:
            conn.settimeout(_HANDSHAKE_TIMEOUT_S)
            reason = _check_hello(conn)
            if reason is not None:
                try:
                    _send_msg(conn, {"rid": None,
                                     "err": RpcError(reason)})
                    # Drain whatever the peer already sent before
                    # closing: closing with unread rx data turns the
                    # close into an RST, which can discard the error
                    # frame before the peer reads it.
                    conn.settimeout(0.5)
                    for _ in range(16):       # bounded drain
                        if not conn.recv(65536):
                            break
                except (ConnectionError, OSError):
                    pass
                return
            from ray_tpu.runtime.schemas import CODEC_VERSION
            conn.sendall(_HELLO_ACK.pack(MAGIC, CODEC_VERSION))
            conn.settimeout(None)
            while self._running:
                req = _recv_msg(conn)
                if req.get("rid") is None:
                    # One-way pipelined call: handled inline (by
                    # contract these are enqueue-fast), preserving
                    # arrival order and skipping a thread spawn.
                    self._handle_one(conn, req, send_lock)
                    continue
                # Each request runs on its own thread so one long call
                # doesn't block the connection (client sends one request
                # per pooled connection at a time).
                threading.Thread(
                    target=self._handle_one, args=(conn, req, send_lock),
                    daemon=True).start()
        except (ConnectionError, OSError, struct.error):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _handle_one(self, conn: socket.socket, req: Dict[str, Any],
                    send_lock: threading.Lock):
        rid = req.get("rid")
        raw = None
        cleanup = None
        try:
            from ray_tpu.runtime.schemas import validate_request
            validate_request(req["method"], req.get("args", ()),
                             req.get("kwargs", {}))
            method = getattr(self.handler, req["method"])
            result = method(*req.get("args", ()),
                            **req.get("kwargs", {}))
            if req["method"].startswith("raw_"):
                # Raw-framed reply: a tiny pickled header announcing
                # the byte count, then the buffer itself straight out
                # of the handler's view — no pickling of the payload,
                # so bulk transfer costs zero extra copies server-side.
                if isinstance(result, tuple):
                    raw, cleanup = result
                else:
                    raw = result
                reply = {"rid": rid, "raw": len(raw)}
            else:
                reply = {"rid": rid, "ok": result}
        except BaseException as e:  # noqa: BLE001
            reply = {"rid": rid, "err": e,
                     "tb": traceback.format_exc()}
        if rid is None:
            if cleanup is not None:
                cleanup()
            return     # one-way call: no reply expected
        with send_lock:
            try:
                _send_msg(conn, reply)
                if raw is not None:
                    conn.sendall(raw)
            except (ConnectionError, OSError):
                pass
            finally:
                if cleanup is not None:
                    cleanup()

    def stop(self):
        self._running = False
        try:
            self._sock.close()
        except OSError:
            pass


class RpcError(RuntimeError):
    pass


class RpcClient:
    """Thread-safe client. Each call gets a pooled connection; replies are
    matched by request id per connection (one in-flight call per pooled
    connection keeps the protocol trivial)."""

    def __init__(self, address: str, timeout: Optional[float] = None):
        host, port = address.rsplit(":", 1)
        self.host, self.port = host, int(port)
        self.timeout = timeout
        self._pool: list = []
        self._pool_lock = threading.Lock()
        self._rid = 0
        self._oneway_sock: Optional[socket.socket] = None
        self._oneway_lock = threading.Lock()

    def _connect(self) -> socket.socket:
        # Pre-size the receive buffer BEFORE connect: the TCP window
        # scale factor is fixed at SYN time from rcvbuf, and buffer
        # autotuning warms up too slowly under the lock-step
        # request/reply pattern (see RpcServer._accept_loop).
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF,
                            4 * 1024 * 1024)
        except OSError:
            pass
        sock.settimeout(self.timeout or _HANDSHAKE_TIMEOUT_S)
        try:
            sock.connect((self.host, self.port))
        except BaseException:
            sock.close()
            raise
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # Bound the ack read even for timeout=None clients: a wedged
        # server whose backlog still accepts connects must not hang
        # the handshake forever (call() re-applies the caller's
        # timeout on the pooled socket afterwards).
        sock.settimeout(_HANDSHAKE_TIMEOUT_S)
        self.peer_codec = _send_hello(sock)
        sock.settimeout(self.timeout)
        return sock

    def _get_conn(self) -> socket.socket:
        with self._pool_lock:
            if self._pool:
                return self._pool.pop()
        return self._connect()

    def _put_conn(self, sock: socket.socket):
        with self._pool_lock:
            if len(self._pool) < 16:
                self._pool.append(sock)
            else:
                sock.close()

    def call(self, method: str, *args, timeout: Optional[float] = None,
             **kwargs) -> Any:
        with self._pool_lock:
            self._rid += 1
            rid = self._rid
        sock = None
        try:
            sock = self._get_conn()
            # Always (re)set: pooled sockets keep the previous call's
            # timeout otherwise. Fall back to the client-level default.
            sock.settimeout(self.timeout if timeout is None else timeout)
            _send_msg(sock, {"rid": rid, "method": method,
                             "args": args, "kwargs": kwargs})
            reply = _recv_msg(sock)
        except (ConnectionError, OSError) as e:
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
            raise RpcError(f"RPC {method} to {self.host}:{self.port} "
                           f"failed: {e}") from e
        if reply.get("rid") != rid:
            # Connection-level rejection (handshake failure): the
            # server closed this socket — pooling it would surface a
            # misleading 'peer closed' on the NEXT call.
            try:
                sock.close()
            except OSError:
                pass
            raise reply.get("err") or RpcError(
                f"RPC {method}: connection rejected")
        if "raw" in reply:
            # A raw_-framed method was invoked via plain call(): the
            # payload is already in flight on this pooled socket, so
            # drain it before reuse (leaving it would desynchronize
            # every later call on the connection), then fail clearly.
            n = int(reply["raw"])
            try:
                left = n
                sink = bytearray(min(left, 1 << 20))
                while left > 0:
                    got = sock.recv_into(sink, min(left, len(sink)))
                    if got == 0:
                        raise ConnectionError("peer closed mid-drain")
                    left -= got
                self._put_conn(sock)
            except (ConnectionError, OSError):
                try:
                    sock.close()
                except OSError:
                    pass
            raise RpcError(
                f"RPC {method} returns a raw-framed payload "
                f"({n} bytes); use call_into() with a dest buffer")
        self._put_conn(sock)
        if "err" in reply:
            raise reply["err"]
        return reply["ok"]

    def call_into(self, method: str, *args, dest,
                  timeout: Optional[float] = None) -> int:
        """Call a raw-framed server method (name must start with
        ``raw_``) and receive the payload DIRECTLY into ``dest`` (a
        writable buffer, e.g. a shm mapping view) via recv_into — the
        bulk bytes never pass through pickle or an intermediate
        buffer. Returns the byte count received."""
        with self._pool_lock:
            self._rid += 1
            rid = self._rid
        sock = None
        try:
            sock = self._get_conn()
            sock.settimeout(self.timeout if timeout is None else timeout)
            _send_msg(sock, {"rid": rid, "method": method,
                             "args": args})
            reply = _recv_msg(sock)
            if reply.get("rid") != rid:
                try:
                    sock.close()
                except OSError:
                    pass
                raise reply.get("err") or RpcError(
                    f"RPC {method}: connection rejected")
            if "err" in reply:
                self._put_conn(sock)
                raise reply["err"]
            n = reply["raw"]
            if n > len(dest):
                try:
                    sock.close()   # raw bytes are in flight: unpoolable
                except OSError:
                    pass
                raise RpcError(f"raw reply {n}B exceeds dest "
                               f"{len(dest)}B")
            mv = memoryview(dest)[:n]
            got = 0
            while got < n:
                r = sock.recv_into(mv[got:], n - got)
                if r == 0:
                    raise ConnectionError("peer closed mid-raw-reply")
                got += r
            self._put_conn(sock)
            return n
        except (ConnectionError, OSError) as e:
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
            raise RpcError(f"RPC {method} to {self.host}:{self.port} "
                           f"failed: {e}") from e

    def call_oneway(self, method: str, *args, fast: bool = False,
                    **kwargs) -> None:
        """Fire-and-forget: send the request and return without waiting
        for (or receiving) a reply. Used on hot submission paths where
        the outcome surfaces elsewhere (e.g. the object store). A
        dedicated pipelined connection keeps one-way sends ordered with
        each other and off the request/reply sockets. fast=True asserts
        the args are plain-picklable (see _send_msg)."""
        with self._pool_lock:
            sock = self._oneway_sock
            if sock is None:
                sock = self._oneway_sock = self._connect()
        try:
            with self._oneway_lock:
                _send_msg(sock, {"rid": None, "method": method,
                                 "args": args, "kwargs": kwargs},
                          fast=fast)
        except (ConnectionError, OSError) as e:
            with self._pool_lock:
                self._oneway_sock = None
            try:
                sock.close()
            except OSError:
                pass
            raise RpcError(f"RPC {method} to {self.host}:{self.port} "
                           f"failed: {e}") from e

    def close(self):
        with self._pool_lock:
            for s in self._pool:
                try:
                    s.close()
                except OSError:
                    pass
            self._pool.clear()
            if self._oneway_sock is not None:
                try:
                    self._oneway_sock.close()
                except OSError:
                    pass
                self._oneway_sock = None
