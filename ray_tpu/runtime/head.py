"""HEAD service: control plane + cluster scheduler.

Capability parity (single service, multiprocess scale) with the reference's
GCS (src/ray/gcs/gcs_server/ — node membership, actor directory, named
actors, KV) and the cluster scheduling path (ClusterTaskManager
scheduling/cluster_task_manager.cc: queue + pick node by resource fit;
LocalTaskManager dispatch == direct RPC push to the chosen worker's
executor). Placement groups reserve per-worker resources (the 2PC of
gcs_placement_group_scheduler.h collapses to one phase on a single head).

Fault tolerance: worker death (reported by the node manager) fails or
retries its running tasks (owner-style retry, task_manager.h:135) and
restarts its actors elsewhere up to max_restarts
(gcs_actor_manager.cc:1037 semantics).
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private.ids import ObjectID
from ray_tpu._private.serialization import dumps
from ray_tpu.exceptions import ActorDiedError, NodeDiedError
from ray_tpu.runtime.rpc import RpcClient, RpcError


class _WorkerInfo:
    def __init__(self, worker_id: str, address: str,
                 resources: Dict[str, float], node_id: str = "head",
                 env_key: Optional[str] = None):
        self.worker_id = worker_id
        self.env_key = env_key      # runtime-env pool key (or None)
        self.last_active = time.time()
        self.address = address
        self.resources = dict(resources)
        self.available = dict(resources)
        self.alive = True
        self.client = RpcClient(address)
        self.running: set = set()   # task ids currently dispatched
        # task id -> (resources, pg_id) actually deducted from THIS
        # worker; release happens from here (not from task meta) so a
        # duplicate completion after a spurious death-mark can't
        # double-release.
        self.running_res: Dict[str, Tuple[Dict[str, float], Any]] = {}
        self.node_id = node_id
        # Event-driven dispatch: the scheduler enqueues, one sender
        # thread per worker pushes (the reference amortizes raylet
        # round trips with lease reuse + pipelined PushTask,
        # direct_task_transport.cc:170 OnWorkerIdle; here dispatch is a
        # fire-and-forget enqueue RPC and completion arrives via
        # batched tasks_done).
        import queue as _queue
        self.outbox: "_queue.Queue" = _queue.Queue()
        self.sender: Optional[threading.Thread] = None


class _OrderedSet(dict):
    """Insertion-ordered set (dict keys): supports add/discard plus
    iteration/len/in, preserving first-registration order."""

    def add(self, k):
        self[k] = True

    def discard(self, k):
        self.pop(k, None)


class _NodeInfo:
    def __init__(self, node_id: str, object_addr: str, store_name: str):
        self.node_id = node_id
        self.object_addr = object_addr
        self.store_name = store_name
        self.alive = True
        self.last_heartbeat = time.time()
        self.object_client = RpcClient(object_addr, timeout=10)


class _ActorInfo:
    def __init__(self, actor_id: str, worker_id: str, payload: bytes,
                 resources: Dict[str, float], max_restarts: int,
                 name: Optional[str], namespace: str,
                 pg_id: Optional[str] = None, bundle_index: int = -1,
                 env_key: Optional[str] = None,
                 runtime_env: Optional[Dict] = None):
        self.actor_id = actor_id
        self.env_key = env_key
        self.runtime_env = runtime_env
        self.worker_id = worker_id
        self.payload = payload          # creation spec (for restarts)
        self.resources = resources
        self.max_restarts = max_restarts
        self.restarts = 0
        self.dead = False
        self.death_reason = ""
        self.name = name
        self.namespace = namespace
        # PG-pinned actors consume the placement group's reservation
        # (tracked per-bundle in pg["bundle_used"]), which was already
        # deducted from the worker at PG creation — per-actor accounting
        # must not double-count it against the worker.
        self.pg_id = pg_id
        self.bundle_index = bundle_index
        # declared concurrency groups (validated at call submission so
        # an unknown group fails synchronously at .remote(), matching
        # the in-process runtime)
        self.concurrency_groups: Dict[str, int] = {}


class HeadService:
    """Handler object served by RpcServer in the head process."""

    def __init__(self, store_name: str,
                 state_dir: Optional[str] = None):
        self.store_name = store_name
        self.state_dir = state_dir
        self._lock = threading.RLock()
        self._workers: Dict[str, _WorkerInfo] = {}
        self._actors: Dict[str, _ActorInfo] = {}
        self._named: Dict[Tuple[str, str], str] = {}
        self._kv: Dict[str, bytes] = {}
        # Pending queue indexed by resource signature: one scheduler
        # pass probes each distinct (resources, pg) shape once and
        # dispatches from its FIFO until placement fails — O(shapes)
        # per pass instead of O(queue length), which keeps a deep
        # homogeneous backlog (the 1M-queued-tasks envelope) cheap.
        self._pending: "collections.OrderedDict[tuple, collections.deque]" = \
            collections.OrderedDict()
        self._task_meta: Dict[str, Dict[str, Any]] = {}
        self._pgs: Dict[str, Dict[str, Any]] = {}
        # Demands not in the task queue but still unmet — blocked actor
        # creations and unplaceable placement groups — so the autoscaler
        # sees them (reference: resource load includes actor/PG shapes).
        self._pending_actor_demands: Dict[str, Dict[str, float]] = {}
        self._failed_pg_demands: Dict[str, Any] = {}   # pg_id -> (bundles, ts)
        self._store = None
        self._shutdown = False
        # --- multi-node object/control plane ---------------------------
        from ray_tpu.runtime.pubsub import PubSubHub
        self.hub = PubSubHub()
        self._nodes: Dict[str, _NodeInfo] = {}
        # object directory: oid hex -> set of node ids holding a copy
        # (owner-based directory parity, ownership_based_object_directory.cc)
        # Insertion-ordered per-object location "set": the FIRST
        # entry is the original producer. Transfer admission prefers
        # earlier sources — a continuously-serving producer stays warm
        # while rarely-used replicas pay cold-path penalties on
        # shared hosts.
        self._obj_locs: Dict[str, _OrderedSet] = {}
        # lineage: return oid hex -> creating task (meta+payload), LRU
        # bounded by bytes (reference max_lineage_bytes semantics,
        # core_worker/task_manager.h:251).
        self._lineage: "collections.OrderedDict[str, Dict]" = \
            collections.OrderedDict()
        self._lineage_bytes = 0
        from ray_tpu._private.config import GlobalConfig
        self._lineage_budget = int(GlobalConfig.lineage_max_bytes)
        self._sched_cv = threading.Condition(self._lock)
        # --- persistence (GCS table-storage parity) --------------------
        self._persist_dirty = threading.Event()
        if state_dir:
            self._restore_state()
            self._persist_thread = threading.Thread(
                target=self._persist_loop, daemon=True,
                name="head-persist")
            self._persist_thread.start()
        self._sched_thread = threading.Thread(
            target=self._scheduler_loop, daemon=True, name="head-sched")
        self._sched_thread.start()
        self._node_monitor = threading.Thread(
            target=self._node_monitor_loop, daemon=True,
            name="head-node-monitor")
        self._node_monitor.start()

    # ---- persistence / recovery (gcs_table_storage.h:261,
    # redis_store_client.h:28 role — here a debounced full snapshot of
    # the durable tables; worker/node bindings are NOT persisted, they
    # re-attach via heartbeats) ---------------------------------------

    def _dirty(self):
        self._persist_dirty.set()

    def _snapshot_path(self) -> str:
        import os
        return os.path.join(self.state_dir, "head_state.pkl")

    def _persist_loop(self):
        import os
        import cloudpickle
        os.makedirs(self.state_dir, exist_ok=True)
        while not self._shutdown:
            if not self._persist_dirty.wait(timeout=1.0):
                continue
            time.sleep(0.25)            # debounce bursts
            self._persist_dirty.clear()
            with self._lock:
                state = {
                    "kv": dict(self._kv),
                    "functions": dict(getattr(self, "_functions", {})),
                    "named": dict(self._named),
                    "actors": {
                        aid: {"payload": a.payload,
                              "resources": a.resources,
                              "max_restarts": a.max_restarts,
                              "restarts": a.restarts,
                              "name": a.name, "namespace": a.namespace,
                              "pg_id": a.pg_id,
                              "bundle_index": a.bundle_index,
                              "env_key": a.env_key,
                              "concurrency_groups":
                                  a.concurrency_groups,
                              "runtime_env": a.runtime_env}
                        for aid, a in self._actors.items()
                        if not a.dead},
                    "pg_specs": {
                        pg_id: {"bundles": [dict(b) for _, b in
                                            pg["bundles"]],
                                "strategy": pg.get("strategy", "PACK")}
                        for pg_id, pg in self._pgs.items()},
                }
            tmp = self._snapshot_path() + ".tmp"
            with open(tmp, "wb") as f:
                cloudpickle.dump(state, f)
            os.replace(tmp, self._snapshot_path())

    def _restore_state(self):
        import os
        import cloudpickle
        path = self._snapshot_path()
        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            state = cloudpickle.load(f)
        with self._lock:
            self._kv.update(state.get("kv", {}))
            self._functions = dict(state.get("functions", {}))
            self._named.update(state.get("named", {}))
            for aid, rec in state.get("actors", {}).items():
                info = _ActorInfo(
                    aid, "", rec["payload"], rec["resources"],
                    rec["max_restarts"], rec["name"], rec["namespace"],
                    pg_id=rec.get("pg_id"),
                    bundle_index=rec.get("bundle_index", -1),
                    env_key=rec.get("env_key"),
                    runtime_env=rec.get("runtime_env"))
                info.restarts = rec.get("restarts", 0)
                info.concurrency_groups = dict(
                    rec.get("concurrency_groups") or {})
                # worker_id="" == awaiting re-attach: the worker that
                # hosts this actor re-reports it on its next heartbeat
                # miss; calls meanwhile wait (submit_actor_task).
                self._actors[aid] = info
            # PGs are restored as specs awaiting re-reservation once
            # workers re-register.
            self._recovering_pgs = dict(state.get("pg_specs", {}))

    def _try_recover_pgs_locked(self):
        pending = getattr(self, "_recovering_pgs", None)
        if not pending:
            return
        for pg_id in list(pending):
            spec = pending[pg_id]
            # Re-reserve outside the actor accounting; actors re-report
            # and re-occupy their bundles afterwards. Keep the spec
            # until creation SUCCEEDS — early attempts can fail while
            # only some workers have re-attached (e.g. STRICT_SPREAD
            # needing more distinct workers).
            if self.create_placement_group(pg_id, spec["bundles"],
                                           spec["strategy"]):
                del pending[pg_id]

    def _get_store(self):
        if self._store is None:
            from ray_tpu._private.shm_store import ShmObjectStore
            self._store = ShmObjectStore.attach(self.store_name)
        return self._store

    # ---- node membership (multi-node control plane) -----------------------

    def register_node(self, node_id: str, object_addr: str,
                      store_name: str) -> None:
        with self._lock:
            self._nodes[node_id] = _NodeInfo(node_id, object_addr,
                                             store_name)
        self._publish_nodes()

    def node_heartbeat(self, node_id: str, hw: Optional[Dict] = None
                       ) -> bool:
        with self._lock:
            n = self._nodes.get(node_id)
            if n is None or not n.alive:
                return False    # tells a zombie agent to re-register
            n.last_heartbeat = time.time()
            if hw is not None:
                # per-node hardware snapshot riding the heartbeat
                # (reporter_agent.py role)
                n.hw = hw
            return True

    def node_count(self) -> int:
        with self._lock:
            return sum(1 for n in self._nodes.values() if n.alive)

    def list_nodes(self) -> List[Dict[str, Any]]:
        self._refresh_own_hw()
        with self._lock:
            return [{"node_id": n.node_id, "alive": n.alive,
                     "object_addr": n.object_addr,
                     "store_name": n.store_name,
                     "last_heartbeat": getattr(n, "last_heartbeat", 0),
                     "hw": getattr(n, "hw", None)}
                    for n in self._nodes.values()]

    def _refresh_own_hw(self, max_age_s: float = 2.0):
        """The head node has no agent heartbeating at it: snapshot its
        hardware locally (cached) when someone asks."""
        now = time.time()
        if now - getattr(self, "_own_hw_ts", 0) < max_age_s:
            return
        self._own_hw_ts = now
        try:
            from ray_tpu._private.hw_report import collect_hw_stats
            hw = collect_hw_stats(self._get_store())
        except Exception:
            return
        with self._lock:
            n = self._nodes.get("head")
            if n is not None:
                n.hw = hw

    def _publish_nodes(self):
        self.hub.publish_state("nodes", self.list_nodes())

    def mark_node_dead(self, node_id: str):
        with self._lock:
            n = self._nodes.get(node_id)
            if n is None or not n.alive:
                return
            n.alive = False
            workers = [w.worker_id for w in self._workers.values()
                       if w.node_id == node_id and w.alive]
            # Objects whose only copies lived there are gone; getters
            # fall back to lineage reconstruction.
            for locs in self._obj_locs.values():
                locs.discard(node_id)
        for wid in workers:
            self.mark_worker_dead(wid)
        self._reconcile_borrows_for_dead_node(node_id)
        self._publish_nodes()
        self.hub.publish_stream(
            "node_events", {"type": "node_dead", "node_id": node_id,
                            "ts": time.time()})

    def _node_monitor_loop(self):
        from ray_tpu._private.config import GlobalConfig
        period = GlobalConfig.heartbeat_period_ms / 1000.0
        timeout = period * GlobalConfig.num_heartbeats_timeout
        while not self._shutdown:
            time.sleep(period)
            now = time.time()
            stale = []
            with self._lock:
                self._reap_idle_env_workers_locked()
                for n in self._nodes.values():
                    # The head's own node has no heartbeating agent.
                    if n.alive and n.node_id != "head" and \
                            now - n.last_heartbeat > timeout:
                        stale.append(n.node_id)
            for node_id in stale:
                self.mark_node_dead(node_id)
            self._sweep_borrows(now)
            self._sync_resources()

    # ---- resource syncer (ray_syncer / gcs_resource_manager role:
    # push-based cluster-state distribution — subscribers hold a
    # locally-served resource view instead of polling RPCs) ----------

    def _aggregate_resources_locked(self) -> Tuple[Dict[str, float],
                                                   Dict[str, float]]:
        """One aggregation path for RPC queries AND the synced
        snapshot, so push subscribers and pollers see one accounting."""
        cluster: Dict[str, float] = {}
        avail: Dict[str, float] = {}
        for w in self._workers.values():
            if not w.alive:
                continue
            for k, v in w.resources.items():
                cluster[k] = cluster.get(k, 0.0) + v
            for k, v in w.available.items():
                avail[k] = round(avail.get(k, 0.0) + v, 6)
        return cluster, avail

    def _resource_snapshot_locked(self) -> Dict[str, Any]:
        cluster, avail = self._aggregate_resources_locked()
        return {"cluster_resources": cluster,
                "available_resources": avail,
                "num_workers": sum(1 for w in self._workers.values()
                                   if w.alive),
                "num_nodes": max(1, sum(1 for n in self._nodes.values()
                                        if n.alive))}

    def _sync_resources(self):
        """Publish the resource view when it changed — once per
        monitor period for availability drift, immediately from
        membership events (register/death). snapshot+compare+publish
        all run under the (reentrant) head lock so concurrent callers
        can never publish snapshots out of order."""
        with self._lock:
            snap = self._resource_snapshot_locked()
            now = time.time()
            changed = snap != getattr(self, "_last_resource_snap",
                                      None)
            # keepalive republish: subscribers key freshness off the
            # last push, so a quiet-but-healthy cluster must still
            # heartbeat the channel or their TTL would force them
            # back to polling RPCs.
            stale = now - getattr(self, "_last_resource_pub", 0) > 5.0
            if changed or stale:
                self._last_resource_snap = snap
                self._last_resource_pub = now
                self.hub.publish_state("resources", snap)

    # ---- object directory (owner-based location parity) -------------------

    # Recently-freed guard: a worker finishing a task AFTER the caller
    # already dropped the return ref re-registers an object the head
    # just freed; without this, that late registration resurrects a
    # location entry for an owner-less object (it would linger until
    # LRU). Bounded FIFO — the race window is sub-second.
    _RECENT_FREED_CAP = 100_000

    def register_objects(self, node_id: str, oid_hexes: List[str]):
        with self._lock:
            rf = getattr(self, "_recently_freed", None)
            for oid_hex in oid_hexes:
                if rf is not None and oid_hex in rf:
                    continue     # freed already: don't resurrect
                self._obj_locs.setdefault(oid_hex,
                                           _OrderedSet()).add(node_id)

    def locate_objects(self, oid_hexes: List[str]
                       ) -> Dict[str, List[Dict[str, str]]]:
        """Batch location lookup (no probing/reconstruction — the
        per-object slow path handles those)."""
        out: Dict[str, List[Dict[str, str]]] = {}
        with self._lock:
            for oid_hex in oid_hexes:
                node_ids = [nid for nid in
                            self._obj_locs.get(oid_hex, ())
                            if nid in self._nodes and
                            self._nodes[nid].alive]
                if node_ids:
                    out[oid_hex] = [
                        {"node_id": nid,
                         "object_addr": self._nodes[nid].object_addr}
                        for nid in node_ids]
        return out

    _PULL_SLOT_TTL_S = 120.0        # reclaim slots of dead pullers

    def begin_pull(self, oid_hex: str, node_id: str,
                   probe: bool = False, reconstruct: bool = False):
        """Admission-controlled source selection for a BULK pull
        (callers gate on bulk_pull_threshold_bytes).

        Two caps (reference: push_manager.h:29 in-flight transfer
        caps, driven from the directory side):
        - per source: each replica serves at most
          bulk_pull_slots_per_source concurrent pullers, so an N-node
          broadcast disseminates along a doubling tree (owner→A;
          owner→B, A→C; …) instead of N pullers thrashing the owner;
        - global: at most bulk_pull_global_slots bulk transfers run
          cluster-wide — on shared/virtualized hosts concurrent bulk
          memory traffic degrades superlinearly, so near-serial
          transfer IS the fast path there.

        Returns a location, {"busy": True} when budgets are exhausted
        (caller backs off hard), or None when no copy exists."""
        from ray_tpu._private.config import GlobalConfig
        locs = self.locate_object(oid_hex, probe=probe,
                                  reconstruct=reconstruct)
        if not locs:
            return None
        per_source = GlobalConfig.bulk_pull_slots_per_source
        global_cap = GlobalConfig.bulk_pull_global_slots
        now = time.time()
        with self._lock:
            pulls = getattr(self, "_pulls", None)
            if pulls is None:
                pulls = self._pulls = {}
            # Reclaim reservations whose puller died/hung, and total
            # each SOURCE's in-flight transfers across ALL objects —
            # the per-source cap protects the replica process, so it
            # must count every object it is serving.
            total_inflight = 0
            src_load: Dict[str, int] = {}
            for key in list(pulls):
                slots = pulls[key]
                for src in list(slots):
                    slots[src] = [t for t in slots[src]
                                  if t > now - self._PULL_SLOT_TTL_S]
                    if not slots[src]:
                        del slots[src]
                    else:
                        n_src = len(slots[src])
                        total_inflight += n_src
                        src_load[src] = src_load.get(src, 0) + n_src
                if not slots:
                    del pulls[key]
            slots = pulls.setdefault(oid_hex, {})
            best = None
            any_peer = False
            # First-fit in registration order: the first location is
            # the original producer — keeping it the preferred source
            # concentrates serving in one warm process (replicas only
            # absorb spillover once the producer's slots fill).
            for loc in locs:
                if loc["node_id"] == node_id:
                    continue
                any_peer = True
                if total_inflight >= global_cap:
                    continue
                if src_load.get(loc["node_id"], 0) < per_source:
                    best = loc
                    break
            if best is None:
                # Distinguish "replicas exist but are saturated" from
                # "no copy anywhere": a busy caller must back off HARD
                # (on a contended host the waiters' polling otherwise
                # steals the CPU the transfer needs), while a
                # no-location caller keeps its fast retry (the object
                # is probably about to be registered by its producer).
                return {"busy": True} if any_peer else None
            slots.setdefault(best["node_id"], []).append(now)
        best = dict(best)
        best["slot_ts"] = now       # end_pull releases THIS stamp
        return best

    def end_pull(self, oid_hex: str, node_id: str, source_node: str,
                 slot_ts: float = 0.0):
        with self._lock:
            pulls = getattr(self, "_pulls", None)
            if not pulls:
                return
            slots = pulls.get(oid_hex)
            if not slots:
                return
            ts = slots.get(source_node)
            if ts:
                # Release the finishing pull's OWN stamp (popping an
                # arbitrary one would age a still-running pull's slot
                # toward TTL reclamation and overshoot the caps).
                if slot_ts in ts:
                    ts.remove(slot_ts)
                else:
                    ts.pop()
                if not ts:
                    del slots[source_node]
            if not slots:
                del pulls[oid_hex]

    def unregister_object(self, oid_hex: str, node_id: str):
        with self._lock:
            locs = self._obj_locs.get(oid_hex)
            if locs is not None:
                locs.discard(node_id)
                if not locs:
                    del self._obj_locs[oid_hex]

    # ---- distributed borrower protocol ------------------------------------
    # The owner-eager-GC extension for ESCAPED refs (reference:
    # reference_count.h:39-61 — the owner tracks borrowers and frees
    # only after every borrow drops). Head-brokered here: borrowers
    # register/drop with the head (batched, async), owners report
    # their own last-ref drop, and the head frees an escaped object
    # once owner_released AND borrows==0 AND a grace window has passed
    # since the last escape (covering the pickle->deserialize gap
    # where a borrow exists on the wire but is not yet registered).

    def _pin_args_locked(self, meta) -> None:
        """Pin a queued/running task's ref args against borrower-
        protocol eager free (reference: task specs hold references
        until the task completes, reference_count.h). Mirrors
        _task_meta's lifecycle exactly: pinned at ingest/requeue,
        unpinned wherever the meta leaves the table."""
        pins = getattr(self, "_arg_pins", None)
        if pins is None:
            pins = self._arg_pins = {}
        for oh in meta.get("pin_oids", ()):
            pins[oh] = pins.get(oh, 0) + 1

    def _unpin_args_locked(self, meta) -> None:
        if not meta:
            return
        pins = getattr(self, "_arg_pins", None)
        if not pins:
            return
        st = getattr(self, "_borrows", None)
        from ray_tpu._private.config import GlobalConfig
        now = time.time()
        for oh in meta.get("pin_oids", ()):
            n = pins.get(oh, 0) - 1
            if n > 0:
                pins[oh] = n
                continue
            pins.pop(oh, None)
            # Last pin gone: if the owner already released and no
            # borrows remain, start the free clock now.
            if st:
                ent = st.get(oh)
                if ent and ent["released"] and ent["n"] == 0 and \
                        ent["free_at"] is None:
                    ent["free_at"] = now + GlobalConfig.borrow_grace_s

    def _borrow_state(self) -> Dict[str, Dict[str, Any]]:
        st = getattr(self, "_borrows", None)
        if st is None:
            st = self._borrows = {}
        return st

    def add_borrows(self, oid_hexes: List[str],
                    node_id: str = "") -> None:
        with self._lock:
            st = self._borrow_state()
            for oh in oid_hexes:
                ent = st.setdefault(oh, {"n": 0, "released": False,
                                         "free_at": None,
                                         "by_node": {}})
                ent["n"] += 1
                bn = ent.setdefault("by_node", {})
                bn[node_id] = bn.get(node_id, 0) + 1

    def drop_borrows(self, oid_hexes: List[str],
                     node_id: str = "") -> None:
        from ray_tpu._private.config import GlobalConfig
        grace = GlobalConfig.borrow_grace_s
        now = time.time()
        with self._lock:
            st = self._borrow_state()
            for oh in oid_hexes:
                ent = st.get(oh)
                if ent is None:
                    continue
                ent["n"] = max(0, ent["n"] - 1)
                bn = ent.get("by_node")
                if bn is not None and node_id in bn:
                    bn[node_id] -= 1
                    if bn[node_id] <= 0:
                        del bn[node_id]
                if ent["n"] == 0:
                    pins = getattr(self, "_arg_pins", None) or {}
                    if ent["released"]:
                        if not pins.get(oh):
                            # Grace after the LAST drop too: the
                            # borrower may have re-pickled the ref to
                            # a third process whose registration is
                            # still in flight.
                            ent["free_at"] = now + grace
                    else:
                        del st[oh]              # owner still holds it
        self._sweep_borrows(now)

    def owner_released(self, items: List) -> None:
        """Owner's last local ref dropped for escaped objects.
        items: [(oid_hex, seconds_since_last_escape), ...]."""
        from ray_tpu._private.config import GlobalConfig
        grace = GlobalConfig.borrow_grace_s
        now = time.time()
        with self._lock:
            st = self._borrow_state()
            pins = getattr(self, "_arg_pins", None) or {}
            for oh, age in items:
                ent = st.setdefault(oh, {"n": 0, "released": False,
                                         "free_at": None})
                ent["released"] = True
                if ent["n"] == 0 and not pins.get(oh):
                    ent["free_at"] = now + max(0.0, grace - age)
        self._sweep_borrows(now)

    def _reconcile_borrows_for_dead_node(self, node_id: str) -> None:
        """A dead node's borrow registrations can never be dropped by
        their (dead) borrowers: forget them so escaped objects still
        free eagerly instead of leaking the head entry forever
        (reference: the owner clears borrowers on borrower death,
        reference_count.h). Borrows from surviving processes on other
        nodes are untouched. (A single crashed WORKER on a live node
        is narrower: its borrows fall back to the LRU bound.)"""
        from ray_tpu._private.config import GlobalConfig
        grace = GlobalConfig.borrow_grace_s
        now = time.time()
        with self._lock:
            st = getattr(self, "_borrows", None)
            if not st:
                return
            pins = getattr(self, "_arg_pins", None) or {}
            for oh in list(st):
                ent = st[oh]
                bn = ent.get("by_node")
                if not bn or node_id not in bn:
                    continue
                dead = bn.pop(node_id)
                ent["n"] = max(0, ent["n"] - dead)
                if ent["n"] == 0:
                    if ent["released"]:
                        if not pins.get(oh):
                            ent["free_at"] = now + grace
                    else:
                        del st[oh]
        self._sweep_borrows(now)

    def _sweep_borrows(self, now: float) -> None:
        ready = []
        with self._lock:
            st = getattr(self, "_borrows", None)
            if not st:
                return
            for oh in list(st):
                ent = st[oh]
                if ent["released"] and ent["n"] == 0 and \
                        ent["free_at"] is not None and \
                        now >= ent["free_at"]:
                    ready.append(oh)
                    del st[oh]
        if ready:
            self.free_objects(ready)

    def free_objects(self, oid_hexes: List[str]):
        """Owner-driven eager free (reference: reference_count.h:39-61
        owner releases -> deletes broadcast to holders): the owner's
        last ref dropped, so every node's copy can go NOW instead of
        waiting for LRU pressure. Location directory and lineage are
        cleared (a deliberately freed object must not be rebuilt); the
        delete rides the pub/sub hub to every node agent. Processed in
        chunks: a million-ref drop must not hold the head lock or ship
        one giant pub/sub frame while transfers are in flight."""
        CHUNK = 20000
        for i in range(0, len(oid_hexes), CHUNK):
            part = oid_hexes[i:i + CHUNK]
            with self._lock:
                rf = getattr(self, "_recently_freed", None)
                if rf is None:
                    import collections as _c
                    rf = self._recently_freed = _c.OrderedDict()
                for oid_hex in part:
                    self._obj_locs.pop(oid_hex, None)
                    ent = self._lineage.pop(oid_hex, None)
                    if ent is not None:
                        self._lineage_bytes -= ent.get("cost", 0)
                    rf[oid_hex] = True
                while len(rf) > self._RECENT_FREED_CAP:
                    rf.popitem(last=False)
            self.hub.publish_stream("object_free", {"oids": part})

    def locate_object(self, oid_hex: str, probe: bool = False,
                      reconstruct: bool = False) -> List[Dict[str, str]]:
        """Live locations of an object. `probe=True` additionally asks
        every node's object service on a directory miss (covers puts
        whose async registration hasn't landed). `reconstruct=True`
        resubmits the creating task from lineage when no copy is left."""
        now = time.time()
        with self._lock:
            node_ids = [nid for nid in self._obj_locs.get(oid_hex, ())
                        if nid in self._nodes and
                        self._nodes[nid].alive]
            out = [{"node_id": nid,
                    "object_addr": self._nodes[nid].object_addr}
                   for nid in node_ids]
            probe_targets = []
            if not out and probe:
                # Probing fans an RPC to every node: rate-limit it to
                # one sweep per object per 500 ms so M waiting getters
                # polling every few ms don't turn into O(M*N) probe
                # traffic (the common miss — a task still running — is
                # answered by registration, not probing).
                probes = getattr(self, "_probe_at", None)
                if probes is None:
                    probes = self._probe_at = {}
                if probes.get(oid_hex, 0) <= now:
                    probes[oid_hex] = now + 0.5
                    if len(probes) > 10000:
                        for k in [k for k, t in probes.items()
                                  if t <= now]:
                            del probes[k]
                    probe_targets = [
                        (n.node_id, n.object_client, n.object_addr)
                        for n in self._nodes.values() if n.alive]
        if probe_targets:
            # Parallel probe sweep: serial per-node RPCs would make a
            # directory miss cost O(nodes x timeout) — quadratic
            # badness at 50 nodes (each node's miss loop probing all
            # others). A SHARED bounded executor (not per-sweep thread
            # spawns) caps concurrent probes cluster-wide; stragglers
            # past the wait deadline finish in the pool instead of
            # leaking fresh threads.
            from concurrent.futures import ThreadPoolExecutor, wait
            pool = getattr(self, "_probe_pool", None)
            if pool is None:
                pool = self._probe_pool = ThreadPoolExecutor(
                    max_workers=16, thread_name_prefix="obj-probe")
            found: List = []
            flock = threading.Lock()

            def _probe(nid, client, addr):
                try:
                    if client.call("has_object", oid_hex, timeout=2):
                        with flock:
                            found.append((nid, addr))
                except RpcError:
                    pass

            futs = [pool.submit(_probe, *t) for t in probe_targets]
            wait(futs, timeout=3)
            for nid, addr in found:
                self.register_objects(nid, [oid_hex])
                out.append({"node_id": nid, "object_addr": addr})
        if not out and reconstruct:
            self._maybe_reconstruct(oid_hex)
        return out

    # ---- lineage / reconstruction -----------------------------------------

    def _record_lineage_locked(self, meta: Dict[str, Any]):
        payload = meta.get("payload")
        if payload is None:
            return
        cost = len(payload)
        entry = {"meta": {k: meta[k] for k in
                          ("task_id", "return_ids", "resources",
                           "max_retries", "pg_id", "env_key",
                           "runtime_env", "strategy") if k in meta},
                 "payload": payload}
        for rid in meta.get("return_ids", ()):
            rid_hex = rid.hex() if isinstance(rid, bytes) else rid
            old = self._lineage.pop(rid_hex, None)
            if old is not None:
                self._lineage_bytes -= old["cost"]
            self._lineage[rid_hex] = {"entry": entry, "cost": cost}
            self._lineage_bytes += cost
        while self._lineage_bytes > self._lineage_budget and \
                self._lineage:
            _, dropped = self._lineage.popitem(last=False)
            self._lineage_bytes -= dropped["cost"]

    def _enqueue_locked(self, task_id: str, meta: Dict[str, Any]):
        strat = meta.get("strategy")
        sig = (tuple(sorted(meta.get("resources", {}).items())),
               meta.get("pg_id"), meta.get("env_key"),
               tuple(sorted(strat.items())) if strat else None,
               bool(meta.get("arg_oids")))
        self._pending.setdefault(sig, collections.deque()).append(
            task_id)
        self._sched_cv.notify_all()

    def _pending_count_locked(self) -> int:
        return sum(len(q) for q in self._pending.values())

    def _maybe_reconstruct(self, oid_hex: str) -> bool:
        """Resubmit the creating task of a lost object (lineage
        reconstruction parity, object_recovery_manager.h:41)."""
        with self._lock:
            rec = self._lineage.get(oid_hex)
            if rec is None:
                return False
            meta = dict(rec["entry"]["meta"])
            task_id = meta["task_id"]
            live = self._task_meta.get(task_id)
            if live is not None and live.get("state") in (
                    "pending", "dispatched"):
                return True     # already being rebuilt
            meta["payload"] = rec["entry"]["payload"]
            meta["attempt"] = 0
            meta["state"] = "pending"
            meta["reconstruction"] = True
            self._task_meta[task_id] = meta
            self._pin_args_locked(meta)
            # The task is live again: a lingering FINISHED row would
            # contradict the pending one in list_tasks.
            rec = getattr(self, "_done_tasks", None)
            if rec is not None:
                rec.pop(task_id, None)
            self._enqueue_locked(task_id, meta)
            return True

    # ---- pub/sub RPC ------------------------------------------------------

    def psub_poll(self, state_versions=None, stream_seqs=None,
                  poll_timeout: float = 30.0):
        return self.hub.poll(state_versions, stream_seqs,
                             timeout=poll_timeout)

    def psub_stream_seq(self, channel: str) -> int:
        """Next sequence number of a stream channel — late subscribers
        start here instead of replaying the retained history."""
        return self.hub.next_seq(channel)

    def publish(self, channel: str, value: Any, stream: bool = False):
        if stream:
            return self.hub.publish_stream(channel, value)
        return self.hub.publish_state(channel, value)

    # ---- worker membership ------------------------------------------------

    def register_worker(self, worker_id: str, address: str,
                        resources: Dict[str, float],
                        node_id: str = "head",
                        env_key: Optional[str] = None
                        ) -> Dict[str, Any]:
        with self._lock:
            self._workers[worker_id] = _WorkerInfo(
                worker_id, address, resources, node_id, env_key)
            self._try_recover_pgs_locked()
            self._sched_cv.notify_all()
            node = self._nodes.get(node_id)
            store = node.store_name if node else self.store_name
        self._sync_resources()
        return {"store_name": store, "multinode": self.node_count() > 1}

    def worker_heartbeat(self, worker_id: str) -> bool:
        """False tells the worker this head doesn't know it (restarted
        head, or it was marked dead) — re-register + report_actors."""
        with self._lock:
            w = self._workers.get(worker_id)
            return w is not None and w.alive

    def report_actors(self, worker_id: str,
                      actor_ids: List[str]) -> None:
        """Worker re-attaching after a head restart re-binds the actors
        it hosts (the directory was restored from the snapshot with
        empty bindings)."""
        with self._lock:
            for aid in actor_ids:
                a = self._actors.get(aid)
                if a is not None and not a.dead and a.worker_id == "":
                    # Only fill EMPTY bindings: a live binding means a
                    # restart already placed the actor elsewhere (the
                    # reporter holds a stale instance).
                    a.worker_id = worker_id
            self._sched_cv.notify_all()

    def mark_worker_dead(self, worker_id: str):
        """Called by the node manager when a worker process dies."""
        with self._lock:
            # A spawned env worker can die BEFORE registering (setup
            # crash): remember the id so the env-spawn tracker knows
            # its in-flight spawn is gone and may retry.
            done = getattr(self, "_env_spawn_done", None)
            if done is None:
                done = self._env_spawn_done = collections.deque(
                    maxlen=256)
            done.append(worker_id)
            w = self._workers.get(worker_id)
            if w is None or not w.alive:
                return
            w.alive = False
            running = list(w.running)
            w.running.clear()
            w.running_res.clear()
            dead_actors = [a for a in self._actors.values()
                           if a.worker_id == worker_id and not a.dead]
        # Push-based death broadcast (reference: worker failure events
        # over GCS pub/sub) — actor-handle holders and monitors
        # subscribe instead of polling list_workers.
        self.hub.publish_stream(
            "worker_events", {"type": "worker_dead",
                              "worker_id": worker_id,
                              "ts": time.time()})
        self._sync_resources()
        # Fail or retry tasks that were on that worker.
        for task_id in running:
            self._handle_lost_task(task_id)
        # Restart or kill its actors.
        for a in dead_actors:
            self._handle_lost_actor(a)

    def list_workers(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [{"worker_id": w.worker_id, "address": w.address,
                     "alive": w.alive, "resources": dict(w.resources),
                     "available": dict(w.available),
                     "running_tasks": list(w.running)}
                    for w in self._workers.values()]

    # Completed tasks kept for the state API (bounded ring; reference:
    # the task-events buffer behind list_tasks, GcsTaskManager).
    _DONE_TASKS_CAP = 2000

    def _record_task_done_locked(self, task_id: str, meta,
                                 state: str) -> None:
        rec = getattr(self, "_done_tasks", None)
        if rec is None:
            import collections as _c
            rec = self._done_tasks = _c.OrderedDict()
        rec[task_id] = {"task_id": task_id,
                        "name": (meta or {}).get("name", ""),
                        "state": state,
                        "end_time": time.time()}
        while len(rec) > self._DONE_TASKS_CAP:
            rec.popitem(last=False)

    def cancel_task(self, task_id: str, force: bool = False) -> str:
        """Cancel a task (reference: CoreWorker CancelTask). Queued
        tasks dequeue with TaskCancelledError. Running tasks are
        interrupted only with force=True — delivered as an async
        TaskCancelledError into the executing THREAD (this executor
        multiplexes tasks, so the reference's kill-the-worker force
        path would take out co-resident tasks; see
        Executor.cancel_task_exec for the interruption window).
        Returns "cancelled" | "running" | "interrupted" | "done"
        ("done" also covers refs that never were task returns — put()
        refs are not distinguishable and never cancellable).
        recursive child-cancellation is NOT yet implemented."""
        from ray_tpu.exceptions import TaskCancelledError
        with self._lock:
            meta = self._task_meta.get(task_id)
            if meta is None:
                if task_id in getattr(self, "_done_tasks", {}):
                    return "done"    # genuinely finished
                # Unknown: the submission may still be in the client's
                # flush buffer (cancel raced it here). Mark it so the
                # ingest drops it on arrival — otherwise a cancel
                # issued right after .remote() silently no-ops.
                pc = getattr(self, "_precancelled", None)
                if pc is None:
                    import collections as _c
                    pc = self._precancelled = _c.OrderedDict()
                pc[task_id] = True
                while len(pc) > 10000:
                    pc.popitem(last=False)
                return "cancelled"
            running_worker = None
            for w in self._workers.values():
                if task_id in w.running:
                    running_worker = w
                    break
            if running_worker is None:
                # Still queued: drop it from its pending lane.
                for sig, queue in self._pending.items():
                    if task_id in queue:
                        queue.remove(task_id)
                        break
                self._task_meta.pop(task_id, None)
                self._unpin_args_locked(meta)
                self._record_task_done_locked(task_id, meta,
                                              "CANCELLED")
                rids = meta["return_ids"]
            elif not force:
                return "running"     # no safe in-band interruption
        if running_worker is None:
            self._store_error(rids, TaskCancelledError(task_id))
            return "cancelled"
        # The interrupted task fails through the NORMAL completion
        # path (its thread raises, the error is written to the
        # returns, tasks_done releases resources) — no retry budget
        # surgery, no worker death, no capacity loss. A "not-running"
        # reply means it finished between our check and delivery.
        try:
            r = running_worker.client.call("cancel_task_exec",
                                           task_id, timeout=10)
        except Exception:
            return "running"         # unreachable: nothing cancelled
        return "interrupted" if r == "interrupted" else "done"

    def list_objects(self) -> List[Dict[str, Any]]:
        """State-API object listing from the location directory
        (reference: list_objects over the object table). Single-node
        clusters skip per-object registration, so entries appear once
        a second node joins (directory-backed, like the reference's
        GCS-backed listing)."""
        import itertools
        CAP = 10000
        with self._lock:
            out = []
            borrows = getattr(self, "_borrows", {})
            for oid_hex, nodes in itertools.islice(
                    self._obj_locs.items(), CAP):
                ent = borrows.get(oid_hex)
                out.append({"object_id": oid_hex,
                            "locations": list(nodes),
                            "borrows": ent["n"] if ent else 0})
            truncated = len(self._obj_locs) > CAP
        if truncated:
            out.append({"object_id": "...",
                        "truncated": True,
                        "locations": [],
                        "borrows": 0})
        return out

    def list_tasks(self) -> List[Dict[str, Any]]:
        """State-API task listing (reference:
        experimental/state/api.py list_tasks): queued + running from
        the live tables, finished from the bounded ring."""
        out: List[Dict[str, Any]] = []
        with self._lock:
            running = set()
            for w in self._workers.values():
                running.update(w.running)
            for task_id, meta in self._task_meta.items():
                out.append({
                    "task_id": task_id,
                    "name": meta.get("name", ""),
                    "state": "RUNNING" if task_id in running
                             else "PENDING",
                    "attempt": meta.get("attempt", 0),
                })
            for rec in reversed(
                    getattr(self, "_done_tasks", {}).values()):
                out.append(dict(rec))
        return out

    def cluster_resources(self) -> Dict[str, float]:
        with self._lock:
            return self._aggregate_resources_locked()[0]

    def available_resources(self) -> Dict[str, float]:
        with self._lock:
            return self._aggregate_resources_locked()[1]

    # ---- function table (function_manager.py parity) ----------------------

    def register_function(self, fn_id: str, blob: bytes):
        with self._lock:
            if not hasattr(self, "_functions"):
                self._functions = {}
            self._functions[fn_id] = blob
        self._dirty()

    def get_function(self, fn_id: str) -> Optional[bytes]:
        with self._lock:
            return getattr(self, "_functions", {}).get(fn_id)

    # ---- KV (gcs internal kv parity) -------------------------------------

    def kv_put(self, key: str, value: bytes):
        with self._lock:
            self._kv[key] = value
        self._dirty()

    def kv_get(self, key: str) -> Optional[bytes]:
        with self._lock:
            return self._kv.get(key)

    def kv_del(self, key: str):
        with self._lock:
            self._kv.pop(key, None)
        self._dirty()

    def kv_keys(self, prefix: str = "") -> List[str]:
        with self._lock:
            return [k for k in self._kv if k.startswith(prefix)]

    # ---- error reporting into the object store ---------------------------

    def _store_error(self, return_ids: List[bytes], exc: BaseException):
        store = self._get_store()
        payload = dumps(("err", exc))
        for rid in return_ids:
            try:
                store.put_bytes(ObjectID(rid), payload)
            except Exception:
                pass  # already stored
        # Error objects live in the head node's store; remote getters
        # find them through the directory.
        self.register_objects(
            "head", [rid.hex() for rid in return_ids])

    # ---- normal tasks -----------------------------------------------------

    def submit_task(self, meta: Dict[str, Any], payload: bytes):
        """meta: task_id, return_ids [bytes], resources, max_retries,
        pg_id (optional). payload: pickled executable spec."""
        self.submit_tasks([(meta, payload)])

    def submit_tasks(self, batch: List[Tuple[Dict[str, Any], bytes]]):
        """Batched submission: one lock acquire + one scheduler wake
        for a whole client-side flush window."""
        precancel_rids = []
        with self._lock:
            pc = getattr(self, "_precancelled", None)
            for meta, payload in batch:
                meta = dict(meta)
                if pc and pc.pop(meta["task_id"], None):
                    # Cancelled before arrival: never enqueue.
                    self._record_task_done_locked(
                        meta["task_id"], meta, "CANCELLED")
                    precancel_rids.append(meta["return_ids"])
                    continue
                meta["payload"] = payload
                meta["attempt"] = 0
                meta["state"] = "pending"
                self._task_meta[meta["task_id"]] = meta
                self._pin_args_locked(meta)
                strat = meta.get("strategy")
                sig = (tuple(sorted(meta.get("resources",
                                             {}).items())),
                       meta.get("pg_id"), meta.get("env_key"),
                       tuple(sorted(strat.items())) if strat else None,
                       bool(meta.get("arg_oids")))
                self._pending.setdefault(
                    sig, collections.deque()).append(meta["task_id"])
            self._sched_cv.notify_all()
        if precancel_rids:
            from ray_tpu.exceptions import TaskCancelledError
            for rids in precancel_rids:
                self._store_error(rids, TaskCancelledError())

    def task_blocked(self, worker_id: str, resources: Dict[str, float]):
        """Worker reports a task blocked in get(): release its resources
        (unblocked-worker oversubscription semantics, as in local mode)."""
        with self._lock:
            w = self._workers.get(worker_id)
            if w and w.alive:
                for k, v in resources.items():
                    w.available[k] = min(w.resources.get(k, 0.0),
                                         w.available.get(k, 0.0) + v)
                self._sched_cv.notify_all()

    def task_unblocked(self, worker_id: str,
                       resources: Dict[str, float]) -> bool:
        with self._lock:
            w = self._workers.get(worker_id)
            if w is None or not w.alive:
                return False
            for k, v in resources.items():
                w.available[k] = w.available.get(k, 0.0) - v
            return True

    def _scheduler_loop(self):
        while not self._shutdown:
            with self._lock:
                progressed = self._try_dispatch_locked()
                if not progressed:
                    self._sched_cv.wait(timeout=0.05)

    def _pick_worker_locked(self, resources: Dict[str, float],
                            pg_id: Optional[str],
                            env_key: Optional[str] = None,
                            strategy: Optional[Dict[str, Any]] = None,
                            arg_oids: Optional[List[str]] = None
                            ) -> Optional[_WorkerInfo]:
        """Placement policies (reference:
        src/ray/raylet/scheduling/policy/*_scheduling_policy.cc):

        - default: hybrid — pack onto the head node while its
          utilization stays under scheduler_spread_threshold, then
          spill to the least-loaded feasible worker anywhere
          (hybrid_scheduling_policy.cc shape).
        - locality (default + object args): among feasible workers,
          prefer the node holding the most argument objects — the
          LeasePolicy locality path (core_worker/lease_policy.cc).
        - spread: fewest-running NODE first, then worker
          (spread_scheduling_policy.cc).
        - node_affinity: only that node; soft=True spills back to the
          hybrid choice when the node is gone or full
          (node_affinity_scheduling_policy.cc).
        """
        if pg_id is not None:
            pg = self._pgs.get(pg_id)
            if not pg or not pg["ready"]:
                return None
            # Run inside the reservation on one of the PG's workers.
            for wid in pg["workers"]:
                w = self._workers.get(wid)
                if w and w.alive:
                    return w
            return None
        feasible = []
        for w in self._workers.values():
            if not w.alive:
                continue
            # Runtime-env isolation (worker_pool.h:149 parity): tasks
            # with an env run ONLY in that env's dedicated workers, and
            # env-less tasks never land in env workers — concurrent
            # executions cannot observe each other's environment.
            if w.env_key != env_key:
                continue
            if all(w.available.get(k, 0.0) + 1e-9 >= v
                   for k, v in resources.items()):
                feasible.append(w)
        if not feasible:
            return None

        def least_loaded(ws):
            return min(ws, key=lambda w: len(w.running))

        stype = (strategy or {}).get("type")
        if stype == "node_affinity":
            on_node = [w for w in feasible
                       if w.node_id == strategy.get("node_id")]
            if on_node:
                return least_loaded(on_node)
            if strategy.get("soft"):
                return least_loaded(feasible)   # spillback
            return None
        if stype == "spread":
            by_node: Dict[str, List[_WorkerInfo]] = {}
            for w in feasible:
                by_node.setdefault(w.node_id, []).append(w)
            node_load = {nid: sum(len(w.running) for w in ws)
                         for nid, ws in by_node.items()}
            nid = min(node_load, key=node_load.get)
            return least_loaded(by_node[nid])
        if arg_oids:
            # Locality: count arg objects already on each node.
            node_score: Dict[str, int] = {}
            for oid_hex in arg_oids:
                for nid in self._obj_locs.get(oid_hex, ()):
                    node_score[nid] = node_score.get(nid, 0) + 1
            if node_score:
                best_nid = max(node_score, key=node_score.get)
                local = [w for w in feasible
                         if w.node_id == best_nid]
                if local:
                    return least_loaded(local)
        # Hybrid default: pack the head node under the threshold.
        from ray_tpu._private.config import GlobalConfig
        threshold = GlobalConfig.scheduler_spread_threshold
        head_ws = [w for w in feasible if w.node_id == "head"]
        if head_ws:
            cap = sum(max(1.0, w.resources.get("CPU", 1.0))
                      for w in head_ws)
            used = sum(len(w.running) for w in head_ws)
            if used / cap < threshold:
                return least_loaded(head_ws)
        return least_loaded(feasible)

    def _try_dispatch_locked(self) -> bool:
        progressed = False
        for sig in list(self._pending):
            queue = self._pending.get(sig)
            if queue is None:
                # a fail-fast path (env setup failure) deleted this
                # sig after the snapshot was taken
                continue
            while queue:
                task_id = queue[0]
                meta = self._task_meta.get(task_id)
                if meta is None or meta.get("state") != "pending":
                    queue.popleft()     # stale duplicate queue entry
                    continue
                res = meta.get("resources", {})
                pg_id = meta.get("pg_id")
                env_key = meta.get("env_key")
                w = self._pick_worker_locked(
                    res, pg_id, env_key, meta.get("strategy"),
                    meta.get("arg_oids"))
                if w is None:
                    if env_key is not None:
                        self._ensure_env_worker_locked(
                            env_key, meta.get("runtime_env"), res)
                    break    # this shape can't place now; next shape
                queue.popleft()
                if pg_id is None:
                    for k, v in res.items():
                        w.available[k] = w.available.get(k, 0.0) - v
                w.running.add(task_id)
                w.running_res[task_id] = (dict(res), pg_id)
                w.last_active = time.time()
                meta["state"] = "dispatched"
                meta["worker_id"] = w.worker_id
                if w.sender is None or not w.sender.is_alive():
                    w.sender = threading.Thread(
                        target=self._sender_loop, args=(w,),
                        daemon=True,
                        name=f"head-send-{w.worker_id[:12]}")
                    w.sender.start()
                w.outbox.put(meta)
                progressed = True
            if not queue:
                del self._pending[sig]
        return progressed

    def _sender_loop(self, w: _WorkerInfo):
        """Per-worker dispatch sender: drains the outbox, pushing each
        task as a ONE-WAY pipelined send (measured: a request/reply
        dispatch costs ~2.2 ms under worker GIL load and serializes the
        per-worker rate at ~450 tasks/s; one-way sends are ~10 us).
        Delivery failure surfaces as a send error or through the worker
        death monitor — either way mark_worker_dead retries everything
        in w.running. Per-worker ordering rides on the dedicated
        one-way socket."""
        import queue as _queue
        while not self._shutdown:
            try:
                meta = w.outbox.get(timeout=0.5)
            except Exception:
                if not w.alive:
                    return
                continue
            if meta is None:
                return
            # Greedy batch: everything already queued ships as one
            # one-way RPC (amortizes envelope pickling + syscalls).
            batch = [meta]
            while len(batch) < 128:
                try:
                    nxt = w.outbox.get_nowait()
                except _queue.Empty:
                    break
                if nxt is None:
                    break
                batch.append(nxt)
            failure: Optional[BaseException] = None
            for _attempt in range(2):
                try:
                    w.client.call_oneway(
                        "push_tasks", [m["payload"] for m in batch],
                        fast=True)
                    failure = None
                    break
                except RpcError as e:
                    # One retry: a stale socket raises the same error
                    # as a dead worker; the retry reconnects.
                    failure = e
            if failure is not None:
                # Unreachable worker == death detection (don't wait for
                # the node monitor poll).
                self.mark_worker_dead(w.worker_id)
                for m in batch:
                    self._handle_lost_task(m["task_id"])
                return

    def env_setup_failed(self, env_key: str, message: str):
        """A dedicated env worker failed its environment setup (pip
        install error, bad working_dir, ...) before registering: fail
        every queued task for that env with the real error instead of
        hanging the callers, and stop respawning for a while
        (reference: runtime-env agent setup errors fail the task with
        RuntimeEnvSetupError)."""
        with self._lock:
            failures = getattr(self, "_env_failures", None)
            if failures is None:
                failures = self._env_failures = {}
            failures[env_key] = (time.time(), message)
            self._fail_env_tasks_locked(env_key, message)
            self._sched_cv.notify_all()

    def _fail_env_tasks_locked(self, env_key: str, message: str):
        err = RuntimeError(
            f"runtime_env setup failed for this task's environment: "
            f"{message}")
        doomed = []
        for sig, queue in list(self._pending.items()):
            if sig[2] != env_key:      # sig: (res, pg, env_key, ...)
                continue
            for task_id in queue:
                meta = self._task_meta.pop(task_id, None)
                self._unpin_args_locked(meta)
                if meta is not None:
                    self._record_task_done_locked(task_id, meta,
                                                  "FAILED")
                    doomed.append(meta["return_ids"])
            del self._pending[sig]
        if doomed:
            def _store():
                for rids in doomed:
                    self._store_error(rids, err)
            threading.Thread(target=_store, daemon=True).start()

    def _ensure_env_worker_locked(self, env_key: str,
                                  runtime_env: Optional[Dict],
                                  resources: Optional[Dict] = None):
        """Spawn one dedicated worker for a runtime-env key when no
        FEASIBLE one exists (worker_pool StartWorkerProcess parity).
        At most one spawn in flight per key: the cooldown stays armed
        while the spawned process is still setting up (pip installs
        can take minutes) and is disarmed when it registers or dies."""
        if runtime_env is None:
            return
        failures = getattr(self, "_env_failures", {})
        failed = failures.get(env_key)
        if failed is not None:
            if time.time() - failed[0] < 60:
                # recent deterministic failure: fail fast instead of
                # respawn-looping; retry window after 60s
                self._fail_env_tasks_locked(env_key, failed[1])
                return
            failures.pop(env_key, None)
        need = dict(resources or {})
        if any(w.env_key == env_key and w.alive and
               all(w.resources.get(k, 0.0) + 1e-9 >= v
                   for k, v in need.items())
               for w in self._workers.values()):
            return
        spawns = getattr(self, "_env_spawns", None)
        if spawns is None:
            spawns = self._env_spawns = {}
        ent = spawns.get(env_key)
        if ent is not None:
            deadline, wid = ent
            if wid is not None and (
                    wid in self._workers
                    or wid in getattr(self, "_env_spawn_done", ())):
                spawns.pop(env_key, None)   # registered or died
            elif time.time() < deadline:
                return                      # still starting up
        # generous deadline: setup may build a venv
        spawns[env_key] = (time.time() + 600, None)
        ns = getattr(self, "_node_service", None)
        if ns is None:
            return

        spawn_res = dict(need)
        spawn_res["CPU"] = max(1.0, spawn_res.get("CPU", 1.0))

        def spawn():
            try:
                wid = ns.call("start_worker", ns.call("num_workers"),
                              spawn_res, runtime_env)
                with self._lock:
                    ent = spawns.get(env_key)
                    if ent is not None:
                        spawns[env_key] = (ent[0], wid)
            except Exception:
                with self._lock:
                    spawns.pop(env_key, None)

        threading.Thread(target=spawn, daemon=True,
                         name=f"env-spawn-{env_key[:8]}").start()

    def _reap_idle_env_workers_locked(self):
        """Idle reaping for dedicated env workers (worker_pool idle
        reaping parity): no running tasks, no actors, idle past the
        timeout -> stop the process."""
        from ray_tpu._private.config import GlobalConfig
        timeout = GlobalConfig.env_worker_idle_timeout_s
        now = time.time()
        victims = []
        actors_by_worker = {a.worker_id for a in self._actors.values()
                            if not a.dead}
        pg_workers = {wid for pg in self._pgs.values()
                      for wid in pg["workers"]}
        for w in self._workers.values():
            if (w.env_key is not None and w.alive and not w.running and
                    w.worker_id not in actors_by_worker and
                    w.worker_id not in pg_workers and
                    now - w.last_active > timeout):
                victims.append(w.worker_id)
        for wid in victims:
            threading.Thread(target=self.stop_worker, args=(wid,),
                             daemon=True).start()

    def tasks_done(self, worker_id: str, task_ids: List[str]):
        """Batched completion report from a worker executor: releases
        resources, records result locations + lineage."""
        with self._lock:
            w = self._workers.get(worker_id)
            if w is not None:
                w.last_active = time.time()
            for task_id in task_ids:
                meta = self._task_meta.pop(task_id, None)
                self._unpin_args_locked(meta)
                if meta is not None:
                    # meta None = already finalized elsewhere (e.g.
                    # failed via worker death while this report was in
                    # flight): never overwrite that terminal record.
                    self._record_task_done_locked(task_id, meta,
                                                  "FINISHED")
                if w is not None:
                    w.running.discard(task_id)
                    held = w.running_res.pop(task_id, None)
                    if held is not None and held[1] is None and w.alive:
                        for k, v in held[0].items():
                            w.available[k] = min(
                                w.resources.get(k, 0.0),
                                w.available.get(k, 0.0) + v)
                if meta is None or w is None:
                    continue
                # Results live on the executing worker's node; keep the
                # spec so lost results can be rebuilt (lineage). Both
                # only matter past one node: a single-node cluster has
                # nothing to pull from or fail over to, so skip the
                # per-task directory/lineage bookkeeping until a second
                # node joins (probe fallback covers objects created
                # before the join).
                if len(self._nodes) > 1:
                    for rid in meta.get("return_ids", ()):
                        self._obj_locs.setdefault(
                            rid.hex(), _OrderedSet()).add(w.node_id)
                    self._record_lineage_locked(meta)
            self._sched_cv.notify_all()

    def _handle_lost_task(self, task_id: str):
        with self._lock:
            meta = self._task_meta.get(task_id)
            if meta is None or meta.get("state") != "dispatched":
                # Already requeued (the dispatch-failure path and the
                # node monitor can both observe one death) or done.
                return
            if meta["attempt"] < meta.get("max_retries", 0):
                meta["attempt"] += 1
                meta["state"] = "pending"
                self._enqueue_locked(task_id, meta)
                return
            self._task_meta.pop(task_id, None)
            self._unpin_args_locked(meta)
            self._record_task_done_locked(task_id, meta, "FAILED")
        self._store_error(meta["return_ids"],
                          NodeDiedError(
                              f"worker died running task {task_id}"))

    # ---- actors -----------------------------------------------------------

    def create_actor(self, meta: Dict[str, Any], payload: bytes):
        """meta: actor_id, resources, max_restarts, name, namespace."""
        actor_id = meta["actor_id"]
        name = meta.get("name")
        ns = meta.get("namespace") or "default"
        with self._lock:
            if name:
                existing_id = self._named.get((ns, name))
                if existing_id is not None:
                    existing = self._actors.get(existing_id)
                    if existing is not None and not existing.dead:
                        if meta.get("get_if_exists"):
                            return {"actor_id": existing_id}
                        raise ValueError(
                            f"Actor name {name!r} already taken")
            pass
        deadline = time.time() + 60
        pg_id = meta.get("pg_id")
        bundle_index = meta.get("bundle_index", -1)
        while True:
            with self._lock:
                w = None
                while w is None:
                    w, placed_bidx = self._pick_actor_worker_locked(
                        meta.get("resources", {}), pg_id, bundle_index,
                        meta.get("env_key"))
                    if w is None:
                        env_key = meta.get("env_key")
                        if env_key is not None:
                            failed = getattr(self, "_env_failures",
                                             {}).get(env_key)
                            if failed is not None and \
                                    time.time() - failed[0] < 60:
                                # surface the REAL setup error (pip
                                # stderr), not a placement timeout;
                                # stale entries (>60s) fall through to
                                # a fresh spawn attempt like the task
                                # path
                                self._pending_actor_demands.pop(
                                    actor_id, None)
                                raise RuntimeError(
                                    f"runtime_env setup failed for "
                                    f"this actor's environment: "
                                    f"{failed[1]}")
                            self._ensure_env_worker_locked(
                                env_key, meta.get("runtime_env"),
                                meta.get("resources", {}))
                        # Surface the blocked demand to the autoscaler.
                        self._pending_actor_demands[actor_id] = dict(
                            meta.get("resources", {}))
                        if time.time() > deadline:
                            self._pending_actor_demands.pop(actor_id,
                                                            None)
                            raise TimeoutError(
                                f"No worker fits actor resources "
                                f"{meta.get('resources')}")
                        self._sched_cv.wait(timeout=0.1)
                self._pending_actor_demands.pop(actor_id, None)
                if pg_id is None:    # PG bundle already holds the reservation
                    for k, v in meta.get("resources", {}).items():
                        w.available[k] = w.available.get(k, 0.0) - v
                else:                # consume the bundle's reservation
                    used = self._pgs[pg_id]["bundle_used"][placed_bidx]
                    for k, v in meta.get("resources", {}).items():
                        used[k] = used.get(k, 0.0) + v
                info = _ActorInfo(actor_id, w.worker_id, payload,
                                  meta.get("resources", {}),
                                  meta.get("max_restarts", 0), name, ns,
                                  pg_id=pg_id, bundle_index=placed_bidx,
                                  env_key=meta.get("env_key"),
                                  runtime_env=meta.get("runtime_env"))
                info.concurrency_groups = dict(
                    meta.get("concurrency_groups") or {})
                self._actors[actor_id] = info
                if name:
                    self._named[(ns, name)] = actor_id
                client = w.client
            try:
                client.call("create_actor", actor_id, payload)
                self._dirty()
                return {"actor_id": actor_id}
            except RpcError:
                # Worker died under us (monitor lag): mark it dead —
                # which releases nothing for this not-yet-counted actor —
                # give back the reservation, and retry elsewhere.
                with self._lock:
                    self._actors.pop(actor_id, None)
                    if name:
                        self._named.pop((ns, name), None)
                    if pg_id is None:
                        for k, v in meta.get("resources", {}).items():
                            w.available[k] = w.available.get(k, 0.0) + v
                    else:
                        self._release_bundle_locked(
                            pg_id, placed_bidx, meta.get("resources", {}))
                self.mark_worker_dead(w.worker_id)
                if time.time() > deadline:
                    raise

    def _release_bundle_locked(self, pg_id, idx, resources):
        pg = self._pgs.get(pg_id)
        if pg is None or not (0 <= idx < len(pg.get("bundle_used", []))):
            return
        used = pg["bundle_used"][idx]
        for k, v in resources.items():
            used[k] = max(0.0, used.get(k, 0.0) - v)

    def _bundle_fits_locked(self, pg, idx, resources) -> bool:
        cap = pg["bundles"][idx][1]
        used = pg["bundle_used"][idx]
        return all(used.get(k, 0.0) + v <= cap.get(k, 0.0) + 1e-9
                   for k, v in resources.items())

    def _pick_actor_worker_locked(self, resources, pg_id,
                                  bundle_index, env_key=None):
        """PG-pinned actors go to the worker holding their bundle (the
        reference routes actor creation through the bundle's raylet —
        gcs_actor_scheduler.cc); others fall back to resource fit.

        Returns (worker, bundle_index) — bundle_index is -1 for
        non-PG placement. PG placement is capacity-checked against the
        bundle's reservation (pg["bundle_used"]) so actors can't
        overcommit a bundle."""
        if pg_id is not None:
            pg = self._pgs.get(pg_id)
            if not pg or not pg["ready"]:
                return None, -1
            if 0 <= bundle_index < len(pg["bundles"]):
                candidates = [bundle_index]
            else:
                candidates = range(len(pg["bundles"]))
            for idx in candidates:
                wid = pg["bundles"][idx][0]
                w = self._workers.get(wid)
                if w and w.alive and \
                        self._bundle_fits_locked(pg, idx, resources):
                    return w, idx
            return None, -1
        return self._pick_worker_locked(resources, None, env_key), -1

    def _handle_lost_actor(self, a: _ActorInfo):
        with self._lock:
            if a.max_restarts != -1 and a.restarts >= a.max_restarts:
                a.dead = True
                a.death_reason = "worker died"
                return
            a.restarts += 1
            a.worker_id = ""   # in-restart: not routable
        threading.Thread(target=self._restart_actor, args=(a,),
                         daemon=True).start()

    def _restart_actor(self, a: _ActorInfo, timeout: float = 60.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            with self._lock:
                if a.pg_id is not None:
                    # The actor still holds its bundle_used claim, so
                    # route straight back to its own bundle's worker —
                    # no capacity re-check, no re-deduction.
                    w = None
                    pg = self._pgs.get(a.pg_id)
                    if pg and 0 <= a.bundle_index < len(pg["bundles"]):
                        cand = self._workers.get(
                            pg["bundles"][a.bundle_index][0])
                        if cand and cand.alive:
                            w = cand
                else:
                    w = self._pick_worker_locked(a.resources, None,
                                                 a.env_key)
                    if w is None and a.env_key is not None:
                        self._ensure_env_worker_locked(
                            a.env_key, a.runtime_env, a.resources)
                if w is None:
                    self._sched_cv.wait(timeout=0.1)
                    continue
                if a.pg_id is None:
                    for k, v in a.resources.items():
                        w.available[k] = w.available.get(k, 0.0) - v
                a.worker_id = w.worker_id
                client = w.client
            try:
                client.call("create_actor", a.actor_id, a.payload)
                return
            except RpcError:
                if a.pg_id is None:
                    with self._lock:
                        for k, v in a.resources.items():
                            w.available[k] = w.available.get(k, 0.0) + v
                self.mark_worker_dead(w.worker_id)
        a.dead = True
        a.death_reason = "no worker available for restart"

    def actor_address(self, actor_id: str) -> Optional[str]:
        """Worker address for direct actor-task dispatch (reference:
        the CoreWorker direct actor transport resolves the actor's
        worker and pushes tasks peer-to-peer,
        core_worker/transport/direct_actor_transport — the head only
        brokers the address). Returns None while the actor is
        rebinding (caller falls back to the head-routed path, which
        waits out the restart)."""
        with self._lock:
            a = self._actors.get(actor_id)
            if a is None or a.dead:
                reason = a.death_reason if a else "unknown actor"
                raise ActorDiedError(actor_id, reason)
            if a.worker_id == "":
                return None
            w = self._workers.get(a.worker_id)
            if w is None or not w.alive:
                return None
            return w.address

    def reroute_actor_task(self, actor_id: str, payload: bytes,
                           attempts: int = 0):
        """A direct-dispatched actor task landed on a worker that no
        longer hosts the actor (restart/migration race): re-deliver
        through the head-routed path, or fail the task's return
        objects if the actor is truly dead. Runs on its own thread —
        re-delivery legitimately blocks while a restarting actor
        rebinds."""
        def _run():
            try:
                # Bounce backoff: each extra hop means we raced a
                # rebind — give the new worker time to finish creation.
                if attempts:
                    time.sleep(0.1 * attempts)
                self.submit_actor_task(actor_id, {}, payload, attempts)
            except BaseException as e:  # noqa: BLE001
                if not isinstance(e, ActorDiedError):
                    e = ActorDiedError(actor_id, f"reroute failed: {e}")
                try:
                    import cloudpickle
                    spec = cloudpickle.loads(payload)
                    self._store_error(spec["return_ids"], e)
                except Exception:
                    pass
        threading.Thread(target=_run, daemon=True,
                         name="actor-reroute").start()

    def submit_actor_task(self, actor_id: str, meta: Dict[str, Any],
                          payload: bytes, attempts: int = 0):
        deadline = time.time() + 30
        while True:
            with self._lock:
                while True:
                    a = self._actors.get(actor_id)
                    if a is None or a.dead:
                        reason = a.death_reason if a else "unknown actor"
                        raise ActorDiedError(actor_id, reason)
                    group = meta.get("concurrency_group")
                    if group and group not in a.concurrency_groups:
                        raise ValueError(
                            f"actor has no concurrency group {group!r} "
                            f"(declared: "
                            f"{sorted(a.concurrency_groups) or 'none'})")
                    if a.worker_id == "":
                        # Restored-from-snapshot (or mid-restart) actor
                        # awaiting its worker's re-attach: wait for the
                        # binding instead of failing the call.
                        if time.time() > deadline:
                            raise ActorDiedError(
                                actor_id,
                                "no worker re-attached the actor")
                        self._sched_cv.wait(timeout=0.2)
                        continue
                    w = self._workers.get(a.worker_id)
                    if w is None or not w.alive:
                        raise ActorDiedError(actor_id, "worker dead")
                    client = w.client
                    worker_id = w.worker_id
                    break
            try:
                client.call("push_actor_task", actor_id, payload,
                            attempts)
                return
            except RpcError:
                # Unreachable worker == death evidence (a reroute can
                # beat the node monitor's poll here): mark it dead —
                # which kicks off the actor's restart — and re-enter
                # the wait loop under the SAME deadline instead of
                # failing a restartable actor's call.
                self.mark_worker_dead(worker_id)
                if time.time() > deadline:
                    raise ActorDiedError(actor_id, "worker unreachable")

    def kill_actor(self, actor_id: str, no_restart: bool = True):
        with self._lock:
            a = self._actors.get(actor_id)
            if a is None:
                raise ValueError(f"Unknown actor {actor_id}")
            w = self._workers.get(a.worker_id)
            restart = (not no_restart and
                       (a.max_restarts == -1 or
                        a.restarts < a.max_restarts))
            if not restart:
                a.dead = True
                a.death_reason = ("killed via kill()" if no_restart
                                  else "crashed (out of restarts)")
                if a.name:
                    self._named.pop((a.namespace, a.name), None)
                if a.pg_id is not None:
                    self._release_bundle_locked(
                        a.pg_id, a.bundle_index, a.resources)
                elif w and w.alive:
                    for k, v in a.resources.items():
                        w.available[k] = min(
                            w.resources.get(k, 0.0),
                            w.available.get(k, 0.0) + v)
            else:
                a.restarts += 1
            client = w.client if (w and w.alive) else None
        self._dirty()
        if client is not None:
            try:
                client.call("kill_actor", actor_id,
                            restart)
            except RpcError:
                pass

    def lookup_named_actor(self, name: str, namespace: str) -> str:
        with self._lock:
            key = (namespace or "default", name)
            actor_id = self._named.get(key)
            if actor_id is None:
                raise ValueError(f"No actor named {name!r}")
            return actor_id

    def actor_class_payload(self, actor_id: str) -> bytes:
        with self._lock:
            a = self._actors.get(actor_id)
            if a is None:
                raise ValueError(f"Unknown actor {actor_id}")
            return a.payload

    def list_actors(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [{"actor_id": a.actor_id, "worker_id": a.worker_id,
                     "state": "DEAD" if a.dead else "ALIVE",
                     "name": a.name or "", "restarts": a.restarts}
                    for a in self._actors.values()]

    # ---- autoscaler feed ---------------------------------------------------

    def request_resources(self, bundles: List[Dict[str, float]]
                          ) -> None:
        """Autoscaler SDK (reference: ray.autoscaler.sdk.
        request_resources): pin a STANDING demand floor the scaler
        satisfies regardless of queue state. Idempotent — the latest
        call replaces the previous floor; an empty list clears it.
        Bundles are validated here: a standing malformed entry would
        otherwise poison EVERY autoscaler tick."""
        clean = []
        for b in bundles:
            if not isinstance(b, dict) or not all(
                    isinstance(k, str) and
                    isinstance(v, (int, float)) and
                    not isinstance(v, bool) and v >= 0
                    for k, v in b.items()):
                raise ValueError(
                    f"request_resources bundle must be a "
                    f"Dict[str, number >= 0], got {b!r}")
            clean.append({k: float(v) for k, v in b.items()})
        with self._lock:
            self._requested_resources = clean

    def load_metrics_snapshot(self) -> Dict[str, Any]:
        """Demand + usage view consumed by the autoscaler monitor
        (reference: LoadMetrics fed by raylet resource reports,
        python/ray/autoscaler/_private/load_metrics.py:62)."""
        with self._lock:
            pending: List[Dict[str, float]] = []
            pending.extend(
                dict(b) for b in
                getattr(self, "_requested_resources", ()))
            for queue in self._pending.values():
                for task_id in queue:
                    meta = self._task_meta.get(task_id)
                    if meta is not None:
                        pending.append(dict(meta.get("resources", {})))
            pending.extend(dict(d) for d in
                           self._pending_actor_demands.values())
            now = time.time()
            for pg_id in list(self._failed_pg_demands):
                bundles, ts = self._failed_pg_demands[pg_id]
                if now - ts > 5.0 or pg_id in self._pgs:
                    del self._failed_pg_demands[pg_id]
                else:
                    pending.extend(dict(b) for b in bundles)
            actors_per_worker: Dict[str, int] = {}
            for a in self._actors.values():
                if not a.dead and a.worker_id:
                    actors_per_worker[a.worker_id] = \
                        actors_per_worker.get(a.worker_id, 0) + 1
            nodes = []
            for w in self._workers.values():
                nodes.append({
                    "worker_id": w.worker_id,
                    "alive": w.alive,
                    "resources": dict(w.resources),
                    "available": dict(w.available),
                    "num_running_tasks": len(w.running),
                    "num_actors": actors_per_worker.get(w.worker_id, 0),
                })
            return {"pending_demands": pending, "nodes": nodes}

    # ---- placement groups -------------------------------------------------

    def create_placement_group(self, pg_id: str,
                               bundles: List[Dict[str, float]],
                               strategy: str) -> bool:
        with self._lock:
            reserved: List[Tuple[str, Dict[str, float]]] = []
            used: set = set()
            ok = True
            for b in bundles:
                w = None
                for cand in self._workers.values():
                    if not cand.alive:
                        continue
                    # Dedicated runtime-env workers never host PG
                    # bundles: a bundle would let env-less PG work run
                    # inside a mutated environment, and would pin a
                    # worker the idle reaper may stop.
                    if cand.env_key is not None:
                        continue
                    if strategy in ("SPREAD", "STRICT_SPREAD") and \
                            cand.worker_id in used:
                        continue
                    if all(cand.available.get(k, 0.0) + 1e-9 >= v
                           for k, v in b.items()):
                        w = cand
                        break
                if w is None:
                    ok = False
                    break
                for k, v in b.items():
                    w.available[k] = w.available.get(k, 0.0) - v
                reserved.append((w.worker_id, b))
                used.add(w.worker_id)
            if not ok:
                for wid, b in reserved:
                    w = self._workers[wid]
                    for k, v in b.items():
                        w.available[k] = w.available.get(k, 0.0) + v
                self._failed_pg_demands[pg_id] = (
                    [dict(b) for b in bundles], time.time())
                return False
            self._failed_pg_demands.pop(pg_id, None)
            self._pgs[pg_id] = {
                "ready": True,
                "strategy": strategy,
                "workers": [wid for wid, _ in reserved],
                "bundles": reserved,
                # Per-bundle resources consumed by PG-pinned actors —
                # bounds packing into a bundle without touching the
                # worker's own availability (already deducted above).
                "bundle_used": [dict() for _ in reserved],
            }
            self._sched_cv.notify_all()
            return True

    def remove_placement_group(self, pg_id: str):
        with self._lock:
            pg = self._pgs.pop(pg_id, None)
            if pg is None:
                return
            for wid, b in pg["bundles"]:
                w = self._workers.get(wid)
                if w and w.alive:
                    for k, v in b.items():
                        w.available[k] = min(
                            w.resources.get(k, 0.0),
                            w.available.get(k, 0.0) + v)
            self._sched_cv.notify_all()

    # ---- lifecycle --------------------------------------------------------

    # ---- jobs / node-manager services -------------------------------------

    def attach_node_service(self, node_service_addr: str):
        """Called by the head node's NodeManager once its
        worker-lifecycle RPC endpoint is bound (the head runs in its
        own process and calls back for request_worker/stop_worker)."""
        self._node_service = RpcClient(node_service_addr, timeout=60)

    def _job_manager(self):
        jm = getattr(self, "_jm", None)
        if jm is None:
            from ray_tpu.job.manager import JobManager
            jm = self._jm = JobManager(
                getattr(self, "_address", ""))
        return jm

    def submit_job(self, entrypoint, submission_id=None,
                   runtime_env=None, metadata=None) -> str:
        return self._job_manager().submit_job(
            entrypoint, submission_id=submission_id,
            runtime_env=runtime_env, metadata=metadata)

    def stop_job(self, job_id: str) -> bool:
        return self._job_manager().stop_job(job_id)

    def get_job_status(self, job_id: str) -> str:
        return self._job_manager().get_job_status(job_id)

    def get_job_info(self, job_id: str) -> Dict[str, Any]:
        return self._job_manager().get_job_info(job_id)

    def get_job_logs(self, job_id: str) -> str:
        return self._job_manager().get_job_logs(job_id)

    def list_jobs(self) -> List[Dict[str, Any]]:
        return self._job_manager().list_jobs()

    def request_worker(self, resources: Optional[Dict[str, float]] = None
                       ) -> str:
        """Start another worker process on the head's node (CLI
        ``ray-tpu start --address`` analogue for one-machine clusters)."""
        ns = getattr(self, "_node_service", None)
        if ns is None:
            raise RuntimeError("No node service attached to this head")
        return ns.call("start_worker", ns.call("num_workers"),
                       resources)

    def stop_worker(self, worker_id: str) -> None:
        """Tear down a (dedicated) worker process — the inverse of
        request_worker; used by gang trainers to retire their gang's
        processes so re-bootstrap always gets fresh ones."""
        ns = getattr(self, "_node_service", None)
        if ns is not None:
            try:
                ns.call("kill_worker", worker_id)
            except Exception:
                pass
        self.mark_worker_dead(worker_id)

    def store_stats(self) -> Dict[str, Any]:
        store = self._get_store()
        return store.stats()

    def metrics_snapshot(self) -> Dict[str, Any]:
        """Cluster-wide metrics from the native shm segment (N20)."""
        reg = getattr(self, "_metrics_reg", None)
        if reg is None:
            from ray_tpu._private.shm_metrics import ShmMetricsRegistry
            try:
                reg = self._metrics_reg = ShmMetricsRegistry.attach(
                    self.store_name + "_m")
            except OSError:
                return {}
        return reg.read_all()

    def metrics_prometheus(self) -> str:
        reg = getattr(self, "_metrics_reg", None)
        if reg is None:
            self.metrics_snapshot()
            reg = getattr(self, "_metrics_reg", None)
        return reg.prometheus_text() if reg else ""

    def ping(self) -> str:
        return "pong"

    def cluster_info(self) -> Dict[str, Any]:
        """Bootstrap info for drivers attaching by address (the Ray
        Client analogue, python/ray/util/client/ — here the driver talks
        the same protocol as workers instead of a proxied one)."""
        return {"store_name": self.store_name}

    def shutdown(self):
        self._shutdown = True
        jm = getattr(self, "_jm", None)
        if jm is not None:
            jm.shutdown()
        with self._lock:
            workers = list(self._workers.values())
        for w in workers:
            if w.alive:
                try:
                    w.client.call("shutdown", timeout=2)
                except Exception:
                    pass
