"""HEAD service: control plane + cluster scheduler.

Capability parity (single service, multiprocess scale) with the reference's
GCS (src/ray/gcs/gcs_server/ — node membership, actor directory, named
actors, KV) and the cluster scheduling path (ClusterTaskManager
scheduling/cluster_task_manager.cc: queue + pick node by resource fit;
LocalTaskManager dispatch == direct RPC push to the chosen worker's
executor). Placement groups reserve per-worker resources (the 2PC of
gcs_placement_group_scheduler.h collapses to one phase on a single head).

Fault tolerance: worker death (reported by the node manager) fails or
retries its running tasks (owner-style retry, task_manager.h:135) and
restarts its actors elsewhere up to max_restarts
(gcs_actor_manager.cc:1037 semantics).
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private.ids import ObjectID
from ray_tpu._private.serialization import dumps
from ray_tpu.exceptions import ActorDiedError, NodeDiedError
from ray_tpu.runtime.rpc import RpcClient, RpcError


class _WorkerInfo:
    def __init__(self, worker_id: str, address: str,
                 resources: Dict[str, float], node_id: str = "head"):
        self.worker_id = worker_id
        self.address = address
        self.resources = dict(resources)
        self.available = dict(resources)
        self.alive = True
        self.client = RpcClient(address)
        self.running: set = set()   # task ids currently dispatched
        # task id -> (resources, pg_id) actually deducted from THIS
        # worker; release happens from here (not from task meta) so a
        # duplicate completion after a spurious death-mark can't
        # double-release.
        self.running_res: Dict[str, Tuple[Dict[str, float], Any]] = {}
        self.node_id = node_id
        # Event-driven dispatch: the scheduler enqueues, one sender
        # thread per worker pushes (the reference amortizes raylet
        # round trips with lease reuse + pipelined PushTask,
        # direct_task_transport.cc:170 OnWorkerIdle; here dispatch is a
        # fire-and-forget enqueue RPC and completion arrives via
        # batched tasks_done).
        import queue as _queue
        self.outbox: "_queue.Queue" = _queue.Queue()
        self.sender: Optional[threading.Thread] = None


class _NodeInfo:
    def __init__(self, node_id: str, object_addr: str, store_name: str):
        self.node_id = node_id
        self.object_addr = object_addr
        self.store_name = store_name
        self.alive = True
        self.last_heartbeat = time.time()
        self.object_client = RpcClient(object_addr, timeout=10)


class _ActorInfo:
    def __init__(self, actor_id: str, worker_id: str, payload: bytes,
                 resources: Dict[str, float], max_restarts: int,
                 name: Optional[str], namespace: str,
                 pg_id: Optional[str] = None, bundle_index: int = -1):
        self.actor_id = actor_id
        self.worker_id = worker_id
        self.payload = payload          # creation spec (for restarts)
        self.resources = resources
        self.max_restarts = max_restarts
        self.restarts = 0
        self.dead = False
        self.death_reason = ""
        self.name = name
        self.namespace = namespace
        # PG-pinned actors consume the placement group's reservation
        # (tracked per-bundle in pg["bundle_used"]), which was already
        # deducted from the worker at PG creation — per-actor accounting
        # must not double-count it against the worker.
        self.pg_id = pg_id
        self.bundle_index = bundle_index


class HeadService:
    """Handler object served by RpcServer in the driver process."""

    def __init__(self, store_name: str):
        self.store_name = store_name
        self._lock = threading.RLock()
        self._workers: Dict[str, _WorkerInfo] = {}
        self._actors: Dict[str, _ActorInfo] = {}
        self._named: Dict[Tuple[str, str], str] = {}
        self._kv: Dict[str, bytes] = {}
        # Pending queue indexed by resource signature: one scheduler
        # pass probes each distinct (resources, pg) shape once and
        # dispatches from its FIFO until placement fails — O(shapes)
        # per pass instead of O(queue length), which keeps a deep
        # homogeneous backlog (the 1M-queued-tasks envelope) cheap.
        self._pending: "collections.OrderedDict[tuple, collections.deque]" = \
            collections.OrderedDict()
        self._task_meta: Dict[str, Dict[str, Any]] = {}
        self._pgs: Dict[str, Dict[str, Any]] = {}
        # Demands not in the task queue but still unmet — blocked actor
        # creations and unplaceable placement groups — so the autoscaler
        # sees them (reference: resource load includes actor/PG shapes).
        self._pending_actor_demands: Dict[str, Dict[str, float]] = {}
        self._failed_pg_demands: Dict[str, Any] = {}   # pg_id -> (bundles, ts)
        self._store = None
        self._shutdown = False
        # --- multi-node object/control plane ---------------------------
        from ray_tpu.runtime.pubsub import PubSubHub
        self.hub = PubSubHub()
        self._nodes: Dict[str, _NodeInfo] = {}
        # object directory: oid hex -> set of node ids holding a copy
        # (owner-based directory parity, ownership_based_object_directory.cc)
        self._obj_locs: Dict[str, set] = {}
        # lineage: return oid hex -> creating task (meta+payload), LRU
        # bounded by bytes (reference max_lineage_bytes semantics,
        # core_worker/task_manager.h:251).
        self._lineage: "collections.OrderedDict[str, Dict]" = \
            collections.OrderedDict()
        self._lineage_bytes = 0
        from ray_tpu._private.config import GlobalConfig
        self._lineage_budget = int(GlobalConfig.lineage_max_bytes)
        self._sched_cv = threading.Condition(self._lock)
        self._sched_thread = threading.Thread(
            target=self._scheduler_loop, daemon=True, name="head-sched")
        self._sched_thread.start()
        self._node_monitor = threading.Thread(
            target=self._node_monitor_loop, daemon=True,
            name="head-node-monitor")
        self._node_monitor.start()

    def _get_store(self):
        if self._store is None:
            from ray_tpu._private.shm_store import ShmObjectStore
            self._store = ShmObjectStore.attach(self.store_name)
        return self._store

    # ---- node membership (multi-node control plane) -----------------------

    def register_node(self, node_id: str, object_addr: str,
                      store_name: str) -> None:
        with self._lock:
            self._nodes[node_id] = _NodeInfo(node_id, object_addr,
                                             store_name)
        self._publish_nodes()

    def node_heartbeat(self, node_id: str) -> bool:
        with self._lock:
            n = self._nodes.get(node_id)
            if n is None or not n.alive:
                return False    # tells a zombie agent to re-register
            n.last_heartbeat = time.time()
            return True

    def node_count(self) -> int:
        with self._lock:
            return sum(1 for n in self._nodes.values() if n.alive)

    def list_nodes(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [{"node_id": n.node_id, "alive": n.alive,
                     "object_addr": n.object_addr,
                     "store_name": n.store_name}
                    for n in self._nodes.values()]

    def _publish_nodes(self):
        self.hub.publish_state("nodes", self.list_nodes())

    def mark_node_dead(self, node_id: str):
        with self._lock:
            n = self._nodes.get(node_id)
            if n is None or not n.alive:
                return
            n.alive = False
            workers = [w.worker_id for w in self._workers.values()
                       if w.node_id == node_id and w.alive]
            # Objects whose only copies lived there are gone; getters
            # fall back to lineage reconstruction.
            for locs in self._obj_locs.values():
                locs.discard(node_id)
        for wid in workers:
            self.mark_worker_dead(wid)
        self._publish_nodes()
        self.hub.publish_stream(
            "node_events", {"type": "node_dead", "node_id": node_id,
                            "ts": time.time()})

    def _node_monitor_loop(self):
        from ray_tpu._private.config import GlobalConfig
        period = GlobalConfig.heartbeat_period_ms / 1000.0
        timeout = period * GlobalConfig.num_heartbeats_timeout
        while not self._shutdown:
            time.sleep(period)
            now = time.time()
            stale = []
            with self._lock:
                for n in self._nodes.values():
                    # The head's own node has no heartbeating agent.
                    if n.alive and n.node_id != "head" and \
                            now - n.last_heartbeat > timeout:
                        stale.append(n.node_id)
            for node_id in stale:
                self.mark_node_dead(node_id)

    # ---- object directory (owner-based location parity) -------------------

    def register_objects(self, node_id: str, oid_hexes: List[str]):
        with self._lock:
            for oid_hex in oid_hexes:
                self._obj_locs.setdefault(oid_hex, set()).add(node_id)

    def locate_objects(self, oid_hexes: List[str]
                       ) -> Dict[str, List[Dict[str, str]]]:
        """Batch location lookup (no probing/reconstruction — the
        per-object slow path handles those)."""
        out: Dict[str, List[Dict[str, str]]] = {}
        with self._lock:
            for oid_hex in oid_hexes:
                node_ids = [nid for nid in
                            self._obj_locs.get(oid_hex, ())
                            if nid in self._nodes and
                            self._nodes[nid].alive]
                if node_ids:
                    out[oid_hex] = [
                        {"node_id": nid,
                         "object_addr": self._nodes[nid].object_addr}
                        for nid in node_ids]
        return out

    def unregister_object(self, oid_hex: str, node_id: str):
        with self._lock:
            locs = self._obj_locs.get(oid_hex)
            if locs is not None:
                locs.discard(node_id)
                if not locs:
                    del self._obj_locs[oid_hex]

    def locate_object(self, oid_hex: str, probe: bool = False,
                      reconstruct: bool = False) -> List[Dict[str, str]]:
        """Live locations of an object. `probe=True` additionally asks
        every node's object service on a directory miss (covers puts
        whose async registration hasn't landed). `reconstruct=True`
        resubmits the creating task from lineage when no copy is left."""
        now = time.time()
        with self._lock:
            node_ids = [nid for nid in self._obj_locs.get(oid_hex, ())
                        if nid in self._nodes and
                        self._nodes[nid].alive]
            out = [{"node_id": nid,
                    "object_addr": self._nodes[nid].object_addr}
                   for nid in node_ids]
            probe_targets = []
            if not out and probe:
                # Probing fans an RPC to every node: rate-limit it to
                # one sweep per object per 500 ms so M waiting getters
                # polling every few ms don't turn into O(M*N) probe
                # traffic (the common miss — a task still running — is
                # answered by registration, not probing).
                probes = getattr(self, "_probe_at", None)
                if probes is None:
                    probes = self._probe_at = {}
                if probes.get(oid_hex, 0) <= now:
                    probes[oid_hex] = now + 0.5
                    if len(probes) > 10000:
                        for k in [k for k, t in probes.items()
                                  if t <= now]:
                            del probes[k]
                    probe_targets = [
                        (n.node_id, n.object_client, n.object_addr)
                        for n in self._nodes.values() if n.alive]
        for nid, client, addr in probe_targets:
            try:
                if client.call("has_object", oid_hex, timeout=2):
                    self.register_objects(nid, [oid_hex])
                    out.append({"node_id": nid, "object_addr": addr})
            except RpcError:
                pass
        if not out and reconstruct:
            self._maybe_reconstruct(oid_hex)
        return out

    # ---- lineage / reconstruction -----------------------------------------

    def _record_lineage_locked(self, meta: Dict[str, Any]):
        payload = meta.get("payload")
        if payload is None:
            return
        cost = len(payload)
        entry = {"meta": {k: meta[k] for k in
                          ("task_id", "return_ids", "resources",
                           "max_retries", "pg_id") if k in meta},
                 "payload": payload}
        for rid in meta.get("return_ids", ()):
            rid_hex = rid.hex() if isinstance(rid, bytes) else rid
            old = self._lineage.pop(rid_hex, None)
            if old is not None:
                self._lineage_bytes -= old["cost"]
            self._lineage[rid_hex] = {"entry": entry, "cost": cost}
            self._lineage_bytes += cost
        while self._lineage_bytes > self._lineage_budget and \
                self._lineage:
            _, dropped = self._lineage.popitem(last=False)
            self._lineage_bytes -= dropped["cost"]

    def _enqueue_locked(self, task_id: str, meta: Dict[str, Any]):
        sig = (tuple(sorted(meta.get("resources", {}).items())),
               meta.get("pg_id"))
        self._pending.setdefault(sig, collections.deque()).append(
            task_id)
        self._sched_cv.notify_all()

    def _pending_count_locked(self) -> int:
        return sum(len(q) for q in self._pending.values())

    def _maybe_reconstruct(self, oid_hex: str) -> bool:
        """Resubmit the creating task of a lost object (lineage
        reconstruction parity, object_recovery_manager.h:41)."""
        with self._lock:
            rec = self._lineage.get(oid_hex)
            if rec is None:
                return False
            meta = dict(rec["entry"]["meta"])
            task_id = meta["task_id"]
            live = self._task_meta.get(task_id)
            if live is not None and live.get("state") in (
                    "pending", "dispatched"):
                return True     # already being rebuilt
            meta["payload"] = rec["entry"]["payload"]
            meta["attempt"] = 0
            meta["state"] = "pending"
            meta["reconstruction"] = True
            self._task_meta[task_id] = meta
            self._enqueue_locked(task_id, meta)
            return True

    # ---- pub/sub RPC ------------------------------------------------------

    def psub_poll(self, state_versions=None, stream_seqs=None,
                  poll_timeout: float = 30.0):
        return self.hub.poll(state_versions, stream_seqs,
                             timeout=poll_timeout)

    def publish(self, channel: str, value: Any, stream: bool = False):
        if stream:
            return self.hub.publish_stream(channel, value)
        return self.hub.publish_state(channel, value)

    # ---- worker membership ------------------------------------------------

    def register_worker(self, worker_id: str, address: str,
                        resources: Dict[str, float],
                        node_id: str = "head") -> Dict[str, Any]:
        with self._lock:
            self._workers[worker_id] = _WorkerInfo(worker_id, address,
                                                   resources, node_id)
            self._sched_cv.notify_all()
            node = self._nodes.get(node_id)
            store = node.store_name if node else self.store_name
        return {"store_name": store, "multinode": self.node_count() > 1}

    def mark_worker_dead(self, worker_id: str):
        """Called by the node manager when a worker process dies."""
        with self._lock:
            w = self._workers.get(worker_id)
            if w is None or not w.alive:
                return
            w.alive = False
            running = list(w.running)
            w.running.clear()
            w.running_res.clear()
            dead_actors = [a for a in self._actors.values()
                           if a.worker_id == worker_id and not a.dead]
        # Push-based death broadcast (reference: worker failure events
        # over GCS pub/sub) — actor-handle holders and monitors
        # subscribe instead of polling list_workers.
        self.hub.publish_stream(
            "worker_events", {"type": "worker_dead",
                              "worker_id": worker_id,
                              "ts": time.time()})
        # Fail or retry tasks that were on that worker.
        for task_id in running:
            self._handle_lost_task(task_id)
        # Restart or kill its actors.
        for a in dead_actors:
            self._handle_lost_actor(a)

    def list_workers(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [{"worker_id": w.worker_id, "address": w.address,
                     "alive": w.alive, "resources": dict(w.resources),
                     "available": dict(w.available),
                     "running_tasks": list(w.running)}
                    for w in self._workers.values()]

    def cluster_resources(self) -> Dict[str, float]:
        with self._lock:
            total: Dict[str, float] = {}
            for w in self._workers.values():
                if not w.alive:
                    continue
                for k, v in w.resources.items():
                    total[k] = total.get(k, 0.0) + v
            return total

    def available_resources(self) -> Dict[str, float]:
        with self._lock:
            total: Dict[str, float] = {}
            for w in self._workers.values():
                if not w.alive:
                    continue
                for k, v in w.available.items():
                    total[k] = total.get(k, 0.0) + v
            return total

    # ---- function table (function_manager.py parity) ----------------------

    def register_function(self, fn_id: str, blob: bytes):
        with self._lock:
            if not hasattr(self, "_functions"):
                self._functions = {}
            self._functions[fn_id] = blob

    def get_function(self, fn_id: str) -> Optional[bytes]:
        with self._lock:
            return getattr(self, "_functions", {}).get(fn_id)

    # ---- KV (gcs internal kv parity) -------------------------------------

    def kv_put(self, key: str, value: bytes):
        with self._lock:
            self._kv[key] = value

    def kv_get(self, key: str) -> Optional[bytes]:
        with self._lock:
            return self._kv.get(key)

    def kv_del(self, key: str):
        with self._lock:
            self._kv.pop(key, None)

    def kv_keys(self, prefix: str = "") -> List[str]:
        with self._lock:
            return [k for k in self._kv if k.startswith(prefix)]

    # ---- error reporting into the object store ---------------------------

    def _store_error(self, return_ids: List[bytes], exc: BaseException):
        store = self._get_store()
        payload = dumps(("err", exc))
        for rid in return_ids:
            try:
                store.put_bytes(ObjectID(rid), payload)
            except Exception:
                pass  # already stored
        # Error objects live in the head node's store; remote getters
        # find them through the directory.
        self.register_objects(
            "head", [rid.hex() for rid in return_ids])

    # ---- normal tasks -----------------------------------------------------

    def submit_task(self, meta: Dict[str, Any], payload: bytes):
        """meta: task_id, return_ids [bytes], resources, max_retries,
        pg_id (optional). payload: pickled executable spec."""
        self.submit_tasks([(meta, payload)])

    def submit_tasks(self, batch: List[Tuple[Dict[str, Any], bytes]]):
        """Batched submission: one lock acquire + one scheduler wake
        for a whole client-side flush window."""
        with self._lock:
            for meta, payload in batch:
                meta = dict(meta)
                meta["payload"] = payload
                meta["attempt"] = 0
                meta["state"] = "pending"
                self._task_meta[meta["task_id"]] = meta
                sig = (tuple(sorted(meta.get("resources",
                                             {}).items())),
                       meta.get("pg_id"))
                self._pending.setdefault(
                    sig, collections.deque()).append(meta["task_id"])
            self._sched_cv.notify_all()

    def task_blocked(self, worker_id: str, resources: Dict[str, float]):
        """Worker reports a task blocked in get(): release its resources
        (unblocked-worker oversubscription semantics, as in local mode)."""
        with self._lock:
            w = self._workers.get(worker_id)
            if w and w.alive:
                for k, v in resources.items():
                    w.available[k] = min(w.resources.get(k, 0.0),
                                         w.available.get(k, 0.0) + v)
                self._sched_cv.notify_all()

    def task_unblocked(self, worker_id: str,
                       resources: Dict[str, float]) -> bool:
        with self._lock:
            w = self._workers.get(worker_id)
            if w is None or not w.alive:
                return False
            for k, v in resources.items():
                w.available[k] = w.available.get(k, 0.0) - v
            return True

    def _scheduler_loop(self):
        while not self._shutdown:
            with self._lock:
                progressed = self._try_dispatch_locked()
                if not progressed:
                    self._sched_cv.wait(timeout=0.05)

    def _pick_worker_locked(self, resources: Dict[str, float],
                            pg_id: Optional[str]) -> Optional[_WorkerInfo]:
        if pg_id is not None:
            pg = self._pgs.get(pg_id)
            if not pg or not pg["ready"]:
                return None
            # Run inside the reservation on one of the PG's workers.
            for wid in pg["workers"]:
                w = self._workers.get(wid)
                if w and w.alive:
                    return w
            return None
        best = None
        for w in self._workers.values():
            if not w.alive:
                continue
            if all(w.available.get(k, 0.0) + 1e-9 >= v
                   for k, v in resources.items()):
                # Least-loaded fit.
                if best is None or len(w.running) < len(best.running):
                    best = w
        return best

    def _try_dispatch_locked(self) -> bool:
        progressed = False
        for sig in list(self._pending):
            queue = self._pending[sig]
            while queue:
                task_id = queue[0]
                meta = self._task_meta.get(task_id)
                if meta is None or meta.get("state") != "pending":
                    queue.popleft()     # stale duplicate queue entry
                    continue
                res = meta.get("resources", {})
                pg_id = meta.get("pg_id")
                w = self._pick_worker_locked(res, pg_id)
                if w is None:
                    break    # this shape can't place now; next shape
                queue.popleft()
                if pg_id is None:
                    for k, v in res.items():
                        w.available[k] = w.available.get(k, 0.0) - v
                w.running.add(task_id)
                w.running_res[task_id] = (dict(res), pg_id)
                meta["state"] = "dispatched"
                meta["worker_id"] = w.worker_id
                if w.sender is None or not w.sender.is_alive():
                    w.sender = threading.Thread(
                        target=self._sender_loop, args=(w,),
                        daemon=True,
                        name=f"head-send-{w.worker_id[:12]}")
                    w.sender.start()
                w.outbox.put(meta)
                progressed = True
            if not queue:
                del self._pending[sig]
        return progressed

    def _sender_loop(self, w: _WorkerInfo):
        """Per-worker dispatch sender: drains the outbox, pushing each
        task as a ONE-WAY pipelined send (measured: a request/reply
        dispatch costs ~2.2 ms under worker GIL load and serializes the
        per-worker rate at ~450 tasks/s; one-way sends are ~10 us).
        Delivery failure surfaces as a send error or through the worker
        death monitor — either way mark_worker_dead retries everything
        in w.running. Per-worker ordering rides on the dedicated
        one-way socket."""
        import queue as _queue
        while not self._shutdown:
            try:
                meta = w.outbox.get(timeout=0.5)
            except Exception:
                if not w.alive:
                    return
                continue
            if meta is None:
                return
            # Greedy batch: everything already queued ships as one
            # one-way RPC (amortizes envelope pickling + syscalls).
            batch = [meta]
            while len(batch) < 128:
                try:
                    nxt = w.outbox.get_nowait()
                except _queue.Empty:
                    break
                if nxt is None:
                    break
                batch.append(nxt)
            failure: Optional[BaseException] = None
            for _attempt in range(2):
                try:
                    w.client.call_oneway(
                        "push_tasks", [m["payload"] for m in batch],
                        fast=True)
                    failure = None
                    break
                except RpcError as e:
                    # One retry: a stale socket raises the same error
                    # as a dead worker; the retry reconnects.
                    failure = e
            if failure is not None:
                # Unreachable worker == death detection (don't wait for
                # the node monitor poll).
                self.mark_worker_dead(w.worker_id)
                for m in batch:
                    self._handle_lost_task(m["task_id"])
                return

    def tasks_done(self, worker_id: str, task_ids: List[str]):
        """Batched completion report from a worker executor: releases
        resources, records result locations + lineage."""
        with self._lock:
            w = self._workers.get(worker_id)
            for task_id in task_ids:
                meta = self._task_meta.pop(task_id, None)
                if w is not None:
                    w.running.discard(task_id)
                    held = w.running_res.pop(task_id, None)
                    if held is not None and held[1] is None and w.alive:
                        for k, v in held[0].items():
                            w.available[k] = min(
                                w.resources.get(k, 0.0),
                                w.available.get(k, 0.0) + v)
                if meta is None or w is None:
                    continue
                # Results live on the executing worker's node; keep the
                # spec so lost results can be rebuilt (lineage). Both
                # only matter past one node: a single-node cluster has
                # nothing to pull from or fail over to, so skip the
                # per-task directory/lineage bookkeeping until a second
                # node joins (probe fallback covers objects created
                # before the join).
                if len(self._nodes) > 1:
                    for rid in meta.get("return_ids", ()):
                        self._obj_locs.setdefault(
                            rid.hex(), set()).add(w.node_id)
                    self._record_lineage_locked(meta)
            self._sched_cv.notify_all()

    def _handle_lost_task(self, task_id: str):
        with self._lock:
            meta = self._task_meta.get(task_id)
            if meta is None or meta.get("state") != "dispatched":
                # Already requeued (the dispatch-failure path and the
                # node monitor can both observe one death) or done.
                return
            if meta["attempt"] < meta.get("max_retries", 0):
                meta["attempt"] += 1
                meta["state"] = "pending"
                self._enqueue_locked(task_id, meta)
                return
            self._task_meta.pop(task_id, None)
        self._store_error(meta["return_ids"],
                          NodeDiedError(
                              f"worker died running task {task_id}"))

    # ---- actors -----------------------------------------------------------

    def create_actor(self, meta: Dict[str, Any], payload: bytes):
        """meta: actor_id, resources, max_restarts, name, namespace."""
        actor_id = meta["actor_id"]
        name = meta.get("name")
        ns = meta.get("namespace") or "default"
        with self._lock:
            if name:
                existing_id = self._named.get((ns, name))
                if existing_id is not None:
                    existing = self._actors.get(existing_id)
                    if existing is not None and not existing.dead:
                        if meta.get("get_if_exists"):
                            return {"actor_id": existing_id}
                        raise ValueError(
                            f"Actor name {name!r} already taken")
            pass
        deadline = time.time() + 60
        pg_id = meta.get("pg_id")
        bundle_index = meta.get("bundle_index", -1)
        while True:
            with self._lock:
                w = None
                while w is None:
                    w, placed_bidx = self._pick_actor_worker_locked(
                        meta.get("resources", {}), pg_id, bundle_index)
                    if w is None:
                        # Surface the blocked demand to the autoscaler.
                        self._pending_actor_demands[actor_id] = dict(
                            meta.get("resources", {}))
                        if time.time() > deadline:
                            self._pending_actor_demands.pop(actor_id,
                                                            None)
                            raise TimeoutError(
                                f"No worker fits actor resources "
                                f"{meta.get('resources')}")
                        self._sched_cv.wait(timeout=0.1)
                self._pending_actor_demands.pop(actor_id, None)
                if pg_id is None:    # PG bundle already holds the reservation
                    for k, v in meta.get("resources", {}).items():
                        w.available[k] = w.available.get(k, 0.0) - v
                else:                # consume the bundle's reservation
                    used = self._pgs[pg_id]["bundle_used"][placed_bidx]
                    for k, v in meta.get("resources", {}).items():
                        used[k] = used.get(k, 0.0) + v
                info = _ActorInfo(actor_id, w.worker_id, payload,
                                  meta.get("resources", {}),
                                  meta.get("max_restarts", 0), name, ns,
                                  pg_id=pg_id, bundle_index=placed_bidx)
                self._actors[actor_id] = info
                if name:
                    self._named[(ns, name)] = actor_id
                client = w.client
            try:
                client.call("create_actor", actor_id, payload)
                return {"actor_id": actor_id}
            except RpcError:
                # Worker died under us (monitor lag): mark it dead —
                # which releases nothing for this not-yet-counted actor —
                # give back the reservation, and retry elsewhere.
                with self._lock:
                    self._actors.pop(actor_id, None)
                    if name:
                        self._named.pop((ns, name), None)
                    if pg_id is None:
                        for k, v in meta.get("resources", {}).items():
                            w.available[k] = w.available.get(k, 0.0) + v
                    else:
                        self._release_bundle_locked(
                            pg_id, placed_bidx, meta.get("resources", {}))
                self.mark_worker_dead(w.worker_id)
                if time.time() > deadline:
                    raise

    def _release_bundle_locked(self, pg_id, idx, resources):
        pg = self._pgs.get(pg_id)
        if pg is None or not (0 <= idx < len(pg.get("bundle_used", []))):
            return
        used = pg["bundle_used"][idx]
        for k, v in resources.items():
            used[k] = max(0.0, used.get(k, 0.0) - v)

    def _bundle_fits_locked(self, pg, idx, resources) -> bool:
        cap = pg["bundles"][idx][1]
        used = pg["bundle_used"][idx]
        return all(used.get(k, 0.0) + v <= cap.get(k, 0.0) + 1e-9
                   for k, v in resources.items())

    def _pick_actor_worker_locked(self, resources, pg_id,
                                  bundle_index):
        """PG-pinned actors go to the worker holding their bundle (the
        reference routes actor creation through the bundle's raylet —
        gcs_actor_scheduler.cc); others fall back to resource fit.

        Returns (worker, bundle_index) — bundle_index is -1 for
        non-PG placement. PG placement is capacity-checked against the
        bundle's reservation (pg["bundle_used"]) so actors can't
        overcommit a bundle."""
        if pg_id is not None:
            pg = self._pgs.get(pg_id)
            if not pg or not pg["ready"]:
                return None, -1
            if 0 <= bundle_index < len(pg["bundles"]):
                candidates = [bundle_index]
            else:
                candidates = range(len(pg["bundles"]))
            for idx in candidates:
                wid = pg["bundles"][idx][0]
                w = self._workers.get(wid)
                if w and w.alive and \
                        self._bundle_fits_locked(pg, idx, resources):
                    return w, idx
            return None, -1
        return self._pick_worker_locked(resources, None), -1

    def _handle_lost_actor(self, a: _ActorInfo):
        with self._lock:
            if a.max_restarts != -1 and a.restarts >= a.max_restarts:
                a.dead = True
                a.death_reason = "worker died"
                return
            a.restarts += 1
            a.worker_id = ""   # in-restart: not routable
        threading.Thread(target=self._restart_actor, args=(a,),
                         daemon=True).start()

    def _restart_actor(self, a: _ActorInfo, timeout: float = 60.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            with self._lock:
                if a.pg_id is not None:
                    # The actor still holds its bundle_used claim, so
                    # route straight back to its own bundle's worker —
                    # no capacity re-check, no re-deduction.
                    w = None
                    pg = self._pgs.get(a.pg_id)
                    if pg and 0 <= a.bundle_index < len(pg["bundles"]):
                        cand = self._workers.get(
                            pg["bundles"][a.bundle_index][0])
                        if cand and cand.alive:
                            w = cand
                else:
                    w = self._pick_worker_locked(a.resources, None)
                if w is None:
                    self._sched_cv.wait(timeout=0.1)
                    continue
                if a.pg_id is None:
                    for k, v in a.resources.items():
                        w.available[k] = w.available.get(k, 0.0) - v
                a.worker_id = w.worker_id
                client = w.client
            try:
                client.call("create_actor", a.actor_id, a.payload)
                return
            except RpcError:
                if a.pg_id is None:
                    with self._lock:
                        for k, v in a.resources.items():
                            w.available[k] = w.available.get(k, 0.0) + v
                self.mark_worker_dead(w.worker_id)
        a.dead = True
        a.death_reason = "no worker available for restart"

    def submit_actor_task(self, actor_id: str, meta: Dict[str, Any],
                          payload: bytes):
        with self._lock:
            a = self._actors.get(actor_id)
            if a is None or a.dead:
                reason = a.death_reason if a else "unknown actor"
                raise ActorDiedError(actor_id, reason)
            w = self._workers.get(a.worker_id)
            if w is None or not w.alive:
                raise ActorDiedError(actor_id, "worker dead")
            client = w.client
        client.call("push_actor_task", actor_id, payload)

    def kill_actor(self, actor_id: str, no_restart: bool = True):
        with self._lock:
            a = self._actors.get(actor_id)
            if a is None:
                raise ValueError(f"Unknown actor {actor_id}")
            w = self._workers.get(a.worker_id)
            restart = (not no_restart and
                       (a.max_restarts == -1 or
                        a.restarts < a.max_restarts))
            if not restart:
                a.dead = True
                a.death_reason = ("killed via kill()" if no_restart
                                  else "crashed (out of restarts)")
                if a.name:
                    self._named.pop((a.namespace, a.name), None)
                if a.pg_id is not None:
                    self._release_bundle_locked(
                        a.pg_id, a.bundle_index, a.resources)
                elif w and w.alive:
                    for k, v in a.resources.items():
                        w.available[k] = min(
                            w.resources.get(k, 0.0),
                            w.available.get(k, 0.0) + v)
            else:
                a.restarts += 1
            client = w.client if (w and w.alive) else None
        if client is not None:
            try:
                client.call("kill_actor", actor_id,
                            restart)
            except RpcError:
                pass

    def lookup_named_actor(self, name: str, namespace: str) -> str:
        with self._lock:
            key = (namespace or "default", name)
            actor_id = self._named.get(key)
            if actor_id is None:
                raise ValueError(f"No actor named {name!r}")
            return actor_id

    def actor_class_payload(self, actor_id: str) -> bytes:
        with self._lock:
            a = self._actors.get(actor_id)
            if a is None:
                raise ValueError(f"Unknown actor {actor_id}")
            return a.payload

    def list_actors(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [{"actor_id": a.actor_id, "worker_id": a.worker_id,
                     "state": "DEAD" if a.dead else "ALIVE",
                     "name": a.name or "", "restarts": a.restarts}
                    for a in self._actors.values()]

    # ---- autoscaler feed ---------------------------------------------------

    def load_metrics_snapshot(self) -> Dict[str, Any]:
        """Demand + usage view consumed by the autoscaler monitor
        (reference: LoadMetrics fed by raylet resource reports,
        python/ray/autoscaler/_private/load_metrics.py:62)."""
        with self._lock:
            pending: List[Dict[str, float]] = []
            for queue in self._pending.values():
                for task_id in queue:
                    meta = self._task_meta.get(task_id)
                    if meta is not None:
                        pending.append(dict(meta.get("resources", {})))
            pending.extend(dict(d) for d in
                           self._pending_actor_demands.values())
            now = time.time()
            for pg_id in list(self._failed_pg_demands):
                bundles, ts = self._failed_pg_demands[pg_id]
                if now - ts > 5.0 or pg_id in self._pgs:
                    del self._failed_pg_demands[pg_id]
                else:
                    pending.extend(dict(b) for b in bundles)
            actors_per_worker: Dict[str, int] = {}
            for a in self._actors.values():
                if not a.dead and a.worker_id:
                    actors_per_worker[a.worker_id] = \
                        actors_per_worker.get(a.worker_id, 0) + 1
            nodes = []
            for w in self._workers.values():
                nodes.append({
                    "worker_id": w.worker_id,
                    "alive": w.alive,
                    "resources": dict(w.resources),
                    "available": dict(w.available),
                    "num_running_tasks": len(w.running),
                    "num_actors": actors_per_worker.get(w.worker_id, 0),
                })
            return {"pending_demands": pending, "nodes": nodes}

    # ---- placement groups -------------------------------------------------

    def create_placement_group(self, pg_id: str,
                               bundles: List[Dict[str, float]],
                               strategy: str) -> bool:
        with self._lock:
            reserved: List[Tuple[str, Dict[str, float]]] = []
            used: set = set()
            ok = True
            for b in bundles:
                w = None
                for cand in self._workers.values():
                    if not cand.alive:
                        continue
                    if strategy in ("SPREAD", "STRICT_SPREAD") and \
                            cand.worker_id in used:
                        continue
                    if all(cand.available.get(k, 0.0) + 1e-9 >= v
                           for k, v in b.items()):
                        w = cand
                        break
                if w is None:
                    ok = False
                    break
                for k, v in b.items():
                    w.available[k] = w.available.get(k, 0.0) - v
                reserved.append((w.worker_id, b))
                used.add(w.worker_id)
            if not ok:
                for wid, b in reserved:
                    w = self._workers[wid]
                    for k, v in b.items():
                        w.available[k] = w.available.get(k, 0.0) + v
                self._failed_pg_demands[pg_id] = (
                    [dict(b) for b in bundles], time.time())
                return False
            self._failed_pg_demands.pop(pg_id, None)
            self._pgs[pg_id] = {
                "ready": True,
                "workers": [wid for wid, _ in reserved],
                "bundles": reserved,
                # Per-bundle resources consumed by PG-pinned actors —
                # bounds packing into a bundle without touching the
                # worker's own availability (already deducted above).
                "bundle_used": [dict() for _ in reserved],
            }
            self._sched_cv.notify_all()
            return True

    def remove_placement_group(self, pg_id: str):
        with self._lock:
            pg = self._pgs.pop(pg_id, None)
            if pg is None:
                return
            for wid, b in pg["bundles"]:
                w = self._workers.get(wid)
                if w and w.alive:
                    for k, v in b.items():
                        w.available[k] = min(
                            w.resources.get(k, 0.0),
                            w.available.get(k, 0.0) + v)
            self._sched_cv.notify_all()

    # ---- lifecycle --------------------------------------------------------

    # ---- jobs / node-manager services -------------------------------------

    def attach_node_service(self, node_service_addr: str):
        """Called by the head node's NodeManager once its
        worker-lifecycle RPC endpoint is bound (the head runs in its
        own process and calls back for request_worker/stop_worker)."""
        self._node_service = RpcClient(node_service_addr, timeout=60)

    def _job_manager(self):
        jm = getattr(self, "_jm", None)
        if jm is None:
            from ray_tpu.job.manager import JobManager
            jm = self._jm = JobManager(
                getattr(self, "_address", ""))
        return jm

    def submit_job(self, entrypoint, submission_id=None,
                   runtime_env=None, metadata=None) -> str:
        return self._job_manager().submit_job(
            entrypoint, submission_id=submission_id,
            runtime_env=runtime_env, metadata=metadata)

    def stop_job(self, job_id: str) -> bool:
        return self._job_manager().stop_job(job_id)

    def get_job_status(self, job_id: str) -> str:
        return self._job_manager().get_job_status(job_id)

    def get_job_info(self, job_id: str) -> Dict[str, Any]:
        return self._job_manager().get_job_info(job_id)

    def get_job_logs(self, job_id: str) -> str:
        return self._job_manager().get_job_logs(job_id)

    def list_jobs(self) -> List[Dict[str, Any]]:
        return self._job_manager().list_jobs()

    def request_worker(self, resources: Optional[Dict[str, float]] = None
                       ) -> str:
        """Start another worker process on the head's node (CLI
        ``ray-tpu start --address`` analogue for one-machine clusters)."""
        ns = getattr(self, "_node_service", None)
        if ns is None:
            raise RuntimeError("No node service attached to this head")
        return ns.call("start_worker", ns.call("num_workers"),
                       resources)

    def stop_worker(self, worker_id: str) -> None:
        """Tear down a (dedicated) worker process — the inverse of
        request_worker; used by gang trainers to retire their gang's
        processes so re-bootstrap always gets fresh ones."""
        ns = getattr(self, "_node_service", None)
        if ns is not None:
            try:
                ns.call("kill_worker", worker_id)
            except Exception:
                pass
        self.mark_worker_dead(worker_id)

    def store_stats(self) -> Dict[str, Any]:
        store = self._get_store()
        return store.stats()

    def metrics_snapshot(self) -> Dict[str, Any]:
        """Cluster-wide metrics from the native shm segment (N20)."""
        reg = getattr(self, "_metrics_reg", None)
        if reg is None:
            from ray_tpu._private.shm_metrics import ShmMetricsRegistry
            try:
                reg = self._metrics_reg = ShmMetricsRegistry.attach(
                    self.store_name + "_m")
            except OSError:
                return {}
        return reg.read_all()

    def metrics_prometheus(self) -> str:
        reg = getattr(self, "_metrics_reg", None)
        if reg is None:
            self.metrics_snapshot()
            reg = getattr(self, "_metrics_reg", None)
        return reg.prometheus_text() if reg else ""

    def ping(self) -> str:
        return "pong"

    def cluster_info(self) -> Dict[str, Any]:
        """Bootstrap info for drivers attaching by address (the Ray
        Client analogue, python/ray/util/client/ — here the driver talks
        the same protocol as workers instead of a proxied one)."""
        return {"store_name": self.store_name}

    def shutdown(self):
        self._shutdown = True
        jm = getattr(self, "_jm", None)
        if jm is not None:
            jm.shutdown()
        with self._lock:
            workers = list(self._workers.values())
        for w in workers:
            if w.alive:
                try:
                    w.client.call("shutdown", timeout=2)
                except Exception:
                    pass
