"""Worker process entry point.

Capability parity with the reference's worker bootstrap + executor side
(python/ray/_private/workers/default_worker.py + CoreWorker task execution
core_worker.cc:2181/2543): serves an executor endpoint (PushTask
equivalent), attaches the node's shm object store, resolves args, executes
tasks/actor methods, writes results to the store, and installs a
WorkerRuntime so nested ray_tpu API calls inside tasks route back through
the head scheduler.

Run: python -m ray_tpu.runtime.worker_main --head H:P --store NAME \
         --worker-id ID --resources '{"CPU": 2}'
"""
from __future__ import annotations

import argparse
import json
import os
import queue
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

import cloudpickle

from ray_tpu._private.ids import ObjectID
from ray_tpu._private.serialization import dumps, loads
from ray_tpu.exceptions import ActorDiedError, TaskError
from ray_tpu.runtime.rpc import RpcClient, RpcServer

from ray_tpu._private.execution_context import task_ctx as _task_ctx


class _ActorSlot:
    def __init__(self, instance=None, error: Optional[BaseException] = None,
                 concurrency_groups: Optional[Dict[str, int]] = None,
                 max_concurrency: int = 1):
        from ray_tpu._private.concurrency_groups import GroupMailboxes
        self.instance = instance
        self.error = error
        self.gm = GroupMailboxes(concurrency_groups, max_concurrency)
        self.threads: list = []
        self.thread: Optional[threading.Thread] = None
        self.runtime_env = None
        # Bounded replay filter for direct-dispatch batch retries
        # (ordered dict as an LRU set of task ids).
        import collections
        self.seen_tasks: "collections.OrderedDict" = \
            collections.OrderedDict()
        self.aloop = None      # asyncio actors: their event loop
        # sync actors: coroutine-returning methods drive a PER-THREAD
        # loop — multiple group threads must never share one loop
        self._thread_loops = threading.local()

    def thread_loop(self):
        loop = getattr(self._thread_loops, "loop", None)
        if loop is None:
            import asyncio
            loop = self._thread_loops.loop = asyncio.new_event_loop()
        return loop

    def close_thread_loop(self):
        loop = getattr(self._thread_loops, "loop", None)
        if loop is not None:
            loop.close()
            self._thread_loops.loop = None


class Executor:
    """RPC handler for this worker process."""

    def __init__(self, worker_id: str, head: RpcClient, plane,
                 resources: Dict[str, float]):
        self.worker_id = worker_id
        self.head = head
        self.plane = plane           # ObjectPlane over the node's store
        self.store = plane.store
        self.resources = resources
        self.actors: Dict[str, _ActorSlot] = {}
        self._lock = threading.Lock()
        self._shutdown = threading.Event()
        self.startup_env_key: Optional[str] = None
        self._task_q: "queue.Queue" = queue.Queue()
        self._pool_lock = threading.Lock()
        self._idle_threads = 0
        # Batched completion reports back to the head (event-driven
        # dispatch: push_task replies at enqueue; the head releases
        # resources when tasks_done arrives).
        self._done: List[str] = []
        self._push_clients: Dict[str, Any] = {}   # owner-direct returns
        # task_id -> executing thread ident (force-cancel targeting);
        # _cancel_on_start absorbs cancels that beat their task's
        # dequeue (dispatched-but-not-started window).
        self._task_threads: Dict[str, int] = {}
        self._threads_lock = threading.Lock()
        self._cancel_on_start: Dict[str, bool] = {}
        self._done_lock = threading.Lock()
        self._done_wake = threading.Event()
        self._notifier = threading.Thread(
            target=self._notify_loop, daemon=True,
            name="executor-notify")
        self._notifier.start()

    # ---- helpers ----------------------------------------------------------

    def _resolve(self, value):
        from ray_tpu._private.object_ref import ObjectRef
        if isinstance(value, ObjectRef):
            return self._read_object(value.id)
        return value

    def _read_object(self, oid: ObjectID):
        status, value = loads(self.plane.get_blob(oid, timeout_ms=-1))
        if status == "err":
            raise value
        if status == "devobj":
            # HBM-resident device object: resolve the descriptor to a
            # living Array (mesh/device_objects.py).
            from ray_tpu.mesh.device_objects import resolve_handle
            return resolve_handle(value, self.plane)
        return value


    # Serialized returns at or below this size are PUSHED straight to
    # the caller's node store instead of waiting to be pulled — the
    # owner-direct return path (small cross-node results go from 4-6
    # control RPCs + poll latency to one one-way push).
    PUSH_RETURN_MAX = 256 * 1024

    def _push_return(self, oid: ObjectID, blob, ret_addr: str) -> None:
        client = self._push_clients.get(ret_addr)
        if client is None:
            from ray_tpu.runtime.rpc import RpcClient
            client = self._push_clients[ret_addr] = \
                RpcClient(ret_addr, timeout=10)
        try:
            client.call_oneway("push_object", oid.hex(),
                               bytes(blob) if not isinstance(blob, bytes)
                               else blob)
        except Exception:
            pass      # caller's pull path still resolves the local copy

    def _write_returns(self, return_ids: List[bytes], num_returns: int,
                      result: Any, ret_addr: Optional[str] = None):
        if num_returns == 0:
            return
        if ret_addr and ret_addr == self.plane._self_service_addr:
            ret_addr = None          # caller shares this node's store
        if num_returns == 1:
            if result is None:
                # Side-effect-only tasks are common; skip the
                # serializer for the constant result (and the
                # unpickler on the reader side — interned blob).
                from ray_tpu._private.serialization import \
                    NONE_RESULT_BLOB
                oid = ObjectID(return_ids[0])
                self.plane.put_bytes(oid, NONE_RESULT_BLOB)
                if ret_addr:
                    self._push_return(oid, NONE_RESULT_BLOB, ret_addr)
                return
            values = [result]
        else:
            values = list(result)
            if len(values) != num_returns:
                raise ValueError(
                    f"expected {num_returns} returns, got {len(values)}")
        from ray_tpu._private.serialization import serialize_parts
        for rid, v in zip(return_ids, values):
            oid = ObjectID(rid)
            if ret_addr:
                parts, total, _ = serialize_parts(("ok", v))
                self.plane.put_serialized(oid, parts, total)
                if total <= self.PUSH_RETURN_MAX:
                    blob = b"".join(
                        bytes(p) if not isinstance(p, bytes) else p
                        for p in parts)
                    self._push_return(oid, blob, ret_addr)
                continue
            # put_obj streams serialized parts into shm (single copy);
            # returns are owned by the CALLER, so never inline here —
            # a worker-process memory tier would be invisible to it.
            self.plane.put_obj(oid, ("ok", v))

    def _write_error(self, return_ids: List[bytes], exc: BaseException):
        payload = dumps(("err", exc))
        for rid in return_ids:
            try:
                self.plane.put_bytes(ObjectID(rid), payload)
            except Exception:
                pass

    # ---- normal tasks -----------------------------------------------------

    @staticmethod
    def _chaos_delay():
        """Env-configured random handler delay (N22; flags propagated
        via RAY_TPU_* env by NodeManager.start_worker)."""
        from ray_tpu._private.config import chaos_delay
        chaos_delay()

    def push_task(self, payload: bytes) -> str:
        """Enqueue-and-return: the task body runs on a pooled thread and
        completion flows back through the batched tasks_done channel —
        the head's dispatch RPC never waits on user code."""
        return self.push_tasks([payload])

    def push_tasks(self, payloads: List[bytes]) -> str:
        """Batched dispatch from the head's per-worker sender. Raw
        payload bytes go straight onto the pool queue; pool threads do
        the deserialization (keeps the RPC reader thread lean)."""
        self._chaos_delay()
        need = 0
        for payload in payloads:
            self._task_q.put(payload)
        # Elastic cached pool: spawn only when nobody is idle. Blocked
        # tasks (nested get) occupy their thread, so the pool must be
        # able to grow past the resource slot count — a fixed pool
        # could deadlock a dependency chain.
        with self._pool_lock:
            need = max(0, len(payloads) - self._idle_threads)
        for _ in range(need):
            threading.Thread(target=self._pool_loop, daemon=True,
                             name="task-pool").start()
        return "queued"

    def _pool_loop(self):
        while not self._shutdown.is_set():
            with self._pool_lock:
                self._idle_threads += 1
            try:
                item = self._task_q.get(timeout=20)
            except queue.Empty:
                # Exit-vs-enqueue race: push_tasks may have enqueued
                # after our timeout but before we deregister. Decide
                # under the pool lock with a queue re-check, so either
                # we see the item (and keep serving) or push_tasks sees
                # our decremented idle count (and spawns).
                with self._pool_lock:
                    if not self._task_q.empty():
                        self._idle_threads -= 1
                        continue
                    self._idle_threads -= 1
                    return     # idle-reap this thread
            with self._pool_lock:
                self._idle_threads -= 1
            self._run_task(cloudpickle.loads(item))

    def _notify_loop(self):
        last_send = 0.0
        while not self._shutdown.is_set():
            self._done_wake.wait(timeout=1.0)
            self._done_wake.clear()
            # Adaptive coalescing: under load (back-to-back sends),
            # wait half a millisecond so completions batch and the
            # head runs one scheduler pass per batch instead of per
            # task; idle completions still report immediately.
            if time.monotonic() - last_send < 0.001:
                time.sleep(0.0005)
            with self._done_lock:
                batch, self._done = self._done, []
            last_send = time.monotonic()
            if batch:
                try:
                    # One-way: completions pile up naturally while a
                    # send is in flight, so batching is load-adaptive
                    # without an artificial delay on the idle path.
                    self.head.call_oneway("tasks_done", self.worker_id,
                                          batch, fast=True)
                except Exception:
                    # A dropped batch would leak the head's resource
                    # accounting for these tasks even though both ends
                    # are alive (transient socket error): requeue and
                    # retry after a backoff until the head is truly
                    # unreachable-forever (then our death supersedes).
                    with self._done_lock:
                        self._done = batch + self._done
                    self._done_wake.set()
                    time.sleep(0.2)

    def _report_done(self, task_id: str):
        with self._done_lock:
            self._done.append(task_id)
        self._done_wake.set()

    def _resolve_function(self, spec):
        fn_ref = spec.get("fn_ref")
        if fn_ref is None:
            return spec["func"]
        cache = getattr(self, "_fn_cache", None)
        if cache is None:
            cache = self._fn_cache = {}
        if isinstance(fn_ref, str) and (
                fn_ref.startswith(("import://", "registry://"))
                or ":" in fn_ref):
            # Cross-language task (reference: C++/Java task specs name
            # functions, core_worker cross_language path): the spec
            # carries a descriptor instead of a pickled closure, so
            # non-Python clients can submit work. Bare "module:attr"
            # counts (function-table hashes are hex, colon-free).
            # registry:// is deliberately NOT memoized — a
            # re-registration must take effect on every worker — and
            # descriptor results/args are validated against the
            # plain-data contract at this boundary.
            from ray_tpu.util.cross_lang import (resolve_descriptor,
                                                 validate_args)
            target = cache.get(fn_ref) \
                if not fn_ref.startswith("registry://") else None
            if target is None:
                target = resolve_descriptor(fn_ref)
                if not fn_ref.startswith("registry://"):
                    cache[fn_ref] = target

            import functools

            @functools.wraps(target)
            def checked(*args, **kwargs):
                validate_args(list(args))
                validate_args(kwargs)
                out = target(*args, **kwargs)
                validate_args(out)
                return out

            return checked
        func = cache.get(fn_ref)
        if func is None:
            blob = self.head.call("get_function", fn_ref)
            if blob is None:
                raise RuntimeError(f"unknown function {fn_ref}")
            func = cloudpickle.loads(blob)
            cache[fn_ref] = func
        return func

    def _run_task(self, spec) -> str:
        _task_ctx.resources = spec.get("resources", {})
        _task_ctx.blocked = False
        _task_ctx.task_id = spec.get("task_id")
        _task_ctx.actor_id = None
        # Register this thread as the task's executor so a
        # force-cancel can interrupt exactly this task (and nothing
        # co-resident on the worker).
        from ray_tpu.exceptions import TaskCancelledError as _TCE
        tid_key = spec.get("task_id", "")
        with self._threads_lock:
            precancelled = self._cancel_on_start.pop(tid_key, False)
            if not precancelled:
                self._task_threads[tid_key] = threading.get_ident()
        if precancelled:
            self._write_error(spec["return_ids"], _TCE(tid_key))
            self._report_done(tid_key)
            return "cancelled"
        from ray_tpu._private.log_streaming import set_log_tag
        set_log_tag(f"{spec.get('name', 'task')} "
                    f"task={spec.get('task_id', '')[:12]}")
        try:
            func = self._resolve_function(spec)
            args = [self._resolve(a) for a in spec["args"]]
            kwargs = {k: self._resolve(v)
                      for k, v in spec["kwargs"].items()}
            if self.startup_env_key is not None:
                # Dedicated env worker: the env is this process.
                spec = dict(spec)
                spec["runtime_env"] = None
            if spec.get("runtime_env") is None and \
                    spec.get("trace_ctx") is None:
                # Hot path: no env to apply, no span to propagate —
                # skip both context managers.
                result = func(*args, **kwargs)
            else:
                from ray_tpu._private.runtime_env import \
                    runtime_env_context
                from ray_tpu.util.tracing import execution_span
                with runtime_env_context(spec.get("runtime_env")), \
                        execution_span(spec.get("name", "task"),
                                       "task", spec.get("trace_ctx")):
                    result = func(*args, **kwargs)
            # User code is done: close the cancellation window BEFORE
            # committing results (a cancel landing mid-commit would
            # corrupt the very value the caller may already observe).
            with self._threads_lock:
                self._task_threads.pop(tid_key, None)
            from ray_tpu.util import metrics as metrics_mod
            reg = metrics_mod.get_shm_registry()
            if reg is not None:
                # Before the result write: a caller observing the result
                # must also observe the counter.
                reg.counter_add("raytpu_tasks_executed_total")
            try:
                self._write_returns(spec["return_ids"],
                                    spec["num_returns"], result,
                                    ret_addr=spec.get("ret_addr"))
            except _TCE:
                # An already-scheduled async cancel fired mid-commit:
                # the user code DID complete — commit anyway.
                self._write_returns(spec["return_ids"],
                                    spec["num_returns"], result,
                                    ret_addr=spec.get("ret_addr"))
            return "ok"
        except BaseException as e:  # noqa: BLE001
            if not isinstance(e, (TaskError, _TCE)):
                e = TaskError(e, task_name=spec.get("name", ""),
                              remote_traceback=traceback.format_exc())
            try:
                self._write_error(spec["return_ids"], e)
            except _TCE:
                # A second async cancel landed mid-write: the write
                # must still commit or the caller hangs.
                self._write_error(spec["return_ids"], e)
            return "error"
        finally:
            # Deregister under the SAME lock delivery uses: once this
            # pop runs, no new cancel can target this thread, so the
            # commit below cannot be interrupted by a fresh cancel.
            with self._threads_lock:
                self._task_threads.pop(tid_key, None)
            _task_ctx.task_id = None
            _task_ctx.resources = None
            set_log_tag(None)
            try:
                self._report_done(spec.get("task_id", ""))
            except _TCE:
                self._report_done(spec.get("task_id", ""))

    # ---- actors -----------------------------------------------------------

    @staticmethod
    def _wants_asyncio(cls) -> bool:
        import asyncio
        import inspect
        for _name, m in inspect.getmembers(cls):
            if asyncio.iscoroutinefunction(m):
                return True
        return False

    def create_actor(self, actor_id: str, payload: bytes) -> str:
        spec = cloudpickle.loads(payload)
        slot = _ActorSlot(
            concurrency_groups=spec.get("concurrency_groups"),
            max_concurrency=spec.get("max_concurrency", 1))
        cls = spec["cls"]
        slot.runtime_env = spec.get("runtime_env")
        if self._wants_asyncio(cls):
            # asyncio actor: instantiate AND serve inside a dedicated
            # event loop (fiber-transport parity, core_worker fiber.h)
            # — __init__ may create background tasks
            # (asyncio.get_event_loop().create_task), and they
            # interleave with ordered message execution at awaits.
            init_done = threading.Event()
            slot.thread = threading.Thread(
                target=self._actor_asyncio_main,
                args=(actor_id, slot, spec, init_done), daemon=True,
                name=f"actor-{actor_id[:8]}")
            slot.thread.start()
            init_done.wait(timeout=300)
        else:
            try:
                from ray_tpu._private.runtime_env import \
                    runtime_env_context
                with runtime_env_context(slot.runtime_env):
                    slot.instance = cls(*spec["args"], **spec["kwargs"])
            except BaseException as e:  # noqa: BLE001
                slot.error = e
            for group, box in slot.gm.items():
                for i in range(slot.gm.size(group)):
                    t = threading.Thread(
                        target=self._actor_loop,
                        args=(actor_id, slot, box), daemon=True,
                        name=f"actor-{actor_id[:8]}-{group}-{i}")
                    t.start()
                    slot.threads.append(t)
            slot.thread = slot.threads[0]
        with self._lock:
            self.actors[actor_id] = slot
        return "ok" if slot.error is None else "init_failed"

    def _actor_asyncio_main(self, actor_id: str, slot: _ActorSlot,
                            spec, init_done: threading.Event):
        import asyncio
        from ray_tpu._private.log_streaming import set_log_tag
        set_log_tag(f"actor={actor_id[:12]}")
        loop = slot.aloop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        # The loop's DEFAULT executor sizes to min(32, cpus + 4) —
        # on a small host that silently caps every run_in_executor
        # offload (serve replicas run sync user methods there) far
        # below the actor's declared max_concurrency. Size it to the
        # actor's own concurrency; threads spawn lazily.
        # + one thread per group: each group's pump parks a blocking
        # box.get in this same pool while idle
        from concurrent.futures import ThreadPoolExecutor
        loop.set_default_executor(ThreadPoolExecutor(
            max_workers=slot.gm.max_concurrency + len(slot.gm.boxes),
            thread_name_prefix=f"actor-exec-{actor_id[:8]}"))
        try:
            from ray_tpu._private.runtime_env import runtime_env_context
            with runtime_env_context(slot.runtime_env):
                slot.instance = spec["cls"](*spec["args"],
                                            **spec["kwargs"])
        except BaseException as e:  # noqa: BLE001
            slot.error = e
        finally:
            init_done.set()

        # One pump per concurrency group; per-group semaphores bound
        # concurrency independently (default group = max_concurrency).
        sems = {g: asyncio.Semaphore(slot.gm.size(g))
                for g, _ in slot.gm.items()}

        async def drain(box, sem):
            while not self._shutdown.is_set():
                item = await loop.run_in_executor(None, box.get)
                if item is None:
                    return

                async def run_one(item=item):
                    async with sem:
                        await self._execute_actor_item_async(
                            actor_id, slot, item)
                loop.create_task(run_one())

        async def drain_all():
            await asyncio.gather(*[drain(box, sems[g])
                                   for g, box in slot.gm.items()])

        try:
            loop.run_until_complete(drain_all())
            # drain saw its sentinel but fire-and-forget run_one tasks
            # may still be in flight: finish them so every queued call
            # writes its result before the loop dies
            pending = [t for t in asyncio.all_tasks(loop)
                       if not t.done()]
            if pending:
                loop.run_until_complete(
                    asyncio.gather(*pending, return_exceptions=True))
        except Exception:
            pass
        finally:
            loop.close()

    async def _execute_actor_item_async(self, actor_id: str,
                                        slot: _ActorSlot, spec):
        import asyncio
        try:
            if slot.error is not None:
                raise ActorDiedError(
                    actor_id, f"__init__ failed: {slot.error!r}")
            # Identity for get_runtime_context(). Thread-local on the
            # actor's loop thread: interleaved awaits of DIFFERENT
            # methods can observe the most recent setter — a known
            # limit of the async path (ids are per-thread, not
            # per-coroutine).
            _task_ctx.task_id = spec.get("task_id")
            _task_ctx.actor_id = actor_id
            method = getattr(slot.instance, spec["method"])
            args = [self._resolve(a) for a in spec["args"]]
            kwargs = {k: self._resolve(v)
                      for k, v in spec["kwargs"].items()}
            from ray_tpu._private.runtime_env import runtime_env_context
            from ray_tpu.util.tracing import execution_span
            renv = None if self.startup_env_key is not None \
                else slot.runtime_env
            with runtime_env_context(renv), \
                    execution_span(spec.get("name", "actor_task"),
                                   "actor_task",
                                   spec.get("trace_ctx")):
                result = method(*args, **kwargs)
                if asyncio.iscoroutine(result):
                    result = await result
            self._write_returns(spec["return_ids"],
                                spec["num_returns"], result,
                                ret_addr=spec.get("ret_addr"))
        except BaseException as e:  # noqa: BLE001
            if not isinstance(e, (TaskError, ActorDiedError)):
                e = TaskError(e, task_name=spec.get("name", ""),
                              remote_traceback=traceback.format_exc())
            self._write_error(spec["return_ids"], e)

    def _actor_loop(self, actor_id: str, slot: _ActorSlot,
                    box: "queue.Queue"):
        from ray_tpu._private.log_streaming import set_log_tag
        set_log_tag(f"actor={actor_id[:12]}")
        while not self._shutdown.is_set():
            item = box.get()
            if item is None:
                slot.close_thread_loop()   # don't leak per-thread loops
                return
            spec = item
            try:
                if slot.error is not None:
                    raise ActorDiedError(
                        actor_id, f"__init__ failed: {slot.error!r}")
                _task_ctx.task_id = spec.get("task_id")
                _task_ctx.actor_id = actor_id
                method = getattr(slot.instance, spec["method"])
                args = [self._resolve(a) for a in spec["args"]]
                kwargs = {k: self._resolve(v)
                          for k, v in spec["kwargs"].items()}
                from ray_tpu._private.runtime_env import \
                    runtime_env_context
                from ray_tpu.util.tracing import execution_span
                with runtime_env_context(slot.runtime_env), \
                        execution_span(spec.get("name", "actor_task"),
                                       "actor_task",
                                       spec.get("trace_ctx")):
                    result = method(*args, **kwargs)
                    import inspect
                    if inspect.iscoroutine(result):
                        # coroutine from a sync-classified actor: each
                        # group thread drives its OWN loop — a shared
                        # loop would race across concurrent threads
                        result = slot.thread_loop() \
                            .run_until_complete(result)
                self._write_returns(spec["return_ids"],
                                    spec["num_returns"], result,
                                    ret_addr=spec.get("ret_addr"))
            except BaseException as e:  # noqa: BLE001
                if not isinstance(e, (TaskError, ActorDiedError)):
                    e = TaskError(e, task_name=spec.get("name", ""),
                                  remote_traceback=traceback.format_exc())
                self._write_error(spec["return_ids"], e)

    def push_actor_tasks(self, items: List) -> str:
        """Batched direct dispatch from a CALLER process (reference:
        direct actor transport — tasks skip the head entirely). Items
        are (actor_id, payload, attempts) tuples; per-caller ordering
        rides the caller's dedicated one-way socket, exactly like the
        head's dispatch senders.

        Delivery semantics across ACTOR RESTART are at-least-once:
        the replay filter (slot.seen_tasks) dies with the worker, so a
        batch delivered-but-unacked just before a crash can be
        replayed via the head's reroute to the RESTARTED actor and
        re-execute its side effects — the same window the documented
        ordering relaxation on restart already implies. Exactly-once
        across restarts would need the seen set persisted through the
        head's actor rebind; callers needing it should make actor
        methods idempotent (the reference gives the same guidance for
        max_task_retries with side-effecting actors)."""
        for actor_id, payload, attempts in items:
            self.push_actor_task(actor_id, payload, attempts)
        return "queued"

    def push_actor_task(self, actor_id: str, payload: bytes,
                        attempts: int = 0) -> str:
        spec = cloudpickle.loads(payload)
        with self._lock:
            slot = self.actors.get(actor_id)
        if slot is None:
            # Grace window: a restart publishes the actor's new route
            # before create_actor finishes on this worker, so a prompt
            # push can beat the in-flight creation. Misses are rare —
            # polling briefly here beats bouncing the task around.
            deadline = time.time() + 1.0
            while slot is None and time.time() < deadline:
                time.sleep(0.02)
                with self._lock:
                    slot = self.actors.get(actor_id)
        if slot is None:
            # Stale direct dispatch (the actor restarted elsewhere or
            # the caller's address cache lagged): bounce through the
            # head, which knows the actor's current binding — writing
            # ActorDiedError here would fail calls to a LIVE actor.
            # Tradeoff: a rerouted call can land AFTER a younger call
            # that went straight to the new worker — per-caller order
            # is relaxed across a restart boundary (the reference's
            # direct transport has the same window during actor
            # reconstruction).
            if attempts < 3:
                try:
                    self.head.call_oneway("reroute_actor_task",
                                          actor_id, payload,
                                          attempts + 1)
                    return "rerouted"
                except Exception:
                    pass
            self._write_error(spec["return_ids"],
                              ActorDiedError(actor_id, "not on worker"))
            return "dead"
        try:
            box = slot.gm.route(spec.get("concurrency_group"))
        except ValueError as e:
            # backstop: the head validates groups at submission, so
            # this only fires on a stale/raced actor definition
            self._write_error(spec["return_ids"], TaskError(
                e, task_name=spec.get("name", "")))
            return "bad_group"
        # Enqueue-side dedup: a direct sender retries a batch whose
        # ack timed out, so a delivered-but-unacked task can arrive
        # twice — task ids are unique per call, making replays exact.
        tid = spec.get("task_id")
        if tid is not None:
            with self._lock:
                seen = slot.seen_tasks
                if tid in seen:
                    return "dup"
                seen[tid] = None
                while len(seen) > 8192:
                    seen.popitem(last=False)
        box.put(spec)
        return "queued"

    def kill_actor(self, actor_id: str, restart: bool) -> str:
        with self._lock:
            slot = self.actors.pop(actor_id, None)
        if slot is not None:
            if slot.aloop is not None:      # async: one pump per group
                slot.gm.stop_one_per_group()
            else:
                slot.gm.stop()
        return "ok"

    # ---- lifecycle --------------------------------------------------------

    def ping(self) -> str:
        return "pong"

    def cancel_task_exec(self, task_id: str) -> str:
        """Force-cancel the THREAD executing `task_id` by raising
        TaskCancelledError asynchronously in it (CPython
        PyThreadState_SetAsyncExc). Proportionate for this executor —
        workers multiplex many tasks on a thread pool, so the
        reference's kill-the-worker force path would destroy
        co-resident tasks/actors. The exception lands at the next
        bytecode boundary: pure-Python loops die promptly; a task
        blocked in a C call (sleep, IO, jit execution) is interrupted
        when the call returns. A task DISPATCHED but not yet started
        (still in the worker queue) is marked to cancel at start.
        Returns "interrupted" | "not-running". Delivery happens under
        _threads_lock against the commit-side pop, so a task that
        already finished its user code can no longer be targeted."""
        import ctypes
        from ray_tpu.exceptions import TaskCancelledError
        with self._threads_lock:
            ident = self._task_threads.get(task_id)
            if ident is None:
                # Dispatched-but-queued window: cancel at start.
                self._cancel_on_start[task_id] = True
                while len(self._cancel_on_start) > 1000:
                    self._cancel_on_start.pop(
                        next(iter(self._cancel_on_start)))
                return "interrupted"
            n = ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_ulong(ident),
                ctypes.py_object(TaskCancelledError))
            if n != 1:
                if n > 1:     # invalid state: undo (per CPython docs)
                    ctypes.pythonapi.PyThreadState_SetAsyncExc(
                        ctypes.c_ulong(ident), None)
                return "not-running"
            return "interrupted"

    def shutdown(self) -> str:
        self._shutdown.set()
        threading.Thread(target=lambda: (_sleep_exit()), daemon=True) \
            .start()
        return "bye"


def _sleep_exit():
    import time
    time.sleep(0.2)
    import os
    os._exit(0)


class WorkerRuntime:
    """Runtime interface inside a worker process: nested API calls route
    through the head scheduler; objects through the shm store."""

    def __init__(self, executor: Executor, head: RpcClient,
                 worker_id: str):
        self._ex = executor
        self.head = head
        self.worker_id = worker_id
        from ray_tpu._private.object_store import ReferenceCounter
        self.ref_counter = ReferenceCounter(
            on_object_released=self._ex.plane.release_owned)
        from ray_tpu._private.ids import JobID
        self.job_id = JobID.next()
        self._handles: Dict[Any, Any] = {}

    @property
    def _actor_handles(self):
        return self._handles

    # Shared implementation with the driver client.
    def put(self, value):
        from ray_tpu._private.object_ref import ObjectRef
        from ray_tpu.runtime.client import _maybe_put_device
        oid = ObjectID.from_random()
        if _maybe_put_device(self._ex.plane, oid, value,
                             self._ex.plane.node_id):
            return ObjectRef(oid, owner_hint="put")
        self._ex.plane.put_obj(oid, ("ok", value), owned=True)
        return ObjectRef(oid, owner_hint="put")

    def get(self, refs, timeout=None):
        from ray_tpu.runtime.client import resolve_refs
        res = getattr(_task_ctx, "resources", None)
        blocked = False
        if res:
            # Local-store miss == we are about to block; an object
            # fetchable from a peer node resolves fast enough that
            # releasing resources isn't worth the head round trip.
            missing = any(not self._ex.store.contains(r.id)
                          for r in ([refs] if not isinstance(refs, list)
                                    else refs))
            if missing:
                self.head.call("task_blocked", self.worker_id, res)
                blocked = True
        try:
            return resolve_refs(self._ex.plane, refs, timeout)
        finally:
            if blocked:
                self.head.call("task_unblocked", self.worker_id, res)

    def wait(self, refs, num_returns=1, timeout=None):
        from ray_tpu.runtime.client import wait_refs
        return wait_refs(self._ex.plane, refs, num_returns, timeout)

    def object_future(self, oid):
        from ray_tpu.runtime.client import object_future
        return object_future(self._ex.plane, oid)

    def submit_task(self, spec):
        from ray_tpu.runtime.client import submit_task_via_head
        refs = submit_task_via_head(
            self.head, spec, ret_addr=self._ex.plane.ret_addr())
        self._ex.plane.mark_owned([r.id for r in refs])
        return refs

    def create_actor(self, spec):
        from ray_tpu.runtime.client import create_actor_via_head
        return create_actor_via_head(self.head, spec)

    def submit_actor_task(self, actor_id, spec):
        from ray_tpu.runtime.client import submit_actor_task_via_head
        refs = submit_actor_task_via_head(
            self.head, actor_id, spec,
            ret_addr=self._ex.plane.ret_addr())
        self._ex.plane.mark_owned([r.id for r in refs])
        return refs

    def kill_actor(self, actor_id, no_restart=True):
        self.head.call("kill_actor", actor_id.hex(), no_restart)

    def lookup_named_actor(self, name, namespace):
        from ray_tpu._private.ids import ActorID
        return ActorID.from_hex(
            self.head.call("lookup_named_actor", name,
                           namespace or "default"))

    def get_actor_state(self, actor_id):
        from ray_tpu.runtime.client import actor_state_from_head
        return actor_state_from_head(self.head, actor_id)

    def cancel(self, ref, force=False, recursive=True):
        """Nested cancel from inside a task (same head path and
        same non-cancellable-ref contract as the driver's)."""
        hint = getattr(ref, "owner_hint", None)
        if hint == "put":
            raise TypeError("ray_tpu.cancel() on a put() ref: only "
                            "task returns are cancellable")
        if hint == "actor":
            raise TypeError("ray_tpu.cancel() on an actor-task ref: "
                            "use ray_tpu.kill(actor)")
        return self.head.call("cancel_task",
                              ref.id.task_id().hex(), force)

    def cluster_resources(self):
        return self.head.call("cluster_resources")

    def available_resources(self):
        return self.head.call("available_resources")

    def create_placement_group(self, spec):
        from ray_tpu.runtime.client import create_pg_via_head
        return create_pg_via_head(self.head, spec)

    def remove_placement_group(self, pg):
        self.head.call("remove_placement_group", pg.id.hex())

    def list_actors(self):
        return self.head.call("list_actors")

    def list_tasks(self):
        return []

    def list_objects(self):
        return []

    def shutdown(self):
        pass


def _watch_parent():
    """Exit when the spawning node manager/agent process dies (orphan
    prevention; covers SIGKILL of the parent, which no signal handler
    there could)."""
    import os
    ppid = int(os.environ.get("RAY_TPU_PARENT_PID", "0"))
    if not ppid:
        return

    def loop():
        while True:
            try:
                os.kill(ppid, 0)
            except OSError:
                os._exit(0)
            time.sleep(1.0)

    threading.Thread(target=loop, daemon=True,
                     name="parent-watch").start()


def main():
    _watch_parent()
    parser = argparse.ArgumentParser()
    parser.add_argument("--head", required=True)
    parser.add_argument("--store", required=True)
    parser.add_argument("--worker-id", required=True)
    parser.add_argument("--node-id", default="head")
    parser.add_argument("--resources", default='{"CPU": 1}')
    parser.add_argument("--runtime-env", default=None)
    args = parser.parse_args()

    startup_env = json.loads(args.runtime_env) if args.runtime_env \
        else None
    env_key = None
    if startup_env:
        from ray_tpu._private.runtime_env import (
            enter_runtime_env_permanently, pip_env_dir,
            runtime_env_key, stage_pip_env)
        env_key = runtime_env_key(startup_env)
        try:
            if startup_env.get("pip") is not None:
                # pip env: stage the venv on this node and RE-EXEC
                # into its interpreter (reference: the runtime-env
                # agent builds the venv and workers launch with its
                # python, _private/runtime_env/pip.py). The marker env
                # var breaks the exec loop and tells
                # runtime_env_context this process already IS the
                # venv.
                vdir = pip_env_dir(startup_env)
                if os.environ.get("RAY_TPU_VENV") != vdir:
                    venv_py = stage_pip_env(startup_env)
                    env = dict(os.environ)
                    env["RAY_TPU_VENV"] = vdir
                    os.execve(venv_py, [venv_py, "-m",
                                        "ray_tpu.runtime.worker_main",
                                        *sys.argv[1:]], env)
            elif startup_env.get("conda") is not None:
                # conda env: resolve (or create) it on this node and
                # RE-EXEC under its interpreter (reference:
                # runtime_env/conda.py — the worker process IS the
                # env). The marker breaks the exec loop.
                from ray_tpu._private.runtime_env import \
                    conda_env_python
                conda_py = conda_env_python(startup_env)
                if os.environ.get("RAY_TPU_CONDA") != conda_py:
                    env = dict(os.environ)
                    env["RAY_TPU_CONDA"] = conda_py
                    os.execve(conda_py,
                              [conda_py, "-m",
                               "ray_tpu.runtime.worker_main",
                               *sys.argv[1:]], env)
            # Dedicated env-keyed worker: apply once, forever — the
            # head routes only matching tasks/actors here, so
            # per-execution apply/restore is skipped (true process
            # isolation, worker_pool.h:149 semantics).
            enter_runtime_env_permanently(startup_env)
        except BaseException as e:  # noqa: BLE001
            # Setup failure must surface to the callers, not hang
            # them: tell the head so queued tasks for this env fail
            # with the real error (pip stderr etc).
            try:
                RpcClient(args.head, timeout=10).call(
                    "env_setup_failed", env_key, str(e)[-2000:])
            except Exception:
                pass
            raise

    from ray_tpu._private.shm_store import ShmObjectStore
    store = ShmObjectStore.attach(args.store)
    try:
        from ray_tpu._private.shm_metrics import ShmMetricsRegistry
        from ray_tpu.util import metrics as metrics_mod
        metrics_mod.set_shm_registry(
            ShmMetricsRegistry.attach(args.store + "_m"))
    except Exception:
        pass   # metrics are best-effort
    head = RpcClient(args.head)
    resources = json.loads(args.resources)

    from ray_tpu.runtime.object_plane import ObjectPlane
    plane = ObjectPlane(store, head, node_id=args.node_id)
    executor = Executor(args.worker_id, head, plane, resources)
    executor.startup_env_key = env_key
    server = RpcServer(executor)

    # Install the worker-side runtime for nested API usage.
    runtime = WorkerRuntime(executor, head, args.worker_id)
    from ray_tpu._private import worker as worker_mod
    from ray_tpu._private.object_ref import set_global_reference_counter
    worker_mod._worker = worker_mod.Worker(runtime, mode="worker")
    set_global_reference_counter(runtime.ref_counter)
    from ray_tpu._private.object_ref import set_borrow_notifier
    set_borrow_notifier(executor.plane.note_borrow)

    reply = head.call("register_worker", args.worker_id, server.address,
                      resources, args.node_id, env_key)
    plane.multinode = bool(reply.get("multinode"))
    # Capture this worker's stdout/stderr and stream to the driver
    # (log_to_driver pipeline; the reference's log_monitor analogue).
    from ray_tpu._private.log_streaming import WorkerLogPublisher
    WorkerLogPublisher(head, args.worker_id).install()

    def heartbeat_loop():
        # Worker->head liveness + head-restart re-attach: a False reply
        # means the head lost us (restart from snapshot or a spurious
        # death mark) — re-register and re-bind our live actors.
        while not executor._shutdown.is_set():
            time.sleep(1.0)
            try:
                known = head.call("worker_heartbeat", args.worker_id,
                                  timeout=5)
            except Exception:
                continue        # head down; retry
            if not known:
                try:
                    reply2 = head.call("register_worker",
                                       args.worker_id, server.address,
                                       resources, args.node_id,
                                       env_key)
                    plane.multinode = bool(reply2.get("multinode"))
                    with executor._lock:
                        live = [aid for aid, s in
                                executor.actors.items()
                                if s.error is None]
                    if live:
                        head.call("report_actors", args.worker_id,
                                  live)
                except Exception:
                    pass

    threading.Thread(target=heartbeat_loop, daemon=True,
                     name="worker-heartbeat").start()
    # Track node membership by push so the single-node fast path flips
    # the moment a second node joins (and back).
    from ray_tpu.runtime.pubsub import Subscriber
    sub = Subscriber(RpcClient(args.head))
    sub.subscribe_state("nodes", plane.on_nodes_update)
    executor._shutdown.wait()


if __name__ == "__main__":
    main()
