"""Typed wire-message schemas for the control-plane RPC protocol.

The capability of the reference's 21 protobuf files
(src/ray/protobuf/*.proto, e.g. gcs_service.proto): every
control-plane method has a declared signature, unknown fields are
rejected instead of silently absorbed, and the peer's codec version is
exchanged at connection setup so version skew fails CLOSED with a
clear error instead of corrupting state mid-flight.

Schemas are declarative tuples instead of generated classes — both
ends are this codebase, so the value of protos here is validation +
versioning, not cross-language codegen.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

# Bumped whenever a schema or the frame layout changes incompatibly.
# Exchanged in the handshake ack; PROTO_VERSION (rpc.py) gates the
# handshake itself.
CODEC_VERSION = 2


@dataclasses.dataclass(frozen=True)
class Param:
    name: str
    types: Optional[tuple] = None     # None = any
    required: bool = True


def P(name, types=None, required=True):
    if types is not None and not isinstance(types, tuple):
        types = (types,)
    return Param(name, types, required)


_BYTES = (bytes, bytearray, memoryview)

# Method name -> parameter schema. Methods not listed are legacy /
# dynamic endpoints and pass through unvalidated (the registry covers
# the control-plane surface the reference declares in protos).
SCHEMAS: Dict[str, Tuple[Param, ...]] = {
    # task submission / dispatch
    "submit_tasks": (P("batch", list),),
    "push_tasks": (P("payloads", list),),
    "tasks_done": (P("worker_id", str), P("task_ids", list)),
    "cancel_task": (P("task_id", str),
                    P("force", bool, required=False)),
    "cancel_task_exec": (P("task_id", str),),
    # actors
    "submit_actor_task": (P("actor_id", str), P("meta", dict),
                          P("payload", _BYTES),
                          P("attempts", int, required=False)),
    "push_actor_task": (P("actor_id", str), P("payload", _BYTES),
                        P("attempts", int, required=False)),
    "push_actor_tasks": (P("items", list),),
    "reroute_actor_task": (P("actor_id", str), P("payload", _BYTES),
                           P("attempts", int, required=False)),
    "actor_address": (P("actor_id", str),),
    "kill_actor": (P("actor_id", str),
                   P("restart", (bool, int), required=False)),
    # object directory / transfer
    "register_objects": (P("node_id", str), P("oid_hexes", list)),
    "free_objects": (P("oid_hexes", list),),
    "locate_object": (P("oid_hex", str),
                      P("probe", bool, required=False),
                      P("reconstruct", bool, required=False)),
    "locate_objects": (P("oid_hexes", list),),
    "begin_pull": (P("oid_hex", str), P("node_id", str),
                   P("probe", bool, required=False),
                   P("reconstruct", bool, required=False)),
    "end_pull": (P("oid_hex", str), P("node_id", str),
                 P("source_node", str),
                 P("slot_ts", (int, float), required=False)),
    "unregister_object": (P("oid_hex", str), P("node_id", str)),
    "add_borrows": (P("oid_hexes", list),
                    P("node_id", str, required=False)),
    "drop_borrows": (P("oid_hexes", list),
                     P("node_id", str, required=False)),
    "owner_released": (P("items", list),),
    "object_size": (P("oid_hex", str),),
    "has_object": (P("oid_hex", str),),
    "pull_chunk": (P("oid_hex", str), P("offset", int),
                   P("length", int)),
    "fetch_object": (P("oid_hex", str),
                     P("reconstruct", bool, required=False)),
    "push_object": (P("oid_hex", str), P("data", _BYTES)),
    "raw_pull_chunk": (P("oid_hex", str), P("offset", int),
                       P("length", int)),
    # membership
    "register_node": (P("node_id", str), P("object_addr", str),
                      P("store_name", str)),
    "node_heartbeat": (P("node_id", str),
                       P("hw", (dict, type(None)), required=False)),
    "mark_worker_dead": (P("worker_id", str),),
    "env_setup_failed": (P("env_key", str), P("message", str)),
    # KV
    # autoscaler
    "request_resources": (P("bundles", list),),
    # KV
    "kv_put": (P("key", str), P("value", _BYTES)),
    "kv_get": (P("key", str),),
    "kv_del": (P("key", str),),
    "kv_keys": (P("prefix", str, required=False),),
}


class SchemaError(Exception):
    """Request rejected by schema validation (fails closed)."""


def validate_request(method: str, args: tuple,
                     kwargs: Dict[str, Any]) -> None:
    """Raise SchemaError for malformed requests to schema'd methods.
    Unknown kwargs are rejected outright — the unknown-field
    protection protos give (a newer peer's extra field must not be
    silently dropped by an older server)."""
    schema = SCHEMAS.get(method)
    if schema is None:
        return
    by_name = {p.name: p for p in schema}
    if len(args) > len(schema):
        raise SchemaError(
            f"{method}: takes at most {len(schema)} arguments, "
            f"got {len(args)}")
    seen = set()
    for p, a in zip(schema, args):
        seen.add(p.name)
        _check_type(method, p, a)
    for k, v in kwargs.items():
        p = by_name.get(k)
        if p is None:
            raise SchemaError(
                f"{method}: unknown field {k!r} (schema fields: "
                f"{sorted(by_name)}; version skew? this server "
                f"speaks codec {CODEC_VERSION})")
        if p.name in seen:
            raise SchemaError(f"{method}: duplicate field {k!r}")
        seen.add(p.name)
        _check_type(method, p, v)
    missing = [p.name for p in schema
               if p.required and p.name not in seen]
    if missing:
        raise SchemaError(f"{method}: missing required fields "
                          f"{missing}")


def _check_type(method: str, p: Param, value: Any) -> None:
    if p.types is None or value is None and not p.required:
        return
    if not isinstance(value, p.types):
        want = "/".join(t.__name__ for t in p.types)
        raise SchemaError(
            f"{method}: field {p.name!r} expects {want}, got "
            f"{type(value).__name__}")
