"""Node manager: process lifecycle for the multiprocess runtime.

Capability parity with the reference's node/process management
(python/ray/_private/node.py start_head_processes + services.py
start_raylet, and the raylet WorkerPool worker_pool.h:149): creates the
node's C++ shm store, serves the head, spawns/monitors/kills worker
processes (the chaos NodeKiller hook used by fault-tolerance tests).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
import uuid
from typing import Dict, List, Optional

from ray_tpu.runtime.head import HeadService
from ray_tpu.runtime.rpc import RpcServer

_REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", ".."))


class NodeManager:
    def __init__(self, num_workers: int = 2,
                 resources_per_worker: Optional[Dict[str, float]] = None,
                 store_capacity: int = 256 * 1024 * 1024,
                 tpu_owner_worker: Optional[int] = None):
        self.resources_per_worker = resources_per_worker or {"CPU": 2}
        self.store_name = f"/raytpu_{os.getpid()}_{uuid.uuid4().hex[:8]}"
        from ray_tpu._private.shm_store import ShmObjectStore
        self.store = ShmObjectStore.create(self.store_name,
                                           store_capacity)
        # Native metrics segment: workers record with lock-free atomics,
        # the head aggregates without RPC (N20, src/metrics/).
        from ray_tpu._private.shm_metrics import ShmMetricsRegistry
        self.metrics = ShmMetricsRegistry.create(self.store_name + "_m")
        self.head_service = HeadService(self.store_name)
        self.head_server = RpcServer(self.head_service)
        self.head_service.attach_node_manager(
            self, self.head_server.address)
        self.procs: Dict[str, subprocess.Popen] = {}
        self.tpu_owner_worker = tpu_owner_worker
        self._stopped = False
        for i in range(num_workers):
            self.start_worker(i)
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         daemon=True, name="node-monitor")
        self._monitor.start()

    @property
    def head_address(self) -> str:
        return self.head_server.address

    def start_worker(self, index: int,
                     resources: Optional[Dict[str, float]] = None
                     ) -> str:
        worker_id = f"worker-{index}-{uuid.uuid4().hex[:6]}"
        env = dict(os.environ)
        env.pop("PYTHONPATH", None)   # breaks the TPU plugin (see skills)
        # Propagate driver-side flag overrides (chaos delays, spill
        # settings, …) to the worker, reference `_system_config` style.
        from ray_tpu._private.config import GlobalConfig
        env.update(GlobalConfig.to_env())
        res = dict(resources or self.resources_per_worker)
        # Only a designated worker may own the TPU; everyone else is
        # forced onto the CPU backend so they can't grab the chip.
        if self.tpu_owner_worker is not None and \
                index == self.tpu_owner_worker:
            res.setdefault("TPU", 1.0)
        else:
            env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.runtime.worker_main",
             "--head", self.head_address,
             "--store", self.store_name,
             "--worker-id", worker_id,
             "--resources", json.dumps(res)],
            cwd=_REPO_ROOT, env=env)
        self.procs[worker_id] = proc
        return worker_id

    def wait_for_workers(self, n: Optional[int] = None,
                         timeout: float = 30) -> None:
        deadline = time.time() + timeout
        while time.time() < deadline:
            if n is None:
                # Wait for every live worker process to be registered.
                target = sum(1 for p in self.procs.values()
                             if p.poll() is None)
            else:
                target = n
            alive = [w for w in self.head_service.list_workers()
                     if w["alive"]]
            if len(alive) >= target:
                return
            time.sleep(0.05)
        raise TimeoutError(
            f"Only {len(self.head_service.list_workers())} of {target} "
            f"workers registered in {timeout}s")

    def kill_worker(self, worker_id: str):
        """Chaos hook: SIGKILL a worker process (the NodeKillerActor
        analogue, python/ray/_private/test_utils.py:1089)."""
        proc = self.procs.get(worker_id)
        if proc is not None:
            proc.kill()
            proc.wait(timeout=10)

    def _monitor_loop(self):
        import traceback
        while not self._stopped:
            try:
                for worker_id, proc in list(self.procs.items()):
                    if proc.poll() is not None:
                        self.procs.pop(worker_id, None)
                        self.head_service.mark_worker_dead(worker_id)
            except Exception:  # noqa: BLE001 — keep monitoring
                traceback.print_exc()
            time.sleep(0.05)

    def stop(self):
        self._stopped = True
        self.head_service.shutdown()
        try:
            self.metrics.close()
        except Exception:
            pass
        deadline = time.time() + 3
        for proc in self.procs.values():
            try:
                if proc.poll() is None and time.time() < deadline:
                    proc.terminate()
            except Exception:
                pass
        for proc in self.procs.values():
            try:
                proc.wait(timeout=3)
            except Exception:
                proc.kill()
        self.head_server.stop()
        self.store.close()
